"""L4: distributed Frames — chunk homes on the DKV ring.

Reference: a ``Vec`` is a *distributed* column whose chunks live on
ESPC-assigned home nodes and compute moves to the data
(``water/fvec/Vec.java`` chunk/ESPC arithmetic, ``water/MRTask.java``
map-side execution).  Here a chunk-homed parse tokenizes each CSV chunk
ON its ring home and stores the tokenized payload there (replicated to
``H2O3_TPU_CHUNK_REPLICAS`` ring successors at write time); the frame
the caller gets back is a :class:`DistFrame` — a lazy Frame whose
columns live as chunk ranges on the ring, described by a routable
LAYOUT dict stored under ``fr#<key>#layout``.

Placement: every chunk key ``fr#<key>#g<j>t<t>#c<i>`` ring-hashes by its
GROUP ANCHOR (``dkv.ring_key``), so a group's chunks land contiguously
on one member and ride the DKV's existing fault machinery — replica
walk, read-repair, anti-entropy sweep — as a unit.  The anchor's ``t``
is probed at parse time so group ``j`` homes on worker ``j``: placement
stays balanced and deterministic for a fixed membership.

``map_reduce`` over a chunk-homed frame runs map-side on each group's
CURRENT ring home over its local chunks (the existing shard_map path)
with only partials crossing the wire.  When a home dies mid-fan-out the
group re-executes from replica chunks on the ring successors
(``cluster_fanout_recovered_total{path=replica}``); survivors and the
caller are deeper rungs of the same ladder.  A restarted-empty home
pulls its chunks back through the store's read-repair walk.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster import transport
from h2o3_tpu.cluster.dkv import MAX_REPLICAS
from h2o3_tpu.frame.frame import ColType, Column, Frame, NA_CAT
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

_CHUNK_HOMES = telemetry.gauge(
    "cluster_chunk_homes",
    "chunk groups the most recent chunk-homed parse landed on ring "
    "members (one group of contiguous chunks per home)",
)
_REPLICA_BYTES = telemetry.counter(
    "cluster_chunk_replica_bytes",
    "tokenized chunk payload bytes fanned to ring-successor replicas "
    "at parse time (write-time durability cost of chunk homes)",
)

#: room the pickled RPC/store envelope (key, token, trace ids, pickle
#: framing) needs around a chunk payload inside one transport frame
_ENVELOPE_SLACK = 1 << 16


class ChunkTooLargeError(ValueError):
    """A chunk payload cannot cross the wire in one transport frame —
    raised with the offending chunk id BEFORE the opaque mid-transfer
    ``FrameTooLarge`` the transport would otherwise die with."""

    def __init__(self, chunk_id: str, nbytes: int, limit: int) -> None:
        super().__init__(
            f"chunk {chunk_id!r} is {nbytes} bytes but at most {limit} "
            f"fit one transport frame (transport.MAX_FRAME_BYTES = "
            f"{transport.MAX_FRAME_BYTES} minus envelope slack); re-parse "
            f"with smaller chunks (set H2O3_TPU_PARSE_CHUNK_BYTES below "
            f"{limit}) or raise transport.MAX_FRAME_BYTES on every member")
        self.chunk_id = chunk_id
        self.nbytes = nbytes
        self.limit = limit


def guard_chunk_payload(chunk_id: str, value: Any) -> int:
    """Size ``value`` as it will cross the wire and raise a typed
    :class:`ChunkTooLargeError` when it cannot fit one transport frame.
    Returns the measured byte size (the replica-bytes meter reuses it).

    Callers landing tokenized chunks MUST pass the ENCODED value (after
    :func:`h2o3_tpu.frame.codecs.encode_chunk`): the wire carries the
    encoded bytes, so guarding the dense size would refuse chunks that
    ship fine — and under-meter the replica fan-out."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        nbytes = len(value)
    else:
        nbytes = len(pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL))
    limit = max(0, int(transport.MAX_FRAME_BYTES) - _ENVELOPE_SLACK)
    if nbytes > limit:
        raise ChunkTooLargeError(chunk_id, nbytes, limit)
    return nbytes


def chunk_replicas() -> int:
    """Replica depth for chunk payloads: ``H2O3_TPU_CHUNK_REPLICAS``
    (default 2 = home + one successor), clamped to the ring's reachable
    depth."""
    try:
        r = int(os.environ.get("H2O3_TPU_CHUNK_REPLICAS", "2"))
    except ValueError:
        r = 2
    return max(1, min(r, MAX_REPLICAS))


# ---------------------------------------------------------------------------
# key scheme (see dkv.ring_key for the placement contract)


def layout_key(frame_key: str) -> str:
    return f"fr#{frame_key}#layout"


def setup_key(frame_key: str) -> str:
    """The parse setup that produced the frame — stored beside the layout
    so any member (a grid-search executor restoring a ``__dist__`` frame
    reference, a REST handler resolving a key) can rebuild a full
    :class:`DistFrame` handle from the ring alone."""
    return f"fr#{frame_key}#setup"


def setup_payload(setup) -> Dict[str, Any]:
    """A :class:`~h2o3_tpu.frame.parse.ParseSetup` as a plain dict —
    dataclasses are node-local in the DKV (``ROUTABLE_VALUE_TYPES``),
    so the ring copy stored under :func:`setup_key` must be plain data
    or it would silently never leave the caller."""
    return {
        "separator": setup.separator,
        "header": bool(setup.header),
        "column_names": list(setup.column_names),
        "column_types": list(setup.column_types),
        "na_strings": list(setup.na_strings),
        "skip_blank_lines": bool(setup.skip_blank_lines),
        "quote_char": setup.quote_char,
    }


def setup_from_payload(d: Dict[str, Any]):
    from h2o3_tpu.frame.parse import ParseSetup

    if not isinstance(d, dict):
        return d  # already a ParseSetup (a caller-local store hit)
    return ParseSetup(
        separator=d["separator"], header=d["header"],
        column_names=list(d["column_names"]),
        column_types=list(d["column_types"]),
        na_strings=tuple(d["na_strings"]),
        skip_blank_lines=d["skip_blank_lines"],
        quote_char=d["quote_char"])


def chunk_key(anchor: str, i: int) -> str:
    """Chunk ``i`` (GLOBAL chunk index) of the group homed at ``anchor``."""
    return f"{anchor}#c{i}"


def _probe_anchor(router, frame_key: str, g: int, want_ident: str) -> str:
    """Smallest ``t`` whose anchor ``fr#<key>#g<g>t<t>`` ring-homes on
    the wanted member — group ``g`` then deterministically homes on
    worker ``g`` and parse placement stays balanced regardless of how
    the raw hashes fall."""
    fallback = f"fr#{frame_key}#g{g}t0"
    for t in range(512):
        cand = f"fr#{frame_key}#g{g}t{t}"
        hm = router.home_members(cand, 1)
        if hm and hm[0].info.ident == want_ident:
            return cand
    return fallback


def _layout_stamp(espc: Sequence[int], anchors: Sequence[str]) -> str:
    return hashlib.md5(
        repr((tuple(int(e) for e in espc), tuple(anchors))).encode()
    ).hexdigest()[:12]


# ---------------------------------------------------------------------------
# DistFrame — the lazy caller-side handle


class DistFrame(Frame):
    """A Frame whose chunks live on their DKV ring homes.

    Shape/metadata (``nrows``/``ncols``/``names``/``types``) answer from
    the layout without touching the ring, so listings never materialize.
    Any column access gathers every chunk through the store (ring walk +
    read-repair) and reduces with the parse pipeline's own phase-2 merge
    — the materialized frame is bit-identical to a local parse."""

    def __init__(self, layout: Dict[str, Any], setup, store) -> None:
        # deliberately NOT calling Frame.__init__: there are no resident
        # columns yet, and _cols below materializes on first touch
        self.chunk_layout = layout
        self.key = layout["frame_key"]
        self._setup = setup
        self._store = store
        self._materialized: Optional[List[Column]] = None

    # -- lazy column storage -------------------------------------------------
    @property
    def _cols(self) -> List[Column]:
        if self._materialized is None:
            self._materialized = self._gather()
        return self._materialized

    def _gather(self) -> List[Column]:
        from h2o3_tpu.frame import parse as _parse

        results = []
        for grp in self.chunk_layout["groups"]:
            for i in range(grp["lo"], grp["hi"]):
                ck = chunk_key(grp["anchor"], i)
                v = self._store.get(ck)
                if v is None:
                    raise KeyError(
                        f"chunk {ck} of frame {self.key!r} is unreachable "
                        f"on the ring (home and every replica down?)")
                results.append(tuple(v))
        return _parse._reduce_chunks(results, self._setup)._cols

    # -- metadata off the layout (no ring traffic) ---------------------------
    @property
    def nbytes_resident(self) -> int:
        """Host bytes this handle actually pins — 0 until materialized.
        The store's spill sizing reads this instead of ``columns`` so a
        put/list of a DistFrame never gathers remote chunks."""
        if self._materialized is None:
            return 0
        return int(sum(getattr(c.data, "nbytes", 0)
                       for c in self._materialized))

    def column_rollups(self, name: str):
        """RollupStats for one NUM/TIME column straight off the ring's
        ENCODED chunk payloads (rollups.payload_rollups) — no gather, no
        dense materialization; const/sparse/affine/dict chunks reduce
        from their own tables.  Other column types (CAT global-domain
        remap, STR/UUID) take the materializing path."""
        from h2o3_tpu.frame import rollups as _rollups

        layout = self.chunk_layout
        j = layout["column_names"].index(name)
        if self._materialized is None and \
                layout["column_types"][j] in (ColType.NUM, ColType.TIME):
            vals = []
            for g in range(len(layout["groups"])):
                vals.extend(_fetch_group_chunks(self._store, layout, g))
            return _rollups.payload_rollups([v[1][j] for v in vals])
        return self._cols[j].rollups

    @property
    def nbytes_wire(self) -> int:
        """ENCODED bytes of this frame's chunks as landed on the ring —
        the size that replication, spill, and the chunk guard actually
        see (frame/codecs.py), NOT the dense f64 footprint.  Answers
        from the layout with no ring traffic."""
        return int(self.chunk_layout.get("nbytes", 0))

    @property
    def nrows(self) -> int:
        return int(self.chunk_layout["espc"][-1])

    @property
    def ncols(self) -> int:
        return len(self.chunk_layout["column_names"])

    @property
    def names(self) -> List[str]:
        return list(self.chunk_layout["column_names"])

    @property
    def types(self) -> Dict[str, ColType]:
        return dict(zip(self.chunk_layout["column_names"],
                        self.chunk_layout["column_types"]))

    def col_types(self) -> List[ColType]:
        if self._materialized is not None:
            return [c.type for c in self._materialized]
        return list(self.chunk_layout["column_types"])

    def __repr__(self) -> str:
        lay = self.chunk_layout
        state = "resident" if self._materialized is not None else "remote"
        return (f"<DistFrame {self.key!r} {self.nrows}x{self.ncols} "
                f"groups={len(lay['groups'])} replicas={lay['replicas']} "
                f"{state}>")


# ---------------------------------------------------------------------------
# parse-to-homes (caller side)


def _resolve_store(cloud, store=None):
    if store is not None:
        return store
    store = getattr(cloud, "dkv_store", None)
    if store is not None:
        return store
    from h2o3_tpu.keyed import DKV

    return DKV


def distributed_parse_to_homes(
    chunks: Sequence[bytes],
    setup,
    cloud,
    store=None,
    timeout: float = 300.0,
    key: Optional[str] = None,
) -> Frame:
    """Phase-1 tokenization that LANDS each chunk on its ring home
    instead of returning payloads to the caller: contiguous chunk ranges
    (one group per worker) fan out as ``parse_chunk_home`` tasks, each
    home tokenizes locally, stores the payload under its chunk key with
    ``chunk_replicas()`` copies, and returns only shape metadata (nrows
    + CAT domains).  The caller assembles the ESPC + global domains into
    the routable layout and returns a lazy :class:`DistFrame`.

    A home that fails mid-parse degrades per chunk: the caller tokenizes
    that chunk itself and routes the payload through the store (which
    forwards to the chunk's current ring home) — parse completes against
    any single-member loss."""
    from h2o3_tpu.cluster import tasks as _tasks
    from h2o3_tpu.frame import parse as _parse

    store = _resolve_store(cloud, store)
    router = getattr(store, "router", None)
    workers = _tasks._healthy_workers(cloud) if cloud is not None else []
    chunks = list(chunks)
    if router is None or not router.active() or len(workers) < 2:
        # no routable ring: plain local reduce (the caller's fallback)
        na = frozenset(setup.na_strings)
        napack = _parse._pipeline_napack(setup)
        return _parse._reduce_chunks(
            [_parse._parse_chunk(c, setup, na, napack) for c in chunks],
            setup)
    if key is None:
        import uuid

        key = f"frame_{uuid.uuid4().hex[:10]}"

    k = len(workers)
    nchunks = len(chunks)
    ngroups = max(1, min(k, nchunks))
    gbounds = [round(j * nchunks / ngroups) for j in range(ngroups + 1)]
    replicas = chunk_replicas()
    anchors = [_probe_anchor(router, key, j, workers[j].info.ident)
               for j in range(ngroups)]
    group_of = np.searchsorted(gbounds, np.arange(nchunks), side="right") - 1

    na = frozenset(setup.na_strings)
    napack = _parse._pipeline_napack(setup)
    nrows = [0] * nchunks
    stored = [0] * nchunks
    chunk_domains: List[Optional[list]] = [None] * nchunks

    def _local_land(i: int, j: int) -> Dict[str, Any]:
        """Caller-side fallback: tokenize here, route the payload to the
        chunk's CURRENT ring home through the store."""
        from h2o3_tpu.frame import codecs as _codecs

        n, payloads, used_native = _parse._parse_chunk(
            chunks[i], setup, na, napack)
        doms = [p[1] if isinstance(p, tuple) else None for p in payloads]
        value = _codecs.encode_chunk([int(n), payloads, bool(used_native)])
        nbytes = guard_chunk_payload(chunk_key(anchors[j], i), value)
        store.put(chunk_key(anchors[j], i), value, replicas=replicas)
        return {"nrows": int(n), "domains": doms, "nbytes": nbytes}

    with telemetry.Span("distributed_parse_to_homes", chunks=nchunks,
                        groups=ngroups, replicas=replicas):
        ctx = telemetry.current_trace_context()

        def _run(i: int) -> None:
            j = int(group_of[i])
            target = workers[j]
            ck = chunk_key(anchors[j], i)
            guard_chunk_payload(ck, chunks[i])
            with telemetry.Span(
                    "parse_chunk_home", trace_id=ctx["trace_id"],
                    parent_id=ctx["span_id"], member=target.info.name,
                    chunk=i):
                try:
                    if target.info.name == cloud.info.name:
                        resp = parse_chunk_home(
                            {"chunk": chunks[i], "setup": setup,
                             "chunk_key": ck, "replicas": replicas},
                            cloud, store)
                    else:
                        resp = _tasks.submit(
                            cloud, target, "parse_chunk_home",
                            {"chunk": chunks[i], "setup": setup,
                             "chunk_key": ck, "replicas": replicas},
                            timeout=timeout)
                except _rpc.RPCError:
                    resp = _local_land(i, j)
                nrows[i] = int(resp["nrows"])
                stored[i] = int(resp.get("nbytes", 0))
                chunk_domains[i] = resp["domains"]

        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import wait as _futures_wait

        ex = ThreadPoolExecutor(max_workers=2 * k,
                                thread_name_prefix="parse-home")
        futs = [ex.submit(_run, i) for i in range(nchunks)]
        _futures_wait(futs, timeout=timeout)
        ex.shutdown(wait=False, cancel_futures=True)
        for i, f in enumerate(futs):
            if not f.done():
                raise TimeoutError(
                    f"chunk {i} did not land on its home in {timeout}s")
            f.result()  # re-raise guard/tokenize errors with their type

    espc = [0] * (nchunks + 1)
    for i in range(nchunks):
        espc[i + 1] = espc[i] + nrows[i]
    # global CAT domains with the EXACT _reduce_chunks math, so map-side
    # code remapping is bit-identical to a materializing gather
    domains: Dict[str, list] = {}
    for jcol, name in enumerate(setup.column_names):
        if setup.column_types[jcol] is ColType.CAT:
            doms = [(chunk_domains[i] or [None] * len(setup.column_names))
                    [jcol] or [] for i in range(nchunks)]
            domains[name] = (
                sorted(set().union(*map(set, doms))) if doms else [])
    layout = {
        "frame_key": key,
        "espc": espc,
        "replicas": replicas,
        "groups": [
            {"g": j, "anchor": anchors[j],
             "lo": gbounds[j], "hi": gbounds[j + 1],
             "home": workers[j].info.ident,
             "home_name": workers[j].info.name}
            for j in range(ngroups)
        ],
        "column_names": list(setup.column_names),
        "column_types": list(setup.column_types),
        "domains": domains,
        "nbytes": int(sum(stored)),
        "stamp": _layout_stamp(espc, anchors),
    }
    store.put(setup_key(key), setup_payload(setup), replicas=MAX_REPLICAS)
    store.put(layout_key(key), layout, replicas=MAX_REPLICAS)
    _CHUNK_HOMES.set(ngroups)
    return DistFrame(layout, setup, store)


def materialize(frame):
    """A plain resident :class:`Frame` from any frame handle — gathers a
    :class:`DistFrame`'s chunks, passes an already-local frame through."""
    if getattr(frame, "chunk_layout", None) is None:
        return frame
    return Frame(list(frame._cols), key=getattr(frame, "key", None))


# ---------------------------------------------------------------------------
# home-side task bodies (registered as context tasks in cluster/tasks.py)


def parse_chunk_home(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    """Tokenize one chunk ON its home, ENCODE it through the chunk codec
    layer (frame/codecs.py — per-column, bit-exact round-trip or dense
    fallback), and store the encoded payload locally with replica
    fan-out; only shape metadata returns to the caller.  Replicas carry
    the same encoded bytes, so write-time durability cost shrinks with
    the resident footprint."""
    from h2o3_tpu.frame import codecs as _codecs
    from h2o3_tpu.frame import parse as _parse

    setup = payload["setup"]
    na = frozenset(setup.na_strings)
    napack = _parse._pipeline_napack(setup)
    n, payloads, used_native = _parse._parse_chunk(
        payload["chunk"], setup, na, napack)
    doms = [p[1] if isinstance(p, tuple) else None for p in payloads]
    value = _codecs.encode_chunk([int(n), payloads, bool(used_native)])
    ck = payload["chunk_key"]
    replicas = int(payload.get("replicas", 1))
    nbytes = guard_chunk_payload(ck, value)
    store.put(ck, value, replicas=replicas)
    if replicas > 1:
        _REPLICA_BYTES.inc(nbytes * (replicas - 1))
    return {"nrows": int(n), "domains": doms, "nbytes": nbytes,
            "native": bool(used_native)}


#: (frame_key, stamp) -> layout, bounded LRU so repeated map_reduce over
#: the same chunk-homed frame re-reads no layout per call.  Assembled
#: host columns (the DECODED dense working set) moved to the byte-
#: budgeted device frame cache (devcache.cached_host): decode is
#: deferred to first compute touch and dense copies are reclaimed under
#: memory pressure instead of pinned in an entry-counted LRU.
_CACHE_LOCK = threading.Lock()
_LAYOUT_CACHE: "OrderedDict[Tuple[str, str], Dict[str, Any]]" = OrderedDict()
_LAYOUT_CACHE_MAX = 8


def _cache_put(cache: OrderedDict, key, value, cap: int) -> None:
    with _CACHE_LOCK:
        cache[key] = value
        cache.move_to_end(key)
        while len(cache) > cap:
            cache.popitem(last=False)


def _layout_for(store, frame_key: str, stamp: str) -> Dict[str, Any]:
    with _CACHE_LOCK:
        lay = _LAYOUT_CACHE.get((frame_key, stamp))
    if lay is not None:
        return lay
    lay = store.get(layout_key(frame_key))
    if not isinstance(lay, dict):
        raise _rpc.RpcFault(
            f"layout for frame {frame_key!r} unreachable", code=404)
    if lay.get("stamp") != stamp:
        # the caller holds a different parse of this key than the ring —
        # conflict, not absence: the caller falls down its ladder
        raise _rpc.RpcFault(
            f"layout stamp mismatch for frame {frame_key!r}", code=409)
    _cache_put(_LAYOUT_CACHE, (frame_key, stamp), lay, _LAYOUT_CACHE_MAX)
    return lay


def _fetch_group_chunks(store, layout: Dict[str, Any], g: int) -> list:
    grp = layout["groups"][g]
    vals = []
    for i in range(grp["lo"], grp["hi"]):
        ck = chunk_key(grp["anchor"], i)
        v = store.get(ck)
        if v is None:
            raise _rpc.RpcFault(
                f"chunk {ck} unreachable on the ring", code=404)
        vals.append(v)
    # cache-miss path only (columns_from_group short-circuits on its
    # group cache), so the charge counts real ring/chunk reads
    _ledger.charge(_ledger.CHUNK_READS, len(vals))
    return vals


def _cat_group_codes(vals: list, j: int, name: str,
                     layout: Dict[str, Any]) -> np.ndarray:
    """One CAT column's group codes remapped to the layout's GLOBAL
    domain — the EXACT parse phase-2 arithmetic (decode first: encoded
    catpack payloads carry the same int32 codes bit-for-bit)."""
    from h2o3_tpu.frame import codecs as _codecs

    gdl = layout["domains"].get(name) or []
    gd = np.array(gdl) if gdl else None
    parts = []
    for v in vals:
        codes, dom = _codecs.decode_column(v[1][j])
        if dom:
            remap = np.searchsorted(
                gd, np.array(dom)).astype(np.int32)
            codes = np.where(
                codes >= 0, remap[np.clip(codes, 0, None)], NA_CAT
            ).astype(np.int32)
        parts.append(codes)
    return (np.concatenate(parts) if parts
            else np.empty(0, dtype=np.int32))


def columns_from_group(store, layout: Dict[str, Any], g: int,
                       names: Sequence[str]) -> Dict[str, np.ndarray]:
    """Assemble one group's host columns (float64 numeric views) from
    its chunks — local hits on the home/replica holder, ring walk +
    read-repair anywhere else.  CAT codes remap to the layout's GLOBAL
    domain with the same arithmetic as the parse phase-2 merge, so every
    executor sees the numbers a materializing gather would.

    Chunks land ENCODED (frame/codecs.py); each referenced column
    decodes bit-exactly here, and the decoded dense working set lives in
    the byte-budgeted devcache (kind ``group_columns``) — decode is paid
    at first compute touch, not at rest, and dense copies are reclaimed
    under memory pressure while the ring keeps only encoded bytes."""
    from h2o3_tpu.frame import codecs as _codecs
    from h2o3_tpu.frame import devcache as _devcache

    token = (layout["frame_key"], layout["stamp"], int(g), tuple(names))

    def build() -> Dict[str, np.ndarray]:
        vals = _fetch_group_chunks(store, layout, g)
        col_names = layout["column_names"]
        col_types = layout["column_types"]
        out: Dict[str, np.ndarray] = {}
        for name in names:
            j = col_names.index(name)
            ctype = col_types[j]
            if ctype is ColType.CAT:
                data = _cat_group_codes(vals, j, name, layout)
                view = data.astype(np.float64)
                view[data < 0] = np.nan
                out[name] = view
            elif ctype in (ColType.STR, ColType.UUID):
                raise TypeError(
                    f"column {name!r} of type {ctype} has no numeric view")
            else:
                parts = [np.asarray(_codecs.decode_column(v[1][j]),
                                    dtype=np.float64) for v in vals]
                out[name] = (np.concatenate(parts) if parts
                             else np.empty(0, dtype=np.float64))
        return out

    return _devcache.cached_host("group_columns", token, (), build,
                                 frame_key=layout["frame_key"])


def group_column_rep(store, layout: Dict[str, Any], g: int,
                     name: str) -> Tuple:
    """Codec-aware group rep of ONE numeric/CAT column for the fused
    executor: ``("dense", f64)`` / ``("const", v, n)`` /
    ``("affine", codes, offset, scale, sentinel)`` /
    ``("dict", codes, uniq)`` / ``("f32", data)`` — everything but dense
    feeds the jitted program as packed codes plus decode arithmetic,
    with no dense host copy resident.  CAT columns remap to the global
    domain first and present as affine codes over offset 0, scale 1
    (their numeric view), re-verified bit-exactly like every rep."""
    from h2o3_tpu.frame import codecs as _codecs
    from h2o3_tpu.frame import devcache as _devcache

    token = (layout["frame_key"], layout["stamp"], int(g), name)

    def build() -> Tuple:
        vals = _fetch_group_chunks(store, layout, g)
        col_names = layout["column_names"]
        j = col_names.index(name)
        ctype = layout["column_types"][j]
        if ctype in (ColType.STR, ColType.UUID):
            raise TypeError(
                f"column {name!r} of type {ctype} has no numeric view")
        if ctype is ColType.CAT:
            data = _cat_group_codes(vals, j, name, layout)
            view = data.astype(np.float64)
            view[data < 0] = np.nan
            if data.size and 0 <= int(data.max(initial=0)) < 65535:
                codes = np.where(data < 0, 65535, data).astype(np.uint16)
                out = 0.0 + codes.astype(np.float64) * 1.0
                out[codes == 65535] = np.nan
                if np.array_equal(out.view(np.uint64),
                                  view.view(np.uint64)):
                    return ("affine", codes, 0.0, 1.0, 65535)
            return ("dense", view)
        return _codecs.group_rep([v[1][j] for v in vals])

    return _devcache.cached_host("group_rep", token, (), build,
                                 frame_key=layout["frame_key"])


def mr_chunks(payload: Dict[str, Any], cloud, store) -> Any:
    """Map-side execution over one group's LOCAL chunks: assemble the
    group's columns (cache-warm after the first call) and run the
    existing shard_map+psum path; only the partial returns."""
    from h2o3_tpu.cluster import tasks as _tasks

    layout = _layout_for(store, payload["frame_key"], payload["stamp"])
    cols = columns_from_group(
        store, layout, int(payload["g"]), list(payload["names"]))
    return _tasks._mr_shard_local(
        payload["fn"], cols, payload.get("reduce", "sum"))


# ---------------------------------------------------------------------------
# chunk-homed map_reduce (caller side)


def map_reduce_chunk_homed(
    fn,
    frame: Frame,
    reduce: str = "sum",
    cloud=None,
    timeout: float = 300.0,
    names: Optional[Sequence[str]] = None,
) -> Any:
    """MRTask over a chunk-homed frame: each group executes on its
    CURRENT ring home over home-local chunks, only partials cross the
    wire, and the caller combines them in group order.

    Recovery ladder when a group's home fails mid-fan-out (self-healing,
    replica-first): (1) the group's ring successors hold replica CHUNKS,
    so they re-execute from local copies (``path=replica``); (2) any
    other healthy member re-executes by walking the ring for the chunks
    (``path=survivor``); (3) the caller assembles the group itself from
    whatever replicas answer the walk (``path=local``) — never by
    re-parsing the source."""
    from h2o3_tpu.cluster import tasks as _tasks

    layout = frame.chunk_layout
    if layout is None:
        raise ValueError("map_reduce_chunk_homed needs a chunk-homed frame")
    if reduce not in _tasks._COMBINE:
        raise ValueError(
            f"unknown reduce {reduce!r}; valid choices: "
            f"{sorted(_tasks._COMBINE)}")
    if names is None:
        names = [n for n, t in zip(layout["column_names"],
                                   layout["column_types"])
                 if t not in (ColType.STR, ColType.UUID)]
    names = list(names)
    if cloud is None:
        from h2o3_tpu.cluster import active_cloud

        cloud = active_cloud()
    store = getattr(frame, "_store", None) or _resolve_store(cloud)
    router = getattr(store, "router", None)
    workers = _tasks._healthy_workers(cloud) if cloud is not None else []
    groups = layout["groups"]
    if (cloud is None or router is None or not router.active()
            or len(workers) < 2 or not groups):
        # no multi-node ring: gather through the store and run the plain
        # local path — bit-identical to a resident single-node frame
        host = {n: frame.col(n).numeric_view() for n in names}
        return _tasks._mr_shard_local(fn, host, reduce)
    if getattr(fn, "__name__", "<lambda>") == "<lambda>" or \
            getattr(fn, "__closure__", None):
        raise ValueError(
            "distributed map_reduce needs a module-level fn (it crosses "
            "the wire by module reference); got a lambda/closure")

    my_name = cloud.info.name
    _tasks._FANOUT.set(len(groups))
    partials: List[Any] = [None] * len(groups)
    errors: List[Optional[BaseException]] = [None] * len(groups)

    def _exec_local(g: int) -> Any:
        cols = columns_from_group(store, layout, g, names)
        return _tasks._mr_shard_local(fn, cols, reduce)

    with telemetry.Span("map_reduce_chunk_homed", groups=len(groups),
                        rows=int(layout["espc"][-1]), reduce=reduce):
        ctx = telemetry.current_trace_context()
        fo = _flight.FANOUTS.begin("mr_chunk_homed", len(groups),
                                   rows=int(layout["espc"][-1]))
        _flight.record(_flight.FANOUT, "info", "schedule",
                       kind="mr_chunk_homed", groups=len(groups))

        def _run(gi: int) -> None:
            try:
                _run_group(gi)
            finally:
                fo.progress()

        def _run_group(gi: int) -> None:
            grp = groups[gi]
            payload = {"frame_key": layout["frame_key"],
                       "stamp": layout["stamp"], "g": gi,
                       "names": names, "fn": fn, "reduce": reduce}
            cands = router.home_members(grp["anchor"], MAX_REPLICAS)
            with telemetry.Span(
                    "mr_group", trace_id=ctx["trace_id"],
                    parent_id=ctx["span_id"], group=gi,
                    anchor=grp["anchor"]):
                # rung 0: the group's CURRENT ring home — data-local in
                # the healthy case, and the node a restarted-empty home
                # re-adopts its chunks on (its executor's ring walk
                # read-repairs them back)
                try:
                    if cands and cands[0].info.name == my_name:
                        partials[gi] = _exec_local(gi)
                        return
                    if cands:
                        partials[gi] = _tasks.submit(
                            cloud, cands[0], "mr_chunks", payload,
                            timeout=timeout)
                        return
                except (_rpc.RPCError, _rpc.RpcFault):
                    pass
                # rung 1: ring successors hold replica CHUNKS — the dead
                # home's range re-executes from copies, not re-parse
                for m in cands[1:]:
                    try:
                        if m.info.name == my_name:
                            out = _exec_local(gi)
                        else:
                            out = _tasks.submit(cloud, m, "mr_chunks",
                                                payload, timeout=timeout)
                        _tasks._RECOVERED.inc(path="replica")
                        _flight.record(_flight.RECOVERY, "warn",
                                       "mr_group", path="replica",
                                       group=gi, member=m.info.name)
                        partials[gi] = out
                        return
                    except (_rpc.RPCError, _rpc.RpcFault):
                        continue
                # rung 2: any other healthy member (walks the ring for
                # the chunks itself)
                cand_names = {m.info.name for m in cands}
                for m in workers:
                    if (m.info.name in cand_names
                            or m.info.name == my_name or not m.healthy):
                        continue
                    try:
                        out = _tasks.submit(cloud, m, "mr_chunks",
                                            payload, timeout=timeout)
                        _tasks._RECOVERED.inc(path="survivor")
                        _flight.record(_flight.RECOVERY, "warn",
                                       "mr_group", path="survivor",
                                       group=gi, member=m.info.name)
                        partials[gi] = out
                        return
                    except (_rpc.RPCError, _rpc.RpcFault):
                        continue
                # rung 3: the caller itself, from replica chunks via the
                # store's ring walk — the last resort
                try:
                    partials[gi] = _exec_local(gi)
                    _tasks._RECOVERED.inc(path="local")
                    _flight.record(_flight.RECOVERY, "warn", "mr_group",
                                   path="local", group=gi)
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    errors[gi] = e

        threads = [threading.Thread(target=_run, args=(gi,), daemon=True)
                   for gi in range(len(groups))]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=timeout)
        finally:
            fo.end()

        for gi in range(len(groups)):
            if partials[gi] is None and errors[gi] is None:
                # never answered in the deadline: caller-local last resort
                partials[gi] = _exec_local(gi)
                _tasks._RECOVERED.inc(path="local")
                _flight.record(_flight.RECOVERY, "warn", "mr_group",
                               path="local", group=gi, deadline=True)
        for e in errors:
            if e is not None:
                raise e

        import jax

        op = _tasks._COMBINE[reduce]
        out = partials[0]
        for p in partials[1:]:
            out = jax.tree.map(op, out, p)
        return out


# ---------------------------------------------------------------------------
# REST surface helpers (/3/Frames chunk layout + replica health)


def layout_health(frame: Frame, cloud=None) -> Optional[Dict[str, Any]]:
    """Chunk layout + replica health for the /3/Frames listing: per
    group, whether the frozen home is still a healthy member and how
    many ring candidates for its anchor are currently alive.  Answers
    from membership state only — no ring traffic.  ``nbytes`` is the
    ENCODED wire size the chunks actually occupy on the ring
    (frame/codecs.py), not their dense f64 footprint."""
    layout = getattr(frame, "chunk_layout", None)
    if layout is None:
        return None
    if cloud is None:
        try:
            from h2o3_tpu.cluster import active_cloud

            cloud = active_cloud()
        except Exception:
            cloud = None
    store = getattr(frame, "_store", None)
    router = getattr(store, "router", None) if store is not None else None
    groups_out = []
    for grp in layout["groups"]:
        ent = {"group": grp["g"], "home": grp["home_name"],
               "chunks": [grp["lo"], grp["hi"]], "anchor": grp["anchor"]}
        if router is not None:
            cands = router.home_members(grp["anchor"], MAX_REPLICAS)
            ent["holders_alive"] = len(cands)
            ent["home_alive"] = bool(
                cands and any(m.info.ident == grp["home"] for m in cands))
        groups_out.append(ent)
    healthy = all(g.get("home_alive", True) for g in groups_out)
    return {
        "replicas": layout["replicas"],
        "espc": list(layout["espc"]),
        "nbytes": layout.get("nbytes", 0),
        "groups": groups_out,
        "healthy": healthy,
    }


#: module-level MR fns — importable on every member (one codebase per
#: cloud), used by the cluster bench's dist_frame cell and tests
def mr_sum_xy(cols, mask):
    import jax.numpy as jnp

    w = mask.astype(jnp.float32) if hasattr(mask, "astype") else mask
    return {
        "sx": jnp.sum(jnp.where(mask, cols["x"], 0.0)),
        "sy": jnp.sum(jnp.where(mask, cols["y"], 0.0)),
        "n": jnp.sum(w),
    }
