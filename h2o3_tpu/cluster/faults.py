"""Deterministic fault injection for the cluster stack — the chaos plane.

A process holds at most one :class:`FaultPlan`: a seed plus an ordered
list of :class:`FaultRule`\\ s.  The RPC layer consults the plan at two
choke points — the client's attempt loop and the server's frame handler —
so one small module can drop, delay, duplicate, reorder or black-hole
frames, partition node pairs, and crash a process on demand, without any
of those layers knowing more than "ask the plan".

Determinism is the contract that makes chaos scenarios assertable:

* count-based rules (``after``/``max_hits``) fire on exact match ordinals,
  independent of wall clock;
* probabilistic rules (``p < 1``) and sampled delays draw from a per-rule
  PRNG derived from ``(seed, rule_index)``, so two runs of a
  single-threaded workload under the same plan inject the same faults;
* the plan's shared PRNG also seeds the RPC retry ladder's full-jitter
  backoff, so even retry spacing replays under a fixed seed.

Rules are matched first-wins in list order.  Action semantics:

``drop``
    client side: the attempt fails with ``ConnectionError`` before any
    bytes move (a request frame lost in flight); server side: the method
    EXECUTES but the response frame is discarded and the connection
    closed — the classic lost-ack that forces the caller's retry through
    the idempotency memo.
``black_hole``
    client side: the attempt raises ``socket.timeout`` immediately —
    models a peer that swallows frames without consuming the caller's
    real wall clock; server side: same as ``drop``.
``partition``
    directional client-side ``drop`` matched on (src, dst) — two rules
    with swapped ends make a symmetric partition, one rule makes the
    asymmetric half.
``delay``
    sleep ``delay_ms`` before the attempt (client) or before dispatch
    (server) — the slow-node ladder.
``reorder``
    sleep a per-rule-PRNG uniform draw in ``[0, delay_ms]`` — concurrent
    frames overtake each other, which is how a FIFO-per-connection
    transport exhibits reordering.
``duplicate``
    client side only: after a successful attempt the SAME envelope (same
    idempotency token) is sent again — the server's dedup memo must make
    the duplicate invisible.
``crash``
    ``os._exit(137)`` — a SIGKILL-shaped death, no finalizers.

Enablement: :func:`install_from_env` reads ``H2O3_TPU_FAULT_PLAN`` (a
JSON plan, or ``@/path/to/plan.json``) at node boot; the test-only
RPC/REST nemesis surface registers when :func:`surface_enabled` — env
``H2O3_TPU_FAULTS=1`` or a plan env present.  Production processes set
neither and pay one ``is None`` check per consult point.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import json
import os
import random
import threading
from typing import Any, Dict, List, Optional

from h2o3_tpu.util import telemetry

_INJECTED = telemetry.counter(
    "cluster_faults_injected_total",
    "faults the active FaultPlan injected, by action",
    labels=("action",),
)

#: every action a rule may carry (validated at plan build, not at match)
ACTIONS = ("drop", "delay", "duplicate", "reorder", "black_hole",
           "partition", "crash")

#: sides a rule can bind to — the consult points in rpc.py
SIDES = ("client", "server")


@dataclasses.dataclass
class FaultRule:
    """One match-and-inject rule.  Globs (`fnmatch`) match the injecting
    node's name (``src``), the call target ident/address (``dst``) and
    the RPC method name."""

    action: str
    side: str = "client"
    src: str = "*"
    dst: str = "*"
    method: str = "*"
    #: probability a matching event injects (drawn from the rule's PRNG)
    p: float = 1.0
    #: skip the first N matching events (count-based scheduling)
    after: int = 0
    #: stop injecting after N hits; 0 = unlimited
    max_hits: int = 0
    #: delay/reorder magnitude
    delay_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {self.action!r}; one of {ACTIONS}")
        if self.side not in SIDES:
            raise ValueError(
                f"unknown fault side {self.side!r}; one of {SIDES}")


@dataclasses.dataclass(frozen=True)
class Directive:
    """What a consult point must do: the matched action plus a resolved
    delay in seconds (already sampled for ``reorder``)."""

    action: str
    delay_s: float = 0.0


_RULE_FIELDS = {f.name for f in dataclasses.fields(FaultRule)}


class FaultPlan:
    """Seeded, counter-tracked rule set; one per process at most."""

    def __init__(self, seed: int = 0,
                 rules: Optional[List[FaultRule]] = None) -> None:
        self.seed = int(seed)
        self.rules: List[FaultRule] = list(rules or [])
        self._lock = threading.Lock()
        #: shared PRNG — backoff jitter rides it so retry spacing replays
        self.rng = random.Random(self.seed)
        #: per-rule PRNGs: rule i's draws depend only on (seed, i) and
        #: its own match ordinal, never on other rules' traffic
        self._rngs = [random.Random((self.seed << 16) ^ i)
                      for i in range(len(self.rules))]
        self._matches = [0] * len(self.rules)
        self._hits = [0] * len(self.rules)

    def consult(self, side: str, src: str, dst: str,
                method: str) -> Optional[Directive]:
        """First matching rule that fires, as a :class:`Directive`."""
        for i, r in enumerate(self.rules):
            if r.side != side:
                continue
            if not (fnmatch.fnmatch(src or "", r.src)
                    and fnmatch.fnmatch(dst or "", r.dst)
                    and fnmatch.fnmatch(method or "", r.method)):
                continue
            with self._lock:
                self._matches[i] += 1
                if self._matches[i] <= r.after:
                    continue
                if r.max_hits and self._hits[i] >= r.max_hits:
                    continue
                if r.p < 1.0 and self._rngs[i].random() >= r.p:
                    continue
                self._hits[i] += 1
                delay = r.delay_ms / 1000.0
                if r.action == "reorder":
                    delay = self._rngs[i].uniform(0.0, delay)
            _INJECTED.inc(action=r.action)
            return Directive(r.action, delay)
        return None

    def hits(self) -> List[int]:
        """Per-rule injection counts (a nemesis asserts its faults LANDED
        — a scenario whose rules never fired proves nothing)."""
        with self._lock:
            return list(self._hits)

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed,
                "rules": [dataclasses.asdict(r) for r in self.rules]}


def plan_from_dict(d: Dict[str, Any]) -> FaultPlan:
    """Build a plan from its JSON shape; unknown rule fields are ignored
    so a newer nemesis script can drive an older node."""
    rules = [
        FaultRule(**{k: v for k, v in r.items() if k in _RULE_FIELDS})
        for r in d.get("rules", [])
    ]
    return FaultPlan(seed=int(d.get("seed", 0)), rules=rules)


# ---------------------------------------------------------------------------
# process-wide singleton + enablement

_PLAN: Optional[FaultPlan] = None
#: jitter source when no plan is active (unseeded: production spread)
_BACKOFF_RNG = random.Random()


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def set_plan(plan: Optional[FaultPlan]) -> None:
    global _PLAN
    _PLAN = plan


def clear_plan() -> None:
    set_plan(None)


def consult_subtask(node_name: str, task: str) -> Optional[Directive]:
    """Server-side consult for one named DTask: matches method
    ``dtask:<task>`` so a plan can target a single task kind on a single
    node (the RPC-layer consult only sees the umbrella ``dtask``).
    Returns None when no plan is active."""
    plan = _PLAN
    if plan is None:
        return None
    return plan.consult("server", node_name or "", "", f"dtask:{task}")


def backoff_rng() -> random.Random:
    """The retry ladder's jitter source: the active plan's seeded PRNG
    under chaos (deterministic spacing), a plain Random otherwise."""
    plan = _PLAN
    return plan.rng if plan is not None else _BACKOFF_RNG


def surface_enabled() -> bool:
    """Whether the test-only nemesis RPC/REST surface may register."""
    return (os.environ.get("H2O3_TPU_FAULTS") == "1"
            or bool(os.environ.get("H2O3_TPU_FAULT_PLAN")))


def install_from_env() -> Optional[FaultPlan]:
    """Install the plan ``H2O3_TPU_FAULT_PLAN`` describes (inline JSON or
    ``@/path``); returns it, or None when the env is unset."""
    spec = os.environ.get("H2O3_TPU_FAULT_PLAN", "").strip()
    if not spec:
        return None
    if spec.startswith("@"):
        with open(spec[1:]) as f:
            spec = f.read()
    plan = plan_from_dict(json.loads(spec))
    set_plan(plan)
    return plan


def crash_now(code: int = 137) -> None:
    """SIGKILL-shaped death: no atexit, no flush, no goodbye frame."""
    os._exit(code)
