"""L3b: remote task execution — DTask fan-out over cloud members.

Reference: ``water/DTask.java`` ships a serialized task to a node, runs
it there, ships the result back; ``water/MRTask.java:96-127`` composes
that into the node-tree fan-out/reduce every algorithm rides.  Here a
task is a registered name + a pickled payload (functions cross the wire
by module reference — one codebase per cloud, like the reference's
shared classpath), executed on the receiving node's RPC thread.

Two fan-outs mirror the two distributed workloads this repro has:

* :func:`distributed_map_reduce` — slice a frame's host columns into one
  contiguous row range per healthy member, run the member's range through
  the local :func:`~h2o3_tpu.compute.mapreduce.map_reduce` (shard_map +
  psum over that node's own device mesh), and combine the per-node
  partials on the caller.  A cloud of one (or none) takes the plain local
  path, bit-for-bit.
* :func:`distributed_parse_chunks` — round-robin CSV chunk tokenization
  (``frame/parse._parse_chunk``) over members, reducing with the parse
  pipeline's own phase-2 merge, so multi-node parse shares the serial
  path's bit-identity contract.

When the cloud has a DKV store installed (:func:`dkv.install`), both
fan-outs upgrade to CHUNK HOMES (``cluster/frames.py``): parse lands
tokenized chunks on their ring homes with replication and map_reduce
over the resulting :class:`~h2o3_tpu.cluster.frames.DistFrame` executes
map-side on each home with only partials crossing the wire.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster.membership import Cloud, Member
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

_TASKS_METER = telemetry.counter(
    "cluster_tasks_total", "remote DTask executions",
    labels=("task", "result"),
)
_FANOUT = telemetry.gauge(
    "cluster_task_fanout", "members the most recent fan-out spanned")
_RECOVERED = telemetry.counter(
    "cluster_fanout_recovered_total",
    "fan-out work units re-run after a member failure: path=replica "
    "re-executed a chunk group from replica chunks on the dead home's "
    "ring successors, path=survivor rescheduled onto another live "
    "member, path=local fell back to the caller (the last resort)",
    labels=("path",),
)

#: name -> handler; a task must be registered on every node of the cloud
#: (one codebase per cloud), like DTask classes on the shared classpath
_REGISTRY: Dict[str, Callable[[Any], Any]] = {}

#: name -> handler(payload, cloud, store) for tasks that need the node's
#: own cloud + DKV store (the chunk-home tasks store/read ring data)
_CTX_REGISTRY: Dict[str, Callable[[Any, Any, Any], Any]] = {}


def register_task(name: str, fn: Optional[Callable[[Any], Any]] = None):
    """Register (or decorate) a named task handler."""
    def _reg(f: Callable[[Any], Any]) -> Callable[[Any], Any]:
        _REGISTRY[name] = f
        return f
    return _reg(fn) if fn is not None else _reg


def register_ctx_task(name: str,
                      fn: Optional[Callable[[Any, Any, Any], Any]] = None):
    """Register (or decorate) a context task handler — called as
    ``fn(payload, cloud, store)`` with the EXECUTING node's cloud and
    installed DKV store."""
    def _reg(f: Callable[[Any, Any, Any], Any]):
        _CTX_REGISTRY[name] = f
        return f
    return _reg(fn) if fn is not None else _reg


def _consult_subtask_faults(cloud, name: str) -> None:
    """Per-task nemesis hook: the RPC server consult sees every dtask as
    method ``dtask``; this one matches ``dtask:<name>`` so a chaos plan
    can target one task kind on one node (e.g. delay only ``mr_chunks``
    on the victim home)."""
    from h2o3_tpu.cluster import faults as _faults

    d = _faults.consult_subtask(
        getattr(getattr(cloud, "info", None), "name", "") or "", name)
    if d is None:
        return
    if d.action == "crash":
        _faults.crash_now()
    if d.action in ("delay", "reorder") and d.delay_s > 0:
        time.sleep(d.delay_s)
    elif d.action in ("drop", "black_hole"):
        raise _rpc.RpcFault(f"fault-injected drop of dtask:{name}", code=503)


def _run_task(payload: Dict[str, Any], cloud=None, store=None) -> Any:
    name = payload.get("task")
    cfn = _CTX_REGISTRY.get(name)
    fn = _REGISTRY.get(name)
    if cfn is None and fn is None:
        _TASKS_METER.inc(task=str(name), result="unknown")
        raise _rpc.RpcFault(f"unknown task {name!r}", code=404)
    _consult_subtask_faults(cloud, str(name))
    try:
        if cfn is not None:
            if store is None:
                store = getattr(cloud, "dkv_store", None)
            out = cfn(payload.get("payload"), cloud, store)
        else:
            out = fn(payload.get("payload"))
    except Exception:
        _TASKS_METER.inc(task=str(name), result="error")
        raise
    _TASKS_METER.inc(task=str(name), result="ok")
    return out


def install(cloud: Cloud, store=None) -> None:
    """Register the DTask endpoint on a cloud's RPC server.  ``store``
    resolves lazily from ``cloud.dkv_store`` (set by :func:`dkv.install`)
    so install order between the two does not matter."""
    cloud.rpc_server.register(
        "dtask",
        lambda p: _run_task(
            p, cloud, store or getattr(cloud, "dkv_store", None)))


def submit(cloud: Cloud, member: Member, task: str, payload: Any = None,
           timeout: float = 120.0) -> Any:
    """Run one named task on one member and return its result."""
    return cloud.client.call(
        member.info.addr, "dtask", {"task": task, "payload": payload},
        timeout=timeout, target=member.info.ident)


# ---------------------------------------------------------------------------
# built-in tasks


@register_task("echo")
def _task_echo(payload: Any) -> Any:
    return payload


def _table_from_columns(columns: Dict[str, np.ndarray]):
    """Row-shard a dict of host columns onto THIS node's device mesh —
    the per-node half of a distributed map_reduce."""
    from h2o3_tpu.compute.mapreduce import FrameTable
    from h2o3_tpu.parallel.mesh import default_mesh, row_mask, shard_rows

    mesh = default_mesh()
    arrays = {}
    n = 0
    for name, host in columns.items():
        arr, n = shard_rows(
            np.asarray(host, dtype=np.float32), mesh, fill=np.nan)
        arrays[name] = arr
    some = next(iter(arrays.values()))
    return FrameTable(arrays, row_mask(n, some.shape[0], mesh), n, mesh)


# XLA:CPU wedges when multi-device collective programs are launched
# concurrently from several Python threads of one process: the virtual
# device threads interleave across the two programs' collectives and wait
# on each other forever.  Only the in-process test topology (many Clouds,
# one interpreter) can hit this — a real node owns its process — so a
# process-global lock around the shard execution costs nothing in
# production while making the in-process fan-out deadlock-free.
_SHARD_EXEC_LOCK = threading.Lock()


def _mr_shard_local(fn: Callable, columns: Dict[str, np.ndarray],
                    reduce: str) -> Any:
    """Run fn over one node's row range; partials come back as numpy so
    they frame-serialize without device references."""
    import jax

    from h2o3_tpu.compute.mapreduce import map_reduce

    t0 = time.perf_counter()
    with _SHARD_EXEC_LOCK:
        out = map_reduce(fn, _table_from_columns(columns), reduce=reduce)
        out = jax.tree.map(np.asarray, out)
    # on a remote node this runs under the rpc_server span, so the wall
    # (lock wait included — it is wall the trace experienced) folds back
    # to the ORIGINATING trace under the serving node's name
    _ledger.charge(_ledger.SHARD_WALL_SECONDS, time.perf_counter() - t0)
    return out


@register_task("mr_shard")
def _task_mr_shard(payload: Dict[str, Any]) -> Any:
    return _mr_shard_local(
        payload["fn"], payload["columns"], payload.get("reduce", "sum"))


@register_task("parse_chunk")
def _task_parse_chunk(payload: Dict[str, Any]) -> Any:
    from h2o3_tpu.frame import parse as _parse

    setup = payload["setup"]
    na = frozenset(setup.na_strings)
    napack = _parse._pipeline_napack(setup)
    return _parse._parse_chunk(payload["chunk"], setup, na, napack)


@register_ctx_task("parse_chunk_home")
def _task_parse_chunk_home(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.cluster import frames as _frames

    if store is None:
        raise _rpc.RpcFault("no DKV store installed on this node", code=503)
    return _frames.parse_chunk_home(payload, cloud, store)


@register_ctx_task("mr_chunks")
def _task_mr_chunks(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.cluster import frames as _frames

    if store is None:
        raise _rpc.RpcFault("no DKV store installed on this node", code=503)
    return _frames.mr_chunks(payload, cloud, store)


@register_ctx_task("search_init")
def _task_search_init(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.cluster import search as _search

    return _search.search_init(payload, cloud, store)


@register_ctx_task("search_cell")
def _task_search_cell(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.cluster import search as _search

    return _search.search_cell(payload, cloud, store)


@register_ctx_task("search_end")
def _task_search_end(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.cluster import search as _search

    return _search.search_end(payload, cloud, store)


@register_ctx_task("hist_open")
def _task_hist_open(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.models.tree import dist_hist as _dh

    return _dh.hist_open(payload, cloud, store)


@register_ctx_task("hist_bind")
def _task_hist_bind(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.models.tree import dist_hist as _dh

    return _dh.hist_bind(payload, cloud, store)


@register_ctx_task("hist_level")
def _task_hist_level(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.models.tree import dist_hist as _dh

    return _dh.hist_level(payload, cloud, store)


@register_ctx_task("hist_levels")
def _task_hist_levels(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.models.tree import dist_hist as _dh

    return _dh.hist_levels(payload, cloud, store)


@register_ctx_task("hist_replay")
def _task_hist_replay(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.models.tree import dist_hist as _dh

    return _dh.hist_replay(payload, cloud, store)


@register_ctx_task("hist_fin")
def _task_hist_fin(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.models.tree import dist_hist as _dh

    return _dh.hist_fin(payload, cloud, store)


@register_ctx_task("rapids_exec")
def _task_rapids_exec(payload: Dict[str, Any], cloud, store) -> Any:
    from h2o3_tpu.rapids import dist_exec as _dx

    return _dx.rapids_exec(payload, cloud, store)


@register_ctx_task("predict_remote")
def _task_predict_remote(payload: Dict[str, Any], cloud, store) -> Any:
    """Serving plane: score a forwarded bundle on this node — the
    model's ring home (where bundles from N front doors coalesce into
    one dispatch) or a replica taking spilled/failed-over load.  See
    cluster/serving.py."""
    from h2o3_tpu.cluster import serving as _serving

    return _serving.serve_entries(
        payload["model_key"], payload["entries"], store)


# ---------------------------------------------------------------------------
# fan-outs


_COMBINE = {"sum": np.add, "max": np.maximum, "min": np.minimum}


def _healthy_workers(cloud: Cloud) -> List[Member]:
    return [m for m in cloud.members_sorted()
            if m.healthy and not m.info.client]


def distributed_map_reduce(
    fn: Callable,
    columns: Dict[str, np.ndarray],
    reduce: str = "sum",
    cloud: Optional[Cloud] = None,
    timeout: float = 300.0,
) -> Any:
    """MRTask over the cloud: contiguous row ranges fan out to members,
    each runs the local shard_map+psum ``map_reduce`` over its range, and
    the partials combine here in canonical member order.

    ``fn`` must be importable on every member (module-level, one shared
    codebase) — a closure raises immediately rather than failing remotely.
    Falls back to plain local execution when no multi-node cloud is live.

    SELF-healing, not caller-healing: a failed member's range is first
    rescheduled onto the surviving members (canonical order, starting at
    the failed member's ring neighbor) so the cluster — not the caller —
    absorbs the loss; the caller re-runs a range locally only as the
    last resort.  ``cluster_fanout_recovered_total{path}`` distinguishes
    the two.
    """
    if reduce not in _COMBINE:
        raise ValueError(
            f"unknown reduce {reduce!r}; valid choices: {sorted(_COMBINE)}")
    if cloud is None:
        from h2o3_tpu.cluster import active_cloud

        cloud = active_cloud()
    if getattr(columns, "chunk_layout", None) is not None:
        # a chunk-homed DistFrame: execute map-side on each chunk group's
        # ring home — only partials cross the wire (cluster/frames.py)
        from h2o3_tpu.cluster import frames as _frames

        return _frames.map_reduce_chunk_homed(
            fn, columns, reduce=reduce, cloud=cloud, timeout=timeout)
    if cloud is None:
        return _mr_shard_local(fn, columns, reduce)
    workers = _healthy_workers(cloud)
    if len(workers) < 2:
        return _mr_shard_local(fn, columns, reduce)
    if getattr(fn, "__name__", "<lambda>") == "<lambda>" or \
            getattr(fn, "__closure__", None):
        raise ValueError(
            "distributed map_reduce needs a module-level fn (it crosses "
            "the wire by module reference); got a lambda/closure")

    n = len(next(iter(columns.values())))
    k = len(workers)
    bounds = [round(i * n / k) for i in range(k + 1)]
    _FANOUT.set(k)
    partials: List[Any] = [None] * k
    errors: List[Optional[Exception]] = [None] * k
    #: members whose submit failed — later reschedules skip them (set
    #: mutations are GIL-atomic; worst case a race costs one wasted RPC)
    failed: set = set()

    def _reschedule(i: int, part: Dict[str, np.ndarray]) -> Any:
        """Re-run range ``i`` on a surviving member; caller-local only
        when every survivor is gone or also fails."""
        for step in range(1, k):
            m2 = workers[(i + step) % k]
            if (m2.info.name in failed
                    or m2.info.name == cloud.info.name
                    or not m2.healthy):
                continue
            try:
                out = submit(cloud, m2, "mr_shard",
                             {"fn": fn, "columns": part, "reduce": reduce},
                             timeout=timeout)
                _RECOVERED.inc(path="survivor")
                _flight.record(_flight.RECOVERY, "warn", "mr_range",
                               path="survivor", range=i,
                               member=m2.info.name)
                return out
            except _rpc.RPCError:
                failed.add(m2.info.name)
        _RECOVERED.inc(path="local")
        _flight.record(_flight.RECOVERY, "warn", "mr_range",
                       path="local", range=i)
        return _mr_shard_local(fn, part, reduce)

    # one span covers the whole fan-out; its context is captured and handed
    # to every worker thread (spans are thread-local, so without the explicit
    # hand-off each member's work would mint its own disconnected trace) —
    # the RPC client then rides the per-member span across the wire, so one
    # trace_id threads caller -> member span -> remote execution
    with telemetry.Span("distributed_map_reduce", members=k, rows=int(n),
                        reduce=reduce):
        ctx = telemetry.current_trace_context()
        # the watchdog's fanout_stalled rule reads this context: ranges
        # scheduled now, progress ticked as each partial lands
        fo = _flight.FANOUTS.begin("map_reduce", k, rows=int(n))
        _flight.record(_flight.FANOUT, "info", "schedule",
                       kind="map_reduce", members=k, rows=int(n))

        def _run(i: int, member: Member) -> None:
            lo, hi = bounds[i], bounds[i + 1]
            part = {name: np.ascontiguousarray(arr[lo:hi])
                    for name, arr in columns.items()}
            if hi <= lo:
                fo.progress()
                return  # empty range contributes the identity (skipped)
            with telemetry.Span(
                    "mr_member", trace_id=ctx["trace_id"],
                    parent_id=ctx["span_id"], member=member.info.name,
                    lo=lo, hi=hi):
                try:
                    if member.info.name == cloud.info.name:
                        partials[i] = _mr_shard_local(fn, part, reduce)
                    else:
                        partials[i] = submit(
                            cloud, member, "mr_shard",
                            {"fn": fn, "columns": part, "reduce": reduce},
                            timeout=timeout)
                except _rpc.RPCError as e:
                    errors[i] = e
                    failed.add(member.info.name)
                    partials[i] = _reschedule(i, part)
                finally:
                    fo.progress()

        threads = [threading.Thread(target=_run, args=(i, m), daemon=True)
                   for i, m in enumerate(workers)]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=timeout)
        finally:
            fo.end()

        # take ONE snapshot per range: a member that answered contributes its
        # partial; a member that failed (error) already recovered inside _run;
        # a member that never answered inside the deadline re-runs HERE — a
        # silent missing range would be a silently wrong reduction
        recovered = 0
        parts = []
        for i in range(k):
            lo, hi = bounds[i], bounds[i + 1]
            if hi <= lo:
                continue
            p = partials[i]
            if p is None:
                # the member never answered inside the deadline: the
                # fan-out already consumed its full timeout, so the last
                # resort (caller-local) is the only honest option left
                part = {name: np.ascontiguousarray(arr[lo:hi])
                        for name, arr in columns.items()}
                p = _mr_shard_local(fn, part, reduce)
                _RECOVERED.inc(path="local")
                _flight.record(_flight.RECOVERY, "warn", "mr_range",
                               path="local", range=i, deadline=True)
                recovered += 1
            parts.append(p)
        if recovered or any(e is not None for e in errors):
            from h2o3_tpu.util.log import get_logger

            get_logger("cluster").warning(
                "map_reduce fan-out recovered %d member range(s) locally",
                recovered + sum(1 for e in errors if e is not None))

        if not parts:  # zero-row input: the local path defines the answer
            return _mr_shard_local(fn, columns, reduce)

        import jax

        op = _COMBINE[reduce]
        out = parts[0]
        for p in parts[1:]:
            out = jax.tree.map(op, out, p)
        return out


def distributed_parse_chunks(
    chunks: Sequence[bytes],
    setup,
    cloud: Optional[Cloud] = None,
    timeout: float = 300.0,
    key: Optional[str] = None,
):
    """Phase-1 chunk tokenization fanned over cloud members.  On a cloud
    with a live DKV ring this lands each chunk ON its ring home with
    replication and returns a lazy chunk-homed
    :class:`~h2o3_tpu.cluster.frames.DistFrame` (``key`` names it; see
    ``cluster/frames.py``).  Without a routable store it round-robins
    tokenization and reduces with the pipeline's own phase-2 merge —
    either way the frame the caller observes is bit-identical to the
    serial path.  Local-only when no multi-node cloud is live."""
    from h2o3_tpu.frame import parse as _parse

    na = frozenset(setup.na_strings)
    if cloud is None:
        from h2o3_tpu.cluster import active_cloud

        cloud = active_cloud()
    workers = _healthy_workers(cloud) if cloud is not None else []
    results: List[Any] = [None] * len(chunks)
    if len(workers) < 2:
        napack = _parse._pipeline_napack(setup)
        for i, chunk in enumerate(chunks):
            results[i] = _parse._parse_chunk(chunk, setup, na, napack)
        return _parse._reduce_chunks(results, setup)
    store = getattr(cloud, "dkv_store", None)
    router = getattr(store, "router", None) if store is not None else None
    if router is not None and router.active():
        from h2o3_tpu.cluster import frames as _frames

        return _frames.distributed_parse_to_homes(
            chunks, setup, cloud, store=store, timeout=timeout, key=key)
    _FANOUT.set(len(workers))
    napack = _parse._pipeline_napack(setup)
    failed: set = set()

    def _recover_chunk(i: int, chunk: bytes, first: Member):
        """Reschedule a failed chunk onto surviving members before the
        caller-local last resort (mirrors distributed_map_reduce)."""
        for step in range(1, len(workers)):
            m2 = workers[(i + step) % len(workers)]
            if (m2.info.name in failed
                    or m2.info.name in (first.info.name, cloud.info.name)
                    or not m2.healthy):
                continue
            try:
                out = submit(cloud, m2, "parse_chunk",
                             {"chunk": chunk, "setup": setup},
                             timeout=timeout)
                _RECOVERED.inc(path="survivor")
                _flight.record(_flight.RECOVERY, "warn", "parse_chunk",
                               path="survivor", chunk=i,
                               member=m2.info.name)
                return out
            except _rpc.RPCError:
                failed.add(m2.info.name)
        _RECOVERED.inc(path="local")
        _flight.record(_flight.RECOVERY, "warn", "parse_chunk",
                       path="local", chunk=i)
        return _parse._parse_chunk(chunk, setup, na, napack)

    with telemetry.Span("distributed_parse", chunks=len(chunks),
                        members=len(workers)):
        ctx = telemetry.current_trace_context()
        fo = _flight.FANOUTS.begin("parse", len(chunks),
                                   members=len(workers))
        _flight.record(_flight.FANOUT, "info", "schedule", kind="parse",
                       chunks=len(chunks), members=len(workers))

        def _run(i: int, chunk: bytes, member: Member) -> None:
            # executor threads are not the caller's thread: join its trace
            # explicitly so remote chunk tokenization shows in one tree
            with telemetry.Span(
                    "parse_chunk_remote", trace_id=ctx["trace_id"],
                    parent_id=ctx["span_id"], member=member.info.name,
                    chunk=i):
                try:
                    if member.info.name == cloud.info.name:
                        results[i] = _parse._parse_chunk(
                            chunk, setup, na, napack)
                    else:
                        results[i] = submit(
                            cloud, member, "parse_chunk",
                            {"chunk": chunk, "setup": setup},
                            timeout=timeout)
                except _rpc.RPCError:
                    failed.add(member.info.name)
                    results[i] = _recover_chunk(i, chunk, member)
                finally:
                    fo.progress()

        # bounded fan-out: a couple of chunks in flight per member pipelines
        # the stream at constant memory — one thread (and one pickled copy
        # of its chunk) per chunk at once would hold ~2x the input resident
        from concurrent.futures import ThreadPoolExecutor
        from concurrent.futures import wait as _futures_wait

        ex = ThreadPoolExecutor(
            max_workers=2 * len(workers), thread_name_prefix="parse-fanout")
        try:
            futs = [ex.submit(_run, i, c, workers[i % len(workers)])
                    for i, c in enumerate(chunks)]
            _futures_wait(futs, timeout=timeout)
            ex.shutdown(wait=False, cancel_futures=True)
        finally:
            fo.end()
        for i, r in enumerate(results):
            if r is None:  # member never answered in time: tokenize here
                _RECOVERED.inc(path="local")
                _flight.record(_flight.RECOVERY, "warn", "parse_chunk",
                               path="local", chunk=i, deadline=True)
                results[i] = _parse._parse_chunk(chunks[i], setup, na, napack)
        return _parse._reduce_chunks(results, setup)
