"""Distributed model search — grid/AutoML cells fanned across cluster members.

The caller (the node running GridSearch or AutoML) partitions independent
model builds — "cells" — over the cloud's DTask plane (``cluster/tasks.py``):
``search_init`` ships the training frame(s) to each member ONCE,
``search_cell`` trains one cell there and returns ``(hyperparams, scoring
summary, serialized model artifact)`` — the model rehydrates on the caller
through ``models/persist.py``, so training rows cross the wire per member
and never per model (the XGBoost-GPU merge-only-partials discipline
applied to AutoML).

Determinism contract: per-cell seeds derive from ``(search_seed, canonical
cell key)`` — never dispatch or completion order — and the caller records
results in canonical walk order, so the resulting Grid/Leaderboard is
bit-identical to a single-node run at a fixed seed regardless of member
count or scheduling.

Recovery ladder (composing the fan-out and snapshot mechanisms): a member
dying mid-search costs only its in-flight cells — survivors re-claim them
(``cluster_search_recovered_total{path="survivor"}``) and the caller
trains the remainder itself only as the last resort (``path="local"``) —
while the caller's recovery snapshot records per-cell completion so
``auto_recover`` resumes an interrupted distributed grid without
retraining finished cells.

Progress streams back per model: members call the caller's
``search_progress`` RPC as cells start and finish, so ``/3/Jobs`` and
``/3/Grids/{id}`` show live cluster-wide completion.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster import tasks as _tasks
from h2o3_tpu.cluster.membership import Cloud
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry
from h2o3_tpu.util.log import get_logger

log = get_logger("cluster.search")

_CELLS = telemetry.counter(
    "cluster_search_cells_total",
    "search cells (one hyperparameter combo = one model build) executed "
    "anywhere in the cloud; result=ok|error",
    labels=("result",),
)
_RECOVERED = telemetry.counter(
    "cluster_search_recovered_total",
    "search cells re-claimed after a member failure: path=survivor "
    "completed by another live member, path=local fell back to the "
    "caller (the last resort)",
    labels=("path",),
)
_PROGRESS_EVENTS = telemetry.counter(
    "cluster_search_progress_total",
    "per-model search_progress events observed by the caller; "
    "status=building|done|error",
    labels=("status",),
)

#: RPC error code a member raises when a cell's MODEL BUILD failed —
#: deterministic, so the caller records a grid failure instead of
#: rescheduling (an infra 5xx reschedules onto a survivor instead)
CELL_BUILD_FAILED = 520


def _dist_enabled() -> bool:
    return os.environ.get("H2O3_TPU_SEARCH_DIST", "1").lower() not in (
        "0", "false", "off")


def _inflight_per_member() -> int:
    return max(1, int(os.environ.get("H2O3_TPU_SEARCH_INFLIGHT", "2")))


def _cell_timeout() -> float:
    return float(os.environ.get("H2O3_TPU_SEARCH_TIMEOUT_S", "600"))


def _cache_cap() -> int:
    return max(1, int(os.environ.get("H2O3_TPU_SEARCH_CACHE", "4")))


def search_cloud() -> Optional[Cloud]:
    """The live cloud when distribution is on and at least two healthy
    non-client members exist, else None (local execution)."""
    if not _dist_enabled():
        return None
    from h2o3_tpu.cluster import active_cloud

    cloud = active_cloud()
    if cloud is None:
        return None
    if len(_tasks._healthy_workers(cloud)) < 2:
        return None
    return cloud


# ---------------------------------------------------------------------------
# determinism: canonical cell keys and per-cell seeds live in models/grid.py
# (the home of the walk they canonicalize); re-exported here as the search
# plane's public contract
from h2o3_tpu.models.grid import cell_key, cell_seed  # noqa: E402,F401

# ---------------------------------------------------------------------------
# wire format: frames cross once per member, models come back as blobs


def frame_payload(fr) -> Dict[str, Any]:
    """A Frame as plain host data (no rollup caches, no device arrays).

    A chunk-homed :class:`~h2o3_tpu.cluster.frames.DistFrame` ships as a
    tiny ``__dist__`` reference instead — its rows are already on the
    ring, so members rebuild the handle from the layout/setup keys and
    train against the homes directly (map-side histograms for the tree
    algos, lazy gather for everything else) rather than receiving a full
    copy per member."""
    if getattr(fr, "chunk_layout", None) is not None:
        return {"__dist__": {
            "frame_key": fr.key,
            "stamp": fr.chunk_layout["stamp"],
        }}
    return {
        "names": list(fr.names),
        "cols": [
            {
                "name": c.name,
                "type": c.type.name,
                "domain": list(c.domain) if c.domain else None,
                "data": np.asarray(c.data),
            }
            for c in fr.columns
        ],
    }


def frame_restore(payload: Optional[Dict[str, Any]], store=None):
    if payload is None:
        return None
    ref = payload.get("__dist__")
    if ref is not None:
        from h2o3_tpu.cluster import frames as _frames

        if store is None:
            raise _rpc.RpcFault(
                f"no DKV store on this member to resolve chunk-homed "
                f"frame {ref['frame_key']!r}", code=503)
        layout = _frames._layout_for(store, ref["frame_key"], ref["stamp"])
        setup = store.get(_frames.setup_key(ref["frame_key"]))
        if setup is None:
            raise _rpc.RpcFault(
                f"parse setup for frame {ref['frame_key']!r} unreachable "
                f"on the ring", code=404)
        return _frames.DistFrame(
            layout, _frames.setup_from_payload(setup), store)
    from h2o3_tpu.frame.frame import Column, ColType, Frame

    cols = [
        Column(d["name"], d["data"], ColType[d["type"]], d["domain"])
        for d in payload["cols"]
    ]
    return Frame(cols)


def model_to_blob(model) -> bytes:
    from h2o3_tpu.models.persist import dumps_model

    return dumps_model(model)


def model_from_blob(blob: bytes):
    """Rehydrate a member-built model on the caller and register it.  A
    key collision with a live different object (possible across node
    processes — keys are minted per-process) re-keys the arrival."""
    from h2o3_tpu.keyed import DKV
    from h2o3_tpu.models.persist import loads_model

    m = loads_model(blob, register=False)
    if getattr(m, "key", None) and DKV.get(m.key) is not None:
        m.key = DKV.make_key("model")
    if getattr(m, "key", None):
        DKV.put(m.key, m)
    return m


# ---------------------------------------------------------------------------
# member side: cached search context + cell execution

#: search_id -> {"frame": Frame, "valid": Frame|None}; tiny LRU so a
#: member never holds more than a few live searches' training data
_CTX_CACHE: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
_CTX_LOCK = threading.Lock()


def _ctx_put(search_id: str, ctx: Dict[str, Any]) -> None:
    with _CTX_LOCK:
        _CTX_CACHE[search_id] = ctx
        _CTX_CACHE.move_to_end(search_id)
        while len(_CTX_CACHE) > _cache_cap():
            _CTX_CACHE.popitem(last=False)


def _ctx_get(search_id: str) -> Optional[Dict[str, Any]]:
    with _CTX_LOCK:
        ctx = _CTX_CACHE.get(search_id)
        if ctx is not None:
            _CTX_CACHE.move_to_end(search_id)
        return ctx


def _ctx_drop(search_id: str) -> None:
    with _CTX_LOCK:
        _CTX_CACHE.pop(search_id, None)


def search_init(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    """DTask ``search_init``: cache the search's frames on this member."""
    _ctx_put(payload["search_id"], {
        "frame": frame_restore(payload["frame"], store),
        "valid": frame_restore(payload.get("valid"), store),
    })
    return {"ok": True}


def search_end(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    """DTask ``search_end``: drop the cached context (best-effort)."""
    _ctx_drop(payload["search_id"])
    return {"ok": True}


def _send_progress(cloud, caller: Optional[Dict[str, Any]],
                   event: Dict[str, Any]) -> None:
    """Stream one per-model event to the caller's search_progress RPC.
    Best-effort: progress is cosmetic; results ride the task response."""
    if caller is None or cloud is None:
        _note_progress(event)  # caller-local build: no wire needed
        return
    if caller.get("name") == getattr(
            getattr(cloud, "info", None), "name", None):
        _note_progress(event)
        return
    try:
        cloud.client.call(
            tuple(caller["addr"]), "search_progress", event,
            timeout=5.0, target=caller.get("ident", ""), retries=0)
    except Exception:
        pass


def _execute_cell(payload: Dict[str, Any], cloud) -> Dict[str, Any]:
    """Train one cell against the cached context.  Shared by the member
    DTask handler and the caller's local path so both meter identically."""
    search_id = payload["search_id"]
    ctx = _ctx_get(search_id)
    if ctx is None:
        raise _rpc.RpcFault(
            f"no cached context for search {search_id!r}", code=404)
    caller = payload.get("caller")
    event = {
        "search_id": search_id,
        "job_key": payload.get("job_key"),
        "index": payload["index"],
        "total": payload.get("total", 0),
        "hp": payload.get("hp", {}),
        "member": getattr(getattr(cloud, "info", None), "name", "local"),
    }
    _send_progress(cloud, caller, {**event, "status": "building"})
    builder_cls = payload["builder_cls"]
    params = payload["params"]
    try:
        # XLA:CPU wedges when several threads of one process launch
        # multi-device collective programs concurrently (see
        # tasks._SHARD_EXEC_LOCK) — model training runs shard_map+psum,
        # so every cell build in the process serializes behind that lock
        t0 = time.perf_counter()
        with _tasks._SHARD_EXEC_LOCK:
            model = builder_cls(params).train(ctx["frame"], ctx["valid"])
        # a member-executed cell runs under the rpc_server span, so the
        # wall bills the originating search trace under this node's name
        _ledger.charge(
            _ledger.SEARCH_CELL_SECONDS, time.perf_counter() - t0)
    except Exception as e:
        _CELLS.inc(result="error")
        _send_progress(cloud, caller, {**event, "status": "error"})
        raise _rpc.RpcFault(
            f"cell build failed: {type(e).__name__}: {e}",
            code=CELL_BUILD_FAILED)
    from h2o3_tpu.models.grid import metric_value

    v, larger = metric_value(model, payload.get("stopping_metric", "auto"))
    summary = {"metric": v, "larger": larger}
    _CELLS.inc(result="ok")
    _send_progress(
        cloud, caller, {**event, "status": "done", "metric": v})
    return {
        "index": payload["index"],
        "hp": payload.get("hp", {}),
        "summary": summary,
        "model": model_to_blob(model),
        "member": event["member"],
    }


def search_cell(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    """DTask ``search_cell``: one hyperparameter combo -> one model."""
    return _execute_cell(payload, cloud)


# ---------------------------------------------------------------------------
# caller side: live progress registry + search_progress RPC

#: search_id -> {"total", "done", "building", "errors", "by_member"}
_PROGRESS: Dict[str, Dict[str, Any]] = {}
_PROGRESS_LOCK = threading.Lock()


def _note_progress(event: Dict[str, Any]) -> Dict[str, Any]:
    """Fold one per-model event into the live registry and the Job."""
    status = str(event.get("status", ""))
    _PROGRESS_EVENTS.inc(status=status or "unknown")
    sid = event.get("search_id", "")
    with _PROGRESS_LOCK:
        st = _PROGRESS.setdefault(sid, {
            "total": 0, "done": 0, "errors": 0,
            "building": [], "by_member": {},
        })
        if event.get("total"):
            st["total"] = int(event["total"])
        member = event.get("member", "?")
        idx = event.get("index")
        if status == "building":
            if idx not in st["building"]:
                st["building"].append(idx)
        else:
            if idx in st["building"]:
                st["building"].remove(idx)
        if status == "done":
            st["done"] += 1
            st["by_member"][member] = st["by_member"].get(member, 0) + 1
        elif status == "error":
            st["errors"] += 1
        snapshot = {k: (list(v) if isinstance(v, list) else
                        dict(v) if isinstance(v, dict) else v)
                    for k, v in st.items()}
    job_key = event.get("job_key")
    if job_key:
        from h2o3_tpu.keyed import DKV

        job = DKV.get(job_key)
        if job is not None and snapshot["total"]:
            job.update(snapshot["done"] / snapshot["total"])
            job.progress_msg = (
                f"{snapshot['done']}/{snapshot['total']} models across "
                f"{max(len(snapshot['by_member']), 1)} member(s)")
    return {"ok": True}


def search_progress(search_id: str) -> Optional[Dict[str, Any]]:
    """Live completion state for ``/3/Grids/{id}`` (None once unknown)."""
    with _PROGRESS_LOCK:
        st = _PROGRESS.get(search_id)
        if st is None:
            return None
        return {k: (list(v) if isinstance(v, list) else
                    dict(v) if isinstance(v, dict) else v)
                for k, v in st.items()}


def _clear_progress(search_id: str) -> None:
    with _PROGRESS_LOCK:
        _PROGRESS.pop(search_id, None)


def install_progress_rpc(cloud: Cloud) -> None:
    """Register the caller-side ``search_progress`` RPC (idempotent)."""
    cloud.rpc_server.register("search_progress", _note_progress)


# ---------------------------------------------------------------------------
# the fan-out scheduler


def fan_out(
    cloud: Cloud,
    frame,
    valid,
    cells: List[Dict[str, Any]],
    search_id: str,
    job=None,
    stopping_metric: str = "auto",
    timeout: Optional[float] = None,
    deadline=None,
) -> Dict[int, Any]:
    """Run ``cells`` (each ``{"index", "builder_cls", "params", "hp"}``)
    across the cloud's healthy members; returns index -> ("ok", result) |
    ("error", message).

    A shared work queue feeds every member ``H2O3_TPU_SEARCH_INFLIGHT``
    cells at a time; a member whose dispatch fails on an infrastructure
    error is marked dead and its in-flight cell goes back on the queue
    for survivors (``path=survivor``); a cell's deterministic build
    failure is recorded, never retried.  Cells left when every member is
    gone train on the caller (``path=local``).  Incomplete only when the
    job is cancelled or the deadline passes mid-run."""
    timeout = _cell_timeout() if timeout is None else timeout
    workers = _tasks._healthy_workers(cloud)
    install_progress_rpc(cloud)
    caller_ref = {
        "addr": tuple(cloud.info.addr),
        "ident": cloud.info.ident,
        "name": cloud.info.name,
    }
    ctx_payload = {
        "search_id": search_id,
        "frame": frame_payload(frame),
        "valid": frame_payload(valid) if valid is not None else None,
    }
    # the caller participates without the wire: prime its own cache
    _ctx_put(search_id, {"frame": frame, "valid": valid})

    total = len(cells)
    queue: deque = deque(range(total))
    results: Dict[int, Any] = {}
    reassigned: set = set()
    qlock = threading.Lock()
    job_key = getattr(job, "key", None) if job is not None else None

    import time as _time

    def _expired() -> bool:
        if job is not None and job.stop_requested:
            return True
        return deadline is not None and _time.time() >= deadline

    def _cell_payload(idx: int) -> Dict[str, Any]:
        cell = cells[idx]
        return {
            "search_id": search_id,
            "index": cell["index"],
            "builder_cls": cell["builder_cls"],
            "params": cell["params"],
            "hp": cell.get("hp", {}),
            "caller": caller_ref,
            "job_key": job_key,
            "total": total,
            "stopping_metric": stopping_metric,
        }

    def _take() -> Optional[int]:
        with qlock:
            if not queue:
                return None
            return queue.popleft()

    def _settle(idx: int, outcome) -> None:
        with qlock:
            results[idx] = outcome
            was_reassigned = idx in reassigned
        _fo.progress()
        if outcome[0] == "ok" and was_reassigned:
            _RECOVERED.inc(path="survivor")
            _flight.record(_flight.RECOVERY, "warn", "search_cell",
                           path="survivor", cell=idx)

    def _requeue(idx: int) -> None:
        # failed-member cells go to the FRONT so survivors re-claim the
        # oldest work first; completion order is irrelevant to results
        with qlock:
            reassigned.add(idx)
            queue.appendleft(idx)

    def _member_loop(member) -> None:
        remote = member.info.name != cloud.info.name
        if remote:
            try:
                _tasks.submit(cloud, member, "search_init", ctx_payload,
                              timeout=timeout)
            except _rpc.RPCError as e:
                log.warning("search %s: member %s init failed: %s",
                            search_id, member.info.name, e)
                return
        while not _expired():
            idx = _take()
            if idx is None:
                return
            try:
                if remote:
                    out = _tasks.submit(cloud, member, "search_cell",
                                        _cell_payload(idx), timeout=timeout)
                else:
                    out = _execute_cell(_cell_payload(idx), cloud)
            except _rpc.RemoteError as e:
                if e.code == CELL_BUILD_FAILED:
                    # deterministic model failure: retrying elsewhere
                    # would fail identically — record it like the
                    # single-node path does
                    _settle(idx, ("error", str(e)))
                    continue
                log.warning("search %s: member %s lost cell %d: %s",
                            search_id, member.info.name, idx, e)
                _requeue(idx)
                return  # member refused/unreachable: stop feeding it
            except _rpc.RPCError as e:
                log.warning("search %s: member %s lost cell %d: %s",
                            search_id, member.info.name, idx, e)
                _requeue(idx)
                return
            except Exception as e:  # caller-local build failure
                _settle(idx, ("error", f"{type(e).__name__}: {e}"))
                continue
            _settle(idx, ("ok", out))

    threads = []
    inflight = _inflight_per_member()
    _fo = _flight.FANOUTS.begin("search", total, members=len(workers))
    _flight.record(_flight.FANOUT, "info", "schedule", kind="search",
                   cells=total, members=len(workers))
    with telemetry.Span("search_fanout", members=len(workers), cells=total):
        for member in workers:
            lanes = inflight if member.info.name != cloud.info.name else 1
            for _ in range(lanes):
                t = threading.Thread(
                    target=_member_loop, args=(member,), daemon=True,
                    name=f"search-{member.info.name}")
                threads.append(t)
                t.start()
        for t in threads:
            t.join()
        # last resort: every member gone (or none ever viable) — the
        # caller absorbs the remainder so the search still completes
        while not _expired():
            idx = _take()
            if idx is None:
                break
            try:
                out = _execute_cell(_cell_payload(idx), cloud)
            except Exception as e:
                _settle(idx, ("error", f"{type(e).__name__}: {e}"))
                continue
            with qlock:
                results[idx] = ("ok", out)
            _fo.progress()
            _RECOVERED.inc(path="local")
            _flight.record(_flight.RECOVERY, "warn", "search_cell",
                           path="local", cell=idx)
        _fo.end()
        # drop member-side caches eagerly; the LRU would get there anyway
        for member in workers:
            if member.info.name == cloud.info.name or not member.healthy:
                continue
            try:
                _tasks.submit(cloud, member, "search_end",
                              {"search_id": search_id}, timeout=5.0)
            except _rpc.RPCError:
                pass
    _ctx_drop(search_id)
    return results


# ---------------------------------------------------------------------------
# the grid driver's distributed path


def distributed_grid_search(
    gs,
    grid,
    frame,
    valid,
    cloud: Cloud,
    rec=None,
    job=None,
    scores: Optional[List[float]] = None,
    init_larger: bool = True,
    consumed=None,
):
    """Execute a GridSearch's walk across the cloud.

    Dispatch happens in rounds: each round materializes the next
    still-needed cells from the canonical walker (all of them, or
    ``max_models - built`` when capped), fans them out, then RECORDS the
    results in canonical walk order under exactly the single-node budget
    and early-stopping predicates — so the recorded model sequence, the
    scores it implies, and the stopping decision are bit-identical to
    the single-node run at a fixed seed.  A failed cell consumes a walk
    position (like single-node) and the next round draws replacements.
    """
    import time as _time

    scores = [] if scores is None else scores
    c = gs.criteria
    t0 = _time.time()
    deadline = (t0 + c.max_runtime_secs) if c.max_runtime_secs else None
    walker = gs._walk(consumed)
    direction = {"larger": init_larger}
    search_id = grid.grid_id
    _clear_progress(search_id)
    stopped = False

    def _budget_full() -> bool:
        return bool(c.max_models) and len(grid.models) >= c.max_models

    while not stopped and not _budget_full():
        if deadline is not None and _time.time() >= deadline:
            break
        if job is not None and job.stop_requested:
            break
        want = (c.max_models - len(grid.models)) if c.max_models else None
        batch: List[Dict[str, Any]] = []
        for hp in walker:
            batch.append(hp)
            if want is not None and len(batch) >= want:
                break
        if not batch:
            break
        cells = [
            {
                "index": i,
                "builder_cls": gs.builder_cls,
                "params": gs._cell_params(hp),
                "hp": hp,
            }
            for i, hp in enumerate(batch)
        ]
        results = fan_out(
            cloud, frame, valid, cells, search_id=search_id, job=job,
            stopping_metric=c.stopping_metric, deadline=deadline)
        # canonical-order recording: identical predicate sequence to the
        # single-node loop, so budgets and early stopping cut at exactly
        # the same cell regardless of completion order
        for i, hp in enumerate(batch):
            if _budget_full() or gs._stopped_early(scores, direction):
                stopped = True
                break
            st = results.get(i)
            if st is None:
                # cancelled / deadline mid-round: this cell never ran
                continue
            kind, val = st
            if kind == "ok":
                model = model_from_blob(val["model"])
                gs._record(grid, hp, model, scores, c, direction)
                if rec is not None:
                    rec.on_model(model, info={"hp": hp})
            else:
                grid.failures.append((hp, val))
                if rec is not None:
                    rec.on_failure({"hp": hp, "error": val})

    grid.runtime_secs = _time.time() - t0
    return grid
