"""L3a: distributed K/V homes — consistent-hash routing over the cloud.

Reference: every ``water.Key`` hashes to a *home node* that owns the
authoritative copy (``water/Key.java:196`` home arithmetic over the
sorted member list, ``water/DKV.java:30-62`` put/get forwarding).  Here
the same contract layers onto :class:`h2o3_tpu.keyed.KeyedStore` without
changing its single-node behavior: a router installed on the store
forwards put/get/remove for keys homed elsewhere over RPC, and
short-circuits to the plain local path when the cloud has one member
(or no cloud exists) — existing callers never see a difference.

Key homes use a consistent-hash ring (virtual nodes per member) rather
than the reference's plain ``hash % cloud_size``: when a member joins or
leaves, only the keys homed on the affected arc move, instead of nearly
every key re-homing — the right trade for clouds whose membership this
layer itself can change (suspicion removal).

``replicas=`` on put stores copies on the next distinct ring successors
— the knob for small metadata keys that must survive their home node.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster.membership import Cloud, Member
from h2o3_tpu.util import telemetry

_FORWARDS = telemetry.counter(
    "cluster_dkv_forwards_total",
    "DKV operations forwarded to / served for another node",
    labels=("op", "direction"),
)

#: virtual nodes per member on the hash ring — enough that key load
#: splits within a few percent of even for small clouds
_VNODES = 64

#: deepest ring successor a replica can land on — and therefore the
#: deepest get-fallback and remove fan-out need to reach.  Copies past
#: this depth would be unreachable by the ring, so replicate clamps to
#: it and remove bounds its RPC fan-out by it (a just-died member then
#: only stalls removes of keys it actually homes, not every remove)
MAX_REPLICAS = 3

#: value types the ring routes to a home node — the plain DATA the
#: /3/DKV surface and metadata puts store.  Framework lifecycle objects
#: (Frame, Model, Job, Grid — anything not listed) stay NODE-LOCAL even
#: on a multi-node cloud: the node that built them owns them, mutates
#: them in place (Job.update / cancel), lists them (keys_of_type behind
#: /3/Frames, /3/Models) and read-locks them — forwarding a pickled
#: snapshot away would freeze that contract mid-air.  Gets of a
#: local-only key still work everywhere they can: remote_get asks the
#: ring home, then falls back to the local store.
ROUTABLE_VALUE_TYPES = (
    str, bytes, bytearray, int, float, bool, complex,
    list, tuple, dict, set, frozenset, type(None),
    np.ndarray, np.generic,
)


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over member idents."""

    def __init__(self, idents: List[str]) -> None:
        points: List[Tuple[int, str]] = []
        for ident in idents:
            for v in range(_VNODES):
                points.append((_hash64(f"{ident}#{v}"), ident))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [o for _, o in points]
        self.idents = sorted(idents)

    def homes(self, key: str, n: int = 1) -> List[str]:
        """The key's home ident plus the next ``n - 1`` DISTINCT ring
        successors (replica placement)."""
        if not self._hashes:
            return []
        out: List[str] = []
        i = bisect.bisect_right(self._hashes, _hash64(key))
        for step in range(len(self._hashes)):
            owner = self._owners[(i + step) % len(self._hashes)]
            if owner not in out:
                out.append(owner)
                if len(out) >= min(n, len(self.idents)):
                    break
        return out


class DkvRouter:
    """Installed on a :class:`~h2o3_tpu.keyed.KeyedStore` as ``.router``;
    the store consults it on every put/get/remove.  All remote traffic
    rides the cloud's pooled RPC client."""

    #: per-op RPC timeout — DKV values can be whole frames
    TIMEOUT = 60.0

    def __init__(self, cloud: Cloud, store) -> None:
        self.cloud = cloud
        self.store = store
        self._ring_lock = threading.Lock()
        self._ring: Optional[HashRing] = None
        self._ring_key: Optional[Tuple[str, ...]] = None
        #: keys THIS node (as home) fanned replica copies out for — the
        #: home performed the replication, so only it knows which keys
        #: need a successor reap on remove (set ops are GIL-atomic)
        self._replicated: set = set()
        cloud.rpc_server.register("dkv_put", self._serve_put)
        cloud.rpc_server.register("dkv_get", self._serve_get)
        cloud.rpc_server.register("dkv_remove", self._serve_remove)

    # -- ring ----------------------------------------------------------------
    def _members(self) -> List[Member]:
        """Key-owning members: healthy, non-client (clients hold no keys,
        matching the reference's client-node exclusion from key homes)."""
        return [m for m in self.cloud.members_sorted()
                if m.healthy and not m.info.client]

    def _current_ring(self) -> Tuple[HashRing, Dict[str, Member]]:
        members = self._members()
        by_ident = {m.info.ident: m for m in members}
        key = tuple(sorted(by_ident))
        with self._ring_lock:
            if self._ring is None or self._ring_key != key:
                self._ring = HashRing(list(key))
                self._ring_key = key
            return self._ring, by_ident

    def active(self) -> bool:
        """Multi-node clouds only — a cloud of one short-circuits every
        caller straight to the local store."""
        return self.cloud.size() > 1 and len(self._members()) > 1

    def home_members(self, key: str, replicas: int = 1) -> List[Member]:
        ring, by_ident = self._current_ring()
        return [by_ident[i] for i in ring.homes(key, replicas)
                if i in by_ident]

    def home_name(self, key: str) -> Optional[str]:
        homes = self.home_members(key, 1)
        return homes[0].info.name if homes else None

    def is_home(self, key: str) -> bool:
        return self.home_name(key) in (None, self.cloud.info.name)

    @staticmethod
    def routes_value(value: Any) -> bool:
        """True for plain-data values the ring owns; framework objects
        (anything else) are node-local (see ROUTABLE_VALUE_TYPES)."""
        return isinstance(value, ROUTABLE_VALUE_TYPES)

    # -- client side (called from KeyedStore) --------------------------------
    def remote_put(self, key: str, value: Any, replicas: int = 1) -> str:
        home = self.home_members(key, 1)[0]
        _FORWARDS.inc(op="put", direction="sent")
        self.cloud.client.call(
            home.info.addr, "dkv_put",
            {"key": key, "value": value, "replicas": int(replicas)},
            timeout=self.TIMEOUT, target=home.info.ident)
        return key

    def _local_fallback(self, key: str, default: Any) -> Any:
        """Keys stored BEFORE the cloud grew (their ring home now lands
        elsewhere) still live only in this node's store — a ring miss
        must check it before declaring the key absent."""
        sentinel = object()
        v = self.store.get(key, sentinel, _local=True)
        return default if v is sentinel else v

    def remote_get(self, key: str, default: Any = None) -> Any:
        """Ask the home; if it is unreachable, fall through the ring
        successors (where replica copies live) before giving up."""
        first_err: Optional[_rpc.RPCError] = None
        for m in self.home_members(key, MAX_REPLICAS):
            if m.info.name == self.cloud.info.name:
                sentinel = object()
                v = self.store.get(key, sentinel, _local=True)
                if v is not sentinel:
                    return v
                continue
            _FORWARDS.inc(op="get", direction="sent")
            try:
                # retries=1: the candidate walk below is the real retry
                # — a full ladder per candidate could block a
                # synchronous get for minutes against a black-holed home
                resp = self.cloud.client.call(
                    m.info.addr, "dkv_get", {"key": key},
                    timeout=self.TIMEOUT, target=m.info.ident, retries=1)
            except _rpc.RPCError as e:
                if first_err is None:
                    first_err = e
                continue  # fall through to the next ring candidate
            if resp.get("found"):
                return resp.get("value")
            # the home answered: absent is authoritative for the RING —
            # but a pre-join local copy is still the caller's data
            return self._local_fallback(key, default)
        sentinel = object()
        v = self.store.get(key, sentinel, _local=True)
        if v is not sentinel:
            return v  # every candidate unreachable, but we hold a copy
        if first_err is not None:
            raise first_err
        return default

    def remote_remove(self, key: str) -> None:
        """Removal routes to the key's HOME only; the home — which
        performed any replica fan-out and tracked it — reaps successor
        copies just for keys that actually have them.  The common
        unreplicated remove (model-build scope sweeps clear dozens of
        temp keys) thus costs at most one RPC, zero when we are home."""
        homes = self.home_members(key, 1)
        if not homes or homes[0].info.name == self.cloud.info.name:
            self._reap_replicas(key)
            return
        m = homes[0]
        _FORWARDS.inc(op="remove", direction="sent")
        try:
            self.cloud.client.call(
                m.info.addr, "dkv_remove", {"key": key},
                timeout=self.TIMEOUT, target=m.info.ident)
        except _rpc.RemoteError as e:
            if e.code == 423:
                # the remote copy is read/write-locked: surface the
                # same ValueError the local _check_unlocked raises,
                # not a silent "removed"
                raise ValueError(e.msg) from e
            # any other remote failure: best-effort
        except _rpc.RPCError:
            pass  # a dead home's copy dies with the member

    def _reap_replicas(self, key: str) -> None:
        """Home-side: remove successor copies IF this home fanned any.
        A home that died between replicate and remove leaks its replica
        copies until their holders churn — acceptable for best-effort
        metadata replicas; the alternative (broadcast every remove) cost
        every sweep a retry ladder against any dying member."""
        if key not in self._replicated:
            return
        self._replicated.discard(key)
        for m in self.home_members(key, MAX_REPLICAS)[1:]:
            if m.info.name == self.cloud.info.name:
                continue
            _FORWARDS.inc(op="remove", direction="sent")
            try:
                self.cloud.client.call(
                    m.info.addr, "dkv_remove",
                    {"key": key, "replica_copy": True},
                    timeout=self.TIMEOUT, target=m.info.ident)
            except _rpc.RPCError:
                pass  # a dead member's copy dies with the member

    def replicate(self, key: str, value: Any, replicas: int) -> None:
        """Push replica copies from the home to its ring successors."""
        for m in self.home_members(key, min(replicas, MAX_REPLICAS))[1:]:
            if m.info.name == self.cloud.info.name:
                continue
            self._replicated.add(key)  # a copy MAY land: reap on remove
            _FORWARDS.inc(op="replicate", direction="sent")
            try:
                self.cloud.client.call(
                    m.info.addr, "dkv_put",
                    {"key": key, "value": value, "replica_copy": True},
                    timeout=self.TIMEOUT, target=m.info.ident)
            except _rpc.RPCError:
                pass  # best-effort: the home copy is the authority

    # -- server side (RPC handlers running on the home node) -----------------
    def _serve_put(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        _FORWARDS.inc(op="put", direction="served")
        key = payload["key"]
        value = payload.get("value")
        if payload.get("replica_copy"):
            self.store.put(key, value, _local=True)
        else:
            # _local: this node answers AS the home — re-entering the
            # routed put here would consult our own ring view, which can
            # disagree with the sender's during suspicion churn and
            # forward the put straight back (a ping-pong that holds an
            # rpc-worker thread per hop). Store locally, replicate
            # explicitly.
            self.store.put(key, value, _local=True)
            replicas = int(payload.get("replicas", 1))
            if replicas > 1:
                self.replicate(key, value, replicas)
        return {"key": key, "home": self.cloud.info.name}

    def _serve_get(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        _FORWARDS.inc(op="get", direction="served")
        sentinel = object()
        v = self.store.get(payload["key"], sentinel, _local=True)
        if v is sentinel:
            return {"found": False}
        return {"found": True, "value": v}

    def _serve_remove(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        _FORWARDS.inc(op="remove", direction="served")
        key = payload["key"]
        try:
            self.store.remove(key, _local=True)
        except ValueError as e:  # Lockable: surface the lock holders
            raise _rpc.RpcFault(str(e), code=423)
        if not payload.get("replica_copy"):
            self._reap_replicas(key)  # serving AS home: reap successors
        return {"removed": True}


def install(cloud: Cloud, store=None) -> DkvRouter:
    """Attach a router for ``cloud`` to ``store`` (default: the global
    DKV singleton) and return it."""
    if store is None:
        from h2o3_tpu.keyed import DKV as store  # noqa: N811
    router = DkvRouter(cloud, store)
    store.router = router
    return router
