"""L3a: distributed K/V homes — consistent-hash routing over the cloud.

Reference: every ``water.Key`` hashes to a *home node* that owns the
authoritative copy (``water/Key.java:196`` home arithmetic over the
sorted member list, ``water/DKV.java:30-62`` put/get forwarding).  Here
the same contract layers onto :class:`h2o3_tpu.keyed.KeyedStore` without
changing its single-node behavior: a router installed on the store
forwards put/get/remove for keys homed elsewhere over RPC, and
short-circuits to the plain local path when the cloud has one member
(or no cloud exists) — existing callers never see a difference.

Key homes use a consistent-hash ring (virtual nodes per member) rather
than the reference's plain ``hash % cloud_size``: when a member joins or
leaves, only the keys homed on the affected arc move, instead of nearly
every key re-homing — the right trade for clouds whose membership this
layer itself can change (suspicion removal).

``replicas=`` on put stores copies on the next distinct ring successors
— the knob for small metadata keys that must survive their home node.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster.membership import Cloud, Member
from h2o3_tpu.util import telemetry

_FORWARDS = telemetry.counter(
    "cluster_dkv_forwards_total",
    "DKV operations forwarded to / served for another node",
    labels=("op", "direction"),
)
_READ_REPAIR = telemetry.counter(
    "cluster_dkv_read_repair_total",
    "gets served from a ring successor and re-put to the current home "
    "(the key re-homes on read after its home died)",
)
_SWEEP = telemetry.counter(
    "cluster_dkv_replica_sweep_total",
    "replica anti-entropy sweep outcomes, by action (promoted/reaped/"
    "kept/adopted/reseeded/rehomed/restored)",
    labels=("action",),
)

#: virtual nodes per member on the hash ring — enough that key load
#: splits within a few percent of even for small clouds
_VNODES = 64

#: deepest ring successor a replica can land on — and therefore the
#: deepest get-fallback and remove fan-out need to reach.  Copies past
#: this depth would be unreachable by the ring, so replicate clamps to
#: it and remove bounds its RPC fan-out by it (a just-died member then
#: only stalls removes of keys it actually homes, not every remove)
MAX_REPLICAS = 3

#: value types the ring routes to a home node — the plain DATA the
#: /3/DKV surface and metadata puts store.  Framework lifecycle objects
#: (Frame, Model, Job, Grid — anything not listed) stay NODE-LOCAL even
#: on a multi-node cloud: the node that built them owns them, mutates
#: them in place (Job.update / cancel), lists them (keys_of_type behind
#: /3/Frames, /3/Models) and read-locks them — forwarding a pickled
#: snapshot away would freeze that contract mid-air.  Gets of a
#: local-only key still work everywhere they can: remote_get asks the
#: ring home, then falls back to the local store.
ROUTABLE_VALUE_TYPES = (
    str, bytes, bytearray, int, float, bool, complex,
    list, tuple, dict, set, frozenset, type(None),
    np.ndarray, np.generic,
)


def _hash64(s: str) -> int:
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


def ring_key(key: str) -> str:
    """Canonical ring-placement key.  Frame chunk keys
    (``fr#<frame>#g<j>t<t>#c<i>``) hash by their GROUP ANCHOR — everything
    before the ``#c<i>`` suffix — so all chunks of a group land
    contiguously on ONE home and ride every ring mechanism (replica
    walk, read-repair, anti-entropy sweep) as a unit.  Every other key
    hashes as itself.  Serving-plane blob keys (``serve#<model_key>``,
    cluster/serving.py) hash by the MODEL key they shadow, so a model's
    blob homes — and replicates — exactly where the serving plane routes
    scoring for that model."""
    if key.startswith("serve#"):
        key = key[len("serve#"):]
    if key.startswith("fr#"):
        i = key.rfind("#c")
        if i > 0 and key[i + 2:].isdigit():
            return key[:i]
    return key


class HashRing:
    """Consistent-hash ring over member idents."""

    def __init__(self, idents: List[str]) -> None:
        points: List[Tuple[int, str]] = []
        for ident in idents:
            for v in range(_VNODES):
                points.append((_hash64(f"{ident}#{v}"), ident))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [o for _, o in points]
        self.idents = sorted(idents)

    def homes(self, key: str, n: int = 1) -> List[str]:
        """The key's home ident plus the next ``n - 1`` DISTINCT ring
        successors (replica placement)."""
        if not self._hashes:
            return []
        out: List[str] = []
        i = bisect.bisect_right(self._hashes, _hash64(ring_key(key)))
        for step in range(len(self._hashes)):
            owner = self._owners[(i + step) % len(self._hashes)]
            if owner not in out:
                out.append(owner)
                if len(out) >= min(n, len(self.idents)):
                    break
        return out


class DkvRouter:
    """Installed on a :class:`~h2o3_tpu.keyed.KeyedStore` as ``.router``;
    the store consults it on every put/get/remove.  All remote traffic
    rides the cloud's pooled RPC client."""

    #: per-op RPC timeout — DKV values can be whole frames
    TIMEOUT = 60.0

    #: keys one anti-entropy sweep pass may touch — bounded so the
    #: heartbeat cadence it piggybacks on never stalls behind a big store
    SWEEP_BATCH = 16

    def __init__(self, cloud: Cloud, store) -> None:
        self.cloud = cloud
        self.store = store
        self._ring_lock = threading.Lock()
        self._ring: Optional[HashRing] = None
        self._ring_key: Optional[Tuple[str, ...]] = None
        #: key -> replica depth THIS node (as home) fanned copies out
        #: for — the home performed the replication, so only it knows
        #: which keys need a successor reap on remove and a re-seed
        #: after membership churn (dict ops are GIL-atomic)
        self._replicated: Dict[str, int] = {}
        #: keys THIS node holds as a ring successor's replica copy —
        #: the sweep validates each against the key's CURRENT home, so
        #: a copy whose home died between replicate and remove is
        #: reaped instead of leaking until the holder churns
        self._replica_copies: set = set()
        #: sweep cursor state: pending holder-side checks + the ring
        #: generation the home-side re-seed last ran against
        self._sweep_queue: List[str] = []
        self._reseed_pending: set = set()
        self._swept_ring: Optional[Tuple[str, ...]] = None
        #: key -> remove EPOCH this node served a remove for (bounded
        #: FIFO) — the holders' sweep uses it to tell "the key WAS
        #: removed" (reap the copy) from "the home never had it /
        #: restarted empty" (restore the copy to the home); without the
        #: distinction a home that rejoins empty would get its keys'
        #: last surviving replicas reaped instead of re-seeded.  The
        #: epoch makes the memory comparable across nodes: a copy
        #: survives a tombstone only when its write epoch is newer,
        #: so a restarted-amnesiac home cannot resurrect a key whose
        #: removal another walk member still remembers
        self._removed: "OrderedDict[str, int]" = OrderedDict()
        #: key -> write epoch of the value THIS node holds (bounded) —
        #: minted at put on the home, carried on replicate/restore
        #: payloads so every copy knows how old it is vs a tombstone
        self._key_epochs: "OrderedDict[str, int]" = OrderedDict()
        self._epoch = 0
        cloud.rpc_server.register("dkv_put", self._serve_put)
        cloud.rpc_server.register("dkv_get", self._serve_get)
        cloud.rpc_server.register("dkv_remove", self._serve_remove)
        cloud.rpc_server.register("dkv_replica_check",
                                  self._serve_replica_check)

    # -- ring ----------------------------------------------------------------
    def _members(self) -> List[Member]:
        """Key-owning members: healthy, non-client (clients hold no keys,
        matching the reference's client-node exclusion from key homes)."""
        return [m for m in self.cloud.members_sorted()
                if m.healthy and not m.info.client]

    def _current_ring(self) -> Tuple[HashRing, Dict[str, Member]]:
        members = self._members()
        by_ident = {m.info.ident: m for m in members}
        key = tuple(sorted(by_ident))
        with self._ring_lock:
            if self._ring is None or self._ring_key != key:
                self._ring = HashRing(list(key))
                self._ring_key = key
            return self._ring, by_ident

    def active(self) -> bool:
        """Multi-node clouds only — a cloud of one short-circuits every
        caller straight to the local store."""
        return self.cloud.size() > 1 and len(self._members()) > 1

    def home_members(self, key: str, replicas: int = 1) -> List[Member]:
        ring, by_ident = self._current_ring()
        return [by_ident[i] for i in ring.homes(key, replicas)
                if i in by_ident]

    def home_name(self, key: str) -> Optional[str]:
        homes = self.home_members(key, 1)
        return homes[0].info.name if homes else None

    def is_home(self, key: str) -> bool:
        return self.home_name(key) in (None, self.cloud.info.name)

    @staticmethod
    def routes_value(value: Any) -> bool:
        """True for plain-data values the ring owns; framework objects
        (anything else) are node-local (see ROUTABLE_VALUE_TYPES)."""
        return isinstance(value, ROUTABLE_VALUE_TYPES)

    # -- write/remove epochs -------------------------------------------------
    def _next_epoch(self) -> int:
        """Monotonic on this node, anchored to wall-clock ms so epochs
        minted by different nodes stay roughly comparable (remove
        tombstones only need to outrank writes that happened BEFORE the
        remove, which wall clocks order within heartbeat tolerances)."""
        self._epoch = max(self._epoch + 1, int(time.time() * 1000))
        return self._epoch

    @staticmethod
    def _bound(d: "OrderedDict[str, int]", key: str) -> None:
        d.move_to_end(key)
        while len(d) > 4096:
            d.popitem(last=False)

    def note_put(self, key: str, epoch: Optional[int] = None) -> int:
        """Record a write epoch for a key stored locally and clear any
        tombstone the write supersedes.  Called by the store's local put
        path (fresh writes mint an epoch) and by the replica-copy
        landing path (the copy adopts the HOME's epoch, so a delayed
        replicate that loses the race with a remove stays older than
        the tombstone and is reaped by the sweep, never restored)."""
        e = self._next_epoch() if epoch is None else int(epoch)
        self._key_epochs[key] = e
        self._bound(self._key_epochs, key)
        removed = self._removed.get(key)
        if removed is not None and e >= removed:
            self._removed.pop(key, None)
        return e

    # -- client side (called from KeyedStore) --------------------------------
    def remote_put(self, key: str, value: Any, replicas: int = 1) -> str:
        home = self.home_members(key, 1)[0]
        _FORWARDS.inc(op="put", direction="sent")
        self.cloud.client.call(
            home.info.addr, "dkv_put",
            {"key": key, "value": value, "replicas": int(replicas)},
            timeout=self.TIMEOUT, target=home.info.ident)
        return key

    def _local_fallback(self, key: str, default: Any) -> Any:
        """Keys stored BEFORE the cloud grew (their ring home now lands
        elsewhere) still live only in this node's store — a ring miss
        must check it before declaring the key absent."""
        sentinel = object()
        v = self.store.get(key, sentinel, _local=True)
        return default if v is sentinel else v

    def remote_get(self, key: str, default: Any = None) -> Any:
        """Ask the home; if it is unreachable, fall through the ring
        successors (where replica copies live) before giving up.  A
        value served by a successor triggers READ-REPAIR: it is re-put
        to the HOME-ELECT (the shallowest candidate that is still
        reachable), so the key re-homes on its first read after the
        home died — within the suspicion window, before membership
        churn rebuilds the ring."""
        first_err: Optional[_rpc.RPCError] = None
        candidates = self.home_members(key, MAX_REPLICAS)
        #: shallowest candidate that answered but lacks the key — the
        #: node the ring will route to once the dead home is removed,
        #: and therefore where a successor-served value must re-home
        elect: Optional[int] = None
        for j, m in enumerate(candidates):
            if m.info.name == self.cloud.info.name:
                sentinel = object()
                v = self.store.get(key, sentinel, _local=True)
                if v is not sentinel:
                    if j > 0:
                        self._read_repair(key, v, m if elect is None
                                          else candidates[elect])
                    return v
                if elect is None:
                    # a local miss AT the home position (j == 0) still
                    # elects this node: it is where the key re-homes
                    # (the just-rejoined-empty-home case)
                    elect = j
                continue
            _FORWARDS.inc(op="get", direction="sent")
            try:
                # retries=1: the candidate walk below is the real retry
                # — a full ladder per candidate could block a
                # synchronous get for minutes against a black-holed home
                resp = self.cloud.client.call(
                    m.info.addr, "dkv_get", {"key": key},
                    timeout=self.TIMEOUT, target=m.info.ident, retries=1)
            except _rpc.RPCError as e:
                if first_err is None:
                    first_err = e
                continue  # fall through to the next ring candidate
            if resp.get("found"):
                v = resp.get("value")
                if j > 0:
                    # every candidate shallower than the elect was
                    # unreachable; no elect means the serving holder
                    # itself is next in line — promote its copy
                    self._read_repair(key, v, m if elect is None
                                      else candidates[elect])
                return v
            if j == 0:
                # the HOME answered: absent is authoritative for the
                # RING — but a pre-join local copy is still the
                # caller's data
                return self._local_fallback(key, default)
            # a successor answered "absent": not authoritative — a
            # deeper replica may still hold the only surviving copy
            if elect is None:
                elect = j
        sentinel = object()
        v = self.store.get(key, sentinel, _local=True)
        if v is not sentinel:
            return v  # every candidate unreachable, but we hold a copy
        if first_err is not None:
            raise first_err
        return default

    def _read_repair(self, key: str, value: Any, target: Member) -> None:
        """Re-home a replica-served value onto the home-elect (the
        shallowest REACHABLE ring candidate — the dead home ahead of it
        cannot take the put).  When the elect is this node or the
        serving holder itself, the copy is promoted to an
        authoritative, tracked one so the key keeps its replica depth.
        Best-effort: the surviving copy keeps serving reads even if
        the repair put fails."""
        if not self.routes_value(value):
            return
        try:
            if target.info.name == self.cloud.info.name:
                self.store.put(key, value, _local=True)
                self._replica_copies.discard(key)
                self._replicated.setdefault(key, 2)
                self.replicate(key, value, self._replicated[key])
            else:
                _FORWARDS.inc(op="put", direction="sent")
                self.cloud.client.call(
                    target.info.addr, "dkv_put",
                    {"key": key, "value": value, "replicas": 2},
                    timeout=self.TIMEOUT, target=target.info.ident,
                    retries=1)
        except _rpc.RPCError:
            return
        _READ_REPAIR.inc()

    def remote_remove(self, key: str) -> None:
        """Removal routes to the key's HOME only; the home — which
        performed any replica fan-out and tracked it — reaps successor
        copies just for keys that actually have them.  The common
        unreplicated remove (model-build scope sweeps clear dozens of
        temp keys) thus costs at most one RPC, zero when we are home."""
        homes = self.home_members(key, 1)
        if not homes or homes[0].info.name == self.cloud.info.name:
            self._mark_removed(key)
            self._reap_replicas(key)
            return
        m = homes[0]
        _FORWARDS.inc(op="remove", direction="sent")
        try:
            self.cloud.client.call(
                m.info.addr, "dkv_remove", {"key": key},
                timeout=self.TIMEOUT, target=m.info.ident)
        except _rpc.RemoteError as e:
            if e.code == 423:
                # the remote copy is read/write-locked: surface the
                # same ValueError the local _check_unlocked raises,
                # not a silent "removed"
                raise ValueError(e.msg) from e
            # any other remote failure: best-effort
        except _rpc.RPCError:
            pass  # a dead home's copy dies with the member

    def _reap_replicas(self, key: str) -> None:
        """Home-side: remove successor copies IF this home fanned any.
        A home that died between replicate and remove no longer leaks
        its replica copies forever: the holders' anti-entropy sweep
        (:meth:`sweep_replicas`) checks each copy against the key's
        CURRENT home and reaps copies the home does not hold."""
        if key not in self._replicated:
            return
        self._replicated.pop(key, None)
        self._reseed_pending.discard(key)
        epoch = self._removed.get(key, 0)
        for m in self.home_members(key, MAX_REPLICAS)[1:]:
            if m.info.name == self.cloud.info.name:
                continue
            _FORWARDS.inc(op="remove", direction="sent")
            try:
                self.cloud.client.call(
                    m.info.addr, "dkv_remove",
                    {"key": key, "replica_copy": True, "epoch": epoch},
                    timeout=self.TIMEOUT, target=m.info.ident)
            except _rpc.RPCError:
                pass  # a dead member's copy dies with the member

    def replicate(self, key: str, value: Any, replicas: int) -> None:
        """Push replica copies from the home to its ring successors."""
        for m in self.home_members(key, min(replicas, MAX_REPLICAS))[1:]:
            if m.info.name == self.cloud.info.name:
                continue
            # a copy MAY land: reap on remove, re-seed on ring churn
            self._replicated[key] = int(replicas)
            _FORWARDS.inc(op="replicate", direction="sent")
            try:
                self.cloud.client.call(
                    m.info.addr, "dkv_put",
                    {"key": key, "value": value, "replica_copy": True,
                     "epoch": self._key_epochs.get(key, 0)},
                    timeout=self.TIMEOUT, target=m.info.ident)
            except _rpc.RPCError:
                pass  # best-effort: the home copy is the authority

    # -- server side (RPC handlers running on the home node) -----------------
    def _serve_put(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        _FORWARDS.inc(op="put", direction="served")
        key = payload["key"]
        value = payload.get("value")
        if payload.get("replica_copy"):
            # tag the copy: the sweep validates every tagged key against
            # its current home, so an orphaned copy is reapable later
            self._replica_copies.add(key)
            self.store.put(key, value, _local=True)
            # the copy ADOPTS the home's write epoch (overriding the
            # fresh one the local put minted): a replicate that lost the
            # race with a remove stays OLDER than the tombstone, so the
            # sweep reaps it instead of resurrecting the key
            if payload.get("epoch"):
                self.note_put(key, payload["epoch"])
        else:
            # _local: this node answers AS the home — re-entering the
            # routed put here would consult our own ring view, which can
            # disagree with the sender's during suspicion churn and
            # forward the put straight back (a ping-pong that holds an
            # rpc-worker thread per hop). Store locally, replicate
            # explicitly.
            self.store.put(key, value, _local=True)
            # serving AS home supersedes any replica tag this node held
            # for the key (e.g. a read-repair promoting the copy), and a
            # replicated put is tracked even when every successor push
            # is skipped — churn re-seeds ride the tracking
            self._replica_copies.discard(key)
            replicas = int(payload.get("replicas", 1))
            if replicas > 1:
                self._replicated[key] = replicas
                self.replicate(key, value, replicas)
        return {"key": key, "home": self.cloud.info.name}

    def _serve_get(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        _FORWARDS.inc(op="get", direction="served")
        sentinel = object()
        v = self.store.get(payload["key"], sentinel, _local=True)
        if v is sentinel:
            return {"found": False}
        return {"found": True, "value": v}

    def _mark_removed(self, key: str, epoch: Optional[int] = None) -> None:
        e = self._next_epoch() if epoch is None else \
            max(int(epoch), self._removed.get(key, 0))
        self._removed[key] = e
        self._bound(self._removed, key)
        self._key_epochs.pop(key, None)

    def _serve_remove(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        _FORWARDS.inc(op="remove", direction="served")
        key = payload["key"]
        try:
            self.store.remove(key, _local=True)
        except ValueError as e:  # Lockable: surface the lock holders
            raise _rpc.RpcFault(str(e), code=423)
        # a reap fan-out carries the home's remove epoch so every walk
        # member records the SAME tombstone (even members holding no
        # copy — they answer replica_check for survivors later)
        self._mark_removed(key, payload.get("epoch"))
        if payload.get("replica_copy"):
            self._replica_copies.discard(key)
        else:
            self._reap_replicas(key)  # serving AS home: reap successors
        return {"removed": True}

    def _serve_replica_check(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Home side of the holders' sweep: does this node (the key's
        current home) hold the key?  Holding it without tracking it
        (e.g. it arrived by read-repair before this node knew it was
        home) adopts tracking, so the NEXT remove reaps successors."""
        key = payload["key"]
        sentinel = object()
        v = self.store.get(key, sentinel, _local=True)
        if v is sentinel:
            # "removed" disambiguates for the holder: a key this home
            # REMOVED is an orphan copy (reap it); a key this home
            # simply never had (it restarted empty, or the arc just
            # moved here) must be restored from the copy instead.  The
            # epoch lets the holder rank its copy against the tombstone
            # — and lets OTHER walk members veto a restore toward a
            # restarted-amnesiac home that forgot the removal
            return {"exists": False, "removed": key in self._removed,
                    "removed_epoch": int(self._removed.get(key, 0))}
        if key not in self._replicated:
            self._replicated[key] = 2
            _SWEEP.inc(action="adopted")
        return {"exists": True}

    # -- anti-entropy sweep (piggybacked on the gossip cadence) ---------------
    def sweep_replicas(self) -> None:
        """One bounded anti-entropy pass, run once per gossip cycle.

        Home side: after membership churn re-homes arcs, every key this
        node tracked as home is either re-seeded onto its (possibly new)
        successors, or — when this node is no longer the home — pushed
        to the new home and demoted to a tagged replica copy.

        Holder side: up to :data:`SWEEP_BATCH` tagged replica copies are
        validated against the key's CURRENT ring home; a copy whose home
        no longer holds the key is an orphan ("home died between
        replicate and remove") and is reaped, a copy whose holder is now
        the ring home is promoted to an authoritative, tracked copy."""
        if not self.active():
            return
        ring, _by_ident = self._current_ring()
        ring_key = tuple(ring.idents)
        if ring_key != self._swept_ring:
            self._swept_ring = ring_key
            self._reseed_pending = set(self._replicated)
        self._sweep_homes()
        self._sweep_copies()

    def _sweep_homes(self) -> None:
        me = self.cloud.info.name
        budget = self.SWEEP_BATCH
        while budget > 0 and self._reseed_pending:
            key = self._reseed_pending.pop()
            budget -= 1
            replicas = self._replicated.get(key)
            if replicas is None:
                continue  # removed since the ring changed
            sentinel = object()
            value = self.store.get(key, sentinel, _local=True)
            if value is sentinel:
                self._replicated.pop(key, None)
                continue
            homes = self.home_members(key, MAX_REPLICAS)
            if not homes:
                continue
            if homes[0].info.name == me:
                # still home: refresh copies onto the current successors
                self.replicate(key, value, replicas)
                _SWEEP.inc(action="reseeded")
                continue
            # the arc moved: push the value to the new home (which fans
            # its own replicas) and demote our copy to a tagged replica
            try:
                _FORWARDS.inc(op="put", direction="sent")
                self.cloud.client.call(
                    homes[0].info.addr, "dkv_put",
                    {"key": key, "value": value, "replicas": replicas},
                    timeout=self.TIMEOUT, target=homes[0].info.ident,
                    retries=1)
            except _rpc.RPCError:
                self._reseed_pending.add(key)  # retry next cycle
                continue
            self._replicated.pop(key, None)
            self._replica_copies.add(key)
            _SWEEP.inc(action="rehomed")

    def _tombstoned(self, key: str) -> bool:
        """Resurrection guard for a copy about to be PROMOTED or
        RESTORED: is there a remove tombstone for ``key``, anywhere on
        its current ring walk, newer than the copy's write epoch?  The
        home alone cannot be trusted here — it may have restarted empty
        and forgotten the removal — so the other walk members are
        polled too.  A copy with no recorded epoch ranks oldest (0):
        any tombstone outranks it, which errs toward re-delete — the
        safe side, since a live key is re-put (minting a newer epoch)
        while a deleted one must stay dead."""
        copy_epoch = self._key_epochs.get(key, 0)
        if self._removed.get(key, 0) > copy_epoch:
            return True
        me = self.cloud.info.name
        for m in self.home_members(key, MAX_REPLICAS):
            if m.info.name == me:
                continue
            try:
                resp = self.cloud.client.call(
                    m.info.addr, "dkv_replica_check", {"key": key},
                    timeout=self.TIMEOUT, target=m.info.ident, retries=1)
            except _rpc.RPCError:
                continue  # unreachable: no removal evidence from it
            if int(resp.get("removed_epoch", 0) or 0) > copy_epoch:
                return True
        return False

    def _sweep_copies(self) -> None:
        me = self.cloud.info.name
        if not self._sweep_queue:
            self._sweep_queue = list(self._replica_copies)
        batch = 0
        while batch < self.SWEEP_BATCH and self._sweep_queue:
            key = self._sweep_queue.pop()
            if key not in self._replica_copies:
                continue
            batch += 1
            homes = self.home_members(key, MAX_REPLICAS)
            names = [m.info.name for m in homes]
            if not homes:
                continue
            if names[0] == me:
                # this holder IS the home now — but ring churn can route
                # a stale copy here (the removing home died and the arc
                # moved): promote only copies no walk member remembers
                # removing, else fall through to the reap
                if not self._tombstoned(key):
                    self._replica_copies.discard(key)
                    sentinel = object()
                    value = self.store.get(key, sentinel, _local=True)
                    if value is not sentinel:
                        self._replicated.setdefault(key, 2)
                        self.replicate(key, value, self._replicated[key])
                    _SWEEP.inc(action="promoted")
                    continue
            elif me in names[1:]:
                # valid successor: keep iff the current home holds the
                # key (an RPC failure keeps the copy — re-check next
                # cycle rather than reap on a transient)
                try:
                    resp = self.cloud.client.call(
                        homes[0].info.addr, "dkv_replica_check",
                        {"key": key}, timeout=self.TIMEOUT,
                        target=homes[0].info.ident, retries=1)
                except _rpc.RPCError:
                    continue
                if resp.get("exists"):
                    _SWEEP.inc(action="kept")
                    continue
                copy_epoch = self._key_epochs.get(key, 0)
                home_removed = bool(resp.get("removed")) and \
                    int(resp.get("removed_epoch", 0) or 0) >= copy_epoch
                if not home_removed and not self._tombstoned(key):
                    # the home LACKS the key and no walk member recalls
                    # a removal newer than this copy — it restarted
                    # empty or just inherited the arc; this copy may be
                    # the last one alive, so restore it to the home
                    # (which re-tracks and fans replicas)
                    sentinel = object()
                    value = self.store.get(key, sentinel, _local=True)
                    if value is not sentinel:
                        try:
                            _FORWARDS.inc(op="put", direction="sent")
                            self.cloud.client.call(
                                homes[0].info.addr, "dkv_put",
                                {"key": key, "value": value,
                                 "replicas": 2},
                                timeout=self.TIMEOUT,
                                target=homes[0].info.ident, retries=1)
                            _SWEEP.inc(action="restored")
                        except _rpc.RPCError:
                            pass  # keep the copy; retry next cycle
                        continue
            # orphan: the home REMOVED the key (died between replicate
            # and remove), a tombstone newer than the copy survives on
            # the walk, or this node left the key's arc
            self._replica_copies.discard(key)
            try:
                self.store.remove(key, _local=True)
            except (KeyError, ValueError):
                pass
            _SWEEP.inc(action="reaped")


def install(cloud: Cloud, store=None) -> DkvRouter:
    """Attach a router for ``cloud`` to ``store`` (default: the global
    DKV singleton) and return it."""
    if store is None:
        from h2o3_tpu.keyed import DKV as store  # noqa: N811
    router = DkvRouter(cloud, store)
    store.router = router
    #: the cloud remembers its store so layers that receive only the
    #: cloud (task executors, chunk-home fan-out) resolve the SAME
    #: store the router serves — critical with several in-process
    #: Clouds, where the global DKV singleton is the wrong one
    cloud.dkv_store = store
    # anti-entropy rides the gossip cadence: one bounded sweep per cycle
    cloud.add_cycle_hook(router.sweep_replicas)
    return router
