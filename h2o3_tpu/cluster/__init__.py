"""Application-plane clustering — H2O's L0–L3 stack on stdlib sockets.

The reference cloud is four layers (SURVEY.md §5): L0 raw byte transport
(``water/AutoBuffer.java``), L1 request/response RPC with a retry ladder
(``water/RPC.java:101``), L2 heartbeat + Paxos-quorum membership
(``water/HeartBeat.java``, ``water/Paxos.java:10-27``) and L3 the
distributed K/V store with home-node key hashing (``water/Key.java:196``,
``water/DKV.java``) plus remote task execution (``water/DTask.java``,
``water/MRTask.java``).

The data plane here is XLA's (``jax.distributed`` + collectives over the
device mesh — ``parallel/mesh.py``); what the runtime must still own
itself is the *control* plane: who is in the cloud, is a member alive,
which node owns a key, and how does shard work reach another host.  That
is this package:

* :mod:`~h2o3_tpu.cluster.transport` — L0: length-prefixed TCP framing +
  connection pool.
* :mod:`~h2o3_tpu.cluster.rpc` — L1: named-method request/response RPC
  with per-call timeout, bounded exponential-backoff retry, idempotency
  tokens, and full telemetry (``rpc_calls_total{target,method,result}``).
* :mod:`~h2o3_tpu.cluster.membership` — L2: periodic heartbeat gossip
  carrying a ``HeartBeat``-style payload, quorum cloud formation on a
  sorted member list + cloud hash, missed-heartbeat suspicion → removal,
  cloud-version fencing of stale members.
* :mod:`~h2o3_tpu.cluster.dkv` — L3a: consistent-hash key homes layered
  onto :mod:`h2o3_tpu.keyed`; put/get on a non-home node forwards over
  RPC (single-node clouds short-circuit to the local store).
* :mod:`~h2o3_tpu.cluster.tasks` — L3b: remote DTask executor fanning
  ``map_reduce`` / parse-chunk work out to members.

A process has at most one live :class:`~h2o3_tpu.cluster.membership.Cloud`
(:func:`local_cloud`); with none — or a cloud of one — every wired call
path behaves exactly as before the cluster layer existed.
"""

from __future__ import annotations

from typing import Optional

from h2o3_tpu.cluster.membership import (  # noqa: F401
    Cloud,
    NodeInfo,
    local_cloud,
    set_local_cloud,
)
from h2o3_tpu.cluster.rpc import (  # noqa: F401
    RemoteError,
    RPCConnectionError,
    RPCError,
    RPCTimeoutError,
)


def active_cloud() -> Optional["Cloud"]:
    """The local cloud when it has MORE than one member, else None — the
    single predicate every wired call path gates on (a cloud of one must
    behave exactly like no cloud at all)."""
    c = local_cloud()
    if c is not None and c.size() > 1:
        return c
    return None
