"""L2 membership: heartbeat gossip + quorum cloud formation.

Reference: every H2O node multicasts/flatfile-unicasts a ``HeartBeat``
(``water/HeartBeat.java`` — free memory, K/V bytes, CPU ticks, client
flag) and Paxos-shaped agreement turns the set of heard-from nodes into
*the cloud*: a sorted member list whose hash every member must report
before consensus is declared (``water/Paxos.java:10-27``), with missed
heartbeats driving suspicion and removal, and a cloud version fencing
stale members out of a re-formed cloud.

TPU-native split: ``jax.distributed`` still owns the *data-plane*
rendezvous (collectives need XLA's fabric); this layer owns the
*application-plane* truth — who is in the cloud RIGHT NOW, which nodes
are suspect, where a key lives — which XLA neither tracks nor exposes.

Formation here is deliberately the flatfile/gossip flavor (no UDP
multicast): each node heartbeats its seeds + known members over
:mod:`~h2o3_tpu.cluster.rpc`; payloads carry the sender's member list and
cloud version, receivers merge, and the cloud has consensus when every
live member reports the same membership hash.  Suspicion after
``H2O3_TPU_HB_SUSPECT`` missed beats, removal after twice that, and a
removed (tombstoned) member heartbeating with its stale cloud version is
rejected with a coded fault until it acknowledges the newer version and
rejoins.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu.cluster import faults as _faults
from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import telemetry

_CLUSTER_SIZE = telemetry.gauge(
    "cluster_size", "members in the application-plane cloud")
_CLUSTER_VERSION = telemetry.gauge(
    "cluster_version", "membership epoch (bumps on every join/removal)")
_CLUSTER_CONSENSUS = telemetry.gauge(
    "cluster_consensus", "1 when every live member reports our cloud hash")
_HEARTBEATS = telemetry.counter(
    "cluster_heartbeats_total", "heartbeats exchanged",
    labels=("direction", "result"),
)
_SUSPICIONS = telemetry.counter(
    "cluster_suspicions_total", "members marked suspect (missed beats)")
_REMOVALS = telemetry.counter(
    "cluster_removals_total", "members removed from the cloud")
_REJOINS = telemetry.counter(
    "cluster_rejoins_total",
    "fenced members that completed the 410 -> rejoin handshake and "
    "re-entered the cloud")
_SCRAPE_ERRORS = telemetry.counter(
    "metrics_scrape_errors_total",
    "cluster-wide metric/timeline scrapes that could not reach a member "
    "(the federation degrades to partial=true instead of 5xx-ing)",
    labels=("node", "method"),
)


class CloudJoinError(Exception):
    """Joining the cloud was rejected (duplicate name, wrong cloud...);
    carries the rejecting node's HTTP-ish code for a clear 4xx surface."""

    def __init__(self, msg: str, code: int = 400) -> None:
        super().__init__(msg)
        self.code = code


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    """Identity of one node: name + RPC address (+ advertised REST port)."""

    name: str
    host: str
    port: int
    client: bool = False
    rest_port: int = 0

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)

    @property
    def ident(self) -> str:
        return f"{self.name}@{self.host}:{self.port}"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "NodeInfo":
        return NodeInfo(
            name=str(d["name"]), host=str(d["host"]), port=int(d["port"]),
            client=bool(d.get("client", False)),
            rest_port=int(d.get("rest_port", 0)),
        )


class Member:
    """One cloud member as this node sees it: identity + freshest
    HeartBeat payload + liveness bookkeeping."""

    def __init__(self, info: NodeInfo, now: Optional[float] = None) -> None:
        self.info = info
        self.last_heard = now if now is not None else time.monotonic()
        self.stats: Dict[str, Any] = {}
        self.reported_hash: Optional[str] = None
        self.reported_version: int = 0
        self.healthy = True
        #: EWMA clock-skew estimate (peer wall clock minus ours, ms) and
        #: heartbeat RTT — sampled on every beat via the response timestamp
        #: midpointed against the send/receive instants (Cristian's method);
        #: the merged cluster timeline aligns remote events with it
        self.clock_skew_ms: Optional[float] = None
        self.rtt_ms: Optional[float] = None

    def heartbeat_age(self) -> float:
        return time.monotonic() - self.last_heard

    def observe_clock(self, peer_now_ms: float, t_sent: float,
                      t_received: float) -> None:
        """Fold one (send wall-time, receive wall-time, peer wall-time)
        triple into the skew/RTT estimates.  EWMA (alpha 0.3) smooths
        scheduler jitter; accuracy is bounded by RTT asymmetry — good to a
        few ms on a LAN, which is what aligning timeline events needs."""
        rtt_ms = max(0.0, (t_received - t_sent) * 1000.0)
        skew_ms = float(peer_now_ms) - (t_sent + t_received) / 2.0 * 1000.0
        if self.rtt_ms is None or self.clock_skew_ms is None:
            self.rtt_ms = rtt_ms
            self.clock_skew_ms = skew_ms
        else:
            self.rtt_ms = 0.7 * self.rtt_ms + 0.3 * rtt_ms
            self.clock_skew_ms = 0.7 * self.clock_skew_ms + 0.3 * skew_ms


def cpu_ticks_payload() -> Dict[str, Any]:
    """Host CPU tick counters (api/WaterMeterCpuTicksHandler.java:6) —
    shared by the local REST handler, the heartbeat payload and the
    cross-node RPC proxy so all three report identical shapes."""
    try:
        with open("/proc/stat") as f:
            first = f.readline().split()
    except OSError:  # non-Linux host: degrade gracefully, not a 500
        return {"cpu_ticks": [], "columns": [], "available": False}
    ticks = [int(x) for x in first[1:8]]
    return {"cpu_ticks": [ticks], "columns": [
        "user", "nice", "system", "idle", "iowait", "irq", "softirq"
    ], "available": True}


def _routable_host() -> str:
    """Best-effort routable address for a wildcard bind: the source
    address the kernel would pick for an outbound dial (a connected UDP
    socket sends no packets)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _free_mem_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


class Cloud:
    """This node's view of the application-plane cloud.

    One instance per process (``set_local_cloud``); a cloud of size 1 is
    indistinguishable from no cloud to every wired call path.
    """

    def __init__(
        self,
        cloud_name: str,
        node_name: str,
        host: str = "127.0.0.1",
        port: int = 0,
        client: bool = False,
        rest_port: int = 0,
        hb_interval: Optional[float] = None,
        suspect_beats: Optional[int] = None,
        advertise_host: Optional[str] = None,
    ) -> None:
        self.cloud_name = cloud_name
        self.hb_interval = hb_interval if hb_interval is not None else float(
            os.environ.get("H2O3_TPU_HB_INTERVAL", 1.0))
        self.suspect_beats = suspect_beats if suspect_beats is not None else int(
            os.environ.get("H2O3_TPU_HB_SUSPECT", 5))
        self.rpc_server = _rpc.RpcServer(host=host, port=port,
                                         node_name=node_name)
        self.client = _rpc.RpcClient(node_name=node_name)
        # bind host and advertised host are distinct: a wildcard bind
        # (0.0.0.0 in a pod) must still gossip an address peers can dial
        if advertise_host is None:
            advertise_host = host
        if advertise_host in ("0.0.0.0", "::", ""):
            advertise_host = _routable_host()
        self.info = NodeInfo(
            name=node_name, host=advertise_host,
            port=self.rpc_server.address[1],
            client=client, rest_port=rest_port,
        )
        self.version = 1
        self.start_time = time.time()
        self._lock = threading.RLock()
        self._members: Dict[str, Member] = {node_name: Member(self.info)}
        #: removed member name -> cloud version at removal (the fence)
        self._tombstones: Dict[str, int] = {}
        self._seeds: List[Tuple[str, int]] = []
        self._needs_rejoin = False
        self._stopping = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        #: monotonic stamp of the last COMPLETED gossip cycle — the
        #: heartbeat_overrun watchdog's only input from this class
        self.last_cycle_mono: Optional[float] = None
        #: per-gossip-cycle callbacks (bounded anti-entropy piggybacks)
        self._cycle_hooks: List[Any] = []
        self.rpc_server.register("heartbeat", self._on_heartbeat)
        self.rpc_server.register("ping", lambda p: {
            "pong": True, "name": self.info.name})
        self.rpc_server.register("echo", lambda p: p)
        self.rpc_server.register("cpu_ticks", lambda p: cpu_ticks_payload())
        self.rpc_server.register("logs", self._on_logs)
        self.rpc_server.register("metrics", lambda p: (
            telemetry.REGISTRY.summary()))
        self.rpc_server.register("metrics_snapshot", self._on_metrics_snapshot)
        self.rpc_server.register("timeline_snapshot", self._on_timeline_snapshot)
        self.rpc_server.register("profiler_snapshot", self._on_profiler_snapshot)
        self.rpc_server.register("trace_ledger", self._on_trace_ledger)
        self.rpc_server.register("diagnostics_snapshot",
                                 self._on_diagnostics_snapshot)
        self.rpc_server.register("members", lambda p: {
            "members": [m.info.ident for m in self.members_sorted()],
            "hash": self.cloud_hash(),
            "version": self.version,
            "consensus": self.consensus(),
            "size": self.size(),
        })
        if _faults.surface_enabled():
            self.enable_fault_surface()
        _CLUSTER_SIZE.set(1)
        _CLUSTER_VERSION.set(self.version)

    # -- views ---------------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._members)

    def members_sorted(self) -> List[Member]:
        """Members in the canonical order (by ident) — node index ``i`` in
        ``/3/Logs/nodes/{i}`` and key-home arithmetic both refer to it."""
        with self._lock:
            return sorted(self._members.values(), key=lambda m: m.info.ident)

    def cloud_hash(self) -> str:
        """Digest of the sorted member list — Paxos's agreement object:
        two nodes are in the same cloud iff their hashes match."""
        idents = ";".join(m.info.ident for m in self.members_sorted())
        return hashlib.md5(
            f"{self.cloud_name}|{idents}".encode()).hexdigest()

    def consensus(self) -> bool:
        """True when every OTHER live member has reported our hash."""
        ours = self.cloud_hash()
        with self._lock:
            others = [m for m in self._members.values()
                      if m.info.name != self.info.name]
        ok = all(m.reported_hash == ours for m in others)
        _CLUSTER_CONSENSUS.set(1 if ok else 0)
        return ok

    def local_member(self) -> Member:
        with self._lock:
            return self._members[self.info.name]

    def add_cycle_hook(self, fn) -> None:
        """Run ``fn()`` once per gossip cycle, after suspicion/consensus
        — the piggyback point for bounded anti-entropy work (the DKV
        replica sweep rides it).  A hook that raises is logged and kept;
        it must never kill the heartbeat loop."""
        self._cycle_hooks.append(fn)

    def enable_fault_surface(self) -> None:
        """Register the test-only nemesis RPC methods so multi-process
        chaos harnesses can script faults on (and crash) a live node.
        Called automatically when ``H2O3_TPU_FAULTS=1`` or a fault-plan
        env is present; never in production boots."""
        def _set(p: Optional[Dict[str, Any]]) -> Dict[str, Any]:
            plan = _faults.plan_from_dict(p or {})
            _faults.set_plan(plan)
            return {"installed": True, "seed": plan.seed,
                    "rules": len(plan.rules)}

        def _get(p: Any) -> Dict[str, Any]:
            plan = _faults.active_plan()
            return {"plan": plan.to_dict() if plan is not None else None,
                    "hits": plan.hits() if plan is not None else []}

        def _clear(p: Any) -> Dict[str, Any]:
            _faults.clear_plan()
            return {"cleared": True}

        def _crash(p: Optional[Dict[str, Any]]) -> Dict[str, Any]:
            # ack first, die a beat later: the nemesis learns its kill
            # LANDED rather than inferring it from a connection error
            delay = float((p or {}).get("delay_s", 0.05))
            threading.Timer(delay, _faults.crash_now).start()
            return {"crashing": True, "delay_s": delay}

        self.rpc_server.register("fault_plan_set", _set)
        self.rpc_server.register("fault_plan_get", _get)
        self.rpc_server.register("fault_plan_clear", _clear)
        self.rpc_server.register("fault_crash", _crash)

    def advertise_rest_port(self, port: int) -> None:
        """Publish this node's REST port into its member info (gossip
        carries it to the rest of the cloud) — the REST server binds
        after the cloud forms when both use OS-assigned ports."""
        with self._lock:
            self.info = dataclasses.replace(self.info, rest_port=int(port))
            m = self._members.get(self.info.name)
            if m is not None:
                m.info = self.info

    def member_schemas(self) -> List[Dict[str, Any]]:
        """The /3/Cloud ``nodes`` array (CloudV3.NodeV3 analogue)."""
        leader = self.members_sorted()[0].info.name if self.size() else None
        out = []
        for m in self.members_sorted():
            is_self = m.info.name == self.info.name
            out.append({
                "h2o": f"{m.info.host}:{m.info.port}",
                "ip_port": f"{m.info.host}:{m.info.rest_port or m.info.port}",
                "name": m.info.name,
                "healthy": bool(m.healthy),
                "last_heartbeat_age_ms": 0 if is_self else int(
                    m.heartbeat_age() * 1000),
                "client": m.info.client,
                "leader": m.info.name == leader,
                "rest_port": m.info.rest_port,
                "free_mem": m.stats.get("free_mem", 0),
                "dkv_bytes": m.stats.get("dkv_bytes", 0),
                "dkv_keys": m.stats.get("dkv_keys", 0),
                "num_cpus": m.stats.get("num_cpus", 0),
                "sys_cpu_ticks": m.stats.get("cpu_ticks", []),
                "clock_skew_ms": (0.0 if is_self else m.clock_skew_ms),
                "rtt_ms": (0.0 if is_self else m.rtt_ms),
            })
        return out

    # -- lifecycle -----------------------------------------------------------
    def start(self, seeds: Optional[List[Tuple[str, int]]] = None) -> "Cloud":
        """Begin gossip.  ``seeds`` (the flatfile) are addresses to court
        until they answer; the FIRST round runs synchronously so a coded
        rejection (duplicate name: 409, wrong cloud: 400) surfaces as
        :class:`CloudJoinError` at the launcher instead of a silent
        hash-mismatch stall."""
        with self._lock:
            self._seeds = [s for s in (seeds or [])
                           if s != self.info.addr]
        for addr in list(self._seeds):
            try:
                self._beat_one(addr, timeout=max(2.0, self.hb_interval * 2))
            except _rpc.RemoteError as e:
                if e.code == 410:
                    # a restarted node wearing a tombstoned name: adopt
                    # the cloud's epoch and rejoin rather than die
                    self._adopt_fence(e)
                    try:
                        self._beat_one(
                            addr, timeout=max(2.0, self.hb_interval * 2))
                    except _rpc.RPCError:
                        pass  # the periodic loop finishes the rejoin
                elif 400 <= e.code < 500:
                    raise CloudJoinError(
                        f"cloud join rejected by {addr[0]}:{addr[1]}: "
                        f"{e.msg}", code=e.code) from e
            except _rpc.RPCError:
                pass  # seed not up yet: the periodic loop keeps courting it
        self.last_cycle_mono = time.monotonic()  # arm heartbeat_overrun
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True,
            name=f"heartbeat-{self.info.name}")
        self._hb_thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        self.rpc_server.stop()
        self.client.close()

    # -- heartbeat plumbing --------------------------------------------------
    def _hb_stats(self) -> Dict[str, Any]:
        """The HeartBeat payload (water/HeartBeat.java fields that still
        mean something here)."""
        try:
            from h2o3_tpu.keyed import DKV

            dkv_bytes = DKV.resident_frame_bytes()
            dkv_keys = len(DKV)
        except Exception:
            dkv_bytes, dkv_keys = 0, 0
        ticks = cpu_ticks_payload()
        return {
            "free_mem": _free_mem_bytes(),
            "dkv_bytes": dkv_bytes,
            "dkv_keys": dkv_keys,
            "cpu_ticks": ticks["cpu_ticks"][0] if ticks["cpu_ticks"] else [],
            "num_cpus": os.cpu_count() or 0,
            "client": self.info.client,
            "uptime_ms": int((time.time() - self.start_time) * 1000),
        }

    def _payload(self) -> Dict[str, Any]:
        with self._lock:
            members = [m.info.to_dict() for m in self._members.values()]
            version = self.version
            rejoin = self._needs_rejoin
        return {
            "cloud_name": self.cloud_name,
            "sender": self.info.to_dict(),
            "version": version,
            "hash": self.cloud_hash(),
            "members": members,
            "stats": self._hb_stats(),
            "rejoin": rejoin,
        }

    def _merge_members(self, infos: List[Dict[str, Any]],
                       direct_sender: Optional[NodeInfo] = None) -> bool:
        """Fold a peer's member list into ours.  Tombstoned names only
        come back via a DIRECT heartbeat from the node itself (a peer's
        stale gossip must not resurrect a removed member).  Returns True
        when membership changed.  Caller holds the lock."""
        changed = False
        for d in infos:
            try:
                info = NodeInfo.from_dict(d)
            except (KeyError, ValueError, TypeError):
                continue
            if info.name in self._tombstones and (
                    direct_sender is None or info.name != direct_sender.name):
                continue
            cur = self._members.get(info.name)
            if cur is None:
                self._tombstones.pop(info.name, None)
                self._members[info.name] = Member(info)
                changed = True
            elif cur.info.addr != info.addr and not cur.healthy:
                # a node that died and came back on a new ephemeral port
                # replaces its old registration (same name, fresh addr)
                self._members[info.name] = Member(info)
                changed = True
        return changed

    def _on_heartbeat(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Server side of one gossip exchange."""
        if payload.get("cloud_name") != self.cloud_name:
            _HEARTBEATS.inc(direction="received", result="wrong_cloud")
            raise _rpc.RpcFault(
                f"wrong cloud: heartbeat for {payload.get('cloud_name')!r} "
                f"reached cloud {self.cloud_name!r}", code=400)
        sender = NodeInfo.from_dict(payload["sender"])
        peer_version = int(payload.get("version", 0))
        with self._lock:
            cur = self._members.get(sender.name)
            if (cur is not None and cur.info.addr != sender.addr
                    and cur.healthy
                    and cur.info.name != self.info.name):
                # two live nodes claiming one name can never agree on a
                # member list; reject the latecomer with a clear code
                # instead of letting hashes flap forever
                _HEARTBEATS.inc(direction="received", result="duplicate")
                raise _rpc.RpcFault(
                    f"duplicate node name {sender.name!r}: already held by "
                    f"{cur.info.ident}", code=409)
            if sender.name == self.info.name and sender.addr != self.info.addr:
                _HEARTBEATS.inc(direction="received", result="duplicate")
                raise _rpc.RpcFault(
                    f"duplicate node name {sender.name!r}: it is THIS "
                    f"node's name", code=409)
            fence = self._tombstones.get(sender.name)
            if (fence is not None and peer_version < self.version
                    and not payload.get("rejoin")):
                # stale member of a pre-removal epoch: fenced until it
                # acknowledges the current version and rejoins
                _HEARTBEATS.inc(direction="received", result="fenced")
                raise _rpc.RpcFault(
                    f"stale cloud version {peer_version} (cloud is at "
                    f"{self.version}); rejoin required", code=410,
                    detail={"version": self.version})
            changed = self._merge_members(
                payload.get("members", []), direct_sender=sender)
            if sender.name in self._tombstones:
                self._tombstones.pop(sender.name, None)
                self._members[sender.name] = Member(sender)
                changed = True
            m = self._members.get(sender.name)
            if m is not None:
                if m.info.addr == sender.addr:
                    # a node's DIRECT heartbeat is the authority on its
                    # own metadata — rest_port arrives only after the
                    # REST server binds, well after the join beat
                    m.info = sender
                m.last_heard = time.monotonic()
                m.healthy = True
                m.stats = payload.get("stats", {})
                m.reported_hash = payload.get("hash")
                m.reported_version = peer_version
            if changed or peer_version > self.version:
                self.version = max(self.version, peer_version) + (
                    1 if changed else 0)
            response = {
                "cloud_name": self.cloud_name,
                "receiver": self.info.to_dict(),
                "version": self.version,
                "hash": self.cloud_hash(),
                "members": [m.info.to_dict()
                            for m in self._members.values()],
                # wall clock at response build: the beating peer midpoints
                # it against its send/receive instants to estimate skew
                "now_ms": time.time() * 1000.0,
            }
        _HEARTBEATS.inc(direction="received", result="ok")
        self._publish_gauges()
        return response

    def _beat_one(self, addr: Tuple[str, int], timeout: float) -> None:
        """Client side of one gossip exchange with one peer.  Single
        attempt (``retries=0``): the periodic loop IS the retry, and a
        ladder here would serialize ~4 timeouts against one dead peer
        per cycle — long enough to starve healthy peers past the
        suspicion window and flap the whole cloud's health."""
        t_sent = time.time()
        resp = self.client.call(
            addr, "heartbeat", self._payload(),
            timeout=timeout, target=f"{addr[0]}:{addr[1]}", retries=0)
        t_received = time.time()
        _HEARTBEATS.inc(direction="sent", result="ok")
        receiver = NodeInfo.from_dict(resp["receiver"])
        with self._lock:
            changed = self._merge_members(
                resp.get("members", []), direct_sender=receiver)
            peer_version = int(resp.get("version", 0))
            m = self._members.get(receiver.name)
            if m is not None:
                if m.info.addr == receiver.addr:
                    m.info = receiver  # self-reported metadata refresh
                m.last_heard = time.monotonic()
                m.healthy = True
                m.reported_hash = resp.get("hash")
                m.reported_version = peer_version
                peer_now_ms = resp.get("now_ms")
                if peer_now_ms is not None:
                    m.observe_clock(float(peer_now_ms), t_sent, t_received)
            if changed or peer_version > self.version:
                self.version = max(self.version, peer_version) + (
                    1 if changed else 0)
            rejoined = self._needs_rejoin
            if self._needs_rejoin:
                # a fenced epoch just got acknowledged end-to-end: the
                # peer accepted our rejoin beat at the current version
                _REJOINS.inc()
            self._needs_rejoin = False
        if rejoined:
            _flight.record(_flight.MEMBERSHIP, "info", "rejoin",
                           peer=receiver.ident, version=self.version)

    def _beat_quietly(self, addr: Tuple[str, int]) -> None:
        """One peer's beat with every outcome metered, never raising —
        the per-peer unit the gossip cycle fans out."""
        try:
            self._beat_one(addr, timeout=max(1.0, self.hb_interval * 2))
        except _rpc.RemoteError as e:
            if e.code == 410:  # fenced: adopt the epoch, rejoin
                self._adopt_fence(e)
                _HEARTBEATS.inc(direction="sent", result="fenced")
            else:
                _HEARTBEATS.inc(direction="sent", result="rejected")
        except _rpc.RPCError:
            _HEARTBEATS.inc(direction="sent", result="unreachable")

    def _hb_loop(self) -> None:
        while not self._stopping.wait(self.hb_interval):
            with self._lock:
                targets = {
                    m.info.addr: m.info.ident
                    for m in self._members.values()
                    if m.info.name != self.info.name
                }
                for s in self._seeds:
                    targets.setdefault(s, f"{s[0]}:{s[1]}")
            # beat peers CONCURRENTLY: serially, each black-holed peer
            # would block the cycle a full timeout, and two of them push
            # the gap between beats to live members past the suspicion
            # window — dead nodes must not flap healthy ones
            beats = [
                threading.Thread(target=self._beat_quietly, args=(addr,),
                                 daemon=True, name=f"hb-{label}")
                for addr, label in targets.items()
            ]
            for t in beats:
                t.start()
            deadline = time.monotonic() + max(1.0, self.hb_interval * 2) + 0.5
            for t in beats:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            if self._stopping.is_set():
                return
            self._check_suspicion()
            self.consensus()
            self._publish_gauges()
            self.last_cycle_mono = time.monotonic()
            for hook in list(self._cycle_hooks):
                try:
                    hook()
                except Exception:  # noqa: BLE001 — hooks never kill gossip
                    from h2o3_tpu.util.log import get_logger

                    get_logger("cluster").warning(
                        "gossip cycle hook %r failed", hook, exc_info=True)

    def _adopt_fence(self, e: "_rpc.RemoteError") -> None:
        """A 410 fence carries the cloud's current version: adopt it and
        flag the next heartbeat as a rejoin so the fence opens."""
        with self._lock:
            self.version = max(
                self.version, int(e.detail.get("version", self.version)))
            self._needs_rejoin = True
        _flight.record(_flight.MEMBERSHIP, "warn", "fenced",
                       version=self.version)

    def _check_suspicion(self) -> None:
        """Missed-beat suspicion → removal (Paxos's failure detection):
        suspect after ``suspect_beats`` silent intervals, remove (and
        tombstone, bumping the cloud version) after twice that."""
        suspect_after = self.suspect_beats * self.hb_interval
        removed = []
        suspected = []
        with self._lock:
            for name, m in list(self._members.items()):
                if name == self.info.name:
                    continue
                age = m.heartbeat_age()
                if age > 2 * suspect_after:
                    del self._members[name]
                    self._tombstones[name] = self.version
                    self.version += 1
                    removed.append(m.info.ident)
                    _REMOVALS.inc()
                elif age > suspect_after and m.healthy:
                    m.healthy = False
                    suspected.append((m.info.ident, age))
                    _SUSPICIONS.inc()
        for ident, age in suspected:
            _flight.record(_flight.MEMBERSHIP, "warn", "suspect",
                           member=ident, silent_s=round(age, 2))
        for ident in removed:
            _flight.record(_flight.MEMBERSHIP, "error", "tombstone",
                           member=ident, version=self.version)
        if removed:
            from h2o3_tpu.util.log import get_logger

            get_logger("cluster").warning(
                "removed unresponsive member(s) %s; cloud version now %d",
                ", ".join(removed), self.version)

    def _publish_gauges(self) -> None:
        with self._lock:
            _CLUSTER_SIZE.set(len(self._members))
            _CLUSTER_VERSION.set(self.version)

    # -- built-in RPC methods -------------------------------------------------
    @staticmethod
    def _on_logs(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        from h2o3_tpu.util import log as L

        L.init()
        count = int((payload or {}).get("count", 10000))
        return {"lines": L.recent(count), "log_file": L.log_file()}

    def _on_metrics_snapshot(
            self, payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """Full registry snapshot (not the compact ``metrics`` summary) —
        the per-member half of ``GET /3/Metrics?cluster=true``."""
        return {
            "node": self.info.name,
            "metrics": telemetry.REGISTRY.snapshot(),
            "now_ms": time.time() * 1000.0,
        }

    def _on_timeline_snapshot(
            self, payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """This node's event ring — the per-member half of the merged
        cluster timeline (and the ``/3/Timeline/nodes/{i}`` proxy body)."""
        from h2o3_tpu.util import timeline

        out = timeline.snapshot_payload(
            int((payload or {}).get("count", 1000)))
        out["node"] = self.info.name
        return out

    def _on_profiler_snapshot(
            self, payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """Sample this node's Python stacks — the per-member half of
        ``GET /3/Profiler?cluster=true``.  Blocks for ``duration`` seconds
        (the caller's poll timeout must cover it)."""
        from h2o3_tpu.util import profiler

        p = payload or {}
        exclude = p.get("exclude")
        from h2o3_tpu.cluster import health as _health

        return {
            "node": self.info.name,
            "exclude": exclude,
            # the serving node's watchdog verdict rides the existing
            # payload — one scrape answers "is this node ok", no 2nd RPC
            "health": _health.summary(),
            "profile": profiler.collect(
                duration_s=float(p.get("duration", 0.25)),
                depth=int(p.get("depth", 10)),
                exclude=exclude or None,
            ),
        }

    def _on_diagnostics_snapshot(
            self, payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """This node's diagnostics bundle — the per-member half of
        ``GET /3/Diagnostics?cluster=true`` (knobs, verdicts, last-K
        flight events, worst SlowOps, membership view, thread stacks)."""
        from h2o3_tpu.cluster import health as _health

        return _health.diagnostics_snapshot(
            cloud=self, events=int((payload or {}).get("events", 200)))

    def _on_trace_ledger(
            self, payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        """This node's cost-ledger entry for one trace — the per-member
        half of ``GET /3/Traces/{trace_id}``.  ``ledger: None`` when the
        trace never charged anything here (absence is data, not error)."""
        from h2o3_tpu.util import ledger as _ledger_mod

        tid = str((payload or {}).get("trace_id", ""))
        return {
            "node": self.info.name,
            "trace_id": tid,
            "ledger": _ledger_mod.LEDGER.get(tid) if tid else None,
        }

    # -- cluster-wide scrape fan-out ------------------------------------------
    def poll_members(
        self,
        method: str,
        payload: Any = None,
        timeout: float = 5.0,
    ) -> Tuple[Dict[str, Any], Dict[str, str]]:
        """Fan one built-in RPC to every cloud member concurrently and
        return ``(results, errors)`` keyed by member name.

        The local node answers in-process (no loopback RPC, no dedup memo
        churn).  A member that cannot be reached — or does not answer
        inside the deadline — lands in ``errors`` and bumps
        ``metrics_scrape_errors_total{node,method}``; it never raises, so
        the REST federation degrades to ``partial: true`` instead of a
        5xx.  One bounded retry per member (``retries=1``): an HTTP worker
        is usually waiting on the merge."""
        members = self.members_sorted()
        # workers write ONLY their own slot (a single reference
        # assignment); results/errors are built from a one-shot snapshot
        # of the slots after the join deadline, so a straggler thread that
        # answers late mutates nothing the caller is iterating — the
        # federation endpoints keep their never-5xx contract even against
        # a peer that dribbles bytes past every timeout
        slots: List[Optional[Tuple[str, Any]]] = [None] * len(members)

        def _one(i: int, m: Member) -> None:
            if m.info.name == self.info.name:
                fn = self.rpc_server._methods.get(method)
                try:
                    if fn is None:
                        raise KeyError(f"unknown RPC method {method!r}")
                    slots[i] = ("ok", fn(payload))
                except Exception as e:  # noqa: BLE001 — degrade, don't 5xx
                    slots[i] = ("err", f"{type(e).__name__}: {e}")
                return
            try:
                slots[i] = ("ok", self.client.call(
                    m.info.addr, method, payload,
                    timeout=timeout, target=m.info.ident, retries=1))
            except _rpc.RPCError as e:
                slots[i] = ("err", str(e))

        threads = [threading.Thread(target=_one, args=(i, m), daemon=True,
                                    name=f"scrape-{m.info.name}")
                   for i, m in enumerate(members)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 2 * timeout + 1.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        results: Dict[str, Any] = {}
        errors: Dict[str, str] = {}
        for m, slot in zip(members, list(slots)):  # one-shot snapshot
            name = m.info.name
            if slot is None:
                errors[name] = f"no answer within {timeout}s"
            elif slot[0] == "ok":
                results[name] = slot[1]
            else:
                errors[name] = slot[1]
            if name in errors:
                _SCRAPE_ERRORS.inc(node=name, method=method)
        return results, errors


# ---------------------------------------------------------------------------
# process-global cloud (the H2O.CLOUD static)

_LOCAL: Optional[Cloud] = None
_LOCAL_LOCK = threading.Lock()


def local_cloud() -> Optional[Cloud]:
    return _LOCAL


def set_local_cloud(cloud: Optional[Cloud]) -> None:
    global _LOCAL
    with _LOCAL_LOCK:
        _LOCAL = cloud


def boot_node(
    cloud_name: str,
    node_name: str,
    *,
    host: str = "127.0.0.1",
    port: int = 0,
    client: bool = False,
    hb_interval: Optional[float] = None,
    flatfile: Optional[str] = None,
    address_file: Optional[str] = None,
    store=None,
) -> Cloud:
    """One-call cluster-node bootstrap shared by the REST launcher
    (``__main__``), the light ``nodeproc`` harness and ``bench.py``:
    construct the Cloud, install the DKV router and DTask registry,
    publish it as the process cloud, write the resolved RPC address
    atomically, and run the synchronous join round.  On
    :class:`CloudJoinError` the node is already stopped and unpublished
    before the error propagates."""
    from h2o3_tpu.cluster import dkv as _dkv
    from h2o3_tpu.cluster import tasks as _tasks

    # a plan shipped via H2O3_TPU_FAULT_PLAN must be live before the
    # first join beat — chaos scenarios fault the join itself
    _faults.install_from_env()
    cloud = Cloud(cloud_name, node_name, host=host, port=port,
                  client=client, hb_interval=hb_interval)
    # declare the process's trace identity: every timeline event this node
    # records from here on carries node=<name>, so merged cluster timelines
    # and propagated traces attribute work to the member that did it
    telemetry.set_node_name(node_name)
    _dkv.install(cloud, store)
    _tasks.install(cloud)
    set_local_cloud(cloud)
    if address_file:
        tmp = address_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{cloud.info.host}:{cloud.info.port}\n")
        os.replace(tmp, address_file)  # atomic: readers never see half
    seeds = parse_flatfile(flatfile) if flatfile else []
    try:
        cloud.start(seeds)
    except CloudJoinError:
        cloud.stop()
        set_local_cloud(None)
        raise
    # the node's watchdog thread + crash hooks come up with the cloud
    # (H2O3_TPU_HEALTH=0 leaves the monitor idle)
    from h2o3_tpu.cluster import health as _health

    _health.start(node=node_name)
    return cloud


def parse_flatfile(path: str) -> List[Tuple[str, int]]:
    """Flatfile lines -> RPC addresses.  The reference's ``-flatfile``
    format: one ``host:port`` per line, ``#`` comments and blanks
    ignored."""
    seeds: List[Tuple[str, int]] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            host, _, port = line.rpartition(":")
            if not host:
                raise ValueError(
                    f"flatfile line {line!r} is not host:port")
            seeds.append((host, int(port)))
    return seeds
