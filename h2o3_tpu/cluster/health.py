"""Per-node stall watchdogs + the federated /3/Diagnostics bundle.

The flight recorder (``util/flight.py``) remembers what a node DID; this
module notices what a node is FAILING to do — while it happens, not in a
post-mortem metrics scrape.  A single monitor thread per node evaluates
declarative health rules over SNAPSHOTS of live state every
``H2O3_TPU_HEALTH_INTERVAL_S`` seconds:

===================  ====================================================
check                fires when
===================  ====================================================
``rpc_stuck``        a client RPC has been in flight longer than
                     ``H2O3_TPU_HEALTH_RPC_FACTOR`` x its full ladder
                     budget (critical at 2x that)
``fanout_stalled``   an active fan-out context has made no partial
                     progress for ``H2O3_TPU_HEALTH_STALL_S`` seconds
                     (critical at 2x)
``heartbeat_overrun``  the local gossip cycle has not completed within
                     ``H2O3_TPU_HEALTH_HB_FACTOR`` x ``hb_interval``
                     (critical at 2x)
``http_saturation``  ``http_queue_depth`` exceeds
                     ``H2O3_TPU_HEALTH_QUEUE_PCT``% of the admission
                     queue, or requests were shed
                     (``H2O3_TPU_HEALTH_SHED``+) inside the sliding
                     ``H2O3_TPU_HEALTH_WINDOW_S`` window
``compile_storm``    more than ``H2O3_TPU_HEALTH_COMPILES`` jit compiles
                     landed inside the sliding window (the ledger-visible
                     recompile pathology)
===================  ====================================================

Every verdict TRANSITION fires a flight-recorder event and a log line;
every tick publishes ``cluster_health_state{node,check}`` (0 ok,
1 degraded, 2 critical).  A transition INTO critical escalates: all
thread stacks are dumped into the flight ring (same path SIGUSR2 takes),
so the crash file explains the stall even if the process never recovers.

Locking discipline (LOCK001): the monitor owns no subsystem lock, ever —
every input is a snapshot API (``rpc.inflight_snapshot()``,
``flight.FANOUTS.snapshot()``, telemetry ``value()``/``total()`` reads,
a single monotonic cycle stamp on the Cloud); its own verdict lock is a
leaf around pure dict work.  Rule arithmetic lives in module-level pure
functions so the window math is unit-testable without a thread.

``diagnostics_snapshot()`` assembles this node's half of
``GET /3/Diagnostics``: identity + ``H2O3_TPU_*`` knob snapshot, verdict
table, last-K flight events, worst SlowOps, membership view, and thread
stacks — federated by ``Cloud.poll_members`` under the established
partial-never-5xx contract.  ``H2O3_TPU_HEALTH=0`` keeps the monitor
from starting at boot.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import telemetry

__all__ = [
    "HealthMonitor",
    "MONITOR",
    "start",
    "stop",
    "verdicts",
    "summary",
    "diagnostics_snapshot",
    "thread_stacks",
    # pure rule functions (unit-tested window arithmetic)
    "rpc_stuck_rule",
    "fanout_stall_rule",
    "heartbeat_rule",
    "http_saturation_rule",
    "compile_storm_rule",
]

#: verdict severity order; gauge value = index
STATES = ("ok", "degraded", "critical")
_STATE_NUM = {s: float(i) for i, s in enumerate(STATES)}
_STATE_SEV = {"ok": "info", "degraded": "warn", "critical": "error"}

_HEALTH_STATE = telemetry.gauge(
    "cluster_health_state",
    "watchdog verdict per health check: 0 ok, 1 degraded, 2 critical",
    labels=("node", "check"),
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_on(name: str, default: bool = True) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


# ---------------------------------------------------------------------------
# rule arithmetic: pure functions over snapshots, no I/O, no locks


def rpc_stuck_rule(entries: List[Dict[str, Any]],
                   factor: float) -> Tuple[str, str]:
    """``entries`` from :func:`h2o3_tpu.cluster.rpc.inflight_snapshot`:
    degraded when any call's age exceeds ``factor`` x its full ladder
    budget, critical at twice that.  A healthy slow op — age inside its
    own budget — never trips (the no-false-stall property the tests
    pin)."""
    worst, detail = "ok", ""
    for e in entries:
        budget = max(float(e.get("budget_s", 0.0)), 1e-9)
        age = float(e.get("age_s", 0.0))
        if age <= factor * budget:
            continue
        state = "critical" if age > 2.0 * factor * budget else "degraded"
        if _STATE_NUM[state] > _STATE_NUM[worst]:
            worst = state
            detail = ("%s -> %s in flight %.2fs (budget %.2fs, attempt %d)"
                      % (e.get("method", "?"), e.get("target", "?"), age,
                         budget, int(e.get("attempt", 0))))
    return worst, detail


def fanout_stall_rule(entries: List[Dict[str, Any]],
                      window_s: float) -> Tuple[str, str]:
    """``entries`` from ``flight.FANOUTS.snapshot()``: an unfinished
    fan-out idle past ``window_s`` is degraded, past 2x critical."""
    worst, detail = "ok", ""
    for e in entries:
        if int(e.get("done", 0)) >= int(e.get("total", 0)):
            continue
        idle = float(e.get("idle_s", 0.0))
        if idle <= window_s:
            continue
        state = "critical" if idle > 2.0 * window_s else "degraded"
        if _STATE_NUM[state] > _STATE_NUM[worst]:
            worst = state
            detail = ("%s stalled %.1fs at %d/%d ranges"
                      % (e.get("kind", "?"), idle, int(e.get("done", 0)),
                         int(e.get("total", 0))))
    return worst, detail


def heartbeat_rule(cycle_age_s: Optional[float], hb_interval_s: float,
                   factor: float) -> Tuple[str, str]:
    """``cycle_age_s`` = seconds since the local gossip loop last
    completed a cycle (None: no cloud running, trivially ok)."""
    if cycle_age_s is None:
        return "ok", ""
    limit = factor * max(hb_interval_s, 1e-9) + 1.0
    if cycle_age_s <= limit:
        return "ok", ""
    state = "critical" if cycle_age_s > 2.0 * limit else "degraded"
    return state, ("gossip cycle overdue %.1fs (interval %.2fs)"
                   % (cycle_age_s, hb_interval_s))


def http_saturation_rule(depth: float, capacity: int, shed_delta: float,
                         pct: int, shed_min: int) -> Tuple[str, str]:
    if capacity > 0 and depth >= capacity:
        return "critical", ("admission queue full (%d/%d)"
                            % (int(depth), capacity))
    degraded = []
    if capacity > 0 and depth > capacity * pct / 100.0:
        degraded.append("queue %d/%d" % (int(depth), capacity))
    if shed_delta >= max(1, shed_min):
        degraded.append("%d shed in window" % int(shed_delta))
    if degraded:
        return "degraded", ", ".join(degraded)
    return "ok", ""


def compile_storm_rule(compile_delta: float,
                       threshold: int) -> Tuple[str, str]:
    if compile_delta > 2 * threshold:
        return "critical", "%d jit compiles in window" % int(compile_delta)
    if compile_delta > threshold:
        return "degraded", "%d jit compiles in window" % int(compile_delta)
    return "ok", ""


# ---------------------------------------------------------------------------
# snapshot inputs (every one a point read; the monitor holds nothing open)


def _metric_total(name: str) -> float:
    m = telemetry.REGISTRY.get(name)
    if m is None:
        return 0.0
    try:
        return float(m.total())  # type: ignore[attr-defined]
    except AttributeError:
        return 0.0


def _metric_value(name: str) -> float:
    m = telemetry.REGISTRY.get(name)
    if m is None:
        return 0.0
    try:
        return float(m.value())  # type: ignore[attr-defined]
    except (AttributeError, KeyError):
        return 0.0


def _cycle_age_s() -> Tuple[Optional[float], float]:
    """(seconds since the local cloud's last completed gossip cycle,
    its hb_interval) — (None, 1.0) when no cloud/loop is running."""
    from h2o3_tpu.cluster import membership as _membership

    cloud = _membership.local_cloud()
    if cloud is None:
        return None, 1.0
    stamp = getattr(cloud, "last_cycle_mono", None)
    if stamp is None or getattr(cloud, "_stopping", None) is None \
            or cloud._stopping.is_set():
        return None, float(getattr(cloud, "hb_interval", 1.0))
    return time.monotonic() - stamp, float(cloud.hb_interval)


class _WindowDelta:
    """Value-now minus value-at-window-start over a sliding window of
    (monotonic, value) samples — the shed-rate / compile-storm input."""

    def __init__(self, window_s: float) -> None:
        self.window_s = window_s
        self._samples: "deque[Tuple[float, float]]" = deque()

    def update(self, value: float) -> float:
        now = time.monotonic()
        self._samples.append((now, value))
        while self._samples and self._samples[0][0] < now - self.window_s:
            self._samples.popleft()
        return value - self._samples[0][1]


class HealthMonitor:
    """The per-node watchdog thread.  Restartable: chaos scenarios stop
    and start a fresh monitor per seeded run."""

    def __init__(self, node: Optional[str] = None,
                 interval_s: Optional[float] = None) -> None:
        self.node = node or telemetry.node_name() or "localhost"
        self.interval_s = (
            _env_float("H2O3_TPU_HEALTH_INTERVAL_S", 1.0)
            if interval_s is None else float(interval_s))
        self.rpc_factor = _env_float("H2O3_TPU_HEALTH_RPC_FACTOR", 3.0)
        self.stall_s = _env_float("H2O3_TPU_HEALTH_STALL_S", 10.0)
        self.hb_factor = _env_float("H2O3_TPU_HEALTH_HB_FACTOR", 4.0)
        self.queue_pct = _env_int("H2O3_TPU_HEALTH_QUEUE_PCT", 80)
        self.shed_min = _env_int("H2O3_TPU_HEALTH_SHED", 1)
        self.compiles = _env_int("H2O3_TPU_HEALTH_COMPILES", 20)
        window_s = _env_float("H2O3_TPU_HEALTH_WINDOW_S", 30.0)
        self._shed_win = _WindowDelta(window_s)
        self._compile_win = _WindowDelta(window_s)
        self._lock = threading.Lock()  # leaf: verdict dict only
        self._verdicts: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._queue_cap = _env_int("H2O3_TPU_HTTP_QUEUE", 512)

    # -- lifecycle -----------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="health-monitor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _loop(self) -> None:
        self.tick()  # first verdict immediately, not one interval late
        while not self._stop.wait(self.interval_s):
            self.tick()

    # -- one evaluation round ------------------------------------------------
    def _checks(self) -> List[Tuple[str, Callable[[], Tuple[str, str]]]]:
        from h2o3_tpu.cluster import rpc as _rpc

        def _hb() -> Tuple[str, str]:
            age, interval = _cycle_age_s()
            return heartbeat_rule(age, interval, self.hb_factor)

        return [
            ("rpc_stuck", lambda: rpc_stuck_rule(
                _rpc.inflight_snapshot(), self.rpc_factor)),
            ("fanout_stalled", lambda: fanout_stall_rule(
                _flight.FANOUTS.snapshot(), self.stall_s)),
            ("heartbeat_overrun", _hb),
            ("http_saturation", lambda: http_saturation_rule(
                _metric_value("http_queue_depth"), self._queue_cap,
                self._shed_win.update(_metric_total("http_shed_total")),
                self.queue_pct, self.shed_min)),
            ("compile_storm", lambda: compile_storm_rule(
                self._compile_win.update(_metric_total("jit_compiles_total")),
                self.compiles)),
        ]

    def tick(self) -> None:
        """Evaluate every rule once (the loop body; tests call directly)."""
        now_ms = int(time.time() * 1000)
        for check, fn in self._checks():
            try:
                state, detail = fn()
            except Exception as e:  # noqa: BLE001 — a broken rule must
                state, detail = "ok", f"rule error: {e}"  # not kill the loop
            with self._lock:
                prev = self._verdicts.get(check)
                changed = prev is None or prev["state"] != state
                if changed:
                    self._verdicts[check] = {
                        "state": state, "detail": detail, "since_ms": now_ms}
                else:
                    prev["detail"] = detail
            _HEALTH_STATE.set(_STATE_NUM[state], node=self.node, check=check)
            if not changed:
                continue
            # transition: flight event + log line, stacks on -> critical
            _flight.record(
                _flight.HEALTH, _STATE_SEV[state], "verdict",
                check=check, state=state, detail=detail)
            from h2o3_tpu.util.log import get_logger

            log = get_logger("health")
            if state == "ok":
                log.info("%s: %s recovered", self.node, check)
            else:
                log.warning("%s: %s %s — %s",
                            self.node, check, state, detail)
            if state == "critical":
                _flight.dump_stacks(reason=f"watchdog:{check}")

    # -- read side -----------------------------------------------------------
    def verdicts(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._verdicts.items())}

    def summary(self) -> Dict[str, Any]:
        """The compact block /3/Profiler and /3/SlowOps embed: worst
        state across checks plus the per-check states."""
        with self._lock:
            checks = {k: v["state"] for k, v in sorted(
                self._verdicts.items())}
        worst = "unknown" if not checks else max(
            checks.values(), key=lambda s: _STATE_NUM[s])
        return {"node": self.node, "state": worst, "checks": checks,
                "running": self.running}


#: process-wide monitor (replaced by start() so chaos runs get a fresh one)
MONITOR = HealthMonitor()


def start(node: Optional[str] = None,
          interval_s: Optional[float] = None) -> HealthMonitor:
    """Boot-time entry: (re)create and start the node's monitor, arm the
    crash hooks, and register the crash-file enricher.  Honors
    ``H2O3_TPU_HEALTH=0`` (returns the idle monitor without a thread)."""
    global MONITOR
    if MONITOR.running and node in (None, MONITOR.node):
        return MONITOR
    if MONITOR.running:
        MONITOR.stop()
    MONITOR = HealthMonitor(node=node, interval_s=interval_s)
    _flight.set_crash_extras(
        lambda: {"health": MONITOR.verdicts()})
    if _env_on("H2O3_TPU_HEALTH", True):
        _flight.install_crash_hooks()
        MONITOR.start()
    return MONITOR


def stop() -> None:
    MONITOR.stop()


def verdicts() -> Dict[str, Dict[str, Any]]:
    return MONITOR.verdicts()


def summary() -> Dict[str, Any]:
    return MONITOR.summary()


# ---------------------------------------------------------------------------
# the /3/Diagnostics bundle (per-member half, fanned out via poll_members)


def thread_stacks(limit: int = 64) -> List[Dict[str, Any]]:
    """Every live thread's current stack, JSON-able (the jstack shape)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in list(frames.items())[:limit]:
        out.append({
            "thread": names.get(ident, str(ident)),
            "frames": [ln.rstrip("\n")
                       for ln in traceback.format_stack(frame)],
        })
    return out


def knobs_snapshot() -> Dict[str, str]:
    """Every ``H2O3_TPU_*`` env knob this process booted with."""
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith("H2O3_TPU_")}


def diagnostics_snapshot(cloud: Any = None,
                         events: int = 200) -> Dict[str, Any]:
    """One node's diagnostics bundle: identity + knobs, health verdicts,
    last-``events`` flight events, worst SlowOps, membership view, and
    thread stacks.  Pure snapshot reads — safe to serve mid-wedge."""
    from h2o3_tpu.util import ledger as _ledger

    if cloud is None:
        from h2o3_tpu.cluster import membership as _membership

        cloud = _membership.local_cloud()
    name = (cloud.info.name if cloud is not None
            else telemetry.node_name() or "localhost")
    return {
        "kind": "diagnostics",
        "node": name,
        "pid": os.getpid(),
        "now_ms": int(time.time() * 1000),
        "knobs": knobs_snapshot(),
        "health": {"summary": MONITOR.summary(),
                   "verdicts": MONITOR.verdicts()},
        "flight": _flight.RECORDER.snapshot(count=max(0, int(events))),
        "slowops": _ledger.SLOWOPS.snapshot(),
        "members": cloud.member_schemas() if cloud is not None else [],
        "threads": thread_stacks(),
    }
