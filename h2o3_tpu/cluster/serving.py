"""L3d: the cluster-wide serving plane — score any model from any member.

Reference: in H2O-3 a model is just a ``water.Key`` homed on the DKV
ring, so ``POST /3/Predictions`` works identically on every node of the
cloud; and the TF-Serving batching paper has the serving front-end route
to a warm home and batch THERE, so N front doors still collapse into one
devcache-warm dispatch.  This module composes both out of planes that
already exist:

* **Homing** — a trained model's :func:`~h2o3_tpu.models.persist.dumps_model`
  blob is put under ``serve#<model_key>`` with ``replicas=`` fan-out.
  :func:`~h2o3_tpu.cluster.dkv.ring_key` strips the ``serve#`` prefix,
  so the blob hashes to the SAME ring home the serving plane routes
  scoring to, and the copies ride every existing ring mechanism
  (replicate, read-repair, anti-entropy sweep) unchanged.
* **Forwarding** — a front door that cannot resolve a model locally
  ships the scoring bundle over the ``predict_remote`` DTask to the ring
  home (frames as rows for small payloads, as ``__dist__`` references
  for chunk-homed frames).  The home feeds every forwarded entry through
  a :class:`~h2o3_tpu.api.coalesce.Coalescer`, so bundles from N nodes
  merge into ONE batched raw-score dispatch.
* **Spill + recovery** — a home past its serving budget answers a typed
  429; the front door spills to the ring replicas (which score the SAME
  blob, bit-identically).  A dead home walks the replica → survivor →
  caller-local ladder from ``cluster/frames.py``, so a SIGKILL mid-storm
  degrades to 2xx/429 — never a 5xx, never a wrong answer.

Forwarded work runs under the caller's trace (the RPC plane propagates
trace context and the remote span charges the originating trace), so the
ledger bills forwarded requests to the client that sent them.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from h2o3_tpu.cluster import dkv as _dkv
from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster import tasks as _tasks
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import telemetry

#: outcome of every front-door serving resolution, per request:
#: ok=the ring home served, replica=a ring successor (429 spill or home
#: failure), survivor=any healthy member after the walk died, local=the
#: caller scored its own blob copy as the last resort, shed=429 after
#: the whole ladder, error=no rung could serve
_FORWARD = telemetry.counter(
    "serve_forward_total",
    "front-door scoring requests resolved through the serving ring, "
    "by outcome (ok/replica/survivor/local/shed/error)",
    labels=("result",),
)
_SPILL = telemetry.counter(
    "serve_replica_spill_total",
    "forwarded scoring requests spilled from a shedding home to a ring "
    "replica (the home answered 429 and the replica scored instead)",
)

#: per-forward RPC timeout — a scoring bundle, not a training job
FORWARD_TIMEOUT = 30.0
#: per-entry wait on the serving coalescer's dispatch
SCORE_TIMEOUT = 60.0

#: serving-plane model cache per store (decoded-from-blob models), LRU
_MODEL_CACHE_CAP = 8

_LOCK = threading.Lock()
_COAL = None
_COAL_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# knobs (read at call time so tests and spawned bench nodes can retune
# without rebuilding servers)


def serve_key(model_key: str) -> str:
    """The ring key a model's serving blob lives under.  ``ring_key``
    strips the prefix, so the blob homes exactly where the serving plane
    routes scoring for ``model_key``."""
    return f"serve#{model_key}"


def replicas() -> int:
    """Ring successors that receive a copy of every homed model blob."""
    try:
        n = int(os.environ.get("H2O3_TPU_SERVE_REPLICAS", "2"))
    except ValueError:
        n = 2
    return max(0, min(n, _dkv.MAX_REPLICAS - 1))


def spill_enabled() -> bool:
    """Spill shed (429) forwards to ring replicas instead of failing?"""
    return os.environ.get("H2O3_TPU_SERVE_SPILL", "1").lower() not in (
        "0", "false", "no")


def serve_budget(store=None) -> int:
    """In-flight serving entries a node accepts before shedding 429 —
    the serving-side analogue of the REST per-route budget, sharing its
    knob unless ``H2O3_TPU_SERVE_BUDGET`` pins the serving plane
    separately (how the bench saturates ONE node's serving path without
    touching its REST admission).  A store-level override
    (``store._serve_budget``) lets tests saturate ONE in-process node."""
    if store is not None:
        override = getattr(store, "_serve_budget", None)
        if override is not None:
            return int(override)
    try:
        return int(os.environ.get(
            "H2O3_TPU_SERVE_BUDGET",
            os.environ.get("H2O3_TPU_HTTP_ROUTE_BUDGET", "256")))
    except ValueError:
        return 256


# ---------------------------------------------------------------------------
# homing + replication


def home_model(model, cloud=None, store=None) -> bool:
    """Publish a trained model's blob onto the serving ring: one copy on
    the ring home of its key plus :func:`replicas` successors.  Called
    best-effort after every successful train on a live multi-node cloud;
    returns False (never raises) when there is no ring to home onto —
    single-node serving is untouched."""
    try:
        if cloud is None:
            from h2o3_tpu.cluster import active_cloud

            cloud = active_cloud()
        if cloud is None:
            return False
        if store is None:
            store = getattr(cloud, "dkv_store", None)
        if store is None:
            return False
        router = getattr(store, "router", None)
        if router is None or not router.active():
            return False
        key = getattr(model, "key", None)
        if not key:
            return False
        from h2o3_tpu.models.persist import dumps_model

        blob = dumps_model(model)
        store.put(serve_key(key), blob, replicas=1 + replicas())
        _flight.record(_flight.FANOUT, "info", "serve_home",
                       model=key, bytes=len(blob),
                       replicas=1 + replicas())
        return True
    except Exception:
        return False


def serving_members(model_key: str, store) -> List[Any]:
    """``[home, successor, ...]`` members that (should) hold the model's
    blob — the forwarding order of the ladder.  Empty when no live
    multi-node ring exists."""
    router = getattr(store, "router", None)
    if router is None or not router.active():
        return []
    return router.home_members(serve_key(model_key), 1 + replicas())


def _resolve_model(model_key: str, store):
    """The model object on THIS node: the local store's own registration
    (the builder), the serving cache, or a decode of the ring-homed blob
    (local replica copy first, then the ring walk).  None when no copy
    of the blob is reachable anywhere."""
    from h2o3_tpu.models.framework import Model

    m = store.peek(model_key)
    if isinstance(m, Model):
        return m
    cache = getattr(store, "_serve_models", None)
    if cache is not None:
        with _LOCK:
            m = cache.get(model_key)
        if m is not None:
            return m
    sk = serve_key(model_key)
    blob = store.peek(sk)
    if not isinstance(blob, (bytes, bytearray)):
        try:
            blob = store.get(sk)  # ring walk: home, then replica copies
        except _rpc.RPCError:
            blob = None
    if not isinstance(blob, (bytes, bytearray)):
        return None
    from h2o3_tpu.models.persist import loads_model

    m = loads_model(bytes(blob), register=False)
    m.key = model_key
    with _LOCK:
        cache = getattr(store, "_serve_models", None)
        if cache is None:
            cache = {}
            store._serve_models = cache
        cache[model_key] = m
        while len(cache) > _MODEL_CACHE_CAP:
            cache.pop(next(iter(cache)))
    return m


# ---------------------------------------------------------------------------
# serving side (the ring home or a replica): admission -> coalesce -> score


def _admit(store, n: int) -> None:
    budget = serve_budget(store)
    with _LOCK:
        cur = getattr(store, "_serve_inflight", 0)
        if cur + n > budget:
            raise _rpc.RpcFault(
                f"serving budget ({budget}) exhausted "
                f"({cur} entries in flight)",
                code=429, detail={"retry_after": "1"})
        store._serve_inflight = cur + n


def _release(store, n: int) -> None:
    with _LOCK:
        store._serve_inflight = max(
            0, getattr(store, "_serve_inflight", 0) - n)


def _coalescer():
    """The process-wide serving coalescer (batches key per store+model,
    so in-process test nodes never share a batch).  None when the batch
    window is configured off — bundles then score in one direct call."""
    global _COAL
    if _COAL is None:
        with _COAL_LOCK:
            if _COAL is None:
                try:
                    window_ms = float(
                        os.environ.get("H2O3_TPU_BATCH_WINDOW_MS", "2.0"))
                    max_rows = int(
                        os.environ.get("H2O3_TPU_BATCH_MAX_ROWS", "262144"))
                    max_reqs = int(
                        os.environ.get("H2O3_TPU_BATCH_MAX_REQUESTS", "256"))
                except ValueError:
                    window_ms, max_rows, max_reqs = 2.0, 262144, 256
                if window_ms <= 0:
                    return None
                from h2o3_tpu.api.coalesce import Coalescer, thread_dispatch

                _COAL = Coalescer(
                    dispatch=thread_dispatch,
                    window_s=window_ms / 1000.0,
                    max_rows=max_rows,
                    max_requests=max_reqs,
                )
    return _COAL


def _metrics_payload(mm) -> Optional[Dict[str, Any]]:
    from h2o3_tpu.api.handlers import _metrics_schema

    return _metrics_schema(mm)


def _err(code: int, e: BaseException) -> Dict[str, Any]:
    return {"error": {"code": int(code), "msg": f"{type(e).__name__}: {e}"}}


def _score_batch(payloads: List[Tuple[Any, Any]]) -> List[Dict[str, Any]]:
    """One coalesced dispatch: every payload is ``(model, frame)`` for
    the SAME model — the whole batch costs one raw-score pass, exactly
    the REST coalescer's contract (api/handlers.py predict_batch), so
    forwarded scoring stays bit-identical to local scoring."""
    from h2o3_tpu.cluster.search import frame_payload
    from h2o3_tpu.models.framework import Model

    m = payloads[0][0]
    frames = [fr for _, fr in payloads]
    out: List[Dict[str, Any]] = []
    if type(m).predict is not Model.predict:
        # bespoke predict shapes (PCA, aggregator) can't share a raw pass
        for fr in frames:
            try:
                pred = m.predict(fr)
                try:
                    metrics = _metrics_payload(m.model_performance(fr))
                except Exception:
                    metrics = None
                out.append({"prediction": frame_payload(pred),
                            "metrics": metrics})
            except BaseException as e:  # noqa: BLE001
                out.append(_err(400, e))
        return out
    try:
        scored: List[Any] = m.predict_raw_batched(frames)
    except BaseException:  # noqa: BLE001
        # one bad frame must not poison the bundle: retry serially
        scored = []
        for fr in frames:
            try:
                pre = m._apply_preprocessors(fr)
                scored.append((m._predict_raw(pre), pre))
            except BaseException as e:  # noqa: BLE001
                scored.append(e)
    own_perf = type(m).model_performance is Model.model_performance
    for fr, s in zip(frames, scored):
        if isinstance(s, BaseException):
            out.append(_err(400, s))
            continue
        try:
            raw, pre = s
            pred = m.prediction_from_raw(raw)
            try:
                mm = (m._metrics_from_raw(pre, raw) if own_perf
                      else m.model_performance(fr))
                metrics = _metrics_payload(mm)
            except Exception:
                metrics = None  # frames without a response still score
            out.append({"prediction": frame_payload(pred),
                        "metrics": metrics})
        except BaseException as e:  # noqa: BLE001
            out.append(_err(500, e))
    return out


def serve_entries(model_key: str, entries: List[Dict[str, Any]],
                  store) -> List[Dict[str, Any]]:
    """Score a forwarded bundle on THIS node (the ring home or a replica
    holding the blob).  Every entry rides the serving coalescer keyed by
    (store, model), so concurrent bundles from N front doors close into
    one batched dispatch.  Raises :class:`~h2o3_tpu.cluster.rpc.RpcFault`
    with code 429 (plus a retry_after detail) past the serving budget,
    404 when no blob copy is reachable; per-entry failures come back as
    ``{"error": {...}}`` so one bad frame never poisons the bundle."""
    from h2o3_tpu.cluster.search import frame_restore

    if store is None:
        raise _rpc.RpcFault("no DKV store on this member", code=503)
    n = len(entries)
    _admit(store, n)
    try:
        m = _resolve_model(model_key, store)
        if m is None:
            raise _rpc.RpcFault(
                f"model {model_key!r} has no reachable blob on the "
                f"serving ring", code=404)
        span = telemetry.current_span()
        tid = span.trace_id if span is not None else None
        outs: List[Optional[Dict[str, Any]]] = [None] * n
        coal = _coalescer()
        direct: List[Tuple[int, Any]] = []
        waits: List[Tuple[int, Any]] = []
        for i, e in enumerate(entries):
            try:
                fr = frame_restore(e["frame"], store)
            except _rpc.RpcFault as fe:
                outs[i] = {"error": {"code": fe.code, "msg": str(fe)}}
                continue
            except BaseException as fe:  # noqa: BLE001
                outs[i] = _err(400, fe)
                continue
            if coal is None:
                direct.append((i, fr))
            else:
                waits.append((i, coal.submit(
                    _score_batch, ("serve", id(store), model_key),
                    (m, fr),
                    rows_hint=int(e.get("rows") or
                                  getattr(fr, "nrows", 0) or 0),
                    trace_id=tid,
                )))
        if direct:
            for (i, _), r in zip(direct,
                                 _score_batch([(m, fr)
                                               for _, fr in direct])):
                outs[i] = r
        for i, fut in waits:
            try:
                outs[i] = fut.result(timeout=SCORE_TIMEOUT)
            except BaseException as fe:  # noqa: BLE001
                outs[i] = _err(500, fe)
        return [o if o is not None else _err(500, RuntimeError("unscored"))
                for o in outs]
    finally:
        _release(store, n)


# ---------------------------------------------------------------------------
# front door: resolve the home, forward, spill, walk the recovery ladder


def _shed_code(e: BaseException) -> Optional[int]:
    code = getattr(e, "code", None)
    return code if isinstance(code, int) else None


def _retry_after(e: BaseException) -> str:
    detail = getattr(e, "detail", None) or {}
    return str(detail.get("retry_after", "1"))


def _forward_ladder(cloud, store, members, model_key: str,
                    wire: List[Dict[str, Any]]):
    """Run one wire bundle down the serving ladder: home, then (on 429
    spill or home failure) the ring replicas, then — for failures only —
    any healthy survivor, then the caller itself.  Returns the aligned
    per-entry results; raises RestError(429) when every reachable rung
    shed (propagating the home's Retry-After) and RestError(503) when no
    rung could serve."""
    from h2o3_tpu.api.server import RestError

    me = cloud.info.name
    payload = {"model_key": model_key, "entries": wire}
    n = len(wire)
    shed: Optional[BaseException] = None
    first_err: Optional[BaseException] = None
    tried = set()

    def _try(member):
        tried.add(member.info.name)
        if member.info.name == me:
            return serve_entries(model_key, wire, store)
        return _tasks.submit(cloud, member, "predict_remote", payload,
                             timeout=FORWARD_TIMEOUT)

    # rung 0: the ring home — where forwarded bundles coalesce
    try:
        res = _try(members[0])
        _FORWARD.inc(n, result="ok")
        return res
    except (_rpc.RpcFault, _rpc.RemoteError) as e:
        if _shed_code(e) == 429:
            shed = e
        else:
            first_err = e
    except _rpc.RPCError as e:
        first_err = e

    # rung 1: ring replicas — spill targets on shed, failover otherwise;
    # replica scoring decodes the SAME blob, so answers stay bit-identical
    if shed is None or spill_enabled():
        for m in members[1:]:
            try:
                res = _try(m)
            except (_rpc.RpcFault, _rpc.RemoteError) as e:
                if _shed_code(e) == 429:
                    shed = shed or e
                else:
                    first_err = first_err or e
                continue
            except _rpc.RPCError as e:
                first_err = first_err or e
                continue
            if shed is not None:
                _SPILL.inc(n)
                _flight.record(_flight.RECOVERY, "info", "serve_spill",
                               model=model_key, to=m.info.name)
            else:
                _tasks._RECOVERED.inc(path="replica")
                _flight.record(_flight.RECOVERY, "warn", "serve_forward",
                               model=model_key, path="replica",
                               to=m.info.name)
            _FORWARD.inc(n, result="replica")
            return res
    if shed is None:
        # rung 2: any healthy survivor — it resolves the blob over the
        # ring walk itself (read-repair re-homes it as a side effect)
        for m in _tasks._healthy_workers(cloud):
            if m.info.name in tried or m.info.name == me:
                continue
            try:
                res = _try(m)
            except (_rpc.RpcFault, _rpc.RemoteError) as e:
                if _shed_code(e) == 429:
                    shed = e
                    break
                first_err = first_err or e
                continue
            except _rpc.RPCError as e:
                first_err = first_err or e
                continue
            _tasks._RECOVERED.inc(path="survivor")
            _flight.record(_flight.RECOVERY, "warn", "serve_forward",
                           model=model_key, path="survivor",
                           to=m.info.name)
            _FORWARD.inc(n, result="survivor")
            return res
    if shed is None and me not in tried:
        # rung 3: the caller itself — the last resort, same blob walk
        try:
            res = serve_entries(model_key, wire, store)
            _tasks._RECOVERED.inc(path="local")
            _flight.record(_flight.RECOVERY, "warn", "serve_forward",
                           model=model_key, path="local")
            _FORWARD.inc(n, result="local")
            return res
        except (_rpc.RpcFault, _rpc.RPCError) as e:
            if _shed_code(e) == 429:
                shed = e
            else:
                first_err = first_err or e
    if shed is not None:
        _FORWARD.inc(n, result="shed")
        # the home's Retry-After crosses the front door UNCHANGED, and
        # the front door's own route budget never double-counts the shed
        # (http_shed_total ticks at REST admission only)
        raise RestError(
            429, f"serving capacity for model {model_key!r} exhausted: "
                 f"{getattr(shed, 'msg', None) or shed}",
            headers=(("Retry-After", _retry_after(shed)),))
    _FORWARD.inc(n, result="error")
    raise RestError(
        503, f"model {model_key!r} unreachable on the serving ring"
             + (f": {first_err}" if first_err is not None else ""))


def _front_frame(frame_id: str, store):
    """The front door's view of a frame to forward: its own registration
    (plain or chunk-homed), else the ring's layout/setup for a
    chunk-homed frame parsed elsewhere."""
    from h2o3_tpu.api.server import RestError
    from h2o3_tpu.frame.frame import Frame

    fr = store.get(frame_id)
    if isinstance(fr, Frame):
        return fr
    from h2o3_tpu.cluster import frames as _frames

    try:
        layout = store.get(_frames.layout_key(frame_id))
        if isinstance(layout, dict):
            setup = store.get(_frames.setup_key(frame_id))
            if setup is not None:
                return _frames.DistFrame(
                    layout, _frames.setup_from_payload(setup), store)
    except Exception:
        pass
    raise RestError(404, f"frame {frame_id!r} not found")


def forward_predict(requests, model_id: str, cloud=None, store=None):
    """Resolve a scoring batch the local node cannot serve through the
    serving ring.  ``requests`` is the REST batch shape — a list of
    ``(params, {"model_id", "frame_id"})`` — and the return value aligns
    with it: one REST response dict or exception per entry (what
    ``predict_batch`` returns), or None when no multi-node ring exists
    and the caller should fall back to its local 404."""
    from h2o3_tpu.api.server import RestError
    from h2o3_tpu.cluster.search import frame_payload

    if cloud is None:
        from h2o3_tpu.cluster import active_cloud

        cloud = active_cloud()
    if cloud is None:
        return None
    if store is None:
        store = getattr(cloud, "dkv_store", None)
    if store is None:
        return None
    members = serving_members(model_id, store)
    if not members:
        return None
    results: List[Any] = [None] * len(requests)
    wire: List[Dict[str, Any]] = []
    live: List[int] = []
    for i, (_params, kw) in enumerate(requests):
        try:
            fr = _front_frame(kw["frame_id"], store)
            wire.append({"frame": frame_payload(fr),
                         "rows": int(getattr(fr, "nrows", 0) or 0)})
            live.append(i)
        except BaseException as e:  # noqa: BLE001
            results[i] = e
    if live:
        try:
            outs = _forward_ladder(cloud, store, members, model_id, wire)
            if len(outs) != len(live):
                raise RestError(
                    502, f"serving ring returned {len(outs)} results "
                         f"for {len(live)} entries")
        except BaseException as e:  # noqa: BLE001
            for i in live:
                results[i] = e
            return results
        for i, out in zip(live, outs):
            params, kw = requests[i]
            err = (out or {}).get("error") if isinstance(out, dict) else None
            if err is not None or not isinstance(out, dict):
                results[i] = RestError(
                    int((err or {}).get("code", 502)),
                    str((err or {}).get("msg", "remote scoring failed")))
                continue
            try:
                results[i] = _assemble(
                    params, model_id, kw["frame_id"], out, store)
            except BaseException as e:  # noqa: BLE001
                results[i] = e
    return results


def _assemble(params, model_id: str, frame_id: str,
              out: Dict[str, Any], store) -> Dict[str, Any]:
    """One forwarded entry's REST response: register the predictions
    frame LOCALLY (the client talks to this front door) and mirror the
    local handler's /3/Predictions shape.  The DKV scoring record stays
    on the serving node's side — the model object lives there."""
    from h2o3_tpu.cluster.search import frame_restore

    dest = params.get("predictions_frame") or store.make_key("pred")
    pred = frame_restore(out["prediction"], store)
    pred.key = dest
    store.put(dest, pred)
    resp: Dict[str, Any] = {
        "model_metrics": [
            {
                "frame": {"name": frame_id},
                "model": {"name": model_id},
                "predictions_frame": {"name": dest},
            }
        ]
    }
    if out.get("metrics"):
        resp["model_metrics"][0].update(out["metrics"])
    return resp
