"""L1 RPC: named-method request/response between nodes, with the
reference's retry ladder.

Reference: ``water/RPC.java:101`` — a DTask sent to an H2ONode retries on
a bounded exponential backoff until acked, and the receiving node dedups
re-sent tasks so a retried call never runs its side effects twice.  Both
halves are reproduced here:

* the client ladder: per-call timeout, ``retries`` attempts with
  exponential backoff (base doubling, capped), connection pooling, and a
  typed error surface (:class:`RPCTimeoutError` / :class:`RPCConnectionError`
  / :class:`RemoteError`);
* the server dedup: every logical call carries an idempotency token; the
  server memoizes ``token -> response`` (and parks duplicate deliveries of
  an in-flight token on the first execution), so a retry caused by a lost
  response frame returns the original result instead of re-running the
  method.

Every call is metered: ``rpc_calls_total{target,method,result}``,
``rpc_retries_total``, ``rpc_call_seconds{method}``.

Wire format: one pickled dict per frame.  Pickle is the AutoBuffer
analogue — nodes of one cloud run one codebase inside one trust boundary
(the reference ships compiled DTask classes over the same wire); the REST
surface, not this port, is the untrusted boundary.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from h2o3_tpu.cluster import transport
from h2o3_tpu.util import telemetry

_RPC_CALLS = telemetry.counter(
    "rpc_calls_total", "node RPC calls by outcome",
    labels=("target", "method", "result"),
)
_RPC_RETRIES = telemetry.counter(
    "rpc_retries_total", "RPC attempts re-sent by the backoff ladder"
)
_RPC_SECONDS = telemetry.histogram(
    "rpc_call_seconds", "RPC round-trip wall seconds (incl. retries)",
    labels=("method",),
)
_RPC_SERVED = telemetry.counter(
    "rpc_served_total", "RPC requests served by the local node",
    labels=("method", "result"),
)


class RPCError(Exception):
    """Base of every typed RPC failure."""


class RPCTimeoutError(RPCError):
    """The call's per-attempt timeout expired on every attempt."""


class RPCConnectionError(RPCError):
    """No attempt could reach (or keep) a connection to the target."""


class RemoteError(RPCError):
    """The remote method raised; carries the remote type and an HTTP-ish
    status code so control-plane callers (cloud join, REST proxies) can
    answer 4xx instead of opaque 500s."""

    def __init__(self, remote_type: str, msg: str, code: int = 500,
                 detail: Optional[dict] = None) -> None:
        super().__init__(f"{remote_type}: {msg}")
        self.remote_type = remote_type
        self.msg = msg
        self.code = code
        self.detail = detail or {}


class RpcFault(Exception):
    """Raise from a method handler to send a typed, coded error to the
    caller (surfaces there as :class:`RemoteError` with the same code)."""

    def __init__(self, msg: str, code: int = 400,
                 detail: Optional[dict] = None) -> None:
        super().__init__(msg)
        self.code = code
        self.detail = detail or {}


def _encode(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


class RpcClient:
    """Pooled caller with the bounded exponential-backoff retry ladder."""

    def __init__(
        self,
        dialer: Callable[[transport.Address, float], transport.Connection] = transport.dial,
        retries: int = 3,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
    ) -> None:
        self.pool = transport.ConnectionPool(dialer)
        self.retries = int(retries)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max

    def call(
        self,
        addr: transport.Address,
        method: str,
        payload: Any = None,
        timeout: float = 5.0,
        target: str = "",
        retries: Optional[int] = None,
    ) -> Any:
        """One logical call: up to ``1 + retries`` attempts, every retry
        re-sending the SAME idempotency token so the server side never
        double-executes (water/RPC.java's resend discipline).

        ``timeout`` is PER ATTEMPT: worst-case blocking against a
        black-holed peer is ``(1 + retries) * timeout`` plus backoff.
        Deadline-sensitive callers (heartbeat loops, REST proxies) pass
        ``retries=`` to shrink or disable the ladder for that call.
        """
        token = uuid.uuid4().hex
        request = _encode(
            {"id": token, "method": method, "payload": payload}
        )
        target = target or f"{addr[0]}:{addr[1]}"
        ladder = self.retries if retries is None else max(0, int(retries))
        t0 = time.perf_counter()
        last_exc: Optional[BaseException] = None
        timed_out = False
        try:
            for attempt in range(ladder + 1):
                if attempt:
                    _RPC_RETRIES.inc()
                    time.sleep(min(
                        self.backoff_base * (2 ** (attempt - 1)),
                        self.backoff_max,
                    ))
                try:
                    raw = self._attempt(addr, request, timeout)
                except socket.timeout as e:
                    timed_out = True
                    last_exc = e
                    continue
                except (ConnectionError, OSError) as e:
                    last_exc = e
                    continue
                resp = pickle.loads(raw)
                if resp.get("ok"):
                    _RPC_CALLS.inc(target=target, method=method, result="ok")
                    return resp.get("value")
                err = resp.get("error") or {}
                _RPC_CALLS.inc(
                    target=target, method=method, result="remote_error")
                raise RemoteError(
                    err.get("type", "Exception"),
                    err.get("msg", "remote call failed"),
                    int(err.get("code", 500)),
                    err.get("detail"),
                )
            result = "timeout" if timed_out else "connect_error"
            _RPC_CALLS.inc(target=target, method=method, result=result)
            if timed_out:
                raise RPCTimeoutError(
                    f"{method} to {target} timed out after "
                    f"{ladder + 1} attempts of {timeout}s"
                ) from last_exc
            raise RPCConnectionError(
                f"{method} to {target} unreachable after "
                f"{ladder + 1} attempts: {last_exc}"
            ) from last_exc
        finally:
            _RPC_SECONDS.observe(time.perf_counter() - t0, method=method)

    def _attempt(self, addr: transport.Address, request: bytes,
                 timeout: float) -> bytes:
        """One ladder attempt.  Every idle pooled socket to a restarted
        peer is stale at once (pool max_idle == ladder depth), so a
        pooled connection that fails is closed and the next tried WITHIN
        the attempt — only a fresh dial's failure, or any timeout,
        charges the retry ladder."""
        while True:
            conn = self.pool.pop_idle(addr)
            if conn is None:
                break
            try:
                raw = conn.request(request, timeout)
            except socket.timeout:
                conn.close()  # live but slow: the ladder's problem
                raise
            except (ConnectionError, OSError):
                conn.close()  # stale pooled socket: try the next
                continue
            self.pool.put(conn)
            return raw
        conn = self.pool.dial(addr, timeout)
        try:
            raw = conn.request(request, timeout)
        except BaseException:
            conn.close()  # response may still arrive: poisoned
            raise
        self.pool.put(conn)
        return raw

    def close(self) -> None:
        self.pool.close_all()


class RpcServer:
    """Method registry + idempotent dispatch over a TransportServer."""

    #: responses remembered per idempotency token — deep enough that a
    #: retry ladder (seconds) can never outlive the memo (thousands of
    #: calls) under any realistic call rate
    DEDUP_CAPACITY = 4096
    #: byte budget across memoized responses: big payloads (DKV frames,
    #: echo benches) must not pin hundreds of MB of dead responses —
    #: oldest entries evict first once the budget is exceeded
    DEDUP_BYTE_BUDGET = 64 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._methods: Dict[str, Callable[[Any], Any]] = {}
        self._lock = threading.Lock()
        #: token -> (done_event, encoded_response|None): duplicates of an
        #: in-flight token wait on the first execution instead of racing it
        self._seen: "OrderedDict[str, Tuple[threading.Event, Optional[bytes]]]" = OrderedDict()
        self._seen_bytes = 0
        self._server = transport.TransportServer(
            self._handle, host=host, port=port)
        self.address = self._server.address

    def register(self, method: str, fn: Callable[[Any], Any]) -> None:
        self._methods[method] = fn

    def _execute(self, method: str, payload: Any) -> bytes:
        fn = self._methods.get(method)
        try:
            if fn is None:
                raise RpcFault(f"unknown RPC method {method!r}", code=404)
            value = fn(payload)
            _RPC_SERVED.inc(method=method, result="ok")
            return _encode({"ok": True, "value": value})
        except RpcFault as e:
            _RPC_SERVED.inc(method=method, result="fault")
            return _encode({"ok": False, "error": {
                "type": "RpcFault", "msg": str(e), "code": e.code,
                "detail": e.detail,
            }})
        except Exception as e:  # noqa: BLE001 — ships to the caller typed
            _RPC_SERVED.inc(method=method, result="error")
            return _encode({"ok": False, "error": {
                "type": type(e).__name__, "msg": str(e), "code": 500,
            }})

    def _evict_memo_locked(self) -> None:
        """Oldest-first memo eviction that never drops an IN-FLIGHT
        token: evicting one would re-execute a retried mutation (if its
        first run later completed) or 409 a parked duplicate of a call
        that actually succeeded.  In-flight entries hold no response
        bytes, so the byte budget is enforceable without them; capacity
        may transiently exceed by the number of concurrent calls."""
        def _over() -> bool:
            return len(self._seen) > self.DEDUP_CAPACITY or (
                self._seen_bytes > self.DEDUP_BYTE_BUDGET
                and len(self._seen) > 1)

        if not _over():
            return
        for tok in list(self._seen):
            if not _over():
                return
            _ev, resp = self._seen[tok]
            if resp is None:
                continue  # in-flight: protected
            del self._seen[tok]
            self._seen_bytes -= len(resp)

    def _handle(self, raw: bytes) -> bytes:
        try:
            req = pickle.loads(raw)
            token = req["id"]
            method = req["method"]
        except Exception as e:  # undecodable frame: typed error, no memo
            return _encode({"ok": False, "error": {
                "type": type(e).__name__, "msg": f"bad request frame: {e}",
                "code": 400,
            }})
        with self._lock:
            entry = self._seen.get(token)
            if entry is None:
                event = threading.Event()
                self._seen[token] = (event, None)
                self._evict_memo_locked()
            else:
                event = entry[0]
        if entry is not None:
            # duplicate delivery (retry after a lost response): wait for
            # the original execution, return its memoized response
            event.wait(timeout=300)
            with self._lock:
                memo = self._seen.get(token)
            if memo is not None and memo[1] is not None:
                return memo[1]
            return _encode({"ok": False, "error": {
                "type": "RpcFault", "code": 409,
                "msg": "duplicate of a call that never completed",
            }})
        response = self._execute(method, req.get("payload"))
        with self._lock:
            if token in self._seen:
                self._seen[token] = (event, response)
                self._seen_bytes += len(response)
        event.set()
        return response

    def stop(self) -> None:
        self._server.stop()
