"""L1 RPC: named-method request/response between nodes, with the
reference's retry ladder.

Reference: ``water/RPC.java:101`` — a DTask sent to an H2ONode retries on
a bounded exponential backoff until acked, and the receiving node dedups
re-sent tasks so a retried call never runs its side effects twice.  Both
halves are reproduced here:

* the client ladder: per-call timeout, ``retries`` attempts with
  exponential backoff (base doubling, capped), connection pooling, and a
  typed error surface (:class:`RPCTimeoutError` / :class:`RPCConnectionError`
  / :class:`RemoteError`);
* the server dedup: every logical call carries an idempotency token; the
  server memoizes ``token -> response`` (and parks duplicate deliveries of
  an in-flight token on the first execution), so a retry caused by a lost
  response frame returns the original result instead of re-running the
  method.

Every call is metered: ``rpc_calls_total{target,method,result}``,
``rpc_retries_total``, ``rpc_call_seconds{method,side}`` (observed on BOTH
sides — the client's round trip including retries, and the server's pure
dispatch wall), with ``rpc_inflight{side}`` tracking calls currently in
flight so a wedged member shows up in ``/3/Metrics`` before the heartbeat
suspicion window fires.

Tracing: when the caller holds an open :class:`~h2o3_tpu.util.telemetry.Span`,
``call`` wraps the ladder in an ``rpc_client`` span and injects trace context
into the request envelope; the server opens an ``rpc_server`` child span
around method dispatch under the serving node's identity.  One ``trace_id``
therefore threads caller → wire → remote execution.  When the ladder
actually RETRIES, every attempt becomes a visible sibling ``rpc_attempt``
span under the ``rpc_client`` (the failed first attempt is materialized
retroactively at retry time) — the single-attempt common case pays for two
spans, not three, keeping traced-call overhead within the documented bench
budget.  Untraced calls (heartbeats) add no envelope bytes and no spans.

Wire format: one pickled dict per frame.  Pickle is the AutoBuffer
analogue — nodes of one cloud run one codebase inside one trust boundary
(the reference ships compiled DTask classes over the same wire); the REST
surface, not this port, is the untrusted boundary.
"""

from __future__ import annotations

import pickle
import socket
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from h2o3_tpu.cluster import faults as _faults
from h2o3_tpu.cluster import transport
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

_RPC_CALLS = telemetry.counter(
    "rpc_calls_total", "node RPC calls by outcome",
    labels=("target", "method", "result"),
)
_RPC_RETRIES = telemetry.counter(
    "rpc_retries_total", "RPC attempts re-sent by the backoff ladder"
)
_RPC_SECONDS = telemetry.histogram(
    "rpc_call_seconds",
    "RPC wall seconds: side=client is the round trip incl. retries, "
    "side=server the pure method dispatch",
    labels=("method", "side"),
)
_RPC_SERVED = telemetry.counter(
    "rpc_served_total", "RPC requests served by the local node",
    labels=("method", "result"),
)
_RPC_PAYLOAD_BYTES = telemetry.counter(
    "rpc_payload_bytes_total",
    "encoded RPC envelope bytes this node's client moved, by direction "
    "(sent = requests out, received = responses in) and method — the "
    "wire meter that proves a chunk-homed map_reduce ships partials, "
    "not chunks, with control-plane vs data-plane traffic separated "
    "on the method label",
    labels=("direction", "method"),
)
_RPC_INFLIGHT = telemetry.gauge(
    "rpc_inflight",
    "RPC calls currently in flight (client: awaiting a response; server: "
    "executing) — a wedged member pins this above zero before the "
    "heartbeat suspicion window fires",
    labels=("side",),
)
#: bound series handles: these tick on EVERY call/dispatch, so the label
#: resolution happens once here, not per event
_INFLIGHT_CLIENT = _RPC_INFLIGHT.bind(side="client")
_INFLIGHT_SERVER = _RPC_INFLIGHT.bind(side="server")

#: (method, side) -> bound histogram series; RPC method names are a small
#: closed set per process, so the cache is tiny and the per-call observe
#: drops to a dict hit + locked update
_seconds_bound: Dict[Tuple[str, str], telemetry._Bound] = {}

#: (direction, method) -> bound byte-meter series — same closed-set cache
#: pattern as ``_seconds_bound``, so the per-attempt tick stays a dict hit
_payload_bound: Dict[Tuple[str, str], telemetry._Bound] = {}

#: wire direction -> cost-ledger category
_LEDGER_BYTES_CAT = {"sent": _ledger.RPC_SENT_BYTES,
                     "received": _ledger.RPC_RECV_BYTES}

#: in-flight CLIENT call table: the ``rpc_stuck`` watchdog rule reads
#: :func:`inflight_snapshot` to find calls aged past N x their ladder
#: budget — the gauge says HOW MANY are stuck, this says WHICH.  The
#: lock is a leaf (pure dict work, ~1us per call round trip).
_calls_lock = threading.Lock()
_calls_inflight: Dict[int, Dict[str, Any]] = {}
_calls_next = 0


def _call_begin(method: str, target: str, timeout: float,
                budget_s: float) -> int:
    global _calls_next
    entry = {"method": method, "target": target, "attempt": 0,
             "timeout_s": float(timeout), "budget_s": float(budget_s),
             "t0": time.monotonic()}
    with _calls_lock:
        _calls_next += 1
        cid = _calls_next
        _calls_inflight[cid] = entry
    return cid


def _call_attempt(cid: int, attempt: int) -> None:
    with _calls_lock:
        e = _calls_inflight.get(cid)
        if e is not None:
            e["attempt"] = attempt


def _call_end(cid: int) -> None:
    with _calls_lock:
        _calls_inflight.pop(cid, None)


def inflight_snapshot() -> list:
    """JSON-able view of every client call currently in flight, each with
    its ``age_s`` against the full ladder ``budget_s``."""
    now = time.monotonic()
    with _calls_lock:
        entries = [dict(e) for e in _calls_inflight.values()]
    for e in entries:
        e["age_s"] = round(now - e.pop("t0"), 3)
    return entries


def _charge_bytes(direction: str, method: str, n: int) -> None:
    """Meter one attempt's wire bytes AND bill them to the open trace.

    During ``_attempt`` the CALLER's span is still current on this thread
    (the rpc_client wrapper is a recorded event, not a pushed span), so
    the ledger charge lands on the originating trace; untraced calls
    (heartbeats) tick the meter and charge nothing."""
    b = _payload_bound.get((direction, method))
    if b is None:
        b = _payload_bound[(direction, method)] = _RPC_PAYLOAD_BYTES.bind(
            direction=direction, method=method)
    b.inc(n)
    _ledger.charge(_LEDGER_BYTES_CAT[direction], n)


def _observe_seconds(method: str, side: str, v: float) -> None:
    b = _seconds_bound.get((method, side))
    if b is None:
        b = _seconds_bound[(method, side)] = _RPC_SECONDS.bind(
            method=method, side=side)
    b.observe(v)


class RPCError(Exception):
    """Base of every typed RPC failure."""


class RPCTimeoutError(RPCError):
    """The call's per-attempt timeout expired on every attempt."""


class RPCConnectionError(RPCError):
    """No attempt could reach (or keep) a connection to the target."""


class RemoteError(RPCError):
    """The remote method raised; carries the remote type and an HTTP-ish
    status code so control-plane callers (cloud join, REST proxies) can
    answer 4xx instead of opaque 500s."""

    def __init__(self, remote_type: str, msg: str, code: int = 500,
                 detail: Optional[dict] = None) -> None:
        super().__init__(f"{remote_type}: {msg}")
        self.remote_type = remote_type
        self.msg = msg
        self.code = code
        self.detail = detail or {}


class RpcFault(Exception):
    """Raise from a method handler to send a typed, coded error to the
    caller (surfaces there as :class:`RemoteError` with the same code)."""

    def __init__(self, msg: str, code: int = 400,
                 detail: Optional[dict] = None) -> None:
        super().__init__(msg)
        self.code = code
        self.detail = detail or {}


def _encode(obj: Any) -> bytes:
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


class RpcClient:
    """Pooled caller with the bounded exponential-backoff retry ladder."""

    def __init__(
        self,
        dialer: Callable[[transport.Address, float], transport.Connection] = transport.dial,
        retries: int = 3,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        node_name: str = "",
    ) -> None:
        self.pool = transport.ConnectionPool(dialer)
        self.retries = int(retries)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        #: this client's cluster identity — recorded as the trace origin in
        #: every injected envelope so remote spans name their caller
        self.node_name = node_name

    def call(
        self,
        addr: transport.Address,
        method: str,
        payload: Any = None,
        timeout: float = 5.0,
        target: str = "",
        retries: Optional[int] = None,
    ) -> Any:
        """One logical call: up to ``1 + retries`` attempts, every retry
        re-sending the SAME idempotency token so the server side never
        double-executes (water/RPC.java's resend discipline).

        ``timeout`` is PER ATTEMPT: worst-case blocking against a
        black-holed peer is ``(1 + retries) * timeout`` plus backoff.
        Deadline-sensitive callers (heartbeat loops, REST proxies) pass
        ``retries=`` to shrink or disable the ladder for that call.

        When the calling thread holds an open Span, the call joins its
        trace: an ``rpc_client`` span covers the ladder, trace context rides
        the request envelope to parent the remote ``rpc_server`` span, and
        a retried call materializes each attempt as a sibling
        ``rpc_attempt`` child.
        """
        target = target or f"{addr[0]}:{addr[1]}"
        caller = telemetry.current_span()
        if caller is None:
            return self._call(addr, method, payload, timeout, target,
                              retries, None, "")
        # lightweight client span: a minted id + ONE recorded event, no
        # thread-local stack traffic — nothing nests under it on this
        # thread (the remote dispatch parents via the envelope ids), so
        # the full Span machinery would buy nothing but overhead on the
        # hot path the bench budget governs
        from h2o3_tpu.util import timeline

        span_id = telemetry._new_id()
        node = self.node_name or telemetry.node_name() or ""
        t0 = time.perf_counter()
        ok = False
        try:
            out = self._call(addr, method, payload, timeout, target,
                             retries, (caller.trace_id, span_id), node)
            ok = True
            return out
        finally:
            evt = {
                "kind": "rpc_client",
                "duration_ms": round((time.perf_counter() - t0) * 1e3, 3),
                "ok": ok,
                "trace_id": caller.trace_id,
                "span_id": span_id,
                "parent_id": caller.span_id,
                "method": method,
                "target": target,
            }
            if node:
                evt["node"] = node
            timeline.record_event(evt)

    def _call(
        self,
        addr: transport.Address,
        method: str,
        payload: Any,
        timeout: float,
        target: str,
        retries: Optional[int],
        trace_ctx: Optional[Tuple[str, str]],
        origin: str,
    ) -> Any:
        token = uuid.uuid4().hex
        ladder = self.retries if retries is None else max(0, int(retries))
        request: Optional[bytes] = None
        if trace_ctx is None:  # untraced envelope is attempt-invariant
            request = _encode(
                {"id": token, "method": method, "payload": payload}
            )

        def _record_attempt(span_id: str, t_a: float, ok: bool,
                            attempt: int) -> None:
            from h2o3_tpu.util import timeline

            evt = {
                "kind": "rpc_attempt",
                "duration_ms": round((time.perf_counter() - t_a) * 1e3, 3),
                "ok": ok,
                "trace_id": trace_ctx[0],
                "span_id": span_id,
                "parent_id": trace_ctx[1],
                "method": method, "target": target, "attempt": attempt,
            }
            if origin:
                evt["node"] = origin
            timeline.record_event(evt)

        def _one_attempt(attempt: int) -> bytes:
            if trace_ctx is None:
                return self._attempt(addr, request, timeout, method)
            if attempt == 0:
                # common case: the envelope carries the rpc_client ids (no
                # per-attempt span — one span per side keeps traced
                # overhead inside the bench budget); if this attempt fails
                # and a retry follows, it is materialized as a sibling
                # rpc_attempt retroactively below
                req = _encode({
                    "id": token, "method": method, "payload": payload,
                    "trace": {"trace_id": trace_ctx[0],
                              "span_id": trace_ctx[1],
                              "origin": origin, "attempt": 0},
                })
                t_a = time.perf_counter()
                try:
                    return self._attempt(addr, req, timeout, method)
                except Exception:
                    if ladder:  # a retry will follow: show attempt 0
                        _record_attempt(telemetry._new_id(), t_a, False, 0)
                    raise
            # a real retry: every subsequent attempt is its own sibling
            # and the envelope carries THAT attempt's ids, so a remote
            # dispatch parents under the attempt that reached it
            attempt_id = telemetry._new_id()
            req = _encode({
                "id": token, "method": method, "payload": payload,
                "trace": {"trace_id": trace_ctx[0], "span_id": attempt_id,
                          "origin": origin, "attempt": attempt},
            })
            t_a = time.perf_counter()
            try:
                raw = self._attempt(addr, req, timeout, method)
            except Exception:
                _record_attempt(attempt_id, t_a, False, attempt)
                raise
            _record_attempt(attempt_id, t_a, True, attempt)
            return raw

        t0 = time.perf_counter()
        last_exc: Optional[BaseException] = None
        timed_out = False
        plan = _faults.active_plan()
        _INFLIGHT_CLIENT.inc()
        cid = _call_begin(method, target, timeout,
                          (ladder + 1) * timeout)
        try:
            for attempt in range(ladder + 1):
                if attempt:
                    _RPC_RETRIES.inc()
                    _call_attempt(cid, attempt)
                    # every rung of the ladder is a flight event: after a
                    # wedge, the recorder holds the full attempt trail
                    _flight.record(
                        _flight.RPC, "warn", "retry",
                        trace_id=trace_ctx[0] if trace_ctx else None,
                        method=method, target=target, attempt=attempt)
                    # FULL-jitter backoff, U(0, min(cap, base*2^(a-1))):
                    # N callers retrying against one recovering member
                    # spread out instead of re-converging into a
                    # thundering herd each doubling; under an active
                    # fault plan the draw comes from its seeded PRNG so
                    # chaos runs replay their retry spacing
                    time.sleep(_faults.backoff_rng().uniform(0.0, min(
                        self.backoff_base * (2 ** (attempt - 1)),
                        self.backoff_max,
                    )))
                fd = None if plan is None else plan.consult(
                    "client", self.node_name, target, method)
                try:
                    if fd is not None:
                        if fd.action in ("drop", "partition"):
                            raise ConnectionError(
                                f"fault-injected {fd.action}: "
                                f"{method} -> {target}")
                        if fd.action == "black_hole":
                            # models a frame-swallowing peer without
                            # consuming the attempt's real wall clock
                            raise socket.timeout(
                                f"fault-injected black_hole: "
                                f"{method} -> {target}")
                        if fd.action == "crash":
                            _faults.crash_now()
                        if fd.delay_s > 0.0:
                            time.sleep(fd.delay_s)
                    raw = _one_attempt(attempt)
                    if fd is not None and fd.action == "duplicate":
                        # re-send the SAME envelope (same token): the
                        # server's dedup memo must absorb it
                        try:
                            _one_attempt(attempt)
                        except (socket.timeout, ConnectionError, OSError):
                            pass
                except socket.timeout as e:
                    timed_out = True
                    last_exc = e
                    continue
                except (ConnectionError, OSError) as e:
                    last_exc = e
                    continue
                resp = pickle.loads(raw)
                if resp.get("ok"):
                    _RPC_CALLS.inc(target=target, method=method, result="ok")
                    if method != "heartbeat":  # gossip stays ring-free
                        _flight.record(
                            _flight.RPC, "info", "call",
                            trace_id=trace_ctx[0] if trace_ctx else None,
                            method=method, target=target,
                            ms=round((time.perf_counter() - t0) * 1e3, 3))
                    return resp.get("value")
                err = resp.get("error") or {}
                _RPC_CALLS.inc(
                    target=target, method=method, result="remote_error")
                _flight.record(
                    _flight.RPC, "error", "remote_error",
                    trace_id=trace_ctx[0] if trace_ctx else None,
                    method=method, target=target,
                    type=err.get("type", "Exception"),
                    code=int(err.get("code", 500)))
                raise RemoteError(
                    err.get("type", "Exception"),
                    err.get("msg", "remote call failed"),
                    int(err.get("code", 500)),
                    err.get("detail"),
                )
            result = "timeout" if timed_out else "connect_error"
            _RPC_CALLS.inc(target=target, method=method, result=result)
            _flight.record(
                _flight.RPC, "error", result,
                trace_id=trace_ctx[0] if trace_ctx else None,
                method=method, target=target, attempts=ladder + 1,
                timeout_s=timeout)
            if timed_out:
                raise RPCTimeoutError(
                    f"{method} to {target} timed out after "
                    f"{ladder + 1} attempts of {timeout}s"
                ) from last_exc
            raise RPCConnectionError(
                f"{method} to {target} unreachable after "
                f"{ladder + 1} attempts: {last_exc}"
            ) from last_exc
        finally:
            _call_end(cid)
            _INFLIGHT_CLIENT.dec()
            _observe_seconds(method, "client", time.perf_counter() - t0)

    def _attempt(self, addr: transport.Address, request: bytes,
                 timeout: float, method: str) -> bytes:
        """One ladder attempt.  Every idle pooled socket to a restarted
        peer is stale at once (pool max_idle == ladder depth), so a
        pooled connection that fails is closed and the next tried WITHIN
        the attempt — only a fresh dial's failure, or any timeout,
        charges the retry ladder."""
        _charge_bytes("sent", method, len(request))
        while True:
            conn = self.pool.pop_idle(addr)
            if conn is None:
                break
            try:
                raw = conn.request(request, timeout)
            except socket.timeout:
                conn.close()  # live but slow: the ladder's problem
                raise
            except (ConnectionError, OSError):
                conn.close()  # stale pooled socket: try the next
                continue
            self.pool.put(conn)
            _charge_bytes("received", method, len(raw))
            return raw
        conn = self.pool.dial(addr, timeout)
        try:
            raw = conn.request(request, timeout)
        except BaseException:
            conn.close()  # response may still arrive: poisoned
            raise
        self.pool.put(conn)
        _charge_bytes("received", method, len(raw))
        return raw

    def close(self) -> None:
        self.pool.close_all()


class RpcServer:
    """Method registry + idempotent dispatch over a TransportServer."""

    #: responses remembered per idempotency token — deep enough that a
    #: retry ladder (seconds) can never outlive the memo (thousands of
    #: calls) under any realistic call rate
    DEDUP_CAPACITY = 4096
    #: byte budget across memoized responses: big payloads (DKV frames,
    #: echo benches) must not pin hundreds of MB of dead responses —
    #: oldest entries evict first once the budget is exceeded
    DEDUP_BYTE_BUDGET = 64 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 node_name: str = "") -> None:
        self._methods: Dict[str, Callable[[Any], Any]] = {}
        #: serving identity: dispatches run under a thread-local node scope
        #: so events recorded during remote execution name THIS node even
        #: with several in-process Clouds (the test harness)
        self.node_name = node_name
        self._lock = threading.Lock()
        #: token -> (done_event, encoded_response|None): duplicates of an
        #: in-flight token wait on the first execution instead of racing it
        self._seen: "OrderedDict[str, Tuple[threading.Event, Optional[bytes]]]" = OrderedDict()
        self._seen_bytes = 0
        self._server = transport.TransportServer(
            self._handle, host=host, port=port)
        self.address = self._server.address

    def register(self, method: str, fn: Callable[[Any], Any]) -> None:
        self._methods[method] = fn

    def _execute(self, method: str, payload: Any,
                 trace: Optional[Dict[str, Any]] = None) -> bytes:
        if trace and trace.get("trace_id"):
            # the caller's envelope context parents this dispatch: one
            # trace now threads caller -> wire -> remote execution, and
            # anything fn records (nested spans, log lines) inherits it.
            # The serving node's identity scopes the dispatch so those
            # events attribute to THIS node even with several in-process
            # Clouds (untraced calls skip both — heartbeats stay free).
            sp = telemetry.Span(
                "rpc_server",
                trace_id=str(trace["trace_id"]),
                parent_id=trace.get("span_id"),
                method=method,
                origin=trace.get("origin", ""),
                attempt=int(trace.get("attempt", 0)),
            )
            if self.node_name:
                with telemetry.node_scope(self.node_name), sp:
                    return self._dispatch(method, payload, sp)
            with sp:
                return self._dispatch(method, payload, sp)
        return self._dispatch(method, payload, None)

    def _dispatch(self, method: str, payload: Any,
                  sp: Optional["telemetry.Span"]) -> bytes:
        fn = self._methods.get(method)
        t0 = time.perf_counter()
        try:
            if fn is None:
                raise RpcFault(f"unknown RPC method {method!r}", code=404)
            value = fn(payload)
            _RPC_SERVED.inc(method=method, result="ok")
            if sp is not None:
                sp.set(result="ok")
            return _encode({"ok": True, "value": value})
        except RpcFault as e:
            _RPC_SERVED.inc(method=method, result="fault")
            if sp is not None:
                sp.set(result="fault")
            _flight.record(_flight.RPC, "warn", "dispatch_fault",
                           method=method, code=e.code)
            return _encode({"ok": False, "error": {
                "type": "RpcFault", "msg": str(e), "code": e.code,
                "detail": e.detail,
            }})
        except Exception as e:  # noqa: BLE001 — ships to the caller typed
            _RPC_SERVED.inc(method=method, result="error")
            if sp is not None:
                sp.set(result="error")
            _flight.record(_flight.RPC, "error", "dispatch_error",
                           method=method, type=type(e).__name__)
            return _encode({"ok": False, "error": {
                "type": type(e).__name__, "msg": str(e), "code": 500,
            }})
        finally:
            _observe_seconds(method, "server",
                             time.perf_counter() - t0)

    def _evict_memo_locked(self) -> None:
        """Oldest-first memo eviction that never drops an IN-FLIGHT
        token: evicting one would re-execute a retried mutation (if its
        first run later completed) or 409 a parked duplicate of a call
        that actually succeeded.  In-flight entries hold no response
        bytes, so the byte budget is enforceable without them; capacity
        may transiently exceed by the number of concurrent calls.

        The scan stops at the first evictable entry per round: once the
        memo sits at capacity (steady state under sustained load), each
        call evicts exactly one completed token from the front — O(1)
        unless the oldest entries are all in flight, never an O(capacity)
        list build per call."""
        while (len(self._seen) > self.DEDUP_CAPACITY
               or (self._seen_bytes > self.DEDUP_BYTE_BUDGET
                   and len(self._seen) > 1)):
            victim = None
            for tok, (_ev, resp) in self._seen.items():  # oldest first
                if resp is not None:
                    victim = tok
                    break
            if victim is None:
                return  # every old entry is in flight: protected
            self._seen_bytes -= len(self._seen.pop(victim)[1])

    def _handle(self, raw: bytes) -> Optional[bytes]:
        try:
            req = pickle.loads(raw)
            token = req["id"]
            method = req["method"]
        except Exception as e:  # undecodable frame: typed error, no memo
            return _encode({"ok": False, "error": {
                "type": type(e).__name__, "msg": f"bad request frame: {e}",
                "code": 400,
            }})
        plan = _faults.active_plan()
        fd = None if plan is None else plan.consult(
            "server", self.node_name, "", method)
        if fd is not None:
            if fd.action == "crash":
                _faults.crash_now()
            if fd.delay_s > 0.0:
                time.sleep(fd.delay_s)
        _INFLIGHT_SERVER.inc()
        try:
            with self._lock:
                entry = self._seen.get(token)
                if entry is None:
                    event = threading.Event()
                    self._seen[token] = (event, None)
                    self._evict_memo_locked()
                else:
                    event = entry[0]
            if entry is not None:
                # duplicate delivery (retry after a lost response): wait for
                # the original execution, return its memoized response
                event.wait(timeout=300)
                with self._lock:
                    memo = self._seen.get(token)
                if memo is not None and memo[1] is not None:
                    return memo[1]
                return _encode({"ok": False, "error": {
                    "type": "RpcFault", "code": 409,
                    "msg": "duplicate of a call that never completed",
                }})
            response = self._execute(
                method, req.get("payload"), req.get("trace"))
            with self._lock:
                if token in self._seen:
                    self._seen[token] = (event, response)
                    self._seen_bytes += len(response)
            event.set()
            if fd is not None and fd.action in ("drop", "black_hole"):
                # server-side drop is a LOST RESPONSE: the method ran and
                # its result is memoized; returning None makes the
                # transport close the connection unreplied, so the
                # caller's retry must come back through the dedup memo
                return None
            return response
        finally:
            _INFLIGHT_SERVER.dec()

    def stop(self) -> None:
        self._server.stop()
