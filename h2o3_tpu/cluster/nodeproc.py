"""Minimal cluster node process — membership + RPC + DKV + DTask, no REST.

``python -m h2o3_tpu.cluster.nodeproc --cluster-name c --node-name n1
--address-file /tmp/n1.addr [--flatfile peers.txt]`` boots the
application-plane node the multi-process tests and ``bench.py
--cluster-bench`` peer against: it binds port 0, writes the resolved
``host:port`` to the address file (the rendezvous the harness folds into
the other nodes' flatfiles), joins the cloud, and serves until its stdin
closes or it is signalled — the harness owns its lifetime.

The full launcher (``python -m h2o3_tpu --flatfile ...``) layers the
REST server and JAX runtime on the same bootstrap; this entry exists so
cluster tests and benches pay milliseconds, not a backend init, per node.
"""

from __future__ import annotations

import argparse
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="python -m h2o3_tpu.cluster.nodeproc")
    p.add_argument("--cluster-name", required=True)
    p.add_argument("--node-name", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="RPC port (0 = OS-assigned)")
    p.add_argument("--flatfile", default=None,
                   help="host:port peer list (one per line)")
    p.add_argument("--address-file", default=None,
                   help="write the resolved host:port here after bind")
    p.add_argument("--hb-interval", type=float, default=None)
    p.add_argument("--client", action="store_true",
                   help="join as a client node (holds no keys)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from h2o3_tpu.cluster.membership import CloudJoinError, boot_node

    try:
        cloud = boot_node(
            args.cluster_name,
            args.node_name,
            host=args.host,
            port=args.port,
            client=args.client,
            hb_interval=args.hb_interval,
            flatfile=args.flatfile,
            address_file=args.address_file,
        )
    except CloudJoinError as e:
        print(f"cluster join failed ({e.code}): {e}", file=sys.stderr)
        return 2
    print(f"node {cloud.info.ident} up in cloud "
          f"'{args.cluster_name}'", flush=True)

    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    # serve until the harness closes stdin or signals; a dead parent must
    # never leave an orphan listener behind (polling select so a signal
    # is noticed within half a second, not only at the next stdin byte)
    import select

    while not stop["flag"]:
        ready, _, _ = select.select([sys.stdin], [], [], 0.5)
        if ready and not sys.stdin.readline():
            break
    cloud.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
