"""ctypes bindings for the native (C++) runtime components.

Reference mapping (SURVEY.md §2.3: native components get TPU-native
equivalents, and the runtime around the JAX compute path is native):

  * ``native/csv.cpp``    — the parser hot loop (water/parser/CsvParser.java
    byte scanning, chunk-parallel like MultiFileParseTask)
  * ``native/codecs.cpp`` — chunk compression codecs (water/fvec/C*Chunk)
    + LSD radix argsort (water/rapids/RadixOrder.java analogue)

Everything here degrades gracefully: if the shared library cannot be built
(no compiler) or H2O3_TPU_NATIVE=0, callers use the numpy fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libh2o3native.so"))

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        out = subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
            capture_output=True, text=True, timeout=120,
        )
        return out.returncode == 0 and os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _stale() -> bool:
    """True when a source file is newer than the built library (the .so
    would lack symbols added since it was compiled)."""
    try:
        lib_m = os.path.getmtime(_LIB_PATH)
        return any(
            os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_m
            for f in ("csv.cpp", "codecs.cpp")
        )
    except OSError:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if os.environ.get("H2O3_TPU_NATIVE", "1") == "0":
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        if _stale() and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.h2o3_count_rows.restype = ctypes.c_int64
        lib.h2o3_count_rows.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.h2o3_parse_numeric_csv.restype = ctypes.c_int64
        lib.h2o3_parse_numeric_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_char,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.c_int32,
        ]
        _i32p = ctypes.POINTER(ctypes.c_int32)
        _u8p = ctypes.POINTER(ctypes.c_uint8)
        _f64p = ctypes.POINTER(ctypes.c_double)
        lib.h2o3_csv_index_chunk.restype = ctypes.c_int64
        lib.h2o3_csv_index_chunk.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int32,
            ctypes.c_int32, _i32p, _i32p, ctypes.c_int64,
        ]
        lib.h2o3_parse_cells_f64.restype = None
        lib.h2o3_parse_cells_f64.argtypes = [
            ctypes.c_char_p, _i32p, _i32p, ctypes.c_int64, _f64p,
        ]
        lib.h2o3_parse_cells_time.restype = ctypes.c_int64
        lib.h2o3_parse_cells_time.argtypes = [
            ctypes.c_char_p, _i32p, _i32p, ctypes.c_int64, _f64p, _u8p,
        ]
        lib.h2o3_dict_encode_cells.restype = ctypes.c_int64
        lib.h2o3_dict_encode_cells.argtypes = [
            ctypes.c_char_p, _i32p, _i32p, ctypes.c_int64,
            ctypes.c_char_p, _i32p, _i32p, ctypes.c_int32,
            _i32p, _i32p, _i32p,
        ]
        lib.h2o3_gather_cells.restype = ctypes.c_int64
        lib.h2o3_gather_cells.argtypes = [
            ctypes.c_char_p, _i32p, _i32p, ctypes.c_int64,
            ctypes.c_char_p, _i32p, _i32p, ctypes.c_int32,
            ctypes.c_char_p, _u8p,
        ]
        lib.h2o3_codec_bound.restype = ctypes.c_int64
        lib.h2o3_codec_bound.argtypes = [ctypes.c_int64]
        lib.h2o3_codec_encode.restype = ctypes.c_int64
        lib.h2o3_codec_encode.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.h2o3_codec_decode.restype = ctypes.c_int64
        lib.h2o3_codec_decode.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_double),
        ]
        lib.h2o3_radix_argsort_u64.restype = None
        lib.h2o3_radix_argsort_u64.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
# csv


def parse_numeric_csv(
    text: bytes, start: int, sep: str, ncols: int, nrows: int,
    nthreads: int = 0,
) -> Optional[np.ndarray]:
    """All-numeric CSV body -> [nrows, ncols] float64 (NaN = NA/junk).
    Returns None when the native lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if nthreads <= 0:
        nthreads = min(os.cpu_count() or 1, 8)
    out = np.empty((nrows, ncols), dtype=np.float64)
    got = lib.h2o3_parse_numeric_csv(
        text, len(text), start, sep.encode()[:1], ncols,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), nrows, nthreads,
    )
    if got < 0 or got > nrows:
        return None
    return out[:got]


# ---------------------------------------------------------------------------
# chunk-parallel two-phase parse primitives (frame/parse.py workers)
#
# Every wrapper is one ctypes call over one body chunk; ctypes drops the
# GIL for the call's duration, which is what lets the ThreadPoolExecutor
# in frame/parse.py tokenize chunks genuinely concurrently.


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def csv_index_chunk(
    chunk: bytes, sep: str, ncols: int, skip_blanks: bool
) -> Optional[tuple]:
    """Tokenize one body chunk -> ([n, ncols] cell starts, ends) offset
    grids (whitespace-stripped; blank records skipped). None if the lib is
    unavailable or the preallocation was insufficient."""
    lib = get_lib()
    if lib is None:
        return None
    cap = chunk.count(b"\n") + 1
    starts = np.empty(cap * ncols, dtype=np.int32)
    ends = np.empty(cap * ncols, dtype=np.int32)
    n = lib.h2o3_csv_index_chunk(
        chunk, len(chunk), sep.encode()[:1], ncols,
        1 if skip_blanks else 0, _i32(starts), _i32(ends), cap,
    )
    if n < 0:
        return None
    return (
        starts[: n * ncols].reshape(n, ncols),
        ends[: n * ncols].reshape(n, ncols),
    )


def parse_cells_f64(
    chunk: bytes, starts: np.ndarray, ends: np.ndarray
) -> Optional[np.ndarray]:
    """One column's cells -> float64 (NaN for NA/junk)."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(starts)
    out = np.empty(n, dtype=np.float64)
    lib.h2o3_parse_cells_f64(chunk, _i32(starts), _i32(ends), n, _f64(out))
    return out


def parse_cells_time(
    chunk: bytes, starts: np.ndarray, ends: np.ndarray
) -> Optional[tuple]:
    """One column's cells -> epoch-ms float64 for strictly canonical time
    tokens, plus a uint8 flag array marking cells the caller must re-parse
    in python (NA tokens / nonstandard formats)."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(starts)
    out = np.empty(n, dtype=np.float64)
    flags = np.empty(n, dtype=np.uint8)
    lib.h2o3_parse_cells_time(
        chunk, _i32(starts), _i32(ends), n, _f64(out), _u8(flags)
    )
    return out, flags


def dict_encode_cells(
    chunk: bytes, starts: np.ndarray, ends: np.ndarray,
    na_blob: bytes, na_starts: np.ndarray, na_ends: np.ndarray,
) -> Optional[tuple]:
    """One column's cells -> (int32 codes, uniq_starts, uniq_ends): the
    local categorical dictionary in first-appearance order as offsets into
    the chunk; NA cells get code -1."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(starts)
    codes = np.empty(n, dtype=np.int32)
    ust = np.empty(n, dtype=np.int32)
    uen = np.empty(n, dtype=np.int32)
    nu = lib.h2o3_dict_encode_cells(
        chunk, _i32(starts), _i32(ends), n,
        na_blob, _i32(na_starts), _i32(na_ends), len(na_starts),
        _i32(codes), _i32(ust), _i32(uen),
    )
    return codes, ust[:nu], uen[:nu]


def gather_cells(
    chunk: bytes, starts: np.ndarray, ends: np.ndarray,
    na_blob: bytes, na_starts: np.ndarray, na_ends: np.ndarray,
) -> Optional[tuple]:
    """One column's cells -> (newline-joined bytes, uint8 NA mask), for a
    single bulk decode+split on the python side."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(starts)
    total = int((ends.astype(np.int64) - starts).sum()) + n
    out = ctypes.create_string_buffer(max(total, 1))
    mask = np.empty(n, dtype=np.uint8)
    got = lib.h2o3_gather_cells(
        chunk, _i32(starts), _i32(ends), n,
        na_blob, _i32(na_starts), _i32(na_ends), len(na_starts),
        out, _u8(mask),
    )
    return out.raw[:got], mask


# ---------------------------------------------------------------------------
# chunk codecs (compressed column store)


def codec_encode(x: np.ndarray) -> Optional[bytes]:
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    buf = np.empty(int(lib.h2o3_codec_bound(len(x))), dtype=np.uint8)
    n = lib.h2o3_codec_encode(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), len(x),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return bytes(buf[:n])


def codec_decode(blob: bytes) -> Optional[np.ndarray]:
    lib = get_lib()
    if lib is None:
        return None
    n = int.from_bytes(blob[1:9], "little")
    out = np.empty(n, dtype=np.float64)
    raw = np.frombuffer(blob, dtype=np.uint8)
    got = lib.h2o3_codec_decode(
        raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if got != n:
        return None
    return out


# ---------------------------------------------------------------------------
# radix argsort


def radix_argsort(keys: np.ndarray) -> Optional[np.ndarray]:
    """Stable LSD-radix argsort for int64/uint64/float64 keys (NaN last)."""
    lib = get_lib()
    if lib is None:
        return None
    k = np.asarray(keys)
    if k.dtype == np.float64:
        # order-preserving float->uint64 transform (flip sign bit / negate);
        # canonicalize NaNs (negative-sign NaNs must also sort last) and
        # -0.0 -> +0.0 (numpy treats them as equal ties; the bit transform
        # would otherwise order them)
        k = np.where(np.isnan(k), np.nan, k + 0.0)
        bits = k.view(np.uint64).copy()
        neg = bits >> np.uint64(63) == 1
        bits[neg] = ~bits[neg]
        bits[~neg] |= np.uint64(1) << np.uint64(63)
        # NaNs (exponent all-ones, mantissa != 0) end up above +inf: fine
        u = bits
    elif k.dtype == np.int64:
        u = (k.astype(np.int64) ^ np.int64(-0x8000000000000000)).view(np.uint64)
    elif k.dtype == np.uint64:
        u = k
    else:
        return None
    u = np.ascontiguousarray(u)
    order = np.empty(len(u), dtype=np.int64)
    lib.h2o3_radix_argsort_u64(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(u),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    return order
