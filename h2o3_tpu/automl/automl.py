"""The AutoML driver: steps, budget, leaderboard, event log.

Reference call shape: ``H2OAutoML(max_models=…, max_runtime_secs=…,
seed=…).train(y=…, training_frame=…)`` then ``aml.leaderboard`` /
``aml.leader``.  The default modeling plan mirrors the reference's step
sequence (AutoML.java defaultModelingPlan: XGBoost defaults, GLM, DRF,
GBM defaults, DeepLearning, random grids, StackedEnsembles best-of-family
and all — ``modeling/*StepsProvider``); every model is trained with
k-fold CV and the leaderboard ranks by the CV metric, exactly the
reference's leaderboard semantics (``leaderboard/Leaderboard.java``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models.framework import Model, ModelParameters
from h2o3_tpu.models.grid import metric_value


class EventLog:
    """events/EventLog.java — timestamped orchestration trace."""

    def __init__(self) -> None:
        self.events: List[Dict[str, Any]] = []

    def log(self, stage: str, message: str) -> None:
        self.events.append(
            {"timestamp": time.time(), "stage": stage, "message": message}
        )

    def __repr__(self) -> str:
        return f"<EventLog {len(self.events)} events>"


class Leaderboard:
    """leaderboard/Leaderboard.java — models ranked by the sort metric."""

    def __init__(self, sort_metric: str = "auto") -> None:
        self.sort_metric = sort_metric
        self.models: List[Model] = []

    def add(self, model: Model) -> None:
        self.models.append(model)
        self._sort()

    def _sort(self) -> None:
        vals = [metric_value(m, self.sort_metric) for m in self.models]
        larger = vals[0][1] if vals else True
        order = np.argsort([v for v, _ in vals])
        if larger:
            order = order[::-1]
        order = sorted(order, key=lambda i: np.isnan(vals[i][0]))
        self.models = [self.models[i] for i in order]

    @property
    def leader(self) -> Optional[Model]:
        return self.models[0] if self.models else None

    def as_table(self) -> List[Dict[str, Any]]:
        out = []
        for m in self.models:
            v, _ = metric_value(m, self.sort_metric)
            out.append({"model_id": m.key, "algo": m.algo_name, "metric": v})
        return out

    def __repr__(self) -> str:
        rows = "\n".join(
            f"  {r['model_id']}  {r['algo']}  {r['metric']:.5f}"
            for r in self.as_table()[:10]
        )
        return f"<Leaderboard ({self.sort_metric})>\n{rows}"


@dataclass
class _Step:
    """StepDefinition/ModelingStep — one budgeted training unit."""

    id: str
    weight: int  # work allocation units (WorkAllocations.java)
    build: Callable[["AutoML", Frame], List[Model]]
    #: (builder_cls, params_cls, extra-params dict) for steps that are a
    #: single fully-determined model build — the shape the distributed
    #: search plane (cluster/search.py) can fan across cluster members.
    #: None for steps with sequential dependencies (grids read the
    #: budget, exploitation/ensembles read the leaderboard).
    spec: Optional[Any] = None


class AutoML:
    """The orchestrator (AutoML.java:40)."""

    def __init__(
        self,
        max_models: int = 10,
        max_runtime_secs: float = 0.0,
        seed: int = -1,
        nfolds: int = 5,
        sort_metric: str = "auto",
        include_algos: Optional[Sequence[str]] = None,
        exclude_algos: Optional[Sequence[str]] = None,
        keep_cross_validation_predictions: bool = True,
        preprocessing: Optional[Sequence[str]] = None,
        exploitation_ratio: float = 0.1,
    ) -> None:
        self.max_models = max_models
        self.max_runtime_secs = max_runtime_secs
        self.seed = seed
        self.nfolds = max(2, nfolds)
        self.sort_metric = sort_metric
        self.include_algos = set(a.lower() for a in include_algos) if include_algos else None
        self.exclude_algos = set(a.lower() for a in exclude_algos) if exclude_algos else set()
        self.keep_cv_preds = keep_cross_validation_predictions
        #: ["target_encoding"] enables the TE preprocessing step
        #: (h2o-automl/.../preprocessing/TargetEncoding.java)
        self.preprocessing = [p.lower() for p in (preprocessing or [])]
        for p_ in self.preprocessing:
            if p_ != "target_encoding":
                raise ValueError(f"unknown preprocessing step {p_!r}")
        #: fraction of the budget reserved for refining the best model
        #: (the reference's exploitation phase, AutoML exploitation_ratio)
        self.exploitation_ratio = float(exploitation_ratio)
        self.project_key = DKV.make_key("automl")
        self.leaderboard = Leaderboard(sort_metric)
        self.event_log = EventLog()
        self._t0 = 0.0
        self._y: Optional[str] = None
        self._ignored: List[str] = []
        self._nclasses: int = 1
        self._te_model = None
        DKV.put(self.project_key, self)

    # -- budget (WorkAllocations.java) ---------------------------------------
    def _max_models_reached(self) -> bool:
        # the reference does not count Stacked Ensembles against max_models
        n = len([
            m for m in self.leaderboard.models
            if m.algo_name != "stackedensemble"
        ])
        return bool(self.max_models) and n >= self.max_models

    def _out_of_time(self) -> bool:
        return bool(self.max_runtime_secs) and (
            time.time() - self._t0
        ) >= self.max_runtime_secs

    def _algo_allowed(self, algo: str) -> bool:
        algo = algo.lower()
        if self.include_algos is not None:
            return algo in self.include_algos
        return algo not in self.exclude_algos

    # -- steps (modeling/*StepsProvider) -------------------------------------
    def _common(self, extra: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "response_column": self._y,
            "ignored_columns": list(self._ignored),
            "nfolds": self.nfolds,
            "keep_cross_validation_predictions": self.keep_cv_preds,
            "seed": self.seed if self.seed != -1 else 42,
            **extra,
        }

    def _one(self, builder_cls, params_cls, frame, **extra) -> List[Model]:
        # pass the remaining wall-clock budget into builders that can
        # enforce it mid-build (the booster's monitor hook); others keep
        # step-boundary enforcement only
        if self.max_runtime_secs and "max_runtime_secs" in getattr(
            builder_cls, "SUPPORTED_COMMON", ()
        ):
            remaining = self.max_runtime_secs - (time.time() - self._t0)
            if remaining > 0:
                extra.setdefault("max_runtime_secs", remaining)
        p = params_cls(**self._common(extra))
        m = builder_cls(p).train(frame)
        return [m]

    # -- preprocessing (preprocessing/TargetEncoding.java) -------------------
    def _apply_target_encoding(self, frame: Frame) -> Frame:
        """Fit a k-fold-leakage-safe target encoder on the training frame
        and append <col>_te columns; the encoder model itself joins the DKV
        so predict-time frames can be transformed identically."""
        from h2o3_tpu.frame.frame import ColType
        from h2o3_tpu.models.target_encoder import (
            TargetEncoder,
            TargetEncoderParameters,
        )

        cat_cols = [
            c.name for c in frame.columns
            if c.type is ColType.CAT and c.name != self._y
            and c.name not in self._ignored
        ]
        if not cat_cols:
            self.event_log.log(
                "DataProcessing", "target encoding skipped: no categorical columns"
            )
            return frame
        # nfolds stays 0 on the params (no model-level CV for a transform);
        # the encoder's k_fold leakage handling defaults to 5 folds itself
        te = TargetEncoder(
            TargetEncoderParameters(
                response_column=self._y,
                columns_to_encode=cat_cols,
                data_leakage_handling="k_fold",
                blending=True,
                seed=self.seed if self.seed != -1 else 42,
            )
        ).train(frame)
        self._te_model = te
        out = te.transform(frame, as_training=True)
        self.event_log.log(
            "DataProcessing",
            f"target encoding applied to {len(cat_cols)} columns "
            f"(k_fold leakage handling) -> {te.key}",
        )
        return out

    def _default_plan(self) -> List[_Step]:
        from h2o3_tpu.models.deeplearning import DeepLearning, DeepLearningParameters
        from h2o3_tpu.models.glm import GLM, GLMParameters
        from h2o3_tpu.models.tree.drf import DRF, DRFParameters
        from h2o3_tpu.models.tree.gbm import GBM, GBMParameters
        from h2o3_tpu.models.tree.xgboost import XGBoost, XGBoostParameters

        steps: List[_Step] = []

        def add(algo: str, sid: str, weight: int, fn, spec=None) -> None:
            if self._algo_allowed(algo):
                steps.append(_Step(f"{algo}_{sid}", weight, fn, spec))

        def one(bcls, pcls, **extra):
            """A fully-determined single-model step: the sequential build
            closure plus the (builder, params, extra) spec the distributed
            search plane fans out — both train the SAME params."""
            return (
                lambda a, f: a._one(bcls, pcls, f, **extra),
                (bcls, pcls, extra),
            )

        fam = (
            "multinomial" if self._nclasses > 2
            else "binomial" if self._nclasses == 2 else "gaussian"
        )
        # the reference's default plan order (AutoML.java defaultModelingPlan)
        add("xgboost", "def_1", 10, *one(
            XGBoost, XGBoostParameters, ntrees=50, max_depth=6, learn_rate=0.1))
        add("glm", "def_1", 10, *one(
            GLM, GLMParameters, family=fam, alpha=0.5, lambda_=1e-4))
        add("drf", "def_1", 10, *one(
            DRF, DRFParameters, ntrees=50, max_depth=12))
        add("gbm", "def_1", 10, *one(
            GBM, GBMParameters, ntrees=50, max_depth=5, learn_rate=0.1))
        add("gbm", "def_2", 10, *one(
            GBM, GBMParameters, ntrees=50, max_depth=3, learn_rate=0.1))
        add("deeplearning", "def_1", 10, *one(
            DeepLearning, DeepLearningParameters, hidden=[32, 32], epochs=10))
        add("xgboost", "def_2", 10, *one(
            XGBoost, XGBoostParameters, ntrees=100, max_depth=4, learn_rate=0.05))
        add("gbm", "grid_1", 20, self._gbm_grid)
        if self.exploitation_ratio > 0:
            steps.append(_Step("exploitation", 10, lambda a, f: a._exploitation(f)))
        add("stackedensemble", "best_of_family", 5,
            lambda a, f: a._stacked(f, best_of_family=True))
        add("stackedensemble", "all", 5, lambda a, f: a._stacked(f, best_of_family=False))
        return steps

    def _distribute_prefix(
        self, steps: List[_Step], frame: Frame
    ) -> List[_Step]:
        """Fan the plan's leading run of fully-determined single-model
        steps across a live cloud (cluster/search.py) and return the
        remaining steps for the sequential loop.

        Leaderboard-identical to the sequential run: each step's params
        (seed included) are exactly what ``_one`` would build, and the
        leaderboard re-sorts by metric on every add, so training order
        cannot change the ranking.  Wall-clock-budgeted runs stay
        sequential — ``max_runtime_secs`` is enforced at step boundaries
        and a fan-out has none."""
        if self.max_runtime_secs:
            return steps
        try:
            from h2o3_tpu.cluster import search as _search

            cloud = _search.search_cloud()
        except Exception:
            cloud = None
        if cloud is None:
            return steps
        prefix: List[_Step] = []
        rest = list(steps)
        while rest and rest[0].spec is not None:
            prefix.append(rest.pop(0))
        if self.max_models:
            room = max(self.max_models, 0)
            prefix, over = prefix[:room], prefix[room:]
            # steps past the budget rejoin the loop so the event log
            # records each skip exactly like the sequential run
            rest = over + rest
        if len(prefix) < 2:
            return steps
        ev = self.event_log
        ev.log(
            "ModelTraining",
            f"distributing {len(prefix)} steps across "
            f"{cloud.size()} cluster members",
        )
        cells = []
        for i, step in enumerate(prefix):
            bcls, pcls, extra = step.spec
            cells.append({
                "index": i,
                "builder_cls": bcls,
                "params": pcls(**self._common(dict(extra))),
                "hp": {"step": step.id},
            })
        results = _search.fan_out(
            cloud, frame, None, cells,
            search_id=self.project_key,
            stopping_metric=self.sort_metric,
        )
        for i, step in enumerate(prefix):
            st = results.get(i)
            if st is None:
                ev.log("ModelTraining", f"step {step.id} failed: no result")
                continue
            kind, val = st
            if kind != "ok":
                ev.log("ModelTraining", f"step {step.id} failed: {val}")
                continue
            m = _search.model_from_blob(val["model"])
            if self._te_model is not None:
                m.preprocessors = [self._te_model]
            self.leaderboard.add(m)
            v, _ = metric_value(m, self.sort_metric)
            ev.log(
                "ModelTraining",
                f"{step.id} -> {m.key} metric={v:.5f} "
                f"(built on {val.get('member', '?')})",
            )
        return rest

    def _gbm_grid(self, a: "AutoML", frame: Frame) -> List[Model]:
        """Random GBM grid (modeling/GBMStepsProvider grid step)."""
        from h2o3_tpu.models.grid import GridSearch, SearchCriteria
        from h2o3_tpu.models.tree.gbm import GBM, GBMParameters

        budget_models = 3
        if self.max_models:
            budget_models = max(
                1, min(3, self.max_models - len(self.leaderboard.models) - 2)
            )
        remaining = (
            self.max_runtime_secs - (time.time() - self._t0)
            if self.max_runtime_secs else 0.0
        )
        crit = SearchCriteria(
            strategy="RandomDiscrete",
            max_models=budget_models,
            max_runtime_secs=max(remaining, 0.0),
            seed=self.seed if self.seed != -1 else 42,
        )
        gs = GridSearch(
            GBM,
            GBMParameters(**self._common({})),
            {
                "max_depth": [3, 5, 7, 9],
                "learn_rate": [0.05, 0.1, 0.2],
                "sample_rate": [0.6, 0.8, 1.0],
            },
            search_criteria=crit,
        )
        grid = gs.train(frame)
        return list(grid.models)

    def _exploitation(self, frame: Frame) -> List[Model]:
        """Refine the current best tree model (the reference's exploitation
        phase: AutoML spends exploitation_ratio of the budget improving the
        champion rather than exploring): checkpoint-continue the leader's
        booster with more trees at a lower learning rate."""
        # only boosted champions: DRF has no learn_rate (nor mid-build
        # budget support), and refining bagging with more trees at a lower
        # rate is a boosting notion
        leaders = [
            m for m in self.leaderboard.models
            if m.algo_name in ("gbm", "xgboost")
        ]
        if not leaders:
            self.event_log.log("ModelTraining", "skip exploitation: no boosted leader")
            return []
        best = leaders[0]  # leaderboard sorted best-first
        p = best.params
        import dataclasses as _dc

        # more boosting rounds at a lower learning rate around the champion
        # (the reference's GBM lr-annealing / XGBoost lr exploitation steps)
        kw = {f.name: getattr(p, f.name) for f in _dc.fields(p)}
        kw.update(
            ntrees=int(p.ntrees * 1.5) + 10,
            learn_rate=max(getattr(p, "learn_rate", 0.1) * 0.75, 0.01),
        )
        if self.max_runtime_secs:
            remaining = self.max_runtime_secs - (time.time() - self._t0)
            if remaining <= 0:
                return []
            kw["max_runtime_secs"] = remaining
        from h2o3_tpu.api.registry import algo_map

        bcls, pcls = algo_map()[best.algo_name]
        self.event_log.log(
            "ModelTraining",
            f"exploitation: refining {best.key} "
            f"(ntrees {p.ntrees} -> {kw['ntrees']})",
        )
        return [bcls(pcls(**kw)).train(frame)]

    def _stacked(self, frame: Frame, best_of_family: bool) -> List[Model]:
        from h2o3_tpu.models.stacked_ensemble import (
            StackedEnsemble,
            StackedEnsembleParameters,
        )

        bases = [
            m for m in self.leaderboard.models
            if m.algo_name != "stackedensemble"
            and getattr(m, "cv_holdout_predictions", None) is not None
        ]
        if best_of_family:
            seen: Dict[str, Model] = {}
            for m in bases:  # leaderboard is sorted best-first
                seen.setdefault(m.algo_name, m)
            bases = list(seen.values())
        if len(bases) < 2:
            self.event_log.log("ModelTraining", "skip ensemble: <2 base models")
            return []
        p = StackedEnsembleParameters(
            response_column=self._y, base_models=bases
        )
        return [StackedEnsemble(p).train(frame)]

    # -- the run (AutoML.learn) ----------------------------------------------
    def train(
        self,
        y: str,
        training_frame: Frame,
        x: Optional[Sequence[str]] = None,
        leaderboard_frame: Optional[Frame] = None,
    ) -> Model:
        self._y = y
        self._t0 = time.time()
        ev = self.event_log
        ev.log("Workflow", f"AutoML build started: {self.project_key}")
        self._ignored = (
            [c for c in training_frame.names if c not in x and c != y]
            if x is not None else []
        )
        ycol = training_frame.col(y)
        self._nclasses = len(ycol.domain) if ycol.domain else 1

        if "target_encoding" in self.preprocessing:
            try:
                training_frame = self._apply_target_encoding(training_frame)
            except Exception as e:  # preprocessing failure never kills the run
                ev.log("DataProcessing", f"target encoding failed: {e}")

        plan = self._default_plan()
        # cluster-parallel prefix: independent default models fan out
        # across members; grids/exploitation/ensembles stay sequential
        plan = self._distribute_prefix(plan, training_frame)
        for step in plan:
            if self._out_of_time():
                ev.log("Workflow", f"time budget exhausted before {step.id}")
                break
            if self._max_models_reached() and not step.id.startswith(
                "stackedensemble"
            ):
                # ensembles still run: they are not counted (reference
                # AutoML max_models semantics)
                ev.log("Workflow", f"max_models reached, skipping {step.id}")
                continue
            ev.log("ModelTraining", f"step {step.id} starting")
            try:
                models = step.build(self, training_frame)
            except Exception as e:  # a failed step never kills the run
                ev.log("ModelTraining", f"step {step.id} failed: {e}")
                continue
            for m in models:
                if self._te_model is not None:
                    # raw frames score correctly: the model re-applies the
                    # encoder at predict time (Model._apply_preprocessors)
                    m.preprocessors = [self._te_model]
                self.leaderboard.add(m)
                v, _ = metric_value(m, self.sort_metric)
                ev.log("ModelTraining", f"{step.id} -> {m.key} metric={v:.5f}")
        ev.log(
            "Workflow",
            f"AutoML build done: {len(self.leaderboard.models)} models in "
            f"{time.time() - self._t0:.1f}s",
        )
        if self.leaderboard.leader is None:
            raise RuntimeError("AutoML built no models (budget too small?)")
        return self.leaderboard.leader

    @property
    def leader(self) -> Optional[Model]:
        return self.leaderboard.leader

    def __repr__(self) -> str:
        return f"<AutoML {self.project_key} models={len(self.leaderboard.models)}>"
