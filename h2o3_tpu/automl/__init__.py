"""AutoML — budgeted automatic model selection + stacking.

Reference: ``h2o-automl`` (9.2k LoC, SURVEY.md §2.5): ``AutoML.java:40``
orchestrator running provider-registered modeling steps
(``modeling/{XGBoost,GLM,DRF,GBM,DeepLearning,StackedEnsemble}StepsProvider``)
under a time/model budget (``WorkAllocations``), CV-metric leaderboard
(``leaderboard/``), event log (``events/EventLog.java``).
"""

from h2o3_tpu.automl.automl import AutoML, EventLog, Leaderboard

__all__ = ["AutoML", "EventLog", "Leaderboard"]
