from h2o3_tpu.ops.histogram import build_histogram_sharded, make_bins, apply_bins

__all__ = ["build_histogram_sharded", "make_bins", "apply_bins"]
