"""Pallas TPU kernels for the gradient-histogram hot op (``tpu_hist``).

Reference semantics: ``hex/tree/DHistogram.java:433`` (updateHisto — per
(node, feature, bin) accumulation of {Σg, Σh, Σw}) as driven by
``hex/tree/ScoreBuildHistogram2.java:273-280`` (private per-thread
histograms, then merge) and the native ``grow_gpu_hist`` updater in the
XGBoost extension (SURVEY.md §2.3).

Two TPU-native designs, both turning the scatter-add into dense MXU work:

**Fixed-layout node-matmul kernel** (default for K·C ≤ 512, i.e. every
level of a depth ≤ 6 tree): rows NEVER move. Grid over (feature-block,
row-tile); each step computes ``one_hot(bins)[R, Fb·B1]ᵀ ⊗
node_masked_vals[R, K·C]`` as ONE dot_general on the MXU and accumulates
into a VMEM-resident [Fb·B1, K·C] block revisited across row tiles. There
is no sort, no scatter, no partition maintenance — the per-level prep the
sorted kernel needs (and its O(N log N) bitonic argsort on TPU) vanishes.
The histogram for ALL nodes of the level materializes in one pass.

**Sorted tile-per-node kernel** (fallback for deep levels, K·C > 512,
where the all-nodes output exceeds VMEM): stable-sort row ids by node, pad
each node's segment to a row-tile multiple, then a 1-D grid with
``pltpu.PrefetchScalarGridSpec`` where the output BlockSpec's index map
reads the prefetched node id — each grid step's output block IS that
node's (F, C, B) slab, accumulated in VMEM across that node's tiles.

The portable XLA scatter path in ``h2o3_tpu/ops/histogram.py`` is the
correctness oracle; ``tests/test_pallas_histogram.py`` checks parity in
interpreter mode on CPU.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# channels: 0=Σg, 1=Σh, 2=Σw(count); a 4th pad channel keeps the matmul
# operand lane-friendly.
_C = 4


def _out_sds(shape, dtype, vma):
    """ShapeDtypeStruct carrying the shard-varying axes when this jax
    version tracks them (the ``vma`` kwarg and ``lax.pvary`` arrived
    together); older versions have no VMA machinery to inform."""
    try:
        return jax.ShapeDtypeStruct(
            shape, dtype, vma=frozenset(vma) if vma else None)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _pvary(x, vma):
    if vma and hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(vma))
    return x

#: node-matmul kernel applies while K*_C <= this (VMEM budget for the
#: [Fb*B1, K*C] accumulator + operands; ~16 MB/core on v5e)
_NODE_MATMUL_MAX_KC = 512

#: factorized kernel applies while K*_C <= this (0 disables; override via
#: H2O3_TPU_HIST_FACT_MAX_KC once measured on hardware — the crossover vs
#: the node-matmul kernel is where (KC+1)*_FACT_LO ≈ n_bins1)
_FACT_MAX_KC_DEFAULT = 0


def _fact_max_kc() -> int:
    import os

    v = os.environ.get("H2O3_TPU_HIST_FACT_MAX_KC")
    return int(v) if v else _FACT_MAX_KC_DEFAULT

#: feature-block width of the node-matmul kernel grid (callers preparing an
#: aligned feature-major bins copy must pad features to a multiple of this)
_FEAT_BLOCK = 8

#: row-tile height; callers pre-padding rows must use a multiple of this
#: (bigger tiles amortize per-step VPU overhead; 2048 overflows VMEM)
_ROW_TILE = 512


# ---------------------------------------------------------------------------
# fixed-layout node-matmul kernel


def _nm_kernel(
    jmod_ref, bins_ref, node_ref, vals_ref, out_ref, oh_ref, *,
    n_feat_b, n_bins1, n_nodes
):
    """One grid step = one (feature-block, row-tile).

    jmod_ref: [B1, 1] f32 CONSTANT (the bin-index iota), loaded once —
    replaces a per-step 3-D int32 iota materialization (the VPU pass that
    used to dominate the whole kernel); bins_ref: [Fb, R] int32
    (feature-major — Mosaic wants the long axis in lanes); node_ref:
    [R, 1] int32 (-1 inactive; 2-D so the block layout matches XLA's 1-D
    tiling); vals_ref: [R, C] f32; out_ref: [1, K*C, Fb*B1] f32 (revisited
    across the row-tile grid dimension — accumulates in VMEM).

    Orientation: the MXU lane (N) dimension is Fb*B1 (~2000, always full);
    K*C sits in the sublane (M) dimension whose padding granularity is 8.
    The transposed orientation ([Fb*B1, K*C]) padded K*C up to 128 lanes,
    wasting up to 97% of the MXU at shallow levels (K*C = 4 at the root).
    """
    r = node_ref.shape[0]
    rt = pl.program_id(1)
    dtype = vals_ref.dtype

    # [Fb*B1, R] one-hot of bin codes, written per-feature into a VMEM
    # scratch: each 2-D compare pairs a lane-splat ([B1, 1] iota constant)
    # with a sublane-splat ([1, R] bin row) — both native broadcasts, so
    # the whole construction is ~one write pass (no 3-D broadcast
    # materialization, no concat). Bin codes <= 256 are exact in f32.
    binsb = bins_ref[...].astype(jnp.float32)  # [Fb, R] (tiny)
    jm = jmod_ref[...]  # [B1, 1] f32 iota constant
    for f in range(n_feat_b):
        # compare in f32 (codes <= 256 exact); the 0/1 mask is stored at
        # the histogram dtype — in bf16 mode this halves the dominant
        # VMEM write traffic of the whole kernel, losslessly (0/1 exact)
        oh_ref[f * n_bins1 : (f + 1) * n_bins1, :] = (
            jm == binsb[f][None, :]
        ).astype(dtype)
    onehot = oh_ref[...]

    # [R, K*C] node-masked values in ~ONE VPU pass: lane j carries node
    # j//C, channel j%C. A lane CONCAT of K copies of vals (Mosaic
    # handles lane concat; it cannot merge a (K, C) reshape) replaces
    # the former per-channel where+add loop (3 select passes -> 1).
    # Channel 3 is already the zero pad, so no extra masking per channel.
    node = node_ref[...]  # [R, 1]
    vals = vals_ref[...]  # [R, C]
    kc = n_nodes * _C
    iota_kc = jax.lax.broadcasted_iota(jnp.int32, (r, kc), 1)
    m_node = (iota_kc // _C) == node  # node<0 never matches
    tiled = jnp.concatenate([vals] * n_nodes, axis=1)  # [R, K*C]
    vals_k = jnp.where(m_node, tiled, jnp.zeros((), dtype))

    # [K*C, Fb*B1] = vals_kᵀ ⊗ onehotᵀ — contraction over rows on the MXU
    # (bf16 operands run at 2x the f32 MXU rate; accumulation stays f32)
    slab = jax.lax.dot_general(
        vals_k, onehot, (((0,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[None]

    @pl.when(rt == 0)
    def _():
        out_ref[...] = slab

    @pl.when(rt != 0)
    def _():
        out_ref[...] = out_ref[...] + slab


def _build_histogram_nodematmul(
    bins, nodes, g, h, n_nodes: int, n_bins1: int,
    row_tile: int, feat_block: int, interpret: bool, vma: tuple,
    bins_fm=None, rw=None, dtype=jnp.float32,
):
    n, n_feat = bins.shape
    r = row_tile
    fb = min(feat_block, n_feat)
    padf = (-n_feat) % fb
    n_feat_p = n_feat + padf
    if bins_fm is not None and bins_fm.shape == (n_feat_p, n) and n % r == 0:
        pass  # caller prepared the aligned feature-major copy: zero prep here
    else:
        if n % r:
            pad = (-n) % r
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            nodes = jnp.pad(nodes, (0, pad), constant_values=-1)
            g = jnp.pad(g, (0, pad))
            h = jnp.pad(h, (0, pad))
            if rw is not None:
                rw = jnp.pad(rw, (0, pad))
            n = n + pad
        if padf:
            # pad features with bin code 0: sliced away after the reshape below
            bins = jnp.pad(bins, ((0, 0), (0, padf)))
        bins_fm = bins.T  # [Fp, N] feature-major: rows land in the lane axis

    w = (nodes >= 0).astype(jnp.float32)
    cw = w if rw is None else w * rw.astype(jnp.float32)
    vals = jnp.stack(
        [g.astype(jnp.float32) * w, h.astype(jnp.float32) * w, cw, jnp.zeros_like(w)],
        axis=1,
    ).astype(dtype)  # [N, C]; bf16 mode rounds inputs, accumulates f32

    n_ftiles = n_feat_p // fb
    n_rtiles = n // r

    # resident constant: one-hot sublane b (within a feature) covers bin b
    jmod = jnp.asarray(np.arange(n_bins1)[:, None], dtype=jnp.float32)
    jmod = _pvary(jmod, vma)

    out = pl.pallas_call(
        partial(_nm_kernel, n_feat_b=fb, n_bins1=n_bins1, n_nodes=n_nodes),
        grid=(n_ftiles, n_rtiles),
        in_specs=[
            pl.BlockSpec((n_bins1, 1), lambda f, t: (0, 0)),
            pl.BlockSpec((fb, r), lambda f, t: (f, t)),
            pl.BlockSpec((r, 1), lambda f, t: (t, 0)),
            pl.BlockSpec((r, _C), lambda f, t: (t, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((fb * n_bins1, r), dtype)],
        out_specs=pl.BlockSpec(
            (1, n_nodes * _C, fb * n_bins1), lambda f, t: (f, 0, 0)
        ),
        out_shape=_out_sds(
            (n_ftiles, n_nodes * _C, fb * n_bins1), jnp.float32, vma),
        interpret=interpret,
    )(jmod, bins_fm, nodes[:, None], vals)

    # [Ft, K*C, Fb*B1] -> [K, F, B1, 3]
    out = out.reshape(n_ftiles, n_nodes, _C, fb, n_bins1)
    out = jnp.transpose(out, (1, 0, 3, 4, 2)).reshape(
        n_nodes, n_feat_p, n_bins1, _C
    )
    return out[:, :n_feat, :, :3]


# ---------------------------------------------------------------------------
# factorized hi/lo one-hot kernel (shallow levels)
#
# bin = hi*_FACT_LO + lo. Instead of materializing the [B1, R] one-hot (the
# dominant VPU write volume of the node-matmul kernel), materialize
# Ihi [HI, R] plus U [(k,c,lo), R] = Ilo[lo,r]*node_masked_vals[(k,c),r];
# ONE dot_general contracting rows then yields [HI, KC*LO] = the full
# (bin, node, chan) histogram of the feature. Per-feature VPU write volume
# drops from B1*R (~257R) to (HI + (KC+1)*LO)*R (~97R at K=1) — a win while
# KC is small; the node-matmul kernel stays better once KC*LO > B1.

_FACT_LO = 16


def _fact_kernel(bins_ref, node_ref, vals_ref, out_ref, *, n_feat_b, n_nodes,
                 n_hi):
    rt = pl.program_id(1)
    r = node_ref.shape[0]
    dtype = vals_ref.dtype
    kc = n_nodes * _C

    node = node_ref[...]  # [R, 1]
    vals = vals_ref[...]  # [R, C]
    iota_kc = jax.lax.broadcasted_iota(jnp.int32, (r, kc), 1)
    m_node = (iota_kc // _C) == node  # node<0 never matches
    tiled = jnp.concatenate([vals] * n_nodes, axis=1)  # [R, KC]
    vals_k = jnp.where(m_node, tiled, jnp.zeros((), dtype)).T  # [KC, R]

    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (n_hi, r), 0)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (_FACT_LO, r), 0)

    slabs = []
    for f in range(n_feat_b):
        b = bins_ref[f][None, :]  # [1, R]
        ihi = (iota_hi == (b // _FACT_LO)).astype(dtype)  # [HI, R]
        ilo = (iota_lo == (b % _FACT_LO)).astype(dtype)  # [LO, R]
        # U [(k,c,lo), R]: per (node, channel) a [LO, R] block ilo*vals_k[j]
        u = jnp.concatenate(
            [ilo * vals_k[j][None, :] for j in range(kc)], axis=0
        )  # [KC*LO, R]
        slab = jax.lax.dot_general(  # [HI, KC*LO], contraction over rows
            ihi, u, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        slabs.append(slab)
    block = jnp.concatenate(slabs, axis=0)[None]  # [1, Fb*HI, KC*LO]

    @pl.when(rt == 0)
    def _():
        out_ref[...] = block

    @pl.when(rt != 0)
    def _():
        out_ref[...] = out_ref[...] + block


def _build_histogram_factorized(
    bins, nodes, g, h, n_nodes: int, n_bins1: int,
    row_tile: int, feat_block: int, interpret: bool, vma: tuple,
    bins_fm=None, rw=None, dtype=jnp.float32,
):
    """Factorized-kernel histogram; same contract/layout as the
    node-matmul builder (returns [n_nodes, F, n_bins1, 3] f32)."""
    n, n_feat = bins.shape
    r = row_tile
    fb = min(feat_block, n_feat)
    padf = (-n_feat) % fb
    n_feat_p = n_feat + padf
    n_hi = (n_bins1 + _FACT_LO - 1) // _FACT_LO
    if bins_fm is not None and bins_fm.shape == (n_feat_p, n) and n % r == 0:
        pass  # caller prepared the aligned feature-major copy
    else:
        if n % r:
            pad = (-n) % r
            bins = jnp.pad(bins, ((0, pad), (0, 0)))
            nodes = jnp.pad(nodes, (0, pad), constant_values=-1)
            g = jnp.pad(g, (0, pad))
            h = jnp.pad(h, (0, pad))
            if rw is not None:
                rw = jnp.pad(rw, (0, pad))
            n = n + pad
        if padf:
            bins = jnp.pad(bins, ((0, 0), (0, padf)))
        bins_fm = bins.T  # [Fp, N]

    w = (nodes >= 0).astype(jnp.float32)
    cw = w if rw is None else w * rw.astype(jnp.float32)
    vals = jnp.stack(
        [g.astype(jnp.float32) * w, h.astype(jnp.float32) * w, cw,
         jnp.zeros_like(w)], axis=1,
    ).astype(dtype)  # [N, C]

    n_ftiles = n_feat_p // fb
    n_rtiles = n // r
    kc = n_nodes * _C

    out = pl.pallas_call(
        partial(_fact_kernel, n_feat_b=fb, n_nodes=n_nodes, n_hi=n_hi),
        grid=(n_ftiles, n_rtiles),
        in_specs=[
            pl.BlockSpec((fb, r), lambda f, t: (f, t)),
            pl.BlockSpec((r, 1), lambda f, t: (t, 0)),
            pl.BlockSpec((r, _C), lambda f, t: (t, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, fb * n_hi, kc * _FACT_LO), lambda f, t: (f, 0, 0)
        ),
        out_shape=_out_sds(
            (n_ftiles, fb * n_hi, kc * _FACT_LO), jnp.float32, vma),
        interpret=interpret,
    )(bins_fm, nodes[:, None], vals)

    # [Ft, Fb*HI, KC*LO] with columns laid out (k, c, lo) -> [K, F, B1, 3]
    out = out.reshape(n_ftiles, fb, n_hi, n_nodes, _C, _FACT_LO)
    out = jnp.transpose(out, (3, 0, 1, 2, 5, 4)).reshape(
        n_nodes, n_feat_p, n_hi * _FACT_LO, _C
    )
    return out[:, :n_feat, :n_bins1, :3]


# ---------------------------------------------------------------------------
# sorted tile-per-node kernel (deep levels)


def _hist_kernel(node_ref, first_ref, bins_ref, vals_ref, out_ref, *, n_feat, n_bins1):
    """One grid step = one row tile of one node.

    bins_ref: [R, F] int32 (VMEM); vals_ref: [R, C] f32 (VMEM);
    out_ref:  [1, F, C, B1] f32 — the current node's slab (revisited across
    consecutive tiles of the same node).
    """
    t = pl.program_id(0)
    r = bins_ref.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (r, n_bins1), 1)
    vals = vals_ref[:]  # [R, C]; bf16 mode: both matmul operands bf16

    slabs = []
    for f in range(n_feat):
        b = bins_ref[:, f]
        onehot = (iota_b == b[:, None]).astype(vals.dtype)  # [R, B1]
        # [C, B1] = valsᵀ[C, R] @ onehot[R, B1]  (contraction over rows)
        h_f = jax.lax.dot_general(
            vals, onehot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        slabs.append(h_f)
    slab = jnp.stack(slabs, axis=0)[None]  # [1, F, C, B1]

    first = first_ref[t] == 1

    @pl.when(first)
    def _():
        out_ref[...] = slab

    @pl.when(jnp.logical_not(first))
    def _():
        out_ref[...] = out_ref[...] + slab


def _prep_padded(bins, nodes, g, h, n_nodes: int, row_tile: int, t_max: int,
                 rw=None, dtype=jnp.float32):
    """Sort rows by node, pad each node segment to a row_tile multiple.

    Returns (bins_p [T*R, F] int32, vals_p [T*R, C] f32,
    item_node [T] int32 — dummy slot n_nodes for unused tiles,
    item_first [T] int32).
    """
    n, _ = bins.shape
    r = row_tile
    total = t_max * r
    # inactive rows (node < 0) -> dummy node n_nodes, dropped by OOB scatter
    nd = jnp.where(nodes >= 0, nodes, n_nodes)
    order = jnp.argsort(nd, stable=True)
    nd_s = nd[order]

    counts = jnp.bincount(nd, length=n_nodes + 1)[:n_nodes]
    # every node gets >= 1 tile so empty nodes' slabs are zero-initialized,
    # never left undefined
    padded = jnp.maximum((counts + r - 1) // r, 1) * r
    pad_off = jnp.concatenate([jnp.zeros((1,), padded.dtype), jnp.cumsum(padded)])
    sort_off = jnp.concatenate([jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)])

    rank = jnp.arange(n) - sort_off[jnp.clip(nd_s, 0, n_nodes - 1)]
    dest = jnp.where(
        nd_s < n_nodes, pad_off[jnp.clip(nd_s, 0, n_nodes - 1)] + rank, total
    ).astype(jnp.int32)

    bins_p = jnp.zeros((total, bins.shape[1]), jnp.int32).at[dest].set(
        bins[order].astype(jnp.int32), mode="drop"
    )
    w = (nodes >= 0).astype(jnp.float32)
    cw = w if rw is None else w * rw.astype(jnp.float32)
    vals = jnp.stack(
        [g.astype(jnp.float32) * w, h.astype(jnp.float32) * w, cw,
         jnp.zeros_like(w)], axis=1
    ).astype(dtype)
    vals_p = jnp.zeros((total, _C), dtype).at[dest].set(vals[order], mode="drop")

    # tile t belongs to the node whose padded segment contains row t*r
    tile_starts = jnp.arange(t_max) * r
    item_node = jnp.searchsorted(pad_off[1:], tile_starts, side="right").astype(jnp.int32)
    item_node = jnp.minimum(item_node, n_nodes)  # trailing unused tiles -> dummy slab
    item_first = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (item_node[1:] != item_node[:-1]).astype(jnp.int32)]
    )
    return bins_p, vals_p, item_node, item_first


def _resolve_hist_dtype(dtype: str):
    """'auto' -> env H2O3_TPU_HIST_DTYPE, else bf16 on real TPU (2x MXU
    rate, halved VMEM traffic; accumulation is always f32) and f32
    elsewhere (the CPU interpreter path doubles as the exact-parity
    oracle)."""
    import os

    if dtype == "auto":
        dtype = os.environ.get("H2O3_TPU_HIST_DTYPE") or (
            "bf16" if jax.default_backend() == "tpu" else "f32"
        )
    if dtype not in ("f32", "bf16"):
        raise ValueError(f"hist dtype must be 'f32' or 'bf16', got {dtype!r}")
    return jnp.bfloat16 if dtype == "bf16" else jnp.float32


def build_histogram_pallas(
    bins, nodes, g, h, n_nodes: int, n_bins1: int,
    row_tile: int = None, interpret: bool = False, vma: tuple = (),
    kernel: str = "auto", bins_fm=None, rw=None, dtype: str = "auto",
):
    """Drop-in Pallas replacement for ``histogram._shard_histogram``.

    bins: [N, F] int bin codes (NA bucket = n_bins1 - 1 handled upstream);
    nodes: [N] int32 (-1 = inactive row); g, h: [N] float; rw: optional [N]
    per-row count weight (weights_column -> the count channel reports Σw).
    dtype: 'f32' | 'bf16' | 'auto' — matmul operand precision (the one-hot
    mask is exact either way; bf16 rounds g/h/w inputs to 8 mantissa bits,
    accumulation stays f32).
    Returns [n_nodes, F, n_bins1, 3] float32 of (Σg, Σh, Σw).
    """
    # resolve env-var defaults OUTSIDE the jit boundary: a cached trace
    # must never pin a stale H2O3_TPU_HIST_DTYPE / _FACT_MAX_KC (when
    # already inside a trace — called from _build_histogram_jit — dtype
    # and kernel arrive pre-resolved)
    if dtype == "auto":
        dtype = "bf16" if _resolve_hist_dtype("auto") == jnp.bfloat16 else "f32"
    if kernel == "auto" and n_nodes * _C <= _fact_max_kc():
        kernel = "factorized"
    return _build_histogram_pallas_jit(
        bins, nodes, g, h, n_nodes, n_bins1, row_tile, interpret,
        vma, kernel, bins_fm, rw, dtype,
    )


@partial(
    jax.jit,
    static_argnames=(
        "n_nodes", "n_bins1", "row_tile", "interpret", "vma", "kernel", "dtype"
    ),
)
def _build_histogram_pallas_jit(
    bins, nodes, g, h, n_nodes: int, n_bins1: int,
    row_tile, interpret: bool, vma: tuple,
    kernel: str, bins_fm, rw, dtype: str,
):
    if kernel == "factorized":
        return _build_histogram_factorized(
            bins, nodes, g, h, n_nodes, n_bins1,
            row_tile=row_tile or _ROW_TILE, feat_block=_FEAT_BLOCK,
            interpret=interpret, vma=vma, bins_fm=bins_fm, rw=rw,
            dtype=_resolve_hist_dtype(dtype),
        )
    if kernel == "nodematmul" or (
        kernel == "auto" and n_nodes * _C <= _NODE_MATMUL_MAX_KC
    ):
        return _build_histogram_nodematmul(
            bins, nodes, g, h, n_nodes, n_bins1,
            row_tile=row_tile or _ROW_TILE, feat_block=_FEAT_BLOCK,
            interpret=interpret, vma=vma, bins_fm=bins_fm, rw=rw,
            dtype=_resolve_hist_dtype(dtype),
        )
    n, n_feat = bins.shape
    r = row_tile or 512  # sorted kernel keeps its original tile height
    t_max = (n + r - 1) // r + n_nodes  # ≤ R-1 pad rows per node

    bins_p, vals_p, item_node, item_first = _prep_padded(
        bins, nodes, g, h, n_nodes, r, t_max, rw=rw,
        dtype=_resolve_hist_dtype(dtype),
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((r, n_feat), lambda t, nref, fref: (t, 0)),
            pl.BlockSpec((r, _C), lambda t, nref, fref: (t, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, n_feat, _C, n_bins1), lambda t, nref, fref: (nref[t], 0, 0, 0)
        ),
    )

    out = pl.pallas_call(
        partial(_hist_kernel, n_feat=n_feat, n_bins1=n_bins1),
        grid_spec=grid_spec,
        # slab n_nodes is the dummy for trailing all-pad tiles; vma marks the
        # per-shard output as varying over the mesh axes when called inside
        # shard_map (each shard builds its private histogram pre-psum)
        out_shape=_out_sds(
            (n_nodes + 1, n_feat, _C, n_bins1), jnp.float32, vma),
        interpret=interpret,
    )(item_node, item_first, bins_p, vals_p)

    # [K, F, C, B1] -> [K, F, B1, 3] to match the XLA oracle layout
    return jnp.transpose(out[:n_nodes], (0, 1, 3, 2))[..., :3]
