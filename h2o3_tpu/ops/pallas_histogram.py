"""Pallas TPU kernel for the gradient-histogram hot op (``tpu_hist``).

Reference semantics: ``hex/tree/DHistogram.java:433`` (updateHisto — per
(node, feature, bin) accumulation of {Σg, Σh, Σw}) as driven by
``hex/tree/ScoreBuildHistogram2.java:273-280`` (private per-thread
histograms, then merge) and the native ``grow_gpu_hist`` updater in the
XGBoost extension (SURVEY.md §2.3).

TPU-native redesign — the scatter-add becomes dense MXU matmuls:

1. XLA prep (per tree level): stable-sort the row ids by tree node, pad
   each node's segment of the sorted order to a multiple of the row tile
   ``R`` (padded rows carry zero values, so no masking is needed in the
   kernel), and gather bins/values into that padded layout.  Per row-tile
   scalars (its node id, and a first-tile-of-node flag) are precomputed.
2. Pallas kernel: 1-D grid over row tiles with
   ``pltpu.PrefetchScalarGridSpec``.  The output BlockSpec's index map
   reads the prefetched node id, so each grid step's output block IS that
   node's (F, C, B) histogram slab; consecutive tiles of the same node
   revisit the same block and accumulate in VMEM.  Within a step, each
   feature's histogram is ``one_hot(bins)ᵀ @ vals`` — a [B1, R] × [R, C]
   contraction on the MXU instead of a serialized scatter.

Total matmul work is N·F·B1·C MACs per level — independent of tree depth
(the sort gives each row exactly one node slab), unlike a dense
one-hot-over-(node,bin) formulation which would cost K× more.

The portable XLA scatter path in ``h2o3_tpu/ops/histogram.py`` is the
correctness oracle; ``tests/test_pallas_histogram.py`` checks parity in
interpreter mode on CPU.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# channels: 0=Σg, 1=Σh, 2=Σw(count); a 4th pad channel keeps the matmul
# operand lane-friendly.
_C = 4


def _hist_kernel(node_ref, first_ref, bins_ref, vals_ref, out_ref, *, n_feat, n_bins1):
    """One grid step = one row tile of one node.

    bins_ref: [R, F] int32 (VMEM); vals_ref: [R, C] f32 (VMEM);
    out_ref:  [1, F, C, B1] f32 — the current node's slab (revisited across
    consecutive tiles of the same node).
    """
    t = pl.program_id(0)
    r = bins_ref.shape[0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (r, n_bins1), 1)
    vals = vals_ref[:]  # [R, C]

    slabs = []
    for f in range(n_feat):
        b = bins_ref[:, f]
        onehot = (iota_b == b[:, None]).astype(jnp.float32)  # [R, B1]
        # [C, B1] = valsᵀ[C, R] @ onehot[R, B1]  (contraction over rows)
        h_f = jax.lax.dot_general(
            vals, onehot, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        slabs.append(h_f)
    slab = jnp.stack(slabs, axis=0)[None]  # [1, F, C, B1]

    first = first_ref[t] == 1

    @pl.when(first)
    def _():
        out_ref[...] = slab

    @pl.when(jnp.logical_not(first))
    def _():
        out_ref[...] = out_ref[...] + slab


def _prep_padded(bins, nodes, g, h, n_nodes: int, row_tile: int, t_max: int):
    """Sort rows by node, pad each node segment to a row_tile multiple.

    Returns (bins_p [T*R, F] int32, vals_p [T*R, C] f32,
    item_node [T] int32 — dummy slot n_nodes for unused tiles,
    item_first [T] int32).
    """
    n, _ = bins.shape
    r = row_tile
    total = t_max * r
    # inactive rows (node < 0) -> dummy node n_nodes, dropped by OOB scatter
    nd = jnp.where(nodes >= 0, nodes, n_nodes)
    order = jnp.argsort(nd, stable=True)
    nd_s = nd[order]

    counts = jnp.bincount(nd, length=n_nodes + 1)[:n_nodes]
    # every node gets >= 1 tile so empty nodes' slabs are zero-initialized,
    # never left undefined
    padded = jnp.maximum((counts + r - 1) // r, 1) * r
    pad_off = jnp.concatenate([jnp.zeros((1,), padded.dtype), jnp.cumsum(padded)])
    sort_off = jnp.concatenate([jnp.zeros((1,), counts.dtype), jnp.cumsum(counts)])

    rank = jnp.arange(n) - sort_off[jnp.clip(nd_s, 0, n_nodes - 1)]
    dest = jnp.where(
        nd_s < n_nodes, pad_off[jnp.clip(nd_s, 0, n_nodes - 1)] + rank, total
    ).astype(jnp.int32)

    bins_p = jnp.zeros((total, bins.shape[1]), jnp.int32).at[dest].set(
        bins[order].astype(jnp.int32), mode="drop"
    )
    w = (nodes >= 0).astype(jnp.float32)
    vals = jnp.stack(
        [g.astype(jnp.float32) * w, h.astype(jnp.float32) * w, w,
         jnp.zeros_like(w)], axis=1
    )
    vals_p = jnp.zeros((total, _C), jnp.float32).at[dest].set(vals[order], mode="drop")

    # tile t belongs to the node whose padded segment contains row t*r
    tile_starts = jnp.arange(t_max) * r
    item_node = jnp.searchsorted(pad_off[1:], tile_starts, side="right").astype(jnp.int32)
    item_node = jnp.minimum(item_node, n_nodes)  # trailing unused tiles -> dummy slab
    item_first = jnp.concatenate(
        [jnp.ones((1,), jnp.int32),
         (item_node[1:] != item_node[:-1]).astype(jnp.int32)]
    )
    return bins_p, vals_p, item_node, item_first


@partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins1", "row_tile", "interpret", "vma"),
)
def build_histogram_pallas(
    bins, nodes, g, h, n_nodes: int, n_bins1: int,
    row_tile: int = 512, interpret: bool = False, vma: tuple = (),
):
    """Drop-in Pallas replacement for ``histogram._shard_histogram``.

    bins: [N, F] int bin codes (NA bucket = n_bins1 - 1 handled upstream);
    nodes: [N] int32 (-1 = inactive row); g, h: [N] float.
    Returns [n_nodes, F, n_bins1, 3] float32 of (Σg, Σh, count).
    """
    n, n_feat = bins.shape
    r = row_tile
    t_max = (n + r - 1) // r + n_nodes  # ≤ R-1 pad rows per node

    bins_p, vals_p, item_node, item_first = _prep_padded(
        bins, nodes, g, h, n_nodes, r, t_max
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(t_max,),
        in_specs=[
            pl.BlockSpec((r, n_feat), lambda t, nref, fref: (t, 0)),
            pl.BlockSpec((r, _C), lambda t, nref, fref: (t, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, n_feat, _C, n_bins1), lambda t, nref, fref: (nref[t], 0, 0, 0)
        ),
    )

    out = pl.pallas_call(
        partial(_hist_kernel, n_feat=n_feat, n_bins1=n_bins1),
        grid_spec=grid_spec,
        # slab n_nodes is the dummy for trailing all-pad tiles; vma marks the
        # per-shard output as varying over the mesh axes when called inside
        # shard_map (each shard builds its private histogram pre-psum)
        out_shape=jax.ShapeDtypeStruct(
            (n_nodes + 1, n_feat, _C, n_bins1), jnp.float32,
            vma=frozenset(vma) if vma else None,
        ),
        interpret=interpret,
    )(item_node, item_first, bins_p, vals_p)

    # [K, F, C, B1] -> [K, F, B1, 3] to match the XLA oracle layout
    return jnp.transpose(out[:n_nodes], (0, 1, 3, 2))[..., :3]
