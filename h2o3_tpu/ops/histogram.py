"""Gradient-histogram construction — the tree-training hot kernel.

Reference: ``hex/tree/DHistogram.java:433`` (updateHisto: accumulate
{Σw, Σwy, Σwy²} per (leaf, column, bin) in a flat double[]), built per node
tree-level by ``ScoreBuildHistogram2`` (``tree/ScoreBuildHistogram2.java:
273-280,385-396``) as a two-stage pass: per-thread private histograms, then a
shared atomic merge, then a cross-node MRTask reduce. The XGBoost extension
does the same thing on GPU inside ``grow_gpu_hist`` (native, §2.3 of
SURVEY.md).

TPU-native redesign (the "tpu_hist" kernel):
  * features are pre-quantized to int bin codes (global quantile binning like
    XGBoost hist / H2O ``histogram_type=QuantilesGlobal``) — static shapes,
    uint8-sized codes, NA gets a dedicated trailing bin;
  * per device shard, the (node, feature, bin) histogram of (grad, hess,
    count) is ONE fused scatter-add into a zeros array — the shard-private
    histogram, exactly ScoreBuildHistogram2's private stage;
  * the cross-device merge is ``lax.psum`` over the data axis — the MRTask
    reduce, emitted by XLA as a log-depth ICI collective.

A Pallas VMEM-resident variant lives in h2o3_tpu/ops/pallas_histogram.py;
this module is the portable XLA path and the correctness oracle.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Optional, Tuple  # noqa: F401

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from h2o3_tpu.parallel.mesh import DATA_AXIS
from h2o3_tpu.util import telemetry


# ---------------------------------------------------------------------------
# fixed-shape level plans: the node-bucket ladder
#
# ``n_nodes`` is a static jit argname, so every tree level 2^d used to be a
# fresh plan (~100-250 ms of XLA compile per level, per HIST_BENCH). Padding
# the node dimension up to a small ladder of power-of-2 buckets makes one
# traced plan serve every level in the bucket: pad rows are zero-filled (a
# scatter-add / one-hot contraction never touches a node id beyond the real
# range) and the real ``n_nodes`` rows are sliced back out, so the result is
# bit-identical to the unpadded build.

_DEFAULT_NODE_BUCKETS = (8, 64, 512)

PLAN_CACHE = telemetry.counter(
    "hist_plan_cache_total",
    "histogram level-plan lookups against the padded-bucket jit cache",
    labels=("result",),
)

_PLAN_LOCK = threading.Lock()
_PLAN_KEYS: set = set()


def node_buckets() -> Tuple[int, ...]:
    """The node-capacity ladder from ``H2O3_TPU_HIST_NODE_BUCKETS``
    (comma-separated, default ``8,64,512``; ``0``/empty disables padding)."""
    raw = os.environ.get("H2O3_TPU_HIST_NODE_BUCKETS")
    if raw is None:
        return _DEFAULT_NODE_BUCKETS
    try:
        vals = sorted({int(t) for t in raw.split(",") if t.strip()})
    except ValueError:
        return _DEFAULT_NODE_BUCKETS
    return tuple(v for v in vals if v > 0)


def pad_nodes(n_nodes: int) -> int:
    """Smallest ladder bucket >= ``n_nodes`` (identity above the ladder
    or with the ladder disabled)."""
    for b in node_buckets():
        if n_nodes <= b:
            return b
    return n_nodes


def _shape_sig(arrays) -> Tuple:
    return tuple(
        None if a is None else (tuple(a.shape), str(a.dtype)) for a in arrays
    )


def _note_plan(key: Tuple) -> None:
    """Meter a plan-cache lookup: ``miss`` the first time a jit cache key
    is seen by this process, ``hit`` after — the bench asserts warm tree
    levels are all hits (compile-free) instead of inferring it from walls."""
    with _PLAN_LOCK:
        seen = key in _PLAN_KEYS
        if not seen:
            _PLAN_KEYS.add(key)
    PLAN_CACHE.inc(result="hit" if seen else "miss")


# ---------------------------------------------------------------------------
# quantile binning (GlobalQuantilesCalc / XGBoost sketch analogue)


def make_bins(
    X: np.ndarray, nbins: int = 256, sample: int = 200_000, seed: int = 0
) -> np.ndarray:
    """Per-feature bin edges from (sampled) quantiles. Returns [F, nbins-1]
    interior edges; value -> bin = searchsorted(edges, v, 'right')."""
    n, F = X.shape
    if n > sample:
        idx = np.random.default_rng(seed).choice(n, sample, replace=False)
        Xs = X[idx]
    else:
        Xs = X
    qs = np.linspace(0, 1, nbins + 1)[1:-1]
    edges = np.empty((F, nbins - 1), dtype=np.float64)
    for f in range(F):
        col = Xs[:, f]
        col = col[~np.isnan(col)]
        if col.size == 0:
            edges[f] = np.arange(nbins - 1, dtype=np.float64)
            continue
        distinct = np.unique(col)
        if len(distinct) <= nbins:
            # low-cardinality (incl. one-hot indicators): exact midpoint
            # edges give every distinct value its own bin — data quantiles
            # would collapse rare values (e.g. a 3%-frequency indicator)
            # into their neighbor's bin and make them unsplittable
            mids = (distinct[:-1] + distinct[1:]) / 2.0
            e = np.full(nbins - 1, np.inf)  # inf pad: never <= any value
            e[: len(mids)] = mids
            edges[f] = e
            continue
        e = np.quantile(col, qs)
        # de-duplicate while keeping monotonicity (constant-ish features)
        e = np.maximum.accumulate(e)
        edges[f] = e
    return edges


def _apply_bins_batched(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Vectorized per-row searchsorted (no Python loop over features).

    One stable argsort of the per-feature ``[edges | values]`` concatenation
    ranks every value against its own feature's edges in a single batched
    pass: with edges FIRST and the sort stable, an equal edge sorts before
    the value, so the running edge count at a value's sorted position is
    exactly ``searchsorted(edges[f], x, side="right")`` — float64-exact
    (ties, ±inf and NaN-last included). Row chunks bound the workspace.
    """
    n, F = X.shape
    E = edges.shape[1]
    out = np.empty((n, F), dtype=np.int32)
    rows = np.arange(F)[:, None]
    chunk = max(1, 4_000_000 // max(F, 1))
    for s in range(0, n, chunk):
        xb = X[s:s + chunk].T  # [F, m]
        comb = np.concatenate([edges, xb], axis=1)  # [F, E+m]
        order = np.argsort(comb, axis=1, kind="stable")
        is_val = order >= E
        edges_before = np.cumsum(~is_val, axis=1)  # edges at/before position
        blk = np.empty(xb.shape, dtype=np.int32)
        blk[np.broadcast_to(rows, order.shape)[is_val],
            order[is_val] - E] = edges_before[is_val]
        out[s:s + chunk] = blk.T
    return out


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Quantize raw features to bin codes [N, F] int8-range; NA -> nbins.

    Implementation is measurement-dispatched (single-core CPU numbers, see
    PR notes): for tall matrices — the booster shape, e.g. 1M x 28 — the
    per-feature ``np.searchsorted`` loop IS the fastest exact kernel
    (binary search over L1-resident edges beats every batched formulation:
    argsort ~0.7x, pooled-rank ~0.6x, broadcast-count ~0.3x, grid-bucketed
    ~0.7x, jnp/f32 ~0.7x AND inexact), while for wide-short matrices the
    per-call overhead of F tiny searchsorteds dominates and the batched
    argsort path wins (n=8, F=5000: ~1.8x). Both paths are bit-exact
    against the per-feature formulation; the hot repeat-fit case no longer
    reaches either — the device frame cache serves the bin codes resident.
    """
    X = np.asarray(X)
    n, F = X.shape
    nbins = edges.shape[1] + 1
    if n == 0 or F == 0:
        return np.empty((n, F), dtype=np.int32)
    if F > 32 * max(n, 1):  # wide-short: loop overhead dominates
        out = _apply_bins_batched(X, edges)
    else:
        out = np.empty((n, F), dtype=np.int32)
        for f in range(F):
            out[:, f] = np.searchsorted(edges[f], X[:, f], side="right")
    out[np.isnan(X)] = nbins  # NA bucket (DHistogram NA bin at end)
    return out


# ---------------------------------------------------------------------------
# histogram-partial payload guard (distributed training)


class HistPartialTooLargeError(ValueError):
    """A histogram partial would exceed the RPC frame limit.

    Raised caller-side before a distributed fit starts (worst level of the
    planned tree) and home-side before a partial ships, so the operator
    sees the arithmetic and the remediation instead of a transport
    ``MAX_FRAME_BYTES`` failure mid-level."""

    def __init__(self, what: str, nbytes: int, limit: int,
                 n_classes: int, n_nodes: int, n_features: int,
                 n_bins1: int) -> None:
        self.nbytes = int(nbytes)
        self.limit = int(limit)
        super().__init__(
            f"histogram partial for {what} is {nbytes} bytes "
            f"({n_classes} classes x {n_nodes} nodes x {n_features} "
            f"features x {n_bins1} bins x 3 channels x 8 bytes) "
            f"but the RPC frame limit leaves {limit}; lower "
            f"H2O3_TPU_TREE_BLOCK to ship fewer class trees per level, "
            f"or reduce max_depth / nbins")


def guard_hist_payload(what: str, n_classes: int, n_nodes: int,
                       n_features: int, n_bins1: int) -> int:
    """Raise :class:`HistPartialTooLargeError` if a ``(classes, nodes,
    features, bins, 3)`` float64 partial cannot fit one RPC frame.
    Returns the payload size in bytes."""
    nbytes = int(n_classes) * int(n_nodes) * int(n_features) \
        * int(n_bins1) * 3 * 8
    # lazy: ops must stay importable without the cluster package loaded
    from h2o3_tpu.cluster import transport

    limit = max(0, int(transport.MAX_FRAME_BYTES) - (1 << 16))
    if nbytes > limit:
        raise HistPartialTooLargeError(
            what, nbytes, limit, n_classes, n_nodes, n_features, n_bins1)
    return nbytes


# ---------------------------------------------------------------------------
# the scatter-add histogram


def _shard_histogram(bins, nodes, g, h, n_nodes: int, n_bins1: int, rw=None):
    """Shard-private histogram: [K, F, B+1, 3] of (Σg, Σh, Σw).

    rw: optional [N] per-row count weight — the third channel becomes the
    weighted observation count (DHistogram Σw), so min_rows sees weighted
    counts under a weights_column. None keeps raw row counts."""
    n, F = bins.shape
    valid = nodes >= 0
    node = jnp.where(valid, nodes, 0)
    flat = (node[:, None] * F + jnp.arange(F, dtype=jnp.int32)[None, :]) * n_bins1 + bins
    w = valid.astype(g.dtype)
    cw = w if rw is None else w * rw
    # one 1-D scatter per channel: scatter updates must stay 1-D — any
    # [N*F, 3] (or batched [3, N*F]) update tensor gets canonicalized by
    # XLA:TPU into a copy whose 3-lane axis pads to 128 (≈42x HBM blowup;
    # observed as a 28.6 GB allocation at N=2M, F=28)
    flat = flat.reshape(-1)
    size = n_nodes * F * n_bins1
    chans = []
    for v in (g * w, h * w, cw):
        upd = jnp.broadcast_to(v[:, None], (n, F)).reshape(-1)
        chans.append(jnp.zeros(size, g.dtype).at[flat].add(upd))
    hist = jnp.stack(chans, axis=0)
    return jnp.moveaxis(hist.reshape(3, n_nodes, F, n_bins1), 0, -1)


def _shard_node_totals(nodes, g, h, n_nodes: int, rw=None):
    """Per-node (Σg, Σh, Σw) [K, 3] — one masked 1-D scatter-add per channel.

    The terminal tree level needs only these totals (leaf values), not the
    full per-(feature, bin) histogram: splitting is impossible at max
    depth, so the [K, F, B+1, 3] build there would be pure waste — and it
    is the widest (most expensive) level of the whole tree.

    Scatter (not a one-hot contraction): a scatter-add accumulates per
    destination index in a capacity-independent order, so a node dimension
    padded to the bucket ladder stays bit-identical to the unpadded build —
    a dot_general's blocking (and with it the float accumulation order)
    shifts with the padded K."""
    valid = nodes >= 0
    node = jnp.where(valid, nodes, 0)  # masked rows add an exact 0.0 below
    w = valid.astype(g.dtype)
    cw = w if rw is None else w * rw
    chans = [
        jnp.zeros(n_nodes, g.dtype).at[node].add(v)
        for v in (g * w, h * w, cw)
    ]
    return jnp.stack(chans, axis=1)  # [K, 3]


def node_totals_sharded(nodes, g, h, n_nodes: int, mesh=None, rw=None):
    """Distributed per-node totals: shard-private contraction + psum.

    The node dimension is padded to the bucket ladder (``pad_nodes``) so one
    traced shape serves every level in a bucket; node ids never reach the
    pad columns, so slicing the real rows back out is bit-identical."""
    k_pad = pad_nodes(n_nodes)
    _note_plan(("totals", k_pad, _shape_sig((nodes, g, h, rw)), mesh))
    if mesh is None:
        out = _shard_node_totals(nodes, g, h, k_pad, rw=rw)
        return out[:n_nodes] if k_pad != n_nodes else out

    extras = [] if rw is None else [rw]

    def fn(nd, gg, hh, *rest):
        part = _shard_node_totals(
            nd, gg, hh, k_pad, rw=rest[0] if rest else None
        )
        return jax.lax.psum(part, DATA_AXIS)

    out = _shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
        + tuple(P(DATA_AXIS) for _ in extras),
        out_specs=P(),
    )(nodes, g, h, *extras)
    return out[:n_nodes] if k_pad != n_nodes else out


def _hist_impl(impl: Optional[str]) -> str:
    """Resolve histogram implementation: Pallas MXU kernel on TPU, XLA
    scatter elsewhere. Override with H2O3_TPU_HIST_IMPL=scatter|pallas."""
    import os

    impl = impl or os.environ.get("H2O3_TPU_HIST_IMPL") or (
        "pallas" if jax.default_backend() == "tpu" else "scatter"
    )
    if impl not in ("scatter", "pallas"):
        raise ValueError(
            f"H2O3_TPU_HIST_IMPL must be 'scatter' or 'pallas', got {impl!r}"
        )
    return impl


def _one_shard_histogram(
    bins, nodes, g, h, n_nodes, n_bins1, impl, vma=(), bins_fm=None, rw=None,
    dtype="auto", kernel="auto",
):
    if impl == "pallas":
        from h2o3_tpu.ops.pallas_histogram import build_histogram_pallas

        return build_histogram_pallas(
            bins, nodes, g, h, n_nodes, n_bins1,
            interpret=jax.default_backend() != "tpu", vma=vma, bins_fm=bins_fm,
            rw=rw, dtype=dtype, kernel=kernel,
        )
    return _shard_histogram(bins, nodes, g, h, n_nodes, n_bins1, rw=rw)


def build_histogram_sharded(
    bins, nodes, g, h, n_nodes: int, n_bins1: int, mesh=None,
    impl: Optional[str] = None, bins_fm=None, rw=None,
):
    """Full distributed histogram: private scatter-add per shard, psum merge.

    bins:[N,F] int32 row-sharded; nodes:[N] int32 (-1 = inactive row);
    g,h:[N] float32. bins_fm: optional feature-major [F, N] copy of bins
    (already padded to the kernel row tile) — callers in a training loop pass
    it so the pallas path skips a per-call transpose. rw: optional [N]
    per-row count weight (weights_column: the count channel reports Σw).
    Returns replicated [n_nodes, F, n_bins1, 3].

    The node dimension is padded up to the bucket ladder (``pad_nodes``)
    before the jit call — one compiled plan per bucket instead of one per
    tree level — and the real ``n_nodes`` rows are sliced back out.
    """
    # resolve the env overrides OUTSIDE the jit cache so changing them
    # between calls takes effect (the resolved values are static cache keys);
    # the scatter impl ignores dtype — pin it so flipping the dtype env var
    # neither recompiles nor (if invalid) breaks the path that never reads it
    impl = _hist_impl(impl)
    k_pad = pad_nodes(n_nodes)
    kernel = "auto"
    if impl == "pallas":
        from h2o3_tpu.ops.pallas_histogram import (
            _C,
            _fact_max_kc,
            _resolve_hist_dtype,
        )

        dtype = (
            "bf16" if _resolve_hist_dtype("auto") == jnp.bfloat16 else "f32"
        )
        # kernel choice keys off the PADDED count — that is the shape the
        # kernel actually compiles for, so every level in a bucket picks
        # the same kernel and shares the one plan
        if k_pad * _C <= _fact_max_kc():
            kernel = "factorized"
    else:
        dtype = "f32"
    _note_plan((
        "hist", k_pad, n_bins1, _shape_sig((bins, nodes, g, h, bins_fm, rw)),
        mesh, impl, dtype, kernel,
    ))
    out = _build_histogram_jit(
        bins, nodes, g, h, bins_fm, rw, k_pad, n_bins1, mesh, impl, dtype,
        kernel,
    )
    return out[:n_nodes] if k_pad != n_nodes else out


@partial(
    jax.jit,
    static_argnames=("n_nodes", "n_bins1", "mesh", "impl", "dtype", "kernel"),
)
def _build_histogram_jit(
    bins, nodes, g, h, bins_fm, rw, n_nodes: int, n_bins1: int, mesh,
    impl: str, dtype: str = "auto", kernel: str = "auto",
):
    if mesh is None:
        return _one_shard_histogram(
            bins, nodes, g, h, n_nodes, n_bins1, impl, bins_fm=bins_fm, rw=rw,
            dtype=dtype, kernel=kernel,
        )

    # optional row-sharded / feature-major extras enter the shard_map only
    # when present so the base program is unchanged without them
    extras = []
    if bins_fm is not None:
        extras.append(("bins_fm", bins_fm, P(None, DATA_AXIS)))
    if rw is not None:
        extras.append(("rw", rw, P(DATA_AXIS)))

    def fn(b, nd, gg, hh, *rest):
        kw = dict(zip([name for name, _, _ in extras], rest))
        part = _one_shard_histogram(
            b, nd, gg, hh, n_nodes, n_bins1, impl, vma=(DATA_AXIS,),
            dtype=dtype, kernel=kernel, **kw
        )
        return jax.lax.psum(part, DATA_AXIS)

    sm_kw = {}
    if impl == "pallas" and jax.default_backend() != "tpu":
        # interpreter-mode pallas lowers VMEM scratch to plain arrays
        # whose varying-axis metadata can't match the shard-varying
        # values written into them; the check only exists to validate
        # collective placement, which the real-TPU path still enforces.
        # (the kwarg is check_vma on jax.shard_map but check_rep on the
        # jax.experimental fallback — key off the actual signature)
        import inspect

        params = inspect.signature(_shard_map).parameters
        if "check_vma" in params:
            sm_kw["check_vma"] = False
        elif "check_rep" in params:
            sm_kw["check_rep"] = False

    return _shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
        + tuple(spec for _, _, spec in extras),
        out_specs=P(),
        **sm_kw,
    )(bins, nodes, g, h, *[a for _, a, _ in extras])
