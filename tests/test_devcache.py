"""Device frame cache + dispatch plan cache — the PR 3 caching tentpole.

Acceptance (ISSUE 3): with the cache warm, a second identical map_reduce
dispatch records result="hit" with ZERO new XLA compiles, and a second
GLM/GBM fit on the same unmutated frame adds 0 to shard_bytes_total.
Mutation through rapids assign / as_factor / column append re-uploads;
KeyedStore remove/clear evict; the byte budget evicts LRU-first.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.compute.mapreduce import FrameTable, map_reduce
from h2o3_tpu.frame import devcache
from h2o3_tpu.frame.devcache import DEVCACHE, DeviceFrameCache, frame_token
from h2o3_tpu.keyed import DKV
from h2o3_tpu.util import telemetry

# models register themselves in the DKV; the module-level sweeper
# removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _counter(name, **labels):
    m = telemetry.REGISTRY.get(name)
    return m.value(**labels) if m is not None else 0.0


def _frame(rng, n=4000):
    return Frame.from_dict({
        "a": rng.normal(size=n),
        "b": rng.normal(size=n),
        "y": rng.normal(size=n),
    })


def _sum_a(cols, mask):
    # module-level fn: repeat dispatches share one plan-cache identity
    return jnp.sum(jnp.where(mask & ~jnp.isnan(cols["a"]), cols["a"], 0.0))


# ---------------------------------------------------------------------------
# version stamps


class TestVersionStamps:
    def test_invalidate_rollups_bumps_version(self):
        fr = Frame.from_dict({"x": [1.0, 2.0]})
        v0 = fr.col("x").version
        fr.col("x").invalidate_rollups()
        assert fr.col("x").version > v0
        assert fr.version == (fr.col("x").version,)

    def test_rapids_assign_changes_token(self):
        from h2o3_tpu.rapids import Session, exec_rapids

        s = Session()
        fr = Frame.from_dict({"a": [1.0, 2.0, 3.0], "b": [4.0, 5.0, 6.0]})
        s.assign("devc_fr", fr)
        t0 = frame_token(fr)
        out = exec_rapids("(:= devc_fr 99 [0] [0:2])", s).as_frame()
        assert frame_token(out) != t0
        assert frame_token(fr) == t0  # source frame untouched
        s.end()

    def test_as_factor_and_append_change_token(self):
        fr = Frame.from_dict({"a": [1.0, 2.0, 1.0], "b": [0.0, 1.0, 0.0]})
        t0 = frame_token(fr)
        fr2 = fr.add_column(fr.col("b").as_factor())
        assert frame_token(fr2) != t0
        from h2o3_tpu.frame.frame import Column, ColType

        fr3 = fr.add_column(Column("c", np.zeros(3), ColType.NUM))
        assert frame_token(fr3) != t0


# ---------------------------------------------------------------------------
# FrameTable placement cache + warm dispatch


class TestFrameTableCache:
    def test_from_frame_hit_is_same_table_no_upload(self, mesh, rng):
        fr = _frame(rng)
        before = _counter("shard_bytes_total")
        t1 = FrameTable.from_frame(fr, mesh=mesh)
        uploaded = _counter("shard_bytes_total") - before
        assert uploaded > 0
        t2 = FrameTable.from_frame(fr, mesh=mesh)
        assert t2 is t1
        assert _counter("shard_bytes_total") - before == uploaded  # no re-up
        # matrix() caches its stacked matrix on the (cached) table
        assert t1.matrix() is t1.matrix()

    def test_mutation_forces_reupload(self, mesh, rng):
        fr = _frame(rng)
        t1 = FrameTable.from_frame(fr, mesh=mesh)
        old_device_a = t1.arrays["a"]
        fr.col("a").data[0] = 123.0
        fr.col("a").invalidate_rollups()  # the mutating-path contract
        before = _counter("shard_bytes_total")
        t2 = FrameTable.from_frame(fr, mesh=mesh)
        assert t2 is not t1
        assert t2.arrays["a"] is not old_device_a
        assert _counter("shard_bytes_total") > before
        assert float(np.asarray(t2.arrays["a"])[0]) == 123.0

    def test_warm_dispatch_zero_recompiles(self, mesh, rng):
        """ISSUE acceptance: second identical dispatch -> plan + jit cache
        hits and a compile-listener delta of exactly zero."""
        telemetry.install_jax_compile_listener()
        fr = _frame(rng)
        t = FrameTable.from_frame(fr, mesh=mesh)
        cold = float(map_reduce(_sum_a, t))
        hits0 = _counter("mapreduce_jit_cache_total",
                         op="map_reduce", result="hit")
        plan0 = _counter("mapreduce_plan_cache_total",
                         op="map_reduce", result="hit")
        compiles0 = telemetry.thread_compile_count()
        warm = float(map_reduce(_sum_a, t))
        assert warm == cold
        assert telemetry.thread_compile_count() - compiles0 == 0
        assert _counter("mapreduce_jit_cache_total",
                        op="map_reduce", result="hit") == hits0 + 1
        assert _counter("mapreduce_plan_cache_total",
                        op="map_reduce", result="hit") == plan0 + 1

    def test_unknown_reduce_raises_value_error(self, mesh, rng):
        t = FrameTable.from_frame(_frame(rng), mesh=mesh)
        with pytest.raises(ValueError, match="valid choices.*max.*min.*sum"):
            map_reduce(_sum_a, t, reduce="bogus")


# ---------------------------------------------------------------------------
# model fits: second fit uploads nothing


class TestWarmFits:
    def test_second_glm_fit_adds_zero_shard_bytes(self, mesh, rng):
        from h2o3_tpu.models.glm import GLM

        fr = _frame(rng, n=1500)
        m1 = GLM(response_column="y", lambda_=0.0).train(fr)
        before = _counter("shard_bytes_total")
        m2 = GLM(response_column="y", lambda_=0.0).train(fr)
        assert _counter("shard_bytes_total") == before
        assert m1.coefficients == pytest.approx(m2.coefficients)
        # mutated frame re-uploads
        fr.col("a").invalidate_rollups()
        GLM(response_column="y", lambda_=0.0).train(fr)
        assert _counter("shard_bytes_total") > before

    def test_second_gbm_fit_hits_tree_bins_cache(self, mesh, rng):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng, n=800)
        GBM(response_column="y", ntrees=2, max_depth=3, seed=5).train(fr)
        hit0 = _counter("devcache_requests_total",
                        kind="tree_bins", result="hit")
        shard0 = _counter("shard_bytes_total")
        GBM(response_column="y", ntrees=2, max_depth=3, seed=5).train(fr)
        assert _counter("devcache_requests_total",
                        kind="tree_bins", result="hit") == hit0 + 1
        assert _counter("shard_bytes_total") == shard0


# ---------------------------------------------------------------------------
# lifecycle eviction + budget


class TestEviction:
    def test_dkv_remove_evicts_placements(self, mesh, rng):
        fr = _frame(rng)
        fr.key = "devc_evict.hex"
        DKV.put(fr.key, fr)
        FrameTable.from_frame(fr, mesh=mesh)
        token = frame_token(fr)
        assert any(k[1] == token for k in DEVCACHE._entries
                   if k[0] == "frame_table")
        ev0 = _counter("devcache_evictions_total", reason="invalidate")
        DKV.remove(fr.key)
        assert not any(k[1] == token for k in DEVCACHE._entries)
        assert _counter("devcache_evictions_total",
                        reason="invalidate") == ev0 + 1

    def test_rekey_evicts_old_registration(self, mesh, rng):
        fr = _frame(rng)
        fr.key = "devc_rekey.hex"
        DKV.put(fr.key, fr)
        FrameTable.from_frame(fr, mesh=mesh)
        token = frame_token(fr)
        DKV.rekey(fr, "devc_rekey2.hex")
        assert not any(k[1] == token for k in DEVCACHE._entries)
        DKV.remove("devc_rekey2.hex")

    def test_store_clear_empties_devcache(self, mesh, rng):
        # a scratch store, NOT the global DKV (clearing that mid-suite
        # would wipe persisted Jobs); KeyedStore.clear drops the whole
        # device tier regardless of which store instance nukes the world
        from h2o3_tpu.keyed import KeyedStore

        store = KeyedStore()
        fr = _frame(rng)
        store.put("devc_clear.hex", fr)
        FrameTable.from_frame(fr, mesh=mesh)
        assert len(DEVCACHE) > 0
        store.clear()
        assert len(DEVCACHE) == 0

    def test_budget_lru_eviction(self):
        cache = DeviceFrameCache(max_bytes=100)
        a = np.zeros(10, dtype=np.float64)  # 80 bytes
        b = np.ones(10, dtype=np.float64)
        c = np.full(10, 2.0)
        cache.get_or_put(("k1",), lambda: a, kind="test")
        cache.get_or_put(("k2",), lambda: b, kind="test")  # evicts k1 (LRU)
        assert ("k1",) not in cache._entries
        assert ("k2",) in cache._entries
        # touching k2 then inserting keeps k2 the newest... LRU is insertion
        # + access ordered: hit k2, insert k3 -> k2 evicted? no: k2 touched
        assert cache.get_or_put(("k2",), lambda: b, kind="test") is b
        cache.get_or_put(("k3",), lambda: c, kind="test")
        # over budget again: the oldest (k2) goes, newest (k3) stays
        assert ("k3",) in cache._entries
        assert cache.stats()["bytes"] <= 100 or len(cache._entries) == 1

    def test_single_oversized_entry_stays_usable(self):
        cache = DeviceFrameCache(max_bytes=8)
        big = np.zeros(100)
        assert cache.get_or_put(("big",), lambda: big, kind="test") is big
        assert cache.get_or_put(("big",), lambda: big, kind="test") is big

    def test_matrix_bytes_attributed_to_entry(self, mesh, rng):
        fr = _frame(rng)
        t = FrameTable.from_frame(fr, mesh=mesh)
        before = DEVCACHE.stats()["bytes"]
        m = t.matrix()
        # the stacked matrix on a cache-resident table must be visible to
        # the byte budget (review finding: silent undercount)
        assert DEVCACHE.stats()["bytes"] >= before + int(m.nbytes)
        t.matrix()  # cached: no double counting
        assert DEVCACHE.stats()["bytes"] < before + 2 * int(m.nbytes)

    def test_set_max_bytes_shrinks(self):
        cache = DeviceFrameCache(max_bytes=10_000)
        for i in range(4):
            cache.get_or_put((f"k{i}",), lambda: np.zeros(100), kind="test")
        cache.set_max_bytes(900)  # one 800-byte entry fits
        assert len(cache) == 1


# ---------------------------------------------------------------------------
# apply_bins vectorization (satellite)


class TestApplyBins:
    @staticmethod
    def _reference(X, edges):
        n, F = X.shape
        nbins = edges.shape[1] + 1
        out = np.empty((n, F), dtype=np.int32)
        for f in range(F):
            out[:, f] = np.searchsorted(edges[f], X[:, f], side="right")
            out[np.isnan(X[:, f]), f] = nbins
        return out

    def test_matches_reference_with_na_inf_ties(self, rng):
        from h2o3_tpu.ops.histogram import apply_bins, make_bins

        X = rng.normal(size=(3000, 6))
        X[:, 1] = rng.integers(0, 3, size=3000)  # low cardinality
        X[:, 2] = 1.5                            # constant
        X[::7, 3] = np.nan
        X[::11, 4] = np.inf
        X[::13, 4] = -np.inf
        X[::17, 5] = -0.0
        edges = make_bins(X, nbins=16)
        assert np.array_equal(apply_bins(X, edges), self._reference(X, edges))
        # values exactly on edges (tie semantics: side='right')
        Xe = np.repeat(edges[:6, 3:4].T, 5, axis=0)
        assert np.array_equal(apply_bins(Xe, edges[:6]),
                              self._reference(Xe, edges[:6]))

    def test_batched_wide_path_matches_reference(self, rng):
        from h2o3_tpu.ops.histogram import _apply_bins_batched, apply_bins

        X = rng.normal(size=(4, 200))  # wide-short: batched dispatch
        X[0, 5] = np.nan
        edges = np.sort(rng.normal(size=(200, 9)), axis=1)
        assert np.array_equal(apply_bins(X, edges),
                              self._reference(X, edges))
        raw = _apply_bins_batched(X, edges)
        want = self._reference(X, edges)
        want_no_na = want.copy()
        want_no_na[0, 5] = np.searchsorted(edges[5], np.nan, side="right")
        assert np.array_equal(raw, want_no_na)

    def test_empty_shapes(self):
        from h2o3_tpu.ops.histogram import apply_bins

        edges = np.array([[0.0, 1.0]])
        assert apply_bins(np.empty((0, 1)), edges).shape == (0, 1)
