"""GLM correctness vs sklearn/scipy oracles — the M3 end-to-end slice.

Reference analogue: hex/glm tests (GLMTest.java etc., SURVEY.md §4);
reference solver: hex/glm/GLM.java:1160 IRLSM."""

import numpy as np
import pytest
from sklearn.linear_model import LinearRegression, LogisticRegression, PoissonRegressor, Ridge

from h2o3_tpu import Frame
from h2o3_tpu.models.glm import GLM, GLMParameters


@pytest.fixture()
def lin_data(rng):
    n, p = 2000, 5
    X = rng.normal(size=(n, p))
    beta = np.array([1.5, -2.0, 0.5, 0.0, 3.0])
    y = X @ beta + 0.7 + rng.normal(0, 0.5, n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    return fr, X, y


def test_gaussian_matches_ols(mesh, lin_data):
    fr, X, y = lin_data
    m = GLM(family="gaussian", response_column="y", lambda_=0.0).train(fr)
    sk = LinearRegression().fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(5)])
    np.testing.assert_allclose(got, sk.coef_, atol=2e-4)
    assert m.coefficients["Intercept"] == pytest.approx(sk.intercept_, abs=2e-4)
    assert m.training_metrics.r2 > 0.9


def test_gaussian_ridge_matches_sklearn(mesh, lin_data):
    fr, X, y = lin_data
    lam = 0.1
    m = GLM(family="gaussian", response_column="y", lambda_=lam, alpha=0.0, standardize=False).train(fr)
    # sklearn Ridge penalizes sum b^2 * alpha; our objective: dev/(2N) + lam/2 |b|^2
    sk = Ridge(alpha=lam * len(y), fit_intercept=True).fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(5)])
    np.testing.assert_allclose(got, sk.coef_, atol=1e-3)


def test_binomial_matches_sklearn(mesh, rng):
    n, p = 3000, 4
    X = rng.normal(size=(n, p))
    beta = np.array([1.0, -1.5, 0.7, 2.0])
    logit = X @ beta - 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(p)} | {"y": np.where(y > 0, "yes", "no")}
    )
    m = GLM(family="binomial", response_column="y", lambda_=0.0).train(fr)
    sk = LogisticRegression(penalty=None, max_iter=500, tol=1e-10).fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(got, sk.coef_[0], atol=2e-3)
    assert m.coefficients["Intercept"] == pytest.approx(sk.intercept_[0], abs=2e-3)
    assert m.training_metrics.auc > 0.85
    # prediction frame shape: predict + two probability columns
    pred = m.predict(fr)
    assert pred.names == ["predict", "pno", "pyes"]
    p1 = pred.col("pyes").data
    sk_p = sk.predict_proba(X)[:, 1]
    np.testing.assert_allclose(p1, sk_p, atol=5e-3)


def test_poisson_matches_sklearn(mesh, rng):
    n, p = 2000, 3
    X = rng.normal(size=(n, p)) * 0.5
    mu = np.exp(X @ np.array([0.5, -0.3, 0.8]) + 1.0)
    y = rng.poisson(mu).astype(np.float64)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    m = GLM(family="poisson", response_column="y", lambda_=0.0).train(fr)
    sk = PoissonRegressor(alpha=0.0, max_iter=500, tol=1e-10).fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(got, sk.coef_, atol=1e-3)


def test_lasso_sparsifies(mesh, rng):
    n = 1500
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2.0 + X[:, 1] * -1.0 + rng.normal(0, 0.3, n)  # x2..x5 irrelevant
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(6)} | {"y": y})
    m = GLM(family="gaussian", response_column="y", lambda_=0.1, alpha=1.0).train(fr)
    coefs = np.array([m.coefficients_std[f"x{i}"] for i in range(6)])
    assert np.sum(np.abs(coefs[2:]) < 1e-8) >= 3, f"L1 should zero noise coefs, got {coefs}"
    assert abs(coefs[0]) > 0.5


def test_categorical_predictors(mesh, rng):
    n = 2000
    g = rng.integers(0, 3, n)
    x = rng.normal(size=n)
    effect = np.array([0.0, 1.0, -2.0])
    y = 2.0 * x + effect[g] + rng.normal(0, 0.3, n)
    fr = Frame.from_dict({"x": x, "g": np.array(["a", "b", "c"])[g], "y": y})
    m = GLM(family="gaussian", response_column="y", lambda_=0.0).train(fr)
    # one-hot with first level dropped: coefs for g.b, g.c relative to a
    assert m.coefficients["g.b"] == pytest.approx(1.0, abs=0.1)
    assert m.coefficients["g.c"] == pytest.approx(-2.0, abs=0.1)
    assert m.coefficients["x"] == pytest.approx(2.0, abs=0.05)


def test_weights_and_offset(mesh, rng):
    n = 1000
    x = rng.normal(size=n)
    y = 3.0 * x + 1.0 + rng.normal(0, 0.5, n)
    w = rng.random(n) + 0.5
    fr = Frame.from_dict({"x": x, "y": y, "w": w})
    m = GLM(family="gaussian", response_column="y", weights_column="w", lambda_=0.0).train(fr)
    sk = LinearRegression().fit(x[:, None], y, sample_weight=w)
    assert m.coefficients["x"] == pytest.approx(sk.coef_[0], abs=1e-3)


def test_p_values(mesh, rng):
    n = 500
    X = rng.normal(size=(n, 3))
    y = X @ np.array([2.0, 0.0, 1.0]) + rng.normal(0, 1.0, n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = GLM(
        family="gaussian", response_column="y", lambda_=0.0, compute_p_values=True, standardize=False
    ).train(fr)
    assert m.p_values["x0"] < 1e-6  # strong effect
    assert m.p_values["x1"] > 0.01  # null effect


def test_cross_validation(mesh, rng):
    n = 1200
    X = rng.normal(size=(n, 3))
    logit = X @ np.array([1.0, -1.0, 0.5])
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": np.where(y > 0, "p", "n")})
    m = GLM(family="binomial", response_column="y", nfolds=3, seed=7).train(fr)
    assert m.cross_validation_metrics is not None
    assert m.cross_validation_metrics.auc > 0.7
    assert len(m.cv_models) == 3


def test_validation_errors(mesh):
    fr = Frame.from_dict({"x": [1.0, 2.0], "y": [0.0, 1.0]})
    with pytest.raises(ValueError, match="response_column"):
        GLM(family="gaussian", response_column="nope").train(fr)
    with pytest.raises(ValueError, match="family"):
        GLM(family="bogus", response_column="y").train(fr)
    with pytest.raises(ValueError, match="alpha"):
        GLM(family="gaussian", response_column="y", alpha=2.0).train(fr)


def test_binomial_numeric_response_autoconverts(mesh, rng):
    """Regression: numeric 0/1 response + binomial family (review finding)."""
    n = 800
    x = rng.normal(size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-2 * x))).astype(np.float64)
    fr = Frame.from_dict({"x": x, "y": y})
    m = GLM(family="binomial", response_column="y", lambda_=0.0).train(fr)
    assert m.is_classifier and m.training_metrics.auc > 0.7


def test_no_intercept_solution(mesh, rng):
    """Regression: intercept=False must exclude the ones column (review finding)."""
    n = 1000
    x = rng.normal(size=n) + 1.0
    y = x + 5.0 + rng.normal(0, 0.1, n)
    fr = Frame.from_dict({"x": x, "y": y})
    m = GLM(family="gaussian", response_column="y", intercept=False, standardize=False).train(fr)
    want = float((x * y).sum() / (x * x).sum())  # closed-form no-intercept OLS
    assert m.coefficients["x"] == pytest.approx(want, rel=1e-4)
    assert m.coefficients["Intercept"] == 0.0
