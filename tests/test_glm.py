"""GLM correctness vs sklearn/scipy oracles — the M3 end-to-end slice.

Reference analogue: hex/glm tests (GLMTest.java etc., SURVEY.md §4);
reference solver: hex/glm/GLM.java:1160 IRLSM."""

import numpy as np
import pytest
from sklearn.linear_model import LinearRegression, LogisticRegression, PoissonRegressor, Ridge

from h2o3_tpu import Frame
from h2o3_tpu.models.glm import GLM, GLMParameters


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture()
def lin_data(rng):
    n, p = 2000, 5
    X = rng.normal(size=(n, p))
    beta = np.array([1.5, -2.0, 0.5, 0.0, 3.0])
    y = X @ beta + 0.7 + rng.normal(0, 0.5, n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    return fr, X, y


def test_gaussian_matches_ols(mesh, lin_data):
    fr, X, y = lin_data
    m = GLM(family="gaussian", response_column="y", lambda_=0.0).train(fr)
    sk = LinearRegression().fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(5)])
    np.testing.assert_allclose(got, sk.coef_, atol=2e-4)
    assert m.coefficients["Intercept"] == pytest.approx(sk.intercept_, abs=2e-4)
    assert m.training_metrics.r2 > 0.9


def test_gaussian_ridge_matches_sklearn(mesh, lin_data):
    fr, X, y = lin_data
    lam = 0.1
    m = GLM(family="gaussian", response_column="y", lambda_=lam, alpha=0.0, standardize=False).train(fr)
    # sklearn Ridge penalizes sum b^2 * alpha; our objective: dev/(2N) + lam/2 |b|^2
    sk = Ridge(alpha=lam * len(y), fit_intercept=True).fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(5)])
    np.testing.assert_allclose(got, sk.coef_, atol=1e-3)


def test_binomial_matches_sklearn(mesh, rng):
    n, p = 3000, 4
    X = rng.normal(size=(n, p))
    beta = np.array([1.0, -1.5, 0.7, 2.0])
    logit = X @ beta - 0.3
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(p)} | {"y": np.where(y > 0, "yes", "no")}
    )
    m = GLM(family="binomial", response_column="y", lambda_=0.0).train(fr)
    sk = LogisticRegression(penalty=None, max_iter=500, tol=1e-10).fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(got, sk.coef_[0], atol=2e-3)
    assert m.coefficients["Intercept"] == pytest.approx(sk.intercept_[0], abs=2e-3)
    assert m.training_metrics.auc > 0.85
    # prediction frame shape: predict + two probability columns
    pred = m.predict(fr)
    assert pred.names == ["predict", "pno", "pyes"]
    p1 = pred.col("pyes").data
    sk_p = sk.predict_proba(X)[:, 1]
    np.testing.assert_allclose(p1, sk_p, atol=5e-3)


def test_poisson_matches_sklearn(mesh, rng):
    n, p = 2000, 3
    X = rng.normal(size=(n, p)) * 0.5
    mu = np.exp(X @ np.array([0.5, -0.3, 0.8]) + 1.0)
    y = rng.poisson(mu).astype(np.float64)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    m = GLM(family="poisson", response_column="y", lambda_=0.0).train(fr)
    sk = PoissonRegressor(alpha=0.0, max_iter=500, tol=1e-10).fit(X, y)
    got = np.array([m.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(got, sk.coef_, atol=1e-3)


def test_lasso_sparsifies(mesh, rng):
    n = 1500
    X = rng.normal(size=(n, 6))
    y = X[:, 0] * 2.0 + X[:, 1] * -1.0 + rng.normal(0, 0.3, n)  # x2..x5 irrelevant
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(6)} | {"y": y})
    m = GLM(family="gaussian", response_column="y", lambda_=0.1, alpha=1.0).train(fr)
    coefs = np.array([m.coefficients_std[f"x{i}"] for i in range(6)])
    assert np.sum(np.abs(coefs[2:]) < 1e-8) >= 3, f"L1 should zero noise coefs, got {coefs}"
    assert abs(coefs[0]) > 0.5


def test_categorical_predictors(mesh, rng):
    n = 2000
    g = rng.integers(0, 3, n)
    x = rng.normal(size=n)
    effect = np.array([0.0, 1.0, -2.0])
    y = 2.0 * x + effect[g] + rng.normal(0, 0.3, n)
    fr = Frame.from_dict({"x": x, "g": np.array(["a", "b", "c"])[g], "y": y})
    m = GLM(family="gaussian", response_column="y", lambda_=0.0).train(fr)
    # one-hot with first level dropped: coefs for g.b, g.c relative to a
    assert m.coefficients["g.b"] == pytest.approx(1.0, abs=0.1)
    assert m.coefficients["g.c"] == pytest.approx(-2.0, abs=0.1)
    assert m.coefficients["x"] == pytest.approx(2.0, abs=0.05)


def test_weights_and_offset(mesh, rng):
    n = 1000
    x = rng.normal(size=n)
    y = 3.0 * x + 1.0 + rng.normal(0, 0.5, n)
    w = rng.random(n) + 0.5
    fr = Frame.from_dict({"x": x, "y": y, "w": w})
    m = GLM(family="gaussian", response_column="y", weights_column="w", lambda_=0.0).train(fr)
    sk = LinearRegression().fit(x[:, None], y, sample_weight=w)
    assert m.coefficients["x"] == pytest.approx(sk.coef_[0], abs=1e-3)


def test_p_values(mesh, rng):
    n = 500
    X = rng.normal(size=(n, 3))
    y = X @ np.array([2.0, 0.0, 1.0]) + rng.normal(0, 1.0, n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = GLM(
        family="gaussian", response_column="y", lambda_=0.0, compute_p_values=True, standardize=False
    ).train(fr)
    assert m.p_values["x0"] < 1e-6  # strong effect
    assert m.p_values["x1"] > 0.01  # null effect


def test_cross_validation(mesh, rng):
    n = 1200
    X = rng.normal(size=(n, 3))
    logit = X @ np.array([1.0, -1.0, 0.5])
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": np.where(y > 0, "p", "n")})
    m = GLM(family="binomial", response_column="y", nfolds=3, seed=7).train(fr)
    assert m.cross_validation_metrics is not None
    assert m.cross_validation_metrics.auc > 0.7
    assert len(m.cv_models) == 3


def test_validation_errors(mesh):
    fr = Frame.from_dict({"x": [1.0, 2.0], "y": [0.0, 1.0]})
    with pytest.raises(ValueError, match="response_column"):
        GLM(family="gaussian", response_column="nope").train(fr)
    with pytest.raises(ValueError, match="family"):
        GLM(family="bogus", response_column="y").train(fr)
    with pytest.raises(ValueError, match="alpha"):
        GLM(family="gaussian", response_column="y", alpha=2.0).train(fr)


def test_binomial_numeric_response_autoconverts(mesh, rng):
    """Regression: numeric 0/1 response + binomial family (review finding)."""
    n = 800
    x = rng.normal(size=n)
    y = (rng.random(n) < 1 / (1 + np.exp(-2 * x))).astype(np.float64)
    fr = Frame.from_dict({"x": x, "y": y})
    m = GLM(family="binomial", response_column="y", lambda_=0.0).train(fr)
    assert m.is_classifier and m.training_metrics.auc > 0.7


def test_no_intercept_solution(mesh, rng):
    """Regression: intercept=False must exclude the ones column (review finding)."""
    n = 1000
    x = rng.normal(size=n) + 1.0
    y = x + 5.0 + rng.normal(0, 0.1, n)
    fr = Frame.from_dict({"x": x, "y": y})
    m = GLM(family="gaussian", response_column="y", intercept=False, standardize=False).train(fr)
    want = float((x * y).sum() / (x * x).sum())  # closed-form no-intercept OLS
    assert m.coefficients["x"] == pytest.approx(want, rel=1e-4)
    assert m.coefficients["Intercept"] == 0.0


# ---------------------------------------------------------------------------
# round 3: multinomial / ordinal / lambda_search / lbfgs
# (reference: hex/glm/GLM.java:1160 multinomial IRLSM, :1632 lambda search,
#  GLMModel.java:268-334 solver enum)


@pytest.fixture()
def iris_like(rng):
    """3-class separable-ish data shaped like iris."""
    n_per, p = 300, 4
    centers = np.array([
        [0.0, 0.0, 0.0, 0.0],
        [2.0, 1.0, -1.0, 0.5],
        [-1.0, 2.5, 1.0, -1.5],
    ])
    X = np.concatenate([rng.normal(size=(n_per, p)) + c for c in centers])
    y = np.repeat(np.array(["setosa", "versi", "virgi"]), n_per)
    perm = rng.permutation(len(y))
    X, y = X[perm], y[perm]
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    return fr, X, y


def test_multinomial_matches_sklearn(mesh, iris_like):
    fr, X, y = iris_like
    m = GLM(family="multinomial", response_column="y", lambda_=0.0).train(fr)
    sk = LogisticRegression(penalty=None, max_iter=1000, tol=1e-10).fit(X, y)
    ours = m._predict_raw(fr)
    theirs = sk.predict_proba(X)
    # probabilities agree (coefs are only identified up to a per-row shift)
    np.testing.assert_allclose(ours, theirs, atol=0.01)
    acc_ours = (np.array(sorted(set(y)))[ours.argmax(1)] == y).mean()
    acc_sk = (sk.predict(X) == y).mean()
    assert acc_ours >= acc_sk - 0.01
    assert m.training_metrics.logloss < 0.5
    assert m.residual_deviance < m.null_deviance
    # per-class coefficient tables exposed
    assert set(m.coefficients_multinomial) == {"setosa", "versi", "virgi"}


def test_multinomial_regularized_and_predict_frame(mesh, iris_like):
    fr, X, y = iris_like
    m = GLM(family="multinomial", response_column="y", lambda_=0.01, alpha=0.5).train(fr)
    pred = m.predict(fr)
    assert pred.names[0] == "predict"
    probs = np.stack([pred.col(f"p{lv}").numeric_view() for lv in sorted(set(y))], axis=1)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)


def test_ordinal_recovers_ordering(mesh, rng):
    """Proportional-odds data: P(y<=k) = sigmoid(t_k - x.beta)."""
    n, p = 3000, 3
    X = rng.normal(size=(n, p))
    beta = np.array([1.0, -0.5, 2.0])
    eta = X @ beta
    t = np.array([-1.0, 1.5])
    u = rng.random(n)
    c0 = 1 / (1 + np.exp(-(t[0] - eta)))
    c1 = 1 / (1 + np.exp(-(t[1] - eta)))
    y = np.where(u < c0, "low", np.where(u < c1, "mid", "high"))
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    # domain order must be the ordinal order
    from h2o3_tpu.frame.frame import Column, ColType
    codes = np.array([{"low": 0, "mid": 1, "high": 2}[v] for v in y], dtype=np.int32)
    fr = fr.add_column(Column("y", codes, ColType.CAT, ["low", "mid", "high"]))
    m = GLM(family="ordinal", response_column="y", lambda_=0.0, standardize=False).train(fr)
    got_beta = np.array([m.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(got_beta, beta, atol=0.15)
    assert m.ordinal_thresholds[0] < m.ordinal_thresholds[1]
    np.testing.assert_allclose(m.ordinal_thresholds, t, atol=0.2)
    probs = m._predict_raw(fr)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)
    acc = (probs.argmax(1) == codes).mean()
    assert acc > 0.6


def test_lambda_search_path(mesh, rng):
    n, p = 1000, 8
    X = rng.normal(size=(n, p))
    beta = np.array([2.0, -1.5, 1.0, 0.0, 0.0, 0.0, 0.0, 0.0])
    y = X @ beta + rng.normal(0, 0.5, n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    m = GLM(
        family="gaussian", response_column="y", lambda_search=True, nlambdas=12,
        alpha=1.0,
    ).train(fr)
    assert m.lambda_path is not None and len(m.lambda_path) == 12
    lams = [e["lambda"] for e in m.lambda_path]
    assert lams == sorted(lams, reverse=True)
    # sparsity decreases along the path; the largest lambda kills every coef
    nz = [e["nonzeros"] for e in m.lambda_path]
    assert nz[0] <= 1 and nz[-1] >= 3
    assert m.lambda_best == lams[-1]  # training-deviance selection -> smallest
    # the selected model recovers the signal
    got = np.array([m.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(got[:3], beta[:3], atol=0.1)


def test_lambda_search_validation_selection(mesh, rng):
    n, p = 600, 20
    X = rng.normal(size=(n, p))
    beta = np.zeros(p)
    beta[:2] = [1.0, -1.0]
    y = X @ beta + rng.normal(0, 2.0, n)  # noisy: heavy shrinkage should win
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(p)} | {"y": y})
    tr = fr.rows(np.arange(n) < 400)
    va = fr.rows(np.arange(n) >= 400)
    m = GLM(
        family="gaussian", response_column="y", lambda_search=True, nlambdas=15,
        alpha=1.0,
    ).train(tr, valid=va)
    assert all("deviance_valid" in e for e in m.lambda_path)
    best = min(m.lambda_path, key=lambda e: e["deviance_valid"])
    assert m.lambda_best == best["lambda"]


def test_lbfgs_matches_irlsm(mesh, rng):
    n, p = 2000, 5
    X = rng.normal(size=(n, p))
    beta = np.array([1.0, -1.0, 0.5, 0.0, 1.5])
    yb = (rng.random(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(np.float64)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(p)} | {"y": np.where(yb > 0, "y", "n")}
    )
    m1 = GLM(family="binomial", response_column="y", lambda_=0.01, alpha=0.0,
             solver="irlsm").train(fr)
    m2 = GLM(family="binomial", response_column="y", lambda_=0.01, alpha=0.0,
             solver="lbfgs").train(fr)
    c1 = np.array([m1.coefficients[f"x{i}"] for i in range(p)])
    c2 = np.array([m2.coefficients[f"x{i}"] for i in range(p)])
    np.testing.assert_allclose(c1, c2, atol=5e-3)


def test_lbfgs_rejects_l1(mesh, lin_data):
    fr, _, _ = lin_data
    with pytest.raises(ValueError, match="lbfgs"):
        GLM(family="gaussian", response_column="y", solver="lbfgs",
            lambda_=0.1, alpha=0.5).train(fr)


def test_multinomial_lambda_search(mesh, iris_like):
    fr, X, y = iris_like
    m = GLM(family="multinomial", response_column="y", lambda_search=True,
            nlambdas=5, alpha=0.5).train(fr)
    assert len(m.lambda_path) == 5
    assert m.training_metrics.logloss < 1.0


def test_multinomial_lbfgs_matches_irlsm(mesh, iris_like):
    fr, X, y = iris_like
    m1 = GLM(family="multinomial", response_column="y", lambda_=0.01, alpha=0.0,
             solver="irlsm").train(fr)
    m2 = GLM(family="multinomial", response_column="y", lambda_=0.01, alpha=0.0,
             solver="lbfgs").train(fr)
    np.testing.assert_allclose(m1._predict_raw(fr), m2._predict_raw(fr), atol=0.01)


def test_lbfgs_rejects_noncanonical_link(mesh, lin_data):
    fr, _, _ = lin_data
    with pytest.raises(ValueError, match="canonical"):
        GLM(family="gaussian", link="log", response_column="y",
            solver="lbfgs").train(fr)


def test_multinomial_rejects_offset(mesh, iris_like):
    fr, X, y = iris_like
    from h2o3_tpu.frame.frame import Column, ColType
    fr = fr.add_column(Column("off", np.ones(fr.nrows), ColType.NUM))
    with pytest.raises(ValueError, match="offset"):
        GLM(family="multinomial", response_column="y", offset_column="off").train(fr)


def test_ordinal_rejects_lambda_search_and_irlsm(mesh, rng):
    fr = Frame.from_dict({"x0": rng.normal(size=50),
                          "y": np.where(rng.random(50) > 0.5, "a", "b")})
    with pytest.raises(ValueError, match="lambda_search"):
        GLM(family="ordinal", response_column="y", lambda_search=True).train(fr)
    with pytest.raises(ValueError, match="gradient solver"):
        GLM(family="ordinal", response_column="y", solver="irlsm").train(fr)
    with pytest.raises(ValueError, match="p_values"):
        GLM(family="multinomial", response_column="y", compute_p_values=True).train(fr)
