"""Grid search (hex/grid/GridSearch.java) and segment models (hex/segments/)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.glm import GLM, GLMParameters
from h2o3_tpu.models.grid import Grid, GridSearch, SearchCriteria, metric_value
from h2o3_tpu.models.segments import SegmentModelsBuilder


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def _binomial_frame(rng, n=600):
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.normal(size=n) * 0.5 > 0).astype(np.int32)
    cols = [Column(f"x{i}", X[:, i]) for i in range(3)]
    cols.append(Column("y", y, ColType.CAT, ["0", "1"]))
    return Frame(cols)


class TestGridSearch:
    def test_cartesian_covers_product(self, rng):
        fr = _binomial_frame(rng)
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"alpha": [0.0, 0.5, 1.0], "lambda_": [0.0, 0.01]},
        )
        grid = gs.train(fr)
        assert len(grid.models) + len(grid.failures) == 6
        assert len(grid.models) == 6
        combos = {(h["alpha"], h["lambda_"]) for h in grid.hyper_params}
        assert len(combos) == 6

    def test_sorted_leaderboard(self, rng):
        fr = _binomial_frame(rng)
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"lambda_": [0.0, 0.5, 5.0]},
        )
        g = gs.train(fr).get_grid(sort_by="auc")
        aucs = [metric_value(m, "auc")[0] for m in g.models]
        assert aucs == sorted(aucs, reverse=True)
        # heavy shrinkage must hurt AUC
        assert g.hyper_params[0]["lambda_"] < 5.0

    def test_random_discrete_max_models_and_seed(self, rng):
        fr = _binomial_frame(rng)
        crit = SearchCriteria(strategy="RandomDiscrete", max_models=4, seed=7)
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"alpha": [0.0, 0.25, 0.5, 0.75, 1.0], "lambda_": [0.0, 0.01, 0.1]},
            search_criteria=crit,
        )
        g1 = gs.train(fr)
        assert len(g1.models) == 4
        g2 = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"alpha": [0.0, 0.25, 0.5, 0.75, 1.0], "lambda_": [0.0, 0.01, 0.1]},
            search_criteria=crit,
        ).train(fr)
        assert g1.hyper_params == g2.hyper_params  # seeded order reproducible

    def test_failures_recorded_not_fatal(self, rng):
        fr = _binomial_frame(rng)
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"alpha": [0.5, -123.0]},  # invalid alpha -> failure
        )
        grid = gs.train(fr)
        assert len(grid.models) + len(grid.failures) == 2
        assert len(grid.failures) >= 1

    def test_unknown_hyperparam_rejected(self):
        with pytest.raises(ValueError, match="unknown hyperparameter"):
            GridSearch(GLM, GLMParameters(), {"nope": [1]})

    def test_save_load_roundtrip(self, rng, tmp_path):
        fr = _binomial_frame(rng)
        grid = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"lambda_": [0.0, 0.1]},
        ).train(fr)
        p = str(tmp_path / "grid.bin")
        grid.save(p)
        g2 = Grid.load(p)
        assert g2.model_ids == grid.model_ids
        assert len(g2.models) == 2
        # loaded models still score
        assert g2.models[0].predict(fr).nrows == fr.nrows

    def test_parallel_matches_serial(self, rng):
        fr = _binomial_frame(rng)
        hp = {"lambda_": [0.0, 0.01, 0.1, 1.0]}
        base = GLMParameters(response_column="y", family="binomial")
        serial = GridSearch(GLM, base, hp).train(fr)
        par = GridSearch(GLM, base, hp, parallelism=4).train(fr)
        a = sorted(metric_value(m, "auc")[0] for m in serial.models)
        b = sorted(metric_value(m, "auc")[0] for m in par.models)
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestSegmentModels:
    def test_per_segment_models(self, rng):
        n = 900
        seg = rng.integers(0, 3, size=n)
        x = rng.normal(size=n)
        # different slope per segment
        y = x * np.array([1.0, -2.0, 0.5])[seg] + rng.normal(size=n) * 0.1
        fr = Frame(
            [
                Column("g", seg.astype(np.int32), ColType.CAT, ["a", "b", "c"]),
                Column("x", x),
                Column("y", y),
            ]
        )
        sb = SegmentModelsBuilder(
            GLM,
            GLMParameters(response_column="y", family="gaussian", lambda_=0.0),
            segment_columns=["g"],
        )
        sm = sb.train(fr)
        assert len(sm.segments) == 3
        assert all(e is None for e in sm.errors)
        slopes = {
            s["g"]: sm.model_for(g=s["g"]).coefficients["x"] for s in sm.segments
        }
        assert abs(slopes["a"] - 1.0) < 0.05
        assert abs(slopes["b"] + 2.0) < 0.05
        assert abs(slopes["c"] - 0.5) < 0.05

    def test_results_frame(self, rng):
        n = 300
        seg = rng.integers(0, 2, size=n)
        x = rng.normal(size=n)
        y = x + rng.normal(size=n) * 0.1
        fr = Frame(
            [
                Column("g", seg.astype(np.int32), ColType.CAT, ["u", "v"]),
                Column("x", x),
                Column("y", y),
            ]
        )
        sm = SegmentModelsBuilder(
            GLM, GLMParameters(response_column="y"), segment_columns=["g"]
        ).train(fr)
        out = sm.as_frame()
        assert out.nrows == 2
        assert set(out.names) == {"g", "status", "model", "errors"}
        st = out.col("status")
        assert all(st.domain[v] == "succeeded" for v in st.data)


class TestGridSegmentsReviewFixes:
    def test_parallel_minimize_metric_does_not_stop_while_improving(self, rng):
        n = 400
        x = rng.normal(size=n)
        y = 2.0 * x + rng.normal(size=n) * 0.1
        fr = Frame([Column("x", x), Column("y", y)])
        # lambdas from heavy to none: rmse strictly improves
        hp = {"lambda_": [1.0, 0.3, 0.1, 0.03, 0.0]}
        crit = SearchCriteria(stopping_rounds=1, stopping_tolerance=1e-3)
        grid = GridSearch(
            GLM, GLMParameters(response_column="y"), hp,
            search_criteria=crit, parallelism=2,
        ).train(fr)
        # with the direction bug this stopped after 2 models
        assert len(grid.models) == 5

    def test_segment_nan_numeric_column(self, rng):
        n = 200
        seg = rng.integers(0, 2, size=n).astype(np.float64)
        seg[:30] = np.nan
        x = rng.normal(size=n)
        y = x * np.where(np.nan_to_num(seg, nan=2.0) == 0, 1.0, -1.0)
        fr = Frame([Column("g", seg), Column("x", x), Column("y", y)])
        sm = SegmentModelsBuilder(
            GLM, GLMParameters(response_column="y"), segment_columns=["g"]
        ).train(fr)
        # NaN rows form ONE segment, not one per row
        assert len(sm.segments) == 3
        assert sum(s["g"] is None for s in sm.segments) == 1
        assert all(e is None for e in sm.errors)

    def test_grid_export_is_not_pickle(self, rng, tmp_path):
        """Grid.save uses the allowlisted zip format, never pickle
        (round-1/2 ADVICE item; pickle loads arbitrary code)."""
        import zipfile

        fr = _binomial_frame(rng)
        grid = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"lambda_": [0.0]},
        ).train(fr)
        p = str(tmp_path / "grid.bin")
        grid.save(p)
        assert zipfile.is_zipfile(p)
        with zipfile.ZipFile(p) as z:
            assert {"meta.json", "model.json", "arrays.npz"} <= set(z.namelist())

    def test_no_pickle_anywhere_in_package(self):
        """No `import pickle` in the product package (tests may use it)."""
        import pathlib

        import h2o3_tpu

        root = pathlib.Path(h2o3_tpu.__file__).parent
        offenders = [
            str(f)
            for f in root.rglob("*.py")
            if any(
                line.strip().startswith(("import pickle", "from pickle"))
                for line in f.read_text().splitlines()
            )
        ]
        assert offenders == []
