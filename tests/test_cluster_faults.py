"""Chaos plane, in-process tier: the deterministic FaultPlan, the RPC
consult points it drives, the retry ladder's full-jitter backoff, DKV
read-repair through a dead home, the replica anti-entropy sweep's
reap-vs-restore disambiguation, survivor rescheduling of a partitioned
member's fan-out ranges, and the test-only nemesis RPC/REST surface.

Everything runs multiple Cloud instances inside ONE process over real
loopback sockets (same machinery as test_cluster.py); the multi-process
chaos drills live in scripts/chaos.py and tests/test_chaos.py.
"""

import json
import socket
import time

import numpy as np
import pytest

from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import faults
from h2o3_tpu.cluster import rpc as crpc
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster.faults import FaultPlan, FaultRule, plan_from_dict
from h2o3_tpu.cluster.membership import Cloud
from h2o3_tpu.keyed import KeyedStore
from h2o3_tpu.util import telemetry


def _mr_stat(cols, mask):
    """Module-level map fn: crosses the RPC wire by module reference."""
    import jax.numpy as jnp

    return {
        "s": jnp.sum(jnp.where(mask, cols["x"], 0.0)),
        "n": jnp.sum(mask.astype(jnp.float32)),
    }


def _wait_for(cond, timeout=10.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


def _counter_total(name):
    m = telemetry.REGISTRY.get(name)
    return 0.0 if m is None else m.total()


def _counter_value(name, **labels):
    m = telemetry.REGISTRY.get(name)
    return 0.0 if m is None else m.value(**labels)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear_plan()
    yield
    faults.clear_plan()


# ---------------------------------------------------------------------------
# FaultPlan unit tier: matching, windows, determinism, JSON shape


class TestFaultPlan:
    def test_first_match_wins_and_globs(self):
        plan = FaultPlan(seed=1, rules=[
            FaultRule(action="delay", method="dkv_*", delay_ms=5.0),
            FaultRule(action="drop", method="*"),
        ])
        d = plan.consult("client", "node-a", "h:1", "dkv_get")
        assert d is not None and d.action == "delay"
        assert d.delay_s == pytest.approx(0.005)
        # the catch-all never sees dkv_* traffic (first match won) but
        # does see everything else
        assert plan.consult("client", "node-a", "h:1", "echo").action == "drop"
        assert plan.hits() == [1, 1]

    def test_side_and_endpoint_matching(self):
        plan = FaultPlan(seed=0, rules=[
            FaultRule(action="drop", side="server", src="node-b"),
            FaultRule(action="partition", src="node-a", dst="*:9999"),
        ])
        assert plan.consult("server", "node-a", "", "m") is None
        assert plan.consult("server", "node-b", "", "m").action == "drop"
        assert plan.consult("client", "node-a", "h:1234", "m") is None
        assert plan.consult(
            "client", "node-a", "h:9999", "m").action == "partition"

    def test_after_and_max_hits_windows(self):
        plan = FaultPlan(seed=0, rules=[
            FaultRule(action="drop", after=2, max_hits=3),
        ])
        fired = [plan.consult("client", "n", "d", "m") is not None
                 for _ in range(8)]
        # skips matches 1-2, injects on 3-5, exhausted afterwards
        assert fired == [False, False, True, True, True, False, False, False]
        assert plan.hits() == [3]

    def test_probabilistic_rules_replay_under_seed(self):
        def run(seed):
            plan = FaultPlan(seed=seed, rules=[
                FaultRule(action="drop", p=0.5),
            ])
            return [plan.consult("client", "n", "d", "m") is not None
                    for _ in range(64)]

        a, b = run(7), run(7)
        assert a == b  # same seed -> identical injection schedule
        assert run(8) != a  # and the seed actually matters
        assert 8 < sum(a) < 56  # p=0.5 really is probabilistic

    def test_reorder_sampled_delay_replays_and_bounds(self):
        def draws(seed):
            plan = FaultPlan(seed=seed, rules=[
                FaultRule(action="reorder", delay_ms=20.0),
            ])
            return [plan.consult("client", "n", "d", "m").delay_s
                    for _ in range(16)]

        a = draws(3)
        assert a == draws(3)
        assert all(0.0 <= d <= 0.020 for d in a)
        assert len(set(a)) > 8  # a spread, not a constant

    def test_per_rule_prng_isolated_from_other_rules(self):
        # rule 1's draws depend only on (seed, index) and its own match
        # ordinal — traffic hitting rule 0 must not perturb them
        mk = lambda: FaultPlan(seed=9, rules=[
            FaultRule(action="drop", method="noise", p=0.5),
            FaultRule(action="reorder", method="probe", delay_ms=10.0),
        ])
        quiet = mk()
        probe_only = [quiet.consult("client", "n", "d", "probe").delay_s
                      for _ in range(8)]
        noisy = mk()
        for _ in range(50):
            noisy.consult("client", "n", "d", "noise")
        with_noise = [noisy.consult("client", "n", "d", "probe").delay_s
                      for _ in range(8)]
        assert probe_only == with_noise

    def test_plan_from_dict_roundtrip_and_unknown_fields(self):
        d = {"seed": 5, "rules": [
            {"action": "delay", "method": "dkv_put", "delay_ms": 2.0,
             "added_in_a_newer_nemesis": True},
        ]}
        plan = plan_from_dict(d)
        assert plan.seed == 5 and len(plan.rules) == 1
        assert plan.rules[0].method == "dkv_put"
        back = plan.to_dict()
        assert back["seed"] == 5
        assert back["rules"][0]["action"] == "delay"
        assert "added_in_a_newer_nemesis" not in back["rules"][0]

    def test_rule_validation(self):
        with pytest.raises(ValueError, match="unknown fault action"):
            FaultRule(action="explode")
        with pytest.raises(ValueError, match="unknown fault side"):
            FaultRule(action="drop", side="middle")

    def test_install_from_env_inline_and_path(self, monkeypatch, tmp_path):
        spec = {"seed": 11, "rules": [{"action": "drop", "method": "x"}]}
        monkeypatch.setenv("H2O3_TPU_FAULT_PLAN", json.dumps(spec))
        assert faults.surface_enabled()
        plan = faults.install_from_env()
        assert plan is faults.active_plan() and plan.seed == 11
        faults.clear_plan()

        p = tmp_path / "plan.json"
        p.write_text(json.dumps(spec))
        monkeypatch.setenv("H2O3_TPU_FAULT_PLAN", f"@{p}")
        plan = faults.install_from_env()
        assert plan.seed == 11 and len(plan.rules) == 1

        monkeypatch.delenv("H2O3_TPU_FAULT_PLAN")
        faults.clear_plan()
        assert faults.install_from_env() is None
        assert faults.active_plan() is None


# ---------------------------------------------------------------------------
# retry ladder: full-jitter backoff spread + seeded replay


class TestBackoffJitter:
    def _closed_port(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def test_full_jitter_spread_and_seeded_replay(self, monkeypatch):
        samples = []
        monkeypatch.setattr(crpc.time, "sleep", samples.append)
        addr = ("127.0.0.1", self._closed_port())

        def run():
            samples.clear()
            faults.set_plan(FaultPlan(seed=42))  # seeds the jitter source
            client = crpc.RpcClient(retries=6, backoff_base=0.01,
                                    backoff_max=0.04, node_name="jitter")
            with pytest.raises(crpc.RPCConnectionError):
                client.call(addr, "echo", None, timeout=0.5, target="gone")
            return list(samples)

        first = run()
        assert len(first) == 6  # one sleep before each retry attempt
        for a, s in enumerate(first, start=1):
            # FULL jitter: U(0, min(cap, base * 2^(a-1))) — never a bare
            # deterministic doubling
            assert 0.0 <= s <= min(0.04, 0.01 * (2 ** (a - 1))) + 1e-12
        assert len(set(first)) >= 3  # a spread, not a constant ladder
        assert max(first) > 0.0

        # a fresh plan with the SAME seed replays the exact spacing —
        # this is what makes chaos runs reproducible end to end
        assert run() == first

        # and without a plan the draws come from an unseeded PRNG:
        # still bounded, still a spread
        faults.clear_plan()
        samples.clear()
        client = crpc.RpcClient(retries=6, backoff_base=0.01,
                                backoff_max=0.04, node_name="jitter")
        with pytest.raises(crpc.RPCConnectionError):
            client.call(addr, "echo", None, timeout=0.5, target="gone")
        assert len(samples) == 6
        assert all(0.0 <= s <= 0.04 + 1e-12 for s in samples)


# ---------------------------------------------------------------------------
# RPC consult points: client drop ladder, lost-response dedup, duplicate
# absorption, black-hole timeout — on a bare server/client pair


class TestRpcFaultInjection:
    @pytest.fixture()
    def pair(self):
        srv = crpc.RpcServer(node_name="srv")
        executions = []

        def count_me(payload):
            executions.append(payload)
            return {"n": len(executions)}

        srv.register("count_me", count_me)
        client = crpc.RpcClient(retries=3, backoff_base=0.001,
                                backoff_max=0.004, node_name="cli")
        try:
            yield srv, client, executions
        finally:
            srv.stop()

    def test_client_drop_consumes_the_ladder(self, pair):
        srv, client, executions = pair
        plan = FaultPlan(seed=0, rules=[
            FaultRule(action="drop", side="client",
                      method="count_me", max_hits=2),
        ])
        faults.set_plan(plan)
        before = _counter_value("cluster_faults_injected_total",
                                action="drop")
        out = client.call(srv.address, "count_me", {}, timeout=2.0,
                          target="srv")
        # two attempts died on the floor, the third got through — and the
        # method ran exactly once (the dropped attempts never sent bytes)
        assert out == {"n": 1} and len(executions) == 1
        assert plan.hits() == [2]
        assert _counter_value("cluster_faults_injected_total",
                              action="drop") - before == 2

    def test_server_drop_forces_retry_through_dedup(self, pair):
        srv, client, executions = pair
        faults.set_plan(FaultPlan(seed=0, rules=[
            FaultRule(action="drop", side="server",
                      method="count_me", max_hits=1),
        ]))
        out = client.call(srv.address, "count_me", {}, timeout=2.0,
                          target="srv")
        # the lost-ack classic: the first execution's response was
        # discarded, the retry carried the SAME token and was served
        # from the memo — one execution, correct result
        assert out == {"n": 1}
        assert len(executions) == 1

    def test_duplicate_envelope_absorbed_by_memo(self, pair):
        srv, client, executions = pair
        faults.set_plan(FaultPlan(seed=0, rules=[
            FaultRule(action="duplicate", side="client",
                      method="count_me", max_hits=1),
        ]))
        out = client.call(srv.address, "count_me", {}, timeout=2.0,
                          target="srv")
        assert out == {"n": 1}
        _wait_for(lambda: len(executions) == 1, timeout=2.0,
                  msg="duplicate absorbed without a second execution")
        time.sleep(0.05)  # the duplicate frame has landed by now
        assert len(executions) == 1

    def test_black_hole_exhausts_as_timeout(self, pair):
        srv, client, executions = pair
        plan = FaultPlan(seed=0, rules=[
            FaultRule(action="black_hole", side="client",
                      method="count_me"),
        ])
        faults.set_plan(plan)
        with pytest.raises(crpc.RPCTimeoutError):
            client.call(srv.address, "count_me", {}, timeout=2.0,
                        target="srv", retries=1)
        assert plan.hits() == [2]  # both ladder attempts swallowed
        assert executions == []  # no bytes ever reached the server


# ---------------------------------------------------------------------------
# cluster tier: read-repair, sweep reap-vs-restore, survivor rescheduling


@pytest.fixture()
def fault_cloud3():
    """A formed 3-node cloud with DKV + DTask installed, suspicion set
    far out so fault windows are entirely script-controlled."""
    clouds, stores = [], []
    try:
        for i in range(3):
            c = Cloud("faultcloud", f"fc-{i}", hb_interval=0.05,
                      suspect_beats=200)
            s = KeyedStore()
            cdkv.install(c, s)
            ctasks.install(c)
            clouds.append(c)
            stores.append(s)
        seeds = []
        for c in clouds:
            c.start(list(seeds))
            seeds.append(c.info.addr)
        _wait_for(lambda: all(c.size() == 3 for c in clouds),
                  msg="3-node fault cloud formation")
        yield clouds, stores
    finally:
        faults.clear_plan()
        for c in clouds:
            c.stop()


def _key_homed(router, first, second, prefix):
    """A key whose ring candidates start [first, second] — placement is
    port-dependent, so probe rather than assume."""
    for i in range(400):
        k = f"{prefix}-{i}"
        names = [m.info.name for m in router.home_members(k, 3)]
        if names[:2] == [first, second]:
            return k
    pytest.fail(f"no key found with candidate order [{first}, {second}]")


class TestReadRepairAndSweep:
    def test_read_repair_through_dead_home(self, fault_cloud3):
        clouds, stores = fault_cloud3
        a, b, c = clouds
        ra = stores[0].router
        # homed on b, replica copy on c; caller a is neither
        key = _key_homed(ra, b.info.name, c.info.name, "chaos/rr")
        stores[0].put(key, [1, 2, 3], replicas=2)
        _wait_for(lambda: stores[2].get(key, _local=True) == [1, 2, 3],
                  timeout=2.0, msg="replica copy lands on the successor")
        b.stop()  # dies INSIDE the suspicion window: still in the ring
        before = _counter_total("cluster_dkv_read_repair_total")
        assert stores[0].get(key) == [1, 2, 3]  # served by the successor
        assert _counter_total("cluster_dkv_read_repair_total") - before == 1
        # the serving holder was promoted to home-elect: it now tracks
        # the key as an authoritative, replicated one
        rc = stores[2].router
        assert key in rc._replicated
        assert key not in rc._replica_copies

    def test_sweep_reaps_orphan_copy(self, fault_cloud3):
        clouds, stores = fault_cloud3
        a, b, c = clouds
        ra = stores[0].router
        key = _key_homed(ra, b.info.name, a.info.name, "chaos/reap")
        stores[0].put(key, {"v": 1}, replicas=2)
        _wait_for(lambda: stores[0].get(key, _local=True) == {"v": 1},
                  timeout=2.0, msg="replica copy lands on node a")
        # make b's home-side reap push fail: its dkv_remove to the
        # holder is dropped on the client side, orphaning a's copy
        faults.set_plan(FaultPlan(seed=0, rules=[
            FaultRule(action="drop", side="client", src=b.info.name,
                      method="dkv_remove"),
        ]))
        before = _counter_value("cluster_dkv_replica_sweep_total",
                                action="reaped")
        stores[1].remove(key)
        faults.clear_plan()
        # the orphan does NOT leak: the holder's heartbeat-piggybacked
        # sweep validates the copy against the home, learns the key WAS
        # removed (the home's removed-set disambiguates), and reaps it
        _wait_for(lambda: key not in ra._replica_copies, timeout=5.0,
                  msg="orphan copy reaped by the anti-entropy sweep")
        assert _counter_value("cluster_dkv_replica_sweep_total",
                              action="reaped") - before >= 1
        assert stores[0].get(key, "GONE", _local=True) == "GONE"

    def test_sweep_restores_copy_to_amnesiac_home(self, fault_cloud3):
        clouds, stores = fault_cloud3
        a, b, c = clouds
        ra = stores[0].router
        key = _key_homed(ra, b.info.name, a.info.name, "chaos/restore")
        stores[0].put(key, [9, 9], replicas=2)
        _wait_for(lambda: stores[0].get(key, _local=True) == [9, 9],
                  timeout=2.0, msg="replica copy lands on node a")
        # the home loses the value WITHOUT serving a remove (a restart
        # that came back empty): _local bypasses the routed path, so
        # b's removed-set never learns the key
        before = _counter_value("cluster_dkv_replica_sweep_total",
                                action="restored")
        stores[1].remove(key, _local=True)
        _wait_for(lambda: stores[1].get(key, _local=True) == [9, 9],
                  timeout=5.0, msg="value restored onto the home")
        assert _counter_value("cluster_dkv_replica_sweep_total",
                              action="restored") - before >= 1
        assert key in ra._replica_copies  # the copy survives the restore

    def test_remove_vs_amnesia_no_resurrection(self, fault_cloud3):
        """The remove-vs-amnesia race: the home removes a replicated key
        but its reap push to the holder is dropped; the home then
        restarts EMPTY (forgetting the removal).  The holder's sweep
        sees exists=False, removed=False from the home — the exact
        signature of the legitimate amnesiac-restore path — and before
        the per-key remove epochs would have resurrected the key.  Now
        the OTHER walk members still carry a tombstone epoch newer than
        the copy's write epoch, so the sweep reaps instead of restoring.
        (The positive control — a value lost WITHOUT a remove is still
        restored — is test_sweep_restores_copy_to_amnesiac_home.)"""
        clouds, stores = fault_cloud3
        a, b, c = clouds
        ra, rb = stores[0].router, stores[1].router
        key = _key_homed(ra, b.info.name, a.info.name, "chaos/resurrect")
        stores[0].put(key, [4, 2], replicas=2)
        _wait_for(lambda: stores[0].get(key, _local=True) == [4, 2],
                  timeout=2.0, msg="replica copy lands on node a")
        # the reap push toward the HOLDER is dropped (seeded plan), but
        # the third walk member still records the tombstone epoch; the
        # holder's own sweep is frozen (its replica_check dropped) so
        # the amnesia below is staged BEFORE the sweep can adjudicate
        faults.set_plan(FaultPlan(seed=7, rules=[
            FaultRule(action="drop", side="client", src=b.info.name,
                      dst=f"*:{a.info.addr[1]}", method="dkv_remove"),
            FaultRule(action="drop", side="client", src=a.info.name,
                      method="dkv_replica_check"),
        ]))
        stores[1].remove(key)
        assert key in ra._replica_copies  # the orphaned copy survives
        # the home restarts empty: store already lacks the key; it also
        # forgets every removal and every tracked replication
        rb._removed.clear()
        rb._key_epochs.clear()
        rb._replicated.clear()
        faults.clear_plan()
        before = _counter_value("cluster_dkv_replica_sweep_total",
                                action="restored")
        _wait_for(lambda: key not in ra._replica_copies, timeout=5.0,
                  msg="stale copy reaped despite the amnesiac home")
        # reaped, never restored: the key stays dead everywhere
        assert _counter_value("cluster_dkv_replica_sweep_total",
                              action="restored") == before
        assert stores[0].get(key, "GONE", _local=True) == "GONE"
        assert stores[1].get(key, "GONE", _local=True) == "GONE"

    def test_fanout_rescheduled_onto_survivors(self, fault_cloud3):
        clouds, stores = fault_cloud3
        a, b, c = clouds
        n = 3001
        cols = {"x": (np.arange(n) % 97).astype(np.float32)}
        baseline = ctasks.distributed_map_reduce(_mr_stat, cols, cloud=None)
        # partition c off from the driver: every dtask to it dies
        # client-side, so its ranges must land on the survivors
        faults.set_plan(FaultPlan(seed=0, rules=[
            FaultRule(action="partition", side="client",
                      dst=f"*:{c.info.addr[1]}", method="dtask"),
        ]))
        before = _counter_value("cluster_fanout_recovered_total",
                                path="survivor")
        out = ctasks.distributed_map_reduce(_mr_stat, cols, cloud=a)
        assert _counter_value("cluster_fanout_recovered_total",
                              path="survivor") - before >= 1
        # bit-identical despite the reschedule: integer-valued float32
        # partials are exact, so the k-way split cannot perturb sums
        assert float(out["s"]) == float(baseline["s"])
        assert float(out["n"]) == float(baseline["n"])


# ---------------------------------------------------------------------------
# chunk shipping: the transport-frame guard fails typed, not mid-transfer


class TestChunkPayloadGuard:
    """A chunk that cannot fit one transport frame must fail BEFORE the
    wire with a typed error naming the chunk and the remediation — not
    as an opaque mid-transfer transport death."""

    def test_boundary_exact_fit_and_one_over(self, monkeypatch):
        from h2o3_tpu.cluster import frames, transport

        monkeypatch.setattr(transport, "MAX_FRAME_BYTES",
                            frames._ENVELOPE_SLACK + 1024)
        # exactly at the limit: passes, returns the measured size
        assert frames.guard_chunk_payload("fr#k#g0t0#c0", b"x" * 1024) == 1024
        # one byte over: typed refusal with id + size + limit + hint
        with pytest.raises(frames.ChunkTooLargeError) as ei:
            frames.guard_chunk_payload("fr#k#g0t0#c7", b"x" * 1025)
        err = ei.value
        assert err.chunk_id == "fr#k#g0t0#c7"
        assert err.nbytes == 1025
        assert err.limit == 1024
        assert "H2O3_TPU_PARSE_CHUNK_BYTES" in str(err)
        assert isinstance(err, ValueError)  # callers' ValueError nets work

    def test_non_bytes_payload_measured_as_pickled(self, monkeypatch):
        from h2o3_tpu.cluster import frames, transport

        monkeypatch.setattr(transport, "MAX_FRAME_BYTES",
                            frames._ENVELOPE_SLACK + 64)
        # a tokenized-chunk dict ships pickled: the guard must size that
        # wire form, not some notional raw length
        with pytest.raises(frames.ChunkTooLargeError) as ei:
            frames.guard_chunk_payload("fr#k#g1t0#c3", {"cols": "y" * 256})
        assert ei.value.nbytes > 256  # pickle framing counted too


# ---------------------------------------------------------------------------
# nemesis surfaces: RPC (gated by env) and REST /3/Faults (gated per call)


class TestNemesisSurface:
    def test_rpc_surface_absent_by_default(self, monkeypatch):
        monkeypatch.delenv("H2O3_TPU_FAULTS", raising=False)
        monkeypatch.delenv("H2O3_TPU_FAULT_PLAN", raising=False)
        c = Cloud("nofaults", "plain", hb_interval=0.05)
        try:
            assert "fault_plan_set" not in c.rpc_server._methods
            assert "fault_crash" not in c.rpc_server._methods
        finally:
            c.stop()

    def test_rpc_surface_roundtrip(self, monkeypatch):
        monkeypatch.setenv("H2O3_TPU_FAULTS", "1")
        a = Cloud("nemesis", "nem-a", hb_interval=0.05)
        b = Cloud("nemesis", "nem-b", hb_interval=0.05)
        try:
            a.start([])
            b.start([a.info.addr])
            _wait_for(lambda: a.size() == 2 and b.size() == 2,
                      msg="nemesis cloud formation")
            spec = {"seed": 13, "rules": [
                {"action": "delay", "method": "never_called",
                 "delay_ms": 1.0}]}
            out = a.client.call(b.info.addr, "fault_plan_set", spec)
            assert out == {"installed": True, "seed": 13, "rules": 1}
            got = a.client.call(b.info.addr, "fault_plan_get", None)
            assert got["plan"]["seed"] == 13
            assert got["plan"]["rules"][0]["method"] == "never_called"
            assert got["hits"] == [0]
            out = a.client.call(b.info.addr, "fault_plan_clear", None)
            assert out == {"cleared": True}
            assert faults.active_plan() is None  # in-process: shared
        finally:
            faults.clear_plan()
            a.stop()
            b.stop()


@pytest.mark.leaks_keys
def test_rest_faults_surface_gated(monkeypatch):
    import urllib.error
    import urllib.request

    from h2o3_tpu.api import start_server

    def req(server, method, path, data=None):
        body = json.dumps(data).encode() if data is not None else None
        headers = {"Content-Type": "application/json"} if body else {}
        r = urllib.request.Request(
            server.url + path, data=body, headers=headers, method=method)
        try:
            with urllib.request.urlopen(r) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    monkeypatch.delenv("H2O3_TPU_FAULTS", raising=False)
    monkeypatch.delenv("H2O3_TPU_FAULT_PLAN", raising=False)
    s = start_server(port=0)
    try:
        st, _ = req(s, "GET", "/3/Faults")
        assert st == 403  # production boots never expose the nemesis
        monkeypatch.setenv("H2O3_TPU_FAULTS", "1")
        st, body = req(s, "POST", "/3/Faults", {
            "seed": 3, "rules": [{"action": "delay", "method": "x",
                                  "delay_ms": 1.0}]})
        assert st == 200 and body["installed"] and body["rules"] == 1
        st, body = req(s, "GET", "/3/Faults")
        assert st == 200 and body["plan"]["seed"] == 3
        st, _ = req(s, "POST", "/3/Faults",
                    {"rules": [{"action": "explode"}]})
        assert st == 400
        st, body = req(s, "DELETE", "/3/Faults")
        assert st == 200
        assert faults.active_plan() is None
    finally:
        faults.clear_plan()
        s.stop()
