"""Breadth round 3: GAM, GLRM, CoxPH (SURVEY.md §2.2)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.frame.frame import ColType, Column


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


class TestGAM:
    def test_recovers_nonlinear_effect(self, rng):
        from h2o3_tpu.models.gam import GAM

        n = 1200
        x = rng.uniform(-3, 3, size=n)
        z = rng.normal(size=n)
        y = np.sin(x) + 0.5 * z + rng.normal(size=n) * 0.1
        fr = Frame.from_dict({"x": x, "z": z, "y": y})
        m = GAM(response_column="y", gam_columns=["x"], num_knots=10,
                family="gaussian", scale=0.1, seed=1).train(fr)
        pred = m.predict(fr).col("predict").numeric_view()
        resid = y - pred
        # a linear model can't do better than sd(sin residual) ~ .45; GAM should
        assert resid.std() < 0.2
        # r2 via metrics
        assert m.training_metrics.r2 > 0.95

    def test_gam_binomial(self, rng):
        from h2o3_tpu.models.gam import GAM

        n = 1500
        x = rng.uniform(-3, 3, size=n)
        p_true = 1 / (1 + np.exp(-2 * np.sin(x)))
        y = (rng.random(n) < p_true).astype(np.int32)
        fr = Frame([
            Column("x", x, ColType.NUM),
            Column("y", y, ColType.CAT, ["0", "1"]),
        ])
        m = GAM(response_column="y", gam_columns=["x"], num_knots=8,
                family="binomial", scale=0.01, seed=1).train(fr)
        assert m.training_metrics.auc > 0.75

    def test_smoothing_scale_shrinks_wiggle(self, rng):
        from h2o3_tpu.models.gam import GAM

        n = 400
        x = rng.uniform(-3, 3, size=n)
        y = np.sin(3 * x) + rng.normal(size=n) * 0.3
        fr = Frame.from_dict({"x": x, "y": y})
        loose = GAM(response_column="y", gam_columns=["x"], num_knots=12,
                    scale=1e-4, seed=1).train(fr)
        stiff = GAM(response_column="y", gam_columns=["x"], num_knots=12,
                    scale=1e4, seed=1).train(fr)
        # heavy smoothing -> worse training fit (approaches a line)
        assert stiff.training_metrics.mse > loose.training_metrics.mse

    def test_requires_gam_columns(self, rng):
        from h2o3_tpu.models.gam import GAM

        fr = Frame.from_dict({"x": rng.normal(size=30), "y": rng.normal(size=30)})
        with pytest.raises(ValueError, match="gam_columns"):
            GAM(response_column="y").train(fr)


class TestGLRM:
    def test_low_rank_recovery(self, rng):
        from h2o3_tpu.models.glrm import GLRM

        n, p, k = 300, 10, 3
        Xtrue = rng.normal(size=(n, k))
        Ytrue = rng.normal(size=(k, p))
        A = Xtrue @ Ytrue + rng.normal(size=(n, p)) * 0.01
        fr = Frame.from_dict({f"c{j}": A[:, j] for j in range(p)})
        m = GLRM(k=k, max_iterations=100, seed=1).train(fr)
        R = m.x_factors @ m.archetypes
        rel = np.linalg.norm(R - A) / np.linalg.norm(A)
        assert rel < 0.05
        assert m.archetypes.shape == (k, p)

    def test_missing_value_imputation(self, rng):
        from h2o3_tpu.models.glrm import GLRM

        n, p, k = 200, 8, 2
        A = rng.normal(size=(n, k)) @ rng.normal(size=(k, p))
        Aobs = A.copy()
        holes = rng.random(A.shape) < 0.15
        Aobs[holes] = np.nan
        fr = Frame.from_dict({f"c{j}": Aobs[:, j] for j in range(p)})
        m = GLRM(k=k, max_iterations=150, seed=1).train(fr)
        R = m.x_factors @ m.archetypes
        # reconstruction should approximate the TRUE values in the holes
        err = np.abs(R[holes] - A[holes]).mean()
        scale = np.abs(A).mean()
        assert err < 0.2 * scale

    def test_nonneg_regularization(self, rng):
        from h2o3_tpu.models.glrm import GLRM

        W = np.abs(rng.normal(size=(100, 2)))
        H = np.abs(rng.normal(size=(2, 6)))
        A = W @ H
        fr = Frame.from_dict({f"c{j}": A[:, j] for j in range(6)})
        m = GLRM(k=2, regularization_x="non_negative", regularization_y="non_negative",
                 init="random", max_iterations=200, seed=3).train(fr)
        assert (m.x_factors >= 0).all()
        assert (m.archetypes >= 0).all()

    def test_transform_new_frame(self, rng):
        from h2o3_tpu.models.glrm import GLRM

        A = rng.normal(size=(120, 5))
        fr = Frame.from_dict({f"c{j}": A[:, j] for j in range(5)})
        m = GLRM(k=2, seed=1).train(fr)
        xf = m.transform_frame(fr)
        assert xf.shape == (120, 2)
        assert xf.names == ["Arch1", "Arch2"]


def _naive_cox_nll(beta, X, t, d, ties="breslow"):
    """Independent O(n^2) negative partial log-likelihood oracle."""
    eta = X @ beta
    r = np.exp(eta)
    ll = 0.0
    for ti in np.unique(t[d > 0]):
        ev = (t == ti) & (d > 0)
        risk = t >= ti
        ll += eta[ev].sum() - ev.sum() * np.log(r[risk].sum())
    return -ll


class TestCoxPH:
    def _sim(self, rng, n=500, beta=(0.8, -0.5)):
        X = rng.normal(size=(n, len(beta)))
        lam = np.exp(X @ np.array(beta))
        t_event = rng.exponential(1.0 / lam)
        t_cens = rng.exponential(2.0, size=n)
        t = np.minimum(t_event, t_cens)
        d = (t_event <= t_cens).astype(np.float64)
        return X, t, d

    def test_matches_naive_breslow_oracle(self, rng):
        from scipy.optimize import minimize

        from h2o3_tpu.models.coxph import CoxPH

        X, t, d = self._sim(rng, n=300)
        fr = Frame.from_dict({"x0": X[:, 0], "x1": X[:, 1], "time": t, "event": d})
        m = CoxPH(response_column="event", stop_column="time", ties="breslow").train(fr)

        res = minimize(_naive_cox_nll, np.zeros(2), args=(X, t, d), method="BFGS")
        ours = np.array([m.coefficients["x0"], m.coefficients["x1"]])
        assert np.allclose(ours, res.x, atol=2e-3)

    def test_recovers_hazard_ratio(self, rng):
        from h2o3_tpu.models.coxph import CoxPH

        X, t, d = self._sim(rng, n=2000, beta=(1.0, 0.0))
        fr = Frame.from_dict({"x0": X[:, 0], "x1": X[:, 1], "time": t, "event": d})
        m = CoxPH(response_column="event", stop_column="time").train(fr)
        assert abs(m.coefficients["x0"] - 1.0) < 0.15
        assert abs(m.coefficients["x1"]) < 0.15
        assert m.concordance > 0.65
        assert m.loglik > m.loglik_null

    def test_efron_handles_ties(self, rng):
        from h2o3_tpu.models.coxph import CoxPH

        X, t, d = self._sim(rng, n=400)
        t = np.round(t, 1)  # induce heavy ties
        fr = Frame.from_dict({"x0": X[:, 0], "x1": X[:, 1], "time": t, "event": d})
        me = CoxPH(response_column="event", stop_column="time", ties="efron").train(fr)
        mb = CoxPH(response_column="event", stop_column="time", ties="breslow").train(fr)
        # both sane, efron != breslow under ties but close
        for m in (me, mb):
            assert np.isfinite(list(m.coefficients.values())).all()
        diff = abs(me.coefficients["x0"] - mb.coefficients["x0"])
        assert 0 < diff < 0.2

    def test_se_and_z(self, rng):
        from h2o3_tpu.models.coxph import CoxPH

        X, t, d = self._sim(rng, n=800, beta=(1.0, 0.0))
        fr = Frame.from_dict({"x0": X[:, 0], "x1": X[:, 1], "time": t, "event": d})
        m = CoxPH(response_column="event", stop_column="time").train(fr)
        assert m.std_errors["x0"] > 0
        assert abs(m.z_values["x0"]) > 2  # strong true effect
        assert abs(m.z_values["x1"]) < 2  # null effect


def _naive_cox_nll_trunc(beta, X, s, t, d):
    """Breslow oracle with left truncation: risk set = {j: s_j < ti <= t_j}."""
    eta = X @ beta
    r = np.exp(eta)
    ll = 0.0
    for ti in np.unique(t[d > 0]):
        ev = (t == ti) & (d > 0)
        risk = (t >= ti) & (s < ti)
        ll += eta[ev].sum() - ev.sum() * np.log(r[risk].sum())
    return -ll


class TestCoxPHLeftTruncation:
    def test_matches_truncated_oracle(self, rng):
        from scipy.optimize import minimize

        from h2o3_tpu.models.coxph import CoxPH

        n = 300
        X = rng.normal(size=(n, 2))
        lam = np.exp(X @ np.array([0.8, -0.5]))
        t_event = rng.exponential(1.0 / lam)
        s = rng.uniform(0, 0.3, size=n)  # delayed entry
        t = s + t_event
        d = np.ones(n)
        fr = Frame.from_dict(
            {"x0": X[:, 0], "x1": X[:, 1], "start": s, "time": t, "event": d}
        )
        m = CoxPH(
            response_column="event", start_column="start", stop_column="time",
            ties="breslow",
        ).train(fr)
        res = minimize(
            _naive_cox_nll_trunc, np.zeros(2), args=(X, s, t, d), method="BFGS"
        )
        ours = np.array([m.coefficients["x0"], m.coefficients["x1"]])
        assert np.allclose(ours, res.x, atol=2e-3)

    def test_truncation_changes_fit(self, rng):
        from h2o3_tpu.models.coxph import CoxPH

        n = 400
        X = rng.normal(size=(n, 1))
        lam = np.exp(0.9 * X[:, 0])
        t_event = rng.exponential(1.0 / lam)
        s = rng.uniform(0, 1.0, size=n)
        t = s + t_event
        d = np.ones(n)
        fr = Frame.from_dict({"x0": X[:, 0], "start": s, "time": t, "event": d})
        m_t = CoxPH(
            response_column="event", start_column="start", stop_column="time"
        ).train(fr)
        m_n = CoxPH(response_column="event", stop_column="time").train(fr)
        assert m_t.coefficients["x0"] != m_n.coefficients["x0"]
        # truncated fit should be closer to truth on entry-biased data
        assert abs(m_t.coefficients["x0"] - 0.9) < abs(m_n.coefficients["x0"] - 0.9) + 0.05


class TestGAMElasticNet:
    def test_l1_shrinks_noise_coefs(self, rng):
        from h2o3_tpu.models.gam import GAM

        n = 800
        x = rng.uniform(-3, 3, size=n)
        noise = {f"n{i}": rng.normal(size=n) for i in range(4)}
        y = np.sin(x) + rng.normal(size=n) * 0.1
        fr = Frame.from_dict({"x": x, **noise, "y": y})
        kw = dict(response_column="y", gam_columns=["x"], num_knots=8,
                  family="gaussian", scale=0.1, seed=1)
        m0 = GAM(lambda_=0.0, **kw).train(fr)
        m1 = GAM(lambda_=0.5, alpha=1.0, **kw).train(fr)  # pure LASSO
        c0 = np.array([m0.coefficients[f"n{i}"] for i in range(4)])
        c1 = np.array([m1.coefficients[f"n{i}"] for i in range(4)])
        # L1 must actually penalize: noise coefs collapse toward zero
        assert np.abs(c1).sum() < 0.2 * np.abs(c0).sum() + 1e-6
