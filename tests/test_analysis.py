"""Tests for the static analyzer (``h2o3_tpu/analysis/``).

Each pass gets positive fixtures that MUST be flagged and negatives
that must NOT, plus suppression-comment and baseline round-trips, the
``--json`` schema, and the tier-1 gate: ``scripts/analyze.py`` must run
clean on the repo itself (a new unbaselined finding fails this suite).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from h2o3_tpu.analysis import core
from h2o3_tpu.analysis.core import (analyze_source, load_baseline,
                                    save_baseline, split_baselined)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANALYZE = os.path.join(ROOT, "scripts", "analyze.py")

AST_PASSES = ["lock-discipline", "tracer-purity", "seeded-determinism",
              "knob-registry", "rpc-payload"]


def rules(findings):
    return [f.rule for f in findings]


def src(text):
    return textwrap.dedent(text)


# ---------------------------------------------------------------------------
# lock-discipline


class TestLockDiscipline:
    def test_sleep_under_lock_flagged(self):
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    time.sleep(5)
        """), pass_names=["lock-discipline"])
        assert rules(fs) == ["LOCK001"]
        assert fs[0].line == 6
        assert "time.sleep" in fs[0].message

    def test_sleep_after_lock_not_flagged(self):
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    x = 1
                time.sleep(5)
        """), pass_names=["lock-discipline"])
        assert fs == []

    def test_rpc_call_under_self_lock_flagged(self):
        fs = analyze_source(src("""
            import threading
            class Store:
                def __init__(self):
                    self._lock = threading.RLock()
                def f(self, client, addr):
                    with self._lock:
                        return client.call(addr, "dkv_get", {})
        """), pass_names=["lock-discipline"])
        assert rules(fs) == ["LOCK001"]
        assert fs[0].symbol == "Store.f"

    def test_blocking_via_local_call_propagates(self):
        fs = analyze_source(src("""
            import threading, subprocess
            _lock = threading.Lock()
            def helper():
                subprocess.run(["make"])
            def f():
                with _lock:
                    helper()
        """), pass_names=["lock-discipline"])
        assert rules(fs) == ["LOCK001"]
        assert "helper" in fs[0].message

    def test_device_dispatch_under_lock_flagged(self):
        fs = analyze_source(src("""
            import threading
            import jax.numpy as jnp
            _table_lock = threading.Lock()
            def f(arrays):
                with _table_lock:
                    return jnp.stack(arrays, axis=1)
        """), pass_names=["lock-discipline"])
        assert rules(fs) == ["LOCK001"]
        assert "jnp.stack" in fs[0].message

    def test_nested_def_under_lock_not_flagged(self):
        # a closure defined (not called) under the lock runs later
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    def later():
                        time.sleep(5)
                    return later
        """), pass_names=["lock-discipline"])
        assert fs == []

    def test_condition_wait_in_own_with_not_flagged(self):
        fs = analyze_source(src("""
            import threading
            qlock = threading.Condition()
            def f():
                with qlock:
                    qlock.wait(timeout=1)
        """), pass_names=["lock-discipline"])
        assert fs == []

    def test_lock_order_inversion_flagged(self):
        fs = analyze_source(src("""
            import threading
            a_lock = threading.Lock()
            b_lock = threading.Lock()
            def f():
                with a_lock:
                    with b_lock:
                        pass
            def g():
                with b_lock:
                    with a_lock:
                        pass
        """), pass_names=["lock-discipline"])
        assert "LOCK002" in rules(fs)

    def test_consistent_lock_order_not_flagged(self):
        fs = analyze_source(src("""
            import threading
            a_lock = threading.Lock()
            b_lock = threading.Lock()
            def f():
                with a_lock:
                    with b_lock:
                        pass
            def g():
                with a_lock:
                    with b_lock:
                        pass
        """), pass_names=["lock-discipline"])
        assert [r for r in rules(fs) if r == "LOCK002"] == []


# ---------------------------------------------------------------------------
# tracer-purity


class TestTracerPurity:
    def test_time_in_jitted_fn_flagged(self):
        fs = analyze_source(src("""
            import jax, time
            @jax.jit
            def f(x):
                t = time.time()
                return x + t
        """), pass_names=["tracer-purity"])
        assert rules(fs) == ["TRACE001"]
        assert fs[0].symbol == "f"

    def test_partial_jit_decorator_flagged(self):
        fs = analyze_source(src("""
            import jax, random
            from functools import partial
            @partial(jax.jit, static_argnums=0)
            def f(n, x):
                return x * random.random()
        """), pass_names=["tracer-purity"])
        assert rules(fs) == ["TRACE001"]

    def test_fn_passed_to_map_reduce_flagged(self):
        fs = analyze_source(src("""
            def shard_fn(cols, mask):
                COUNTER.inc()
                return cols
            def run(table):
                return map_reduce(shard_fn, table)
        """), pass_names=["tracer-purity"])
        assert rules(fs) == ["TRACE001"]
        assert "telemetry" in fs[0].message

    def test_emit_lambda_flagged(self):
        fs = analyze_source(src("""
            import time
            SPEC = prim("badop", fusible=True,
                        emit=lambda jnp, a: a * time.time())
        """), pass_names=["tracer-purity"])
        assert rules(fs) == ["TRACE001"]
        assert "emit" in fs[0].message

    def test_functional_at_set_not_flagged(self):
        # arr.at[i].set(v) is functional jax, not telemetry
        fs = analyze_source(src("""
            import jax
            @jax.jit
            def f(x):
                return x.at[0].set(1.0)
        """), pass_names=["tracer-purity"])
        assert fs == []

    def test_untraced_fn_not_flagged(self):
        fs = analyze_source(src("""
            import time
            def plain():
                return time.time()
        """), pass_names=["tracer-purity"])
        assert fs == []


# ---------------------------------------------------------------------------
# seeded-determinism


class TestSeededDeterminism:
    FAULTS = "h2o3_tpu/cluster/faults.py"

    def test_bare_random_in_scope_flagged(self):
        fs = analyze_source(src("""
            import random
            def should_drop():
                return random.random() < 0.5
        """), rel=self.FAULTS, pass_names=["seeded-determinism"])
        assert rules(fs) == ["SEED001"]

    def test_unseeded_random_instance_flagged(self):
        fs = analyze_source(src("""
            import random
            RNG = random.Random()
        """), rel=self.FAULTS, pass_names=["seeded-determinism"])
        assert rules(fs) == ["SEED002"]

    def test_wallclock_in_chaos_file_flagged(self):
        fs = analyze_source(src("""
            import time
            def jitter():
                return time.time() % 1.0
        """), rel="scripts/chaos.py", pass_names=["seeded-determinism"])
        assert rules(fs) == ["SEED003"]

    def test_seeded_random_not_flagged(self):
        fs = analyze_source(src("""
            import random
            def rule_rng(seed, i):
                return random.Random((seed << 16) ^ i)
        """), rel=self.FAULTS, pass_names=["seeded-determinism"])
        assert fs == []

    def test_out_of_scope_file_not_flagged(self):
        fs = analyze_source(src("""
            import random
            def sample():
                return random.random()
        """), rel="h2o3_tpu/models/foo.py",
            pass_names=["seeded-determinism"])
        assert fs == []


# ---------------------------------------------------------------------------
# knob-registry


class TestKnobRegistry:
    def test_undocumented_read_flagged(self):
        fs = analyze_source(src("""
            import os
            V = os.environ.get("H2O3_TPU_FAKE_KNOB", "1")
        """), pass_names=["knob-registry"], readme_text="no knobs here")
        assert rules(fs) == ["KNOB001"]
        assert "H2O3_TPU_FAKE_KNOB" in fs[0].message

    def test_documented_read_not_flagged(self):
        fs = analyze_source(src("""
            import os
            V = os.environ.get("H2O3_TPU_FAKE_KNOB", "1")
        """), pass_names=["knob-registry"],
            readme_text="set `H2O3_TPU_FAKE_KNOB` to tune it")
        assert fs == []

    def test_config_table_constant_counts_as_read(self):
        fs = analyze_source(src("""
            KNOBS = {"workers": ("H2O3_TPU_FAKE_TABLE_KNOB", 16, int)}
        """), pass_names=["knob-registry"],
            readme_text="`H2O3_TPU_FAKE_TABLE_KNOB` documented")
        assert fs == []

    def test_documented_but_never_read_flagged(self):
        fs = analyze_source(src("""
            import os
        """), pass_names=["knob-registry"],
            readme_text="tune `H2O3_TPU_GHOST_KNOB` for speed")
        assert rules(fs) == ["KNOB002"]
        assert fs[0].file == "README.md"
        assert fs[0].symbol == "H2O3_TPU_GHOST_KNOB"


# ---------------------------------------------------------------------------
# rpc-payload


class TestRpcPayload:
    def test_lambda_to_store_put_flagged(self):
        fs = analyze_source(src("""
            def f(store):
                store.put("k", lambda x: x + 1)
        """), pass_names=["rpc-payload"])
        assert rules(fs) == ["ROUTE001"]

    def test_local_function_to_remote_put_flagged(self):
        fs = analyze_source(src("""
            def reducer(a, b):
                return a + b
            def f(router):
                router.remote_put("k", reducer, 2)
        """), pass_names=["rpc-payload"])
        assert rules(fs) == ["ROUTE001"]
        assert "reducer" in fs[0].message

    def test_plain_data_put_not_flagged(self):
        fs = analyze_source(src("""
            def f(store):
                store.put("k", {"rows": [1, 2, 3]})
        """), pass_names=["rpc-payload"])
        assert fs == []

    def test_local_queue_put_not_flagged(self):
        # q.put(...) is a local queue, not a wire crossing
        fs = analyze_source(src("""
            def f(q):
                q.put("k", lambda x: x)
        """), pass_names=["rpc-payload"])
        assert fs == []

    def test_lambda_in_rpc_payload_flagged(self):
        fs = analyze_source(src("""
            def f(client, addr):
                client.call(addr, "run_task", {"fn": lambda p: p})
        """), pass_names=["rpc-payload"])
        assert rules(fs) == ["ROUTE002"]

    def test_plain_rpc_payload_not_flagged(self):
        fs = analyze_source(src("""
            def f(client, addr):
                client.call(addr, "run_task", {"n": 3})
        """), pass_names=["rpc-payload"])
        assert fs == []


# ---------------------------------------------------------------------------
# telemetry-drift (README-parsing side; the live-registry side is
# covered by the tier-1 gate below and scripts/check_telemetry.py)


class TestTelemetryDrift:
    def test_ghost_metric_detected(self, tmp_path):
        from h2o3_tpu.analysis.passes import telemetry_drift as td
        readme = tmp_path / "README.md"
        readme.write_text(
            "## Observability\n\nwe export `ghost_metric_total` here\n")
        documented = td.readme_documented_metrics(str(readme))
        assert "ghost_metric_total" in documented
        # against any registry lacking it, the drift is a failure
        assert documented - {"real_metric_total"} == {"ghost_metric_total"}

    def test_route_table_parsed(self, tmp_path):
        from h2o3_tpu.analysis.passes import telemetry_drift as td
        readme = tmp_path / "README.md"
        readme.write_text(
            "## Observability\n\n"
            "| Route | What |\n|---|---|\n"
            "| `GET /3/Ping` | liveness |\n")
        assert td.readme_documented_routes(str(readme)) == {
            ("GET", "/3/Ping")}

    @pytest.mark.slow
    def test_collect_flags_doctored_readme(self, tmp_path):
        from h2o3_tpu.analysis.passes import telemetry_drift as td
        with open(os.path.join(ROOT, "README.md")) as f:
            text = f.read()
        doctored = text.replace(
            "## Observability\n",
            "## Observability\n\nbogus `h2o3_ghost_metric_total` ref\n", 1)
        readme = tmp_path / "README.md"
        readme.write_text(doctored)
        failures, _ = td.collect(ROOT, str(readme))
        assert any(sym == "h2o3_ghost_metric_total"
                   for _r, _f, sym, _m in failures)


# ---------------------------------------------------------------------------
# suppressions + baseline


LOCK_FIXTURE = """
import threading, time
_lock = threading.Lock()
def f():
    with _lock:
        time.sleep(5)
"""


class TestSuppression:
    def test_noqa_on_line_suppresses(self):
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    time.sleep(5)  # h2o3: noqa[LOCK001]
        """), pass_names=["lock-discipline"])
        assert fs == []

    def test_noqa_on_preceding_line_suppresses(self):
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    # h2o3: noqa[LOCK001]
                    time.sleep(5)
        """), pass_names=["lock-discipline"])
        assert fs == []

    def test_noqa_for_other_rule_does_not_suppress(self):
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    time.sleep(5)  # h2o3: noqa[TRACE001]
        """), pass_names=["lock-discipline"])
        assert rules(fs) == ["LOCK001"]

    def test_bare_noqa_suppresses_everything(self):
        fs = analyze_source(src("""
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    time.sleep(5)  # h2o3: noqa
        """), pass_names=["lock-discipline"])
        assert fs == []


class TestBaseline:
    def test_round_trip(self, tmp_path):
        fs = analyze_source(src(LOCK_FIXTURE),
                            pass_names=["lock-discipline"])
        assert len(fs) == 1
        path = str(tmp_path / "baseline.json")
        save_baseline(path, fs, {fs[0].fingerprint: "known and accepted"})
        baseline = load_baseline(path)
        new, accepted = split_baselined(fs, baseline)
        assert new == [] and len(accepted) == 1
        assert baseline[fs[0].fingerprint]["justification"] == \
            "known and accepted"

    def test_fingerprint_survives_line_drift(self, tmp_path):
        fs1 = analyze_source(src(LOCK_FIXTURE),
                             pass_names=["lock-discipline"])
        # unrelated lines added above the finding must not invalidate it
        shifted = "# a new comment\nX = 1\n" + src(LOCK_FIXTURE)
        fs2 = analyze_source(shifted, pass_names=["lock-discipline"])
        assert fs1[0].line != fs2[0].line
        assert fs1[0].fingerprint == fs2[0].fingerprint

    def test_new_finding_not_matched(self, tmp_path):
        fs = analyze_source(src(LOCK_FIXTURE),
                            pass_names=["lock-discipline"])
        path = str(tmp_path / "baseline.json")
        save_baseline(path, [])
        new, accepted = split_baselined(fs, load_baseline(path))
        assert len(new) == 1 and accepted == []

    def test_version_check(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text('{"version": 99, "entries": []}')
        with pytest.raises(ValueError):
            load_baseline(str(path))


# ---------------------------------------------------------------------------
# driver / CI gate


class TestDriver:
    def run_analyze(self, *args, timeout=240):
        return subprocess.run(
            [sys.executable, ANALYZE, *args], cwd=ROOT,
            capture_output=True, text=True, timeout=timeout)

    @pytest.mark.slow
    def test_repo_runs_clean(self):
        """THE tier-1 gate: any new unbaselined finding fails the suite."""
        proc = self.run_analyze()
        assert proc.returncode == 0, \
            f"analyzer found new issues:\n{proc.stdout}\n{proc.stderr}"
        assert "analyze: OK" in proc.stdout

    def test_repo_runs_clean_ast_passes(self):
        """Fast gate over the pure-AST passes (no runtime imports)."""
        proc = self.run_analyze("--passes", ",".join(AST_PASSES))
        assert proc.returncode == 0, \
            f"analyzer found new issues:\n{proc.stdout}\n{proc.stderr}"

    def test_baseline_is_nonempty_and_justified(self):
        baseline = load_baseline(
            os.path.join(ROOT, "analysis_baseline.json"))
        assert baseline, "checked-in baseline must be non-empty"
        for entry in baseline.values():
            assert entry["justification"].strip(), \
                f"baseline entry {entry['fingerprint']} lacks justification"

    def test_json_schema(self, tmp_path):
        fixture = tmp_path / "fixture.py"
        fixture.write_text(src(LOCK_FIXTURE))
        empty = tmp_path / "baseline.json"
        proc = self.run_analyze(
            "--json", "--passes", "lock-discipline",
            "--baseline", str(empty), str(fixture))
        data = json.loads(proc.stdout)
        assert proc.returncode == 1
        assert data["version"] == 1
        assert data["baselined"] == 0
        assert data["passes"] == ["lock-discipline"]
        (finding,) = data["findings"]
        assert set(finding) == {"rule", "file", "line", "symbol",
                                "message", "snippet", "fingerprint"}
        assert finding["rule"] == "LOCK001"

    def test_changed_only_mode(self):
        proc = self.run_analyze("--changed-only", "--passes",
                                ",".join(AST_PASSES))
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_exit_nonzero_on_new_finding(self, tmp_path):
        fixture = tmp_path / "fixture.py"
        fixture.write_text(src(LOCK_FIXTURE))
        empty = tmp_path / "baseline.json"
        proc = self.run_analyze("--passes", "lock-discipline",
                                "--baseline", str(empty), str(fixture))
        assert proc.returncode == 1
        assert "LOCK001" in proc.stdout

    def test_update_baseline_then_clean(self, tmp_path):
        fixture = tmp_path / "fixture.py"
        fixture.write_text(src(LOCK_FIXTURE))
        bl = tmp_path / "baseline.json"
        proc = self.run_analyze("--passes", "lock-discipline",
                                "--baseline", str(bl),
                                "--update-baseline", str(fixture))
        assert proc.returncode == 0, proc.stdout + proc.stderr
        proc = self.run_analyze("--passes", "lock-discipline",
                                "--baseline", str(bl), str(fixture))
        assert proc.returncode == 0, proc.stdout + proc.stderr


# in-repo regression: the shipped sources the analyzer protects must
# keep satisfying the specific invariants fixed in this change
class TestShippedInvariants:
    def test_keyed_store_analyzer_clean(self):
        with open(os.path.join(ROOT, "h2o3_tpu", "keyed.py")) as f:
            fs = analyze_source(f.read(), rel="h2o3_tpu/keyed.py",
                                pass_names=["lock-discipline"])
        assert fs == [], [f.render() for f in fs]

    def test_mapreduce_matrix_analyzer_clean(self):
        path = os.path.join(ROOT, "h2o3_tpu", "compute", "mapreduce.py")
        with open(path) as f:
            fs = analyze_source(
                f.read(), rel="h2o3_tpu/compute/mapreduce.py",
                pass_names=["lock-discipline"])
        assert fs == [], [f.render() for f in fs]
