"""Parity: Pallas tpu_hist kernel vs the portable XLA scatter oracle.

Runs the kernel in Pallas interpreter mode (CPU-safe); on a real TPU the
same code path compiles to Mosaic. Oracle: ops/histogram.py
(_shard_histogram), itself validated against the reference semantics of
hex/tree/DHistogram.java:433.
"""

import numpy as np
import pytest

import jax

from h2o3_tpu.ops.histogram import _shard_histogram
from h2o3_tpu.ops.pallas_histogram import build_histogram_pallas

INTERPRET = jax.default_backend() != "tpu"


def _mk(n, f, k, b1, seed, frac_inactive=0.0, empty_node=None):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b1, size=(n, f)).astype(np.int32)
    nodes = rng.integers(0, k, size=n).astype(np.int32)
    if empty_node is not None:
        nodes[nodes == empty_node] = (empty_node + 1) % k
    if frac_inactive:
        nodes[rng.random(n) < frac_inactive] = -1
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.1
    return bins, nodes, g, h


@pytest.mark.parametrize(
    "n,f,k,b1,row_tile",
    [
        (1000, 5, 4, 17, 128),
        (513, 3, 1, 9, 256),      # single node, non-divisible rows
        (2048, 7, 8, 33, 512),
    ],
)
def test_parity(n, f, k, b1, row_tile):
    bins, nodes, g, h = _mk(n, f, k, b1, seed=n)
    want = np.asarray(_shard_histogram(bins, nodes, g, h, k, b1))
    got = np.asarray(
        build_histogram_pallas(
            bins, nodes, g, h, k, b1, row_tile=row_tile, interpret=INTERPRET
        )
    )
    assert got.shape == want.shape == (k, f, b1, 3)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_inactive_rows_and_empty_nodes():
    bins, nodes, g, h = _mk(
        1500, 4, 6, 13, seed=7, frac_inactive=0.3, empty_node=2
    )
    want = np.asarray(_shard_histogram(bins, nodes, g, h, 6, 13))
    got = np.asarray(
        build_histogram_pallas(
            bins, nodes, g, h, 6, 13, row_tile=128, interpret=INTERPRET
        )
    )
    # empty node's slab must be exactly zero, not garbage
    assert np.all(got[2] == 0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)


def test_counts_are_exact_integers():
    bins, nodes, g, h = _mk(700, 2, 3, 5, seed=3)
    got = np.asarray(
        build_histogram_pallas(bins, nodes, g, h, 3, 5, row_tile=128,
                               interpret=INTERPRET)
    )
    counts = got[..., 2]
    np.testing.assert_allclose(counts, np.round(counts))
    assert counts.sum() == 700 * 2
