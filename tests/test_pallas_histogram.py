"""Parity: Pallas tpu_hist kernels vs the portable XLA scatter oracle.

Runs the kernels in Pallas interpreter mode (CPU-safe); on a real TPU the
same code paths compile to Mosaic. Oracle: ops/histogram.py
(_shard_histogram), itself validated against the reference semantics of
hex/tree/DHistogram.java:433.

Two kernels are covered explicitly: the fixed-layout node-matmul kernel
(bf16 operands, f32 accumulation — tolerance reflects the bf16 rounding of
g/h inputs; counts are exact because 0/1 are exact in bf16) and the sorted
tile-per-node fallback used for deep levels (f32 throughout).
"""

import numpy as np
import pytest

import jax

from h2o3_tpu.ops.histogram import _shard_histogram
from h2o3_tpu.ops.pallas_histogram import build_histogram_pallas

INTERPRET = jax.default_backend() != "tpu"

# (kernel, rtol, atol): node-matmul carries bf16 operand rounding (~2^-8
# relative per element); sorted kernel is f32 end-to-end; factorized is the
# hi/lo-decomposed one-hot variant (same bf16-on-TPU / f32-in-interpret
# dtype policy as node-matmul)
KERNELS = [
    ("nodematmul", 2e-2, 5e-2),
    ("sorted", 1e-5, 1e-4),
    ("factorized", 2e-2, 5e-2),  # bf16 on real TPU, like nodematmul
]


def _mk(n, f, k, b1, seed, frac_inactive=0.0, empty_node=None):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, b1, size=(n, f)).astype(np.int32)
    nodes = rng.integers(0, k, size=n).astype(np.int32)
    if empty_node is not None:
        nodes[nodes == empty_node] = (empty_node + 1) % k
    if frac_inactive:
        nodes[rng.random(n) < frac_inactive] = -1
    g = rng.normal(size=n).astype(np.float32)
    h = rng.random(n).astype(np.float32) + 0.1
    return bins, nodes, g, h


@pytest.mark.parametrize("kernel,rtol,atol", KERNELS)
@pytest.mark.parametrize(
    "n,f,k,b1,row_tile",
    [
        (1000, 5, 4, 17, 128),
        (513, 3, 1, 9, 256),      # single node, non-divisible rows
        (2048, 7, 8, 33, 512),
        (900, 11, 4, 17, 128),    # features not a multiple of the 8-wide block
    ],
)
def test_parity(n, f, k, b1, row_tile, kernel, rtol, atol):
    bins, nodes, g, h = _mk(n, f, k, b1, seed=n)
    want = np.asarray(_shard_histogram(bins, nodes, g, h, k, b1))
    got = np.asarray(
        build_histogram_pallas(
            bins, nodes, g, h, k, b1, row_tile=row_tile, interpret=INTERPRET,
            kernel=kernel,
        )
    )
    assert got.shape == want.shape == (k, f, b1, 3)
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)


@pytest.mark.parametrize("kernel,rtol,atol", KERNELS)
def test_inactive_rows_and_empty_nodes(kernel, rtol, atol):
    bins, nodes, g, h = _mk(
        1500, 4, 6, 13, seed=7, frac_inactive=0.3, empty_node=2
    )
    want = np.asarray(_shard_histogram(bins, nodes, g, h, 6, 13))
    got = np.asarray(
        build_histogram_pallas(
            bins, nodes, g, h, 6, 13, row_tile=128, interpret=INTERPRET,
            kernel=kernel,
        )
    )
    # empty node's slab must be exactly zero, not garbage
    assert np.all(got[2] == 0)
    np.testing.assert_allclose(got, want, rtol=rtol, atol=atol)


@pytest.mark.parametrize("kernel,rtol,atol", KERNELS)
def test_counts_are_exact_integers(kernel, rtol, atol):
    bins, nodes, g, h = _mk(700, 2, 3, 5, seed=3)
    got = np.asarray(
        build_histogram_pallas(bins, nodes, g, h, 3, 5, row_tile=128,
                               interpret=INTERPRET, kernel=kernel)
    )
    counts = got[..., 2]
    np.testing.assert_allclose(counts, np.round(counts))
    assert counts.sum() == 700 * 2
