"""Device-side distributed sort / merge / group-by (VERDICT r3 item 3).

Reference: water/rapids/RadixOrder.java:20,74-85 (cluster-wide radix
partition + per-partition order), BinaryMerge.java (sorted-range merge),
AstGroup (distributed aggregation). Here the device path is a sample
sort + all_to_all exchange and a segment-reduction + psum over the
8-device CPU mesh; the host engines are the parity oracles."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.rapids import dist
from h2o3_tpu.rapids.groupby import group_by
from h2o3_tpu.rapids.merge import merge_frames, sort_frame


@pytest.fixture
def force_device(monkeypatch):
    """Lower the size threshold AND count device-path entries, so a
    silently-broken device branch (swallowed by the host fallback)
    cannot make the parity tests compare host against host."""
    monkeypatch.setattr(dist, "DIST_SORT_MIN", 1)
    calls = {"n": 0}
    real_sort, real_agg = dist.device_argsort_u64, dist.device_group_aggregate

    def counting_sort(*a, **kw):
        calls["n"] += 1
        return real_sort(*a, **kw)

    def counting_agg(*a, **kw):
        calls["n"] += 1
        return real_agg(*a, **kw)

    monkeypatch.setattr(dist, "device_argsort_u64", counting_sort)
    monkeypatch.setattr(dist, "device_group_aggregate", counting_agg)
    yield calls
    assert calls["n"] > 0, "device path never executed — parity test vacuous"


class TestDeviceArgsort:
    def test_exact_vs_numpy_1m(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=1_000_000)
        order = dist.device_argsort_u64(dist.encode_f64(x))
        np.testing.assert_array_equal(x[order], np.sort(x))

    def test_stable_on_duplicates(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, 50, size=200_000).astype(np.float64)
        order = dist.device_argsort_u64(dist.encode_f64(x))
        want = np.argsort(x, kind="stable")
        np.testing.assert_array_equal(order, want)

    def test_nan_and_inf_ordering(self):
        x = np.array([1.0, np.nan, -np.inf, np.inf, 0.0, -0.0, np.nan, -5.0])
        big = np.tile(x, 2000)
        order = dist.device_argsort_u64(dist.encode_f64(big))
        got = big[order]
        n_nan = np.isnan(big).sum()
        assert np.isnan(got[:n_nan]).all()  # NAs first (Merge.sort)
        rest = got[n_nan:]
        assert (rest[:-1] <= rest[1:]).all()

    def test_negative_zero_ties_with_positive_zero(self):
        # host oracles treat -0.0 == 0.0; the encoding must too, or
        # multi-key sorts order the tie block differently than numpy
        x = np.tile(np.array([-0.0, 0.0, 1.0, -1.0]), 5000)
        order = dist.device_argsort_u64(dist.encode_f64(x))
        np.testing.assert_array_equal(order, np.argsort(x, kind="stable"))

    def test_descending(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=100_000)
        order = dist.device_argsort_u64(dist.encode_f64(x, ascending=False))
        assert (np.diff(x[order]) <= 0).all()

    def test_skewed_distribution_balances(self):
        # heavy skew would starve fixed MSB buckets; sampled splitters
        # must still produce a correct (and complete) permutation
        rng = np.random.default_rng(3)
        x = np.concatenate([
            np.zeros(300_000), rng.normal(size=1000), np.full(100_000, 7.0)])
        order = dist.device_argsort_u64(dist.encode_f64(x))
        assert len(np.unique(order)) == len(x)
        np.testing.assert_array_equal(x[order], np.sort(x))


class TestDeviceSearchsorted:
    def test_matches_numpy_both_sides(self):
        rng = np.random.default_rng(4)
        table = np.sort(
            rng.integers(0, 1 << 60, size=250_000).astype(np.uint64))
        q = rng.integers(0, 1 << 60, size=100_001).astype(np.uint64)
        q[:1000] = table[:1000]  # guarantee exact hits
        for side in ("left", "right"):
            got = dist.device_searchsorted(table, q, side)
            np.testing.assert_array_equal(
                got, np.searchsorted(table, q, side))


def _sort_fixture(n):
    rng = np.random.default_rng(5)
    x = rng.normal(size=n)
    x[rng.random(n) < 0.01] = np.nan
    g = rng.integers(0, 9, size=n).astype(np.int32)
    return Frame([
        Column("x", x),
        Column("g", g, ColType.CAT, [f"l{i}" for i in range(9)]),
        Column("row", np.arange(n, dtype=np.float64)),
    ])


class TestSortFrameParity:
    def test_multikey_device_equals_host(self, force_device, monkeypatch):
        fr = _sort_fixture(1_000_000)
        dev = sort_frame(fr, by=[1, 0], ascending=[True, False])
        monkeypatch.setattr(dist, "DIST_SORT_MIN", 1 << 60)
        host = sort_frame(fr, by=[1, 0], ascending=[True, False])
        for c_d, c_h in zip(dev.columns, host.columns):
            np.testing.assert_array_equal(c_d.data, c_h.data)


class TestMergeParity:
    def _sides(self, n_left, n_right):
        rng = np.random.default_rng(6)
        lk = rng.integers(0, 1000, size=n_left).astype(np.float64)
        rk = rng.integers(0, 1000, size=n_right).astype(np.float64)
        left = Frame([
            Column("k", lk),
            Column("lv", rng.normal(size=n_left)),
        ])
        right = Frame([
            Column("k", rk),
            Column("rv", rng.normal(size=n_right)),
        ])
        return left, right

    @pytest.mark.parametrize("all_left", [False, True])
    def test_device_equals_host(self, all_left, force_device, monkeypatch):
        left, right = self._sides(400_000, 150_000)
        dev = merge_frames(left, right, [0], [0], all_left=all_left)
        monkeypatch.setattr(dist, "DIST_SORT_MIN", 1 << 60)
        host = merge_frames(left, right, [0], [0], all_left=all_left)
        assert dev.nrows == host.nrows
        # same multiset of rows; order within duplicate key runs may
        # legally differ between the two engines, so compare sorted
        d = np.lexsort([dev.col("rv").data, dev.col("lv").data,
                        dev.col("k").data])
        h = np.lexsort([host.col("rv").data, host.col("lv").data,
                        host.col("k").data])
        for name in ("k", "lv", "rv"):
            np.testing.assert_allclose(
                dev.col(name).data[d], host.col(name).data[h],
                rtol=0, atol=0, equal_nan=True)


class TestGroupByParity:
    def test_device_equals_host_1m(self, force_device, monkeypatch):
        n = 1_000_000
        rng = np.random.default_rng(7)
        g = rng.integers(0, 200, size=n).astype(np.int32)
        v = rng.normal(size=n) * 3 + 100.0  # offset stresses f32 moments
        v[rng.random(n) < 0.05] = np.nan
        fr = Frame([
            Column("g", g, ColType.CAT, [f"g{i}" for i in range(200)]),
            Column("v", v),
        ])
        aggs = [("nrow", -1, "all"), ("mean", 1, "rm"), ("sum", 1, "rm"),
                ("min", 1, "rm"), ("max", 1, "rm"), ("sd", 1, "rm"),
                ("var", 1, "rm")]
        dev = group_by(fr, [0], aggs)
        monkeypatch.setattr(dist, "DIST_SORT_MIN", 1 << 60)
        host = group_by(fr, [0], aggs)
        assert dev.nrows == host.nrows == 200
        np.testing.assert_array_equal(dev.col("g").data, host.col("g").data)
        np.testing.assert_array_equal(dev.col("nrow").data,
                                      host.col("nrow").data)
        # min/max pass through the f32 device lanes: identical up to
        # one f32 rounding of the centered value
        np.testing.assert_allclose(dev.col("min_v").data,
                                   host.col("min_v").data,
                                   rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(dev.col("max_v").data,
                                   host.col("max_v").data,
                                   rtol=1e-6, atol=1e-6)
        # f32 device accumulation: rel tolerance plus a small atol for
        # sums that nearly cancel
        np.testing.assert_allclose(dev.col("mean_v").data,
                                   host.col("mean_v").data,
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(dev.col("sum_v").data,
                                   host.col("sum_v").data,
                                   rtol=1e-4, atol=5e-2)
        np.testing.assert_allclose(dev.col("sd_v").data,
                                   host.col("sd_v").data,
                                   rtol=5e-3, atol=1e-4)
        np.testing.assert_allclose(dev.col("var_v").data,
                                   host.col("var_v").data,
                                   rtol=1e-2, atol=1e-4)

    def test_nrow_rm_column_name_matches_host(self, force_device,
                                              monkeypatch):
        rng = np.random.default_rng(12)
        n = 50_000
        v = rng.normal(size=n)
        v[:100] = np.nan
        fr = Frame([
            Column("g", rng.integers(0, 4, n).astype(np.int32),
                   ColType.CAT, list("wxyz")),
            Column("v", v),
        ])
        dev = group_by(fr, [0], [("nrow", 1, "rm")])
        monkeypatch.setattr(dist, "DIST_SORT_MIN", 1 << 60)
        host = group_by(fr, [0], [("nrow", 1, "rm")])
        assert dev.names == host.names == ["g", "nrow"]
        np.testing.assert_array_equal(dev.col("nrow").data,
                                      host.col("nrow").data)

    def test_mode_median_fall_back_to_host(self, monkeypatch):
        monkeypatch.setattr(dist, "DIST_SORT_MIN", 1)
        # order statistics are host-only: the device branch must decline,
        # not crash or mis-aggregate
        rng = np.random.default_rng(8)
        fr = Frame([
            Column("g", rng.integers(0, 3, 100).astype(np.int32),
                   ColType.CAT, ["a", "b", "c"]),
            Column("v", rng.normal(size=100)),
        ])
        out = group_by(fr, [0], [("median", 1, "rm")])
        assert out.nrows == 3

    def test_multi_key_groups(self, force_device, monkeypatch):
        n = 300_000
        rng = np.random.default_rng(9)
        fr = Frame([
            Column("a", rng.integers(0, 5, n).astype(np.int32),
                   ColType.CAT, list("abcde")),
            Column("b", rng.integers(0, 7, n).astype(np.float64)),
            Column("v", rng.normal(size=n)),
        ])
        aggs = [("nrow", -1, "all"), ("sum", 2, "rm")]
        dev = group_by(fr, [0, 1], aggs)
        monkeypatch.setattr(dist, "DIST_SORT_MIN", 1 << 60)
        host = group_by(fr, [0, 1], aggs)
        assert dev.nrows == host.nrows == 35
        np.testing.assert_array_equal(dev.col("a").data, host.col("a").data)
        np.testing.assert_array_equal(dev.col("b").data, host.col("b").data)
        np.testing.assert_array_equal(dev.col("nrow").data,
                                      host.col("nrow").data)
        np.testing.assert_allclose(dev.col("sum_v").data,
                                   host.col("sum_v").data,
                                   rtol=1e-4, atol=5e-2)


class TestRapidsIntegration:
    def test_sort_prim_uses_device_path(self, force_device):
        """(sort ...) over the rapids runtime lands in the device sort for
        large frames and still matches the host result."""
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.rapids import exec_rapids

        fr = _sort_fixture(300_000)
        DKV.put("dist_sort_src", fr)
        try:
            val = exec_rapids("(sort dist_sort_src [0] [1])")
            out = val.as_frame()
            x = out.col("x").data
            fin = x[~np.isnan(x)]
            assert (np.diff(fin) >= 0).all()
            assert np.isnan(x[: int(np.isnan(x).sum())]).all()
        finally:
            DKV.remove("dist_sort_src")
