"""The Jepsen-style chaos drills (scripts/chaos.py) as pytest tier:
every fast scenario must PASS all its invariants AND be deterministic —
two consecutive runs under the same seed produce byte-identical verdict
dicts.  The multi-process SIGKILL drills ride the slow tier.

Scenario bodies build real multi-Cloud topologies (and, slow tier, real
child processes), so each test is a full workload+nemesis+invariant run,
not a unit check."""

import os
import sys

import pytest

SCRIPTS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts")
if SCRIPTS not in sys.path:
    sys.path.insert(0, SCRIPTS)

import chaos  # noqa: E402

SEED = 7

# scenarios leave auto_recovery / DKV traffic behind; the module-end
# sweeper cleans up
pytestmark = pytest.mark.leaks_keys


def _run_twice(name):
    first = chaos.run_scenario(name, SEED)
    second = chaos.run_scenario(name, SEED)
    failed = sorted(k for k, v in first.items() if not v)
    assert not failed, f"{name} invariants failed: {failed}"
    assert first == second, (
        f"{name} is nondeterministic under seed {SEED}: "
        f"{first} != {second}")


def test_scenarios_registered():
    names = set(chaos.SCENARIOS)
    assert {"dup_reorder", "slow_node", "partition_gossip",
            "wedged_member", "kill_chunk_home", "kill_hist_home",
            "kill_rapids_home", "kill_serving_replica",
            "kill_search_member", "kill_fanout", "kill_grid"} <= names
    # the ISSUE floor: at least four scripted scenarios
    assert len(names) >= 4


def test_dup_reorder_deterministic():
    _run_twice("dup_reorder")


def test_slow_node_deterministic():
    _run_twice("slow_node")


def test_partition_gossip_deterministic():
    _run_twice("partition_gossip")


def test_wedged_member_deterministic():
    _run_twice("wedged_member")


def test_kill_chunk_home_deterministic():
    _run_twice("kill_chunk_home")


def test_kill_hist_home_deterministic():
    _run_twice("kill_hist_home")


def test_kill_rapids_home_deterministic():
    _run_twice("kill_rapids_home")


def test_kill_serving_replica_deterministic():
    _run_twice("kill_serving_replica")


def test_kill_search_member_deterministic():
    _run_twice("kill_search_member")


@pytest.mark.slow
def test_kill_fanout_deterministic():
    _run_twice("kill_fanout")


@pytest.mark.slow
def test_kill_grid_deterministic():
    _run_twice("kill_grid")
