"""Tree-algo feature completeness: weights, offset, monotone constraints,
extra distributions, categorical encodings.

Reference analogues: hex/tree/SharedTree.java weights plumbing,
hex/tree/gbm/GBM.java monotone path, hex/Distribution.java families,
hex/DataInfo one-hot (SURVEY.md §2.2). VERDICT r2 item 3."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models.tree import DRF, GBM, XGBoost


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _reg_frame(rng, n=2000, f=4, extra=None):
    X = rng.normal(size=(n, f))
    y = 2.0 * X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=n)
    d = {f"x{i}": X[:, i] for i in range(f)}
    d["y"] = y
    if extra:
        d.update(extra)
    return Frame.from_dict(d), X, y


# ---------------------------------------------------------------------------
# weights_column


@pytest.mark.parametrize("algo", [GBM, XGBoost])
def test_integer_weights_equal_row_replication(algo, rng):
    """A row with weight k must act exactly like k copies of the row
    (SharedTree weighted Σg/Σh semantics). Discrete feature values so the
    quantile bin edges partition both frames' rows identically."""
    n = 600
    X = rng.integers(0, 8, size=(n, 3)).astype(np.float64)
    y = X[:, 0] - 0.5 * X[:, 1] + 0.1 * rng.normal(size=n)
    w = rng.integers(1, 4, size=n).astype(np.float64)

    fr_w = Frame.from_dict(
        {"x0": X[:, 0], "x1": X[:, 1], "x2": X[:, 2], "y": y, "w": w}
    )
    rep = np.repeat(np.arange(n), w.astype(int))
    fr_rep = Frame.from_dict(
        {"x0": X[rep, 0], "x1": X[rep, 1], "x2": X[rep, 2], "y": y[rep]}
    )

    kw = dict(response_column="y", ntrees=5, max_depth=3, seed=7, min_rows=1.0)
    m_w = algo(weights_column="w", **kw).train(fr_w)
    m_rep = algo(**kw).train(fr_rep)

    pred_w = m_w.predict(fr_w).col("predict").numeric_view()
    pred_rep = (
        m_rep.predict(fr_w[["x0", "x1", "x2"]]).col("predict").numeric_view()
    )
    np.testing.assert_allclose(pred_w, pred_rep, rtol=1e-4, atol=1e-5)


def test_zero_weight_rows_are_ignored(rng):
    n = 500
    X = rng.normal(size=(n, 2))
    y = X[:, 0] + 0.1 * rng.normal(size=n)
    # poison half the rows with garbage labels but weight 0
    y_poisoned = y.copy()
    poison = rng.random(n) < 0.5
    y_poisoned[poison] = 1000.0
    w = np.where(poison, 0.0, 1.0)

    fr = Frame.from_dict({"x0": X[:, 0], "x1": X[:, 1], "y": y_poisoned, "w": w})
    fr_clean = Frame.from_dict(
        {"x0": X[~poison, 0], "x1": X[~poison, 1], "y": y[~poison]}
    )
    kw = dict(response_column="y", ntrees=5, max_depth=3, seed=3, min_rows=1.0)
    m = GBM(weights_column="w", **kw).train(fr)
    m_clean = GBM(**kw).train(fr_clean)
    grid = fr[["x0", "x1"]]
    np.testing.assert_allclose(
        m.predict(grid).col("predict").numeric_view(),
        m_clean.predict(grid).col("predict").numeric_view(),
        rtol=1e-4, atol=1e-5,
    )


def test_drf_weights_run_and_beat_garbage(rng):
    fr, X, y = _reg_frame(rng, n=800, extra={"w": np.ones(800)})
    m = DRF(response_column="y", weights_column="w", ntrees=10, seed=1).train(fr)
    assert m.training_metrics.r2 > 0.5


# ---------------------------------------------------------------------------
# offset_column


def test_offset_is_baseline_margin(rng):
    """y = offset + signal: with offset_column the model learns only the
    signal, and scoring adds the frame's offset back (Model.score)."""
    n = 1500
    x = rng.normal(size=n)
    off = rng.choice([0.0, 5.0], size=n)
    y = off + 2.0 * x + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({"x": x, "off": off, "y": y})
    m = GBM(
        response_column="y", offset_column="off",
        ntrees=20, max_depth=3, seed=5, min_rows=5.0,
    ).train(fr)
    pred = m.predict(fr).col("predict").numeric_view()
    resid = y - pred
    assert np.sqrt(np.mean(resid**2)) < 0.6
    # a model that ignored the offset would be off by ~2.5 on half the rows
    m_no = GBM(response_column="y", ignored_columns=["off"], ntrees=20,
               max_depth=3, seed=5, min_rows=5.0).train(fr)
    rmse_no = np.sqrt(
        np.mean((y - m_no.predict(fr[["x"]]).col("predict").numeric_view()) ** 2)
    )
    assert np.sqrt(np.mean(resid**2)) < rmse_no / 2

    # offset column must be present at scoring time
    with pytest.raises(ValueError, match="offset"):
        m.predict(fr[["x"]])


# ---------------------------------------------------------------------------
# monotone constraints


@pytest.mark.parametrize("algo", [GBM, XGBoost])
@pytest.mark.parametrize("direction", [1, -1])
def test_monotone_constraint_property(algo, direction, rng):
    """Predictions must be monotone in the constrained feature for any
    fixed values of the others — even when the data is noisy enough that an
    unconstrained fit is not."""
    n = 3000
    x = rng.uniform(-3, 3, size=n)
    z = rng.normal(size=n)
    y = direction * x + 0.3 * z + 1.5 * rng.normal(size=n)
    fr = Frame.from_dict({"x": x, "z": z, "y": y})
    m = algo(
        response_column="y",
        monotone_constraints={"x": direction},
        ntrees=30, max_depth=4, seed=11, min_rows=5.0,
    ).train(fr)

    grid_x = np.linspace(-3, 3, 101)
    for zval in (-1.0, 0.0, 1.0):
        g = Frame.from_dict({"x": grid_x, "z": np.full_like(grid_x, zval)})
        p = m.predict(g).col("predict").numeric_view()
        diffs = direction * np.diff(p)
        assert (diffs >= -1e-6).all(), (
            f"monotonicity violated at z={zval}: min step {diffs.min()}"
        )
    # the constraint shouldn't destroy the fit
    assert m.training_metrics.r2 > 0.3


def test_monotone_constraint_validation(rng):
    fr, _, _ = _reg_frame(rng, n=200)
    with pytest.raises(ValueError, match="not in predictors"):
        GBM(response_column="y", monotone_constraints={"nope": 1},
            ntrees=2).train(fr)
    with pytest.raises(ValueError, match="must be -1, 0 or 1"):
        GBM(response_column="y", monotone_constraints={"x0": 2},
            ntrees=2).train(fr)


# ---------------------------------------------------------------------------
# distributions (hex/Distribution.java families)


def test_tweedie_deviance_decreases(rng):
    n = 3000
    x = rng.normal(size=n)
    mu = np.exp(0.5 * x)
    # tweedie-ish: poisson-gamma mixture with exact zeros
    y = np.where(rng.random(n) < 0.3, 0.0, rng.gamma(2.0, mu / 2.0))
    fr = Frame.from_dict({"x": x, "y": y})
    m = GBM(
        response_column="y", distribution="tweedie", tweedie_power=1.5,
        ntrees=30, max_depth=3, seed=2, stopping_rounds=0,
        score_tree_interval=5, min_rows=10.0,
    ).train(fr)
    # deviance trace from scoring_history requires stopping_rounds; instead
    # check fit quality directly: predictions on response scale, positive
    pred = m.predict(fr).col("predict").numeric_view()
    assert (pred > 0).all()
    corr = np.corrcoef(pred, mu)[0, 1]
    assert corr > 0.7


def test_gamma_distribution(rng):
    n = 3000
    x = rng.normal(size=n)
    mu = np.exp(1.0 + 0.7 * x)
    y = rng.gamma(3.0, mu / 3.0)
    fr = Frame.from_dict({"x": x, "y": y})
    m = GBM(response_column="y", distribution="gamma", ntrees=30,
            max_depth=3, seed=2, min_rows=10.0).train(fr)
    pred = m.predict(fr).col("predict").numeric_view()
    assert (pred > 0).all()
    assert np.corrcoef(np.log(pred), np.log(mu))[0, 1] > 0.85


def test_huber_is_robust_to_outliers(rng):
    n = 2000
    x = rng.normal(size=n)
    y = 2.0 * x + 0.2 * rng.normal(size=n)
    out = rng.random(n) < 0.05
    y[out] += rng.choice([-1, 1], size=out.sum()) * 50.0
    fr = Frame.from_dict({"x": x, "y": y})
    kw = dict(response_column="y", ntrees=30, max_depth=3, seed=4, min_rows=10.0)
    m_h = GBM(distribution="huber", **kw).train(fr)
    m_g = GBM(distribution="gaussian", **kw).train(fr)
    clean = ~out
    pred_h = m_h.predict(fr).col("predict").numeric_view()
    pred_g = m_g.predict(fr).col("predict").numeric_view()
    rmse_h = np.sqrt(np.mean((pred_h[clean] - 2 * x[clean]) ** 2))
    rmse_g = np.sqrt(np.mean((pred_g[clean] - 2 * x[clean]) ** 2))
    assert rmse_h < rmse_g


def test_quantile_alpha(rng):
    n = 4000
    x = rng.normal(size=n)
    y = x + rng.normal(size=n)
    fr = Frame.from_dict({"x": x, "y": y})
    m = GBM(response_column="y", distribution="quantile", quantile_alpha=0.9,
            ntrees=40, max_depth=3, seed=6, min_rows=20.0).train(fr)
    frac_below = np.mean(y <= m.predict(fr).col("predict").numeric_view())
    assert 0.82 < frac_below < 0.97


def test_negative_response_rejected_for_log_links(rng):
    fr = Frame.from_dict({"x": np.arange(10.0), "y": np.linspace(-1, 1, 10)})
    for dist in ("poisson", "gamma", "tweedie"):
        with pytest.raises(ValueError, match="negative|positive"):
            GBM(response_column="y", distribution=dist, ntrees=2).train(fr)
    # gamma additionally rejects zeros (near-zero hessians explode leaves)
    fr0 = Frame.from_dict({"x": np.arange(10.0), "y": np.r_[0.0, np.ones(9)]})
    with pytest.raises(ValueError, match="strictly positive"):
        GBM(response_column="y", distribution="gamma", ntrees=2).train(fr0)


# ---------------------------------------------------------------------------
# categorical_encoding


def test_one_hot_explicit_isolates_levels(rng):
    """A target depending on a single mid-domain level is hard for ordinal
    splits (needs 2 cuts) but trivial for one-hot (1 cut)."""
    n = 3000
    levels = np.array(["a", "b", "c", "d", "e"])
    codes = rng.integers(0, 5, size=n)
    y = (codes == 2).astype(np.float64) * 3.0 + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({"cat": levels[codes], "y": y})
    m = GBM(
        response_column="y", categorical_encoding="one_hot_explicit",
        ntrees=40, learn_rate=0.3, max_depth=2, seed=9, min_rows=10.0,
    ).train(fr)
    assert m.training_metrics.r2 > 0.95
    vi = m.variable_importances()
    assert "cat.c" in vi  # expanded names
    assert vi["cat.c"] == max(vi.values())

    # mojo round-trip respects the encoding
    import os
    import tempfile

    from h2o3_tpu.genmodel import load_mojo
    from h2o3_tpu.models.mojo_export import write_mojo

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.mojo")
        write_mojo(m, path)
        mm = load_mojo(path)
        scored = mm.score({"cat": levels[codes[:50]].tolist()})
        np.testing.assert_allclose(
            scored, m.predict(fr[["cat"]]).col("predict").numeric_view()[:50],
            rtol=1e-5, atol=1e-6,
        )


def test_bad_categorical_encoding_rejected(rng):
    fr, _, _ = _reg_frame(rng, n=100)
    with pytest.raises(ValueError, match="categorical_encoding"):
        GBM(response_column="y", categorical_encoding="eigen", ntrees=2).train(fr)


# ---------------------------------------------------------------------------
# review follow-ups: weighted min_rows, monotone validation, MOJO offset


def test_min_rows_uses_weighted_counts(rng):
    """min_rows compares against the weighted observation count (DHistogram
    Σw): tiny-weight rows must not satisfy it by headcount alone."""
    n = 60
    x = np.r_[np.zeros(n // 2), np.ones(n // 2)]
    y = x * 10.0
    w = np.full(n, 0.1)
    fr = Frame.from_dict({"x": x, "y": y, "w": w})
    # each side has 30 rows but Σw = 3 < min_rows=4: the root must not split
    m = GBM(response_column="y", weights_column="w", ntrees=1, max_depth=2,
            learn_rate=1.0, min_rows=4.0, seed=1).train(fr)
    p = m.predict(fr[["x"]]).col("predict").numeric_view()
    assert np.allclose(p, p[0]), "tiny-weight rows satisfied min_rows by headcount"
    # same data with weight 1.0 rows: Σw = 30 >= 4, split happens
    fr2 = Frame.from_dict({"x": x, "y": y, "w": np.ones(n)})
    m2 = GBM(response_column="y", weights_column="w", ntrees=1, max_depth=2,
             learn_rate=1.0, min_rows=4.0, seed=1).train(fr2)
    p2 = m2.predict(fr2[["x"]]).col("predict").numeric_view()
    assert not np.allclose(p2, p2[0])


def test_monotone_multinomial_rejected(rng):
    n = 300
    fr = Frame.from_dict({
        "x": rng.normal(size=n),
        "y": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
    })
    with pytest.raises(ValueError, match="multinomial"):
        GBM(response_column="y", monotone_constraints={"x": 1}, ntrees=2).train(fr)


def test_mojo_offset_parity(rng):
    import os
    import tempfile

    from h2o3_tpu.genmodel import load_mojo
    from h2o3_tpu.models.mojo_export import write_mojo

    n = 800
    x = rng.normal(size=n)
    off = rng.choice([0.0, 3.0], size=n)
    y = off + x + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({"x": x, "off": off, "y": y})
    m = GBM(response_column="y", offset_column="off", ntrees=10,
            max_depth=3, seed=8, min_rows=5.0).train(fr)
    want = m.predict(fr).col("predict").numeric_view()

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.mojo")
        write_mojo(m, path)
        mm = load_mojo(path)
        got = mm.score({"x": x, "off": off})
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        # missing offset column must raise, not silently shift
        with pytest.raises(ValueError, match="off"):
            mm.score({"x": x[:5]})
