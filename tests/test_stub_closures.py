"""Round-4 stub closures: KMeans estimate_k, custom-distribution UDFs,
and the key-leak fixture itself.

Reference: hex/kmeans/KMeans.java:80,278,301,398-414 (deterministic
k-finder: split largest cluster, stop on relative tot_withinss
improvement), water/udf/CDistributionFunc.java:12 (user link/init/
gradient quartet plugged into SharedTree)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame


def _blobs(rng, k=4, per=150, spread=0.25):
    centers = rng.normal(size=(k, 3)) * 6
    X = np.concatenate([
        centers[i] + rng.normal(size=(per, 3)) * spread for i in range(k)
    ])
    rng.shuffle(X)
    return Frame([Column(f"x{j}", X[:, j]) for j in range(3)])


class TestEstimateK:
    def test_finds_obvious_cluster_count(self, rng):
        from h2o3_tpu.models.kmeans import KMeans, KMeansParameters

        fr = _blobs(rng, k=4)
        m = KMeans(KMeansParameters(k=10, estimate_k=True,
                                    max_iterations=20)).train(fr)
        k_found = m.centers_std.shape[0]
        assert k_found == 4, f"expected 4 clusters, estimated {k_found}"
        # every found cluster is populated
        assert (m.size > 0).all()
        from h2o3_tpu.keyed import DKV

        DKV.remove(m.key)

    def test_k_is_the_cap(self, rng):
        from h2o3_tpu.models.kmeans import KMeans, KMeansParameters

        fr = _blobs(rng, k=6)
        m = KMeans(KMeansParameters(k=3, estimate_k=True,
                                    max_iterations=15)).train(fr)
        assert m.centers_std.shape[0] <= 3
        from h2o3_tpu.keyed import DKV

        DKV.remove(m.key)

    def test_deterministic(self, rng):
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.kmeans import KMeans, KMeansParameters

        fr = _blobs(rng, k=3)
        m1 = KMeans(KMeansParameters(k=8, estimate_k=True, seed=1,
                                     max_iterations=15)).train(fr)
        m2 = KMeans(KMeansParameters(k=8, estimate_k=True, seed=999,
                                     max_iterations=15)).train(fr)
        # seed is ignored under estimate_k (KMeans.java:86) — identical
        np.testing.assert_allclose(np.sort(m1.centers_std, axis=0),
                                   np.sort(m2.centers_std, axis=0),
                                   rtol=1e-5)
        DKV.remove(m1.key)
        DKV.remove(m2.key)


class TestCustomDistribution:
    def test_custom_gaussian_matches_builtin(self, rng):
        """A custom objective implementing the gaussian gradients must
        train the same trees as distribution='gaussian'."""
        import jax.numpy as jnp

        from h2o3_tpu import udf
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.tree.gbm import GBM

        udf.register_distribution(
            "mygauss",
            grad_hess=lambda y, f: (f - y, jnp.ones_like(f)),
            init=lambda y, w: float(np.average(
                y, weights=w if w is not None else None)),
        )
        n = 400
        X = rng.normal(size=(n, 3))
        y = X[:, 0] * 2 - X[:, 1] + rng.normal(size=n) * 0.1
        fr = Frame([Column(f"x{j}", X[:, j]) for j in range(3)]
                   + [Column("y", y)])
        kw = dict(ntrees=5, max_depth=3, response_column="y", seed=3,
                  min_rows=2)
        m_custom = GBM(distribution="custom:mygauss", **kw).train(fr)
        m_ref = GBM(distribution="gaussian", **kw).train(fr)
        np.testing.assert_allclose(
            m_custom.predict(fr).col("predict").data,
            m_ref.predict(fr).col("predict").data, rtol=1e-5)
        DKV.remove(m_custom.key)
        DKV.remove(m_ref.key)

    def test_custom_link_inverse_applies(self, rng):
        import jax.numpy as jnp

        from h2o3_tpu import udf
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.tree.gbm import GBM

        udf.register_distribution(
            "mypoisson",
            grad_hess=lambda y, f: (jnp.exp(f) - y,
                                    jnp.maximum(jnp.exp(f), 1e-16)),
            init=lambda y, w: float(np.log(max(np.mean(y), 1e-10))),
            link_inv=lambda m: np.exp(m),
        )
        n = 500
        X = rng.normal(size=(n, 2))
        y = rng.poisson(np.exp(0.5 * X[:, 0] + 0.2))
        fr = Frame([Column("x0", X[:, 0]), Column("x1", X[:, 1]),
                    Column("y", y.astype(np.float64))])
        kw = dict(ntrees=8, max_depth=3, response_column="y", seed=4,
                  min_rows=4)
        m_custom = GBM(distribution="custom:mypoisson", **kw).train(fr)
        m_ref = GBM(distribution="poisson", **kw).train(fr)
        p_c = m_custom.predict(fr).col("predict").data
        p_r = m_ref.predict(fr).col("predict").data
        assert (p_c > 0).all()  # link applied: response scale
        np.testing.assert_allclose(p_c, p_r, rtol=1e-4)
        DKV.remove(m_custom.key)
        DKV.remove(m_ref.key)

    def test_unregistered_name_fails_fast(self, rng):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = Frame([Column("x", np.arange(50.0)),
                    Column("y", np.arange(50.0) * 2)])
        with pytest.raises(KeyError, match="no custom distribution"):
            GBM(distribution="custom:nope", ntrees=2,
                response_column="y").train(fr)


class TestKeyLeakFixture:
    def test_clean_test_passes(self, rng):
        from h2o3_tpu.keyed import DKV

        fr = Frame([Column("a", np.arange(4.0))])
        DKV.put("leakcheck_tmp", fr)
        DKV.remove("leakcheck_tmp")

    @pytest.mark.leaks_keys
    def test_marked_test_may_leak(self, rng):
        from h2o3_tpu.keyed import DKV

        fr = Frame([Column("a", np.arange(4.0))])
        DKV.put("leakcheck_marked", fr)
        # no cleanup: the module sweeper removes it; unmarked, this
        # would fail with "DKV key leak"


class TestGAMFamilies:
    """Round-4 GAM depth: thin-plate (bs=1), monotone I-splines (bs=2),
    M-splines (bs=3), per-column specs, user knots (hex/gam/GamSplines:
    ThinPlate*, NBSplinesTypeI/II)."""

    def _wavy(self, rng, n=600):
        x = rng.uniform(-3, 3, size=n)
        y = np.sin(x) * 2 + 0.1 * rng.normal(size=n)
        return Frame([Column("x", x),
                      Column("z", rng.normal(size=n)),
                      Column("y", y)])

    @pytest.mark.parametrize("bs", [0, 1, 3])
    def test_families_fit_nonlinear_signal(self, rng, bs):
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.gam import GAM

        fr = self._wavy(rng)
        m = GAM(response_column="y", gam_columns=["x"], num_knots=10,
                bs=bs, scale=0.1).train(fr)
        pred = m.predict(fr).col("predict").data
        y = fr.col("y").data
        ss_res = ((y - pred) ** 2).sum()
        ss_tot = ((y - y.mean()) ** 2).sum()
        assert 1 - ss_res / ss_tot > 0.9, f"bs={bs} underfits"
        DKV.remove(m.key)

    def test_monotone_isplines_are_monotone(self, rng):
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.gam import GAM

        n = 600
        x = rng.uniform(0, 4, size=n)
        # monotone signal + noise that tempts a wiggle
        y = np.log1p(x) * 3 + rng.normal(size=n) * 0.4
        fr = Frame([Column("x", x), Column("y", y)])
        m = GAM(response_column="y", gam_columns=["x"], num_knots=8,
                bs=2, scale=0.01).train(fr)
        grid = Frame([Column("x", np.linspace(0.05, 3.95, 200))])
        pred = m.predict(grid).col("predict").data
        assert (np.diff(pred) >= -1e-8).all(), "I-spline fit not monotone"
        # and it actually fits
        tr = m.predict(fr).col("predict").data
        assert np.corrcoef(tr, y)[0, 1] > 0.9
        DKV.remove(m.key)

    def test_per_column_specs_and_user_knots(self, rng):
        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.gam import GAM

        n = 500
        x1 = rng.uniform(-2, 2, size=n)
        x2 = rng.uniform(0, 5, size=n)
        y = np.sin(x1 * 2) + 0.5 * x2 + 0.1 * rng.normal(size=n)
        fr = Frame([Column("x1", x1), Column("x2", x2), Column("y", y)])
        m = GAM(response_column="y", gam_columns=["x1", "x2"],
                num_knots=[10, 5], bs=[0, 3], scale=[0.05, 1.0],
                knots=[None, [0.0, 1.0, 2.5, 4.0, 5.0]]).train(fr)
        assert any(k.startswith("x1_cr_") for k in m.coefficients)
        assert any(k.startswith("x2_ms_") for k in m.coefficients)
        pred = m.predict(fr).col("predict").data
        assert np.corrcoef(pred, y)[0, 1] > 0.95
        DKV.remove(m.key)

    def test_misaligned_lists_rejected(self, rng):
        from h2o3_tpu.models.gam import GAM

        fr = self._wavy(rng)
        with pytest.raises(ValueError, match="align"):
            GAM(response_column="y", gam_columns=["x"],
                num_knots=[5, 6]).train(fr)


class TestConcurrentBuildScopes:
    def test_failing_build_cannot_delete_concurrent_builds_keys(self, rng):
        """Scope stacks are per-thread (water/Scope.java): a build that
        fails in one thread must sweep ONLY its own keys, never a
        concurrently-running build's model (review finding)."""
        import threading
        import time

        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.models.glm import GLM, GLMParameters

        n = 300
        X = rng.normal(size=(n, 3))
        y = (X[:, 0] > 0).astype(np.int32)
        fr = Frame([Column(f"x{j}", X[:, j]) for j in range(3)]
                   + [Column("y", y, ColType.CAT, ["n", "p"])])
        orig_fit = GLM._fit
        barrier = threading.Barrier(2)

        def slow_fit(self, frame, valid=None):
            m = orig_fit(self, frame, valid)
            barrier.wait(timeout=30)  # hold until the failing build dies
            time.sleep(0.3)
            return m

        def dying_fit(self, frame, valid=None):
            barrier.wait(timeout=30)
            raise RuntimeError("boom")

        results = {}

        def good():
            GLM._fit = slow_fit  # patched per-thread via closure order
            results["model"] = GLM(GLMParameters(
                response_column="y", family="binomial")).train(fr)

        # run the good build in a thread with slow_fit, the bad one here
        t = threading.Thread(target=good)
        t.start()
        time.sleep(0.1)
        bad = GLM(GLMParameters(response_column="y", family="binomial"))
        bad._fit = dying_fit.__get__(bad)
        with pytest.raises(RuntimeError):
            bad.train(fr)
        t.join(timeout=60)
        GLM._fit = orig_fit
        m = results.get("model")
        assert m is not None, "good build never finished"
        # the survivor's model key must still resolve
        assert DKV.get(m.key) is m
        DKV.remove(m.key)


class TestThinPlateMultiPredictor:
    """Joint multi-predictor thin-plate smoothers (VERDICT r4 weak 4:
    hex/gam GamSplines ThinPlate* + GamUtilsThinPlateRegression)."""

    def _surface(self, seed=5, n=600):
        rng = np.random.default_rng(seed)
        x1 = rng.uniform(-2, 2, n)
        x2 = rng.uniform(-2, 2, n)
        y = np.sin(1.5 * x1) * np.cos(1.5 * x2) + rng.normal(size=n) * 0.05
        fr = Frame([Column("x1", x1), Column("x2", x2), Column("y", y)])
        return fr, x1, x2, y

    def test_joint_smoother_beats_additive(self):
        from h2o3_tpu.models.gam import GAM

        fr, x1, x2, y = self._surface()
        joint = GAM(response_column="y", gam_columns=[["x1", "x2"]],
                    num_knots=30, bs=1, lambda_=0.0, scale=1e-4,
                    standardize=False).train(fr)
        additive = GAM(response_column="y", gam_columns=["x1", "x2"],
                       num_knots=10, lambda_=0.0, scale=1e-4,
                       standardize=False).train(fr)
        # sin(x1)cos(x2) is a pure interaction: the additive model cannot
        # represent it, the joint surface can
        try:
            assert joint.residual_deviance < 0.5 * additive.residual_deviance
            pred = joint.predict(fr).col(0).numeric_view()
            r2 = 1 - np.sum((pred - y) ** 2) / np.sum((y - y.mean()) ** 2)
            assert r2 > 0.9, r2
        finally:
            from h2o3_tpu.keyed import DKV

            DKV.remove(joint.key)
            DKV.remove(additive.key)

    def test_scoring_math_matches_genmodel_port(self):
        """tp_distance / tp_polynomials vs an independent transliteration
        of GamUtilsThinPlateRegression (different code path)."""
        import math

        from h2o3_tpu.models.gam import (
            tp_distance, tp_m, tp_poly_exponents, tp_polynomials)

        rng = np.random.default_rng(0)
        d, K, n = 2, 7, 11
        knots = rng.normal(size=(K, d))
        X = rng.normal(size=(n, d))
        m = tp_m(d)
        # independent port: scalar loops straight from the Java
        const = (math.pow(-1, m + 1 + d / 2.0)
                 / (math.pow(2, 2 * m - 1) * math.pow(math.pi, d / 2.0)
                    * math.factorial(m - 1) * math.factorial(m - d // 2)))
        want = np.zeros((n, K))
        for r in range(n):
            for k in range(K):
                s = sum((X[r, p] - knots[k, p]) ** 2 for p in range(d))
                dist = math.sqrt(s) ** (2 * m - d)
                v = const * dist
                if dist != 0:
                    v *= math.log(dist)
                want[r, k] = v
        np.testing.assert_allclose(tp_distance(X, knots, m), want,
                                   rtol=1e-12)
        expo = tp_poly_exponents(d, m)
        got = tp_polynomials(X, expo)
        for j, t in enumerate(expo):
            col = np.ones(n)
            for p, e in enumerate(t):
                col *= X[:, p] ** e
            np.testing.assert_allclose(got[:, j], col, rtol=1e-14)

    def test_zcs_annihilates_polynomials(self):
        """The distance block must be orthogonal to the polynomial null
        space at the knots (the T'delta = 0 constraint)."""
        from h2o3_tpu.models.gam import _make_tp_spec, tp_polynomials

        rng = np.random.default_rng(1)
        X = rng.normal(size=(200, 2))
        spec = _make_tp_spec(["a", "b"], X, 20)
        T = tp_polynomials(spec.knots, spec.expo)
        np.testing.assert_allclose(T.T @ spec.zcs,
                                   np.zeros((T.shape[1],
                                             spec.zcs.shape[1])),
                                   atol=1e-10)
        # penalty is PSD
        w = np.linalg.eigvalsh((spec.penalty + spec.penalty.T) / 2)
        assert w.min() > -1e-9

    def test_validations(self):
        from h2o3_tpu.models.gam import GAM

        fr, *_ = self._surface(n=100)
        from h2o3_tpu.keyed import DKV

        before = set(DKV.keys()) if hasattr(DKV, "keys") else None
        with pytest.raises(ValueError, match="num_knots"):
            GAM(response_column="y", gam_columns=[["x1", "x2"]],
                num_knots=4, bs=1, standardize=False).train(fr)
        with pytest.raises(ValueError, match="bs=1"):
            GAM(response_column="y", gam_columns=[["x1", "x2"]],
                num_knots=20, standardize=False).train(fr)
        with pytest.raises(ValueError, match="thin-plate"):
            GAM(response_column="y", gam_columns=[["x1", "x2"]],
                num_knots=20, bs=2, standardize=False).train(fr)
        if before is not None:  # failed builds must not leak model keys
            for k in set(DKV.keys()) - before:
                DKV.remove(k)

    def test_persist_roundtrip(self, tmp_path):
        import os

        from h2o3_tpu.models.gam import GAM
        from h2o3_tpu.models.persist import load_model, save_model

        fr, x1, x2, y = self._surface(n=300)
        m = GAM(response_column="y", gam_columns=[["x1", "x2"]],
                num_knots=20, bs=1, lambda_=0.0, standardize=False).train(fr)
        path = os.path.join(tmp_path, "tp.h2o3")
        m2 = None
        try:
            save_model(m, path)
            m2 = load_model(path)
            np.testing.assert_array_equal(
                m.predict(fr).col(0).numeric_view(),
                m2.predict(fr).col(0).numeric_view())
        finally:
            from h2o3_tpu.keyed import DKV

            DKV.remove(m.key)
            if m2 is not None and m2.key != m.key:
                DKV.remove(m2.key)
