"""AutoML orchestration (h2o-automl, SURVEY.md §2.5)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.automl import AutoML


@pytest.fixture
def rng():
    return np.random.default_rng(21)


def _frame(rng, n=400):
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] - 0.7 * X[:, 1] + 0.3 * X[:, 2] * X[:, 3]
         + rng.normal(size=n) * 0.5 > 0).astype(np.int32)
    cols = [Column(f"x{i}", X[:, i]) for i in range(4)]
    cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
    return Frame(cols)


class TestAutoML:
    def test_budgeted_run_builds_leaderboard(self, rng):
        fr = _frame(rng)
        aml = AutoML(max_models=4, nfolds=3, seed=1,
                     include_algos=["glm", "gbm", "drf"])
        leader = aml.train(y="y", training_frame=fr)
        lb = aml.leaderboard.as_table()
        assert 1 <= len(lb) <= 4
        # leaderboard is sorted by AUC descending for binomial
        metrics = [r["metric"] for r in lb]
        assert metrics == sorted(metrics, reverse=True)
        assert leader.key == lb[0]["model_id"]
        assert metrics[0] > 0.7
        # CV metrics drove the ranking
        assert leader.cross_validation_metrics is not None

    def test_event_log_records_steps(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=2, nfolds=2, seed=2, include_algos=["glm", "gbm"])
        aml.train(y="y", training_frame=fr)
        stages = {e["stage"] for e in aml.event_log.events}
        assert "Workflow" in stages and "ModelTraining" in stages

    def test_exclude_algos(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=3, nfolds=2, seed=3,
                     exclude_algos=["xgboost", "deeplearning", "stackedensemble",
                                    "drf", "gbm"])
        aml.train(y="y", training_frame=fr)
        algos = {m.algo_name for m in aml.leaderboard.models}
        assert algos == {"glm"}

    def test_stacked_ensemble_step(self, rng):
        fr = _frame(rng)
        aml = AutoML(max_models=6, nfolds=3, seed=4,
                     include_algos=["glm", "gbm", "drf", "stackedensemble"])
        aml.train(y="y", training_frame=fr)
        algos = [m.algo_name for m in aml.leaderboard.models]
        assert "stackedensemble" in algos

    def test_x_restricts_predictors(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=1, nfolds=2, seed=5, include_algos=["glm"])
        leader = aml.train(y="y", training_frame=fr, x=["x0", "x1"])
        assert set(leader.data_info.predictor_names) == {"x0", "x1"}

    def test_max_runtime_budget(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=0, max_runtime_secs=0.001, nfolds=2, seed=6,
                     include_algos=["glm", "gbm", "drf"])
        # budget expires after the first step at most; never zero models only
        # if even the first failed — accept RuntimeError or >=1 model
        try:
            aml.train(y="y", training_frame=fr)
            assert len(aml.leaderboard.models) >= 1
        except RuntimeError:
            pass


class TestAutoMLOverClient:
    def test_client_automl(self, rng):
        from h2o3_tpu import client as h2o

        h2o.init()
        try:
            X = rng.normal(size=(200, 2))
            y = np.where(X[:, 0] + rng.normal(size=200) * 0.3 > 0, "a", "b")
            csv = "x0,x1,y\n" + "\n".join(
                f"{a:.4f},{b:.4f},{c}" for (a, b), c in zip(X, y)
            )
            fr = h2o.upload_csv(csv)
            aml = h2o.H2OAutoML(max_models=2, nfolds=2, seed=1,
                                include_algos=["glm", "gbm"])
            aml.train(y="y", training_frame=fr)
            assert aml.leader is not None
            assert len(aml.leaderboard) >= 1
            pred = aml.leader.predict(fr)
            assert pred.nrows == 200
        finally:
            h2o.shutdown()
