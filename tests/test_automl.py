"""AutoML orchestration (h2o-automl, SURVEY.md §2.5)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.automl import AutoML


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture
def rng():
    return np.random.default_rng(21)


def _frame(rng, n=400):
    X = rng.normal(size=(n, 4))
    y = (X[:, 0] - 0.7 * X[:, 1] + 0.3 * X[:, 2] * X[:, 3]
         + rng.normal(size=n) * 0.5 > 0).astype(np.int32)
    cols = [Column(f"x{i}", X[:, i]) for i in range(4)]
    cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
    return Frame(cols)


class TestAutoML:
    def test_budgeted_run_builds_leaderboard(self, rng):
        fr = _frame(rng)
        aml = AutoML(max_models=4, nfolds=3, seed=1,
                     include_algos=["glm", "gbm", "drf"])
        leader = aml.train(y="y", training_frame=fr)
        lb = aml.leaderboard.as_table()
        assert 1 <= len(lb) <= 4
        # leaderboard is sorted by AUC descending for binomial
        metrics = [r["metric"] for r in lb]
        assert metrics == sorted(metrics, reverse=True)
        assert leader.key == lb[0]["model_id"]
        assert metrics[0] > 0.7
        # CV metrics drove the ranking
        assert leader.cross_validation_metrics is not None

    def test_event_log_records_steps(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=2, nfolds=2, seed=2, include_algos=["glm", "gbm"])
        aml.train(y="y", training_frame=fr)
        stages = {e["stage"] for e in aml.event_log.events}
        assert "Workflow" in stages and "ModelTraining" in stages

    def test_exclude_algos(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=3, nfolds=2, seed=3,
                     exclude_algos=["xgboost", "deeplearning", "stackedensemble",
                                    "drf", "gbm"])
        aml.train(y="y", training_frame=fr)
        algos = {m.algo_name for m in aml.leaderboard.models}
        assert algos == {"glm"}

    def test_stacked_ensemble_step(self, rng):
        fr = _frame(rng)
        aml = AutoML(max_models=6, nfolds=3, seed=4,
                     include_algos=["glm", "gbm", "drf", "stackedensemble"])
        aml.train(y="y", training_frame=fr)
        algos = [m.algo_name for m in aml.leaderboard.models]
        assert "stackedensemble" in algos

    def test_x_restricts_predictors(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=1, nfolds=2, seed=5, include_algos=["glm"])
        leader = aml.train(y="y", training_frame=fr, x=["x0", "x1"])
        assert set(leader.data_info.predictor_names) == {"x0", "x1"}

    def test_max_runtime_budget(self, rng):
        fr = _frame(rng, n=200)
        aml = AutoML(max_models=0, max_runtime_secs=0.001, nfolds=2, seed=6,
                     include_algos=["glm", "gbm", "drf"])
        # budget expires after the first step at most; never zero models only
        # if even the first failed — accept RuntimeError or >=1 model
        try:
            aml.train(y="y", training_frame=fr)
            assert len(aml.leaderboard.models) >= 1
        except RuntimeError:
            pass


class TestAutoMLOverClient:
    def test_client_automl(self, rng):
        from h2o3_tpu import client as h2o

        h2o.init()
        try:
            X = rng.normal(size=(200, 2))
            y = np.where(X[:, 0] + rng.normal(size=200) * 0.3 > 0, "a", "b")
            csv = "x0,x1,y\n" + "\n".join(
                f"{a:.4f},{b:.4f},{c}" for (a, b), c in zip(X, y)
            )
            fr = h2o.upload_csv(csv)
            aml = h2o.H2OAutoML(max_models=2, nfolds=2, seed=1,
                                include_algos=["glm", "gbm"])
            aml.train(y="y", training_frame=fr)
            assert aml.leader is not None
            assert len(aml.leaderboard) >= 1
            pred = aml.leader.predict(fr)
            assert pred.nrows == 200
        finally:
            h2o.shutdown()


class TestAutoMLFidelity:
    """VERDICT r2 item 8: TE preprocessing, exploitation, budget."""

    def test_target_encoding_improves_leaderboard(self, rng):
        """On a dataset where the signal lives in a high-cardinality
        categorical, TE preprocessing must beat the no-TE run."""
        n = 1500
        n_levels = 40
        codes = rng.integers(0, n_levels, size=n)
        level_effect = rng.normal(size=n_levels) * 2.0
        x = rng.normal(size=n)
        y = level_effect[codes] + 0.2 * x + 0.5 * rng.normal(size=n)
        fr = Frame.from_dict({
            "cat": np.array([f"lv{i}" for i in range(n_levels)])[codes],
            "x": x,
            "y": y,
        })
        kw = dict(max_models=3, nfolds=2, seed=1,
                  include_algos=["gbm"], exploitation_ratio=0.0)
        plain = AutoML(**kw)
        plain.train(y="y", training_frame=fr)
        te = AutoML(preprocessing=["target_encoding"], **kw)
        te.train(y="y", training_frame=fr)

        from h2o3_tpu.models.grid import metric_value

        v_plain, _ = metric_value(plain.leader, "rmse")
        v_te, _ = metric_value(te.leader, "rmse")
        assert v_te < v_plain, (v_te, v_plain)
        # the event log records the preprocessing step
        assert any("target encoding applied" in e["message"]
                   for e in te.event_log.events)
        # and the leader scores RAW frames (the encoder re-applies at
        # predict time via Model._apply_preprocessors)
        pred = te.leader.predict(fr)
        assert pred.nrows == fr.nrows

    def test_exploitation_refines_champion(self, rng):
        n = 800
        X = rng.normal(size=(n, 3))
        y = X[:, 0] - 0.5 * X[:, 1] + 0.3 * rng.normal(size=n)
        fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
        a = AutoML(max_models=4, nfolds=2, seed=2, include_algos=["gbm"],
                   exploitation_ratio=0.1)
        a.train(y="y", training_frame=fr)
        logs = [e["message"] for e in a.event_log.events]
        assert any("exploitation: refining" in m for m in logs)
        # the refined model made it onto the leaderboard
        assert len(a.leaderboard.models) >= 2

    def test_run_respects_max_runtime(self, rng):
        """An AutoML run respects max_runtime_secs within a small margin
        (budget enforcement reaches INSIDE builds via the monitor hook)."""
        import time as _time

        n = 4000
        X = rng.normal(size=(n, 8))
        y = (X[:, 0] + X[:, 1] > 0).astype(int)
        fr = Frame.from_dict(
            {f"x{i}": X[:, i] for i in range(8)}
            | {"y": np.where(y > 0, "a", "b")}
        )
        budget = 20.0
        a = AutoML(max_models=50, max_runtime_secs=budget, nfolds=2, seed=3)
        t0 = _time.time()
        a.train(y="y", training_frame=fr)
        elapsed = _time.time() - t0
        # XLA compiles are not preemptable and dwarf a 20s budget on the
        # CPU tier, so the sharp assertion is on SCHEDULING: once the
        # budget is gone no further step starts (and in-build monitors cut
        # boosting short), so a 50-model request yields very few models
        logs = [e["message"] for e in a.event_log.events]
        assert any("time budget exhausted" in m for m in logs), logs[-5:]
        assert len(a.leaderboard.models) <= 3, [
            m.key for m in a.leaderboard.models
        ]
        # and a budget-ignoring run (50 models x 2-fold CV) would take far
        # longer than even the compile-dominated ceiling
        assert elapsed < 300, f"took {elapsed:.1f}s for a {budget}s budget"
