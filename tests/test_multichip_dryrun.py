"""The driver-facing multi-chip gate, run in-tier.

Covers both driver environments: (a) this process, where conftest already
bootstrapped the 8-device CPU mesh (config route); (b) a process whose
backend initialized with too few devices, forcing the subprocess re-exec
path (the r01 failure mode: axon backend up with 1 chip).
"""

import os
import subprocess
import sys

import jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_in_process():
    from __graft_entry__ import dryrun_multichip

    assert len(jax.devices()) == 8
    dryrun_multichip(8)


def test_dryrun_multichip_from_initialized_backend():
    # Simulate the driver: backend comes up with 1 CPU device *before*
    # dryrun_multichip is called, so the config route is closed and the
    # subprocess re-exec must kick in.
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "jax.config.update('jax_num_cpu_devices', 1)\n"
        "assert len(jax.devices()) == 1\n"
        "from __graft_entry__ import dryrun_multichip\n"
        "dryrun_multichip(8)\n"
    )
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "one tpu_hist boosting round OK" in proc.stdout


def test_entry_compiles():
    from __graft_entry__ import entry

    fn, args = entry()
    res = jax.jit(fn)(*args)
    assert res.shape == (256,)
