"""Cluster-wide serving plane (ISSUE 19): consistent-hash model homing,
replicated bit-identical scoring, forwarded-bundle coalescing at the
model's ring home, 429 spill to replicas, and the replica→survivor
recovery ladder.

Real multi-Cloud topologies over real sockets (the test_cluster_search
fixture idiom) — no mocked transport.  The acceptance contracts pinned
here:

* a model trained on node A scores from B and C **bit-identically**
  (same blob, deterministic ``dumps_model`` container);
* forwarded requests from N front doors **coalesce at the home** —
  dispatch count strictly below request count;
* a shedding home's 429 crosses the front door with its ``Retry-After``
  intact and never double-counts against the front door's route budget.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import serving
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.cluster.search import frame_payload
from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.keyed import DKV, KeyedStore
from h2o3_tpu.util import telemetry

pytestmark = pytest.mark.leaks_keys

N_NODES = 3


def _counter(name, **labels):
    c = telemetry.REGISTRY.get(name)
    if c is None:
        return 0.0
    return float(c.value(**labels)) if labels else float(c.total())


def _wait_for(cond, timeout=15.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


@pytest.fixture(scope="module")
def ring():
    """A formed 3-node cloud with one ring replica per homed blob, the
    first node installed as the process-local cloud (so ``train`` homes
    models automatically, exactly like a booted member)."""
    saved = os.environ.get("H2O3_TPU_SERVE_REPLICAS")
    os.environ["H2O3_TPU_SERVE_REPLICAS"] = "1"
    clouds, stores = [], []
    for i in range(N_NODES):
        c = Cloud("servering", f"sr{i}", hb_interval=0.05)
        s = KeyedStore()
        cdkv.install(c, s)
        ctasks.install(c)
        clouds.append(c)
        stores.append(s)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    _wait_for(lambda: all(c.size() == N_NODES for c in clouds),
              msg="3-node cloud formation")
    set_local_cloud(clouds[0])
    try:
        yield clouds, stores
    finally:
        set_local_cloud(None)
        if saved is None:
            os.environ.pop("H2O3_TPU_SERVE_REPLICAS", None)
        else:
            os.environ["H2O3_TPU_SERVE_REPLICAS"] = saved
        for c in clouds:
            try:
                c.stop()
            except Exception:
                pass


def _train_glm(seed=3, n=400):
    from h2o3_tpu.models.glm import GLM

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    logit = X @ np.array([1.2, -0.8, 0.5, 0.0]) - 0.2
    y = rng.random(n) < 1.0 / (1.0 + np.exp(-logit))
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(4)}
        | {"y": np.where(y, "yes", "no").astype(object)}
    )
    return GLM(family="binomial", response_column="y",
               lambda_=0.0, seed=seed).train(fr), fr


def _train_gbm(seed=5, n=300):
    from h2o3_tpu.models.tree.gbm import GBM

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.normal(size=n) * 0.1 > 0.4)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(3)}
        | {"y": np.where(y, "pos", "neg").astype(object)}
    )
    return GBM(response_column="y", ntrees=5, max_depth=3,
               seed=seed).train(fr), fr


def _score_frame(seed, n, ncols, names=None):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, ncols))
    names = names or [f"x{i}" for i in range(ncols)]
    return Frame.from_dict({nm: X[:, i] for i, nm in enumerate(names)})


def _assert_frames_equal(got, want):
    assert [c.name for c in got.columns] == [c.name for c in want.columns]
    for cg, cw in zip(got.columns, want.columns):
        a, b = np.asarray(cg.data), np.asarray(cw.data)
        if a.dtype.kind in "fc" or b.dtype.kind in "fc":
            np.testing.assert_array_equal(a.astype(np.float64),
                                          b.astype(np.float64))
        else:
            assert list(a) == list(b), cg.name


def _wire(fr):
    return [{"frame": frame_payload(fr),
             "rows": int(getattr(fr, "nrows", 0) or 0)}]


def _forwarded_pred(store, out):
    dest = out["model_metrics"][0]["predictions_frame"]["name"]
    fr = store.get(dest)
    assert isinstance(fr, Frame)
    return fr


class TestBlobRing:
    def test_dumps_model_deterministic_and_replicated(self, ring):
        """The homing hook lands one byte-identical blob copy on the ring
        home AND each successor; ``dumps_model`` itself is deterministic
        (fixed zip timestamps) so copies compare equal by digest."""
        from h2o3_tpu.models.persist import dumps_model, loads_model

        clouds, stores = ring
        m, fr = _train_glm(seed=11)
        assert dumps_model(m) == dumps_model(m)

        members = serving.serving_members(m.key, stores[0])
        names = [mm.info.name for mm in members]
        assert len(names) == 2  # home + 1 replica
        sk = serving.serve_key(m.key)
        holders = {c.info.name: s for c, s in zip(clouds, stores)
                   if c.info.name in names}
        _wait_for(lambda: all(
            isinstance(s.peek(sk), (bytes, bytearray))
            for s in holders.values()), msg="blob replication")
        blobs = [bytes(s.peek(sk)) for s in holders.values()]
        assert blobs[0] == blobs[1] == dumps_model(m)

        # round-trip through the REPLICA's copy scores bit-identically
        back = loads_model(blobs[1], register=False)
        sf = _score_frame(1, 64, 4)
        _assert_frames_equal(back.predict(sf), m.predict(sf))

    def test_replica_scoring_bit_identical_glm_and_gbm(self, ring):
        """Every serving member — home and replica, resolving the model
        from its blob copy — returns predictions array-equal to the
        builder's own ``predict``."""
        clouds, stores = ring
        by_name = {c.info.name: s for c, s in zip(clouds, stores)}
        for trainer, seed in ((_train_glm, 21), (_train_gbm, 22)):
            m, fr = trainer(seed=seed)
            sf = _score_frame(seed, 80, len(fr.names) - 1)
            want = m.predict(sf)
            members = serving.serving_members(m.key, stores[0])
            assert len(members) == 2
            for mm in members:
                store = by_name[mm.info.name]
                outs = serving.serve_entries(m.key, _wire(sf), store)
                assert len(outs) == 1 and "error" not in outs[0]
                from h2o3_tpu.cluster.search import frame_restore

                _assert_frames_equal(
                    frame_restore(outs[0]["prediction"], store), want)


class TestForwarding:
    def test_forward_from_non_member_front_door(self, ring):
        """A node holding neither the model nor its blob serves
        ``forward_predict`` by shipping the bundle to the ring home —
        results bit-identical to local scoring."""
        clouds, stores = ring
        m, fr = _train_glm(seed=31)
        names = [mm.info.name
                 for mm in serving.serving_members(m.key, stores[0])]
        front = next(i for i, c in enumerate(clouds)
                     if c.info.name not in names)
        sf = _score_frame(31, 50, 4)
        stores[front].put("fwd_frame_31", sf)
        ok0 = _counter("serve_forward_total", result="ok")
        reqs = [({}, {"model_id": m.key, "frame_id": "fwd_frame_31"})
                for _ in range(3)]
        outs = serving.forward_predict(
            reqs, m.key, cloud=clouds[front], store=stores[front])
        assert outs is not None and all(isinstance(o, dict) for o in outs)
        assert _counter("serve_forward_total", result="ok") == ok0 + 3
        want = m.predict(sf)
        for o in outs:
            _assert_frames_equal(_forwarded_pred(stores[front], o), want)

    def test_chunk_homed_frame_forwards_as_dist_reference(self, ring):
        """A chunk-homed DistFrame crosses the forward as a ``__dist__``
        reference (no rows on the wire); the home gathers from chunk
        homes and scores bit-identically to a local parse."""
        from h2o3_tpu.frame.parse import (
            _iter_body_chunks, parse_csv, parse_setup,
        )

        clouds, stores = ring
        m, _fr = _train_glm(seed=41)
        rng = np.random.default_rng(41)
        n = 4000
        X = rng.normal(size=(n, 4))
        lines = ["x0,x1,x2,x3"]
        for i in range(n):
            lines.append(",".join(repr(float(v)) for v in X[i]))
        text = "\n".join(lines) + "\n"
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], 8192, setup.header, setup.skip_blank_lines))
        dist = ctasks.distributed_parse_chunks(
            chunks, setup, cloud=clouds[0], key="serve_dist_df")
        assert len({g["home_name"]
                    for g in dist.chunk_layout["groups"]}) >= 2
        payload = frame_payload(dist)
        assert "__dist__" in payload  # rows never ride the forward

        local = parse_csv(text)
        want = m.predict(local)
        names = [mm.info.name
                 for mm in serving.serving_members(m.key, stores[0])]
        front = next(i for i, c in enumerate(clouds)
                     if c.info.name not in names)
        reqs = [({}, {"model_id": m.key, "frame_id": "serve_dist_df"})]
        outs = serving.forward_predict(
            reqs, m.key, cloud=clouds[front], store=stores[front])
        assert outs is not None and isinstance(outs[0], dict)
        _assert_frames_equal(_forwarded_pred(stores[front], outs[0]), want)

    def test_forwarded_bundles_coalesce_at_home(self, ring):
        """The acceptance contract: concurrent forwards from BOTH
        non-home nodes close into fewer dispatches than requests at the
        model's home coalescer."""
        from h2o3_tpu.api.coalesce import _BATCH_SIZE

        clouds, stores = ring
        m, fr = _train_glm(seed=51)
        sf = _score_frame(51, 40, 4)
        members = serving.serving_members(m.key, stores[0])
        home = members[0].info.name
        fronts = [i for i, c in enumerate(clouds) if c.info.name != home]
        per_front = 3
        for i in fronts:
            stores[i].put("coal_frame_51", sf)

        # widen the serving coalescer's window so the two bundles land
        # in one batch even on a loaded single-core runner
        saved = os.environ.get("H2O3_TPU_BATCH_WINDOW_MS")
        os.environ["H2O3_TPU_BATCH_WINDOW_MS"] = "75"
        serving._COAL = None
        before = _BATCH_SIZE.total_count()
        results = {}
        barrier = threading.Barrier(len(fronts))

        def shoot(i):
            barrier.wait()
            reqs = [({}, {"model_id": m.key, "frame_id": "coal_frame_51"})
                    for _ in range(per_front)]
            results[i] = serving.forward_predict(
                reqs, m.key, cloud=clouds[i], store=stores[i])

        try:
            threads = [threading.Thread(target=shoot, args=(i,))
                       for i in fronts]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            dispatches = _BATCH_SIZE.total_count() - before
            total = per_front * len(fronts)
            assert dispatches >= 1
            assert dispatches < total  # coalesced across front doors
            want = m.predict(sf)
            for i in fronts:
                outs = results[i]
                assert outs is not None
                for o in outs:
                    assert isinstance(o, dict), o
                    _assert_frames_equal(
                        _forwarded_pred(stores[i], o), want)
        finally:
            if saved is None:
                os.environ.pop("H2O3_TPU_BATCH_WINDOW_MS", None)
            else:
                os.environ["H2O3_TPU_BATCH_WINDOW_MS"] = saved
            serving._COAL = None


class TestSpillAndLadder:
    def test_shed_home_spills_to_replica(self, ring):
        """A home past its serving budget answers 429; the front door
        spills the bundle to the ring replica, which scores the SAME
        blob bit-identically.  ``serve_replica_spill_total`` proves the
        path."""
        clouds, stores = ring
        by_name = {c.info.name: s for c, s in zip(clouds, stores)}
        m, fr = _train_glm(seed=61)
        members = serving.serving_members(m.key, stores[0])
        home_store = by_name[members[0].info.name]
        names = [mm.info.name for mm in members]
        front = next(i for i, c in enumerate(clouds)
                     if c.info.name not in names)
        sf = _score_frame(61, 30, 4)
        stores[front].put("spill_frame_61", sf)
        spill0 = _counter("serve_replica_spill_total")
        rep0 = _counter("serve_forward_total", result="replica")
        home_store._serve_budget = 0
        try:
            outs = serving.forward_predict(
                [({}, {"model_id": m.key, "frame_id": "spill_frame_61"})],
                m.key, cloud=clouds[front], store=stores[front])
        finally:
            home_store._serve_budget = None
        assert outs is not None and isinstance(outs[0], dict)
        assert _counter("serve_replica_spill_total") == spill0 + 1
        assert _counter("serve_forward_total", result="replica") == rep0 + 1
        _assert_frames_equal(
            _forwarded_pred(stores[front], outs[0]), m.predict(sf))

    def test_dead_home_fails_over_to_replica(self, ring):
        """A home refusing its ``predict_remote`` dtask (the chaos-plane
        death signature) drops the forward down the ladder: the replica
        serves, ``cluster_fanout_recovered_total{path=replica}`` ticks,
        and the answer stays bit-identical."""
        from h2o3_tpu.cluster import faults

        clouds, stores = ring
        m, fr = _train_glm(seed=71)
        members = serving.serving_members(m.key, stores[0])
        names = [mm.info.name for mm in members]
        front = next(i for i, c in enumerate(clouds)
                     if c.info.name not in names)
        sf = _score_frame(71, 30, 4)
        stores[front].put("ladder_frame_71", sf)
        rec0 = _counter("cluster_fanout_recovered_total", path="replica")
        plan = faults.plan_from_dict({"seed": 7, "rules": [
            {"action": "drop", "side": "server", "src": names[0],
             "method": "dtask:predict_remote"},
        ]})
        faults.set_plan(plan)
        try:
            outs = serving.forward_predict(
                [({}, {"model_id": m.key, "frame_id": "ladder_frame_71"})],
                m.key, cloud=clouds[front], store=stores[front])
        finally:
            faults.clear_plan()
        assert plan.hits()[0] > 0
        assert outs is not None and isinstance(outs[0], dict)
        assert _counter(
            "cluster_fanout_recovered_total", path="replica") == rec0 + 1
        _assert_frames_equal(
            _forwarded_pred(stores[front], outs[0]), m.predict(sf))


class TestRestFrontDoor:
    """The REST surface end-to-end: /3/Predictions on a node that never
    saw the model, and the 429/Retry-After propagation contract."""

    def _req(self, srv, method, path, data=None):
        url = srv.url + path
        body = json.dumps(data).encode() if data is not None else None
        headers = {"Content-Type": "application/json"} if body else {}
        req = urllib.request.Request(
            url, data=body, headers=headers, method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, dict(resp.headers), json.loads(
                    resp.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    def test_predict_forwards_and_429_propagates_retry_after(self, ring):
        from h2o3_tpu.api.server import H2OServer, _HTTP_SHED

        clouds, stores = ring
        by_name = {c.info.name: s for c, s in zip(clouds, stores)}
        m, fr = _train_glm(seed=81)
        # evict the model from the front door's local DKV: only the ring
        # blob can serve it now (the trained-elsewhere shape)
        DKV.remove(m.key)
        sf = _score_frame(81, 40, 4)
        stores[0].put("rest_frame_81", sf)

        srv = H2OServer(port=0, http=dict(workers=2)).start()
        path = f"/3/Predictions/models/{m.key}/frames/rest_frame_81"
        route = "/3/Predictions/models/{model_id}/frames/{frame_id}"
        try:
            st, _hdrs, out = self._req(srv, "POST", path, {
                "predictions_frame": "rest_pred_81"})
            assert st == 200, out
            got = stores[0].get("rest_pred_81")
            _assert_frames_equal(got, m.predict(sf))
            assert out["model_metrics"][0]["model"]["name"] == m.key

            # saturate EVERY serving member: the ladder sheds end to end
            shed0 = _counter("http_shed_total", route=route)
            front_shed0 = _HTTP_SHED.total()
            for s in by_name.values():
                s._serve_budget = 0
            try:
                st, hdrs, out = self._req(srv, "POST", path, {})
            finally:
                for s in by_name.values():
                    s._serve_budget = None
            assert st == 429, out
            # the home's Retry-After crosses the front door unchanged
            assert hdrs.get("Retry-After") == "1"
            # ...and never double-counts against the front door's own
            # route budget (http_shed_total ticks at REST admission only)
            assert _counter("http_shed_total", route=route) == shed0
            assert _HTTP_SHED.total() == front_shed0
        finally:
            srv.stop()
