"""Cluster-wide observability: cross-node trace propagation, federated
metrics, and the merged cluster timeline.

Reference analogues: ``water/TimeLine.java`` + ``init/TimelineSnapshot.java``
(the cluster-snapshot timeline every member contributes to) and the
per-node water meters.  Everything runs multiple Cloud instances inside
one process over real loopback sockets — the envelope propagation, span
parenting, scrape fan-out and merge logic are identical to the
multi-process deployment; the only in-process artifact is that both
"nodes" share one timeline ring and one metrics registry, which the
assertions account for.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.cluster import rpc as crpc
from h2o3_tpu.cluster import transport
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.util import log as ulog
from h2o3_tpu.util import telemetry as T
from h2o3_tpu.util import timeline

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)


def _mr_stat(cols, mask):
    """Module-level map fn: crosses the RPC wire by module reference."""
    import jax.numpy as jnp

    return {
        "s": jnp.sum(jnp.where(mask, cols["x"], 0.0)),
        "n": jnp.sum(mask.astype(jnp.float32)),
    }


def _wait_for(cond, timeout=10.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


def _trace_events(trace_id):
    return [e for e in timeline.snapshot(timeline.CAPACITY)
            if e.get("trace_id") == trace_id]


@pytest.fixture()
def two_clouds():
    """A formed 2-node cloud (node-a, node-b) on loopback."""
    a = Cloud("tracecloud", "node-a", hb_interval=0.05)
    b = Cloud("tracecloud", "node-b", hb_interval=0.05)
    try:
        a.start([])
        b.start([a.info.addr])
        _wait_for(
            lambda: a.size() == 2 and b.size() == 2
            and a.consensus() and b.consensus(),
            msg="2-node cloud formation")
        yield a, b
    finally:
        a.stop()
        b.stop()


@pytest.fixture()
def cloud_server(two_clouds):
    from h2o3_tpu.api import start_server

    a, b = two_clouds
    set_local_cloud(a)
    srv = start_server(port=0)
    try:
        yield a, b, srv
    finally:
        srv.stop()
        set_local_cloud(None)


def _get(srv, path):
    try:
        with urllib.request.urlopen(srv.url + path) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# ---------------------------------------------------------------------------
# trace propagation over RPC


class TestTracePropagation:
    def test_one_trace_spans_caller_client_attempt_and_server(
            self, two_clouds):
        a, b = two_clouds
        with T.Span("caller_unit") as caller:
            a.client.call(b.info.addr, "echo", b"x", timeout=5.0,
                          target=b.info.ident)
        evts = _trace_events(caller.trace_id)
        by_kind = {e["kind"]: e for e in evts}
        assert {"rpc_client", "rpc_server", "caller_unit"} <= set(by_kind)
        # parent chain: caller -> rpc_client -> rpc_server; the clean
        # single-attempt path opens NO per-attempt span (bench budget)
        assert "rpc_attempt" not in by_kind
        assert by_kind["rpc_client"]["parent_id"] == caller.span_id
        assert (by_kind["rpc_server"]["parent_id"]
                == by_kind["rpc_client"]["span_id"])
        # the dispatch ran under the SERVING node's identity, and the
        # envelope named its origin
        assert by_kind["rpc_server"]["node"] == "node-b"
        assert by_kind["rpc_server"]["origin"] == "node-a"
        assert by_kind["rpc_server"]["method"] == "echo"

    def test_untraced_calls_inject_nothing_and_open_no_spans(
            self, two_clouds):
        a, b = two_clouds
        assert T.current_span() is None
        before = timeline.total_events()
        a.client.call(b.info.addr, "echo", b"y", timeout=5.0,
                      target=b.info.ident)
        evts = timeline.snapshot(timeline.CAPACITY)
        new = [e for e in evts if e.get("seq", 0) > before
               and e.get("kind", "").startswith("rpc_")]
        assert new == []

    def test_retried_attempts_are_sibling_spans(self):
        """A dropped response forces a retry: the trace shows TWO
        rpc_attempt spans under one rpc_client, and (dedup) only one
        server-side execution span."""
        srv = crpc.RpcServer(node_name="node-s")
        srv.register("bump", lambda p: "ok")
        drop = {"n": 1}

        class _DropFirstReply(transport.Connection):
            def __init__(self, inner):
                self._inner = inner
                self.sock = inner.sock
                self.addr = inner.addr

            def request(self, payload, timeout):
                raw = self._inner.request(payload, timeout)
                if drop["n"]:
                    drop["n"] -= 1
                    raise ConnectionResetError("reply dropped on the wire")
                return raw

        def dialer(addr, timeout):
            return _DropFirstReply(transport.dial(addr, timeout))

        client = crpc.RpcClient(dialer, backoff_base=0.01,
                                node_name="node-c")
        try:
            with T.Span("retry_unit") as caller:
                assert client.call(srv.address, "bump", None,
                                   timeout=5.0, target="s") == "ok"
            evts = _trace_events(caller.trace_id)
            attempts = sorted((e for e in evts if e["kind"] == "rpc_attempt"),
                              key=lambda e: e["attempt"])
            clients = [e for e in evts if e["kind"] == "rpc_client"]
            servers = [e for e in evts if e["kind"] == "rpc_server"]
            assert len(clients) == 1
            assert [e["attempt"] for e in attempts] == [0, 1]
            # siblings: both attempts hang under the one rpc_client span
            # (the failed first attempt materialized at retry time)
            assert {e["parent_id"] for e in attempts} == {
                clients[0]["span_id"]}
            assert attempts[0]["ok"] is False and attempts[1]["ok"] is True
            # the retry was deduped server-side: one execution span, one
            # run — parented under the attempt-0 envelope (the rpc_client)
            assert len(servers) == 1
            assert servers[0]["node"] == "node-s"
            assert servers[0]["parent_id"] == clients[0]["span_id"]
        finally:
            client.close()
            srv.stop()

    def test_distributed_map_reduce_single_trace_with_remote_spans(
            self, two_clouds):
        """Acceptance: a 2-node distributed_map_reduce yields ONE trace_id
        whose span tree includes remote-node execution spans."""
        import numpy as np

        from h2o3_tpu.cluster import tasks as ctasks
        from h2o3_tpu.cluster.tasks import distributed_map_reduce

        ctasks.install(two_clouds[0])
        ctasks.install(two_clouds[1])
        x = np.arange(64, dtype=np.float64)
        with T.Span("fit_unit") as caller:
            out = distributed_map_reduce(
                _mr_stat, {"x": x}, reduce="sum", cloud=two_clouds[0])
        assert float(out["s"]) == float(x.sum())
        evts = _trace_events(caller.trace_id)
        kinds = {e["kind"] for e in evts}
        assert {"distributed_map_reduce", "mr_member", "rpc_client",
                "rpc_server", "mapreduce"} <= kinds
        # the remote half executed under node-b's identity, in OUR trace
        remote_exec = [e for e in evts if e["kind"] == "mapreduce"
                       and e.get("node") == "node-b"]
        assert remote_exec, [
            (e["kind"], e.get("node")) for e in evts]
        members = sorted(e["member"] for e in evts
                         if e["kind"] == "mr_member")
        assert members == ["node-a", "node-b"]

    def test_rest_span_honors_inbound_trace_headers(self, cloud_server):
        _a, _b, srv = cloud_server
        req = urllib.request.Request(
            srv.url + "/3/Ping",
            headers={"X-H2O3-Trace-Id": "feedfacefeedface",
                     "X-H2O3-Span-Id": "0123456789abcdef"})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            assert resp.headers["X-H2O3-Trace-Id"] == "feedfacefeedface"
        rest = [e for e in _trace_events("feedfacefeedface")
                if e["kind"] == "rest"]
        assert rest and rest[-1]["parent_id"] == "0123456789abcdef"

    def test_malformed_trace_header_is_ignored(self, cloud_server):
        """A non-id-shaped inbound trace header must not be adopted (it
        would be echoed back verbatim — a response-header-injection
        primitive) nor recorded into the timeline."""
        _a, _b, srv = cloud_server
        req = urllib.request.Request(
            srv.url + "/3/Ping",
            headers={"X-H2O3-Trace-Id": "NOT-an-id!",
                     "X-H2O3-Span-Id": "also bad"})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
            echoed = resp.headers["X-H2O3-Trace-Id"]
        # a fresh well-formed id was minted instead
        assert echoed != "NOT-an-id!"
        assert len(echoed) == 16 and int(echoed, 16) >= 0
        assert _trace_events("NOT-an-id!") == []

    def test_rest_dkv_put_traces_across_nodes(self, cloud_server):
        """One trace threads REST handler -> routed DKV put -> remote home
        node's RPC dispatch."""
        from h2o3_tpu.cluster import dkv as cdkv
        from h2o3_tpu.keyed import DKV, KeyedStore

        a, b, srv = cloud_server
        ra = cdkv.install(a, DKV)
        cdkv.install(b, KeyedStore())
        try:
            key = next(k for k in (f"trace_k{i}" for i in range(4096))
                       if ra.home_name(k) == "node-b")
            body = json.dumps({"value": 7}).encode()
            req = urllib.request.Request(
                srv.url + f"/3/DKV/{key}", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
                tid = resp.headers["X-H2O3-Trace-Id"]
            assert tid
            evts = _trace_events(tid)
            kinds = {e["kind"] for e in evts}
            assert {"rest", "rpc_client", "rpc_server"} <= kinds
            served_on = {e.get("node") for e in evts
                         if e["kind"] == "rpc_server"}
            assert "node-b" in served_on
            DKV.remove(key)
        finally:
            DKV.router = None

    def test_log_lines_carry_trace_ids(self):
        with T.Span("log_unit") as sp:
            ulog.get_logger("tracetest").info("correlate me")
        hits = [ln for ln in ulog.recent(100)
                if "correlate me" in ln]
        assert hits and f"trace={sp.trace_id}" in hits[-1]
        assert f"span={sp.span_id}" in hits[-1]
        # outside a span: no trace suffix
        ulog.get_logger("tracetest").info("uncorrelated line")
        hits = [ln for ln in ulog.recent(100) if "uncorrelated line" in ln]
        assert hits and "trace=" not in hits[-1]


# ---------------------------------------------------------------------------
# rpc serving-side meters


class TestRpcMeters:
    def test_served_side_seconds_labelled_by_method(self, two_clouds):
        a, b = two_clouds
        h = T.REGISTRY.get("rpc_call_seconds")
        before = h.count(method="echo", side="server")
        a.client.call(b.info.addr, "echo", b"z", timeout=5.0,
                      target=b.info.ident)
        assert h.count(method="echo", side="server") == before + 1
        assert h.count(method="echo", side="client") >= 1

    def test_inflight_gauge_pins_while_a_call_is_wedged(self):
        import threading

        release = threading.Event()
        srv = crpc.RpcServer()
        srv.register("wedge", lambda p: release.wait(10))
        client = crpc.RpcClient(retries=0)
        g = T.REGISTRY.get("rpc_inflight")
        base_srv = g.value(side="server")
        base_cli = g.value(side="client")
        t = threading.Thread(
            target=lambda: client.call(srv.address, "wedge", None,
                                       timeout=10.0),
            daemon=True)
        try:
            t.start()
            _wait_for(lambda: g.value(side="server") == base_srv + 1,
                      msg="server inflight to rise")
            assert g.value(side="client") == base_cli + 1
        finally:
            release.set()
            t.join(timeout=10)
            client.close()
            srv.stop()
        assert g.value(side="server") == base_srv
        assert g.value(side="client") == base_cli


# ---------------------------------------------------------------------------
# federated metrics


class TestFederatedMetrics:
    def test_cluster_metrics_merge_node_labels(self, cloud_server):
        _a, _b, srv = cloud_server
        st, out, _hd = _get(srv, "/3/Metrics?cluster=true")
        assert st == 200
        assert out["partial"] is False and out["errors"] == {}
        assert out["nodes"] == ["node-a", "node-b"]
        series = out["metrics"]["rpc_calls_total"]["series"]
        nodes = {s["labels"]["node"] for s in series}
        assert {"node-a", "node-b", "_cluster"} <= nodes
        # counters sum into the _cluster aggregate: for any label set the
        # aggregate equals the per-node sum
        per_node = {}
        agg = {}
        for s in series:
            key = tuple(sorted((k, v) for k, v in s["labels"].items()
                               if k != "node"))
            if s["labels"]["node"] == "_cluster":
                agg[key] = s["value"]
            else:
                per_node[key] = per_node.get(key, 0.0) + s["value"]
        assert agg and all(
            abs(agg[k] - per_node[k]) < 1e-9 for k in agg)
        # gauges got NO aggregate
        gser = out["metrics"]["cluster_size"]["series"]
        assert all(s["labels"]["node"] != "_cluster" for s in gser)

    def test_cluster_metrics_partial_when_member_down(self, cloud_server):
        a, b, srv = cloud_server
        errs = T.REGISTRY.get("metrics_scrape_errors_total")
        before = errs.total()
        b.stop()
        # a killed PROCESS closes its sockets; an in-process stop() leaves
        # the peer's pooled connections half-alive — drain them so the
        # scrape meets a genuinely dead member
        a.client.pool.close_all()
        st, out, _hd = _get(srv, "/3/Metrics?cluster=true")
        assert st == 200  # degraded, never a 5xx
        assert out["partial"] is True
        assert "node-b" in out["errors"]
        assert "node-a" in out["nodes"] and "node-b" not in out["nodes"]
        assert errs.total() > before
        # merged payload still has node-a's series
        series = out["metrics"]["rpc_calls_total"]["series"]
        assert any(s["labels"]["node"] == "node-a" for s in series)

    def test_cluster_prometheus_variant(self, cloud_server):
        _a, _b, srv = cloud_server
        with urllib.request.urlopen(
                srv.url + "/3/Metrics/prometheus?cluster=true") as resp:
            assert resp.status == 200
            text = resp.read().decode()
        assert 'node="node-a"' in text and 'node="node-b"' in text
        assert 'node="_cluster"' in text
        # histogram contract survives the merge: +Inf bucket == count
        assert "rpc_call_seconds_bucket" in text

    def test_histogram_buckets_merge_in_aggregate(self):
        snap_a = {"m_seconds": {
            "type": "histogram", "help": "", "buckets": [0.1, 1.0],
            "series": [{"labels": {}, "bucket_counts": [2, 1],
                        "sum": 1.5, "count": 4}],
        }}
        snap_b = {"m_seconds": {
            "type": "histogram", "help": "", "buckets": [0.1, 1.0],
            "series": [{"labels": {}, "bucket_counts": [0, 3],
                        "sum": 2.0, "count": 3}],
        }}
        merged = T.merge_snapshots({"na": snap_a, "nb": snap_b})
        agg = [s for s in merged["m_seconds"]["series"]
               if s["labels"]["node"] == "_cluster"]
        assert agg == [{"labels": {"node": "_cluster"},
                        "bucket_counts": [2, 4], "sum": 3.5, "count": 7}]

    def test_single_node_cluster_query_degenerates_cleanly(self):
        from h2o3_tpu.api import start_server

        srv = start_server(port=0)
        try:
            st, out, _hd = _get(srv, "/3/Metrics?cluster=true")
            assert st == 200 and out["partial"] is False
            assert len(out["nodes"]) == 1
            node = out["nodes"][0]
            series = out["metrics"]["rest_requests_total"]["series"]
            assert all(s["labels"]["node"] in (node, "_cluster")
                       for s in series)
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# merged cluster timeline


class TestClusterTimeline:
    def test_merged_timeline_tags_nodes_and_sorts(self, cloud_server):
        a, _b, srv = cloud_server
        # let at least one heartbeat sample the clock
        _wait_for(lambda: all(
            m.clock_skew_ms is not None for m in a.members_sorted()
            if m.info.name != "node-a"), msg="clock-skew sample")
        st, out, _hd = _get(srv, "/3/Timeline?cluster=true&count=200")
        assert st == 200 and out["partial"] is False
        names = {n["name"] for n in out["nodes"]}
        assert names == {"node-a", "node-b"}
        meta_b = next(n for n in out["nodes"] if n["name"] == "node-b")
        assert isinstance(meta_b["skew_ms"], float)
        assert meta_b["rtt_ms"] is not None
        assert out["events"], "merged stream is non-empty"
        assert all("node" in e for e in out["events"])
        ts = [e["ns"] for e in out["events"]]
        assert ts == sorted(ts)

    def test_merged_timeline_partial_when_member_down(self, cloud_server):
        a, b, srv = cloud_server
        b.stop()
        a.client.pool.close_all()  # see the federated-metrics twin test
        st, out, _hd = _get(srv, "/3/Timeline?cluster=true&count=50")
        assert st == 200 and out["partial"] is True
        down = [n for n in out["nodes"] if "error" in n]
        assert down and down[0]["name"] == "node-b"

    def test_timeline_node_proxy(self, cloud_server):
        _a, _b, srv = cloud_server
        st, out, _hd = _get(srv, "/3/Timeline/nodes/1?count=20")
        assert st == 200
        assert out["node"] == "node-b"
        assert "events" in out and "total_events" in out
        st, out0, _hd = _get(srv, "/3/Timeline/nodes/0?count=20")
        assert st == 200 and out0["node"] == "node-a"
        # self index and remote proxy answer ONE shape (clock comparison
        # needs now_ns from both)
        assert set(out0) == set(out)
        assert "now_ns" in out0
        st, _out, _hd = _get(srv, "/3/Timeline/nodes/9")
        assert st == 404
        st, _out, _hd = _get(srv, "/3/Timeline/nodes/bogus")
        assert st == 404


# ---------------------------------------------------------------------------
# trace_view smoke (CI: the renderer cannot rot)


class TestTraceView:
    def test_smoke_renders_nested_spans_from_snapshot(self, tmp_path):
        with T.Span("outer_view", route="/3/X") as outer:
            timeline.record("note_event", detail="hi")
            with T.Span("inner_view", member="node-z"):
                pass
        snap = {"events": _trace_events(outer.trace_id)}
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(snap))
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "scripts", "trace_view.py"),
             str(path)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        out = proc.stdout
        assert f"trace {outer.trace_id}" in out
        assert "outer_view" in out and "inner_view" in out
        # the child renders indented under the parent
        lines = out.splitlines()
        i_outer = next(i for i, ln in enumerate(lines) if "outer_view" in ln)
        i_inner = next(i for i, ln in enumerate(lines) if "inner_view" in ln)
        indent = len(lines[i_inner]) - len(lines[i_inner].lstrip())
        assert indent > len(lines[i_outer]) - len(lines[i_outer].lstrip())
        # plain records attach as notes
        assert "note_event" in out

    def test_bad_input_is_a_clean_error(self, tmp_path):
        path = tmp_path / "junk.json"
        path.write_text("{not json")
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "scripts", "trace_view.py"),
             str(path)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1
        assert "trace_view:" in proc.stderr
