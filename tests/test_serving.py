"""Serving plane: event-loop front-end, admission control, coalesced
batched scoring (ISSUE 9).

Like test_rest_api.py these run real sockets on localhost (SURVEY.md §4
'no mocked network backends').  Each class that needs non-default knobs
starts its own server with ``http={...}`` overrides; the coalescer tests
assert the tentpole contract directly: N concurrent scoring requests
execute in far fewer dispatches than N, bit-identical to serial.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.api import start_server
from h2o3_tpu.api.coalesce import _BATCH_SIZE
from h2o3_tpu.api.server import _HTTP_SHED, H2OServer
from h2o3_tpu.keyed import DKV

# servers and trained models share keys across tests; the module-level
# sweeper removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _req(server, method, path, data=None):
    url = server.url + path
    body = None
    headers = {}
    if data is not None:
        body = json.dumps(data).encode()
        headers["Content-Type"] = "application/json"
    req = urllib.request.Request(
        url, data=body, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _train_binomial(n=600, seed=3):
    from h2o3_tpu.models.glm import GLM

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    logit = X @ np.array([1.2, -0.8, 0.5, 0.0]) - 0.2
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(float)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(4)}
        | {"y": np.where(y > 0, "yes", "no").astype(object)}
    )
    fr.key = f"serve_bin_{n}_{seed}.hex"
    DKV.put(fr.key, fr)
    m = GLM(family="binomial", response_column="y", lambda_=0.0).train(fr)
    return m, fr


def _frame_cols(key):
    fr = DKV.get(key)
    assert isinstance(fr, Frame)
    return {c.name: np.asarray(c.data, dtype=np.float64) for c in fr.columns}


class TestCoalescedScoring:
    """The tentpole contract: concurrency collapses into few dispatches,
    results stay bit-identical to serial execution."""

    def test_concurrent_predicts_coalesce_and_match_serial(self):
        m, fr = _train_binomial()
        srv = H2OServer(port=0, http=dict(
            workers=4, batch_window_ms=50.0)).start()
        try:
            serial = m.predict(fr)
            want = {c.name: np.asarray(c.data, dtype=np.float64)
                    for c in serial.columns}
            n = 16
            path = f"/3/Predictions/models/{m.key}/frames/{fr.key}"
            barrier = threading.Barrier(n)
            statuses = [None] * n

            def shoot(i):
                barrier.wait()
                statuses[i] = _req(srv, "POST", path, {
                    "predictions_frame": f"serve_pred_{i}"})[0]

            before = _BATCH_SIZE.total_count()
            threads = [threading.Thread(target=shoot, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            dispatches = _BATCH_SIZE.total_count() - before
            assert statuses == [200] * n
            # the point of the coalescer: nowhere near one dispatch per
            # request (same model + same frame usually lands in 1-2)
            assert 1 <= dispatches <= n // 2
            for i in range(n):
                got = _frame_cols(f"serve_pred_{i}")
                assert set(got) == set(want)
                for name, col in want.items():
                    np.testing.assert_array_equal(got[name], col), name
        finally:
            srv.stop()

    def test_window_zero_disables_coalescing(self):
        m, fr = _train_binomial(n=80, seed=9)
        srv = H2OServer(port=0, http=dict(
            workers=2, batch_window_ms=0)).start()
        try:
            assert srv._coalescer is None
            before = _BATCH_SIZE.total_count()
            st, out = _req(
                srv, "POST", f"/3/Predictions/models/{m.key}/frames/{fr.key}",
                {"predictions_frame": "serve_pred_nc"})
            assert st == 200
            pf = out["model_metrics"][0]["predictions_frame"]
            assert pf["name"] == "serve_pred_nc"
            assert _BATCH_SIZE.total_count() == before
        finally:
            srv.stop()


class TestKeepAlive:
    def test_two_requests_one_connection(self):
        srv = start_server(port=0, http=dict(workers=2))
        try:
            with socket.create_connection(
                    ("127.0.0.1", srv.port), timeout=10) as s:
                f = s.makefile("rb")
                for _ in range(2):
                    s.sendall(b"GET /3/About HTTP/1.1\r\n"
                              b"Host: localhost\r\n\r\n")
                    status = f.readline().split()[1]
                    assert status == b"200"
                    length = 0
                    while True:
                        h = f.readline()
                        if h in (b"\r\n", b"\n"):
                            break
                        if h.lower().startswith(b"content-length:"):
                            length = int(h.split(b":")[1])
                    assert length > 0
                    json.loads(f.read(length))  # full body on same socket
        finally:
            srv.stop()


class TestAdmissionControl:
    def _slow_server(self, **http):
        srv = H2OServer(port=0, http=http)

        def slow(params):
            time.sleep(float(params.get("sleep_s", 0.4)))
            return {"ok": True}

        srv.registry.register("POST", "/3/TestSlow", slow, "test-only")
        return srv.start()

    def test_queue_overflow_sheds_429_never_hangs(self):
        srv = self._slow_server(workers=1, queue=2, batch_window_ms=0)
        try:
            n = 10
            results = [None] * n
            barrier = threading.Barrier(n)

            def shoot(i):
                barrier.wait()
                results[i] = _req(srv, "POST", "/3/TestSlow", {})

            shed0 = _HTTP_SHED.value(route="/3/TestSlow")
            t0 = time.monotonic()
            threads = [threading.Thread(target=shoot, args=(i,))
                       for i in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            took = time.monotonic() - t0
            statuses = [r[0] for r in results]
            assert set(statuses) <= {200, 429}       # never 5xx
            assert statuses.count(200) >= 1          # in-flight completed
            assert statuses.count(429) >= 1          # overflow was shed
            assert _HTTP_SHED.value(route="/3/TestSlow") > shed0
            # worker=1 x 0.4s each: admitted <= 3, so the whole burst
            # resolves in a couple of seconds — overload never hangs
            assert took < 20
            for st, out in results:
                if st == 429:
                    assert out["http_status"] == 429
        finally:
            srv.stop()

    def test_per_route_budget_sheds_429(self):
        srv = self._slow_server(
            workers=4, queue=64, batch_window_ms=0,
            route_budgets={"/3/TestSlow": 1})
        try:
            results = [None] * 4
            barrier = threading.Barrier(4)

            def shoot(i):
                barrier.wait()
                results[i] = _req(srv, "POST", "/3/TestSlow", {})

            threads = [threading.Thread(target=shoot, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            statuses = sorted(r[0] for r in results)
            assert statuses[0] == 200 and statuses[-1] == 429
            # other routes keep their own budget: not shed
            assert _req(srv, "GET", "/3/About")[0] == 200
        finally:
            srv.stop()


class TestRequestHygiene:
    def test_oversized_header_413(self):
        srv = start_server(port=0, http=dict(
            workers=2, max_header_bytes=1024))
        try:
            st, out = _req(srv, "GET", "/3/About?x=" + "a" * 4096)
            assert st == 413
            assert out["http_status"] == 413
        finally:
            srv.stop()

    def test_oversized_body_413(self):
        srv = start_server(port=0, http=dict(
            workers=2, max_body_bytes=2048))
        try:
            st, out = _req(srv, "POST", "/3/PostFile",
                           {"data": "x" * 8192})
            assert st == 413
            assert out["http_status"] == 413
        finally:
            srv.stop()

    def test_slow_client_408(self):
        srv = start_server(port=0, http=dict(
            workers=2, read_timeout_s=0.3))
        try:
            with socket.create_connection(
                    ("127.0.0.1", srv.port), timeout=10) as s:
                # request line arrives, headers never finish: slow-loris
                s.sendall(b"GET /3/About HTTP/1.1\r\nHost: lo")
                t0 = time.monotonic()
                data = s.recv(4096)
                assert time.monotonic() - t0 < 10
                assert b"408" in data.split(b"\r\n", 1)[0]
        finally:
            srv.stop()


class TestBoundedDrain:
    def test_stop_returns_within_drain_deadline(self):
        srv = H2OServer(port=0, http=dict(
            workers=2, batch_window_ms=0, drain_s=0.5))

        def very_slow(params):
            time.sleep(30)
            return {"ok": True}

        srv.registry.register("POST", "/3/TestVerySlow", very_slow, "")
        srv.start()
        outcome = {}

        def shoot():
            try:
                outcome["resp"] = _req(srv, "POST", "/3/TestVerySlow", {})
            except Exception as e:  # connection cut mid-drain is legal
                outcome["err"] = type(e).__name__

        t = threading.Thread(target=shoot)
        t.start()
        time.sleep(0.3)  # let the request reach a worker
        t0 = time.monotonic()
        srv.stop()
        took = time.monotonic() - t0
        assert took < 10  # drain_s + bounded teardown, not the 30s handler
        t.join(timeout=15)
        assert not t.is_alive()  # the client got 503 or a closed socket
        if "resp" in outcome:
            assert outcome["resp"][0] == 503
        srv.stop()  # idempotent

    def test_drain_flushes_open_batches(self):
        m, fr = _train_binomial(n=60, seed=11)
        # a window far longer than the test: only the drain flush can
        # close the batch
        srv = H2OServer(port=0, http=dict(
            workers=2, batch_window_ms=60000.0, drain_s=5.0)).start()
        out = {}

        def shoot():
            out["r"] = _req(
                srv, "POST",
                f"/3/Predictions/models/{m.key}/frames/{fr.key}",
                {"predictions_frame": "serve_pred_drain"})

        t = threading.Thread(target=shoot)
        t.start()
        time.sleep(0.5)  # request is parked in the open batch
        srv.stop()
        t.join(timeout=30)
        assert not t.is_alive()
        assert out["r"][0] == 200  # flushed and answered before teardown


class TestServeBenchSmoke:
    def test_serve_bench_smoke(self, monkeypatch):
        import bench

        monkeypatch.setenv("BENCH_SERVE_SMOKE", "1")
        result = bench._serve_bench()
        assert result["metric"] == "serve_warm_rps_speedup"
        assert result["value"] > 0
        cells = result["detail"]["matrix"]
        assert cells  # every (server, clients) cell ran
        for cell in cells:
            assert cell["rps"] > 0
            assert cell["p99_ms"] >= cell["p50_ms"] > 0
            bad = [s for s in cell["statuses"]
                   if not (200 <= int(s) < 300 or int(s) in (408, 413, 429))]
            assert not bad, f"unexpected statuses in {cell}"
        assert result["detail"]["bit_identical"] is True
        # the multi-node cell: three real node processes, forwarding
        # through the serving ring (cluster/serving.py).  Its invariants
        # (overload_clean, bit_identical, forwards coalesce at the home,
        # spill reaches the replica) are asserted IN-RUN by the bench —
        # a violation raises — so here we pin the contract shape
        mn = result["detail"]["multinode"]
        assert mn["nodes"] == 3
        assert mn["one_door_rps"] > 0
        assert mn["three_door_rps"] > 0
        assert mn["replica_spill_rps"] > 0
        assert mn["forwarded_requests"] > 0
        assert mn["replica_spilled"] > 0
        assert mn["home_dispatches"] < mn["home_coalesced_requests"]
        assert mn["overload_clean"] is True
        assert mn["bit_identical"] is True
