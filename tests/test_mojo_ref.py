"""Reference-format MOJO (VERDICT r3 Missing #6, second half).

Reference: hex/ModelMojoWriter.java (container), hex/tree/DTree.java
compress (tree bytes), hex/genmodel/ModelMojoReader + SharedTreeMojoModel
.scoreTree + GbmMojoModel.unifyPreds (consumer contract). The reader here
is an INDEPENDENT decoder of the byte format — write → decode → score
parity against in-framework predict validates both sides."""

import struct
import zipfile

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.mojo_ref import read_mojo, write_mojo

pytestmark = pytest.mark.leaks_keys


def _frame(rng, n=500, nclass=2):
    X = rng.normal(size=(n, 4))
    logit = X[:, 0] - 0.8 * X[:, 1] + 0.4 * X[:, 2] * X[:, 3]
    if nclass == 2:
        y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.int32)
        ycol = Column("y", y, ColType.CAT, ["n", "p"])
    elif nclass > 2:
        y = np.clip(np.digitize(logit, [-1.0, 1.0]), 0, 2).astype(np.int32)
        ycol = Column("y", y, ColType.CAT, ["a", "b", "c"])
    else:
        ycol = Column("y", logit + rng.normal(size=n) * 0.1)
    cols = [Column(f"x{i}", X[:, i]) for i in range(4)]
    cols.append(ycol)
    fr = Frame(cols)
    xs = fr.col("x0").data
    xs[rng.random(n) < 0.06] = np.nan  # exercise NA routing bytes
    return fr


def _score_all(mojo, X32):
    return np.stack([
        mojo.score0(X32[i].astype(np.float64)) for i in range(len(X32))
    ])


class TestReferenceMojoParity:
    def test_binomial(self, rng, tmp_path):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=7, max_depth=4, response_column="y", seed=1,
                min_rows=2).train(fr)
        path = str(tmp_path / "m.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "gbm"
        assert mojo.info["category"] == "Binomial"
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _score_all(mojo, X32)
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_multinomial_bakes_class_inits(self, rng, tmp_path):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng, nclass=3)
        m = GBM(ntrees=4, max_depth=3, response_column="y", seed=2,
                min_rows=2).train(fr)
        path = str(tmp_path / "m3.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert int(mojo.info["n_trees_per_class"]) == 3
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _score_all(mojo, X32)
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("dist", ["gaussian", "poisson"])
    def test_regression_links(self, rng, tmp_path, dist):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng, nclass=0)
        if dist == "poisson":
            y = fr.col("y").data
            y[:] = np.exp(np.clip(y, -3, 2))
        m = GBM(ntrees=6, max_depth=3, response_column="y", seed=3,
                min_rows=2, distribution=dist).train(fr)
        path = str(tmp_path / f"r_{dist}.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _score_all(mojo, X32)[:, 0]
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestDrfParity:
    @pytest.mark.parametrize("nclass", [0, 2, 3])
    def test_drf_families(self, rng, tmp_path, nclass):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.drf import DRF

        fr = _frame(rng, nclass=nclass)
        m = DRF(ntrees=6, max_depth=4, response_column="y", seed=7,
                min_rows=2).train(fr)
        path = str(tmp_path / f"drf_{nclass}.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "drf"
        assert mojo.info["binomial_double_trees"] == "false"
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _score_all(mojo, X32)
        want = m._predict_raw(fr)
        if nclass == 0:
            got = got[:, 0]
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestContainerLayout:
    def test_zip_structure_matches_reference(self, rng, tmp_path):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=4,
                min_rows=2).train(fr)
        path = str(tmp_path / "layout.zip")
        write_mojo(m, path)
        with zipfile.ZipFile(path) as z:
            names = set(z.namelist())
            assert "model.ini" in names
            assert "trees/t00_000.bin" in names
            assert "trees/t00_002.bin" in names
            assert "domains/d000.txt" in names  # response domain
            ini = z.read("model.ini").decode()
            for key in ("mojo_version", "n_columns", "supervised",
                        "init_f", "link_function", "distribution"):
                assert key in ini, key
            assert "[columns]" in ini and "[domains]" in ini
            # domain file carries the response levels
            assert z.read("domains/d000.txt").decode().split() == ["n", "p"]

    def test_root_leaf_special_encoding(self):
        """A root-leaf blob is 00 FF FF + float (DTree.java:855) and the
        reader must return exactly that float."""
        from h2o3_tpu.models.mojo_ref import RefMojo

        blob = b"\x00\xff\xff" + struct.pack("<f", 2.5)
        m = RefMojo()
        assert m.score_tree(blob, np.zeros(3)) == 2.5

    def test_unsupported_algo_refuses(self, rng):
        from h2o3_tpu.models.naive_bayes import NaiveBayes

        fr = _frame(rng)
        m = NaiveBayes(response_column="y").train(fr)
        with pytest.raises(ValueError, match="codegen"):
            write_mojo(m, "/tmp/nope.zip")


class TestRestExport:
    def test_reference_format_over_rest(self, rng, tmp_path):
        import io
        import urllib.request

        from h2o3_tpu.api import start_server
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=6,
                min_rows=2).train(fr)
        s = start_server(port=0)
        try:
            with urllib.request.urlopen(
                    f"{s.url}/3/Models/{m.key}/mojo?format=reference") as r:
                blob = r.read()
            with zipfile.ZipFile(io.BytesIO(blob)) as z:
                assert "model.ini" in z.namelist()
                assert any(n.startswith("trees/") for n in z.namelist())
        finally:
            s.stop()


class TestGlmReferenceMojo:
    def _cat_frame(self, rng, n=400):
        X = rng.normal(size=(n, 2))
        g = rng.integers(0, 3, size=n).astype(np.int32)
        logit = X[:, 0] - X[:, 1] + 0.8 * (g == 2)
        y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.int32)
        fr = Frame([
            Column("g", g, ColType.CAT, ["u", "v", "w"]),
            Column("x0", X[:, 0]),
            Column("x1", X[:, 1]),
            Column("y", y, ColType.CAT, ["n", "p"]),
        ])
        xs = fr.col("x0").data
        xs[rng.random(n) < 0.05] = np.nan
        return fr

    def test_binomial_with_categoricals(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = self._cat_frame(rng)
        m = GLM(GLMParameters(response_column="y",
                              family="binomial")).train(fr)
        path = str(tmp_path / "glm.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "glm"
        assert mojo.info["family"] == "binomial"
        # cats-first row layout: [g_code, x0, x1]
        want = m._predict_raw(fr)
        g = fr.col("g").data.astype(np.float64)
        x0 = fr.col("x0").data
        x1 = fr.col("x1").data
        for i in range(0, fr.nrows, 17):
            row = np.array([g[i], x0[i], x1[i]])
            got = mojo.score0(row)
            np.testing.assert_allclose(got, want[i], rtol=1e-8, atol=1e-10)

    def test_gamma_regression(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = self._cat_frame(rng)
        y = np.exp(np.clip(fr.col("x0").numeric_view(), -2, 2)) + 0.1
        fr = fr.drop("y").add_column(Column("y", y))
        m = GLM(GLMParameters(response_column="y", family="gamma")).train(fr)
        path = str(tmp_path / "glm_gamma.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        want = m._predict_raw(fr)
        g = fr.col("g").data.astype(np.float64)
        x0 = fr.col("x0").data
        x1 = fr.col("x1").data
        for i in range(0, fr.nrows, 23):
            got = mojo.score0(np.array([g[i], x0[i], x1[i]]))
            np.testing.assert_allclose(got[0], want[i], rtol=1e-8)

    def test_multinomial_with_categoricals(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM, GLMParameters

        n = 500
        X = rng.normal(size=(n, 2))
        g = rng.integers(0, 3, size=n).astype(np.int32)
        score = X[:, 0] + 0.5 * (g == 1) - 0.7 * X[:, 1]
        y = np.clip(np.digitize(score, [-0.7, 0.7]), 0, 2).astype(np.int32)
        fr = Frame([
            Column("g", g, ColType.CAT, ["u", "v", "w"]),
            Column("x0", X[:, 0]),
            Column("x1", X[:, 1]),
            Column("y", y, ColType.CAT, ["a", "b", "c"]),
        ])
        m = GLM(GLMParameters(response_column="y",
                              family="multinomial")).train(fr)
        path = str(tmp_path / "glm_mn.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["category"] == "Multinomial"
        assert int(mojo.info["n_classes"]) == 3
        want = m._predict_raw(fr)
        gd = fr.col("g").data.astype(np.float64)
        x0 = fr.col("x0").data
        x1 = fr.col("x1").data
        for i in range(0, n, 19):
            got = mojo.score0(np.array([gd[i], x0[i], x1[i]]))
            np.testing.assert_allclose(got, want[i], rtol=1e-6, atol=1e-8)

    def test_ordinal_glm_refuses(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = _frame(rng, nclass=3)
        m = GLM(GLMParameters(response_column="y",
                              family="ordinal")).train(fr)
        with pytest.raises(ValueError, match="ordinal"):
            write_mojo(m, str(tmp_path / "x.zip"))


class TestClientDownloadMojo:
    def test_both_formats(self, rng, tmp_path):
        from h2o3_tpu import client as h2o
        from h2o3_tpu.api import start_server
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=8,
                min_rows=2).train(fr)
        s = start_server(port=0)
        try:
            h2o.connect(s.url)
            ref = h2o.download_mojo(m, str(tmp_path / "ref.zip"),
                                    format="reference")
            with zipfile.ZipFile(ref) as z:
                assert "model.ini" in z.namelist()
            nat = h2o.download_mojo(m, str(tmp_path / "nat.mojo"))
            from h2o3_tpu.genmodel import load_mojo

            scorer = load_mojo(nat)
            assert scorer is not None
        finally:
            h2o.shutdown()  # reset the module connection for later tests
            s.stop()


class TestKMeansReferenceMojo:
    """KMeansMojoWriter/KMeansMojoModel layout: standardize kv arrays +
    center_<i> rows, closest-center scoring in standardized space."""

    def test_assignment_parity(self, rng, tmp_path):
        from h2o3_tpu.frame.frame import Column, Frame
        from h2o3_tpu.models.kmeans import KMeans

        n = 600
        X = np.concatenate([
            rng.normal(size=(n // 2, 3)) + 4.0,
            rng.normal(size=(n // 2, 3)) - 4.0,
        ])
        fr = Frame([Column(f"x{i}", X[:, i]) for i in range(3)])
        m = KMeans(k=2, seed=7).train(fr)
        path = str(tmp_path / "km.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "kmeans"
        assert mojo.info["category"] == "Clustering"
        assert int(mojo.info["center_num"]) == 2
        got = _score_all(mojo, X.astype(np.float32))[:, 0].astype(int)
        want = m.predict(fr).col("predict").numeric_view().astype(int)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("standardize", [True, False])
    def test_nan_goes_to_mean(self, rng, tmp_path, standardize):
        from h2o3_tpu.frame.frame import Column, Frame
        from h2o3_tpu.models.kmeans import KMeans

        X = rng.normal(size=(300, 2))
        fr = Frame([Column("a", X[:, 0]), Column("b", X[:, 1])])
        m = KMeans(k=3, seed=1, standardize=standardize).train(fr)
        path = str(tmp_path / "km.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        # a NaN row imputes to the column means (standardized or not) and
        # must match the in-framework assignment for that imputed row
        out = mojo.score0(np.array([np.nan, np.nan]))
        nan_fr = Frame([Column("a", np.array([np.nan])),
                        Column("b", np.array([np.nan]))])
        want = m.predict(nan_fr).col("predict").numeric_view()[0]
        assert out[0] == want

    def test_categorical_model_refuses(self, rng, tmp_path):
        from h2o3_tpu.frame.frame import Column, Frame
        from h2o3_tpu.models.kmeans import KMeans

        cats = np.array(["a", "b", "c"])[rng.integers(0, 3, 200)]
        fr = Frame([
            Column("num", rng.normal(size=200)),
            Column("cat", cats).as_factor(),
        ])
        m = KMeans(k=2, seed=1).train(fr)
        with pytest.raises(ValueError, match="numeric"):
            write_mojo(m, str(tmp_path / "km.zip"))


class TestIsolationForestReferenceMojo:
    """IsolationForestMojoWriter layout: SharedTree trees with path-length
    leaves + min/max_path_length normalization (unifyPreds)."""

    def test_mean_path_parity(self, rng, tmp_path):
        from h2o3_tpu.frame.frame import Column, Frame
        from h2o3_tpu.models.isolation_forest import (
            IsolationForest, _path_lengths)
        import jax.numpy as jnp

        n = 400
        X = rng.normal(size=(n, 4)).astype(np.float32)
        X[:10] += 6.0  # anomalies
        X[rng.random((n, 4)) < 0.05] = np.nan  # NA routing
        fr = Frame([Column(f"x{i}", X[:, i].astype(np.float64))
                    for i in range(4)])
        m = IsolationForest(ntrees=12, max_depth=6, seed=5).train(fr)
        path = str(tmp_path / "if.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "isolation_forest"
        assert mojo.info["category"] == "AnomalyDetection"

        from h2o3_tpu.models.tree.common import tree_matrix
        Xm = tree_matrix(m.data_info, fr)
        feats, threshs, splits, plens = m.trees
        want_mean = np.asarray(_path_lengths(
            jnp.asarray(Xm), jnp.asarray(feats), jnp.asarray(threshs),
            jnp.asarray(splits), jnp.asarray(plens), m.max_depth))
        got = _score_all(mojo, Xm)
        np.testing.assert_allclose(got[:, 1], want_mean, rtol=1e-5,
                                   atol=1e-5)
        # normalized scores: anomalies (shorter paths) score higher, and
        # training rows stay inside [0, 1] by the conservative rounding
        assert got[:, 0].min() >= 0.0 and got[:, 0].max() <= 1.0
        assert got[:10, 0].mean() > got[10:, 0].mean()


class TestWord2VecReferenceMojo:
    """Word2VecMojoWriter layout: vocabulary text + big-endian float32
    vectors blob (Java ByteBuffer default order)."""

    def test_vector_roundtrip(self, rng, tmp_path):
        from h2o3_tpu.models.word2vec import Word2Vec

        words = ["alpha", "beta", "gamma", "del\\nta"]  # literal \ + n
        text = [" ".join(rng.choice(words, 8)) for _ in range(200)]
        fr = Frame([Column("w", np.array(
            [w for s in text for w in s.split()], dtype=object),
            ColType.STR)])
        m = Word2Vec(vec_size=8, window_size=2, epochs=2, min_word_freq=1,
                     seed=3).train(fr)
        path = str(tmp_path / "w2v.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "word2vec"
        assert int(mojo.info["vec_size"]) == 8
        assert set(mojo.word_vectors) == set(m.words)
        for w in m.words:
            np.testing.assert_allclose(
                mojo.word_vectors[w], m.word_vector(w).astype(np.float32),
                rtol=0, atol=0)  # float32 round-trip is exact
        # the blob really is big-endian: decoding little-endian differs
        import zipfile as _zf
        with _zf.ZipFile(path) as z:
            raw = z.read("vectors")
        le = np.frombuffer(raw, "<f4")
        be = np.frombuffer(raw, ">f4")
        assert not np.allclose(le, be)


class TestDeepLearningReferenceMojo:
    """DeepLearningMojoWriter layout: neural_network_sizes + row-major
    weight_layer<i>/bias_layer<i> kv arrays, setInput normalization."""

    def _num_frame(self, rng, n=400, classif=True):
        X = rng.normal(size=(n, 5))
        X[rng.random((n, 5)) < 0.05] = np.nan
        logit = np.nan_to_num(X[:, 0]) - 0.7 * np.nan_to_num(X[:, 1])
        cols = [Column(f"x{i}", X[:, i]) for i in range(5)]
        if classif:
            y = (logit > 0).astype(np.int32)
            cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
        else:
            cols.append(Column("y", logit + 0.1 * rng.normal(size=n)))
        return Frame(cols)

    @pytest.mark.parametrize("classif", [True, False])
    @pytest.mark.parametrize("standardize", [True, False])
    def test_forward_parity(self, rng, tmp_path, classif, standardize):
        from h2o3_tpu.models.deeplearning import DeepLearning

        fr = self._num_frame(rng, classif=classif)
        m = DeepLearning(hidden=[8, 6], epochs=3, response_column="y",
                         seed=2, activation="tanh",
                         standardize=standardize).train(fr)
        path = str(tmp_path / f"dl_{classif}_{standardize}.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "deeplearning"
        assert mojo.info["activation"] == "Tanh"
        # the MOJO consumes raw rows (it normalizes internally)
        raw = np.stack([fr.col(f"x{i}").numeric_view() for i in range(5)],
                       axis=1)
        got = _score_all(mojo, raw)
        want = m._predict_raw(fr)
        if classif:
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        else:
            np.testing.assert_allclose(got[:, 0], want, rtol=1e-4,
                                       atol=1e-5)

    def test_autoencoder_refuses(self, rng, tmp_path):
        from h2o3_tpu.models.deeplearning import DeepLearning

        fr = self._num_frame(rng, n=120, classif=False).drop("y")
        m = DeepLearning(hidden=[4], epochs=1, autoencoder=True,
                         seed=1).train(fr)
        with pytest.raises(ValueError, match="autoencoder"):
            write_mojo(m, str(tmp_path / "ae.zip"))


class TestTargetEncoderReferenceMojo:
    """TargetEncoderMojoWriter layout: encoding_map.ini sections +
    NA-presence and column-mapping files, blending kv."""

    @pytest.mark.parametrize("blending", [False, True])
    def test_transform_parity(self, rng, tmp_path, blending):
        from h2o3_tpu.models.target_encoder import TargetEncoder

        n = 600
        g1 = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
        g2 = np.array(["x", "y"])[rng.integers(0, 2, n)]
        y = ((g1 == "a") | (rng.random(n) < 0.3)).astype(np.int32)
        g1c = Column("g1", g1).as_factor()
        # inject NA codes: the map-derived prior must still equal the
        # model's global prior (the writer's synthetic correction row)
        g1c.data[rng.random(n) < 0.1] = -1
        fr = Frame([
            g1c,
            Column("g2", g2).as_factor(),
            Column("y", y, ColType.CAT, ["n", "p"]),
        ])
        m = TargetEncoder(response_column="y", blending=blending,
                          noise=0.0).train(fr)
        path = str(tmp_path / f"te_{blending}.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "targetencoder"
        assert set(mojo.te_columns) == {"g1", "g2"}
        want = m.transform(fr)
        c1 = fr.col("g1").data
        c2 = fr.col("g2").data
        w1 = want.col("g1_te").numeric_view()
        w2 = want.col("g2_te").numeric_view()
        for i in range(0, n, 29):
            got = mojo.te_transform(
                {"g1": float(c1[i]), "g2": float(c2[i])})
            np.testing.assert_allclose(got["g1_te"], w1[i], rtol=1e-10)
            np.testing.assert_allclose(got["g2_te"], w2[i], rtol=1e-10)
        # unseen level falls back to the prior
        got = mojo.te_transform({"g1": float("nan"), "g2": 0.0})
        prior = float(np.mean(y))
        np.testing.assert_allclose(got["g1_te"], prior, rtol=1e-10)


class TestPCAReferenceMojo:
    """PCAMojoWriter layout: big-endian eigenvectors_raw blob in
    cats-first order + permutation/catOffsets/norm arrays."""

    def test_projection_parity_with_categoricals(self, rng, tmp_path):
        from h2o3_tpu.models.pca import PCA

        n = 400
        X = rng.normal(size=(n, 3))
        g = rng.integers(0, 3, size=n).astype(np.int32)
        fr = Frame([
            Column("x0", X[:, 0]),
            Column("g", g, ColType.CAT, ["u", "v", "w"]),
            Column("x1", X[:, 1]),
            Column("x2", X[:, 2]),
        ])
        m = PCA(k=3, seed=1).train(fr)
        path = str(tmp_path / "pca.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "pca"
        assert int(mojo.info["k"]) == 3
        want = m._predict_raw(fr)
        # raw rows in predictor order: [x0, g, x1, x2]
        gd = fr.col("g").data.astype(np.float64)
        for i in range(0, n, 31):
            row = np.array([X[i, 0], gd[i], X[i, 1], X[i, 2]])
            got = mojo.score0(row)
            np.testing.assert_allclose(got, want[i], rtol=1e-4, atol=1e-5)


class TestCoxPHReferenceMojo:
    """CoxPHMojoWriter layout: cats-first coef kv + x_mean blobs whose
    coef-weighted sum forms lpBase (score = coef·(x − x̄))."""

    def test_linear_predictor_parity(self, rng, tmp_path):
        from h2o3_tpu.models.coxph import CoxPH

        n = 400
        X = rng.normal(size=(n, 2))
        g = rng.integers(0, 3, size=n).astype(np.int32)
        lam = np.exp(0.8 * X[:, 0] - 0.5 * X[:, 1] + 0.4 * (g == 2))
        t_event = rng.exponential(1.0 / lam)
        t_cens = rng.exponential(2.0, size=n)
        t = np.minimum(t_event, t_cens)
        d = (t_event <= t_cens).astype(np.float64)
        fr = Frame([
            Column("g", g, ColType.CAT, ["u", "v", "w"]),
            Column("x0", X[:, 0]),
            Column("x1", X[:, 1]),
            Column("time", t),
            Column("event", d),
        ])
        m = CoxPH(response_column="event", stop_column="time",
                  ignored_columns=["time"]).train(fr)
        path = str(tmp_path / "cox.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "coxph"
        want = m._predict_raw(fr)
        gd = g.astype(np.float64)
        for i in range(0, n, 23):
            got = mojo.score0(np.array([gd[i], X[i, 0], X[i, 1]]))
            np.testing.assert_allclose(got[0], want[i], rtol=1e-6,
                                       atol=1e-8)


class TestStackedEnsembleReferenceMojo:
    """MultiModelMojoWriter layout: metalearner + base models embedded
    as full MOJOs under models/<algo>/<key>/, parent kv naming them."""

    def test_binomial_parity(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.models.stacked_ensemble import StackedEnsemble
        from h2o3_tpu.models.tree.gbm import GBM

        n = 600
        X = rng.normal(size=(n, 4))
        logit = X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.int32)
        cols = [Column(f"x{j}", X[:, j]) for j in range(4)]
        cols.append(Column("y", y, ColType.CAT, ["0", "1"]))
        fr = Frame(cols)

        common = dict(response_column="y", nfolds=3,
                      keep_cross_validation_predictions=True, seed=11)
        glm = GLM(family="binomial", **common).train(fr)
        gbm = GBM(ntrees=8, max_depth=3, min_rows=2, **common).train(fr)
        se = StackedEnsemble(base_models=[glm, gbm], response_column="y",
                             seed=11).train(fr)
        path = str(tmp_path / "se.zip")
        write_mojo(se, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "stackedensemble"
        assert int(mojo.info["base_models_num"]) == 2
        assert mojo.metalearner.info["algo"] == "glm"
        assert {b.info["algo"] for b in mojo.base_models} == {"glm", "gbm"}

        want = se._predict_raw(fr)  # [n, 2] probabilities
        for i in range(0, n, 37):
            got = mojo.score0(X[i].astype(np.float64))
            np.testing.assert_allclose(got, want[i], rtol=1e-5, atol=1e-6)


class TestJavaDoubleSpelling:
    """ADVICE r4: non-finite doubles must render as Java parseDouble
    spellings ('Infinity'/'NaN'), and the parser must accept both."""

    def test_jarr_roundtrip_nonfinite(self):
        from h2o3_tpu.models.mojo_ref import _jarr, _parse_jarr
        import math

        vals = [1.5, float("inf"), float("-inf"), float("nan"), -0.0]
        s = _jarr(vals)
        assert "Infinity" in s and "NaN" in s
        assert "inf" not in s.replace("Infinity", "")  # no Python spelling
        back = _parse_jarr(s)
        assert back[0] == 1.5 and back[1] == math.inf and back[2] == -math.inf
        assert math.isnan(back[3])

    def test_parse_accepts_python_spelling(self):
        from h2o3_tpu.models.mojo_ref import _parse_jarr
        import math

        back = _parse_jarr("[inf, -inf, nan, 2.0]")
        assert back[0] == math.inf and back[1] == -math.inf
        assert math.isnan(back[2]) and back[3] == 2.0


class TestPipelineReferenceMojo:
    """Reference-format pipeline MOJO (hex/genmodel/MojoPipelineWriter +
    algos/pipeline/MojoPipeline): sub-model predictions feed generated
    columns of the main model inside ONE interoperable zip."""

    def _parts(self, rng, tmp_path):
        from h2o3_tpu.models.glm import GLM, GLMParameters
        from h2o3_tpu.models.tree.gbm import GBM

        n = 400
        X = rng.normal(size=(n, 3))
        y_lin = 2.0 * X[:, 0] - X[:, 1] + rng.normal(size=n) * 0.1
        glm_fr = Frame([
            Column("a", X[:, 0]), Column("b", X[:, 1]),
            Column("ylin", y_lin),
        ])
        glm = GLM(GLMParameters(response_column="ylin", family="gaussian",
                                lambda_=0.0)).train(glm_fr)
        glm_pred = glm.predict(glm_fr).col(0).numeric_view()
        yb = (y_lin + 0.5 * X[:, 2] > 0).astype(np.int32)
        main_fr = Frame([
            Column("c", X[:, 2]), Column("glm_pred", glm_pred),
            Column("y", yb, ColType.CAT, ["n", "p"]),
        ])
        gbm = GBM(ntrees=5, max_depth=3, response_column="y", seed=3,
                  min_rows=2).train(main_fr)
        return glm, gbm, X, glm_pred, main_fr

    def test_write_decode_score_parity(self, rng, tmp_path):
        from h2o3_tpu.models.mojo_ref import write_pipeline_mojo

        glm, gbm, X, glm_pred, main_fr = self._parts(rng, tmp_path)
        path = str(tmp_path / "pipe.zip")
        write_pipeline_mojo({"glm_stage": glm, "main": gbm},
                            {"glm_pred": "glm_stage:0"}, "main", path)

        # reference layout facts an external MultiModelMojoReader needs
        with zipfile.ZipFile(path) as z:
            names = z.namelist()
            assert "models/glm_stage/model.ini" in names
            assert "models/main/model.ini" in names
            ini = z.read("model.ini").decode()
            assert "algorithm = MOJO Pipeline" in ini
            assert "main_model = main" in ini
            assert "generated_column_name_0 = glm_pred" in ini

        mojo = read_mojo(path)
        assert mojo.info["algo"] == "pipeline"
        # pipeline schema: glm features first, then main's non-generated
        assert mojo.columns[:2] == ["a", "b"]
        assert "glm_pred" not in mojo.columns
        ia, ib, ic = (mojo.columns.index(k) for k in ("a", "b", "c"))
        want = gbm._predict_raw(main_fr)
        for i in range(0, 400, 23):
            row = np.full(len(mojo.columns), np.nan)
            row[ia], row[ib], row[ic] = X[i, 0], X[i, 1], X[i, 2]
            got = mojo.score0(row)
            np.testing.assert_allclose(got, want[i], rtol=1e-4, atol=1e-5)

    def test_missing_main_alias_refused(self, rng, tmp_path):
        from h2o3_tpu.models.mojo_ref import write_pipeline_mojo

        glm, gbm, *_ = self._parts(rng, tmp_path)
        with pytest.raises(ValueError, match="alias"):
            write_pipeline_mojo({"glm_stage": glm}, {}, "nope",
                                str(tmp_path / "x.zip"))


class TestGamReferenceMojo:
    """GAM reference MOJO (GAMMojoWriter / GamMojoReader /
    GamUtilsCubicRegression): knots + binvD + zTranspose blobs, centered
    betas, independent re-gamification at score time."""

    def _train(self, rng, family="gaussian"):
        from h2o3_tpu.models.gam import GAM

        n = 400
        x1 = rng.normal(size=n)
        x2 = rng.uniform(-2, 2, size=n)
        z = rng.normal(size=n)
        g = rng.integers(0, 3, size=n)
        f = np.sin(1.3 * x1) + 0.4 * x2 ** 2 + 0.3 * z + 0.2 * g
        if family == "binomial":
            y = (f + rng.normal(size=n) * 0.3 > 0.5).astype(np.int32)
            ycol = Column("y", y, ColType.CAT, ["n", "p"])
        else:
            ycol = Column("y", f + rng.normal(size=n) * 0.1)
        fr = Frame([
            Column("z", z),
            Column("g", g.astype(np.int32), ColType.CAT, ["a", "b", "c"]),
            Column("x1", x1), Column("x2", x2), ycol,
        ])
        m = GAM(response_column="y", gam_columns=["x1", "x2"],
                num_knots=8, family=family, lambda_=0.0,
                standardize=False).train(fr)
        return m, fr

    @pytest.mark.parametrize("family", ["gaussian", "binomial"])
    def test_write_decode_score_parity(self, rng, tmp_path, family):
        from h2o3_tpu.models.mojo_ref import write_mojo

        m, fr = self._train(rng, family)
        path = str(tmp_path / f"gam_{family}.zip")
        write_mojo(m, path)
        mojo = read_mojo(path)
        assert mojo.info["algo"] == "gam"
        assert mojo.gam_columns == ["x1", "x2"]
        want = m._predict_raw(fr)
        g = fr.col("g").data
        for i in range(0, 400, 31):
            row = {"g": float(g[i]),
                   "z": float(fr.col("z").data[i]),
                   "x1": float(fr.col("x1").data[i]),
                   "x2": float(fr.col("x2").data[i])}
            got = mojo.gam_score0(row)
            np.testing.assert_allclose(
                got, np.atleast_1d(want[i]), rtol=1e-6, atol=1e-8)

    def test_layout_facts(self, rng, tmp_path):
        from h2o3_tpu.models.mojo_ref import write_mojo

        m, fr = self._train(rng)
        path = str(tmp_path / "gam.zip")
        write_mojo(m, path)
        with zipfile.ZipFile(path) as z:
            names = z.namelist()
            for entry in ("knots", "zTranspose", "_binvD",
                          "gam_columns_sorted", "gamColNamesCenter",
                          "_names_no_centering"):
                assert entry in names, entry
            ini = z.read("model.ini").decode()
            assert "algorithm = Generalized Additive Model" in ini
            assert "num_TP_col = 0" in ini
            # blob sizes: K=8 knots -> zT (7x8), binvD (6x8), two cols
            assert len(z.read("knots")) == 2 * 8 * 8
            assert len(z.read("zTranspose")) == 2 * 7 * 8 * 8
            assert len(z.read("_binvD")) == 2 * 6 * 8 * 8

    def test_refusals(self, rng, tmp_path):
        from h2o3_tpu.models.gam import GAM
        from h2o3_tpu.models.mojo_ref import write_mojo

        n = 300
        x = rng.normal(size=n)
        fr = Frame([Column("x", x),
                    Column("y", np.sin(x) + rng.normal(size=n) * 0.1)])
        tp = GAM(response_column="y", gam_columns=["x"], num_knots=8,
                 bs=1, lambda_=0.0, standardize=False).train(fr)
        with pytest.raises(ValueError, match="thin-plate|bs=0"):
            write_mojo(tp, str(tmp_path / "tp.zip"))
        std = GAM(response_column="y", gam_columns=["x"], num_knots=8,
                  lambda_=0.0, standardize=True).train(fr)
        with pytest.raises(ValueError, match="standardize"):
            write_mojo(std, str(tmp_path / "std.zip"))
