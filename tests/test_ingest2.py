"""Round-4 ingest: cloud persist backends (S3/GCS/WebHDFS over local
fakes), ORC/Avro/XLSX parsers, range-partitioned SQL import.

Reference: h2o-persist-s3/.../PersistS3.java, h2o-persist-gcs,
h2o-persist-hdfs, h2o-parsers/h2o-{orc,avro}-parser,
water/parser/XlsParser.java, water/jdbc/SQLManager.java."""

import io
import json
import struct
import threading
import zipfile
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.frame.ingest import (
    import_parse,
    import_sql_table,
    parse_bytes,
    sniff_format,
)

CSV = "a,b\n1,x\n2,y\n3,x\n"


# ---------------------------------------------------------------------------
# local fake cloud services


class _Fake:
    """One tiny HTTP server acting as S3 / GCS / WebHDFS, keyed by path."""

    def __init__(self, routes):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                for match, fn in fake.routes:
                    if match(self.path):
                        code, ctype, body = fn(self.path)
                        self.send_response(code)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                self.send_response(404)
                self.end_headers()

        self.routes = routes
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"


class TestS3Backend:
    def test_get_and_list_via_fake(self, monkeypatch):
        listing = (
            '<?xml version="1.0"?><ListBucketResult>'
            "<Contents><Key>data/part1.csv</Key></Contents>"
            "<Contents><Key>data/part2.csv</Key></Contents>"
            "</ListBucketResult>").encode()

        def route_list(path):
            return 200, "application/xml", listing

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([
            (lambda p: "list-type=2" in p, route_list),
            (lambda p: p.startswith("/bkt/data/part"), route_obj),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", fake.url)
            fr = import_parse("s3://bkt/data/")
            assert fr.nrows == 6 and fr.names == ["a", "b"]
            fr1 = import_parse("s3://bkt/data/part1.csv")
            assert fr1.nrows == 3
        finally:
            fake.stop()

    def test_sigv4_header_sent_when_credentialed(self, monkeypatch):
        seen = {}

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([(lambda p: True, route_obj)])
        # wrap handler to capture auth header
        orig_init = fake.httpd.RequestHandlerClass.do_GET

        def do_get(self):
            seen["auth"] = self.headers.get("Authorization", "")
            seen["sha"] = self.headers.get("x-amz-content-sha256", "")
            orig_init(self)

        fake.httpd.RequestHandlerClass.do_GET = do_get
        try:
            monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", fake.url)
            monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKTEST")
            monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sk")
            fr = import_parse("s3://bkt/f.csv")
            assert fr.nrows == 3
            assert seen["auth"].startswith("AWS4-HMAC-SHA256 Credential=AKTEST/")
            assert "SignedHeaders=" in seen["auth"]
            assert len(seen["sha"]) == 64
        finally:
            fake.stop()


class TestGCSBackend:
    def test_get_and_list_via_fake(self, monkeypatch):
        def route_list(path):
            return 200, "application/json", json.dumps(
                {"items": [{"name": "d/x1.csv"}, {"name": "d/x2.csv"}]}
            ).encode()

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([
            (lambda p: "/o?" in p, route_list),
            (lambda p: "alt=media" in p, route_obj),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_GCS_ENDPOINT", fake.url)
            fr = import_parse("gs://bkt/d/")
            assert fr.nrows == 6
        finally:
            fake.stop()


class TestHDFSBackend:
    def test_webhdfs_open_and_list(self, monkeypatch):
        def route_list(path):
            return 200, "application/json", json.dumps({
                "FileStatuses": {"FileStatus": [
                    {"pathSuffix": "p1.csv", "type": "FILE"},
                    {"pathSuffix": "sub", "type": "DIRECTORY"},
                ]}}).encode()

        def route_open(path):
            return 200, "application/octet-stream", CSV.encode()

        fake = _Fake([
            (lambda p: "op=LISTSTATUS" in p, route_list),
            (lambda p: "op=OPEN" in p, route_open),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_WEBHDFS", fake.url)
            fr = import_parse("hdfs://nn:8020/data/")
            assert fr.nrows == 3  # one FILE entry; directory skipped
        finally:
            fake.stop()


# ---------------------------------------------------------------------------
# formats


class TestORC:
    def test_roundtrip(self):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.orc as po

        table = pa.table({"n": [1.5, 2.5, None], "s": ["a", "b", "a"]})
        buf = io.BytesIO()
        po.write_table(table, buf)
        data = buf.getvalue()
        assert sniff_format("f.orc", data) == "orc"
        fr = parse_bytes("f.orc", data)
        assert fr.nrows == 3
        col = fr.col("n")
        assert col.type is ColType.NUM
        assert np.isnan(col.data[2])


def _avro_long(v):
    # zigzag varint
    v = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_str(s):
    b = s.encode()
    return _avro_long(len(b)) + b


def _make_avro(codec="null"):
    schema = {
        "type": "record", "name": "r", "fields": [
            {"name": "id", "type": "long"},
            {"name": "v", "type": "double"},
            {"name": "s", "type": ["null", "string"]},
        ]}
    rows = [(1, 1.5, "x"), (2, 2.5, None), (3, -0.5, "y")]
    body = b""
    for rid, v, s in rows:
        body += _avro_long(rid) + struct.pack("<d", v)
        if s is None:
            body += _avro_long(0)
        else:
            body += _avro_long(1) + _avro_str(s)
    if codec == "deflate":
        comp = zlib.compressobj(9, zlib.DEFLATED, -15)
        body = comp.compress(body) + comp.flush()
    sync = bytes(range(16))
    out = b"Obj\x01"
    out += _avro_long(2)
    out += _avro_str("avro.schema") + _avro_long(
        len(json.dumps(schema).encode())) + json.dumps(schema).encode()
    out += _avro_str("avro.codec") + _avro_long(len(codec)) + codec.encode()
    out += _avro_long(0)
    out += sync
    out += _avro_long(3) + _avro_long(len(body)) + body + sync
    return out


class TestAvro:
    @pytest.mark.parametrize("codec", ["null", "deflate"])
    def test_container_roundtrip(self, codec):
        data = _make_avro(codec)
        assert sniff_format("f.avro", data) == "avro"
        fr = parse_bytes("f.avro", data)
        assert fr.nrows == 3 and fr.names == ["id", "v", "s"]
        np.testing.assert_array_equal(fr.col("id").data, [1.0, 2.0, 3.0])
        np.testing.assert_allclose(fr.col("v").data, [1.5, 2.5, -0.5])
        s = fr.col("s")
        assert s.type is ColType.CAT
        assert s.data[1] < 0  # the null union branch is NA


def _make_xlsx():
    shared = (
        '<?xml version="1.0"?>'
        '<sst xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main" count="3" uniqueCount="3">'
        "<si><t>name</t></si><si><t>alice</t></si><si><t>bob</t></si></sst>")
    sheet = (
        '<?xml version="1.0"?>'
        '<worksheet xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main"><sheetData>'
        '<row r="1"><c r="A1" t="s"><v>0</v></c><c r="B1" t="str"><v>age</v></c></row>'
        '<row r="2"><c r="A2" t="s"><v>1</v></c><c r="B2"><v>31</v></c></row>'
        '<row r="3"><c r="A3" t="s"><v>2</v></c><c r="B3"><v>45.5</v></c></row>'
        "</sheetData></worksheet>")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("[Content_Types].xml", "<Types/>")
        z.writestr("xl/sharedStrings.xml", shared)
        z.writestr("xl/worksheets/sheet1.xml", sheet)
    return buf.getvalue()


class TestXLSX:
    def test_parse(self):
        data = _make_xlsx()
        assert sniff_format("book.xlsx", data) == "xlsx"
        fr = parse_bytes("book.xlsx", data)
        assert fr.names == ["name", "age"]
        assert fr.nrows == 2
        np.testing.assert_allclose(fr.col("age").data, [31.0, 45.5])

    def test_legacy_xls_actionable_error(self):
        with pytest.raises(ValueError, match="xlsx"):
            parse_bytes("old.xls", b"\xd0\xcf\x11\xe0" + b"\x00" * 100)

    def test_plain_zip_of_csvs_still_explodes(self):
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            z.writestr("a.csv", CSV)
            z.writestr("b.csv", CSV)
        fr = parse_bytes("both.zip", buf.getvalue())
        assert fr.nrows == 6


# ---------------------------------------------------------------------------
# SQL: generic DB-API + range partitioning


class TestSQLImport:
    def _db(self, tmp_path, n=100):
        import sqlite3

        path = str(tmp_path / "t.db")
        conn = sqlite3.connect(path)
        conn.execute("CREATE TABLE pts (id INTEGER, val REAL, tag TEXT)")
        rng = np.random.default_rng(0)
        rows = [(int(i), float(rng.normal()), f"t{i % 3}")
                for i in range(n)]
        rows[5] = (rows[5][0], rows[5][1], None)
        conn.executemany("INSERT INTO pts VALUES (?,?,?)", rows)
        conn.commit()
        conn.close()
        return path

    def test_partitioned_matches_single(self, tmp_path):
        path = self._db(tmp_path)
        single = import_sql_table(f"sqlite:{path}", table="pts")
        parted = import_sql_table(
            f"sqlite:{path}", table="pts",
            partition_column="id", num_partitions=4)
        assert parted.nrows == single.nrows == 100
        # partitions concatenate in range order == id order here
        np.testing.assert_array_equal(parted.col("id").data,
                                      single.col("id").data)
        np.testing.assert_allclose(parted.col("val").data,
                                   single.col("val").data)

    def test_null_partition_keys_not_dropped(self, tmp_path):
        import sqlite3

        path = self._db(tmp_path, n=20)
        conn = sqlite3.connect(path)
        conn.execute("INSERT INTO pts VALUES (NULL, 9.5, 'x')")
        conn.commit()
        conn.close()
        parted = import_sql_table(
            f"sqlite:{path}", table="pts",
            partition_column="id", num_partitions=3)
        assert parted.nrows == 21

    def test_unsupported_engine_actionable(self):
        with pytest.raises(ValueError, match="psycopg2"):
            import_sql_table("postgresql://h/db", table="t")

    def test_jdbc_scheme_not_in_persist(self):
        from h2o3_tpu.frame.ingest import resolve_persist

        with pytest.raises(ValueError, match="jdbc"):
            resolve_persist("jdbc:oracle:thin@x")


class TestS3Pagination:
    def test_list_follows_continuation_tokens(self, monkeypatch):
        pages = {
            None: (
                '<?xml version="1.0"?><ListBucketResult>'
                "<IsTruncated>true</IsTruncated>"
                "<NextContinuationToken>tok2</NextContinuationToken>"
                "<Contents><Key>d/a.csv</Key></Contents>"
                "</ListBucketResult>"),
            "tok2": (
                '<?xml version="1.0"?><ListBucketResult>'
                "<IsTruncated>false</IsTruncated>"
                "<Contents><Key>d/b.csv</Key></Contents>"
                "</ListBucketResult>"),
        }

        def route_list(path):
            tok = None
            if "continuation-token=" in path:
                tok = path.split("continuation-token=")[1].split("&")[0]
            return 200, "application/xml", pages[tok].encode()

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([
            (lambda p: "list-type=2" in p, route_list),
            (lambda p: p.startswith("/bkt/d/"), route_obj),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", fake.url)
            fr = import_parse("s3://bkt/d/")
            assert fr.nrows == 6  # BOTH pages' objects imported
        finally:
            fake.stop()
