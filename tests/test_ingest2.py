"""Round-4 ingest: cloud persist backends (S3/GCS/WebHDFS over local
fakes), ORC/Avro/XLSX parsers, range-partitioned SQL import.

Reference: h2o-persist-s3/.../PersistS3.java, h2o-persist-gcs,
h2o-persist-hdfs, h2o-parsers/h2o-{orc,avro}-parser,
water/parser/XlsParser.java, water/jdbc/SQLManager.java."""

import io
import json
import struct
import threading
import zipfile
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.frame.ingest import (
    import_parse,
    import_sql_table,
    parse_bytes,
    sniff_format,
)

CSV = "a,b\n1,x\n2,y\n3,x\n"


# ---------------------------------------------------------------------------
# local fake cloud services


class _Fake:
    """One tiny HTTP server acting as S3 / GCS / WebHDFS, keyed by path."""

    def __init__(self, routes):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                for match, fn in fake.routes:
                    if match(self.path):
                        code, ctype, body = fn(self.path)
                        self.send_response(code)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                self.send_response(404)
                self.end_headers()

        self.routes = routes
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True)
        self.thread.start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"


class TestS3Backend:
    def test_get_and_list_via_fake(self, monkeypatch):
        listing = (
            '<?xml version="1.0"?><ListBucketResult>'
            "<Contents><Key>data/part1.csv</Key></Contents>"
            "<Contents><Key>data/part2.csv</Key></Contents>"
            "</ListBucketResult>").encode()

        def route_list(path):
            return 200, "application/xml", listing

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([
            (lambda p: "list-type=2" in p, route_list),
            (lambda p: p.startswith("/bkt/data/part"), route_obj),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", fake.url)
            fr = import_parse("s3://bkt/data/")
            assert fr.nrows == 6 and fr.names == ["a", "b"]
            fr1 = import_parse("s3://bkt/data/part1.csv")
            assert fr1.nrows == 3
        finally:
            fake.stop()

    def test_sigv4_header_sent_when_credentialed(self, monkeypatch):
        seen = {}

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([(lambda p: True, route_obj)])
        # wrap handler to capture auth header
        orig_init = fake.httpd.RequestHandlerClass.do_GET

        def do_get(self):
            seen["auth"] = self.headers.get("Authorization", "")
            seen["sha"] = self.headers.get("x-amz-content-sha256", "")
            orig_init(self)

        fake.httpd.RequestHandlerClass.do_GET = do_get
        try:
            monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", fake.url)
            monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AKTEST")
            monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "sk")
            fr = import_parse("s3://bkt/f.csv")
            assert fr.nrows == 3
            assert seen["auth"].startswith("AWS4-HMAC-SHA256 Credential=AKTEST/")
            assert "SignedHeaders=" in seen["auth"]
            assert len(seen["sha"]) == 64
        finally:
            fake.stop()


class TestGCSBackend:
    def test_get_and_list_via_fake(self, monkeypatch):
        def route_list(path):
            return 200, "application/json", json.dumps(
                {"items": [{"name": "d/x1.csv"}, {"name": "d/x2.csv"}]}
            ).encode()

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([
            (lambda p: "/o?" in p, route_list),
            (lambda p: "alt=media" in p, route_obj),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_GCS_ENDPOINT", fake.url)
            fr = import_parse("gs://bkt/d/")
            assert fr.nrows == 6
        finally:
            fake.stop()


class TestHDFSBackend:
    def test_webhdfs_open_and_list(self, monkeypatch):
        def route_list(path):
            return 200, "application/json", json.dumps({
                "FileStatuses": {"FileStatus": [
                    {"pathSuffix": "p1.csv", "type": "FILE"},
                    {"pathSuffix": "sub", "type": "DIRECTORY"},
                ]}}).encode()

        def route_open(path):
            return 200, "application/octet-stream", CSV.encode()

        fake = _Fake([
            (lambda p: "op=LISTSTATUS" in p, route_list),
            (lambda p: "op=OPEN" in p, route_open),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_WEBHDFS", fake.url)
            fr = import_parse("hdfs://nn:8020/data/")
            assert fr.nrows == 3  # one FILE entry; directory skipped
        finally:
            fake.stop()


# ---------------------------------------------------------------------------
# formats


class TestORC:
    def test_roundtrip(self):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.orc as po

        table = pa.table({"n": [1.5, 2.5, None], "s": ["a", "b", "a"]})
        buf = io.BytesIO()
        po.write_table(table, buf)
        data = buf.getvalue()
        assert sniff_format("f.orc", data) == "orc"
        fr = parse_bytes("f.orc", data)
        assert fr.nrows == 3
        col = fr.col("n")
        assert col.type is ColType.NUM
        assert np.isnan(col.data[2])


def _avro_long(v):
    # zigzag varint
    v = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_str(s):
    b = s.encode()
    return _avro_long(len(b)) + b


def _make_avro(codec="null"):
    schema = {
        "type": "record", "name": "r", "fields": [
            {"name": "id", "type": "long"},
            {"name": "v", "type": "double"},
            {"name": "s", "type": ["null", "string"]},
        ]}
    rows = [(1, 1.5, "x"), (2, 2.5, None), (3, -0.5, "y")]
    body = b""
    for rid, v, s in rows:
        body += _avro_long(rid) + struct.pack("<d", v)
        if s is None:
            body += _avro_long(0)
        else:
            body += _avro_long(1) + _avro_str(s)
    if codec == "deflate":
        comp = zlib.compressobj(9, zlib.DEFLATED, -15)
        body = comp.compress(body) + comp.flush()
    sync = bytes(range(16))
    out = b"Obj\x01"
    out += _avro_long(2)
    out += _avro_str("avro.schema") + _avro_long(
        len(json.dumps(schema).encode())) + json.dumps(schema).encode()
    out += _avro_str("avro.codec") + _avro_long(len(codec)) + codec.encode()
    out += _avro_long(0)
    out += sync
    out += _avro_long(3) + _avro_long(len(body)) + body + sync
    return out


class TestAvro:
    @pytest.mark.parametrize("codec", ["null", "deflate"])
    def test_container_roundtrip(self, codec):
        data = _make_avro(codec)
        assert sniff_format("f.avro", data) == "avro"
        fr = parse_bytes("f.avro", data)
        assert fr.nrows == 3 and fr.names == ["id", "v", "s"]
        np.testing.assert_array_equal(fr.col("id").data, [1.0, 2.0, 3.0])
        np.testing.assert_allclose(fr.col("v").data, [1.5, 2.5, -0.5])
        s = fr.col("s")
        assert s.type is ColType.CAT
        assert s.data[1] < 0  # the null union branch is NA


def _make_xlsx():
    shared = (
        '<?xml version="1.0"?>'
        '<sst xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main" count="3" uniqueCount="3">'
        "<si><t>name</t></si><si><t>alice</t></si><si><t>bob</t></si></sst>")
    sheet = (
        '<?xml version="1.0"?>'
        '<worksheet xmlns="http://schemas.openxmlformats.org/spreadsheetml/2006/main"><sheetData>'
        '<row r="1"><c r="A1" t="s"><v>0</v></c><c r="B1" t="str"><v>age</v></c></row>'
        '<row r="2"><c r="A2" t="s"><v>1</v></c><c r="B2"><v>31</v></c></row>'
        '<row r="3"><c r="A3" t="s"><v>2</v></c><c r="B3"><v>45.5</v></c></row>'
        "</sheetData></worksheet>")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("[Content_Types].xml", "<Types/>")
        z.writestr("xl/sharedStrings.xml", shared)
        z.writestr("xl/worksheets/sheet1.xml", sheet)
    return buf.getvalue()


class TestXLSX:
    def test_parse(self):
        data = _make_xlsx()
        assert sniff_format("book.xlsx", data) == "xlsx"
        fr = parse_bytes("book.xlsx", data)
        assert fr.names == ["name", "age"]
        assert fr.nrows == 2
        np.testing.assert_allclose(fr.col("age").data, [31.0, 45.5])

    def test_truncated_xls_actionable_error(self):
        # BIFF .xls now parses (TestLegacyXls); a truncated compound doc
        # must still fail with an xls-specific diagnosis, not a crash
        with pytest.raises(ValueError, match="OLE2|stream|xls"):
            parse_bytes("old.xls", b"\xd0\xcf\x11\xe0" + b"\x00" * 100)

    def test_plain_zip_of_csvs_still_explodes(self):
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as z:
            z.writestr("a.csv", CSV)
            z.writestr("b.csv", CSV)
        fr = parse_bytes("both.zip", buf.getvalue())
        assert fr.nrows == 6


# ---------------------------------------------------------------------------
# SQL: generic DB-API + range partitioning


class TestSQLImport:
    def _db(self, tmp_path, n=100):
        import sqlite3

        path = str(tmp_path / "t.db")
        conn = sqlite3.connect(path)
        conn.execute("CREATE TABLE pts (id INTEGER, val REAL, tag TEXT)")
        rng = np.random.default_rng(0)
        rows = [(int(i), float(rng.normal()), f"t{i % 3}")
                for i in range(n)]
        rows[5] = (rows[5][0], rows[5][1], None)
        conn.executemany("INSERT INTO pts VALUES (?,?,?)", rows)
        conn.commit()
        conn.close()
        return path

    def test_partitioned_matches_single(self, tmp_path):
        path = self._db(tmp_path)
        single = import_sql_table(f"sqlite:{path}", table="pts")
        parted = import_sql_table(
            f"sqlite:{path}", table="pts",
            partition_column="id", num_partitions=4)
        assert parted.nrows == single.nrows == 100
        # partitions concatenate in range order == id order here
        np.testing.assert_array_equal(parted.col("id").data,
                                      single.col("id").data)
        np.testing.assert_allclose(parted.col("val").data,
                                   single.col("val").data)

    def test_null_partition_keys_not_dropped(self, tmp_path):
        import sqlite3

        path = self._db(tmp_path, n=20)
        conn = sqlite3.connect(path)
        conn.execute("INSERT INTO pts VALUES (NULL, 9.5, 'x')")
        conn.commit()
        conn.close()
        parted = import_sql_table(
            f"sqlite:{path}", table="pts",
            partition_column="id", num_partitions=3)
        assert parted.nrows == 21

    def test_unsupported_engine_actionable(self):
        with pytest.raises(ValueError, match="psycopg2"):
            import_sql_table("postgresql://h/db", table="t")

    def test_jdbc_scheme_not_in_persist(self):
        from h2o3_tpu.frame.ingest import resolve_persist

        with pytest.raises(ValueError, match="jdbc"):
            resolve_persist("jdbc:oracle:thin@x")


class TestS3Pagination:
    def test_list_follows_continuation_tokens(self, monkeypatch):
        pages = {
            None: (
                '<?xml version="1.0"?><ListBucketResult>'
                "<IsTruncated>true</IsTruncated>"
                "<NextContinuationToken>tok2</NextContinuationToken>"
                "<Contents><Key>d/a.csv</Key></Contents>"
                "</ListBucketResult>"),
            "tok2": (
                '<?xml version="1.0"?><ListBucketResult>'
                "<IsTruncated>false</IsTruncated>"
                "<Contents><Key>d/b.csv</Key></Contents>"
                "</ListBucketResult>"),
        }

        def route_list(path):
            tok = None
            if "continuation-token=" in path:
                tok = path.split("continuation-token=")[1].split("&")[0]
            return 200, "application/xml", pages[tok].encode()

        def route_obj(path):
            return 200, "text/csv", CSV.encode()

        fake = _Fake([
            (lambda p: "list-type=2" in p, route_list),
            (lambda p: p.startswith("/bkt/d/"), route_obj),
        ])
        try:
            monkeypatch.setenv("H2O3_TPU_S3_ENDPOINT", fake.url)
            fr = import_parse("s3://bkt/d/")
            assert fr.nrows == 6  # BOTH pages' objects imported
        finally:
            fake.stop()


class TestLegacyXls:
    """Legacy BIFF .xls (water/parser/XlsParser.java; frame/xls.py).
    The fixtures are written by a from-scratch OLE2+BIFF8 writer below,
    so the reader is exercised against independently-constructed bytes
    (same pattern as the xlsx tests' zipfile-built workbooks)."""

    @staticmethod
    def _biff_stream(rows, sst_strings):
        """Workbook stream: globals (BOF, SST, EOF) + one sheet substream
        with NUMBER / RK / LABELSST / LABEL cells."""
        import struct

        def rec(rid, payload):
            return struct.pack("<HH", rid, len(payload)) + payload

        out = rec(0x0809, struct.pack("<HHHH", 0x0600, 0x0005, 0, 0))
        if sst_strings:
            body = struct.pack("<II", len(sst_strings), len(sst_strings))
            for s in sst_strings:
                enc = s.encode("utf-16-le")
                body += struct.pack("<HB", len(s), 0x01) + enc
            out += rec(0x00FC, body)
        out += rec(0x000A, b"")
        out += rec(0x0809, struct.pack("<HHHH", 0x0600, 0x0010, 0, 0))
        for (r, c, kind, val) in rows:
            if kind == "num":
                out += rec(0x0203, struct.pack("<HHH", r, c, 0)
                           + struct.pack("<d", val))
            elif kind == "rk_int":
                out += rec(0x027E, struct.pack("<HHH", r, c, 0)
                           + struct.pack("<I", (val << 2) | 2))
            elif kind == "rk_cents":
                out += rec(0x027E, struct.pack("<HHH", r, c, 0)
                           + struct.pack("<I", (val << 2) | 3))
            elif kind == "sst":
                out += rec(0x00FD, struct.pack("<HHH", r, c, 0)
                           + struct.pack("<I", val))
            elif kind == "label":
                enc = val.encode("utf-16-le")
                out += rec(0x0204, struct.pack("<HHH", r, c, 0)
                           + struct.pack("<HB", len(val), 0x01) + enc)
        out += rec(0x000A, b"")
        return out

    @staticmethod
    def _ole2(stream):
        """Minimal OLE2 container: 1 FAT sector, 1 directory sector, the
        Workbook stream padded past the 4096-byte mini cutoff (regular
        FAT chain)."""
        import struct

        END, FREE, FATS = 0xFFFFFFFE, 0xFFFFFFFF, 0xFFFFFFFD
        stream = stream + b"\x00" * (max(0, 4096 - len(stream)))
        n_stream_sects = (len(stream) + 511) // 512
        stream = stream + b"\x00" * (n_stream_sects * 512 - len(stream))

        header = bytearray(512)
        header[0:8] = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
        struct.pack_into("<H", header, 24, 0x3E)   # minor
        struct.pack_into("<H", header, 26, 3)      # major
        struct.pack_into("<H", header, 28, 0xFFFE)  # byte order
        struct.pack_into("<H", header, 30, 9)      # sector shift
        struct.pack_into("<H", header, 32, 6)      # mini shift
        struct.pack_into("<I", header, 44, 1)      # one FAT sector
        struct.pack_into("<I", header, 48, 1)      # dir start = sector 1
        struct.pack_into("<I", header, 56, 4096)   # mini cutoff
        struct.pack_into("<I", header, 60, END)    # no miniFAT
        struct.pack_into("<I", header, 68, END)    # no DIFAT chain
        struct.pack_into("<I", header, 76, 0)      # DIFAT[0] = sector 0
        for i in range(1, 109):
            struct.pack_into("<I", header, 76 + 4 * i, FREE)

        fat = [FATS, END]  # sector 0 = FAT itself, sector 1 = directory
        for i in range(n_stream_sects):
            fat.append(2 + i + 1 if i + 1 < n_stream_sects else END)
        fat += [FREE] * (128 - len(fat))
        fat_sect = struct.pack("<128I", *fat)

        def direntry(name, etype, start, size):
            e = bytearray(128)
            enc = name.encode("utf-16-le") + b"\x00\x00"
            e[0:len(enc)] = enc
            struct.pack_into("<H", e, 64, len(enc))
            e[66] = etype
            e[67] = 1  # black
            struct.pack_into("<3i", e, 68, -1, -1, -1)  # siblings/child
            struct.pack_into("<I", e, 116, start)
            struct.pack_into("<I", e, 120, size)
            return bytes(e)

        root = bytearray(direntry("Root Entry", 5, END, 0))
        struct.pack_into("<i", root, 76, 1)  # child = Workbook
        directory = (bytes(root)
                     + direntry("Workbook", 2, 2, len(stream))
                     + b"\x00" * 256)
        return bytes(header) + fat_sect + directory + stream

    def _mk_xls(self):
        rows = [
            (0, 0, "sst", 0), (0, 1, "sst", 1), (0, 2, "sst", 2),
            (1, 0, "num", 1.5), (1, 1, "rk_int", 7), (1, 2, "sst", 3),
            (2, 0, "num", -2.25), (2, 1, "rk_cents", 1995),
            (2, 2, "label", "green"),
        ]
        sst = ["x", "n", "color", "red"]
        return self._ole2(self._biff_stream(rows, sst))

    def test_parse_cells_and_header(self):
        from h2o3_tpu.frame.xls import parse_xls

        fr = parse_xls(self._mk_xls())
        assert fr.names == ["x", "n", "color"]
        assert fr.nrows == 2
        np.testing.assert_allclose(fr.col("x").numeric_view(), [1.5, -2.25])
        np.testing.assert_allclose(fr.col("n").numeric_view(), [7.0, 19.95])
        col = fr.col("color")
        vals = [col.domain[c] if col.domain else col.data[i]
                for i, c in enumerate(col.data)]
        assert vals == ["red", "green"]

    def test_ingest_dispatch_by_magic(self, tmp_path):
        from h2o3_tpu.frame.ingest import import_parse

        p = tmp_path / "legacy.xls"
        p.write_bytes(self._mk_xls())
        fr = import_parse(str(p))
        assert fr.names == ["x", "n", "color"]
        assert fr.nrows == 2

    def test_sst_continue_split(self):
        """A shared string split across SST/CONTINUE resumes with a fresh
        flags byte — the format's nastiest corner."""
        import struct

        from h2o3_tpu.frame.xls import parse_xls

        def rec(rid, payload):
            return struct.pack("<HH", rid, len(payload)) + payload

        long_s = "abcdefghij"
        # SST record carries the header + first 4 chars (compressed),
        # CONTINUE carries flags byte + the rest
        sst_head = struct.pack("<II", 1, 1) + struct.pack(
            "<HB", len(long_s), 0x00) + long_s[:4].encode("latin-1")
        cont = bytes([0x00]) + long_s[4:].encode("latin-1")
        stream = rec(0x0809, struct.pack("<HHHH", 0x0600, 0x0005, 0, 0))
        stream += rec(0x00FC, sst_head) + rec(0x003C, cont)
        stream += rec(0x000A, b"")
        stream += rec(0x0809, struct.pack("<HHHH", 0x0600, 0x0010, 0, 0))
        stream += rec(0x00FD, struct.pack("<HHH", 0, 0, 0)
                      + struct.pack("<I", 0))
        stream += rec(0x0203, struct.pack("<HHH", 1, 0, 0)
                      + struct.pack("<d", 9.0))
        stream += rec(0x000A, b"")
        fr = parse_xls(self._ole2(stream))
        assert fr.names == [long_s]
        np.testing.assert_allclose(fr.col(0).numeric_view(), [9.0])

    def test_garbage_refused(self):
        import pytest as _pytest

        from h2o3_tpu.frame.xls import parse_xls

        with _pytest.raises(ValueError, match="OLE2"):
            parse_xls(b"not an xls at all")


class TestLegacyXlsMiniStream(TestLegacyXls):
    """Small workbooks below the 4096-byte cutoff live in the root's
    mini stream chained by the miniFAT — the reader's other path."""

    @staticmethod
    def _ole2(stream):
        import struct

        END, FREE, FATS = 0xFFFFFFFE, 0xFFFFFFFF, 0xFFFFFFFD
        assert len(stream) < 4096, "mini-stream fixture must be small"
        n_mini = (len(stream) + 63) // 64
        mini = stream + b"\x00" * (n_mini * 64 - len(stream))
        # mini stream itself is a regular stream owned by the root;
        # pad it to whole 512-byte sectors
        n_mini_sects = (len(mini) + 511) // 512
        mini += b"\x00" * (n_mini_sects * 512 - len(mini))

        # sectors: 0=FAT, 1=directory, 2=miniFAT, 3..=mini stream
        header = bytearray(512)
        header[0:8] = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
        struct.pack_into("<H", header, 24, 0x3E)
        struct.pack_into("<H", header, 26, 3)
        struct.pack_into("<H", header, 28, 0xFFFE)
        struct.pack_into("<H", header, 30, 9)
        struct.pack_into("<H", header, 32, 6)
        struct.pack_into("<I", header, 44, 1)
        struct.pack_into("<I", header, 48, 1)      # dir at sector 1
        struct.pack_into("<I", header, 56, 4096)
        struct.pack_into("<I", header, 60, 2)      # miniFAT at sector 2
        struct.pack_into("<I", header, 64, 1)      # one miniFAT sector
        struct.pack_into("<I", header, 68, END)
        struct.pack_into("<I", header, 76, 0)
        for i in range(1, 109):
            struct.pack_into("<I", header, 76 + 4 * i, FREE)

        fat = [FATS, END, END]
        for i in range(n_mini_sects):
            fat.append(3 + i + 1 if i + 1 < n_mini_sects else END)
        fat += [FREE] * (128 - len(fat))
        fat_sect = struct.pack("<128I", *fat)

        minifat = []
        for i in range(n_mini):
            minifat.append(i + 1 if i + 1 < n_mini else END)
        minifat += [FREE] * (128 - len(minifat))
        minifat_sect = struct.pack("<128I", *minifat)

        def direntry(name, etype, start, size):
            e = bytearray(128)
            enc = name.encode("utf-16-le") + b"\x00\x00"
            e[0:len(enc)] = enc
            struct.pack_into("<H", e, 64, len(enc))
            e[66] = etype
            e[67] = 1
            struct.pack_into("<3i", e, 68, -1, -1, -1)
            struct.pack_into("<I", e, 116, start)
            struct.pack_into("<I", e, 120, size)
            return bytes(e)

        root = bytearray(direntry("Root Entry", 5, 3, len(mini)))
        struct.pack_into("<i", root, 76, 1)
        directory = (bytes(root)
                     + direntry("Workbook", 2, 0, len(stream))
                     + b"\x00" * 256)
        return (bytes(header) + fat_sect + directory + minifat_sect
                + mini)

    # inherited tests re-run against the mini-stream container, except
    # the CONTINUE fixture whose stream the parent builds directly
    def test_sst_continue_split(self):
        pass


class TestHiveImport:
    """Hive table import (ImportHiveTableHandler / h2o-ext-hive): rides a
    HiveServer2 DB-API connection. The image has no pyhive, so a stub
    module backed by sqlite pins the flow; without the stub the error is
    actionable."""

    def _stub_pyhive(self, monkeypatch, tmp_path):
        import sqlite3
        import sys
        import types

        db = tmp_path / "warehouse.db"
        conn0 = sqlite3.connect(db)
        conn0.execute("ATTACH DATABASE ? AS dflt", (str(db),))
        conn0.executescript(
            "CREATE TABLE IF NOT EXISTS events"
            "(id INTEGER, v REAL, dt TEXT);"
            "INSERT INTO events VALUES (1, 1.5, '2026-01-01'),"
            "(2, 2.5, '2026-01-01'), (3, -0.5, '2026-01-02');")
        conn0.commit()
        conn0.close()
        seen = {}

        class _Cursor:
            def __init__(self, cur, database):
                self._cur, self._db = cur, database

            def execute(self, q, *a):
                # hive queries say db.table; sqlite sees the bare table
                return self._cur.execute(q.replace(f"{self._db}.", ""), *a)

            def __getattr__(self, name):
                return getattr(self._cur, name)

        class _Conn:
            def __init__(self, path, database):
                self._c = sqlite3.connect(path)
                self._db = database

            def cursor(self):
                return _Cursor(self._c.cursor(), self._db)

            def close(self):
                self._c.close()

        class _Hive(types.ModuleType):
            @staticmethod
            def connect(host, port, username=None, database="default"):
                seen.update(host=host, port=port, database=database)
                return _Conn(db, database)

        pyhive = types.ModuleType("pyhive")
        hive = _Hive("pyhive.hive")
        pyhive.hive = hive
        monkeypatch.setitem(sys.modules, "pyhive", pyhive)
        monkeypatch.setitem(sys.modules, "pyhive.hive", hive)
        return seen

    def test_import_with_partition_filter(self, monkeypatch, tmp_path):
        from h2o3_tpu.frame.ingest import import_hive_table

        seen = self._stub_pyhive(monkeypatch, tmp_path)
        fr = import_hive_table(database="default", table="events")
        assert fr.nrows == 3 and fr.names == ["id", "v", "dt"]
        assert seen["database"] == "default" and seen["port"] == 10000
        part = import_hive_table(
            database="default", table="events",
            partitions=[["dt=2026-01-01"]])
        assert part.nrows == 2
        np.testing.assert_allclose(part.col("v").numeric_view(), [1.5, 2.5])

    def test_validation_and_missing_driver(self):
        import pytest as _pytest

        from h2o3_tpu.frame.ingest import import_hive_table

        with _pytest.raises(ValueError, match="table is required"):
            import_hive_table(database="default")
        with _pytest.raises(ValueError, match="invalid table name"):
            import_hive_table(table="x; DROP TABLE y")
        with _pytest.raises(ValueError, match="pyhive"):
            import_hive_table(table="events")

    def test_rest_route(self, monkeypatch, tmp_path):
        import json as _json
        import urllib.request

        from h2o3_tpu.api import start_server
        from h2o3_tpu.keyed import DKV

        self._stub_pyhive(monkeypatch, tmp_path)
        s = start_server(port=0)
        try:
            req = urllib.request.Request(
                s.url + "/3/ImportHiveTable",
                data=_json.dumps({"database": "default",
                                  "table": "events"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                out = _json.loads(resp.read())
            assert out["num_rows"] == 3
            DKV.remove(out["key"]["name"])
        finally:
            s.stop()
