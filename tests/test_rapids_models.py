"""Model-valued rapids prims (water/rapids/ast/prims/models/):
perfectAUC, model.reset.threshold, PermutationVarImp,
segment_models_as_frame.  Oracles: sklearn's exact AUC, direct metric
deltas, and the segment builder's own frame."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids import Session, exec_rapids

pytestmark = pytest.mark.leaks_keys


def _train_glm(n=400, seed=1):
    from h2o3_tpu.models.glm import GLM

    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 4))
    w = np.array([2.0, -1.0, 0.5, 0.0])
    yv = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.int32)
    fr = Frame(
        [Column(f"x{j}", X[:, j], ColType.NUM) for j in range(4)]
        + [Column("y", yv, ColType.CAT, ["0", "1"])]
    )
    model = GLM(family="binomial", response_column="y").train(fr)
    return model, fr, X, yv


class TestPerfectAUC:
    def test_matches_sklearn_exact_auc(self):
        from sklearn.metrics import roc_auc_score

        rng = np.random.default_rng(0)
        probs = np.round(rng.random(500), 2)  # coarse grid forces ties
        acts = (rng.random(500) < probs).astype(np.float64)
        s = Session()
        s.assign("p", Frame([Column("p", probs, ColType.NUM)]))
        s.assign("a", Frame([Column("a", acts, ColType.NUM)]))
        out = exec_rapids("(perfectAUC p a)", s).as_frame()
        got = float(out.col(0).numeric_view()[0])
        want = roc_auc_score(acts, probs)
        assert got == pytest.approx(want, abs=1e-12)

    def test_validations(self):
        s = Session()
        s.assign("p", Frame([Column("p", np.array([0.1, 1.5]), ColType.NUM)]))
        s.assign("a", Frame([Column("a", np.array([0.0, 1.0]), ColType.NUM)]))
        with pytest.raises(ValueError, match="between 0 and 1"):
            exec_rapids("(perfectAUC p a)", s)
        s.assign("p2", Frame([Column("p", np.array([0.1, 0.5]), ColType.NUM)]))
        s.assign("a2", Frame([Column("a", np.array([0.0, 2.0]), ColType.NUM)]))
        with pytest.raises(ValueError, match="0 or 1"):
            exec_rapids("(perfectAUC p2 a2)", s)


class TestResetThreshold:
    def test_roundtrip_and_predict_effect(self):
        model, fr, X, yv = _train_glm()
        s = Session()
        old = model.default_threshold()
        out = exec_rapids(
            f"(model.reset.threshold {model.key} 0.75)", s).as_frame()
        assert float(out.col(0).numeric_view()[0]) == pytest.approx(old)
        assert model.default_threshold() == 0.75
        # labels actually move with the threshold
        pred = model.predict(fr)
        p1 = pred.col("p1").numeric_view()
        labels = pred.col("predict").data
        np.testing.assert_array_equal(labels, (p1 >= 0.75).astype(np.int32))
        # second reset returns the first override
        out2 = exec_rapids(
            f"(model.reset.threshold {model.key} 0.25)", s).as_frame()
        assert float(out2.col(0).numeric_view()[0]) == pytest.approx(0.75)


class TestPermutationVarImp:
    def test_informative_features_rank_top(self):
        model, fr, X, yv = _train_glm()
        s = Session()
        s.assign("fr", fr)
        out = exec_rapids(
            f'(PermutationVarImp {model.key} fr "auc" -1 1 [] 42)',
            s).as_frame()
        assert out.names == ["Variable", "Relative Importance",
                             "Scaled Importance", "Percentage"]
        vars_ = list(out.col("Variable").data)
        # response is excluded; strongest coefficient shuffles worst
        assert "y" not in vars_
        assert set(vars_) == {"x0", "x1", "x2", "x3"}
        assert vars_[0] == "x0"  # |w|=2 dominates
        rel = out.col("Relative Importance").numeric_view()
        scaled = out.col("Scaled Importance").numeric_view()
        pct = out.col("Percentage").numeric_view()
        assert np.all(np.diff(rel) <= 0)  # sorted descending
        assert scaled[0] == pytest.approx(1.0)
        assert pct.sum() == pytest.approx(1.0)

    def test_repeats_and_features_subset(self):
        model, fr, X, yv = _train_glm()
        s = Session()
        s.assign("fr", fr)
        out = exec_rapids(
            f'(PermutationVarImp {model.key} fr "auto" -1 3 ["x0" "x1"] 7)',
            s).as_frame()
        assert out.names == ["Variable", "Run 1", "Run 2", "Run 3"]
        assert set(out.col("Variable").data) == {"x0", "x1"}
        assert out.nrows == 2

    def test_validations(self):
        model, fr, X, yv = _train_glm()
        s = Session()
        s.assign("fr", fr)
        with pytest.raises(ValueError, match="n_samples"):
            exec_rapids(
                f'(PermutationVarImp {model.key} fr "auc" 1 1 [] 42)', s)
        with pytest.raises(ValueError, match="not present"):
            exec_rapids(
                f'(PermutationVarImp {model.key} fr "auc" -1 1 ["zz"] 42)',
                s)


class TestSegmentModelsAsFrame:
    def test_frame_matches_builder(self):
        from h2o3_tpu.models.glm import GLM, GLMParameters
        from h2o3_tpu.models.segments import SegmentModelsBuilder

        rng = np.random.default_rng(3)
        n = 120
        g = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
        x = rng.normal(size=n)
        yv = 2.0 * x + rng.normal(scale=0.1, size=n)
        dom = ["a", "b", "c"]
        fr = Frame([
            Column("g", np.array([dom.index(v) for v in g], np.int32),
                   ColType.CAT, dom),
            Column("x", x, ColType.NUM),
            Column("y", yv, ColType.NUM),
        ])
        sm = SegmentModelsBuilder(
            GLM,
            GLMParameters(response_column="y", family="gaussian", lambda_=0.0),
            segment_columns=["g"]).train(fr)
        s = Session()
        out = exec_rapids(f"(segment_models_as_frame {sm.key})", s).as_frame()
        want = sm.as_frame()
        assert out.names == want.names
        assert out.nrows == 3
        st = out.col("status")
        assert all(st.domain[c] == "succeeded" for c in st.data)
