"""map_reduce / FrameTable / quantile tests — the M1 compute primitive.

Reference analogue: water/MRTaskTest.java, hex/quantile tests (SURVEY.md §4)."""

import numpy as np
import jax.numpy as jnp
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.compute import FrameTable, map_reduce, quantiles
from h2o3_tpu.compute.mapreduce import gather_rows, map_batches


@pytest.fixture()
def table(mesh, rng):
    n = 10_001  # deliberately not divisible by 8 → exercises pad masking
    fr = Frame.from_dict({"x": rng.normal(size=n), "y": rng.normal(2.0, size=n)})
    return FrameTable.from_frame(fr, mesh=mesh), fr


def test_sum_and_count(table):
    t, fr = table

    def stats(cols, mask):
        m = mask & ~jnp.isnan(cols["x"])
        return {
            "n": jnp.sum(m),
            "sum": jnp.sum(jnp.where(m, cols["x"], 0.0)),
            "sumsq": jnp.sum(jnp.where(m, cols["x"] ** 2, 0.0)),
        }

    out = map_reduce(stats, t)
    x = fr.col("x").data
    assert int(out["n"]) == len(x)
    assert float(out["sum"]) == pytest.approx(x.sum(), rel=1e-4)
    assert float(out["sumsq"]) == pytest.approx((x**2).sum(), rel=1e-4)


def test_minmax_reduce(table):
    t, fr = table

    def lo(cols, mask):
        return jnp.min(jnp.where(mask, cols["x"], jnp.inf))

    def hi(cols, mask):
        return jnp.max(jnp.where(mask, cols["x"], -jnp.inf))

    assert float(map_reduce(lo, t, reduce="min")) == pytest.approx(fr.col("x").data.min(), rel=1e-5)
    assert float(map_reduce(hi, t, reduce="max")) == pytest.approx(fr.col("x").data.max(), rel=1e-5)


def test_map_batches_elementwise(table):
    t, fr = table

    def double_plus(cols, mask):
        return cols["x"] * 2.0 + cols["y"]

    out = map_batches(double_plus, t)
    got = gather_rows(out, t.n_valid)
    want = fr.col("x").data * 2 + fr.col("y").data
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_matrix_shape(table):
    t, fr = table
    m = t.matrix(["x", "y"])
    assert m.shape == (t.n_padded, 2)
    assert t.n_padded % 8 == 0 and t.n_valid == fr.nrows


def test_quantiles_match_numpy(rng):
    x = rng.normal(size=50_000).astype(np.float32)
    probs = [0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99]
    got = quantiles(x, probs)
    want = np.quantile(x.astype(np.float64), probs)
    np.testing.assert_allclose(got, want, atol=2e-3)


def test_quantiles_with_nans(rng):
    x = rng.normal(size=10_000).astype(np.float32)
    x[::7] = np.nan
    got = quantiles(x, [0.5])
    want = np.nanquantile(x.astype(np.float64), 0.5)
    assert got[0] == pytest.approx(want, abs=5e-3)


def test_quantiles_outlier_dominated_range(rng):
    """Regression: zoom must converge past a 1e30 outlier (review finding)."""
    x = np.concatenate([np.arange(1000, dtype=np.float32), [np.float32(1e30)]])
    got = quantiles(x, [0.5])
    assert got[0] == pytest.approx(500.0, abs=1e-3)
