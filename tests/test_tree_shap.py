"""TreeSHAP prediction contributions.

Reference: h2o-genmodel/.../algos/tree/TreeSHAP.java (+Predictor) — exact
Shapley values with the path-dependent (cover-weighted) conditional
expectation. Oracles: local accuracy (contributions + bias == margin,
exactly) and brute-force subset-enumeration Shapley on small feature sets.
"""

import itertools
import json
import urllib.request

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models.tree import GBM
from h2o3_tpu.models.tree.shap import node_covers, predict_contributions


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _expvalue(feat, sb, dl, sp, leaf, covers, x_bins, n_bins1, S):
    """Brute-force EXPVALUE(x, S): follow x for features in S, else
    cover-weighted average over children (the path-dependent semantics)."""

    def go(node):
        if not sp[node]:
            return float(leaf[node])
        f = int(feat[node])
        l, r = 2 * node + 1, 2 * node + 2
        if f in S:
            b = int(x_bins[f])
            go_left = dl[node] if b >= n_bins1 - 1 else b <= int(sb[node])
            return go(l if go_left else r)
        cov = covers[node] or 1.0
        return (covers[l] * go(l) + covers[r] * go(r)) / cov

    return go(0)


def _brute_shapley(feat, sb, dl, sp, leaf, covers, x_bins, n_bins1, F):
    import math

    phi = np.zeros(F)
    feats = list(range(F))
    for j in feats:
        others = [f for f in feats if f != j]
        for k in range(len(others) + 1):
            for S in itertools.combinations(others, k):
                w = (
                    math.factorial(len(S))
                    * math.factorial(F - len(S) - 1)
                    / math.factorial(F)
                )
                v1 = _expvalue(feat, sb, dl, sp, leaf, covers, x_bins,
                               n_bins1, set(S) | {j})
                v0 = _expvalue(feat, sb, dl, sp, leaf, covers, x_bins,
                               n_bins1, set(S))
                phi[j] += w * (v1 - v0)
    return phi


@pytest.fixture()
def reg_model(rng):
    n = 800
    X = rng.normal(size=(n, 3))
    y = 2 * X[:, 0] - X[:, 1] + 0.5 * X[:, 0] * X[:, 2] + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = GBM(response_column="y", ntrees=8, max_depth=3, seed=3,
            min_rows=5.0).train(fr)
    return m, fr


class TestTreeShap:
    def test_local_accuracy_regression(self, reg_model):
        """Σ contributions + bias == raw margin, exactly (TreeSHAP's
        defining property)."""
        m, fr = reg_model
        contribs = predict_contributions(m, fr)
        margin = m.booster.predict_margin(
            np.asarray(
                np.stack([fr.col(f"x{i}").data for i in range(3)], axis=1),
                dtype=np.float32,
            )
        )[:, 0]
        np.testing.assert_allclose(contribs.sum(axis=1), margin,
                                   rtol=1e-5, atol=1e-5)

    def test_matches_brute_force_shapley(self, reg_model):
        """Exact parity with subset-enumeration Shapley values per tree."""
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.ops.histogram import apply_bins

        m, fr = reg_model
        trees = m.booster.trees_per_class[0]
        X = tree_matrix(m.data_info, fr)
        bins = apply_bins(X, trees.edges)
        contribs = predict_contributions(m, fr)

        # check a handful of rows against the brute-force oracle, summed
        # over all trees
        for i in (0, 7, 123):
            want = np.zeros(3)
            for t in range(trees.ntrees):
                covers = node_covers(
                    trees.feat[t], trees.split_bin[t], trees.default_left[t],
                    trees.is_split[t], bins, trees.n_bins1, trees.max_depth,
                )
                want += _brute_shapley(
                    trees.feat[t], trees.split_bin[t], trees.default_left[t],
                    trees.is_split[t], trees.leaf[t].astype(np.float64),
                    covers, bins[i], trees.n_bins1, 3,
                )
            np.testing.assert_allclose(contribs[i, :3], want, rtol=1e-6,
                                       atol=1e-8)

    def test_binomial_and_background_frame(self, rng):
        n = 600
        X = rng.normal(size=(n, 2))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
        fr = Frame.from_dict({
            "x0": X[:, 0], "x1": X[:, 1],
            "y": np.where(y > 0, "yes", "no"),
        })
        m = GBM(response_column="y", ntrees=5, max_depth=3, seed=1,
                min_rows=5.0).train(fr)
        contribs = predict_contributions(m, fr, background_frame=fr[["x0", "x1"]])
        # local accuracy on the logit margin
        from h2o3_tpu.models.tree.common import tree_matrix

        margin = m.booster.predict_margin(tree_matrix(m.data_info, fr))[:, 0]
        np.testing.assert_allclose(contribs.sum(axis=1), margin,
                                   rtol=1e-5, atol=1e-5)
        # the signal feature dominates the contributions
        assert np.abs(contribs[:, 0]).mean() > np.abs(contribs[:, 1]).mean()

    def test_multinomial_rejected(self, rng):
        n = 300
        fr = Frame.from_dict({
            "x": rng.normal(size=n),
            "y": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
        })
        m = GBM(response_column="y", ntrees=2, max_depth=2, seed=1).train(fr)
        with pytest.raises(ValueError, match="regression/binomial"):
            predict_contributions(m, fr)

    def test_over_rest(self, reg_model):
        from h2o3_tpu.api import start_server
        from h2o3_tpu.keyed import DKV

        m, fr = reg_model
        fr.key = "shap_fr"
        DKV.put(fr.key, fr)
        s = start_server(port=0)
        try:
            req = urllib.request.Request(
                s.url + f"/3/PredictContributions/models/{m.key}/frames/shap_fr",
                data=b"{}", headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                out = json.loads(resp.read())
            assert out["columns"][-1] == "BiasTerm"
            contribs = DKV.get(out["predictions_frame"]["name"])
            assert contribs.nrows == fr.nrows
        finally:
            s.stop()
            DKV.remove("shap_fr")
