"""Breadth round 4: Word2Vec, RuleFit, PSVM (SURVEY.md §2.2)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.frame.frame import ColType, Column


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _word_corpus(rng, n_sent=300):
    """Two topic clusters: fruit words co-occur; tool words co-occur."""
    fruit = ["apple", "banana", "cherry", "grape", "melon"]
    tools = ["hammer", "wrench", "drill", "saw", "pliers"]
    words = []
    for _ in range(n_sent):
        topic = fruit if rng.random() < 0.5 else tools
        for _ in range(rng.integers(4, 9)):
            words.append(topic[rng.integers(0, len(topic))])
        words.append(None)  # sentence separator
    return Frame([Column("words", np.array(words, dtype=object), ColType.STR)])


class TestWord2Vec:
    def test_topic_words_cluster(self, rng):
        from h2o3_tpu.models.word2vec import Word2Vec

        fr = _word_corpus(rng)
        m = Word2Vec(vec_size=16, window_size=3, epochs=20, min_word_freq=2,
                     negative_samples=4, sent_sample_rate=0.0, batch_size=256,
                     init_learning_rate=0.5, seed=7).train(fr)
        assert m.vectors.shape[0] == 10
        syn = m.find_synonyms("apple", count=4)
        fruit = {"banana", "cherry", "grape", "melon"}
        # at least 3 of the 4 nearest neighbours are fruit
        assert len(fruit & set(syn)) >= 3

    def test_transform_average(self, rng):
        from h2o3_tpu.models.word2vec import Word2Vec

        fr = _word_corpus(rng, n_sent=100)
        m = Word2Vec(vec_size=8, epochs=3, min_word_freq=1, seed=1).train(fr)
        out = m.transform(fr, aggregate_method="average")
        assert out.ncols == 8
        assert out.nrows == 100  # one vector per sentence
        assert np.isfinite(out.to_numpy()).all()

    def test_unknown_word(self, rng):
        from h2o3_tpu.models.word2vec import Word2Vec

        fr = _word_corpus(rng, n_sent=50)
        m = Word2Vec(vec_size=8, epochs=2, min_word_freq=1, seed=1).train(fr)
        assert m.word_vector("zebra") is None
        assert m.find_synonyms("zebra") == {}


class TestRuleFit:
    def test_finds_threshold_rule(self, rng):
        from h2o3_tpu.models.rulefit import RuleFit

        n = 1000
        x1 = rng.uniform(0, 10, n)
        x2 = rng.normal(size=n)
        y = ((x1 > 5) & (x2 > 0)).astype(np.int32)
        # flip a little noise
        flip = rng.random(n) < 0.02
        y = np.where(flip, 1 - y, y)
        fr = Frame([
            Column("x1", x1, ColType.NUM),
            Column("x2", x2, ColType.NUM),
            Column("y", y, ColType.CAT, ["0", "1"]),
        ])
        m = RuleFit(response_column="y", min_rule_length=2, max_rule_length=3,
                    rule_generation_ntrees=20, model_type="rules", seed=5).train(fr)
        assert m.training_metrics.auc > 0.95
        assert len(m.rule_importance) > 0
        top = m.rule_importance[0]
        assert "rule" in top and top["coefficient"] != 0.0

    def test_linear_only(self, rng):
        from h2o3_tpu.models.rulefit import RuleFit

        n = 400
        x = rng.normal(size=n)
        y = 2.0 * x + rng.normal(size=n) * 0.1
        fr = Frame.from_dict({"x": x, "y": y})
        m = RuleFit(response_column="y", model_type="linear", seed=1).train(fr)
        assert m.training_metrics.r2 > 0.95
        assert all(v["variable"].startswith("linear_") for v in m.rule_importance)

    def test_predict_shape(self, rng):
        from h2o3_tpu.models.rulefit import RuleFit

        n = 300
        fr = Frame.from_dict({
            "a": rng.normal(size=n), "b": rng.normal(size=n),
            "y": rng.normal(size=n),
        })
        m = RuleFit(response_column="y", rule_generation_ntrees=10,
                    min_rule_length=2, max_rule_length=2, seed=1).train(fr)
        assert m.predict(fr).nrows == n


class TestPSVM:
    def test_matches_sklearn_svc_predictions(self, rng):
        from sklearn.svm import SVC

        from h2o3_tpu.models.psvm import PSVM

        n = 400
        X = rng.normal(size=(n, 2))
        y = ((X[:, 0] ** 2 + X[:, 1] ** 2) > 1.2).astype(np.int32)  # ring: needs RBF
        fr = Frame([
            Column("x0", X[:, 0], ColType.NUM),
            Column("x1", X[:, 1], ColType.NUM),
            Column("y", y, ColType.CAT, ["0", "1"]),
        ])
        m = PSVM(response_column="y", hyper_param=1.0, gamma=0.5,
                 rank_ratio=0.5, max_iterations=2000, seed=1).train(fr)

        # sklearn oracle on the standardized features PSVM actually used
        Xs = (X - X.mean(0)) / X.std(0, ddof=1)
        skl = SVC(C=1.0, gamma=0.5).fit(Xs, y)
        ours = (m.decision_function(fr) > 0).astype(np.int32)
        agree = (ours == skl.predict(Xs)).mean()
        assert agree > 0.95
        assert m.svs_count > 0
        assert m.training_metrics.auc > 0.95

    def test_requires_binary(self, rng):
        from h2o3_tpu.models.psvm import PSVM

        n = 60
        fr = Frame([
            Column("x", rng.normal(size=n), ColType.NUM),
            Column("y", rng.integers(0, 3, n).astype(np.int32), ColType.CAT,
                   ["a", "b", "c"]),
        ])
        with pytest.raises(ValueError, match="binary"):
            PSVM(response_column="y").train(fr)
