"""Rapids DSL tests — parser, operators, reducers, mungers, groupby, merge,
strings, time, advmath.  Oracle: hand-computed numpy results (the reference's
pyunit_munging tests are the model; SURVEY.md §4 tier 2)."""

import numpy as np
import pytest

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids import Session, Val, exec_rapids
from h2o3_tpu.rapids.parser import parse, AstExec, AstNum, AstNumList, AstStr, AstFun


# legacy module predating the CheckKeysTask fixture: rapids
# assignments leave frames in the DKV by design; the module-level
# sweeper removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture
def sess():
    s = Session()
    fr = Frame.from_dict(
        {
            "a": [1.0, 2.0, 3.0, 4.0, np.nan],
            "b": [10.0, 20.0, 30.0, 40.0, 50.0],
            "g": ["x", "y", "x", "y", "x"],
        }
    )
    s.assign("fr", fr)
    return s


def ex(s, expr):
    return exec_rapids(expr, s)


# -- parser ------------------------------------------------------------------
def test_parse_basic():
    ast = parse('(+ 1 2)')
    assert isinstance(ast, AstExec) and len(ast.args) == 2

def test_parse_numlist_ranges():
    ast = parse("[0:3 10]")
    assert isinstance(ast, AstNumList)
    np.testing.assert_array_equal(ast.values, [0, 1, 2, 10])

def test_parse_string_and_lambda():
    ast = parse('{x . (+ x 1)}')
    assert isinstance(ast, AstFun) and ast.params == ["x"]


# -- operators ---------------------------------------------------------------
def test_arith_frame_scalar(sess):
    out = ex(sess, "(+ (cols fr [1]) 5)").as_frame()
    np.testing.assert_allclose(out.col(0).data, [15, 25, 35, 45, 55])

def test_arith_frame_frame(sess):
    out = ex(sess, "(* (cols fr [0]) (cols fr [1]))").as_frame()
    np.testing.assert_allclose(out.col(0).data[:4], [10, 40, 90, 160])
    assert np.isnan(out.col(0).data[4])

def test_cmp_string_eq(sess):
    out = ex(sess, '(== (cols fr [2]) "x")').as_frame()
    np.testing.assert_allclose(out.col(0).data, [1, 0, 1, 0, 1])

def test_cmp_string_eq_na_cells(sess):
    # STR column with missing cells: NA compares unequal (0.0, not NaN)
    # through the vectorized object-dtype path
    fr = Frame([Column("s", np.array(["x", None, "y", None, "x"], dtype=object),
                       ColType.STR)])
    sess.assign("strs", fr)
    out = ex(sess, '(== (cols strs [0]) "x")').as_frame()
    np.testing.assert_array_equal(out.col(0).data, [1.0, 0.0, 0.0, 0.0, 1.0])
    out = ex(sess, '(!= (cols strs [0]) "x")').as_frame()
    np.testing.assert_array_equal(out.col(0).data, [0.0, 1.0, 1.0, 1.0, 0.0])

def test_ifelse(sess):
    out = ex(sess, "(ifelse (> (cols fr [1]) 25) 1 0)").as_frame()
    np.testing.assert_allclose(out.col(0).data, [0, 0, 1, 1, 1])


# -- reducers ----------------------------------------------------------------
def test_mean_narm(sess):
    assert ex(sess, "(mean (cols fr [0]) 1 0)").as_num() == pytest.approx(2.5)

def test_max_poisoned_by_na(sess):
    assert np.isnan(ex(sess, "(max (cols fr [0]))").as_num())
    assert ex(sess, "(maxNA (cols fr [0]))").as_num() == 4.0

def test_sum_sd(sess):
    assert ex(sess, "(sum (cols fr [1]))").as_num() == 150.0
    assert ex(sess, "(sd (cols fr [1]))").as_num() == pytest.approx(np.std([10, 20, 30, 40, 50], ddof=1))

def test_cumsum(sess):
    out = ex(sess, "(cumsum (cols fr [1]) 0)").as_frame()
    np.testing.assert_allclose(out.col(0).data, [10, 30, 60, 100, 150])

def test_nacnt(sess):
    assert ex(sess, "(naCnt (cols fr [0]))").as_num() == 1.0


# -- mungers -----------------------------------------------------------------
def test_nrow_ncol_colnames(sess):
    assert ex(sess, "(nrow fr)").as_num() == 5
    assert ex(sess, "(ncol fr)").as_num() == 3
    assert ex(sess, "(colnames fr)").as_strs() == ["a", "b", "g"]

def test_rows_slice(sess):
    out = ex(sess, "(rows fr [0 2])").as_frame()
    np.testing.assert_allclose(out.col("a").data, [1, 3])

def test_rows_bool_mask(sess):
    out = ex(sess, "(rows fr (> (cols fr [1]) 25))").as_frame()
    assert out.nrows == 3

def test_cbind_rbind(sess):
    out = ex(sess, "(cbind (cols fr [0]) (cols fr [1]))").as_frame()
    assert out.ncols == 2
    out2 = ex(sess, "(rbind (cols fr [1]) (cols fr [1]))").as_frame()
    assert out2.nrows == 10

def test_asfactor_levels(sess):
    out = ex(sess, "(as.factor (cols fr [0]))").as_frame()
    assert out.col(0).type is ColType.CAT
    assert ex(sess, "(levels (as.factor (cols fr [0])))").as_strs() == ["1", "2", "3", "4"]

def test_isna_naomit(sess):
    out = ex(sess, "(is.na (cols fr [0]))").as_frame()
    np.testing.assert_allclose(out.col(0).data, [0, 0, 0, 0, 1])
    assert ex(sess, "(na.omit fr)").as_frame().nrows == 4

def test_tmp_assign_and_session_end(sess):
    ex(sess, "(tmp= t1 (+ (cols fr [1]) 1))")
    assert sess.lookup("t1") is not None
    sess.end()
    assert sess.lookup("t1") is None

def test_rectangle_assign(sess):
    out = ex(sess, "(:= fr (cols fr [1]) [0] [0:5])").as_frame()
    np.testing.assert_allclose(out.col("a").data, [10, 20, 30, 40, 50])

def test_append(sess):
    out = ex(sess, '(append fr (* (cols fr [1]) 2) "b2")').as_frame()
    assert "b2" in out.names
    np.testing.assert_allclose(out.col("b2").data, [20, 40, 60, 80, 100])

def test_scale(sess):
    out = ex(sess, "(scale (cols fr [1]) 1 1)").as_frame()
    d = out.col(0).data
    assert abs(np.nanmean(d)) < 1e-12 and np.nanstd(d, ddof=1) == pytest.approx(1.0)

def test_cut(sess):
    out = ex(sess, "(cut (cols fr [1]) [0 25 60] [] 0 1 3)").as_frame()
    c = out.col(0)
    assert c.type is ColType.CAT
    np.testing.assert_array_equal(c.data, [0, 0, 1, 1, 1])

def test_fillna(sess):
    out = ex(sess, '(h2o.fillna (cols fr [0]) "forward" 0 2)').as_frame()
    np.testing.assert_allclose(out.col(0).data, [1, 2, 3, 4, 4])


# -- group-by / ddply --------------------------------------------------------
def test_groupby(sess):
    out = ex(sess, '(GB fr [2] "sum" 1 "all" "nrow" 1 "all")').as_frame()
    assert out.nrows == 2
    g = out.col("g")
    sums = out.col("sum_b").data
    counts = out.col("nrow").data
    by_level = {g.domain[g.data[i]]: (sums[i], counts[i]) for i in range(2)}
    assert by_level["x"] == (90.0, 3.0)
    assert by_level["y"] == (60.0, 2.0)

def test_groupby_mean_narm(sess):
    out = ex(sess, '(GB fr [2] "mean" 0 "rm")').as_frame()
    g = out.col("g")
    means = {g.domain[g.data[i]]: out.col("mean_a").data[i] for i in range(2)}
    assert means["x"] == pytest.approx(2.0)  # (1+3)/2, NA removed
    assert means["y"] == pytest.approx(3.0)

def test_ddply(sess):
    out = ex(sess, "(ddply fr [2] {g . (sum (cols g [1]))})").as_frame()
    assert out.nrows == 2
    assert set(out.col(1).data) == {90.0, 60.0}


# -- merge / sort ------------------------------------------------------------
def test_sort(sess):
    out = ex(sess, "(sort fr [1] [0])").as_frame()  # descending b
    np.testing.assert_allclose(out.col("b").data, [50, 40, 30, 20, 10])

def test_merge(sess):
    right = Frame.from_dict({"g": ["x", "y", "z"], "v": [100.0, 200.0, 300.0]})
    sess.assign("rt", right)
    out = ex(sess, "(merge fr rt 0 0 [2] [0] 'auto')").as_frame()
    assert out.nrows == 5
    gi = out.col("g")
    vals = out.col("v").data
    for i in range(5):
        lvl = gi.domain[gi.data[i]]
        assert vals[i] == (100.0 if lvl == "x" else 200.0)

def test_merge_all_left(sess):
    right = Frame.from_dict({"g": ["x"], "v": [7.0]})
    sess.assign("rt2", right)
    out = ex(sess, "(merge fr rt2 1 0 [2] [0] 'auto')").as_frame()
    assert out.nrows == 5
    assert np.isnan(out.col("v").data).sum() == 2  # the two 'y' rows


# -- strings -----------------------------------------------------------------
def test_string_ops(sess):
    s = Session()
    fr = Frame.from_dict({"s": ["  Hello ", "World", None]})
    # keep as STR: from_dict makes CAT via column_from_strings? ensure STR col
    fr = Frame([Column("s", np.array(["  Hello ", "World", None], dtype=object), ColType.STR)])
    s.assign("sf", fr)
    out = ex(s, "(tolower (trim sf))").as_frame()
    assert list(out.col(0).data) == ["hello", "world", None]
    ln = ex(s, "(length (trim sf))").as_frame()
    np.testing.assert_allclose(ln.col(0).data[:2], [5, 5])
    assert np.isnan(ln.col(0).data[2])

def test_strsplit_substring():
    s = Session()
    fr = Frame([Column("s", np.array(["a_b", "c_d_e"], dtype=object), ColType.STR)])
    s.assign("sf", fr)
    out = ex(s, '(strsplit sf "_")').as_frame()
    assert out.ncols == 3
    assert out.col(0).data[0] == "a" and out.col(2).data[1] == "e"

def test_countmatches_grep():
    s = Session()
    fr = Frame([Column("s", np.array(["banana", "apple"], dtype=object), ColType.STR)])
    s.assign("sf", fr)
    out = ex(s, '(countmatches sf ["an"])').as_frame()
    np.testing.assert_allclose(out.col(0).data, [2, 0])
    g = ex(s, '(grep sf "app" 0 0 0)').as_frame()
    np.testing.assert_allclose(g.col(0).data, [1])

def test_str_distance():
    s = Session()
    f1 = Frame([Column("a", np.array(["kitten"], dtype=object), ColType.STR)])
    f2 = Frame([Column("b", np.array(["sitting"], dtype=object), ColType.STR)])
    s.assign("f1", f1)
    s.assign("f2", f2)
    out = ex(s, '(strDistance f1 f2 "lv" 1)').as_frame()
    assert out.col(0).data[0] == 3.0


# -- time --------------------------------------------------------------------
def test_time_fields():
    s = Session()
    # 2020-06-15 12:34:56 UTC
    ms = 1592224496000.0
    fr = Frame([Column("t", np.array([ms]), ColType.TIME)])
    s.assign("tf", fr)
    assert ex(s, "(year tf)").as_frame().col(0).data[0] == 2020
    assert ex(s, "(month tf)").as_frame().col(0).data[0] == 6
    assert ex(s, "(day tf)").as_frame().col(0).data[0] == 15
    assert ex(s, "(hour tf)").as_frame().col(0).data[0] == 12
    assert ex(s, "(minute tf)").as_frame().col(0).data[0] == 34
    assert ex(s, "(second tf)").as_frame().col(0).data[0] == 56
    assert ex(s, "(dayOfWeek tf)").as_frame().col(0).data[0] == 0  # Monday

def test_mktime_roundtrip():
    s = Session()
    v = exec_rapids("(mktime 2020 5 14 12 34 56 0)", s)  # month/day 0-based
    assert v.as_num() == 1592224496000.0


# -- advmath -----------------------------------------------------------------
def test_cor(sess):
    v = ex(sess, "(cor (cols fr [1]) (cols fr [1]) 'everything' 'Pearson')")
    assert v.as_num() == pytest.approx(1.0)

def test_hist(sess):
    out = ex(sess, "(hist (cols fr [1]) 5)").as_frame()
    assert "counts" in out.names
    assert np.nansum(out.col("counts").data) == 5

def test_table(sess):
    out = ex(sess, "(table (cols fr [2]) 1)").as_frame()
    cnt = {out.col(0).domain[out.col(0).data[i]]: out.col("Count").data[i] for i in range(out.nrows)}
    assert cnt == {"x": 3.0, "y": 2.0}

def test_unique(sess):
    out = ex(sess, "(unique (cols fr [2]) 0)").as_frame()
    assert out.nrows == 2

def test_quantile(sess):
    out = ex(sess, "(quantile (cols fr [1]) [0.5] 'interpolated' _)")
    q = out.as_frame()
    assert q.col(1).data[0] == pytest.approx(30.0)

def test_impute(sess):
    out = ex(sess, "(impute fr 0 'mean' 'interpolate' [] _ _)").as_frame()
    assert out.col("a").data[4] == pytest.approx(2.5)

def test_runif(sess):
    out = ex(sess, "(h2o.runif fr 42)").as_frame()
    assert out.nrows == 5
    assert ((out.col(0).data >= 0) & (out.col(0).data < 1)).all()

def test_kfold(sess):
    out = ex(sess, "(kfold_column fr 2 7)").as_frame()
    assert set(np.unique(out.col(0).data)) <= {0.0, 1.0}

def test_match(sess):
    out = ex(sess, '(match (cols fr [2]) ["y" "x"] nan 1)').as_frame()
    np.testing.assert_allclose(out.col(0).data, [2, 1, 2, 1, 2])

def test_which(sess):
    out = ex(sess, "(which (> (cols fr [1]) 25))").as_frame()
    np.testing.assert_allclose(out.col(0).data, [2, 3, 4])

def test_mmult(sess):
    s = Session()
    a = Frame.from_dict({"x": [1.0, 2.0], "y": [3.0, 4.0]})
    s.assign("A", a)
    out = ex(s, "(x (t A) A)").as_frame()
    m = out.to_numpy()
    np.testing.assert_allclose(m, np.array([[1, 3], [2, 4]]) @ np.array([[1, 3], [2, 4]]).T @ np.eye(2) if False else np.array([[5, 11], [11, 25]]))

def test_seq_replen():
    s = Session()
    out = exec_rapids("(seq 1 5 1)", s).as_frame()
    np.testing.assert_allclose(out.col(0).data, [1, 2, 3, 4, 5])
    out2 = exec_rapids("(rep_len 7 3)", s).as_frame()
    np.testing.assert_allclose(out2.col(0).data, [7, 7, 7])

def test_difflag1(sess):
    out = ex(sess, "(difflag1 (cols fr [1]))").as_frame()
    assert np.isnan(out.col(0).data[0])
    np.testing.assert_allclose(out.col(0).data[1:], [10, 10, 10, 10])

def test_melt(sess):
    out = ex(sess, '(melt fr [2] [0 1] "variable" "value" 0)').as_frame()
    assert out.nrows == 10
    assert "variable" in out.names and "value" in out.names

def test_pivot():
    s = Session()
    fr = Frame.from_dict(
        {"i": [1.0, 1.0, 2.0, 2.0], "c": ["p", "q", "p", "q"], "v": [1.0, 2.0, 3.0, 4.0]}
    )
    s.assign("pf", fr)
    out = ex(s, '(pivot pf "i" "c" "v")').as_frame()
    assert out.nrows == 2 and out.ncols == 3
    np.testing.assert_allclose(out.col("p").data, [1, 3])

def test_topn(sess):
    out = ex(sess, "(topn fr 1 40 1)").as_frame()
    np.testing.assert_allclose(sorted(out.col(1).data, reverse=True), [50, 40])

def test_rank_within_groupby(sess):
    out = ex(sess, '(rankWithinGroupBy fr [2] [1] [1] "rank")').as_frame()
    r = out.col("rank").data
    g = sess.lookup("fr").col("g")
    # within x group (rows 0,2,4 with b=10,30,50): ranks 1,2,3
    assert r[0] == 1 and r[2] == 2 and r[4] == 3
    assert r[1] == 1 and r[3] == 2

def test_stratified_split():
    s = Session()
    y = Frame([Column("y", np.array([0, 0, 0, 0, 1, 1, 1, 1], dtype=np.int32), ColType.CAT, ["a", "b"])])
    s.assign("yf", y)
    out = ex(s, "(h2o.random_stratified_split yf 0.5 42)").as_frame()
    d = out.col(0).data
    assert d[:4].sum() == 2 and d[4:].sum() == 2

def test_dropdup(sess):
    s = Session()
    fr = Frame.from_dict({"a": [1.0, 1.0, 2.0], "b": [5.0, 5.0, 6.0]})
    s.assign("df", fr)
    out = ex(s, "(dropdup df [0 1] 'first')").as_frame()
    assert out.nrows == 2


def test_distance_measures(sess):
    """(distance refs queries measure) — AstDistance parity on small
    oracles for all four measures."""
    import numpy as np

    from h2o3_tpu.frame.frame import Column, Frame

    rng = np.random.default_rng(0)
    R, Q, p = 7, 5, 3
    A = rng.normal(size=(R, p))
    B = rng.normal(size=(Q, p))
    s = Session()
    s.assign("dist_a", Frame([Column(f"x{i}", A[:, i]) for i in range(p)]))
    s.assign("dist_b", Frame([Column(f"x{i}", B[:, i]) for i in range(p)]))
    for measure, want in {
        "l2": np.sqrt(((A[:, None] - B[None]) ** 2).sum(2)),
        "l1": np.abs(A[:, None] - B[None]).sum(2),
        "cosine": (A @ B.T) / np.sqrt(
            (A * A).sum(1)[:, None] * (B * B).sum(1)[None, :]),
        "cosine_sq": (A @ B.T) ** 2 / (
            (A * A).sum(1)[:, None] * (B * B).sum(1)[None, :]),
    }.items():
        out = ex(s, f'(distance dist_a dist_b "{measure}")').as_frame()
        got = np.stack([c.numeric_view() for c in out.columns], axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-12, err_msg=measure)


def test_grouped_permute(sess):
    """(grouped_permute ...) — AstGroupedPermute: D-side x other-side
    id/amount crossings within each group."""
    import numpy as np

    from h2o3_tpu.frame.frame import ColType, Column, Frame

    s = Session()
    fr = Frame([
        Column("acct", np.array([1.0, 1, 1, 1, 2, 2])),
        Column("txn", np.array([10.0, 11, 12, 10, 20, 21])),
        Column("dc", np.array([0, 0, 1, 0, 0, 1], np.int32),
               ColType.CAT, ["D", "C"]),
        Column("amt", np.array([5.0, 7, 9, 3, 4, 6])),
    ])
    s.assign("gp", fr)
    out = ex(s, "(grouped_permute gp 1 [0] 2 3)").as_frame()
    # acct 1: D side {10: 5+3=8, 11: 7}, C side {12: 9} -> 2 rows
    # acct 2: D side {20: 4}, C side {21: 6} -> 1 row
    assert out.nrows == 3
    ins = out.col("In").numeric_view()
    amnts = out.col("InAmnt").numeric_view()
    i10 = int(np.where(ins == 10)[0][0])
    assert amnts[i10] == 8.0  # duplicate D ids merge amounts
    assert float(out.col("OutAmnt").numeric_view()[i10]) == 9.0

    # NA group keys merge into ONE group (reference HashMap<Double>
    # semantics), not one singleton per NaN
    fr2 = Frame([
        Column("acct", np.array([np.nan, np.nan])),
        Column("txn", np.array([1.0, 2.0])),
        Column("dc", np.array([0, 1], np.int32), ColType.CAT, ["D", "C"]),
        Column("amt", np.array([5.0, 9.0])),
    ])
    s.assign("gp2", fr2)
    out2 = ex(s, "(grouped_permute gp2 1 [0] 2 3)").as_frame()
    assert out2.nrows == 1  # the D and C rows cross within the NA group
