"""Test harness: single-process multi-device CPU mesh.

Reference analogue: tests run against an "N JVMs on localhost" cloud via
``water.runner.H2ORunner`` + ``@CloudSize(n)`` (SURVEY.md §4). Here the cloud
is 8 virtual XLA CPU devices in one process — the sharding/collective code
paths are identical to a real TPU slice.
"""

import os

# Force CPU before any backend initializes: the test tier always runs on the
# virtual 8-device CPU mesh, even when a real TPU is attached. (The config
# calls below are authoritative; the env vars cover subprocesses.)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

# Whole-tree training blocks are single large XLA programs; cache compiled
# executables across test runs/processes so only the first run pays.
jax.config.update("jax_compilation_cache_dir", "/tmp/h2o3_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh():
    from h2o3_tpu.parallel.mesh import default_mesh

    m = default_mesh()
    assert m.devices.size == 8, f"expected 8 virtual devices, got {m.devices.size}"
    return m


@pytest.fixture()
def rng():
    return np.random.default_rng(42)
