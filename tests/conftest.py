"""Test harness: single-process multi-device CPU mesh.

Reference analogue: tests run against an "N JVMs on localhost" cloud via
``water.runner.H2ORunner`` + ``@CloudSize(n)`` (SURVEY.md §4). Here the cloud
is 8 virtual XLA CPU devices in one process — the sharding/collective code
paths are identical to a real TPU slice.
"""

import os

# Force CPU before any backend initializes: the test tier always runs on the
# virtual 8-device CPU mesh, even when a real TPU is attached. (The config
# calls below are authoritative; the env vars cover subprocesses.)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5.3 has no jax_num_cpu_devices; the XLA_FLAGS
    # --xla_force_host_platform_device_count set above covers it
    pass

# NOTE: the persistent compilation cache is deliberately NOT enabled for
# the CPU test tier: XLA:CPU AOT executables serialized here carry machine
# feature sets (prefer-no-scatter et al.) that mismatch the host at load
# time and intermittently SIGSEGV in compilation_cache.get/put_executable.
# The TPU bench keeps its own cache (bench.py) where entries are TPU AOT.

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh():
    from h2o3_tpu.parallel.mesh import default_mesh

    m = default_mesh()
    assert m.devices.size == 8, f"expected 8 virtual devices, got {m.devices.size}"
    return m


@pytest.fixture()
def rng():
    return np.random.default_rng(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "leaks_keys: legacy test/module exempt from the strict DKV "
        "key-leak check (keys are still swept after the test)",
    )
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 run (-m 'not slow'): multi-node "
        "formation tests and other long-wall-clock coverage",
    )


def _sweep_keys(keys):
    from h2o3_tpu.keyed import DKV

    DKV.unlock_all()
    for k in keys:
        try:
            DKV.remove(k)
        except Exception:
            pass


@pytest.fixture(autouse=True)
def _check_dkv_keys(request):
    """CheckKeysTask analogue (h2o-test-support/.../runner/
    CheckKeysTask.java): every test must leave the DKV exactly as it
    found it. Keys created and not removed FAIL the test (and are swept
    so one failure cannot cascade). Tests/modules marked ``leaks_keys``
    are exempt — their state persists (module-scoped fixtures share
    keys) and the module-level sweeper below cleans up at module end."""
    from h2o3_tpu.keyed import DKV
    from h2o3_tpu.models.framework import Job

    before = set(DKV.keys())
    yield
    # Jobs persist by design: the /3/Jobs listing is the history of past
    # work (reference: Job keys are CheckKeysTask-exempt the same way)
    leaked = sorted(
        k for k in set(DKV.keys()) - before
        if not isinstance(DKV.peek(k), Job)
    )
    if leaked and request.node.get_closest_marker("leaks_keys") is None:
        _sweep_keys(leaked)
        pytest.fail(
            f"DKV key leak: {len(leaked)} key(s) left behind "
            f"(CheckKeysTask): {leaked[:10]}{'...' if len(leaked) > 10 else ''}"
        )


@pytest.fixture(scope="module", autouse=True)
def _sweep_dkv_between_modules():
    """Whatever a module's tests/fixtures accumulated (including marked
    leaks_keys debt) is removed at module end, so no module ever sees
    another module's keys."""
    from h2o3_tpu.keyed import DKV

    before = set(DKV.keys())
    yield
    _sweep_keys(sorted(set(DKV.keys()) - before))


@pytest.fixture(scope="module", autouse=True)
def _clear_jax_caches_between_modules():
    """Release compiled executables after each test module.

    Without this, the suite accumulates hundreds of live XLA:CPU
    executables in one process and intermittently SIGSEGVs inside a later
    backend_compile_and_load (JIT code-memory exhaustion — reproducible at
    ~90+ heavy compiles regardless of which tests ran). The reference
    suite runs as many separate JVMs; one long-lived Python process needs
    the explicit release."""
    yield
    jax.clear_caches()
