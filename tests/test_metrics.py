"""Metric parity tests vs sklearn (the M2 model-framework tier).

Reference analogue: hex/AUC2 tests, ModelMetrics tests (SURVEY.md §4)."""

import numpy as np
import pytest
from sklearn import metrics as skm

from h2o3_tpu.models import metrics as M


@pytest.fixture()
def binom_data(rng):
    n = 5000
    y = (rng.random(n) < 0.35).astype(np.float64)
    p = np.clip(0.35 + 0.4 * (y - 0.35) + rng.normal(0, 0.25, n), 1e-6, 1 - 1e-6)
    return y, p


def test_auc_exact_matches_sklearn(binom_data):
    y, p = binom_data
    m = M.binomial_metrics(y, p)
    assert m.auc == pytest.approx(skm.roc_auc_score(y, p), abs=1e-10)
    assert m.logloss == pytest.approx(skm.log_loss(y, p), abs=1e-10)
    assert m.gini == pytest.approx(2 * m.auc - 1)


def test_auc_400_bins_close_to_exact(binom_data):
    """The reference's 400-bin approximation (AUC2.java:36) stays within ~1e-3."""
    y, p = binom_data
    exact = M.binomial_metrics(y, p, nbins=0).auc
    approx = M.binomial_metrics(y, p, nbins=400).auc
    assert approx == pytest.approx(exact, abs=2e-3)


def test_max_f1_threshold_and_cm(binom_data):
    y, p = binom_data
    m = M.binomial_metrics(y, p)
    # compare to brute-force F1 over all candidate thresholds
    prec, rec, thr = skm.precision_recall_curve(y, p)
    f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-300)
    best_f1 = f1.max()
    assert m.cm.f1 == pytest.approx(best_f1, abs=1e-6)
    cm = m.confusion_matrix(0.5)
    sk_cm = skm.confusion_matrix(y, (p >= 0.5).astype(int))
    np.testing.assert_allclose(cm.table, sk_cm)


def test_pr_auc_close(binom_data):
    y, p = binom_data
    m = M.binomial_metrics(y, p)
    assert m.pr_auc == pytest.approx(skm.average_precision_score(y, p), abs=5e-3)


def test_regression_metrics(rng):
    y = rng.normal(10, 2, 1000)
    p = y + rng.normal(0, 1, 1000)
    m = M.regression_metrics(y, p)
    assert m.mse == pytest.approx(skm.mean_squared_error(y, p))
    assert m.mae == pytest.approx(skm.mean_absolute_error(y, p))
    assert m.r2 == pytest.approx(skm.r2_score(y, p))


def test_regression_weights(rng):
    y = rng.normal(size=500)
    p = y + rng.normal(0, 1, 500)
    w = rng.random(500) + 0.5
    m = M.regression_metrics(y, p, weights=w)
    assert m.mse == pytest.approx(skm.mean_squared_error(y, p, sample_weight=w))


def test_multinomial_metrics(rng):
    n, k = 3000, 4
    y = rng.integers(0, k, n)
    logits = rng.normal(0, 1, (n, k))
    logits[np.arange(n), y] += 1.5
    probs = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    m = M.multinomial_metrics(y, probs, domain=["a", "b", "c", "d"])
    assert m.logloss == pytest.approx(skm.log_loss(y, probs), abs=1e-9)
    acc = (probs.argmax(1) == y).mean()
    assert m.hit_ratios[0] == pytest.approx(acc, abs=1e-9)
    assert m.hit_ratios[-1] == pytest.approx(1.0)
    assert m.confusion_matrix.sum() == n


def test_stop_early_semantics():
    # monotone improving: never stops
    hist = list(np.linspace(1.0, 0.5, 20))
    assert not M.stop_early(hist, stopping_rounds=3, more_is_better=False, stopping_tolerance=1e-3)
    # plateaued: stops
    hist = [1.0, 0.8, 0.6, 0.5] + [0.45] * 10
    assert M.stop_early(hist, stopping_rounds=3, more_is_better=False, stopping_tolerance=1e-3)
    # too-short history: no decision
    assert not M.stop_early([1.0, 0.9], stopping_rounds=3, more_is_better=False, stopping_tolerance=1e-3)
    # more-is-better plateau (e.g. AUC)
    hist = [0.6, 0.7, 0.75] + [0.76] * 10
    assert M.stop_early(hist, stopping_rounds=3, more_is_better=True, stopping_tolerance=1e-3)
    # still improving AUC
    hist = list(np.linspace(0.6, 0.9, 20))
    assert not M.stop_early(hist, stopping_rounds=3, more_is_better=True, stopping_tolerance=1e-3)
