"""Per-trace cost attribution: the ledger, the slow-op exemplar log,
`GET /3/Traces/{id}` federation, and the cluster-federated profiler.

Reference frame: the reference's WaterMeter answers "what is this NODE
doing"; the ledger answers "what did this REQUEST cost, where" — the
per-step cost visibility the TF-paper line of work insists on.  The
cluster halves run multiple Cloud instances in one process over real
loopback sockets, which means every member shares ONE process-wide
ledger — assertions merge-by-overwrite-aware, like the endpoint itself.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from h2o3_tpu.api.coalesce import Coalescer
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.util import ledger as L
from h2o3_tpu.util import telemetry as T

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)


def _mr_ledger_stat(cols, mask):
    """Module-level map fn (crosses the wire by module reference); unique
    to this file so its first dispatch is a guaranteed fresh compile."""
    import jax.numpy as jnp

    return {
        "s": jnp.sum(jnp.where(mask, cols["x"] * 3.0, 0.0)),
        "n": jnp.sum(mask.astype(jnp.float32)),
    }


def _wait_for(cond, timeout=10.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


@pytest.fixture(autouse=True)
def _clean_ledger():
    L.LEDGER.clear()
    L.SLOWOPS.clear()
    yield
    L.LEDGER.clear()
    L.SLOWOPS.clear()


@pytest.fixture()
def two_clouds():
    a = Cloud("ledgercloud", "node-a", hb_interval=0.05)
    b = Cloud("ledgercloud", "node-b", hb_interval=0.05)
    try:
        a.start([])
        b.start([a.info.addr])
        _wait_for(
            lambda: a.size() == 2 and b.size() == 2
            and a.consensus() and b.consensus(),
            msg="2-node cloud formation")
        yield a, b
    finally:
        a.stop()
        b.stop()


@pytest.fixture()
def cloud_server(two_clouds):
    from h2o3_tpu.api import start_server

    a, b = two_clouds
    set_local_cloud(a)
    srv = start_server(port=0)
    try:
        yield a, b, srv
    finally:
        srv.stop()
        set_local_cloud(None)


def _get(srv, path):
    try:
        with urllib.request.urlopen(srv.url + path) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ---------------------------------------------------------------------------
# the ledger core


class TestCostLedger:
    def test_charge_attributes_by_node_span_and_category(self):
        led = L.CostLedger(max_traces=16)
        led.charge(L.COMPILE_SECONDS, 0.25, trace_id="t1", node="n1",
                   span_id="s1")
        led.charge(L.COMPILE_SECONDS, 0.75, trace_id="t1", node="n2",
                   span_id="s2")
        led.charge(L.RPC_SENT_BYTES, 100, trace_id="t1", node="n1",
                   span_id="s1")
        e = led.get("t1")
        assert e["nodes"] == {
            "n1": {"compile_seconds": 0.25, "rpc_sent_bytes": 100.0},
            "n2": {"compile_seconds": 0.75},
        }
        assert e["spans"]["s1"]["rpc_sent_bytes"] == 100.0
        # the cross-node total sums per-node maps
        assert e["total"] == {"compile_seconds": 1.0,
                              "rpc_sent_bytes": 100.0}

    def test_charge_defaults_to_current_span_context(self):
        led = L.CostLedger(max_traces=16)
        with T.Span("ledger_unit") as sp:
            led.charge(L.CHUNK_READS, 3)
        e = led.get(sp.trace_id)
        assert e is not None
        (node,) = e["nodes"]
        assert e["nodes"][node] == {"chunk_reads": 3.0}
        assert e["spans"][sp.span_id] == {"chunk_reads": 3.0}

    def test_untraced_charge_is_a_noop(self):
        led = L.CostLedger(max_traces=16)
        assert T.current_span() is None
        led.charge(L.CHUNK_READS, 1)
        assert len(led) == 0

    def test_disabled_ledger_charges_nothing(self):
        led = L.CostLedger(max_traces=16)
        led.set_enabled(False)
        led.charge(L.CHUNK_READS, 1, trace_id="t1")
        assert len(led) == 0 and led.get("t1") is None
        led.set_enabled(True)
        led.charge(L.CHUNK_READS, 1, trace_id="t1")
        assert led.get("t1")["total"] == {"chunk_reads": 1.0}

    def test_lru_bound_evicts_oldest(self):
        led = L.CostLedger(max_traces=4)
        for i in range(10):
            led.charge(L.CHUNK_READS, 1, trace_id=f"t{i}", node="n")
        assert len(led) == 4
        assert led.trace_ids() == ["t6", "t7", "t8", "t9"]
        # a charge touches its trace: it survives the next eviction round
        led.charge(L.CHUNK_READS, 1, trace_id="t6", node="n")
        led.charge(L.CHUNK_READS, 1, trace_id="tA", node="n")
        assert "t6" in led.trace_ids() and "t7" not in led.trace_ids()

    def test_span_map_bounded_with_overflow_bucket(self):
        led = L.CostLedger(max_traces=4)
        for i in range(200):
            led.charge(L.CHUNK_READS, 1, trace_id="t", node="n",
                       span_id=f"sp{i}")
        e = led.get("t")
        assert len(e["spans"]) == 129  # _SPAN_CAP named spans + _overflow
        assert e["spans"]["_overflow"]["chunk_reads"] == 72.0
        # node-level attribution never truncates
        assert e["nodes"]["n"]["chunk_reads"] == 200.0

    def test_annotate_only_touches_existing_traces(self):
        led = L.CostLedger(max_traces=4)
        led.annotate("ghost", route="GET /x")
        assert len(led) == 0
        led.charge(L.CHUNK_READS, 1, trace_id="t", node="n")
        led.annotate("t", route="GET /x", wall_ms=12.5)
        e = led.get("t")
        assert e["route"] == "GET /x" and e["wall_ms"] == 12.5

    def test_charge_meter_counts_events_by_category(self):
        c = T.REGISTRY.get("ledger_charges_total")
        before = c.total()
        led = L.CostLedger(max_traces=4)
        led.charge(L.DEVCACHE_UPLOAD_BYTES, 4096, trace_id="t", node="n")
        led.charge(L.DEVCACHE_UPLOAD_BYTES, 4096, trace_id="t", node="n")
        assert c.total() == before + 2  # events, not bytes


class TestSlowOpLog:
    def test_threshold_gates_and_ring_keeps_the_worst(self):
        log = L.SlowOpLog(threshold_ms=100.0, per_route=3)
        assert log.record("GET /x", 99.9) is False
        for w in (150.0, 500.0, 120.0, 300.0, 101.0):
            log.record("GET /x", w)
        snap = log.snapshot()
        walls = [r["wall_ms"] for r in snap["routes"]["GET /x"]]
        assert walls == [500.0, 300.0, 150.0]

    def test_negative_threshold_disables(self):
        log = L.SlowOpLog(threshold_ms=-1.0, per_route=3)
        assert log.record("GET /x", 1e9) is False
        assert log.snapshot()["routes"] == {}

    def test_record_attaches_the_ledger_snapshot(self):
        L.LEDGER.charge(L.COMPILE_SECONDS, 0.5, trace_id="slow-t",
                        node="n1")
        log = L.SlowOpLog(threshold_ms=0.0, per_route=2)
        assert log.record("POST /y", 42.0, trace_id="slow-t", status=200)
        rec = log.snapshot(route="POST /y")["routes"]["POST /y"][0]
        assert rec["status"] == 200
        assert rec["ledger"]["nodes"]["n1"]["compile_seconds"] == 0.5


# ---------------------------------------------------------------------------
# coalesced-batch share accounting


class TestCoalesceShares:
    def test_batch_of_k_splits_cost_evenly_and_sums_to_dispatch(self):
        K, sleep_s = 4, 0.05
        ran = threading.Event()

        def batch_fn(payloads):
            time.sleep(sleep_s)
            ran.set()
            return [p * 2 for p in payloads]

        co = Coalescer(dispatch=lambda fn: fn(), window_s=30.0,
                       max_rows=10**9, max_requests=K)
        tids = [f"rider{i:02d}" for i in range(K)]
        futs = [co.submit(batch_fn, "m1", i, trace_id=tids[i])
                for i in range(K)]  # Kth submission trips max_requests
        assert ran.wait(10)
        assert [f.result(timeout=10) for f in futs] == [0, 2, 4, 6]
        shares = []
        for tid in tids:
            e = L.LEDGER.get(tid)
            assert e is not None, f"no ledger entry for {tid}"
            shares.append(e["total"][L.COALESCE_SHARE_SECONDS])
        # equal split, and the shares sum back to the one dispatch's wall
        assert len(set(shares)) == 1
        assert sum(shares) >= sleep_s
        assert abs(sum(shares) - K * shares[0]) < 1e-12

    def test_failed_batch_still_charges_riders(self):
        def batch_fn(payloads):
            time.sleep(0.01)
            raise RuntimeError("scoring exploded")

        co = Coalescer(dispatch=lambda fn: fn(), window_s=30.0,
                       max_rows=10**9, max_requests=2)
        f1 = co.submit(batch_fn, "m2", 1, trace_id="boom1")
        f2 = co.submit(batch_fn, "m2", 2, trace_id="boom2")
        with pytest.raises(RuntimeError):
            f1.result(timeout=10)
        with pytest.raises(RuntimeError):
            f2.result(timeout=10)
        for tid in ("boom1", "boom2"):
            assert L.LEDGER.get(tid)["total"][L.COALESCE_SHARE_SECONDS] > 0

    def test_untraced_riders_charge_nothing(self):
        co = Coalescer(dispatch=lambda fn: fn(), window_s=30.0,
                       max_rows=10**9, max_requests=1)
        fut = co.submit(lambda ps: [p for p in ps], "m3", 7)
        assert fut.result(timeout=10) == 7
        assert len(L.LEDGER) == 0


# ---------------------------------------------------------------------------
# cross-node attribution: remote work folds back to the caller's trace


class TestRemoteAttribution:
    def test_remote_shard_charges_callers_trace_under_remote_node(
            self, two_clouds):
        import numpy as np

        from h2o3_tpu.cluster import tasks as ctasks
        from h2o3_tpu.cluster.tasks import distributed_map_reduce

        ctasks.install(two_clouds[0])
        ctasks.install(two_clouds[1])
        x = np.arange(64, dtype=np.float64)
        with T.Span("ledger_fit") as caller:
            out = distributed_map_reduce(
                _mr_ledger_stat, {"x": x}, reduce="sum",
                cloud=two_clouds[0])
        assert float(out["s"]) == float((x * 3.0).sum())
        e = L.LEDGER.get(caller.trace_id)
        assert e is not None
        # the remote member executed its shard IN OUR TRACE, charged
        # under ITS node name (the rpc_server envelope context)
        assert "node-b" in e["nodes"], sorted(e["nodes"])
        assert e["nodes"]["node-b"][L.SHARD_WALL_SECONDS] > 0
        # the fresh map fn compiled somewhere inside this trace, and the
        # mr_chunks payloads crossed the wire both ways
        assert e["total"].get(L.COMPILE_SECONDS, 0) > 0
        assert e["total"][L.RPC_SENT_BYTES] > 0
        assert e["total"][L.RPC_RECV_BYTES] > 0

    def test_traces_endpoint_federates_and_degrades(self, cloud_server):
        import numpy as np

        from h2o3_tpu.cluster import tasks as ctasks
        from h2o3_tpu.cluster.tasks import distributed_map_reduce

        a, b, srv = cloud_server
        ctasks.install(a)
        ctasks.install(b)
        x = np.arange(32, dtype=np.float64)
        with T.Span("rest_ledger_fit") as caller:
            distributed_map_reduce(
                _mr_ledger_stat, {"x": x}, reduce="sum", cloud=a)
        st, out = _get(srv, f"/3/Traces/{caller.trace_id}")
        assert st == 200
        assert out["trace_id"] == caller.trace_id
        assert out["partial"] is False
        assert "node-b" in out["nodes"]
        # overwrite-merge: the federated view matches the (shared,
        # process-wide) local entry — per category, never multiplied by
        # the member count
        local = L.LEDGER.get(caller.trace_id)
        assert out["total"] == local["total"]
        st, _ = _get(srv, "/3/Traces/feedfacefeedface")
        assert st == 404
        # one dead member: still 200, partial, with node-a's data intact
        b.stop()
        a.client.pool.close_all()
        st, out = _get(srv, f"/3/Traces/{caller.trace_id}")
        assert st == 200 and out["partial"] is True
        assert "node-b" in out["errors"]
        assert out["total"][L.SHARD_WALL_SECONDS] > 0


# ---------------------------------------------------------------------------
# REST surface: slow-op log, ledgers-on-timeline, federated profiler


class TestRestSurface:
    def test_slowops_endpoint_captures_slow_requests(
            self, cloud_server, monkeypatch):
        _a, _b, srv = cloud_server
        monkeypatch.setattr(L.SLOWOPS, "threshold_ms", 0.0)
        st, _ = _get(srv, "/3/Ping")
        assert st == 200
        st, out = _get(srv, "/3/SlowOps")
        assert st == 200
        assert out["per_route"] >= 1
        ping = [r for route, recs in out["routes"].items()
                if "/3/Ping" in route for r in recs]
        assert ping and ping[0]["wall_ms"] >= 0
        # route narrowing
        route = next(r for r in out["routes"] if "/3/Ping" in r)
        st, out = _get(srv, "/3/SlowOps?route=" +
                       urllib.request.quote(route, safe=""))
        assert st == 200 and list(out["routes"]) == [route]

    def test_timeline_ledgers_param_attaches_cost_breakdowns(
            self, cloud_server):
        _a, _b, srv = cloud_server
        with T.Span("timeline_ledger_unit") as sp:
            L.charge(L.DEVCACHE_UPLOAD_BYTES, 2048)
        st, out = _get(srv, "/3/Timeline?count=500&ledgers=true")
        assert st == 200
        assert sp.trace_id in out["ledgers"]
        entry = out["ledgers"][sp.trace_id]
        assert entry["total"][L.DEVCACHE_UPLOAD_BYTES] == 2048.0
        # without the param: no attachment
        st, out = _get(srv, "/3/Timeline?count=50")
        assert st == 200 and "ledgers" not in out

    def test_cluster_profiler_merges_members_with_aggregate(
            self, cloud_server):
        _a, _b, srv = cloud_server
        st, out = _get(srv, "/3/Profiler?cluster=true&duration=0.05")
        assert st == 200
        assert out["partial"] is False and out["errors"] == {}
        names = [n["node_name"] for n in out["nodes"]]
        assert names[-1] == "_cluster"
        assert {"node-a", "node-b", "_cluster"} <= set(names)
        agg = out["nodes"][-1]["profile"]
        assert agg, "merged aggregate sampled no stacks"
        assert all(
            {"stacktrace", "count", "pct"} <= set(s) for s in agg)
        # pct re-normalizes over the merged total
        assert sum(s["pct"] for s in agg) <= 100.0 + 1e-6

    def test_cluster_profiler_partial_when_member_down(self, cloud_server):
        a, b, srv = cloud_server
        b.stop()
        a.client.pool.close_all()
        st, out = _get(srv, "/3/Profiler?cluster=true&duration=0.05")
        assert st == 200  # degraded, never a 5xx
        assert out["partial"] is True
        assert "node-b" in out["errors"]
        names = {n["node_name"] for n in out["nodes"]}
        assert "node-a" in names and "_cluster" in names

    def test_local_profiler_path_unchanged_without_cluster_param(
            self, cloud_server):
        _a, _b, srv = cloud_server
        st, out = _get(srv, "/3/Profiler?duration=0.05")
        assert st == 200
        assert "partial" not in out
        assert len(out["nodes"]) == 1 and out["nodes"][0]["profile"]


# ---------------------------------------------------------------------------
# rpc byte meter: method label on ALL traffic


class TestRpcByteMeter:
    def test_payload_bytes_labelled_by_method(self, two_clouds):
        a, b = two_clouds
        c = T.REGISTRY.get("rpc_payload_bytes_total")

        def _val(direction, method):
            return sum(
                s["value"] for s in c.snapshot()["series"]
                if s["labels"].get("direction") == direction
                and s["labels"].get("method") == method)

        sent0, recv0 = _val("sent", "echo"), _val("received", "echo")
        a.client.call(b.info.addr, "echo", b"ledger-bytes", timeout=5.0,
                      target=b.info.ident)
        assert _val("sent", "echo") > sent0
        assert _val("received", "echo") > recv0
        # heartbeat traffic meters under its own method label, so shard
        # shipping is separable from gossip
        _wait_for(lambda: _val("sent", "heartbeat") > 0,
                  msg="heartbeat bytes to meter")


# ---------------------------------------------------------------------------
# trace_view cost columns


class TestTraceViewCosts:
    def _render(self, tmp_path, snap):
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(snap))
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "scripts", "trace_view.py"),
             str(path)],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout

    def test_ledger_snapshot_renders_cost_columns(self, tmp_path):
        with T.Span("costed_view", route="/3/X") as outer:
            L.charge(L.COMPILE_SECONDS, 0.125)
            L.charge(L.DEVCACHE_UPLOAD_BYTES, 4096)
            L.charge(L.RPC_SENT_BYTES, 1024)
            L.charge(L.RPC_RECV_BYTES, 1024)
        from h2o3_tpu.util import timeline
        events = [e for e in timeline.snapshot(timeline.CAPACITY)
                  if e.get("trace_id") == outer.trace_id]
        snap = {"events": events,
                "ledgers": L.LEDGER.snapshot_many([outer.trace_id])}
        out = self._render(tmp_path, snap)
        assert "compile 0.125s" in out
        assert "upload 4.0KB" in out
        assert "wire 2.0KB" in out
        # the trace header carries the totals too
        header = next(ln for ln in out.splitlines()
                      if ln.startswith(f"trace {outer.trace_id}"))
        assert "$" in header

    def test_plain_snapshot_renders_without_cost_columns(self, tmp_path):
        with T.Span("plain_view") as outer:
            pass
        from h2o3_tpu.util import timeline
        events = [e for e in timeline.snapshot(timeline.CAPACITY)
                  if e.get("trace_id") == outer.trace_id]
        out = self._render(tmp_path, {"events": events})
        assert f"trace {outer.trace_id}" in out
        assert "$" not in out
