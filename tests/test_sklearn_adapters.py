"""sklearn adapter surface (h2o-py h2o.sklearn analogue): fit/predict/
predict_proba/score over numpy, clone/get_params in sklearn tooling."""

import numpy as np
import pytest

pytestmark = pytest.mark.leaks_keys


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(300, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.int64)
    return X, y


def test_classifier_fit_predict_proba_score(data):
    from h2o3_tpu.client.sklearn import H2OGradientBoostingClassifier

    X, y = data
    clf = H2OGradientBoostingClassifier(ntrees=20, max_depth=3, seed=1)
    assert clf.fit(X, y) is clf
    pred = clf.predict(X)
    assert pred.shape == (300,) and set(np.unique(pred)) <= {0, 1}
    proba = clf.predict_proba(X)
    assert proba.shape == (300, 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-6)
    acc = clf.score(X, y)  # ClassifierMixin accuracy
    assert acc > 0.85
    assert np.all(np.isfinite(clf.predict_log_proba(X)))


def test_regressor_r2(data):
    from h2o3_tpu.client.sklearn import H2OGradientBoostingRegressor

    X, _ = data
    yr = X[:, 0] * 2.0 + X[:, 2] + 0.05 * np.random.default_rng(0).normal(
        size=X.shape[0])
    reg = H2OGradientBoostingRegressor(ntrees=30, max_depth=3, seed=1)
    reg.fit(X, yr)
    assert reg.score(X, yr) > 0.8  # RegressorMixin R^2


def test_clone_and_cross_val(data):
    from sklearn.base import clone
    from sklearn.model_selection import cross_val_score

    from h2o3_tpu.client.sklearn import H2OGeneralizedLinearClassifier

    X, y = data
    clf = H2OGeneralizedLinearClassifier(family="binomial", lambda_=0.0)
    c2 = clone(clf)
    assert c2.get_params() == clf.get_params() and c2 is not clf
    scores = cross_val_score(clf, X, y, cv=2)
    assert scores.shape == (2,) and scores.mean() > 0.8


def test_kmeans_and_pca(data):
    from h2o3_tpu.client.sklearn import (
        H2OKMeansEstimator,
        H2OPrincipalComponentAnalysisEstimator,
    )

    X, _ = data
    km = H2OKMeansEstimator(k=3, seed=1)
    km.fit(X)
    assert km.labels_.shape == (300,) and len(np.unique(km.labels_)) == 3

    pca = H2OPrincipalComponentAnalysisEstimator(k=2, seed=1)
    z = pca.fit(X).transform(X)
    assert z.shape == (300, 2) and np.all(np.isfinite(z))


def test_pipeline_compose(data):
    """The wrappers compose inside a sklearn Pipeline."""
    from sklearn.pipeline import Pipeline
    from sklearn.preprocessing import StandardScaler

    from h2o3_tpu.client.sklearn import H2ORandomForestClassifier

    X, y = data
    pipe = Pipeline([
        ("scale", StandardScaler()),
        ("rf", H2ORandomForestClassifier(ntrees=10, seed=1)),
    ])
    pipe.fit(X, y)
    assert pipe.score(X, y) > 0.8


def test_bool_targets_roundtrip(data):
    """Boolean y (a plain `X[:,0] > 0` mask) must predict back as bools —
    a dtype cast of label strings would turn every 'False' into True."""
    from h2o3_tpu.client.sklearn import H2OGradientBoostingClassifier

    X, _ = data
    yb = X[:, 0] > 0
    clf = H2OGradientBoostingClassifier(ntrees=10, max_depth=3, seed=1)
    pred = clf.fit(X, yb).predict(X)
    assert pred.dtype == np.bool_
    assert 0.1 < pred.mean() < 0.9          # both classes present
    assert (pred == yb).mean() > 0.9
