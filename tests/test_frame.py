"""Frame/parse/rollup tests — the M0 columnar core.

Reference test analogues: h2o-core/src/test/java/water/fvec/* and
water/parser/* parse tests (SURVEY.md §4 tier 1)."""

import numpy as np
import pytest

from h2o3_tpu import Frame, parse_csv, parse_setup
from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.frame.rollups import histogram

CSV = """id,age,weight,sex,signup,comment
1,34,70.5,M,2021-01-02,hello
2,28,NA,F,2021-02-03,world
3,45,88.1,M,2021-03-04,foo
4,NA,61.0,F,2021-04-05,bar
5,52,75.2,NA,2021-05-06,baz
"""


def test_parse_setup_guesses():
    s = parse_setup(CSV)
    assert s.separator == ","
    assert s.header is True
    assert s.column_names == ["id", "age", "weight", "sex", "signup", "comment"]
    assert s.column_types[0] == ColType.NUM
    assert s.column_types[1] == ColType.NUM
    assert s.column_types[2] == ColType.NUM
    assert s.column_types[3] == ColType.CAT
    assert s.column_types[4] == ColType.TIME


def test_parse_values_and_nas():
    fr = parse_csv(CSV)
    assert fr.shape == (5, 6)
    age = fr.col("age")
    assert age.na_count() == 1
    assert np.isnan(age.data[3])
    assert age.data[0] == 34
    sex = fr.col("sex")
    assert sex.type == ColType.CAT
    assert sex.domain == ["F", "M"]  # lexicographic domain like the reference
    assert sex.data[0] == 1 and sex.data[1] == 0 and sex.data[4] == -1
    t = fr.col("signup")
    assert t.type == ColType.TIME
    # 2021-01-02 in ms since epoch
    assert t.data[0] == 1609545600000.0


def test_parse_no_header_and_tabs():
    fr = parse_csv("1\t2.5\tx\n2\t3.5\ty\n3\t4.5\tx\n")
    assert fr.names == ["C1", "C2", "C3"]
    assert fr.col("C1").type == ColType.NUM
    assert fr.col("C3").type == ColType.CAT


def test_quoted_fields():
    fr = parse_csv('a,b\n"x, y",1\n"he said ""hi""",2\n')
    col = fr.col("a")
    assert col.data[0] == "x, y" or (col.type == ColType.CAT and col.domain[col.data[0]] == "x, y")


def test_rollups_match_numpy(rng):
    x = rng.normal(10, 3, size=200_000)
    x[::97] = np.nan
    fr = Frame.from_dict({"x": x})
    r = fr.col("x").rollups
    v = x[~np.isnan(x)]
    assert r.na_count == int(np.isnan(x).sum())
    assert r.mean == pytest.approx(v.mean(), rel=1e-6)
    assert r.sigma == pytest.approx(v.std(ddof=1), rel=1e-6)
    assert r.min == pytest.approx(v.min())
    assert r.max == pytest.approx(v.max())
    assert not r.is_int
    h = histogram(fr.col("x"), nbins=32)
    assert h.sum() == v.size


def test_slicing_and_filter():
    fr = parse_csv(CSV)
    sub = fr[["age", "weight"]]
    assert sub.names == ["age", "weight"]
    m = fr.col("age").data > 30
    m &= ~np.isnan(fr.col("age").data)
    filt = fr[m]
    assert filt.nrows == 3
    head = fr.head(2)
    assert head.nrows == 2


def test_cbind_rbind_naomit():
    a = Frame.from_dict({"x": [1.0, 2.0], "s": ["a", "b"]})
    b = Frame.from_dict({"x": [3.0, np.nan], "s": ["b", "c"]})
    ab = a.rbind(b)
    assert ab.nrows == 4
    s = ab.col("s")
    assert s.type == ColType.CAT
    assert set(s.domain) >= {"a", "b", "c"}
    # same level must map to the same code across both halves
    assert s.data[1] == s.data[2]
    assert ab.na_omit().nrows == 3
    wide = a.cbind(b)
    assert wide.ncols == 4 and wide.nrows == 2


def test_as_factor_as_numeric():
    fr = Frame.from_dict({"x": [0.0, 1.0, 1.0, 2.0]})
    f = fr.col("x").as_factor()
    assert f.type == ColType.CAT
    assert f.domain == ["0", "1", "2"]
    back = f.as_numeric()
    np.testing.assert_allclose(back.data, [0, 1, 1, 2])
