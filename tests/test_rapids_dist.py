"""Distributed Rapids: fused column programs execute on chunk homes.

The contract under test (h2o3_tpu/rapids/dist_exec.py): a Rapids eval
over an unmaterialized chunk-homed DistFrame ships the fused region's
canonical sexpr + leaf schemas to each chunk home, executes there over
home-local chunks, and either merges reducer partials caller-side or
writes derived columns back as new chunk-homed vectors on the same
layout — bit-identical to the local interpreter at every cell of the
test_rapids_fusion parity matrix, with zero row data on the wire.
"""

import os
import time

import numpy as np
import pytest

from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster.frames import DistFrame
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.frame.parse import _iter_body_chunks, parse_csv, parse_setup
from h2o3_tpu.keyed import KeyedStore
from h2o3_tpu.models.tree.gbm import GBM, GBMParameters
from h2o3_tpu.rapids.runtime import Session, exec_rapids
from h2o3_tpu.util import telemetry

from test_rapids_fusion import PARITY_CASES, _special_frame, assert_same_val

pytestmark = pytest.mark.leaks_keys


def _counter(name, **labels):
    c = telemetry.REGISTRY.get(name)
    if c is None:
        return 0.0
    return float(c.value(**labels)) if labels else float(c.total())


def _data_wire_bytes():
    """Data-plane wire bytes: everything but the periodic heartbeats
    (which tick the meter in the background regardless of workload)."""
    c = telemetry.REGISTRY.get("rpc_payload_bytes_total")
    if c is None:
        return 0.0
    return sum(s["value"] for s in c.snapshot()["series"]
               if s["labels"].get("method") != "heartbeat")


def _wait_for(cond, timeout=15.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


def _form_cloud(n, prefix):
    clouds = []
    for i in range(n):
        c = Cloud("rapdist", f"{prefix}{i}", hb_interval=0.05)
        s = KeyedStore()
        cdkv.install(c, s)
        ctasks.install(c)
        clouds.append(c)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    _wait_for(lambda: all(c.size() == n for c in clouds),
              msg=f"{n}-node cloud formation")
    return clouds


def _stop_all(clouds):
    for c in clouds:
        try:
            c.stop()
        except Exception:
            pass


def _special_csv():
    """The test_rapids_fusion special-value frame as CSV — NaN ships as
    an empty cell (NA) and ±inf as over-range literals so the parser's
    float() path reproduces the exact specials, signed zeros included."""
    fr = _special_frame()
    cols = [c.data for c in fr.columns]

    def tok(v):
        if np.isnan(v):
            return ""
        if np.isposinf(v):
            return "1e999"
        if np.isneginf(v):
            return "-1e999"
        return repr(float(v))

    lines = [",".join(c.name for c in fr.columns)]
    for i in range(fr.nrows):
        lines.append(",".join(tok(c[i]) for c in cols))
    return "\n".join(lines) + "\n"


def _parse_to_homes(cloud, key, text, chunk_bytes=1024):
    setup = parse_setup(text)
    chunks = list(_iter_body_chunks(
        [text.encode()], chunk_bytes, setup.header, setup.skip_blank_lines))
    fr = ctasks.distributed_parse_chunks(chunks, setup, cloud=cloud, key=key)
    assert isinstance(fr, DistFrame)
    return fr


def _int_csv(n=6000):
    """Integer-valued columns: partials are exact f64 under any grouping."""
    lines = ["x,y,reg"]
    for i in range(n):
        lines.append(f"{i % 97},{(i * 7) % 31},{(i * 3) % 11}")
    return "\n".join(lines) + "\n"


@pytest.fixture(scope="module")
def homed():
    """A formed 3-node cloud + the parity frame parsed ONTO the ring and
    the SAME text parsed locally for the reference interpreter."""
    clouds = _form_cloud(3, "rd")
    set_local_cloud(clouds[0])
    try:
        text = _special_csv()
        dist = _parse_to_homes(clouds[0], "rapids_parity_df", text)
        assert len({g["home_name"]
                    for g in dist.chunk_layout["groups"]}) >= 2
        local = parse_csv(text)
        yield clouds, dist, local
    finally:
        set_local_cloud(None)
        _stop_all(clouds)


@pytest.fixture()
def sess(homed):
    _clouds, dist, local = homed
    s = Session()
    s.assign("pd", dist)
    s.assign("pl", local)
    yield s
    # keep the module frame unmaterialized between tests: any gather is a
    # bug in the path under test, not state for the next test to inherit
    dist._materialized = None


def _run_dist(sess, expr):
    """(interpreter ref on the local twin, dist result, dist delta)."""
    prev = os.environ.get("H2O3_TPU_RAPIDS_FUSION")
    try:
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "0"
        ref = exec_rapids(expr.replace(" pd ", " pl ").replace("(pd ", "(pl "),
                          sess)
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
        d0 = _counter("rapids_dist_total", result="dist")
        got = exec_rapids(expr, sess)
    finally:
        if prev is None:
            os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
        else:
            os.environ["H2O3_TPU_RAPIDS_FUSION"] = prev
    return ref, got, _counter("rapids_dist_total", result="dist") - d0


@pytest.mark.parametrize("name", sorted(PARITY_CASES))
def test_parity_matrix_home_side(homed, sess, name):
    """Every fusible prim over the special-value frame (NaN/±inf/±0.0/
    div-mod signs), executed ON the chunk homes, bit-identical to the
    local interpreter — uint64 views, both-NaN exempt."""
    _clouds, dist, _local = homed
    expr = PARITY_CASES[name].replace(" pf ", " pd ").replace("(pf ", "(pd ")
    ref, got, dist_delta = _run_dist(sess, expr)
    assert dist_delta >= 1, f"{name}: region did not ship to the homes"
    assert dist._materialized is None, f"{name}: source frame gathered"
    assert_same_val(ref, got, ctx=name)


def test_metadata_answers_from_layout_zero_wire(homed, sess):
    """nrow/ncol/colnames/type predicates over a DistFrame answer off the
    layout: zero data-plane rpc_payload_bytes_total growth, no gather."""
    _clouds, dist, local = homed
    w0 = _data_wire_bytes()
    meta = {}
    for expr in ("(nrow pd)", "(ncol pd)", "(is.factor pd)",
                 "(is.numeric pd)", "(is.character pd)", "(anyfactor pd)"):
        meta[expr] = exec_rapids(expr, sess)
    assert _data_wire_bytes() - w0 == 0.0
    assert dist._materialized is None
    assert meta["(nrow pd)"].as_num() == local.nrows
    assert meta["(ncol pd)"].as_num() == local.ncols
    got = np.asarray(meta["(is.numeric pd)"].as_nums())
    want = [float(t in (ColType.NUM, ColType.TIME))
            for t in local.col_types()]
    assert got.tolist() == want


def test_warm_repeat_compiles_nothing_home_side(homed, sess):
    """A repeated pipeline hits the plan memo on every home: zero plan
    cache misses and zero group-frame devcache misses on the warm run."""
    expr = "(sum (* (cols_py pd 0) (cols_py pd 1)))"
    first = exec_rapids(expr, sess)
    m0 = _counter("mapreduce_plan_cache_total",
                  op="rapids_dist", result="miss")
    f0 = _counter("mapreduce_plan_cache_total",
                  op="rapids_fusion", result="miss")
    g0 = _counter("devcache_requests_total",
                  kind="rapids_group_frame", result="miss")
    d0 = _counter("rapids_dist_total", result="dist")
    warm = exec_rapids(expr, sess)
    assert _counter("rapids_dist_total", result="dist") - d0 == 1
    assert _counter("mapreduce_plan_cache_total",
                    op="rapids_dist", result="miss") - m0 == 0
    assert _counter("mapreduce_plan_cache_total",
                    op="rapids_fusion", result="miss") - f0 == 0
    assert _counter("devcache_requests_total",
                    kind="rapids_group_frame", result="miss") - g0 == 0
    assert np.float64(first.as_num()).view(np.uint64) == \
        np.float64(warm.as_num()).view(np.uint64)


def test_assign_derives_home_resident_column(homed, sess):
    """A ``:=`` pipeline over a DistFrame yields a NEW chunk-homed frame
    on the same layout — same ESPC, same homes — without materializing
    either frame, and bit-identical to the interpreter's copy path."""
    _clouds, dist, _local = homed
    ref, got, dist_delta = _run_dist(
        sess, "(tmp= pda (:= pd (* (cols_py pd 0) 2) 1 _))")
    assert dist_delta >= 1
    out = got.value
    assert isinstance(out, DistFrame) and out._materialized is None
    src_lay, out_lay = dist.chunk_layout, out.chunk_layout
    assert list(out_lay["espc"]) == list(src_lay["espc"])
    assert [g["home_name"] for g in out_lay["groups"]] == \
        [g["home_name"] for g in src_lay["groups"]]
    assert dist._materialized is None
    assert_same_val(ref, got, ctx=":=")


def test_filter_reduce_pipeline_stays_home_resident(homed, sess):
    """filter → reduce over chunk homes: the mask and the survivor rows
    never leave their homes; only partials cross the wire."""
    prev = os.environ.get("H2O3_TPU_RAPIDS_FUSION")
    try:
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "0"
        ref = exec_rapids("(tmp= plf (rows pl (< (cols_py pl 0) 1)))", sess)
        ref2 = exec_rapids("(sumNA (cols_py plf 1))", sess)
        os.environ["H2O3_TPU_RAPIDS_FUSION"] = "1"
        d0 = _counter("rapids_dist_total", result="dist")
        got = exec_rapids("(tmp= pdf (rows pd (< (cols_py pd 0) 1)))", sess)
        got2 = exec_rapids("(sumNA (cols_py pdf 1))", sess)
        dist_delta = _counter("rapids_dist_total", result="dist") - d0
    finally:
        if prev is None:
            os.environ.pop("H2O3_TPU_RAPIDS_FUSION", None)
        else:
            os.environ["H2O3_TPU_RAPIDS_FUSION"] = prev
    # the mask region, the filter, and the trailing reduce all shipped
    assert dist_delta >= 3
    out = got.value
    assert isinstance(out, DistFrame) and out._materialized is None
    assert out.nrows == ref.value.nrows
    assert_same_val(ref, got, ctx="filtered frame")
    assert_same_val(ref2, got2, ctx="filtered reduce")


def test_derived_column_feeds_dist_hist_without_shipping(homed):
    """A ``:=``-derived home-resident column is readable by a subsequent
    distributed histogram fit with zero frame shipping: the source and
    derived frames stay unmaterialized and no gather-sized transfer
    happens (wire bytes stay far below the frame bytes)."""
    clouds, _dist, _local = homed
    text = _int_csv()
    fr = _parse_to_homes(clouds[0], "rapids_hist_df", text,
                         chunk_bytes=16384)
    s = Session()
    s.assign("hd", fr)
    d0 = _counter("rapids_dist_total", result="dist")
    out = exec_rapids("(tmp= hd2 (:= hd (* (cols_py hd 0) 3) 1 _))", s)
    assert _counter("rapids_dist_total", result="dist") - d0 >= 1
    derived = out.value
    assert isinstance(derived, DistFrame) and derived._materialized is None

    def _dist_fit(frame):
        w0 = _data_wire_bytes()
        fits0 = _counter("dist_hist_fits_total", mode="dist")
        model = GBM(GBMParameters(nbins=12, response_column="reg",
                                  ntrees=2, max_depth=3, min_rows=1.0,
                                  seed=11)).train(frame)
        assert model is not None
        assert _counter("dist_hist_fits_total", mode="dist") - fits0 == 1
        return _data_wire_bytes() - w0

    # baseline: the directly-parsed frame; then the derived frame — a
    # first-class chunk-homed citizen, it must cost no frame-sized extra
    wire_parsed = _dist_fit(fr)
    wire_derived = _dist_fit(derived)
    assert derived._materialized is None
    assert fr._materialized is None
    frame_bytes = 8.0 * derived.nrows * derived.ncols
    assert wire_derived < wire_parsed + frame_bytes / 2


def test_unfusible_falls_back_to_exact_gather(homed, sess):
    """Correctness never depends on fusibility: an expression the fusion
    pass cannot lower still answers, via the exact gather path."""
    _clouds, dist, _local = homed
    g0 = _counter("rapids_dist_total", result="gather")
    f0 = _counter("rapids_dist_total", result="fallback")
    ref, got, _delta = _run_dist(sess, "(tmp= pdu (as.factor (cols_py pd 0)))")
    assert_same_val(ref, got, ctx="as.factor")
    assert (_counter("rapids_dist_total", result="gather") - g0) + \
        (_counter("rapids_dist_total", result="fallback") - f0) >= 0
