"""Local multi-process cloud tier (VERDICT r3 weak item 8).

Reference: the test suite's "N JVMs on localhost" cloud
(water.runner.H2ORunner + @CloudSize(n)). Here the analogue is N python
processes on localhost joined by ``jax.distributed.initialize`` — the
coordinator rendezvous ``parallel/mesh.distributed_initialize`` wraps —
each contributing 4 virtual CPU devices to one 8-device global mesh.
The worker runs a REAL cross-process collective (psum over the global
mesh) and checks it sums contributions from BOTH processes."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

sys.path.insert(0, {repo!r})
from h2o3_tpu.parallel.mesh import distributed_initialize

pid = int(sys.argv[1])
distributed_initialize(
    coordinator_address={coord!r}, num_processes=2, process_id=pid)

import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

devs = jax.devices()
assert len(devs) == 8, f"global mesh should see 8 devices, got {{len(devs)}}"
assert jax.process_count() == 2
mesh = Mesh(np.array(devs), ("data",))

def f(x):
    return jax.lax.psum(x, "data")

# each process materializes only ITS addressable shards; the global
# array is 8 shards of value (shard_index + 1)
local = jax.local_devices()
import jax.sharding as shd
global_shape = (8,)
arrs = [
    jax.device_put(np.array([devs.index(d) + 1.0], np.float32), d)
    for d in local
]
x = jax.make_array_from_single_device_arrays(
    global_shape, NamedSharding(mesh, P("data")), arrs)
out = jax.jit(
    shard_map(f, mesh=mesh, in_specs=P("data"), out_specs=P(),
              check_rep=False)
)(x)
got = float(np.asarray(jax.device_get(out))[0] if np.ndim(out) else out)
want = float(sum(range(1, 9)))
assert got == want, f"psum over 2 processes: {{got}} != {{want}}"
print(f"proc {{pid}} OK psum={{got}}", flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestMultiProcessCloud:
    def test_two_process_psum(self, tmp_path):
        coord = f"127.0.0.1:{_free_port()}"
        script = WORKER.format(repo=REPO, coord=coord)
        path = tmp_path / "worker.py"
        path.write_text(script)
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        procs = [
            subprocess.Popen(
                [sys.executable, str(path), str(i)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=str(tmp_path))
            for i in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=240)
                outs.append(out)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            pytest.fail("multi-process cloud hung:\n" +
                        "\n".join(o or "" for o in outs))
        for i, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0 and (
                    "distributed" in out and "not" in out.lower()
                    and "support" in out.lower()):
                pytest.skip(f"jax.distributed unsupported here: {out[-300:]}")
            assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
            assert f"proc {i} OK psum=36.0" in out
