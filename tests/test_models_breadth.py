"""DeepLearning / KMeans / PCA / SVD / NaiveBayes / IsolationForest tests.

Reference analogue: per-algo JUnit tests in h2o-algos (SURVEY.md §4)."""

import numpy as np
import pytest
from sklearn import datasets
from sklearn.cluster import KMeans as SKKMeans
from sklearn.decomposition import PCA as SKPCA
from sklearn.naive_bayes import GaussianNB

from h2o3_tpu import Frame
from h2o3_tpu.models.deeplearning import DeepLearning
from h2o3_tpu.models.isolation_forest import IsolationForest
from h2o3_tpu.models.kmeans import KMeans
from h2o3_tpu.models.naive_bayes import NaiveBayes
from h2o3_tpu.models.pca import PCA, SVD


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture()
def blobs(rng):
    X, y = datasets.make_blobs(
        n_samples=1200, centers=3, n_features=4, random_state=7, cluster_std=1.2
    )
    return X, y


def test_deeplearning_classification(mesh, rng):
    n = 2000
    X = rng.normal(size=(n, 5))
    logit = 2 * X[:, 0] - X[:, 1] + X[:, 2] ** 2 - 1
    y = (rng.random(n) < 1 / (1 + np.exp(-logit)))
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(5)} | {"y": np.where(y, "a", "b")})
    m = DeepLearning(
        response_column="y", hidden=[32, 32], epochs=20, mini_batch_size=128, seed=5
    ).train(fr)
    assert m.training_metrics.auc > 0.85, m.training_metrics
    pred = m.predict(fr)
    assert pred.names == ["predict", "pa", "pb"]


def test_deeplearning_regression(mesh, rng):
    n = 2000
    X = rng.normal(size=(n, 4))
    y = np.sin(X[:, 0]) * 2 + X[:, 1] + 0.1 * rng.normal(size=n)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)} | {"y": y})
    m = DeepLearning(
        response_column="y", hidden=[64, 64], epochs=30, mini_batch_size=128, seed=5
    ).train(fr)
    assert m.training_metrics.r2 > 0.8, m.training_metrics


def test_deeplearning_autoencoder(mesh, rng):
    n = 1000
    X = rng.normal(size=(n, 6))
    X[::50] += 8.0  # planted anomalies
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(6)})
    m = DeepLearning(autoencoder=True, hidden=[3], epochs=30, mini_batch_size=128, seed=5).train(fr)
    scores = m.anomaly(fr)
    planted = scores[::50].mean()
    normal = np.delete(scores, np.arange(0, n, 50)).mean()
    assert planted > normal * 2


def test_kmeans_recovers_blobs(mesh, blobs):
    X, y = blobs
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)})
    m = KMeans(k=3, max_iterations=20, seed=3).train(fr)
    assert m.iterations >= 1
    assert m.tot_withinss > 0 and m.betweenss > 0
    sk = SKKMeans(n_clusters=3, n_init=5, random_state=3).fit(
        (X - X.mean(0)) / X.std(0, ddof=1)
    )
    assert m.tot_withinss == pytest.approx(sk.inertia_, rel=0.05)
    assign = m._predict_raw(fr).astype(int)
    # cluster agreement up to permutation: each true blob maps to one cluster
    from scipy.stats import mode

    agree = sum((assign[y == c] == mode(assign[y == c]).mode).mean() for c in range(3)) / 3
    assert agree > 0.95


def test_kmeans_predict_and_sizes(mesh, blobs):
    X, _ = blobs
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)})
    m = KMeans(k=3, seed=3).train(fr)
    assert int(m.size.sum()) == fr.nrows
    assert m.centers.shape == (3, 4)


def test_pca_matches_sklearn(mesh, rng):
    X = rng.normal(size=(500, 6)) @ rng.normal(size=(6, 6))
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(6)})
    m = PCA(k=3, transform="demean").train(fr)
    sk = SKPCA(n_components=3).fit(X)
    np.testing.assert_allclose(m.std_deviation, np.sqrt(sk.explained_variance_), rtol=1e-3)
    np.testing.assert_allclose(m.pve, sk.explained_variance_ratio_, rtol=1e-3)
    # eigenvectors equal up to sign
    for i in range(3):
        dot = abs(float(np.dot(m.eigenvectors[:, i], sk.components_[i])))
        assert dot == pytest.approx(1.0, abs=1e-3)


def test_svd_singular_values(mesh, rng):
    X = rng.normal(size=(400, 5))
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(5)})
    m = SVD(nv=3, transform="demean").train(fr)
    want = np.linalg.svd(X - X.mean(0), compute_uv=False)[:3]
    np.testing.assert_allclose(m.d, want, rtol=1e-3)


def test_naive_bayes_matches_sklearn_gaussian(mesh, rng):
    n = 2000
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] + X[:, 1] + rng.normal(0, 0.5, n) > 0).astype(int)
    fr = Frame.from_dict(
        {f"x{i}": X[:, i] for i in range(3)} | {"y": np.where(y > 0, "p", "n")}
    )
    m = NaiveBayes(response_column="y").train(fr)
    sk = GaussianNB().fit(X, y)
    ours = m._predict_raw(fr)[:, 1]
    theirs = sk.predict_proba(X)[:, 1]
    # same model family: probabilities should correlate near-perfectly
    assert np.corrcoef(ours, theirs)[0, 1] > 0.999
    assert m.training_metrics.auc > 0.9


def test_naive_bayes_categorical_laplace(mesh, rng):
    n = 1500
    g = rng.integers(0, 4, n)
    y = (rng.random(n) < np.array([0.1, 0.4, 0.6, 0.9])[g]).astype(int)
    fr = Frame.from_dict(
        {"g": np.array(["a", "b", "c", "d"])[g], "y": np.where(y > 0, "t", "f")}
    )
    m = NaiveBayes(response_column="y", laplace=1.0).train(fr)
    assert m.training_metrics.auc > 0.7
    tab = m.cat_probs["g"]
    np.testing.assert_allclose(tab.sum(axis=1), 1.0, atol=1e-9)


def test_isolation_forest_finds_outliers(mesh, rng):
    n = 2000
    X = rng.normal(size=(n, 4))
    X[:20] = X[:20] * 6 + 10  # planted outliers
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)})
    m = IsolationForest(ntrees=60, seed=11).train(fr)
    s = m._predict_raw(fr)
    assert s[:20].mean() > s[20:].mean() + 0.1
    # top-30 by score should include most planted outliers
    top = np.argsort(-s)[:30]
    assert (top < 20).sum() >= 15


def test_kmeans_nondivisible_rows_no_nan(mesh, rng):
    """Regression: pad rows must not poison withinss with NaN (review finding)."""
    X = rng.normal(size=(1201, 3))
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)})
    m = KMeans(k=3, seed=1).train(fr)
    assert np.isfinite(m.tot_withinss) and np.isfinite(m.withinss).all()
    assert int(m.size.sum()) == 1201


def test_deeplearning_tiny_frame_big_batch(mesh, rng):
    """Regression: n < mini_batch_size must keep static batch shape (review finding)."""
    X = rng.normal(size=(99, 3))
    y = (X[:, 0] > 0)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": np.where(y, "a", "b")})
    m = DeepLearning(response_column="y", hidden=[8], epochs=2, seed=1).train(fr)
    assert m.training_metrics is not None


def test_deeplearning_momentum_ramp(mesh, rng):
    X = rng.normal(size=(500, 3))
    y = X[:, 0] * 2 + rng.normal(0, 0.1, 500)
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(3)} | {"y": y})
    m = DeepLearning(
        response_column="y", hidden=[16], epochs=15, adaptive_rate=False,
        rate=0.01, momentum_start=0.5, momentum_stable=0.9, mini_batch_size=64, seed=1,
    ).train(fr)
    assert m.training_metrics.r2 > 0.5


def test_autoencoder_predict_reconstruction_frame(mesh, rng):
    X = rng.normal(size=(300, 4))
    fr = Frame.from_dict({f"x{i}": X[:, i] for i in range(4)})
    m = DeepLearning(autoencoder=True, hidden=[2], epochs=5, seed=1).train(fr)
    rec = m.predict(fr)
    assert rec.ncols == 4 and rec.nrows == 300
    assert all(n.startswith("reconstr_") for n in rec.names)


def test_pca_demean_predict_consistency(mesh, rng):
    """demean/descale statistics from training must be re-applied at
    scoring: projecting the TRAINING frame must equal projecting the
    transformed design matrix the eigenvectors were fit on."""
    from h2o3_tpu.frame.frame import Column, Frame
    from h2o3_tpu.models.data_info import expand_matrix
    from h2o3_tpu.models.pca import PCA

    X = rng.normal(size=(300, 4)) + 5.0  # offset so demean matters
    X[:, 0] *= 10.0  # sd far from 1 so descale matters too
    fr = Frame([Column(f"x{i}", X[:, i]) for i in range(4)])
    for transform in ("demean", "descale"):
        m = PCA(k=2, transform=transform, seed=1).train(fr)
        Xe, _ = expand_matrix(m.data_info, fr, dtype=np.float32)
        if m.transform_sub is not None:
            Xe = Xe - m.transform_sub
        if m.transform_mul is not None:
            Xe = Xe * m.transform_mul
        want = Xe @ m.eigenvectors
        got = m._predict_raw(fr)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        # regression guard for the actual bug: raw projection (no
        # transform) must NOT match when the transform shifts the data
        raw = (expand_matrix(m.data_info, fr, dtype=np.float32)[0]
               @ m.eigenvectors)
        assert not np.allclose(got, raw, atol=1e-3)
