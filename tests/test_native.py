"""Native runtime components: parity against the pure-python/numpy paths.

Covers native/csv.cpp (CsvParser.java hot-loop analogue), native/codecs.cpp
(C*Chunk codec lineup + RadixOrder.java-style LSD radix argsort), and the
frame binary persist layer that rides the codecs
(water/fvec/persist/FramePersist.java analogue).

Every native path has a same-answer oracle here; if the shared library can't
build, the library-level tests skip but the fallbacks still run.
"""

import numpy as np
import pytest

from h2o3_tpu import native
from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.frame.parse import ParseSetup, _native_numeric_fast, parse_csv
from h2o3_tpu.frame import persist
from h2o3_tpu.rapids.merge import lexsort, stable_argsort

HAVE = native.available()
needs_native = pytest.mark.skipif(not HAVE, reason="native lib unavailable")


# ---------------------------------------------------------------------------
# csv fast path


@needs_native
def test_native_csv_matches_python_parse():
    rng = np.random.default_rng(0)
    rows = ["a,b,c"]
    for i in range(500):
        cells = []
        for j in range(3):
            r = rng.random()
            if r < 0.1:
                cells.append("NA")
            elif r < 0.2:
                cells.append(f"{rng.normal():.6e}")  # exponent form -> strtod path
            elif r < 0.3:
                cells.append(str(int(rng.integers(-1000, 1000))))
            else:
                cells.append(f"{rng.normal():.4f}")
        rows.append(",".join(cells))
    rows.append("1.5,2.5")  # short row: trailing cols -> NA
    text = "\n".join(rows) + "\n"

    fr_fast = parse_csv(text)
    assert fr_fast.nrows == 501

    # force the python path by making the fast-path precondition fail
    import h2o3_tpu.frame.parse as parse_mod

    orig = parse_mod._native_numeric_fast
    parse_mod._native_numeric_fast = lambda *a, **k: None
    try:
        fr_py = parse_csv(text)
    finally:
        parse_mod._native_numeric_fast = orig

    assert fr_fast.names == fr_py.names
    for name in fr_fast.names:
        np.testing.assert_array_equal(
            fr_fast.col(name).data, fr_py.col(name).data
        )


def _python_path_parse(text):
    """parse_csv with the native fast path stubbed out — the oracle."""
    import h2o3_tpu.frame.parse as parse_mod

    orig = parse_mod._native_numeric_fast
    parse_mod._native_numeric_fast = lambda *a, **k: None
    try:
        return parse_csv(text)
    finally:
        parse_mod._native_numeric_fast = orig


def _assert_same_frames(a, b):
    assert a.names == b.names
    assert a.nrows == b.nrows
    for name in a.names:
        np.testing.assert_array_equal(a.col(name).data, b.col(name).data)


@needs_native
def test_native_csv_crlf_matches_python():
    """CRLF line endings: native nrows (newline count) and token \r
    stripping must both agree with python's record splitting."""
    rows = ["a,b,c"] + [
        f"{i}.25,{-i},{'NA' if i % 7 == 0 else i * 2}" for i in range(300)
    ]
    text = "\r\n".join(rows) + "\r\n"
    from h2o3_tpu.frame.parse import parse_setup

    setup = parse_setup(text)
    assert _native_numeric_fast(text, setup) is not None  # path engages
    fr = parse_csv(text)
    assert fr.nrows == 300
    _assert_same_frames(fr, _python_path_parse(text))


@needs_native
def test_native_declines_lone_cr_line_endings():
    """Old-Mac lone-\r terminators split records in python (splitlines)
    but not in a byte-level \n scan: the fast path must decline."""
    text = "a,b\r1,2\r3,4\r"
    from h2o3_tpu.frame.parse import parse_setup

    setup = parse_setup(text)
    assert _native_numeric_fast(text, setup) is None
    fr = parse_csv(text)
    assert fr.nrows == 2  # python path splits on \r
    np.testing.assert_array_equal(fr.col("a").data, [1.0, 3.0])


@needs_native
def test_native_fast_path_engages_with_default_na_strings():
    """'NaN'/'nan' in the default NA list parse to NaN on BOTH paths, so
    they must not disable the fast path (only non-NaN numeric NA tokens
    like '999' genuinely diverge)."""
    text = "a,b\n1.5,NA\n2.5,3.5\nNaN,4.5\n"
    from h2o3_tpu.frame.parse import parse_setup

    setup = parse_setup(text)
    assert _native_numeric_fast(text, setup) is not None
    _assert_same_frames(parse_csv(text), _python_path_parse(text))
    # a numeric NA token still declines
    setup999 = parse_setup(text, na_strings=("", "999"))
    assert _native_numeric_fast(text, setup999) is None


@needs_native
def test_native_underscore_scan_is_body_only():
    """A header named col_1 must not disable the fast path (the underscore
    gate protects float('1_000') semantics, which only body bytes can
    trigger) — while an underscore IN the body still declines."""
    from h2o3_tpu.frame.parse import parse_setup

    good = "col_1,col_2\n1,2\n3,4\n"
    setup = parse_setup(good)
    assert _native_numeric_fast(good, setup) is not None
    _assert_same_frames(parse_csv(good), _python_path_parse(good))

    bad = "col_1,col_2\n1_000,2\n3,4\n"
    assert _native_numeric_fast(bad, parse_setup(bad)) is None
    fr = parse_csv(bad)
    assert fr.col("col_1").data[0] == 1000.0  # python float('1_000')


@needs_native
def test_native_fast_path_declines_non_numeric():
    setup = ParseSetup(
        separator=",", header=True, column_names=["a", "b"],
        column_types=[ColType.NUM, ColType.CAT],
    )
    assert _native_numeric_fast("a,b\n1,x\n", setup) is None
    # quoted text must decline too
    setup2 = ParseSetup(
        separator=",", header=True, column_names=["a"],
        column_types=[ColType.NUM],
    )
    assert _native_numeric_fast('a\n"1"\n', setup2) is None


# ---------------------------------------------------------------------------
# codecs


CODEC_CASES = [
    np.full(100, 7.25),                                   # CONST
    np.arange(100, dtype=np.float64),                     # INT8 span
    np.arange(100, dtype=np.float64) * 300,               # INT16 span
    np.arange(100, dtype=np.float64) * 1e6,               # INT32 span
    np.round(np.linspace(-3, 3, 100), 2),                 # SCALED16
    np.concatenate([np.zeros(400), [1.5, -2.25]]),        # SPARSE
    np.array([0.1 + 0.2, 0.3, 1e-17, np.pi]),             # RAW64 (not scalable)
    np.array([np.nan, 1.0, np.nan, 2.0]),                 # NAs in ints
    np.full(10, np.nan),                                  # all-NA
]


@needs_native
@pytest.mark.parametrize("x", CODEC_CASES, ids=range(len(CODEC_CASES)))
def test_codec_roundtrip_bit_exact(x):
    blob = native.codec_encode(x)
    out = native.codec_decode(blob)
    assert np.array_equal(out, x, equal_nan=True), f"tag={blob[0]}"
    # python decoder reads native encodings (portable load path)
    out_py = persist.codec_decode(blob)
    assert np.array_equal(out_py, x, equal_nan=True)


@needs_native
def test_codec_compresses_small_ints():
    x = np.asarray(np.random.default_rng(0).integers(0, 50, 10_000), dtype=np.float64)
    blob = native.codec_encode(x)
    assert len(blob) < 10_000 * 2  # ~1 byte/row + header, vs 8 raw


def test_python_fallback_roundtrip():
    x = np.array([1.5, np.nan, -2.0])
    blob = persist.codec_encode(x)  # native or RAW64 fallback
    out = persist.codec_decode(blob)
    assert np.array_equal(out, x, equal_nan=True)


# ---------------------------------------------------------------------------
# radix argsort / lexsort


@needs_native
def test_radix_argsort_float_matches_numpy():
    rng = np.random.default_rng(1)
    x = rng.normal(size=10_000)
    x[rng.random(10_000) < 0.05] = np.nan
    x[0] = -np.inf
    x[1] = np.inf
    got = native.radix_argsort(x)
    want = np.argsort(x, kind="stable")
    np.testing.assert_array_equal(got, want)


@needs_native
def test_radix_argsort_int64_negative():
    rng = np.random.default_rng(2)
    x = rng.integers(-(10**12), 10**12, 5000)
    np.testing.assert_array_equal(
        native.radix_argsort(x), np.argsort(x, kind="stable")
    )


def test_stable_argsort_and_lexsort_match_numpy():
    rng = np.random.default_rng(3)
    # above the radix threshold so the native path engages when available
    a = rng.integers(0, 50, 10_000).astype(np.int64)
    b = rng.integers(0, 7, 10_000).astype(np.int64)
    np.testing.assert_array_equal(stable_argsort(a), np.argsort(a, kind="stable"))
    np.testing.assert_array_equal(lexsort([a, b]), np.lexsort((a, b)))
    np.testing.assert_array_equal(lexsort([b, a]), np.lexsort((b, a)))


# ---------------------------------------------------------------------------
# frame persist (the codecs' production caller)


def test_frame_save_load_roundtrip(tmp_path):
    n = 200
    rng = np.random.default_rng(4)
    num = rng.normal(size=n)
    num[:5] = np.nan
    ints = rng.integers(0, 9, n).astype(np.float64)
    codes = rng.integers(-1, 3, n).astype(np.int32)
    strs = np.array(
        [None if i % 17 == 0 else f"s{i % 5}" for i in range(n)], dtype=object
    )
    fr = Frame(
        [
            Column("num", num, ColType.NUM),
            Column("ints", ints, ColType.NUM),
            Column("cat", codes, ColType.CAT, ["a", "b", "c"]),
            Column("s", strs, ColType.STR),
            Column("t", np.abs(num) * 1e6, ColType.TIME),
        ],
        key="roundtrip.hex",
    )
    p = tmp_path / "fr.h2f"
    persist.save_frame(fr, p)
    back = persist.load_frame(p)
    assert back.key == "roundtrip.hex"
    assert back.names == fr.names
    for name in fr.names:
        c0, c1 = fr.col(name), back.col(name)
        assert c0.type == c1.type
        assert c0.domain == c1.domain
        if c0.type is ColType.STR:
            assert list(c0.data) == list(c1.data)
        else:
            np.testing.assert_array_equal(c0.data, c1.data)
