"""Telemetry: metrics registry, Prometheus exposition, span-correlated
tracing, the observability rings, and the /3/Metrics REST surface.

The registry under test in the unit half is a private ``Registry()``
instance; the REST half reads the process-global ``REGISTRY`` through
deltas only (the suite's other tests are feeding it concurrently)."""

import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.parse
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.util import log as L
from h2o3_tpu.util import telemetry as T
from h2o3_tpu.util import timeline
from h2o3_tpu.util.profiler import collect
from h2o3_tpu.util.telemetry import Registry

# REST-half tests share server/frame/model keys module-wide; the
# module-level sweeper reclaims them at module end
pytestmark = pytest.mark.leaks_keys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry unit tests (private Registry instances)


class TestCounters:
    def test_inc_and_value(self):
        r = Registry()
        c = r.counter("requests_total", "reqs", labels=("route",))
        c.inc(route="/a")
        c.inc(2, route="/a")
        c.inc(route="/b")
        assert c.value(route="/a") == 3
        assert c.value(route="/b") == 1
        assert c.total() == 4

    def test_label_mismatch_raises(self):
        r = Registry()
        c = r.counter("x_total", labels=("a",))
        with pytest.raises(ValueError):
            c.inc(b=1)
        with pytest.raises(ValueError):
            c.inc()  # missing label

    def test_counters_only_go_up(self):
        r = Registry()
        c = r.counter("y_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_get_or_create_and_type_clash(self):
        r = Registry()
        c1 = r.counter("same", "h", labels=("l",))
        c2 = r.counter("same", "h", labels=("l",))
        assert c1 is c2
        with pytest.raises(ValueError):
            r.gauge("same")  # type clash
        with pytest.raises(ValueError):
            r.counter("same", labels=("other",))  # label clash

    def test_histogram_bucket_clash(self):
        r = Registry()
        h = r.histogram("h_seconds", buckets=(0.1, 1.0))
        assert r.histogram("h_seconds") is h  # default buckets = inherit
        assert r.histogram("h_seconds", buckets=(0.1, 1.0)) is h
        with pytest.raises(ValueError):
            r.histogram("h_seconds", buckets=(10.0, 60.0))

    def test_bad_names_rejected(self):
        r = Registry()
        with pytest.raises(ValueError):
            r.counter("bad-name")
        with pytest.raises(ValueError):
            r.counter("ok_total", labels=("bad-label",))


class TestGaugesAndHistograms:
    def test_gauge_set_inc_dec(self):
        r = Registry()
        g = r.gauge("keys")
        g.set(10)
        g.inc(5)
        g.dec(3)
        assert g.value() == 12

    def test_histogram_buckets_cumulative(self):
        r = Registry()
        h = r.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        snap = h.snapshot()["series"][0]
        assert snap["count"] == 5
        assert snap["bucket_counts"] == [1, 2, 1]  # per-bucket, 50.0 overflows
        assert snap["sum"] == pytest.approx(56.05)

    def test_histogram_count_by_label(self):
        r = Registry()
        h = r.histogram("fit_seconds", labels=("algo",), buckets=(1.0,))
        h.observe(0.5, algo="gbm")
        h.observe(2.5, algo="gbm")
        assert h.count(algo="gbm") == 2
        assert h.count(algo="glm") == 0
        assert h.total_count() == 2


#: one exposition line: name{labels} value  (labels optional)
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'            # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'  # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'  # more labels
    r' (-?[0-9.e+-]+|\+Inf|-Inf|NaN)$'
)


def assert_valid_exposition(text: str) -> None:
    """Line-check Prometheus text exposition v0.0.4."""
    assert text.endswith("\n")
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$", parts[2]), line
            if line.startswith("# TYPE "):
                assert parts[3] in ("counter", "gauge", "histogram"), line
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"


class TestPrometheusExposition:
    def test_help_type_and_samples(self):
        r = Registry()
        r.counter("reqs_total", "requests served", labels=("route",)).inc(
            route="/3/Cloud")
        r.gauge("keys", "store size").set(7)
        r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0)).observe(0.5)
        text = r.prometheus()
        assert_valid_exposition(text)
        assert "# HELP reqs_total requests served" in text
        assert "# TYPE reqs_total counter" in text
        assert "# TYPE keys gauge" in text
        assert "# TYPE lat_seconds histogram" in text
        assert 'reqs_total{route="/3/Cloud"} 1' in text
        assert "keys 7" in text

    def test_label_escaping(self):
        r = Registry()
        c = r.counter("odd_total", labels=("p",))
        c.inc(p='we"ird\\path\nline')
        text = r.prometheus()
        assert_valid_exposition(text)
        assert r'odd_total{p="we\"ird\\path\nline"} 1' in text

    def test_histogram_contract(self):
        r = Registry()
        h = r.histogram("h_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 99.0):
            h.observe(v)
        text = r.prometheus()
        assert_valid_exposition(text)
        # cumulative buckets; +Inf bucket equals _count
        assert 'h_seconds_bucket{le="0.1"} 1' in text
        assert 'h_seconds_bucket{le="1"} 2' in text
        assert 'h_seconds_bucket{le="+Inf"} 3' in text
        assert "h_seconds_count 3" in text
        assert "h_seconds_sum 99.55" in text

    def test_empty_registry_is_empty_text(self):
        assert Registry().prometheus() == ""

    def test_json_snapshot_is_json_able(self):
        r = Registry()
        r.counter("a_total", labels=("x",)).inc(x="1")
        r.histogram("b_seconds", buckets=(1.0,)).observe(0.5)
        json.dumps(r.snapshot())  # must not raise

    def test_summary_collapses_labels(self):
        r = Registry()
        c = r.counter("c_total", labels=("x",))
        c.inc(3, x="a")
        c.inc(4, x="b")
        r.histogram("d_seconds", buckets=(1.0,)).observe(0.1)
        s = r.summary()
        assert s["c_total"] == 7
        assert s["d_seconds_count"] == 1


# ---------------------------------------------------------------------------
# spans + timeline correlation


class TestSpans:
    def test_nesting_threads_trace_and_parent(self):
        with T.Span("outer") as outer:
            assert T.current_trace_id() == outer.trace_id
            with T.Span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
        assert T.current_span() is None

    def test_span_records_enriched_timeline_event(self):
        before = timeline.total_events()
        with T.Span("unit_span", tag="x") as sp:
            pass
        evts = [e for e in timeline.snapshot(50)
                if e.get("kind") == "unit_span" and e.get("seq", 0) > before]
        assert len(evts) == 1
        e = evts[0]
        assert e["trace_id"] == sp.trace_id
        assert e["span_id"] == sp.span_id
        assert e["parent_id"] is None
        assert e["ok"] is True
        assert e["tag"] == "x"
        assert e["duration_ms"] >= 0

    def test_plain_record_under_span_inherits_trace(self):
        with T.Span("enclosing") as sp:
            timeline.record("plain_evt", foo=1)
        evts = [e for e in timeline.snapshot(50)
                if e.get("kind") == "plain_evt"]
        assert evts and evts[-1]["trace_id"] == sp.trace_id

    def test_exception_marks_not_ok(self):
        with pytest.raises(RuntimeError):
            with T.Span("boom_span"):
                raise RuntimeError("x")
        evts = [e for e in timeline.snapshot(50)
                if e.get("kind") == "boom_span"]
        assert evts and evts[-1]["ok"] is False

    def test_spans_are_thread_local(self):
        seen = {}

        def other():
            seen["trace"] = T.current_trace_id()

        with T.Span("main_span"):
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["trace"] is None


# ---------------------------------------------------------------------------
# the rings (satellite: previously-untested timeline/log paths)


class TestTimelineRing:
    def test_clear_total_and_rollover(self):
        timeline.clear()
        assert timeline.total_events() == 0
        for i in range(timeline.CAPACITY + 10):
            timeline.record("spin", i=i)
        # the counter keeps counting past capacity; the ring holds CAPACITY
        assert timeline.total_events() == timeline.CAPACITY + 10
        snap = timeline.snapshot(timeline.CAPACITY * 2)
        assert len(snap) == timeline.CAPACITY
        # oldest events rolled off; the newest survived, in order
        assert snap[0]["i"] == 10
        assert snap[-1]["i"] == timeline.CAPACITY + 9
        seqs = [e["seq"] for e in snap]
        assert seqs == sorted(seqs)
        timeline.clear()
        assert timeline.total_events() == 0
        assert timeline.snapshot() == []

    def test_snapshot_n_limits(self):
        timeline.clear()
        for i in range(20):
            timeline.record("evt", i=i)
        assert len(timeline.snapshot(5)) == 5
        assert [e["i"] for e in timeline.snapshot(3)] == [17, 18, 19]
        # 0/negative must mean "no events", not "[-0:] is everything"
        assert timeline.snapshot(0) == []
        assert timeline.snapshot(-5) == []
        timeline.clear()


class TestLogRing:
    def test_recent_ordering_and_limit(self):
        logger = L.get_logger("telemetry_test")
        marks = [f"ring-order-{i}" for i in range(5)]
        for m in marks:
            logger.info(m)
        lines = L.recent(1000)
        idx = [next(i for i, ln in enumerate(lines) if m in ln) for m in marks]
        assert idx == sorted(idx), "ring must preserve emit order"
        assert any(marks[-1] in ln for ln in L.recent(1))

    def test_concurrent_emit_and_recent(self):
        # the satellite fix: recent() copies under the same lock emit
        # appends under — hammer both concurrently and expect no error
        logger = L.get_logger("telemetry_race")
        errs = []

        def writer():
            try:
                for i in range(300):
                    logger.info("race %d", i)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        def reader():
            try:
                for _ in range(300):
                    L.recent(50)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=f)
                   for f in (writer, writer, reader, reader)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs


class TestProfiler:
    def test_duration_not_overshot(self):
        t0 = time.monotonic()
        collect(duration_s=0.2, interval_s=0.05)
        # pre-fix the tail sleep overshot by a full interval every time
        assert time.monotonic() - t0 < 0.2 + 0.1

    def test_exclude_thread_name_filter(self):
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(range(1000))

        t = threading.Thread(target=busy, name="noisy-housekeeper",
                             daemon=True)
        t.start()
        try:
            with_noise = collect(duration_s=0.15, interval_s=0.01)
            filtered = collect(duration_s=0.15, interval_s=0.01,
                               exclude=r"^noisy-")
        finally:
            stop.set()
            t.join()
        flat = lambda prof: ";".join(
            ";".join(s["stacktrace"]) for s in prof)  # noqa: E731
        assert "busy" in flat(with_noise)
        assert "busy" not in flat(filtered)

    def test_pct_uses_sample_count(self):
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                time.sleep(0.001)

        t = threading.Thread(target=busy, name="pct-probe", daemon=True)
        t.start()
        try:
            prof = collect(duration_s=0.15, interval_s=0.01)
        finally:
            stop.set()
            t.join()
        assert prof, "at least one stack must be sampled"
        # pct is per-sweep share: no single stack can exceed 100
        assert all(0 <= s["pct"] <= 100.0 for s in prof)


# ---------------------------------------------------------------------------
# REST surface + end-to-end acceptance


@pytest.fixture(scope="module")
def server():
    from h2o3_tpu.api import start_server

    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None, raw=False):
    body = json.dumps(data).encode() if data is not None else None
    headers = {"Content-Type": "application/json"} if data else {}
    req = urllib.request.Request(
        server.url + path, data=body, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


CSV = "x,y\n" + "\n".join(f"{i % 7},{(i * 3) % 5}" for i in range(64)) + "\n"


class TestMetricsOverRest:
    def test_acceptance_end_to_end(self, server):
        """ISSUE acceptance: one REST request + one map_reduce + one fit ->
        nonzero rest_requests_total / mapreduce_dispatch_total / a
        model_fit_seconds observation, and the fit's timeline events share
        one trace_id."""
        import jax.numpy as jnp

        from h2o3_tpu.compute.mapreduce import FrameTable, map_reduce
        from h2o3_tpu.keyed import DKV

        st, up = _req(server, "POST", "/3/PostFile", {"data": CSV})
        assert st == 200
        st, _ = _req(server, "POST", "/3/Parse", {
            "source_frames": [up["destination_frame"]],
            "destination_frame": "tele.hex"})
        assert st == 200
        st, out = _req(server, "POST", "/3/ModelBuilders/glm",
                       {"training_frame": "tele.hex", "response_column": "y"})
        assert st == 200, out

        tbl = FrameTable.from_frame(DKV.get("tele.hex"))
        map_reduce(
            lambda cols, mask: jnp.sum(jnp.where(mask, cols["x"], 0.0)), tbl)

        st, m = _req(server, "GET", "/3/Metrics")
        assert st == 200
        metrics = m["metrics"]
        rest_total = sum(
            s["value"] for s in metrics["rest_requests_total"]["series"])
        assert rest_total > 0
        mr_total = sum(
            s["value"] for s in metrics["mapreduce_dispatch_total"]["series"])
        assert mr_total > 0
        fit_series = metrics["model_fit_seconds"]["series"]
        assert any(s["labels"]["algo"] == "glm" and s["count"] > 0
                   for s in fit_series)
        # the jit cache meter attributed every dispatch one way or the other
        jit_series = metrics["mapreduce_jit_cache_total"]["series"]
        assert sum(s["value"] for s in jit_series) >= mr_total

        # trace correlation: the glm train event and its enclosing REST
        # request event carry the same trace_id
        st, tl = _req(server, "GET", "/3/Timeline?count=5000")
        assert st == 200
        trains = [e for e in tl["events"]
                  if e.get("kind") == "train" and e.get("algo") == "glm"]
        assert trains, "fit must land a train event in the timeline"
        evt = trains[-1]
        assert evt.get("trace_id")
        shared = [e["kind"] for e in tl["events"]
                  if e.get("trace_id") == evt["trace_id"]]
        assert "rest" in shared and "train" in shared

    def test_prometheus_exposition_is_valid(self, server):
        st, body = _req(server, "GET", "/3/Metrics/prometheus", raw=True)
        assert st == 200
        text = body.decode()
        assert_valid_exposition(text)
        assert "# TYPE rest_requests_total counter" in text
        assert "# TYPE model_fit_seconds histogram" in text
        # a scrape is accounted before its response flushes, so the SECOND
        # scrape must carry the first one's route label
        st, body2 = _req(server, "GET", "/3/Metrics/prometheus", raw=True)
        assert re.search(
            r'rest_requests_total\{[^}]*route="/3/Metrics/prometheus"[^}]*\} '
            r"[1-9]", body2.decode())
        # histograms expose the full contract
        assert re.search(r'model_fit_seconds_bucket\{[^}]*le="\+Inf"\} \d+',
                         text)
        assert re.search(r"model_fit_seconds_count(\{[^}]*\})? \d+", text)

    def test_metrics_route_labels_are_templates(self, server):
        # hit a parameterized route, then confirm the label is the {name}
        # template, not the raw path (cardinality control)
        _req(server, "GET", "/3/Frames/no_such_frame_xyz")
        st, m = _req(server, "GET", "/3/Metrics")
        routes = {s["labels"]["route"]
                  for s in m["metrics"]["rest_requests_total"]["series"]}
        assert "/3/Frames/{frame_id}" in routes
        assert all("no_such_frame_xyz" not in r for r in routes)

    def test_unmatched_path_collapses(self, server):
        _req(server, "GET", "/3/TotallyNot/a/route")
        st, m = _req(server, "GET", "/3/Metrics")
        routes = {s["labels"]["route"]
                  for s in m["metrics"]["rest_requests_total"]["series"]}
        assert "(unmatched)" in routes
        assert all("TotallyNot" not in r for r in routes)

    def test_cloud_carries_telemetry_summary(self, server):
        st, out = _req(server, "GET", "/3/Cloud")
        assert st == 200
        tel = out["telemetry"]
        assert tel["rest_requests_total"] > 0
        assert "dkv_keys" in tel and "jit_compiles_total" in tel

    def test_timeline_count_and_n_params(self, server):
        for i in range(12):
            timeline.record("param_probe", i=i)
        st, out = _req(server, "GET", "/3/Timeline?count=5")
        assert st == 200 and len(out["events"]) == 5
        st, out = _req(server, "GET", "/3/Timeline?n=3")
        assert st == 200 and len(out["events"]) == 3
        # count wins when both are passed (count is the documented name)
        st, out = _req(server, "GET", "/3/Timeline?count=4&n=9")
        assert st == 200 and len(out["events"]) == 4
        assert out["total_events"] >= 12

    def test_logs_ring_live_from_startup(self, server):
        # server.start() ran log.init(): REST traffic logs must be in the
        # ring without any client having touched the log module first
        st, out = _req(server, "GET", "/3/Logs?count=10000")
        assert st == 200
        assert any("GET /3/" in ln for ln in out["lines"])


class TestCheckTelemetryScript:
    def test_lint_passes(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "scripts",
                                          "check_telemetry.py")],
            capture_output=True, text=True, timeout=240,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr
        assert "OK" in proc.stdout
