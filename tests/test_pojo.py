"""POJO codegen: standalone C/Java scoring source (VERDICT r3 item 6).

Reference: hex/tree/TreeJCodeGen.java, water/codegen/, the
/3/Models.java route. The C emitter is compiled with the image's real
gcc and executed via ctypes; predictions must match the in-framework
predict path (the reference's POJO-vs-model parity contract,
testPojoConsistency)."""

import ctypes
import os
import subprocess
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _frame(rng, n=500, nclass=2):
    X = rng.normal(size=(n, 4))
    cat = rng.integers(0, 3, size=n).astype(np.int32)
    logit = X[:, 0] - 0.8 * X[:, 1] + 0.5 * cat
    if nclass == 2:
        y = (logit + rng.normal(size=n) * 0.3 > 0).astype(np.int32)
        ycol = Column("y", y, ColType.CAT, ["n", "p"])
    elif nclass > 2:
        y = np.clip(np.digitize(logit, [-1.0, 1.0]), 0, 2).astype(np.int32)
        ycol = Column("y", y, ColType.CAT, ["a", "b", "c"])
    else:
        ycol = Column("y", logit + rng.normal(size=n) * 0.1)
    cols = [Column(f"x{i}", X[:, i]) for i in range(4)]
    cols.append(Column("c", cat, ColType.CAT, ["u", "v", "w"]))
    cols.append(ycol)
    fr = Frame(cols)
    # sprinkle NAs so default-direction routing is exercised
    xs = fr.col("x0").data
    xs[rng.random(n) < 0.05] = np.nan
    return fr


def _compile(src: str, tmp_path, name: str):
    c_path = tmp_path / f"{name}.c"
    so_path = tmp_path / f"{name}.so"
    c_path.write_text(src)
    proc = subprocess.run(
        ["gcc", "-O2", "-shared", "-fPIC", "-o", str(so_path), str(c_path),
         "-lm"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return ctypes.CDLL(str(so_path))


def _tree_score_all(lib, X32: np.ndarray, n_out: int) -> np.ndarray:
    lib.score.argtypes = [ctypes.POINTER(ctypes.c_float),
                          ctypes.POINTER(ctypes.c_double)]
    out = np.zeros((X32.shape[0], n_out))
    buf = np.zeros(n_out, dtype=np.float64)
    for i in range(X32.shape[0]):
        row = np.ascontiguousarray(X32[i], dtype=np.float32)
        lib.score(row.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                  buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        out[i] = buf
    return out


class TestTreePojoC:
    @pytest.mark.parametrize("algo", ["gbm", "drf"])
    def test_binomial_parity(self, rng, tmp_path, algo):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.drf import DRF
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        cls = GBM if algo == "gbm" else DRF
        m = cls(ntrees=8, max_depth=4, response_column="y", seed=1,
                min_rows=2).train(fr)
        lib = _compile(m.pojo("c"), tmp_path, f"{algo}_bin")
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _tree_score_all(lib, X32, 3)
        want = m._predict_raw(fr)  # [N, 2] probabilities
        np.testing.assert_allclose(got[:, 1:], want, rtol=1e-5, atol=1e-6)

    def test_multinomial_parity(self, rng, tmp_path):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng, nclass=3)
        m = GBM(ntrees=5, max_depth=3, response_column="y", seed=2,
                min_rows=2).train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "gbm_multi")
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _tree_score_all(lib, X32, 4)
        want = m._predict_raw(fr)  # [N, 3]
        np.testing.assert_allclose(got[:, 1:], want, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(got[:, 0], want.argmax(axis=1))

    def test_drf_multinomial_parity(self, rng, tmp_path):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.drf import DRF

        fr = _frame(rng, nclass=3)
        m = DRF(ntrees=6, max_depth=3, response_column="y", seed=9,
                min_rows=2).train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "drf_multi")
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _tree_score_all(lib, X32, 4)
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got[:, 1:], want, rtol=1e-5, atol=1e-6)

    def test_regression_parity_log_link(self, rng, tmp_path):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng, nclass=0)
        # poisson needs nonnegative response
        y = fr.col("y").data
        y[:] = np.exp(np.clip(y, -3, 2))
        m = GBM(ntrees=6, max_depth=3, response_column="y", seed=3,
                min_rows=2, distribution="poisson").train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "gbm_pois")
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _tree_score_all(lib, X32, 1)
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got[:, 0], want, rtol=1e-5)

    def test_one_hot_encoding_parity(self, rng, tmp_path):
        from h2o3_tpu.models.tree.common import tree_matrix
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=5, max_depth=3, response_column="y", seed=4,
                min_rows=2,
                categorical_encoding="one_hot_explicit").train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "gbm_onehot")
        X32 = tree_matrix(m.data_info, fr, encoding=m.tree_encoding)
        got = _tree_score_all(lib, X32, 3)
        want = m._predict_raw(fr)
        np.testing.assert_allclose(got[:, 1:], want, rtol=1e-5, atol=1e-6)


class TestGLMPojoC:
    def test_binomial_parity(self, rng, tmp_path):
        from h2o3_tpu.models.data_info import expand_matrix
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = _frame(rng)
        m = GLM(GLMParameters(response_column="y", family="binomial",
                              lambda_=0.01)).train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "glm_bin")
        lib.score.argtypes = [ctypes.POINTER(ctypes.c_double),
                              ctypes.POINTER(ctypes.c_double)]
        X, _ = expand_matrix(m.data_info, fr, dtype=np.float64)
        assert X.shape[1] == len(m.data_info.coef_names)
        out = np.zeros(3)
        want = m._predict_raw(fr)
        for i in range(0, fr.nrows, 7):
            row = np.ascontiguousarray(X[i])
            lib.score(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            np.testing.assert_allclose(out[1:], want[i], rtol=1e-10)

    def test_binomial_noncanonical_link_parity(self, rng, tmp_path):
        """A binomial GLM with link='log' must score through ITS link,
        not a hardcoded sigmoid (review finding)."""
        from h2o3_tpu.models.data_info import expand_matrix
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = _frame(rng)
        m = GLM(GLMParameters(response_column="y", family="binomial",
                              link="log")).train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "glm_loglink")
        lib.score.argtypes = [ctypes.POINTER(ctypes.c_double),
                              ctypes.POINTER(ctypes.c_double)]
        X, _ = expand_matrix(m.data_info, fr, dtype=np.float64)
        want = m._predict_raw(fr)
        out = np.zeros(3)
        for i in range(0, fr.nrows, 13):
            row = np.ascontiguousarray(X[i])
            lib.score(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            np.testing.assert_allclose(out[1:], want[i], rtol=1e-10)

    def test_unsupported_glm_families_raise(self, rng):
        # multinomial now exports (TestMultinomialGlmPojo); ordinal is
        # the remaining refusal
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = _frame(rng, nclass=3)
        m = GLM(GLMParameters(response_column="y",
                              family="ordinal")).train(fr)
        with pytest.raises(ValueError, match="ordinal"):
            m.pojo("c")

    def test_offset_models_refuse(self, rng):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng, nclass=0)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=8,
                min_rows=2, offset_column="x3").train(fr)
        with pytest.raises(ValueError, match="offset_column"):
            m.pojo("c")

    def test_gamma_inverse_link_parity(self, rng, tmp_path):
        from h2o3_tpu.models.data_info import expand_matrix
        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = _frame(rng, nclass=0)
        y = fr.col("y").data
        y[:] = np.exp(np.clip(y, -2, 2)) + 0.1
        m = GLM(GLMParameters(response_column="y", family="gamma")).train(fr)
        lib = _compile(m.pojo("c"), tmp_path, "glm_gamma")
        lib.score.argtypes = [ctypes.POINTER(ctypes.c_double),
                              ctypes.POINTER(ctypes.c_double)]
        X, _ = expand_matrix(m.data_info, fr, dtype=np.float64)
        want = m._predict_raw(fr)
        out = np.zeros(1)
        for i in range(0, fr.nrows, 11):
            row = np.ascontiguousarray(X[i])
            lib.score(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            np.testing.assert_allclose(out[0], want[i], rtol=1e-10)


class TestJavaEmitterAndRoutes:
    def test_java_source_structure(self, rng):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=5,
                min_rows=2).train(fr)
        src = m.pojo("java")
        assert "public class POJO_" in src
        assert "public static double[] score0(double[] row" in src
        assert src.count("{") == src.count("}")
        # every tree surfaces as a walk call
        assert src.count("s += walk(") == 3

    def test_rest_routes(self, rng):
        from h2o3_tpu.api import start_server
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=6,
                min_rows=2).train(fr)
        s = start_server(port=0)
        try:
            with urllib.request.urlopen(
                    f"{s.url}/3/Models.java/{m.key}") as resp:
                java = resp.read().decode()
            assert "score0" in java
            with urllib.request.urlopen(
                    f"{s.url}/3/Models.java/{m.key}?lang=c") as resp:
                c_src = resp.read().decode()
            assert "void score(const float *x" in c_src
            with urllib.request.urlopen(
                    f"{s.url}/3/Models.java/{m.key}/preview") as resp:
                prev = resp.read().decode()
            assert len(prev.splitlines()) <= 60
        finally:
            s.stop()

    def test_unsupported_model_is_clean_400(self, rng):
        from h2o3_tpu.models.kmeans import KMeans, KMeansParameters

        fr = _frame(rng)
        m = KMeans(KMeansParameters(k=3)).train(fr.drop("y"))
        with pytest.raises(ValueError, match="POJO export supports"):
            m.pojo()

    @pytest.mark.skipif(not os.path.exists("/usr/bin/javac"),
                        reason="no JDK in this image")
    def test_java_compiles(self, rng, tmp_path):
        from h2o3_tpu.models.tree.gbm import GBM

        fr = _frame(rng)
        m = GBM(ntrees=3, max_depth=3, response_column="y", seed=7,
                min_rows=2).train(fr)
        src = m.pojo("java")
        cls = src.split("public class ")[1].split(" ")[0]
        (tmp_path / f"{cls}.java").write_text(src)
        proc = subprocess.run(["javac", f"{cls}.java"], cwd=tmp_path,
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr


class TestGamPojo:
    """GAM C scorer: emitted source recomputes the CR basis and must
    match in-framework predict bit-for-bit on in-range rows."""

    @pytest.mark.parametrize("family", ["gaussian", "binomial"])
    def test_compiled_parity(self, tmp_path, family):
        from h2o3_tpu.models.data_info import expand_matrix
        from h2o3_tpu.models.gam import GAM
        from h2o3_tpu.models.pojo import pojo_source

        rng = np.random.default_rng(17)
        n = 300
        x1 = rng.normal(size=n)
        z = rng.normal(size=n)
        f = np.sin(1.4 * x1) + 0.5 * z
        if family == "binomial":
            y = (f + rng.normal(size=n) * 0.3 > 0).astype(np.int32)
            ycol = Column("y", y, ColType.CAT, ["n", "p"])
        else:
            ycol = Column("y", f + rng.normal(size=n) * 0.1)
        fr = Frame([Column("z", z), Column("x1", x1), ycol])
        m = GAM(response_column="y", gam_columns=["x1"], num_knots=8,
                family=family, lambda_=0.0, standardize=False).train(fr)
        src = pojo_source(m, "c")
        lib = _compile(src, tmp_path, f"gam_{family}")
        lib.score.argtypes = [ctypes.POINTER(ctypes.c_double),
                              ctypes.POINTER(ctypes.c_double)]
        Xl, _ = expand_matrix(m.data_info, fr, dtype=np.float64)
        want = m._predict_raw(fr)
        out = np.zeros(3)
        for i in range(0, n, 17):
            row = np.concatenate([Xl[i], [x1[i]]])
            lib.score(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            if family == "binomial":
                np.testing.assert_allclose(out[1:], want[i], rtol=1e-10)
            else:
                np.testing.assert_allclose(out[0], want[i], rtol=1e-10)

    def test_refusal_for_non_cr(self, tmp_path):
        from h2o3_tpu.models.gam import GAM
        from h2o3_tpu.models.pojo import pojo_source

        rng = np.random.default_rng(3)
        x = rng.normal(size=200)
        fr = Frame([Column("x", x),
                    Column("y", np.sin(x) + rng.normal(size=200) * 0.1)])
        m = GAM(response_column="y", gam_columns=["x"], num_knots=8,
                bs=1, lambda_=0.0, standardize=False).train(fr)
        with pytest.raises(ValueError, match="cubic-regression"):
            pojo_source(m, "c")


class TestMultinomialGlmPojo:
    def test_compiled_parity(self, tmp_path):
        from h2o3_tpu.models.data_info import expand_matrix
        from h2o3_tpu.models.glm import GLM, GLMParameters
        from h2o3_tpu.models.pojo import pojo_source

        rng = np.random.default_rng(23)
        n = 400
        X = rng.normal(size=(n, 3))
        logits = np.stack([X[:, 0], -X[:, 0] + X[:, 1], 0.5 * X[:, 2]],
                          axis=1)
        y = logits.argmax(axis=1).astype(np.int32)
        fr = Frame([Column(f"x{i}", X[:, i]) for i in range(3)]
                   + [Column("y", y, ColType.CAT, ["a", "b", "c"])])
        m = GLM(GLMParameters(response_column="y", family="multinomial",
                              lambda_=0.0)).train(fr)
        src = pojo_source(m, "c")
        lib = _compile(src, tmp_path, "glm_multi")
        lib.score.argtypes = [ctypes.POINTER(ctypes.c_double),
                              ctypes.POINTER(ctypes.c_double)]
        Xd, _ = expand_matrix(m.data_info, fr, dtype=np.float64)
        want = m._predict_raw(fr)
        out = np.zeros(4)
        for i in range(0, n, 29):
            row = np.ascontiguousarray(Xd[i])
            lib.score(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                      out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            np.testing.assert_allclose(out[1:], want[i], rtol=1e-10)
            assert int(out[0]) == int(np.argmax(want[i]))
