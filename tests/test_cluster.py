"""In-process tier for the application-plane cluster (h2o3_tpu/cluster/).

Reference analogues: water/AutoBuffer (framing), water/RPC.java:101 (the
retry ladder + resend dedup), water/Paxos.java:10-27 (quorum membership,
suspicion, version fencing), water/Key.java:196 + water/DKV.java (key
homes and forwarding), water/DTask (remote execution).

Everything here runs multiple Cloud instances INSIDE one process over
real loopback sockets — the wire, retry, dedup and membership state
machines are identical to the multi-process tier (which covers process
isolation and /3/Cloud end-to-end), at a fraction of the wall clock.
"""

import json
import socket
import struct
import time
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import rpc as crpc
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster import transport
from h2o3_tpu.cluster.dkv import HashRing
from h2o3_tpu.cluster.membership import (
    Cloud,
    CloudJoinError,
    cpu_ticks_payload,
    parse_flatfile,
    set_local_cloud,
)
from h2o3_tpu.keyed import KeyedStore


def _mr_stat(cols, mask):
    """Module-level map fn: crosses the RPC wire by module reference."""
    import jax.numpy as jnp

    return {
        "s": jnp.sum(jnp.where(mask, cols["x"], 0.0)),
        "n": jnp.sum(mask.astype(jnp.float32)),
    }


def _wait_for(cond, timeout=10.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


@pytest.fixture()
def two_clouds():
    """A formed 2-node cloud (node-a, node-b) on loopback."""
    a = Cloud("testcloud", "node-a", hb_interval=0.05)
    b = Cloud("testcloud", "node-b", hb_interval=0.05)
    try:
        a.start([])
        b.start([a.info.addr])
        _wait_for(
            lambda: a.size() == 2 and b.size() == 2
            and a.consensus() and b.consensus(),
            msg="2-node cloud formation")
        yield a, b
    finally:
        a.stop()
        b.stop()


# ---------------------------------------------------------------------------
# L0: framing


class TestTransport:
    def test_frame_roundtrip(self):
        srv = transport.TransportServer(lambda b: b[::-1])
        try:
            conn = transport.dial(srv.address, timeout=2.0)
            assert conn.request(b"hello", timeout=2.0) == b"olleh"
            # the same pooled connection serves many frames
            assert conn.request(b"ab", timeout=2.0) == b"ba"
            conn.close()
        finally:
            srv.stop()

    def test_announced_frame_size_guard(self):
        left, right = socket.socketpair()
        try:
            left.sendall(struct.pack("!I", transport.MAX_FRAME_BYTES + 1))
            with pytest.raises(transport.FrameTooLarge):
                transport.recv_frame(right)
        finally:
            left.close()
            right.close()

    def test_pool_reuses_and_bounds_idle(self):
        srv = transport.TransportServer(lambda b: b)
        pool = transport.ConnectionPool()
        try:
            c1 = pool.get(srv.address, 2.0)
            pool.put(c1)
            assert pool.get(srv.address, 2.0) is c1  # reused, not re-dialed
            c1.close()
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# L1: RPC ladder + typed errors + idempotency


class TestRpc:
    def test_call_and_remote_error_types(self):
        srv = crpc.RpcServer()
        srv.register("double", lambda p: p * 2)

        def _boom(p):
            raise ValueError("boom")

        srv.register("boom", _boom)
        srv.register("teapot", lambda p: (_ for _ in ()).throw(
            crpc.RpcFault("short and stout", code=418)))
        client = crpc.RpcClient()
        try:
            assert client.call(srv.address, "double", 21) == 42
            with pytest.raises(crpc.RemoteError) as ei:
                client.call(srv.address, "boom")
            assert ei.value.remote_type == "ValueError"
            assert ei.value.code == 500
            with pytest.raises(crpc.RemoteError) as ei:
                client.call(srv.address, "teapot")
            assert ei.value.code == 418
            with pytest.raises(crpc.RemoteError) as ei:
                client.call(srv.address, "no_such_method")
            assert ei.value.code == 404
        finally:
            client.close()
            srv.stop()

    def test_timeout_is_typed_and_retries_bounded(self):
        srv = crpc.RpcServer()
        srv.register("slow", lambda p: time.sleep(1.0))
        client = crpc.RpcClient(retries=2, backoff_base=0.01)
        before = crpc._RPC_RETRIES.total()
        try:
            t0 = time.monotonic()
            with pytest.raises(crpc.RPCTimeoutError):
                client.call(srv.address, "slow", timeout=0.05)
            # 3 attempts of 0.05s + two small backoffs, not the 1s handler
            assert time.monotonic() - t0 < 0.8
            assert crpc._RPC_RETRIES.total() - before == 2
        finally:
            client.close()
            srv.stop()

    def test_stale_pooled_connections_dont_consume_retries(self):
        # a restarted peer leaves EVERY pooled socket stale at once; the
        # ladder must drain them within ONE attempt and dial fresh, not
        # burn an attempt per dead socket
        srv = crpc.RpcServer()
        addr = srv.address
        srv.register("echo", lambda p: p)
        client = crpc.RpcClient(retries=0)  # zero ladder budget
        try:
            conns = [client.pool.dial(addr, 2.0) for _ in range(3)]
            for c in conns:
                client.pool.put(c)
            srv.stop()
            srv = crpc.RpcServer(port=addr[1])  # restart on the same addr
            srv.register("echo", lambda p: p)
            assert client.call(addr, "echo", "hi", timeout=2.0) == "hi"
        finally:
            client.close()
            srv.stop()

    def test_connection_refused_bounded_dial_count(self):
        dials = {"n": 0}

        def counting_dial(addr, timeout):
            dials["n"] += 1
            return transport.dial(addr, timeout)

        # a port nothing listens on (bind + close to reserve then free it)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead = s.getsockname()
        s.close()
        client = crpc.RpcClient(
            dialer=counting_dial, retries=3, backoff_base=0.01)
        try:
            with pytest.raises(crpc.RPCConnectionError):
                client.call(dead, "ping", timeout=0.2)
            assert dials["n"] == 4  # 1 + retries, not unbounded
        finally:
            client.close()


class _FlakyDial:
    """Fault-injecting transport double: executes the real exchange, then
    drops / delays / duplicates at the client edge — the server genuinely
    ran, the caller genuinely retries."""

    def __init__(self, drop_first=0, delay=0.0, duplicate=False):
        self.drop_remaining = drop_first
        self.delay = delay
        self.duplicate = duplicate
        self.dials = 0

    def __call__(self, addr, timeout):
        self.dials += 1
        inner = transport.dial(addr, timeout)
        outer = self

        class Flaky(transport.Connection):
            def __init__(self):
                self.sock = inner.sock
                self.addr = inner.addr

            def request(self, payload, timeout):
                if outer.duplicate:
                    # the frame arrives twice; both responses are read
                    # and must agree (server-side token dedup)
                    self.sock.settimeout(timeout)
                    transport.send_frame(self.sock, payload)
                    transport.send_frame(self.sock, payload)
                    first = transport.recv_frame(self.sock)
                    second = transport.recv_frame(self.sock)
                    assert first == second, "duplicate delivery diverged"
                    return second
                if outer.delay:
                    # the response is delayed in flight: the request DID
                    # reach the server, but the caller's recv deadline
                    # fires before the bytes land
                    self.sock.settimeout(timeout)
                    transport.send_frame(self.sock, payload)
                    time.sleep(min(outer.delay, timeout + 0.05))
                    if outer.delay > timeout:
                        raise socket.timeout("injected response delay")
                    return transport.recv_frame(self.sock)
                resp = super().request(payload, timeout)
                if outer.drop_remaining > 0:
                    outer.drop_remaining -= 1
                    raise socket.timeout("injected response drop")
                return resp

        return Flaky()


class TestRpcFaultInjection:
    """Satellite: dropped, delayed and duplicated frames — bounded
    retries, typed errors, and NO duplicate side effects on retried
    mutations (idempotency tokens)."""

    def _counting_server(self):
        srv = crpc.RpcServer()
        hits = []

        def bump(p):
            hits.append(p)
            return len(hits)

        srv.register("bump", bump)
        return srv, hits

    def test_dropped_response_retries_without_double_execution(self):
        srv, hits = self._counting_server()
        flaky = _FlakyDial(drop_first=1)
        client = crpc.RpcClient(dialer=flaky, retries=3, backoff_base=0.01)
        try:
            # attempt 1 executes on the server but the response is lost;
            # the retry carries the same token and gets the memoized
            # response — the mutation ran exactly once
            assert client.call(srv.address, "bump", "put-1", timeout=2.0) == 1
            assert hits == ["put-1"]
            assert flaky.dials >= 2  # the dropped attempt poisoned its conn
        finally:
            client.close()
            srv.stop()

    def test_delayed_response_then_recovery(self):
        srv, hits = self._counting_server()
        flaky = _FlakyDial(delay=0.3)
        client = crpc.RpcClient(dialer=flaky, retries=2, backoff_base=0.01)
        try:
            with pytest.raises(crpc.RPCTimeoutError):
                client.call(srv.address, "bump", "x", timeout=0.05)
            # every delayed attempt still reached the server exactly once
            # per unique token — the timeout bounded the caller, and the
            # dedup bounded the side effects to one per logical call
            assert len(hits) == 1
            flaky.delay = 0.0
            assert client.call(srv.address, "bump", "y", timeout=2.0) == 2
            assert hits == ["x", "y"]
        finally:
            client.close()
            srv.stop()

    def test_duplicated_frames_execute_once(self):
        srv, hits = self._counting_server()
        client = crpc.RpcClient(
            dialer=_FlakyDial(duplicate=True), retries=0)
        try:
            assert client.call(srv.address, "bump", "dup", timeout=2.0) == 1
            assert hits == ["dup"]  # second delivery answered from memo
        finally:
            client.close()
            srv.stop()


# ---------------------------------------------------------------------------
# L3a: consistent-hash homes


class TestHashRing:
    def test_homes_deterministic_and_replicas_distinct(self):
        ring = HashRing(["a@h:1", "b@h:2", "c@h:3"])
        for i in range(50):
            k = f"key{i}"
            homes = ring.homes(k, 2)
            assert homes == ring.homes(k, 2)
            assert len(homes) == 2 and len(set(homes)) == 2
        assert len(ring.homes("k", 99)) == 3  # capped at member count

    def test_member_removal_only_moves_its_keys(self):
        full = HashRing(["a@h:1", "b@h:2", "c@h:3"])
        reduced = HashRing(["a@h:1", "b@h:2"])
        keys = [f"key{i}" for i in range(300)]
        moved = 0
        for k in keys:
            before = full.homes(k, 1)[0]
            after = reduced.homes(k, 1)[0]
            if before != "c@h:3":
                # consistent hashing: keys NOT homed on the removed
                # member must not move
                assert after == before
            else:
                moved += 1
        assert 0 < moved < len(keys)

    def test_spread_is_roughly_even(self):
        ring = HashRing(["a@h:1", "b@h:2", "c@h:3"])
        counts = {}
        for i in range(900):
            h = ring.homes(f"key{i}", 1)[0]
            counts[h] = counts.get(h, 0) + 1
        assert min(counts.values()) > 900 / 3 / 3  # within 3x of even


# ---------------------------------------------------------------------------
# L2: membership, suspicion, fencing


class TestMembership:
    def test_two_node_formation_same_list_and_hash(self, two_clouds):
        a, b = two_clouds
        assert [m.info.ident for m in a.members_sorted()] == \
               [m.info.ident for m in b.members_sorted()]
        assert a.cloud_hash() == b.cloud_hash()
        assert a.consensus() and b.consensus()
        # HeartBeat payload fields made it across
        bm = next(m for m in a.members_sorted() if m.info.name == "node-b")
        assert "free_mem" in bm.stats and "dkv_keys" in bm.stats

    def test_member_schemas_shape(self, two_clouds):
        a, _b = two_clouds
        nodes = a.member_schemas()
        assert [n["name"] for n in nodes] == ["node-a", "node-b"]
        assert sum(1 for n in nodes if n["leader"]) == 1
        for n in nodes:
            assert {"h2o", "healthy", "last_heartbeat_age_ms",
                    "client"} <= set(n)

    def test_suspicion_then_removal_bumps_version(self, two_clouds):
        a, b = two_clouds
        v0 = a.version
        b.stop()
        _wait_for(
            lambda: any(not m.healthy for m in a.members_sorted()),
            timeout=5.0, msg="suspicion of the dead node")
        _wait_for(
            lambda: a.size() == 1, timeout=5.0, msg="removal")
        assert a.version > v0
        assert [m.info.name for m in a.members_sorted()] == ["node-a"]

    def test_wrong_cloud_name_rejected_as_400(self, two_clouds):
        a, _b = two_clouds
        c = Cloud("othercloud", "node-c", hb_interval=0.05)
        try:
            with pytest.raises(CloudJoinError) as ei:
                c.start([a.info.addr])
            assert ei.value.code == 400
        finally:
            c.stop()

    def test_duplicate_node_name_rejected_as_409(self, two_clouds):
        a, _b = two_clouds
        imposter = Cloud("testcloud", "node-b", hb_interval=0.05)
        try:
            with pytest.raises(CloudJoinError) as ei:
                imposter.start([a.info.addr])
            assert ei.value.code == 409
        finally:
            imposter.stop()

    def test_stale_member_fenced_then_rejoins(self, two_clouds):
        a, b = two_clouds
        # force-remove node-b from a's view (as if it missed its beats)
        with a._lock:
            a._members["node-b"].last_heard -= 3600
        a._check_suspicion()
        assert a.size() == 1 and "node-b" in a._tombstones
        # b still believes in the old epoch: its direct beat is fenced
        with b._lock:
            b.version = 1
            b._needs_rejoin = False
        with pytest.raises(crpc.RemoteError) as ei:
            b._beat_one(a.info.addr, timeout=2.0)
        assert ei.value.code == 410
        assert int(ei.value.detail["version"]) >= 2
        # the ladder's response: adopt the epoch + rejoin
        b._adopt_fence(ei.value)
        b._beat_one(a.info.addr, timeout=2.0)
        assert a.size() == 2 and "node-b" not in a._tombstones

    def test_rest_port_advertised_after_join_propagates(self, two_clouds):
        # the REST server binds AFTER the join beat; later heartbeats
        # must refresh the member's self-reported info on the peer, not
        # leave its rest_port frozen at 0 cloud-wide
        a, b = two_clouds
        a.advertise_rest_port(8111)

        def _b_sees():
            rows = [nd for nd in b.member_schemas()
                    if nd["name"] == "node-a"]
            return bool(rows) and rows[0]["rest_port"] == 8111

        _wait_for(_b_sees, msg="rest_port gossip refresh")

    def test_wildcard_bind_advertises_routable_host(self):
        # bind host and advertised host are distinct: a 0.0.0.0 bind
        # must gossip an address peers can actually dial back
        a = Cloud("wildcloud", "w0", host="0.0.0.0", hb_interval=0.05)
        b = Cloud("wildcloud", "w1", hb_interval=0.05)
        try:
            assert a.info.host not in ("0.0.0.0", "::", "")
            a.start([])
            b.start([a.info.addr])
            _wait_for(lambda: a.size() == 2 and b.size() == 2,
                      msg="wildcard-bind cloud formation")
        finally:
            a.stop()
            b.stop()

    def test_parse_flatfile(self, tmp_path):
        p = tmp_path / "flat"
        p.write_text(
            "# peers\n127.0.0.1:5001\n\nhost2:5002  # trailing\n")
        assert parse_flatfile(str(p)) == [
            ("127.0.0.1", 5001), ("host2", 5002)]

    def test_cpu_ticks_payload_shape(self):
        t = cpu_ticks_payload()
        assert set(t) == {"cpu_ticks", "columns", "available"}


# ---------------------------------------------------------------------------
# L3a: DKV routing


class TestDkvRouting:
    @pytest.fixture()
    def routed(self, two_clouds):
        a, b = two_clouds
        sa, sb = KeyedStore(), KeyedStore()
        ra = cdkv.install(a, sa)
        rb = cdkv.install(b, sb)
        return a, b, sa, sb, ra, rb

    @staticmethod
    def _key_homed_on(router, name, prefix="k"):
        return next(k for k in (f"{prefix}{i}" for i in range(4096))
                    if router.home_name(k) == name)

    def test_put_forwards_to_home_and_reads_everywhere(self, routed):
        _a, _b, sa, sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-b")
        sa.put(key, {"payload": [1, 2, 3]})
        # the authoritative copy lives on the home, NOT on the sender
        assert sa.peek(key) is None
        assert sb.get(key, _local=True) == {"payload": [1, 2, 3]}
        # readable through the router from either node
        assert sa.get(key) == {"payload": [1, 2, 3]}
        assert sb.get(key) == {"payload": [1, 2, 3]}
        sa.remove(key)
        assert sb.get(key, "GONE", _local=True) == "GONE"
        assert sa.get(key, "GONE") == "GONE"

    def test_home_keys_stay_local(self, routed):
        _a, _b, sa, sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-a", prefix="h")
        sa.put(key, "mine")
        assert sa.peek(key) == "mine"
        assert sb.peek(key) is None
        assert sb.get(key) == "mine"  # b forwards its read to a
        sa.remove(key)

    def test_replicas_knob_places_copies(self, routed):
        _a, _b, sa, sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-a", prefix="r")
        sa.put(key, "meta", replicas=2)
        # home copy + ring-successor copy: both nodes hold it locally
        assert sa.get(key, _local=True) == "meta"
        assert sb.get(key, _local=True) == "meta"
        sa.remove(key)  # removal broadcast reaps the replica too
        assert sb.get(key, "GONE", _local=True) == "GONE"

    def test_numpy_values_cross_the_wire(self, routed):
        _a, _b, sa, sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-b", prefix="np")
        arr = np.arange(1000, dtype=np.float32)
        sa.put(key, arr)
        got = sa.get(key)
        assert np.array_equal(got, arr) and got.dtype == arr.dtype
        sa.remove(key)

    def test_pre_join_local_key_stays_readable(self, routed):
        # a key stored while the cloud was size 1 lives only in the local
        # store; once the grown ring homes it elsewhere, the home's
        # "absent" answer must fall back to the local copy, not hide it
        _a, _b, sa, _sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-b", prefix="prejoin")
        sa.put(key, "old-data", _local=True)  # the pre-join put
        assert sa.get(key) == "old-data"
        sa.remove(key)

    def test_locked_remote_copy_rejects_remove(self, routed):
        _a, _b, sa, sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-b", prefix="lk")
        sa.put(key, "held")
        sb.read_lock(key, "job-1")
        # the same ValueError the single-node Lockable check raises —
        # not a silent success that leaves the key alive on its home
        with pytest.raises(ValueError, match="locked"):
            sa.remove(key)
        assert sa.get(key) == "held"
        sb.unlock_all()
        sa.remove(key)
        assert sa.get(key, "GONE") == "GONE"

    def test_framework_objects_stay_node_local(self, routed):
        # mutate-in-place lifecycle objects (Job/Frame/Model) never ship
        # over the ring: the building node owns their identity, in-place
        # mutation and listing; only plain data routes to a home
        _a, _b, sa, sb, ra, _rb = routed

        class JobLike:
            status = "CREATED"

        key = self._key_homed_on(ra, "node-b", prefix="job")
        obj = JobLike()
        sa.put(key, obj)
        assert sa.peek(key) is obj                     # identity kept
        assert sb.get(key, None, _local=True) is None  # never forwarded
        obj.status = "RUNNING"
        assert sa.get(key).status == "RUNNING"         # mutation visible
        sa.remove(key)

    def test_unreplicated_local_remove_sends_no_rpc(self, routed):
        # the common case — model-build sweeps removing unreplicated
        # locally-homed temp keys — must not pay remote round-trips
        _a, _b, sa, _sb, ra, _rb = routed
        key = self._key_homed_on(ra, "node-a", prefix="nr")
        sa.put(key, "v")
        before = cdkv._FORWARDS.total()
        sa.remove(key)
        assert cdkv._FORWARDS.total() == before

    def test_single_node_cloud_short_circuits(self):
        solo = Cloud("solocloud", "only", hb_interval=0.05)
        store = KeyedStore()
        router = cdkv.install(solo, store)
        try:
            assert not router.active()
            store.put("k", "v")
            assert store.peek("k") == "v"  # plain local path, no RPC
            assert store.get("k") == "v"
            store.remove("k")
        finally:
            solo.stop()


# ---------------------------------------------------------------------------
# L3b: task fan-out


class TestTaskFanout:
    def test_echo_task_roundtrip(self, two_clouds):
        a, _b = two_clouds
        ctasks.install(a)
        ctasks.install(_b)
        peer = next(m for m in a.members_sorted()
                    if m.info.name == "node-b")
        assert ctasks.submit(a, peer, "echo", {"x": 1}) == {"x": 1}
        with pytest.raises(crpc.RemoteError) as ei:
            ctasks.submit(a, peer, "definitely_not_registered")
        assert ei.value.code == 404

    def test_distributed_map_reduce_bit_exact(self, two_clouds):
        a, b = two_clouds
        ctasks.install(a)
        ctasks.install(b)
        # integer-valued float32 sums are order-exact: the distributed
        # combine must reproduce the single-node result bit for bit
        cols = {"x": np.arange(1001, dtype=np.float64)}
        local = ctasks.distributed_map_reduce(_mr_stat, cols, cloud=None)
        dist = ctasks.distributed_map_reduce(_mr_stat, cols, cloud=a)
        for key in ("s", "n"):
            assert np.asarray(local[key]).tobytes() == \
                np.asarray(dist[key]).tobytes()
        assert float(dist["s"]) == float(np.arange(1001).sum())
        assert float(dist["n"]) == 1001.0

    def test_lambda_rejected_with_clear_error(self, two_clouds):
        a, b = two_clouds
        ctasks.install(a)
        ctasks.install(b)
        with pytest.raises(ValueError, match="module-level"):
            ctasks.distributed_map_reduce(
                lambda c, m: c, {"x": np.zeros(8)}, cloud=a)

    def test_bad_reduce_choice(self):
        with pytest.raises(ValueError, match="valid choices"):
            ctasks.distributed_map_reduce(
                _mr_stat, {"x": np.zeros(8)}, reduce="median", cloud=None)

    def test_map_reduce_frame_entry_local_path(self):
        """No cloud in this process: the cluster-aware Frame entry must
        be the plain local path, returning host arrays."""
        from h2o3_tpu.compute.mapreduce import map_reduce_frame
        from h2o3_tpu.frame.parse import parse_csv

        fr = parse_csv("x\n" + "\n".join(str(i) for i in range(100)))
        out = map_reduce_frame(_mr_stat, fr)
        assert isinstance(out["s"], np.ndarray) or np.isscalar(out["s"])
        assert float(out["s"]) == float(sum(range(100)))
        assert float(out["n"]) == 100.0

    def test_distributed_parse_matches_serial(self, two_clouds):
        a, b = two_clouds
        ctasks.install(a)
        ctasks.install(b)
        from h2o3_tpu.frame.parse import (
            _iter_body_chunks, parse_csv, parse_setup,
        )

        text = "num,cat,s\n" + "".join(
            f"{i}.5,c{i % 3},s{i}\n" for i in range(200))
        setup = parse_setup(text)
        chunks = list(_iter_body_chunks(
            [text.encode()], 256, setup.header, setup.skip_blank_lines))
        assert len(chunks) > 2  # actually fans out
        fr = ctasks.distributed_parse_chunks(chunks, setup, cloud=a)
        serial = parse_csv(text)
        assert fr.nrows == serial.nrows and fr.names == serial.names
        for name in serial.names:
            ca, cb = serial.col(name), fr.col(name)
            assert ca.type == cb.type
            if ca.data.dtype == object:
                assert list(ca.data) == list(cb.data)
            else:
                assert np.array_equal(ca.data, cb.data, equal_nan=True)
            assert getattr(ca, "domain", None) == getattr(cb, "domain", None)


# ---------------------------------------------------------------------------
# satellites: launcher validation + mesh bootstrap error surface


class TestLauncherValidation:
    def test_process_id_out_of_range_is_a_clear_error(self, capsys):
        from h2o3_tpu.__main__ import main

        rc = main(["--coordinator", "localhost:9", "--num-processes", "2",
                   "--process-id", "2", "--port", "0"])
        assert rc == 2
        err = capsys.readouterr().err
        assert "--process-id must be in [0, --num-processes)" in err

    def test_negative_process_id_rejected(self, capsys):
        from h2o3_tpu.__main__ import main

        rc = main(["--coordinator", "localhost:9", "--num-processes", "2",
                   "--process-id", "-1", "--port", "0"])
        assert rc == 2


class TestDistributedInitializeErrors:
    """Runs in clean subprocesses: jax.distributed.initialize must precede
    any computation, and this pytest process has long since computed."""

    @staticmethod
    def _run(code):
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        return subprocess.run(
            [sys.executable, "-c", code], env=env, text=True,
            capture_output=True, timeout=120)

    def test_bare_call_is_a_noop_single_process(self):
        out = self._run(
            "from h2o3_tpu.parallel.mesh import distributed_initialize\n"
            "distributed_initialize()\n"  # no coordinator at all: benign
            "print('NOOP OK')\n")
        assert out.returncode == 0, out.stderr
        assert "NOOP OK" in out.stdout

    def test_misconfigured_kwargs_surface_with_context(self):
        # a real misconfiguration (process id missing) must raise — and
        # the message must carry the attempted kwargs, not just jax's line
        out = self._run(
            "from h2o3_tpu.parallel.mesh import distributed_initialize\n"
            "try:\n"
            "    distributed_initialize(\n"
            "        coordinator_address='127.0.0.1:1', num_processes=2)\n"
            "except ValueError as e:\n"
            "    print('TYPED', str(e))\n")
        assert out.returncode == 0, out.stderr
        assert "TYPED" in out.stdout
        assert "coordinator_address='127.0.0.1:1'" in out.stdout
        assert "num_processes=2" in out.stdout


# ---------------------------------------------------------------------------
# REST wiring (same-process server + 2-node cloud over real sockets)


class TestRestWiring:
    @pytest.fixture()
    def cloud_server(self, two_clouds):
        from h2o3_tpu.api import start_server

        a, b = two_clouds
        set_local_cloud(a)
        srv = start_server(port=0)
        try:
            yield a, b, srv
        finally:
            srv.stop()
            set_local_cloud(None)

    @staticmethod
    def _get(srv, path):
        try:
            with urllib.request.urlopen(srv.url + path) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def test_cloud_lists_real_members(self, cloud_server):
        a, _b, srv = cloud_server
        st, out = self._get(srv, "/3/Cloud")
        assert st == 200
        assert out["cloud_size"] == 2
        assert out["cloud_hash"] == a.cloud_hash()
        assert out["node_name"] == "node-a"
        names = [n["name"] for n in out["nodes"]]
        assert names == ["node-a", "node-b"]
        ages = [n["last_heartbeat_age_ms"] for n in out["nodes"]]
        assert all(isinstance(x, int) for x in ages)
        # the local node advertised its REST port into the cloud
        assert a.info.rest_port == srv.port

    def test_watermeter_proxies_to_addressed_node(self, cloud_server):
        _a, _b, srv = cloud_server
        # index 1 is node-b (canonical sorted order): served over RPC
        st, out = self._get(srv, "/3/WaterMeterCpuTicks/1")
        assert st == 200 and "cpu_ticks" in out
        st, out = self._get(srv, "/3/WaterMeterCpuTicks/0")
        assert st == 200 and "cpu_ticks" in out
        st, _ = self._get(srv, "/3/WaterMeterCpuTicks/7")
        assert st == 404

    def test_logs_nodes_proxies(self, cloud_server):
        _a, _b, srv = cloud_server
        with urllib.request.urlopen(
                srv.url + "/3/Logs/nodes/1/files/default") as resp:
            assert resp.status == 200
        st, _ = self._get(srv, "/3/Logs/nodes/9/files/default")
        assert st == 404

    def test_dkv_rest_surface_routes_to_home(self, cloud_server):
        a, b, srv = cloud_server
        from h2o3_tpu.keyed import DKV

        ra = cdkv.install(a, DKV)
        sb = KeyedStore()
        cdkv.install(b, sb)
        try:
            key = TestDkvRouting._key_homed_on(ra, "node-b", prefix="rest")
            body = json.dumps({"value": {"answer": 42}}).encode()
            req = urllib.request.Request(
                srv.url + f"/3/DKV/{key}", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(req) as resp:
                put_out = json.loads(resp.read())
            assert put_out["home"] == "node-b"
            st, got = self._get(srv, f"/3/DKV/{key}")
            assert st == 200 and got["value"] == {"answer": 42}
            st, home = self._get(srv, f"/3/DKV/{key}/home")
            assert st == 200 and home["home"] == "node-b"
            assert not home["local"]
            # cleanup through the router (broadcast reaps the home copy)
            DKV.remove(key)
            st, _ = self._get(srv, f"/3/DKV/{key}")
            assert st == 404
        finally:
            DKV.router = None
