"""R client package (VERDICT r3 item 5).

Reference: h2o-r/h2o-package/R/{connection,frame,models}.R and
h2o-bindings/bin/gen_R.py. The image has no R runtime, so the contract
here is golden-file + structural: the generated wrappers must stay in
lockstep with the server registry (regeneration is drift), every
registered algo must have its h2o-r-named wrapper with exactly the
server's parameter surface, and the handwritten R sources must at least
be brace/paren balanced and route-correct. When an Rscript appears in
the image, the smoke test below runs a real train/predict."""

import dataclasses
import os
import re
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RPKG = os.path.join(REPO, "h2o3r")


def _read(name):
    with open(os.path.join(RPKG, "R", name)) as f:
        return f.read()


class TestGeneratedWrappers:
    def test_no_drift_vs_registry(self, tmp_path):
        """Regenerating from the live registry must reproduce the
        committed file byte-for-byte — the same guarantee the python
        estimator bindings test pins."""
        out = tmp_path / "gen.R"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "gen_bindings.py"),
             "--r", str(out)],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stderr
        assert out.read_text() == _read("estimators_gen.R")

    def test_every_algo_has_a_wrapper(self):
        from h2o3_tpu.api.registry import algo_map
        from scripts.gen_bindings import R_FUNC_NAMES

        code = _read("estimators_gen.R")
        for algo in algo_map():
            fn = R_FUNC_NAMES.get(algo)
            assert fn, f"no R name mapped for {algo}"
            assert f"{fn} <- function(" in code, fn

    def test_wrapper_args_match_server_params(self):
        from h2o3_tpu.api.registry import algo_map

        code = _read("estimators_gen.R")
        # gbm as the canary: every Parameters field surfaces as an arg
        _, pcls = algo_map()["gbm"]
        m = re.search(r"h2o\.gbm <- function\((.*?)\)\s*\{", code, re.S)
        assert m
        args = {a.split("=")[0].strip() for a in m.group(1).split(",")}
        for f in dataclasses.fields(pcls):
            rn = f.name.rstrip("_") if f.name.endswith("_") else f.name
            assert rn in args, f"gbm wrapper missing {f.name}"

    def test_wrappers_post_to_model_builders(self):
        code = _read("estimators_gen.R")
        assert code.count('.h2o.train("') == code.count("<- function(")


class TestHandwrittenSources:
    FILES = ["json.R", "connection.R", "rapids.R", "frame.R", "models.R"]

    @pytest.mark.parametrize("name", FILES)
    def test_balanced_delimiters(self, name):
        code = _read(name)
        # strip strings and comments line-wise before counting
        stripped = []
        for line in code.splitlines():
            line = re.sub(r'"(\\.|[^"\\])*"', '""', line)
            line = re.sub(r"#.*$", "", line)
            stripped.append(line)
        text = "\n".join(stripped)
        for o, c in ("()", "{}", "[]"):
            assert text.count(o) == text.count(c), (name, o)

    def test_routes_exist_on_server(self):
        """Every REST path the R sources mention must be a registered
        route — the R client can never drift onto a dead endpoint."""
        import jax

        jax.config.update("jax_platforms", "cpu")
        from h2o3_tpu.api.server import H2OServer

        srv = H2OServer(port=0)
        known = [(m, p.pattern) for m, p, _n, _h, _s in srv.registry.routes]

        def served(method, path):
            path = path.split("?")[0]
            return any(
                m == method and re.match(pat, path)
                for m, pat in known
            )

        code = "\n".join(_read(n) for n in self.FILES)
        for m_ in re.finditer(
                r'\.h2o\.(GET|POST|DELETE|GETraw)\(paste0\("([^"]+)"', code):
            verb, prefix = m_.group(1), m_.group(2)
            verb = "GET" if verb == "GETraw" else verb
            # complete the template with a dummy segment per paste0 arg
            probe = prefix + "x"
            if not prefix.endswith("/"):
                probe = prefix.rstrip("?&") if "?" in prefix else prefix + "/x"
                probe = probe.split("?")[0]
                if not served(verb, probe):
                    probe = prefix.split("?")[0]
            assert served(verb, probe), (verb, prefix)
        for m_ in re.finditer(r'\.h2o\.(GET|POST|DELETE)\("([^"]+)"', code):
            verb, path = m_.group(1), m_.group(2)
            assert served(verb, path), (verb, path)

    def test_package_metadata(self):
        assert os.path.exists(os.path.join(RPKG, "DESCRIPTION"))
        assert os.path.exists(os.path.join(RPKG, "NAMESPACE"))
        desc = open(os.path.join(RPKG, "DESCRIPTION")).read()
        assert "Package: h2o3r" in desc


@pytest.mark.skipif(shutil.which("Rscript") is None,
                    reason="no R runtime in this image")
class TestRSmoke:
    def test_train_predict_over_rest(self, tmp_path):
        import numpy as np

        from h2o3_tpu.api import start_server

        rng = np.random.default_rng(3)
        csv = "x0,x1,y\n" + "\n".join(
            f"{a:.4f},{b:.4f},{'yes' if a + b > 0 else 'no'}"
            for a, b in rng.normal(size=(300, 2)))
        data = tmp_path / "train.csv"
        data.write_text(csv)
        s = start_server(port=0)
        try:
            script = f"""
source_dir <- file.path("{RPKG}", "R")
for (f in list.files(source_dir, full.names = TRUE)) source(f)
h2o.init(port = {s.port})
fr <- h2o.uploadFile("{data}")
m <- h2o.glm(fr, response_column = "y", family = "binomial")
stopifnot(h2o.auc(m) > 0.6)
p <- h2o.predict(m, fr)
stopifnot(h2o.nrow(p) == 300)
cat("R-SMOKE-OK\\n")
"""
            proc = subprocess.run(
                ["Rscript", "-e", script], capture_output=True, text=True,
                timeout=300)
            assert proc.returncode == 0, proc.stderr
            assert "R-SMOKE-OK" in proc.stdout
        finally:
            s.stop()


class TestRapidsParity:
    """Golden-transcript parity (VERDICT r4 item 3): the R munging surface
    and the python client must emit IDENTICAL rapids text for the same
    operations. The golden file is the contract; the python side re-derives
    every scenario here (no Rscript needed), and test_munging.R re-derives
    the R side when a runtime exists."""

    GOLDEN = os.path.join(REPO, "tests", "golden",
                          "r_python_rapids_parity.json")

    def _golden(self):
        import json

        with open(self.GOLDEN) as f:
            return json.load(f)

    def _frames(self):
        from h2o3_tpu.client.frame import ExprNode, H2OFrame

        def mk(key, names):
            fr = H2OFrame(None, ExprNode.key(key))
            fr._key, fr._names = key, names
            fr._nrows, fr._ncols = 100, len(names)
            return fr

        return mk("frA", ["a", "b", "g"]), mk("frB", ["a", "c"])

    def test_python_emission_matches_golden(self):
        from h2o3_tpu.client.frame import ExprNode

        frA, frB = self._frames()
        S = {
            "col_by_name": frA["a"],
            "cols_by_list": frA[["a", "b"]],
            "row_slice": frA[0:5],
            "mask_rows": frA[frA["a"] > 6, :],
            "arith": frA["a"] * 2 + 1,
            "rmul": 2 * frA["a"],
            "compare_and": (frA["a"] > 1) & (frA["b"] < 2),
            "not": ~frA["a"],
            "mean": ExprNode("mean", frA["a"], True, 0),
            "sum": ExprNode("sum", frA["a"], True),
            "unique": frA["g"].unique(),
            "table": frA["g"].table(),
            "asfactor": frA["g"].asfactor(),
            "cbind": frA.cbind(frB),
            "rbind": frA.rbind(frA),
            "colnames_assign": frA.set_names(["x", "y", "z"]),
            "sort": frA.sort("a"),
            "sort_desc_multi": frA.sort(["a", "b"], ascending=False),
            "merge": frA.merge(frB),
            "merge_all_x": frA.merge(frB, all_x=True),
            "groupby": frA.group_by("g").sum("a").mean("b").get_frame(),
            "groupby_count": frA.group_by("g").count().get_frame(),
            "ifelse": ExprNode("ifelse", frA["a"] > 0, 1, 0),
            "log": ExprNode("log", frA["a"]),
            "perfect_auc": ExprNode("perfectAUC", frA["a"], frA["b"]),
            "quantile": frA["a"].quantile([0.25, 0.5, 0.75]),
            "impute": frA.impute(0, "median"),
            "cor": frA[["a", "b"]].cor(),
            "scale": frA[["a", "b"]].scale(),
            "cumsum": frA["a"].cumsum(),
            "tolower": frA["g"].tolower(),
            "gsub": frA["g"].gsub("x", "y"),
            "strsplit": frA["g"].strsplit("-"),
            "substring": frA["g"].substring(1, 3),
            "nchar": frA["g"].nchar(),
            "year": frA["b"].year(),
        }
        golden = self._golden()
        assert set(S) == set(golden), "scenario sets diverged"
        for name, obj in S.items():
            ex = obj if not hasattr(obj, "_ex") else obj._ex
            assert ex.to_rapids() == golden[name], name

    def test_r_covers_every_scenario(self):
        """Every golden scenario name appears in test_munging.R, and every
        emitted op has its builder in rapids.R — so the R side cannot
        silently drop a scenario while this suite stays green."""
        import re as _re

        munge = open(os.path.join(RPKG, "tests", "test_munging.R")).read()
        for name in self._golden():
            assert _re.search(rf'"?{_re.escape(name)}"?\s*=', munge), name
        rapids = _read("rapids.R")
        ops = {m.split()[0].lstrip("(")
               for m in self._golden().values()}
        for op in ops:
            assert f'"{op}"' in rapids or f"({op} " in rapids or \
                op in ("+", "-", "*", "/", "^", "%", "==", "!=", "<", "<=",
                       ">", ">=", "&", "|", "not", "log"), op

    def test_golden_ops_execute_server_side(self):
        """Anti-vacuity: every golden transcript is EXECUTABLE — each op
        resolves to a registered rapids prim, so the parity pin cannot
        drift to ops the server no longer serves."""
        from h2o3_tpu.rapids.prims import PRIMS

        for name, text in self._golden().items():
            op = text.split()[0].lstrip("(")
            assert op in PRIMS or op in ("==", "!=", "<", "<=", ">", ">=",
                                         "&", "|", "+", "-", "*", "/"), \
                (name, op)

    @pytest.mark.skipif(shutil.which("Rscript") is None,
                        reason="no R runtime in this image")
    def test_rscript_parity(self):
        proc = subprocess.run(
            ["Rscript", os.path.join(RPKG, "tests", "test_munging.R")],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
