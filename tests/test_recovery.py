"""Job-level fault tolerance — VERDICT r2 item 9.

Reference: hex/faulttolerance/Recovery.java:21-53 (snapshot grid state +
frames to -auto_recovery_dir, reload and resume on restart) /
Recoverable.java."""

import os

import numpy as np
import pytest

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.glm import GLM, GLMParameters
from h2o3_tpu.models.grid import Grid, GridSearch, SearchCriteria
from h2o3_tpu.recovery import Recovery, auto_recover


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _frame(rng, n=300):
    X = rng.normal(size=(n, 3))
    y = (X[:, 0] - X[:, 1] + 0.3 * rng.normal(size=n) > 0).astype(np.int32)
    cols = [Column(f"x{i}", X[:, i]) for i in range(3)]
    cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
    return Frame(cols)


class TestRecovery:
    def test_successful_run_cleans_up(self, rng, tmp_path):
        d = str(tmp_path / "rec")
        fr = _frame(rng)
        gs = GridSearch(
            GLM,
            GLMParameters(response_column="y", family="binomial"),
            {"lambda_": [0.0, 0.1]},
            recovery_dir=d,
        )
        grid = gs.train(fr)
        assert len(grid.models) == 2
        # onDone removed the snapshot — nothing to recover
        assert not Recovery.present(d)
        assert auto_recover(d) is None

    def test_crash_then_resume_skips_finished_models(self, rng, tmp_path):
        """Simulated crash after 2 of 4 combos: resume trains ONLY the
        remaining 2 and the result matches a straight run."""
        d = str(tmp_path / "rec2")
        fr = _frame(rng)
        lambdas = [0.0, 0.01, 0.1, 1.0]
        params = GLMParameters(response_column="y", family="binomial", seed=1)

        # crash injection: the builder dies while training combo 3
        built = {"n": 0}
        orig_fit = GLM._fit

        def dying_fit(self, frame, valid=None):
            if built["n"] >= 2:
                raise KeyboardInterrupt("simulated crash")
            built["n"] += 1
            return orig_fit(self, frame, valid)

        gs = GridSearch(GLM, params, {"lambda_": lambdas}, recovery_dir=d)
        GLM._fit = dying_fit
        try:
            with pytest.raises(KeyboardInterrupt):
                gs.train(fr)
        finally:
            GLM._fit = orig_fit

        # the process "restarts": snapshot is present with 2 finished models
        assert Recovery.present(d)
        grid = auto_recover(d)
        assert isinstance(grid, Grid)
        assert len(grid.models) == 4
        hps = sorted(hp["lambda_"] for hp in grid.hyper_params)
        assert hps == sorted(lambdas)
        # snapshot cleaned after the successful resume
        assert not Recovery.present(d)
        # loaded + freshly-trained models all score
        for m in grid.models:
            assert m.predict(fr).nrows == fr.nrows

    def test_resume_with_missing_snapshot_file_retrains_right_combo(
            self, rng, tmp_path):
        """ADVICE r3 (medium): a vanished model file must not shift the
        survivor/hp pairing — resume retrains exactly the missing combo,
        keeps the survivor under its own hp, and trains no duplicates."""
        d = str(tmp_path / "recm")
        fr = _frame(rng)
        lambdas = [0.0, 0.01, 0.1, 1.0]
        params = GLMParameters(response_column="y", family="binomial", seed=1)

        built = {"n": 0}
        orig_fit = GLM._fit

        def dying_fit(self, frame, valid=None):
            if built["n"] >= 2:
                raise KeyboardInterrupt("simulated crash")
            built["n"] += 1
            return orig_fit(self, frame, valid)

        gs = GridSearch(GLM, params, {"lambda_": lambdas}, recovery_dir=d)
        GLM._fit = dying_fit
        try:
            with pytest.raises(KeyboardInterrupt):
                gs.train(fr)
        finally:
            GLM._fit = orig_fit

        # sabotage: the FIRST finished combo's snapshot file vanishes
        import json as _json
        with open(os.path.join(d, "recovery.json")) as f:
            meta = _json.load(f)
        assert len(meta["models"]) == 2
        lost_hp = meta["models"][0]["hp"]
        kept_hp = meta["models"][1]["hp"]
        os.unlink(os.path.join(d, meta["models"][0]["file"]))

        # resume must retrain lost_hp (and the 2 never-trained combos),
        # NOT retrain kept_hp, and end with all 4 combos exactly once
        trained = []

        def counting_fit(self, frame, valid=None):
            trained.append(float(self.params.lambda_))
            return orig_fit(self, frame, valid)

        GLM._fit = counting_fit
        try:
            grid = auto_recover(d)
        finally:
            GLM._fit = orig_fit
        assert isinstance(grid, Grid)
        assert sorted(trained) == sorted(
            [lost_hp["lambda_"]] +
            [l for l in lambdas
             if l not in (lost_hp["lambda_"], kept_hp["lambda_"])]
        )
        assert len(grid.models) == 4
        assert sorted(hp["lambda_"] for hp in grid.hyper_params) == \
            sorted(lambdas)

    def test_resume_over_rest(self, rng, tmp_path):
        import json
        import urllib.request

        from h2o3_tpu.api import start_server

        d = str(tmp_path / "rec3")
        fr = _frame(rng)
        built = {"n": 0}
        orig_fit = GLM._fit

        def dying_fit(self, frame, valid=None):
            if built["n"] >= 1:
                raise KeyboardInterrupt("simulated crash")
            built["n"] += 1
            return orig_fit(self, frame, valid)

        gs = GridSearch(
            GLM, GLMParameters(response_column="y", family="binomial"),
            {"lambda_": [0.0, 0.1]}, recovery_dir=d,
        )
        GLM._fit = dying_fit
        try:
            with pytest.raises(KeyboardInterrupt):
                gs.train(fr)
        finally:
            GLM._fit = orig_fit

        s = start_server(port=0)
        try:
            req = urllib.request.Request(
                s.url + "/3/Recovery/resume",
                data=json.dumps({"dir": d}).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                out = json.loads(resp.read())
            assert out["resumed"] is True
            assert len(out["model_ids"]) == 2
        finally:
            s.stop()


class TestMemoryManagerSpill:
    """water/MemoryManager + Cleaner: LRU frame spill-to-disk under a host
    memory budget, transparent reload on access."""

    def test_spill_and_transparent_reload(self, rng, tmp_path):
        from h2o3_tpu.keyed import DKV

        frames = {}
        try:
            for i in range(4):
                fr = _frame(rng, n=5000)
                key = f"spill_f{i}"
                fr.key = key
                DKV.put(key, fr)
                frames[key] = np.array(fr.col("x0").data)
            one = DKV.get("spill_f0")
            per = sum(
                c.data.nbytes for c in one.columns
            )
            # tiny budget: EVERYTHING spills except the most recently
            # touched frame (robust to frames other test modules left)
            DKV.set_memory_budget(1, ice_dir=str(tmp_path))
            spilled = DKV.spilled_keys()
            mine = [s for s in spilled if s in frames]
            assert mine, (spilled, list(frames))
            assert DKV.resident_frame_bytes() <= per  # only the newest stays
            # listings still see spilled frames as frames
            assert set(spilled) <= set(DKV.keys_of_type(Frame))
            # transparent reload with identical data
            k = mine[0]
            fr2 = DKV.get(k)
            assert isinstance(fr2, Frame)
            np.testing.assert_array_equal(
                fr2.col("x0").data, frames[k]
            )
            assert k not in DKV.spilled_keys()
        finally:
            DKV.set_memory_budget(None)
            for k in frames:
                DKV.remove(k)

    def test_concurrent_spill_never_loses_frames(self, rng, tmp_path):
        """ADVICE r3 (medium): two threads racing _maybe_spill must never
        pick the same victim — the lost-race unlink used to delete the
        winner's spill file, permanently losing the frame."""
        import threading

        from h2o3_tpu.keyed import DKV

        frames = {}
        try:
            for i in range(6):
                fr = _frame(rng, n=4000)
                key = f"race_f{i}"
                fr.key = key
                DKV.put(key, fr)
                frames[key] = np.array(fr.col("x0").data)
            DKV._budget = 1  # enable without triggering a spill yet
            DKV._ice_dir = str(tmp_path)
            barrier = threading.Barrier(4)
            errors = []

            def spill():
                try:
                    barrier.wait()
                    DKV._maybe_spill()
                except Exception as e:  # pragma: no cover
                    errors.append(e)

            ts = [threading.Thread(target=spill) for _ in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errors
            # EVERY frame must reload with intact data — a lost spill file
            # surfaces here as FileNotFoundError or wrong contents
            DKV._budget = None
            for k, x0 in frames.items():
                fr2 = DKV.get(k)
                assert isinstance(fr2, Frame), k
                np.testing.assert_array_equal(fr2.col("x0").data, x0)
        finally:
            DKV.set_memory_budget(None)
            for k in frames:
                DKV.remove(k)

    def test_remove_cleans_spill_file(self, rng, tmp_path):
        import os

        from h2o3_tpu.keyed import DKV

        try:
            for i in range(3):
                fr = _frame(rng, n=5000)
                fr.key = f"rm_f{i}"
                DKV.put(fr.key, fr)
            DKV.set_memory_budget(1, ice_dir=str(tmp_path))  # spill ~all
            spilled = DKV.spilled_keys()
            assert spilled
            files = os.listdir(tmp_path)
            for k in spilled:
                DKV.remove(k)
            assert len(os.listdir(tmp_path)) < len(files)
        finally:
            DKV.set_memory_budget(None)
            for i in range(3):
                DKV.remove(f"rm_f{i}")


class TestSecurity:
    """SSL + hash-file basic auth (water/network, LoginType.HASH_FILE)."""

    def test_basic_auth_gate(self, tmp_path):
        import base64
        import hashlib
        import json
        import urllib.request

        from h2o3_tpu.api import start_server

        auth = tmp_path / "realm.properties"
        auth.write_text(
            "alice:" + hashlib.sha256(b"secret").hexdigest() + "\n"
        )
        s = start_server(port=0, auth_file=str(auth))
        try:
            # no credentials -> 401 with the challenge header
            try:
                urllib.request.urlopen(s.url + "/3/Ping")
                assert False, "expected 401"
            except urllib.error.HTTPError as e:
                assert e.code == 401
                assert "Basic" in e.headers.get("WWW-Authenticate", "")
            # wrong password -> 401
            req = urllib.request.Request(s.url + "/3/Ping")
            req.add_header(
                "Authorization",
                "Basic " + base64.b64encode(b"alice:wrong").decode(),
            )
            try:
                urllib.request.urlopen(req)
                assert False, "expected 401"
            except urllib.error.HTTPError as e:
                assert e.code == 401
            # correct credentials -> 200
            req = urllib.request.Request(s.url + "/3/Ping")
            req.add_header(
                "Authorization",
                "Basic " + base64.b64encode(b"alice:secret").decode(),
            )
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["ok"] is True
        finally:
            s.stop()

    def test_tls_server(self, tmp_path):
        import json
        import ssl
        import subprocess
        import urllib.request

        from h2o3_tpu.api import start_server

        cert = tmp_path / "cert.pem"
        key = tmp_path / "key.pem"
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(key), "-out", str(cert), "-days", "1",
             "-subj", "/CN=127.0.0.1"],
            check=True, capture_output=True,
        )
        s = start_server(port=0, ssl_cert=str(cert), ssl_key=str(key))
        try:
            assert s.url.startswith("https://")
            ctx = ssl.create_default_context(cafile=str(cert))
            ctx.check_hostname = False
            with urllib.request.urlopen(s.url + "/3/Ping", context=ctx) as resp:
                assert json.loads(resp.read())["ok"] is True
        finally:
            s.stop()


class TestSqlImport:
    """water/jdbc/SQLManager.java — sqlite backend."""

    def test_import_table(self, tmp_path):
        import sqlite3

        from h2o3_tpu.frame.ingest import import_sql_table

        db = tmp_path / "t.db"
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE pts (x REAL, label TEXT, n INTEGER)")
        conn.executemany(
            "INSERT INTO pts VALUES (?, ?, ?)",
            [(1.5, "a", 1), (2.5, "b", 2), (None, "a", 3)],
        )
        conn.commit()
        conn.close()

        fr = import_sql_table(f"sqlite:{db}", table="pts")
        assert fr.names == ["x", "label", "n"]
        assert fr.nrows == 3
        assert np.isnan(fr.col("x").data[2])
        assert fr.col("label").type is ColType.CAT

    def test_select_query_and_rest(self, tmp_path):
        import json
        import sqlite3
        import urllib.request

        from h2o3_tpu.api import start_server

        db = tmp_path / "t2.db"
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE t (a REAL)")
        conn.executemany("INSERT INTO t VALUES (?)", [(i,) for i in range(7)])
        conn.commit()
        conn.close()

        s = start_server(port=0)
        try:
            req = urllib.request.Request(
                s.url + "/3/ImportSQLTable",
                data=json.dumps({
                    "connection_url": f"sqlite:{db}",
                    "select_query": "SELECT a FROM t WHERE a >= 3",
                    "destination_frame": "sql_fr",
                }).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(req) as resp:
                out = json.loads(resp.read())
            assert out["rows"] == 4
        finally:
            s.stop()

    def test_unsupported_engine_named(self):
        from h2o3_tpu.frame.ingest import import_sql_table

        # postgresql now routes to psycopg2 (round 4); absent driver
        # names the missing module and the reference's JDBC layer
        with pytest.raises(ValueError, match="psycopg2"):
            import_sql_table("jdbc:postgresql://h/db", table="t")
        with pytest.raises(ValueError, match="JDBC|SQLManager"):
            import_sql_table("jdbc:oracle:thin:@x", table="t")


class TestFlowLite:
    def test_console_served(self):
        import urllib.request

        from h2o3_tpu.api import start_server

        s = start_server(port=0)
        try:
            with urllib.request.urlopen(s.url + "/") as resp:
                body = resp.read()
            assert b"Flow-lite" in body and b"/3/Frames" in body
        finally:
            s.stop()


class TestBindingsCodegen:
    def test_generated_module_matches_live_surface(self, tmp_path):
        import importlib.util
        import subprocess
        import sys

        out = tmp_path / "gen_est.py"
        subprocess.run(
            [sys.executable, "scripts/gen_bindings.py", str(out)],
            check=True, capture_output=True, cwd=os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            ),
        )
        spec = importlib.util.spec_from_file_location("gen_est", out)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        from h2o3_tpu.api.registry import algo_map

        import dataclasses

        algos = algo_map()
        gen_cls = {
            getattr(mod, n).algo: getattr(mod, n)
            for n in dir(mod)
            if isinstance(getattr(mod, n), type)
            and getattr(getattr(mod, n), "algo", "?") in algos
        }
        assert len(gen_cls) >= 20
        # the generated signature covers every dataclass field
        import inspect

        for algo, cls in gen_cls.items():
            _, pcls = algos[algo]
            want = {f.name for f in dataclasses.fields(pcls)}
            got = set(inspect.signature(cls.__init__).parameters)
            assert want <= got, (algo, want - got)
        # defaults-only construction sends nothing and validates cleanly
        m = gen_cls["gbm"](ntrees=7)
        assert m._params == {"ntrees": 7}


class TestRecoveryWalkerAccounting:
    def test_failures_consume_walker_positions(self, rng, tmp_path):
        """A combo that FAILED before the crash must not be re-trained on
        resume, and trailing combos must not be dropped."""
        d = str(tmp_path / "rec4")
        fr = _frame(rng)
        lambdas = [0.0, 0.01, 0.1, 1.0]
        calls = {"n": 0}
        orig_fit = GLM._fit

        def flaky_fit(self, frame, valid=None):
            calls["n"] += 1
            if calls["n"] == 2:
                raise ValueError("synthetic failure")  # combo 2 fails
            if calls["n"] == 4:
                raise KeyboardInterrupt("crash")  # crash during combo 4
            return orig_fit(self, frame, valid)

        gs = GridSearch(
            GLM, GLMParameters(response_column="y", family="binomial"),
            {"lambda_": lambdas}, recovery_dir=d,
        )
        GLM._fit = flaky_fit
        try:
            with pytest.raises(KeyboardInterrupt):
                gs.train(fr)
        finally:
            GLM._fit = orig_fit

        grid = auto_recover(d)
        # 3 trained (1, 3 recovered + 4 resumed), 1 recorded failure (2)
        assert len(grid.models) == 3
        assert len(grid.failures) == 1
        trained = sorted(hp["lambda_"] for hp in grid.hyper_params)
        failed = grid.failures[0][0]["lambda_"]
        assert sorted(trained + [failed]) == sorted(lambdas)

    def test_random_discrete_resume_requires_seed(self):
        with pytest.raises(ValueError, match="seed"):
            GridSearch(
                GLM, GLMParameters(response_column="y", family="binomial"),
                {"lambda_": [0.0, 0.1]},
                search_criteria=SearchCriteria(strategy="RandomDiscrete"),
                recovery_dir="/tmp/nope",
            )


class TestAuthSPI:
    """Pluggable login backends (LoginType.java; api/auth.py)."""

    def test_salted_pbkdf2_entries_over_http(self, tmp_path):
        import base64
        import urllib.request

        from h2o3_tpu.api import start_server
        from h2o3_tpu.api.auth import hash_entry

        auth = tmp_path / "realm.properties"
        auth.write_text(hash_entry("bob", "hunter2", iterations=2_000) + "\n")
        s = start_server(port=0, auth_file=str(auth))
        try:
            req = urllib.request.Request(s.url + "/3/Ping")
            req.add_header(
                "Authorization",
                "Basic " + base64.b64encode(b"bob:hunter2").decode())
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            bad = urllib.request.Request(s.url + "/3/Ping")
            bad.add_header(
                "Authorization",
                "Basic " + base64.b64encode(b"bob:wrong").decode())
            try:
                urllib.request.urlopen(bad)
                assert False, "expected 401"
            except urllib.error.HTTPError as e:
                assert e.code == 401
        finally:
            s.stop()

    def test_mixed_legacy_and_salted_file(self, tmp_path):
        import hashlib

        from h2o3_tpu.api.auth import HashFileBackend, hash_entry

        auth = tmp_path / "realm.properties"
        auth.write_text(
            "# comment line\n"
            "alice:" + hashlib.sha256(b"secret").hexdigest() + "\n"
            + hash_entry("bob", "hunter2", iterations=1_000) + "\n")
        be = HashFileBackend(str(auth))
        assert len(be) == 2
        assert be.authenticate("alice", "secret")
        assert be.authenticate("bob", "hunter2")
        assert not be.authenticate("alice", "hunter2")
        assert not be.authenticate("bob", "secret")
        assert not be.authenticate("carol", "anything")

    def test_hash_entry_deterministic_with_salt(self):
        from h2o3_tpu.api.auth import hash_entry

        a = hash_entry("u", "p", iterations=1_000, salt=b"\x01" * 16)
        b = hash_entry("u", "p", iterations=1_000, salt=b"\x01" * 16)
        assert a == b
        assert hash_entry("u", "p", iterations=1_000) != a  # random salt

    def test_ldap_backend_via_stub(self):
        from h2o3_tpu.api.auth import LdapBackend

        binds = []

        class _Conn:
            def __init__(self, server, user=None, password=None):
                self.user, self.password = user, password

            def bind(self):
                binds.append((self.user, self.password))
                return self.password == "right"

            def unbind(self):
                pass

        class _Stub:
            Server = staticmethod(lambda url: url)
            Connection = _Conn

        be = LdapBackend("ldap://ldap.example:389",
                         "uid={},ou=people,dc=example,dc=org",
                         _ldap3_module=_Stub)
        assert be.authenticate("alice", "right")
        assert not be.authenticate("alice", "wrong")
        assert binds[0][0] == "uid=alice,ou=people,dc=example,dc=org"
        # hardening: empty password (anonymous bind) and DN injection
        assert not be.authenticate("alice", "")
        assert not be.authenticate("evil,dc=x", "right")

    def test_make_backend_refusals(self, tmp_path):
        import pytest

        from h2o3_tpu.api.auth import make_backend

        with pytest.raises(ValueError, match="kerberos"):
            make_backend("kerberos")
        with pytest.raises(ValueError, match="auth file"):
            make_backend("hash_file")
        with pytest.raises(ValueError, match="ldap-url"):
            make_backend("ldap")

    def test_launcher_hash_password_flag(self, capsys):
        from h2o3_tpu.__main__ import main

        assert main(["--hash-password", "dave", "pw"]) == 0
        line = capsys.readouterr().out.strip()
        assert line.startswith("dave:pbkdf2:120000:")
        from h2o3_tpu.api.auth import HashFileBackend
        import tempfile, os
        with tempfile.NamedTemporaryFile("w", suffix=".properties",
                                         delete=False) as f:
            f.write(line + "\n")
        try:
            be = HashFileBackend(f.name)
            assert be.authenticate("dave", "pw")
            assert not be.authenticate("dave", "pW")
        finally:
            os.unlink(f.name)
