"""Round-4 batch 2 routes: FeatureInteraction, FriedmansPopescusH,
fetchable PDP, frame export by URI, ingest route forms, Assembly.

Reference: ModelsHandler.{makeFeatureInteraction,makeFriedmansPopescusH,
fetchPartialDependence}, FramesHandler.export, ImportFilesHandler,
AssemblyHandler + h2o-py H2OAssembly."""

import json
import os
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import start_server

# module fixtures share server-side keys; swept at module end
pytestmark = pytest.mark.leaks_keys

rng0 = np.random.default_rng(21)
CSV = "x0,x1,y\n" + "\n".join(
    f"{a:.4f},{b:.4f},{'yes' if a * b > 0 else 'no'}"
    for a, b in rng0.normal(size=(500, 2))
)


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None, raw=False):
    body = json.dumps(data).encode() if data is not None else None
    req = urllib.request.Request(
        server.url + path, data=body,
        headers={"Content-Type": "application/json"} if body else {},
        method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def gbm(server):
    st, up = _req(server, "POST", "/3/PostFile", {"data": CSV})
    assert st == 200
    st, out = _req(server, "POST", "/3/Parse",
                   {"source_frames": [up["destination_frame"]],
                    "destination_frame": "ext_train"})
    assert st == 200, out
    st, out = _req(server, "POST", "/3/ModelBuilders/gbm",
                   {"training_frame": "ext_train", "response_column": "y",
                    "ntrees": 10, "max_depth": 4, "seed": 1, "min_rows": 3,
                    "model_id": "ext_gbm"})
    assert st == 200, out
    return "ext_gbm"


class TestFeatureInteraction:
    def test_xor_signal_interacts(self, server, gbm):
        st, out = _req(server, "POST", "/3/FeatureInteraction",
                       {"model_id": gbm})
        assert st == 200, out
        pairs = out["feature_interaction"]
        assert pairs, "no interactions found"
        # y = sign(x0*x1) is a pure interaction: x0|x1 must rank first
        assert pairs[0]["feature_pair"] in ("x0|x1", "x1|x0")
        assert out["split_counts"]

    def test_non_tree_model_400(self, server, gbm):
        st, out = _req(server, "POST", "/3/ModelBuilders/glm",
                       {"training_frame": "ext_train",
                        "response_column": "y", "family": "binomial",
                        "model_id": "ext_glm"})
        assert st == 200, out
        st, out = _req(server, "POST", "/3/FeatureInteraction",
                       {"model_id": "ext_glm"})
        assert st == 400


class TestFriedmansH:
    def test_interacting_pair_has_high_h(self, server, gbm):
        st, out = _req(server, "POST", "/3/FriedmansPopescusH",
                       {"model_id": gbm, "frame": "ext_train",
                        "variables": ["x0", "x1"], "nbins": 40})
        assert st == 200, out
        # multiplicative signal: H should be decisively non-additive
        assert out["h"] > 0.3, out

    def test_bad_variables_400(self, server, gbm):
        st, _ = _req(server, "POST", "/3/FriedmansPopescusH",
                     {"model_id": gbm, "frame": "ext_train",
                      "variables": ["x0"]})
        assert st == 400


class TestFetchPDP:
    def test_make_then_fetch(self, server, gbm):
        st, out = _req(server, "POST", "/3/PartialDependence",
                       {"model_id": gbm, "frame_id": "ext_train",
                        "cols": ["x0"], "nbins": 5,
                        "destination_key": "ext_pdp"})
        assert st == 200, out
        assert out["destination_key"]["name"] == "ext_pdp"
        st, fetched = _req(server, "GET", "/3/PartialDependence/ext_pdp")
        assert st == 200
        assert fetched["partial_dependence_data"][0]["column"] == "x0"
        st, _ = _req(server, "GET", "/3/PartialDependence/nope")
        assert st == 404


class TestFrameExport:
    def test_post_form(self, server, gbm, tmp_path):
        path = str(tmp_path / "out.csv")
        st, out = _req(server, "POST", "/3/Frames/ext_train/export",
                       {"path": path})
        assert st == 200, out
        lines = open(path).read().splitlines()
        assert lines[0] == "x0,x1,y" and len(lines) == 501
        # force=false on existing file conflicts
        st, _ = _req(server, "POST", "/3/Frames/ext_train/export",
                     {"path": path, "force": False})
        assert st == 409

    def test_get_uri_form(self, server, gbm, tmp_path):
        path = str(tmp_path / "out2.csv")
        enc = urllib.request.quote(path, safe="")
        st, out = _req(server, "GET",
                       f"/3/Frames/ext_train/export/{enc}/overwrite/true")
        assert st == 200, out
        assert os.path.exists(path)


class TestIngestForms:
    def test_import_files_multi(self, server, tmp_path):
        (tmp_path / "m1.csv").write_text("a\n1\n")
        (tmp_path / "m2.csv").write_text("a\n2\n")
        st, out = _req(server, "POST", "/3/ImportFilesMulti",
                       {"paths": [str(tmp_path / "m1.csv"),
                                  str(tmp_path / "m2.csv")]})
        assert st == 200, out
        assert len(out["destination_frames"]) == 2

    def test_import_get_form(self, server, tmp_path):
        (tmp_path / "g.csv").write_text("a\n1\n")
        st, out = _req(server, "GET",
                       f"/3/ImportFiles?path={tmp_path}/g.csv")
        assert st == 200, out

    def test_parse_svmlight_route(self, server):
        st, up = _req(server, "POST", "/3/PostFile",
                      {"data": "1 1:0.5 2:1.0\n-1 2:2.0\n"})
        assert st == 200
        st, out = _req(server, "POST", "/3/ParseSVMLight",
                       {"source_frames": [up["destination_frame"]],
                        "destination_frame": "ext_svm"})
        assert st == 200, out
        st, fr = _req(server, "GET", "/3/Frames/ext_svm")
        assert fr["frames"][0]["column_names"][0] == "target"

    def test_gated_routes_actionable(self, server):
        st, out = _req(server, "POST", "/3/DecryptionSetup", {})
        assert st == 400 and "Decryption" in out["msg"]
        # hive import is now a real (pyhive-gated) path: without a table
        # it validates, and without the driver the error names pyhive
        st, out = _req(server, "POST", "/3/ImportHiveTable", {})
        assert st == 400 and "table is required" in out["msg"]
        st, out = _req(server, "POST", "/3/ImportHiveTable",
                       {"table": "t"})
        assert st == 400 and "pyhive" in out["msg"]
        st, out = _req(server, "POST", "/3/SaveToHiveTable", {})
        assert st == 400 and "Hive" in out["msg"]


class TestAssembly:
    def test_fit_and_java(self, server, gbm, tmp_path):
        steps = [
            {"op": "ColOp", "fun": "abs", "col": "x0",
             "new_col_name": "ax0"},
            {"op": "BinaryOp", "fun": "*", "left": "x0", "right": "x1",
             "new_col_name": "x0x1"},
            {"op": "BinaryOp", "fun": "+", "left": "ax0", "right": 10.0,
             "new_col_name": "shifted"},
            {"op": "ColSelect", "cols": ["x0x1", "shifted"]},
        ]
        st, out = _req(server, "POST", "/99/Assembly",
                       {"frame": "ext_train", "steps": steps,
                        "destination_frame": "ext_asm_out"})
        assert st == 200, out
        assert out["out_names"] == ["x0x1", "shifted"]
        st, fr = _req(server, "GET", "/3/Frames/ext_asm_out")
        assert fr["frames"][0]["rows"] == 500
        # numeric correctness of the fitted pipeline
        from h2o3_tpu.keyed import DKV

        src, dst = DKV.get("ext_train"), DKV.get("ext_asm_out")
        x0 = src.col("x0").numeric_view()
        x1 = src.col("x1").numeric_view()
        np.testing.assert_allclose(dst.col("x0x1").data, x0 * x1)
        np.testing.assert_allclose(dst.col("shifted").data,
                                   np.abs(x0) + 10.0)
        # java emitter
        asm_id = out["assembly"]["name"]
        st, java = _req(server, "GET",
                        f"/99/Assembly.java/{asm_id}/MyMunger", raw=True)
        assert st == 200
        java = java.decode()
        assert "public class MyMunger" in java
        assert "public static double[] fit(double[] row)" in java
        assert java.count("{") == java.count("}")

    def test_bad_step_400(self, server, gbm):
        st, out = _req(server, "POST", "/99/Assembly",
                       {"frame": "ext_train",
                        "steps": [{"op": "Nope"}]})
        assert st == 400


class TestClientModuleFunctions:
    """h2o-py-module-level calls added round 4 (h2o.make_metrics,
    h2o.tabulate, h2o.interaction, h2o.export_file, h2o.download_pojo,
    feature_interaction / h_statistic)."""

    @pytest.fixture()
    def client(self, server, gbm):
        from h2o3_tpu import client as h2o

        h2o.connect(server.url)
        return h2o

    def test_make_metrics_and_analysis(self, client, gbm):
        h2o = client
        st = h2o.rapids("(= ext_p (cols_py ext_train 'x0'))")
        h2o.rapids("(= ext_a (cols_py ext_train 'x1'))")
        mm = h2o.make_metrics("ext_p", "ext_a")
        assert mm["rmse"] > 0
        fi = h2o.feature_interaction(gbm)
        assert fi["feature_interaction"]
        h = h2o.h_statistic(gbm, "ext_train", ["x0", "x1"], n_sample=25)
        assert 0.0 <= h <= 1.5

    def test_tabulate_interaction_export(self, client, gbm, tmp_path):
        h2o = client
        t = h2o.tabulate("ext_train", "x0", "y", nbins_predictor=4)
        assert len(t["count_table"]["predictor_labels"]) == 4
        path = str(tmp_path / "exp.csv")
        out = h2o.export_file("ext_train", path)
        assert out == path and os.path.exists(path)
        src = h2o.download_pojo(gbm, lang="c")
        assert "void score(const float *x" in src
        java = h2o.download_pojo(gbm)
        assert "score0" in java


class TestSteamWebsocket:
    """Steam message exchange over a real RFC 6455 websocket
    (h2o-extensions/steam SteamWebsocketServlet + SteamHelloMessenger)."""

    @staticmethod
    def _handshake(sock, host):
        import base64 as b64

        key = b64.b64encode(b"0123456789abcdef").decode()
        req = ("GET /3/Steam.web HTTP/1.1\r\n"
               f"Host: {host}\r\n"
               "Upgrade: websocket\r\nConnection: Upgrade\r\n"
               f"Sec-WebSocket-Key: {key}\r\n"
               "Sec-WebSocket-Version: 13\r\n\r\n")
        sock.sendall(req.encode())
        head = b""
        while b"\r\n\r\n" not in head:
            head += sock.recv(1024)
        return key, head.decode()

    @staticmethod
    def _mask_frame(payload: bytes) -> bytes:
        import os as _os

        mask = _os.urandom(4)
        body = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        assert len(payload) < 126
        return bytes([0x81, 0x80 | len(payload)]) + mask + body

    @staticmethod
    def _read_frame(sock):
        head = sock.recv(2)
        n = head[1] & 0x7F
        assert not head[1] & 0x80  # server frames are unmasked
        payload = b""
        while len(payload) < n:
            payload += sock.recv(n - len(payload))
        return head[0] & 0x0F, payload

    def test_hello_roundtrip(self, server):
        import json as _json
        import socket

        from h2o3_tpu.api.steam import accept_key

        host = server.url.split("//")[1]
        ip, port = host.split(":")
        with socket.create_connection((ip, int(port)), timeout=10) as sock:
            key, resp = self._handshake(sock, host)
            assert "101" in resp.splitlines()[0]
            assert f"Sec-WebSocket-Accept: {accept_key(key)}" in resp
            sock.sendall(self._mask_frame(_json.dumps(
                {"_type": "hello", "_id": "42"}).encode()))
            opcode, payload = self._read_frame(sock)
            assert opcode == 0x1
            msg = _json.loads(payload)
            assert msg["_type"] == "hello_response"
            assert msg["_id"] == "42_response"
            assert int(msg["cloud_size"]) >= 1
            # ping -> pong keeps the exchange alive
            sock.sendall(bytes([0x89, 0x80]) + b"\x00\x00\x00\x00")
            opcode, _ = self._read_frame(sock)
            assert opcode == 0xA
            # close is echoed
            sock.sendall(bytes([0x88, 0x80]) + b"\x00\x00\x00\x00")
            opcode, _ = self._read_frame(sock)
            assert opcode == 0x8


class TestMojoPipelineRoute:
    def test_compose_and_decode(self, server, gbm, tmp_path):
        """POST /99/MojoPipeline returns a reference pipeline zip whose
        main model is the trained GBM (degenerate single-model pipeline:
        no generated columns)."""
        import zipfile as _zip

        st, raw = _req(server, "POST", "/99/MojoPipeline",
                       {"models": {"main": gbm}, "input_mapping": {},
                        "main_model": "main"}, raw=True)
        assert st == 200
        p = tmp_path / "pipe.zip"
        p.write_bytes(raw)
        with _zip.ZipFile(p) as z:
            ini = z.read("model.ini").decode()
            assert "algorithm = MOJO Pipeline" in ini
            assert "models/main/model.ini" in z.namelist()
        from h2o3_tpu.models.mojo_ref import read_mojo

        mojo = read_mojo(str(p))
        assert mojo.pipeline_main == "main"

    def test_validation(self, server):
        st, out = _req(server, "POST", "/99/MojoPipeline", {})
        assert st == 400 and "main_model" in out["msg"]


class TestGamReferenceDownload:
    def test_gam_reference_mojo_over_rest(self, server, tmp_path):
        import numpy as np

        rng = np.random.default_rng(9)
        x = rng.normal(size=200)
        csv = "x,z,y\n" + "\n".join(
            f"{a:.5f},{b:.5f},{np.sin(a) + 0.2 * b:.5f}"
            for a, b in zip(x, rng.normal(size=200)))
        st, up = _req(server, "POST", "/3/PostFile", {"data": csv})
        st, out = _req(server, "POST", "/3/Parse",
                       {"source_frames": [up["destination_frame"]],
                        "destination_frame": "gam_train"})
        assert st == 200, out
        st, out = _req(server, "POST", "/3/ModelBuilders/gam",
                       {"training_frame": "gam_train",
                        "response_column": "y", "gam_columns": ["x"],
                        "num_knots": 8, "lambda_": 0.0,
                        "standardize": False, "model_id": "ext_gam"})
        assert st == 200, out
        st, raw = _req(server, "GET",
                       "/3/Models/ext_gam/mojo?format=reference",
                       raw=True)
        assert st == 200
        p = tmp_path / "gam.zip"
        p.write_bytes(raw)
        from h2o3_tpu.models.mojo_ref import read_mojo

        mojo = read_mojo(str(p))
        assert mojo.info["algo"] == "gam"
        assert mojo.gam_columns == ["x"]
