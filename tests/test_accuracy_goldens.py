"""Stored-oracle accuracy regression tier.

Reference: ``h2o-test-accuracy`` — dataset x algo test cases with stored
expected metrics (``src/test/java/water/TestCase.java``,
``AccuracyTestingSuite.java``). The sklearn-oracle tests elsewhere use loose
tolerances; this tier pins exact metric values on fixed synthetic datasets
so silent accuracy drift (a changed default, a broken kernel, an RNG
regression) fails loudly. Values were recorded on the 8-device CPU mesh the
test tier always runs on (conftest pins the backend), so they are
bit-reproducible up to minor XLA version drift — hence the small epsilon.
"""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.models.deeplearning import DeepLearning
from h2o3_tpu.models.glm import GLM
from h2o3_tpu.models.kmeans import KMeans
from h2o3_tpu.models.tree import DRF, GBM, XGBoost


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys

#: golden metrics; regenerate deliberately (never casually) with
#: the snippet in this file's git history if an intentional algorithm
#: change shifts them
GOLDEN = {
    "glm_binomial_auc": 0.8022620737109191,
    "gbm_binomial_auc": 0.8310825609898799,
    "xgboost_binomial_auc": 0.8873523696367261,
    "drf_binomial_auc": 0.9957684879870464,
    "gbm_regression_rmse": 0.6585004906238698,
    "dl_regression_rmse": 1.0634751969103902,
    "kmeans_tot_withinss": 108.05436325073242,
}

#: tolerance: tight enough to catch real drift, loose enough for
#: XLA-version-level float reassociation
EPS = 2e-3


def _binom_frame(seed=7, n=2000):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5))
    logit = 1.2 * X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(int)
    d = {f"x{i}": X[:, i] for i in range(5)}
    d["y"] = np.where(y > 0, "yes", "no")
    return Frame.from_dict(d)


def _reg_frame():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(2000, 4))
    y = 2 * X[:, 0] - X[:, 1] + 0.5 * rng.normal(size=2000)
    return Frame.from_dict({f"x{i}": X[:, i] for i in range(4)} | {"y": y})


@pytest.fixture(scope="module")
def binom():
    return _binom_frame()


@pytest.fixture(scope="module")
def reg():
    return _reg_frame()


def _check(name, value):
    golden = GOLDEN[name]
    assert value == pytest.approx(golden, abs=EPS), (
        f"{name}: got {value!r}, golden {golden!r} — accuracy drift; if the "
        f"change is intentional, re-record the golden deliberately"
    )


def test_glm_binomial_golden(binom):
    m = GLM(response_column="y", family="binomial", lambda_=0.0, seed=1).train(binom)
    _check("glm_binomial_auc", m.training_metrics.auc)


def test_gbm_binomial_golden(binom):
    m = GBM(response_column="y", ntrees=20, max_depth=4, seed=1,
            min_rows=5.0).train(binom)
    _check("gbm_binomial_auc", m.training_metrics.auc)


def test_xgboost_binomial_golden(binom):
    m = XGBoost(response_column="y", ntrees=20, max_depth=4, seed=1).train(binom)
    _check("xgboost_binomial_auc", m.training_metrics.auc)


def test_drf_binomial_golden(binom):
    m = DRF(response_column="y", ntrees=20, seed=1).train(binom)
    _check("drf_binomial_auc", m.training_metrics.auc)


def test_gbm_regression_golden(reg):
    m = GBM(response_column="y", ntrees=20, max_depth=4, seed=1,
            min_rows=5.0).train(reg)
    _check("gbm_regression_rmse", m.training_metrics.rmse)


def test_dl_regression_golden(reg):
    m = DeepLearning(response_column="y", hidden=[16, 16], epochs=10,
                     seed=1).train(reg)
    _check("dl_regression_rmse", m.training_metrics.rmse)


def test_kmeans_golden():
    rng = np.random.default_rng(5)
    X = np.concatenate(
        [rng.normal(loc=c, scale=0.5, size=(300, 3)) for c in (-3, 0, 3)]
    )
    m = KMeans(k=3, seed=1).train(
        Frame.from_dict({f"x{i}": X[:, i] for i in range(3)})
    )
    _check("kmeans_tot_withinss", m.tot_withinss)
