"""Round-4 REST groups: ModelMetrics CRUD + makeMetrics, model io by URI,
NPS, munging utilities (Tabulate/Interaction/DCT), frame drill-down,
cluster ops, typeahead/help/capabilities, profiler, real shutdown.

Reference: water/api/RegisterV3Api.java (URIs matched exactly),
ModelMetricsHandler.java, ModelsHandler.java,
NodePersistentStorageHandler.java, water/util/Tabulate.java,
hex/Interaction.java, ProfileCollectorTask.java."""

import json
import time
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import start_server


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys

rng0 = np.random.default_rng(11)
CSV = "x0,x1,c1,c2,y\n" + "\n".join(
    f"{a:.3f},{b:.3f},{'u' if a > 0 else 'v'},{'p' if b > 0 else 'q'},"
    f"{'yes' if a + b > 0 else 'no'}"
    for a, b in rng0.normal(size=(400, 2))
)


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None, raw=False, body_bytes=None):
    if body_bytes is not None:
        body = body_bytes
        headers = {"Content-Type": "application/octet-stream"}
    else:
        body = json.dumps(data).encode() if data is not None else None
        headers = {"Content-Type": "application/json"} if body else {}
    req = urllib.request.Request(
        server.url + path, data=body, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def glm(server):
    st, up = _req(server, "POST", "/3/PostFile", {"data": CSV})
    assert st == 200
    st, out = _req(server, "POST", "/3/Parse",
                   {"source_frames": [up["destination_frame"]],
                    "destination_frame": "ops_train"})
    assert st == 200, out
    st, out = _req(server, "POST", "/3/ModelBuilders/glm",
                   {"training_frame": "ops_train", "response_column": "y",
                    "family": "binomial", "model_id": "ops_glm"})
    assert st == 200, out
    return "ops_glm"


class TestModelMetricsCRUD:
    def test_score_caches_record(self, server, glm):
        st, out = _req(server, "POST",
                       f"/3/ModelMetrics/models/{glm}/frames/ops_train")
        assert st == 200, out
        mm = out["model_metrics"][0]
        assert mm["model"]["name"] == glm
        assert mm["frame"]["name"] == "ops_train"
        assert 0.5 < mm["auc"] <= 1.0

    def test_fetch_filters(self, server, glm):
        _req(server, "POST", f"/3/ModelMetrics/models/{glm}/frames/ops_train")
        for path in ("/3/ModelMetrics",
                     f"/3/ModelMetrics/models/{glm}",
                     "/3/ModelMetrics/frames/ops_train",
                     f"/3/ModelMetrics/models/{glm}/frames/ops_train",
                     f"/3/ModelMetrics/frames/ops_train/models/{glm}"):
            st, out = _req(server, "GET", path)
            assert st == 200, (path, out)
            assert any(rec["model"]["name"] == glm
                       for rec in out["model_metrics"]), path
        # a filter that matches nothing returns empty, not 404
        st, out = _req(server, "GET", "/3/ModelMetrics/models/nope")
        assert st == 200 and out["model_metrics"] == []

    def test_delete(self, server, glm):
        _req(server, "POST", f"/3/ModelMetrics/models/{glm}/frames/ops_train")
        st, out = _req(server, "DELETE",
                       f"/3/ModelMetrics/models/{glm}/frames/ops_train")
        assert st == 200 and out["deleted"]
        st, out = _req(server, "GET",
                       f"/3/ModelMetrics/models/{glm}/frames/ops_train")
        assert out["model_metrics"] == []

    def test_predictions_route_leaves_record(self, server, glm):
        _req(server, "DELETE", "/3/ModelMetrics")
        st, _ = _req(server, "POST",
                     f"/3/Predictions/models/{glm}/frames/ops_train")
        assert st == 200
        st, out = _req(server, "GET", f"/3/ModelMetrics/models/{glm}")
        assert st == 200 and out["model_metrics"]


class TestMakeMetrics:
    def _pred_frame(self, server, glm):
        st, out = _req(server, "POST",
                       f"/3/Predictions/models/{glm}/frames/ops_train",
                       {"predictions_frame": "ops_preds"})
        assert st == 200, out

    def test_binomial_make_matches_score(self, server, glm):
        self._pred_frame(server, glm)
        # actuals = the response column only
        st, out = _req(server, "POST", "/99/Rapids", {
            "ast": "(= ops_actuals (cols_py ops_train 'y'))"})
        assert st == 200, out
        st, made = _req(
            server, "POST",
            "/3/ModelMetrics/predictions_frame/ops_preds"
            "/actuals_frame/ops_actuals")
        assert st == 200, made
        mm = made["model_metrics"][0]
        st, scored = _req(server, "POST",
                          f"/3/ModelMetrics/models/{glm}/frames/ops_train",
                          {"force": True})
        want = scored["model_metrics"][0]
        assert abs(mm["auc"] - want["auc"]) < 1e-6
        assert abs(mm["logloss"] - want["logloss"]) < 1e-6

    def test_regression_make(self, server, glm):
        # numeric predictions vs numeric actuals, gaussian
        st, _ = _req(server, "POST", "/99/Rapids", {
            "ast": "(= ops_px (cols_py ops_train 'x0'))"})
        st, _ = _req(server, "POST", "/99/Rapids", {
            "ast": "(= ops_ax (cols_py ops_train 'x1'))"})
        st, made = _req(
            server, "POST",
            "/3/ModelMetrics/predictions_frame/ops_px"
            "/actuals_frame/ops_ax")
        assert st == 200, made
        assert made["model_metrics"][0]["rmse"] > 0


class TestAsyncPredictions:
    def test_v4_predict_job(self, server, glm):
        st, out = _req(server, "POST",
                       f"/4/Predictions/models/{glm}/frames/ops_train")
        assert st == 200, out
        job = out["job"]["key"]["name"]
        dest = out["predictions_frame"]["name"]
        for _ in range(100):
            st, j = _req(server, "GET", f"/3/Jobs/{job}")
            if j["jobs"][0]["status"] in ("DONE", "FAILED"):
                break
            time.sleep(0.05)
        assert j["jobs"][0]["status"] == "DONE", j
        st, fr = _req(server, "GET", f"/3/Frames/{dest}")
        assert st == 200 and fr["frames"][0]["rows"] == 400


class TestModelIO:
    def test_export_import_roundtrip(self, server, glm, tmp_path):
        st, out = _req(server, "GET",
                       f"/99/Models.bin/{glm}?dir={tmp_path}")
        assert st == 200, out
        st, _ = _req(server, "DELETE", "/3/Models/ops_glm_copy")
        st, out = _req(server, "POST",
                       f"/99/Models.bin/ops_glm_copy?dir={tmp_path}/{glm}")
        assert st == 200, out
        assert out["models"][0]["model_id"]["name"] == "ops_glm_copy"

    def test_upload_model_binary(self, server, glm, tmp_path):
        st, out = _req(server, "GET",
                       f"/99/Models.bin/{glm}?dir={tmp_path}/up")
        assert st == 200, out
        blob = open(out["dir"], "rb").read()
        st, out = _req(server, "POST", "/99/Models.upload.bin/ops_glm_up",
                       body_bytes=blob)
        assert st == 200, out
        st, out = _req(server, "GET", "/99/Models/ops_glm_up/json")
        assert st == 200 and out["models"][0]["algo"] == "glm"

    def test_new_model_id(self, server):
        st, out = _req(server, "POST", "/3/ModelBuilders/gbm/model_id")
        assert st == 200 and out["model_id"]["name"].startswith("gbm_model")


class TestMungingUtilities:
    def test_tabulate(self, server, glm):
        st, out = _req(server, "POST", "/99/Tabulate", {
            "dataset": "ops_train", "predictor": "x0", "response": "y",
            "nbins_predictor": 5})
        assert st == 200, out
        ct = out["count_table"]
        assert len(ct["predictor_labels"]) == 5
        assert sum(map(sum, ct["counts"])) == 400
        # x0 drives y: mean response should rise across x0 bins
        mr = out["response_table"]["mean_response"]
        assert mr[-1] > mr[0]

    def test_interaction(self, server, glm):
        st, out = _req(server, "POST", "/3/Interaction", {
            "source_frame": "ops_train", "factor_columns": ["c1", "c2"],
            "dest": "ops_inter"})
        assert st == 200, out
        st, fr = _req(server, "GET", "/3/Frames/ops_inter")
        assert fr["frames"][0]["rows"] == 400
        dom = set(out["domains"][0])
        assert {"u_p", "u_q", "v_p", "v_q"} <= dom

    def test_interaction_max_factors_trims(self, server, glm):
        st, out = _req(server, "POST", "/3/Interaction", {
            "source_frame": "ops_train", "factor_columns": ["c1", "c2"],
            "max_factors": 2, "dest": "ops_inter2"})
        assert st == 200, out
        assert len(out["domains"][0]) == 3  # 2 kept + "other"

    def test_dct(self, server, glm):
        st, out = _req(server, "POST", "/99/Rapids", {
            "ast": "(= ops_num (cols_py ops_train ['x0' 'x1']))"})
        assert st == 200, out
        st, out = _req(server, "POST", "/99/DCTTransformer", {
            "dataset": "ops_num", "dimensions": [2, 1, 1],
            "destination_frame": "ops_dct"})
        assert st == 200, out
        st, fr = _req(server, "GET", "/3/Frames/ops_dct")
        assert fr["frames"][0]["num_columns"] == 2
        # orthonormal DCT preserves the L2 norm of each row
        from h2o3_tpu.keyed import DKV

        src, dst = DKV.get("ops_num"), DKV.get("ops_dct")
        X = np.column_stack([c.numeric_view() for c in src.columns])
        Y = np.column_stack([c.numeric_view() for c in dst.columns])
        np.testing.assert_allclose(
            np.linalg.norm(X, axis=1), np.linalg.norm(Y, axis=1), rtol=1e-6)


class TestNPS:
    def test_full_lifecycle(self, server):
        st, out = _req(server, "GET", "/3/NodePersistentStorage/configured")
        assert st == 200 and out["configured"]
        st, out = _req(server, "POST", "/3/NodePersistentStorage/nb/one",
                       {"value": "hello flow"})
        assert st == 200, out
        st, out = _req(server, "GET",
                       "/3/NodePersistentStorage/categories/nb/exists")
        assert out["exists"]
        st, out = _req(
            server, "GET",
            "/3/NodePersistentStorage/categories/nb/names/one/exists")
        assert out["exists"]
        st, raw = _req(server, "GET", "/3/NodePersistentStorage/nb/one",
                       raw=True)
        assert raw == b"hello flow"
        st, out = _req(server, "GET", "/3/NodePersistentStorage/nb")
        assert any(e["name"] == "one" for e in out["entries"])
        st, out = _req(server, "POST", "/3/NodePersistentStorage/nb",
                       {"value": "auto-named"})
        assert st == 200 and out["name"]
        st, out = _req(server, "DELETE", "/3/NodePersistentStorage/nb/one")
        assert out["deleted"]
        st, out = _req(
            server, "GET",
            "/3/NodePersistentStorage/categories/nb/names/one/exists")
        assert not out["exists"]

    def test_binary_body_put(self, server):
        st, out = _req(server, "POST", "/3/NodePersistentStorage/nb/bin",
                       body_bytes=b"\x00\x01\xff")
        assert st == 200, out
        st, raw = _req(server, "GET", "/3/NodePersistentStorage/nb/bin",
                       raw=True)
        assert raw == b"\x00\x01\xff"

    def test_path_escape_rejected(self, server):
        st, out = _req(server, "POST",
                       "/3/NodePersistentStorage/nb/..%2F..%2Fetc",
                       {"value": "nope"})
        # sanitised into a plain segment (no traversal), never a 500 crash
        assert st in (200, 400)
        import os

        assert not os.path.exists("/tmp/etc")


class TestFrameDrillDown:
    def test_column_page(self, server, glm):
        st, out = _req(server, "GET",
                       "/3/Frames/ops_train/columns/x0?row_count=7")
        assert st == 200, out
        assert out["columns"][0]["label"] == "x0"
        assert len(out["columns"][0]["data"]) == 7

    def test_column_summary(self, server, glm):
        st, out = _req(server, "GET",
                       "/3/Frames/ops_train/columns/x0/summary")
        assert st == 200, out
        c = out["frames"][0]["columns"][0]
        assert len(c["percentiles"]) == 11
        assert sum(c["histogram_bins"]) == 400

    def test_column_domain(self, server, glm):
        st, out = _req(server, "GET",
                       "/3/Frames/ops_train/columns/y/domain")
        assert st == 200 and out["domain"][0] == ["no", "yes"]
        st, out = _req(server, "GET",
                       "/3/Frames/ops_train/columns/x0/domain")
        assert st == 400

    def test_light_and_chunks(self, server, glm):
        st, out = _req(server, "GET", "/3/Frames/ops_train/light")
        assert st == 200 and out["frames"][0]["rows"] == 400
        assert "columns" not in out["frames"][0]
        st, out = _req(server, "GET", "/3/FrameChunks/ops_train")
        assert st == 200 and len(out["chunks"]) == 5

    def test_find(self, server, glm):
        st, out = _req(server, "GET",
                       "/3/Find?key=ops_train&column=c1&match=u&row=0")
        assert st == 200, out
        assert out["next"] >= 0
        st, out2 = _req(
            server, "GET",
            f"/3/Find?key=ops_train&column=c1&match=u&row={out['next'] + 1}")
        assert out2["prev"] <= out["next"] or out2["prev"] == out["next"]

    def test_download_bin(self, server, glm):
        st, raw = _req(server, "GET",
                       "/3/DownloadDataset.bin?frame_id=ops_train", raw=True)
        assert st == 200
        lines = raw.decode().splitlines()
        assert lines[0] == "x0,x1,c1,c2,y" and len(lines) == 401


class TestClusterOps:
    def test_dkv_delete_key(self, server):
        from h2o3_tpu.frame.frame import Column, Frame
        from h2o3_tpu.keyed import DKV

        fr = Frame([Column("a", np.arange(3.0))])
        DKV.put("ops_tmp", fr)
        st, out = _req(server, "DELETE", "/3/DKV/ops_tmp")
        assert st == 200 and "ops_tmp" not in DKV
        st, out = _req(server, "DELETE", "/3/DKV/ops_tmp")
        assert st == 404

    def test_log_and_echo(self, server):
        from h2o3_tpu.util import log as L

        st, out = _req(server, "POST", "/3/LogAndEcho",
                       {"message": "ops-echo-sentinel"})
        assert st == 200 and out["message"] == "ops-echo-sentinel"
        assert any("ops-echo-sentinel" in line for line in L.recent(50))

    def test_kill_minus_3(self, server):
        from h2o3_tpu.util import log as L

        st, _ = _req(server, "GET", "/3/KillMinus3")
        assert st == 200
        assert any("thread" in line.lower() for line in L.recent(200))

    def test_unlock_keys(self, server):
        from h2o3_tpu.keyed import DKV

        DKV.read_lock("ops_lock_target", "test-owner")
        st, _ = _req(server, "POST", "/3/UnlockKeys")
        assert st == 200
        assert DKV.locked_by("ops_lock_target") == []

    def test_cloud_lock(self, server):
        st, out = _req(server, "POST", "/3/CloudLock", {"reason": "test"})
        assert st == 200 and out["locked"]

    def test_network_test(self, server):
        st, out = _req(server, "GET", "/3/NetworkTest")
        assert st == 200
        assert len(out["table"]) == 3
        assert all(row["microseconds"] > 0 for row in out["table"])

    def test_watermeter_io(self, server):
        st, out = _req(server, "GET", "/3/WaterMeterIo")
        assert st == 200
        if out["available"]:
            assert out["persist_stats"][0]["read_bytes"] >= 0
        st, out2 = _req(server, "GET", "/3/WaterMeterIo/0")
        assert st == 200

    def test_watermeter_cpu_node(self, server):
        st, out = _req(server, "GET", "/3/WaterMeterCpuTicks/0")
        assert st == 200

    def test_logs_node_file(self, server):
        st, raw = _req(server, "GET", "/3/Logs/nodes/0/files/default",
                       raw=True)
        assert st == 200 and raw


class TestDiscovery:
    def test_typeahead(self, server, tmp_path):
        for n in ("data1.csv", "data2.csv", "other.txt"):
            (tmp_path / n).write_text("a\n1\n")
        st, out = _req(server, "GET",
                       f"/3/Typeahead/files?src={tmp_path}/data&limit=10")
        assert st == 200
        assert len(out["matches"]) == 2
        st, out = _req(server, "GET",
                       f"/3/Typeahead/files?src={tmp_path}")
        assert len(out["matches"]) == 3

    def test_rapids_help(self, server):
        st, out = _req(server, "GET", "/99/Rapids/help")
        assert st == 200
        names = {s["name"] for s in out["syntaxes"]}
        assert len(names) > 150
        assert {"cols_py", "merge", "sort"} <= names

    def test_capabilities(self, server):
        st, core = _req(server, "GET", "/3/Capabilities/Core")
        assert st == 200 and core["capabilities"]
        st, api = _req(server, "GET", "/3/Capabilities/API")
        assert st == 200 and len(api["capabilities"]) >= 100

    def test_sample_and_steam(self, server):
        st, _ = _req(server, "GET", "/99/Sample")
        assert st == 200
        st, out = _req(server, "GET", "/3/SteamMetrics")
        assert st == 200 and "malloced_bytes" in out

    def test_endpoint_metadata_by_number_and_substring(self, server):
        st, out = _req(server, "GET", "/3/Metadata/endpoints/0")
        assert st == 200 and len(out["routes"]) == 1
        st, out = _req(server, "GET", "/3/Metadata/endpoints/ModelMetrics")
        assert st == 200 and len(out["routes"]) >= 10

    def test_schemaclasses_alias(self, server):
        st, names = _req(server, "GET", "/3/Metadata/schemas")
        assert st == 200
        name = names["schemas"][0]["name"]
        st, out = _req(server, "GET",
                       f"/3/Metadata/schemaclasses/{name}")
        assert st == 200


class TestGridBinURIs:
    def test_grid_bin_roundtrip(self, server, glm, tmp_path):
        st, out = _req(server, "POST", "/99/Grid/glm", {
            "training_frame": "ops_train", "response_column": "y",
            "family": "binomial", "grid_id": "ops_grid",
            "hyper_parameters": {"lambda_": [0.0, 0.1]}})
        assert st == 200, out
        st, out = _req(server, "POST",
                       f"/3/Grid.bin/ops_grid/export?dir={tmp_path}")
        assert st == 200, out
        st, out = _req(server, "POST",
                       f"/3/Grid.bin/import?dir={tmp_path}/ops_grid.bin")
        assert st == 200, out


class TestProfiler:
    def test_sampled_stacks_nonempty(self, server):
        import threading

        stop = threading.Event()

        def busy():
            x = 0
            while not stop.is_set():
                x += 1

        t = threading.Thread(target=busy, daemon=True)
        t.start()
        try:
            st, out = _req(server, "GET", "/3/Profiler?duration=0.2")
        finally:
            stop.set()
        assert st == 200, out
        prof = out["nodes"][0]["profile"]
        assert prof and prof[0]["count"] > 0

    def test_trace_toggle(self, server, tmp_path):
        st, out = _req(server, "POST", "/3/Profiler/trace",
                       {"action": "start", "dir": str(tmp_path / "tr")})
        if st == 500:
            pytest.skip(f"jax.profiler unavailable: {out['msg']}")
        assert st == 200 and out["active"]
        # double start conflicts
        st, _ = _req(server, "POST", "/3/Profiler/trace",
                     {"action": "start", "dir": str(tmp_path / "tr2")})
        assert st == 409
        st, out = _req(server, "POST", "/3/Profiler/trace",
                       {"action": "stop"})
        assert st == 200 and not out["active"]
        st, _ = _req(server, "POST", "/3/Profiler/trace", {"action": "stop"})
        assert st == 409


class TestRealShutdown:
    def test_shutdown_stops_answering(self):
        s = start_server(port=0)
        st, out = _req(s, "POST", "/3/Shutdown")
        assert st == 200
        time.sleep(0.8)
        with pytest.raises(Exception):
            urllib.request.urlopen(s.url + "/3/Ping", timeout=2)


class TestFlowDocuments:
    """Flow-as-notebook (VERDICT r4 item 9): cell documents persist via
    NPS category "notebook" (the reference Flow's own save mechanism,
    h2o-web + NodePersistentStorage) and replay server-side."""

    def test_save_load_replay_roundtrip(self, server):
        import json as _json

        import numpy as np

        from h2o3_tpu.keyed import DKV
        from h2o3_tpu.frame.frame import Column, Frame

        fr = Frame([Column("v", np.array([3.0, 1.0, 2.0]))])
        fr.key = "flowsrc"
        DKV.put("flowsrc", fr)
        doc = {"version": 1, "cells": [
            {"input": "(= flowsorted (sort flowsrc [0] [1]))",
             "output": None},
            {"input": "(mean (cols_py flowsorted 0) 1 0)", "output": None},
        ]}
        # save exactly like the Flow UI's Save button
        st, out = _req(server, "POST",
                       "/3/NodePersistentStorage/notebook/myflow",
                       {"value": _json.dumps(doc)})
        assert st == 200, out
        # load round-trip: the document comes back byte-identical
        st, raw = _req(server, "GET",
                       "/3/NodePersistentStorage/notebook/myflow", raw=True)
        assert _json.loads(raw.decode()) == doc
        # list shows it (the Flow UI's dropdown)
        st, out = _req(server, "GET", "/3/NodePersistentStorage/notebook")
        assert any(e["name"] == "myflow" for e in out["entries"])
        # server-side replay executes every cell in order
        st, out = _req(server, "POST", "/99/Flow/myflow/run")
        assert st == 200, out
        assert [c["ok"] for c in out["cells"]] == [True, True]
        assert out["cells"][1]["result"]["scalar"] == 2.0
        sorted_fr = DKV.get("flowsorted")
        np.testing.assert_array_equal(
            sorted_fr.col(0).numeric_view(), [1.0, 2.0, 3.0])
        DKV.remove("flowsrc")
        DKV.remove("flowsorted")
        _req(server, "DELETE", "/3/NodePersistentStorage/notebook/myflow")

    def test_replay_missing_flow_404s(self, server):
        st, out = _req(server, "POST", "/99/Flow/absent/run")
        assert st == 404

    def test_flow_page_has_notebook_controls(self, server):
        st, raw = _req(server, "GET", "/flow/index.html", raw=True)
        html = raw.decode()
        for el in ("id=history", "id=fsave", "id=fload", "id=freplay",
                   "NodePersistentStorage/notebook"):
            assert el in html, el
