"""Fixed-shape level plans: the node-bucket padding ladder.

Contract (h2o3_tpu/ops/histogram.py): every histogram/totals launch pads
its node dimension up to a bucket ladder (default 8/64/512, override
``H2O3_TPU_HIST_NODE_BUCKETS``) so ONE traced jit plan serves every tree
level that lands in the same bucket; the real node rows are sliced back
out and the result is BIT-identical to the unpadded build, because the
scatter-add accumulation order does not depend on the destination
capacity. ``hist_plan_cache_total{result}`` meters lookups against the
padded-shape plan cache — a warm fit must record zero misses.
"""

import pickle

import numpy as np
import pytest

import jax.numpy as jnp

from h2o3_tpu import Frame
from h2o3_tpu.models.grid import metric_value
from h2o3_tpu.models.tree import DRF, GBM, XGBoost
from h2o3_tpu.ops import histogram as H

pytestmark = pytest.mark.leaks_keys


# ---------------------------------------------------------------------------
# the ladder itself


def test_pad_nodes_default_ladder():
    assert H.node_buckets() == (8, 64, 512)
    # bucket edges: at the edge stays, one past jumps to the next rung,
    # past the top rung runs unpadded
    for n, want in [(1, 8), (7, 8), (8, 8), (9, 64), (64, 64),
                    (65, 512), (512, 512), (513, 513), (4096, 4096)]:
        assert H.pad_nodes(n) == want, (n, want)


def test_pad_nodes_env_ladder(monkeypatch):
    monkeypatch.setenv("H2O3_TPU_HIST_NODE_BUCKETS", "4,16")
    assert H.node_buckets() == (4, 16)
    assert [H.pad_nodes(n) for n in (1, 4, 5, 16, 17)] == [4, 4, 16, 16, 17]
    # no positive buckets -> padding disabled, every shape runs as-is
    monkeypatch.setenv("H2O3_TPU_HIST_NODE_BUCKETS", "0")
    assert H.node_buckets() == ()
    assert H.pad_nodes(3) == 3
    # garbage falls back to the default ladder rather than breaking fits
    monkeypatch.setenv("H2O3_TPU_HIST_NODE_BUCKETS", "eight")
    assert H.node_buckets() == (8, 64, 512)


# ---------------------------------------------------------------------------
# bit-identity of padded launches, across the bucket boundaries


def _level_inputs(rng, n, k, f=3, b=6):
    bins = jnp.asarray(rng.integers(0, b + 1, size=(n, f)).astype(np.int32))
    nodes = jnp.asarray(rng.integers(-1, k, size=n).astype(np.int32))
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray(rng.random(n).astype(np.float32))
    rw = jnp.asarray((1.0 + rng.random(n)).astype(np.float32))
    return bins, nodes, g, h, rw, b + 1


@pytest.mark.parametrize("k", [1, 7, 8, 9, 64, 65])
@pytest.mark.parametrize("with_rw", [False, True])
def test_padded_bit_identical(monkeypatch, rng, k, with_rw):
    bins, nodes, g, h, rw, n_bins1 = _level_inputs(rng, 1024, k)
    rw = rw if with_rw else None
    hist = np.asarray(H.build_histogram_sharded(
        bins, nodes, g, h, n_nodes=k, n_bins1=n_bins1, rw=rw))
    tot = np.asarray(H.node_totals_sharded(nodes, g, h, n_nodes=k, rw=rw))
    monkeypatch.setenv("H2O3_TPU_HIST_NODE_BUCKETS", "0")  # unpadded ref
    ref_h = np.asarray(H.build_histogram_sharded(
        bins, nodes, g, h, n_nodes=k, n_bins1=n_bins1, rw=rw))
    ref_t = np.asarray(H.node_totals_sharded(nodes, g, h, n_nodes=k, rw=rw))
    assert hist.shape == ref_h.shape == (k, 3, n_bins1, 3)
    assert hist.tobytes() == ref_h.tobytes(), f"histogram drift at k={k}"
    assert tot.tobytes() == ref_t.tobytes(), f"totals drift at k={k}"


def test_pad_rows_are_exact_zero(rng):
    # node ids never reach the pad rows, so the padded capacity beyond the
    # real node count accumulates exact 0.0 — assert via the full padded
    # build with the ladder forced to a single oversized bucket
    bins, nodes, g, h, _, n_bins1 = _level_inputs(rng, 512, 3)
    full = np.asarray(H._build_histogram_jit(
        bins, nodes, g, h, None, None, 8, n_bins1, None, "scatter", "f32",
        "auto"))
    assert full.shape[0] == 8
    assert not full[3:].any(), "pad rows picked up mass"


# ---------------------------------------------------------------------------
# plan-cache accounting: one miss per bucket, hits for every level after


def _plan(result):
    from h2o3_tpu.util import telemetry

    c = telemetry.REGISTRY.get("hist_plan_cache_total")
    return 0.0 if c is None else c.value(result=result)


def test_one_plan_per_bucket(rng):
    bins, nodes, g, h, _, n_bins1 = _level_inputs(rng, 2048, 8)
    miss0, hit0 = _plan("miss"), _plan("hit")
    for k in (1, 2, 4, 8):  # one bucket: four "levels", one plan
        nk = jnp.asarray(rng.integers(-1, k, size=2048).astype(np.int32))
        H.build_histogram_sharded(bins, nk, g, h, n_nodes=k, n_bins1=n_bins1)
    miss = _plan("miss") - miss0
    hit = _plan("hit") - hit0
    assert miss <= 1, f"plan churn inside one bucket: {miss} misses"
    assert miss + hit == 4


# ---------------------------------------------------------------------------
# whole-fit bit-identity: the ladder must never change a model


def _frames(seed=7, n=3000):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 5))
    reg = 3 * X[:, 0] + np.sin(3 * X[:, 1]) * 2 + X[:, 2] * X[:, 3]
    cls = np.where(reg + 0.3 * rng.normal(size=n) > 0, "yes", "no")
    cols = {f"x{i}": X[:, i] for i in range(5)}
    return (Frame.from_dict(cols | {"y": reg}),
            Frame.from_dict(cols | {"y": cls}))


def _sig(model):
    bt = model.booster
    arrays = [
        np.stack(getattr(t, f))
        for t in bt.trees_per_class
        for f in ("feat", "split_bin", "default_left", "is_split", "leaf")
    ]
    return pickle.dumps([arrays, np.asarray(bt.init_margin),
                         metric_value(model, "auto")[0]])


def _model(algo):
    kw = dict(response_column="y", ntrees=3, max_depth=4, seed=11)
    if algo == "gbm":
        return GBM(**kw)
    if algo == "drf":
        return DRF(sample_rate=0.7, **kw)
    return XGBoost(**kw)


@pytest.mark.parametrize("algo", ["gbm", "drf", "xgb"])
@pytest.mark.parametrize("resp", ["reg", "bin"])
def test_fit_matrix_padded_vs_unpadded(monkeypatch, algo, resp):
    fr_reg, fr_bin = _frames()
    fr = fr_reg if resp == "reg" else fr_bin
    padded = _model(algo).train(fr)
    monkeypatch.setenv("H2O3_TPU_HIST_NODE_BUCKETS", "0")
    unpadded = _model(algo).train(fr)
    assert _sig(padded) == _sig(unpadded), f"{algo}/{resp} drifts under padding"


def test_warm_fit_compiles_no_plans():
    fr_reg, _ = _frames()
    _model("gbm").train(fr_reg)  # cold: traces this shape family once
    miss0 = _plan("miss")
    _model("gbm").train(fr_reg)  # warm: every level must hit
    assert _plan("miss") == miss0, "warm fit missed the plan cache"
