"""Distributed (map-side histogram) tree training over chunk homes.

The contract under test (h2o3_tpu/models/tree/dist_hist.py): when the
training frame is a chunk-homed DistFrame, GBM/DRF/XGBoost build each
tree level map-side — grad/hess and histograms computed on the rows'
homes, only ``(feature, bin, {Σg, Σh, Σw})`` partials crossing the wire
— and the result is BIT-IDENTICAL to running the same engine entirely
on the caller (``H2O3_TPU_DIST_HIST=local``), at a fixed seed, with or
without histogram subtraction, and through a home's refusal/death
mid-level (replica ladder + seq-fenced context replay).

The multi-run seeded-verdict version of the death drill lives in
scripts/chaos.py (``kill_hist_home``); here each invariant asserts once.
"""

import pickle
import threading
import time

import numpy as np
import pytest

from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import rpc as crpc
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster.frames import DistFrame
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.frame.parse import _iter_body_chunks, parse_setup
from h2o3_tpu.keyed import KeyedStore
from h2o3_tpu.models.grid import metric_value
from h2o3_tpu.models.tree import dist_hist
from h2o3_tpu.models.tree.drf import DRF, DRFParameters
from h2o3_tpu.models.tree.gbm import GBM, GBMParameters
from h2o3_tpu.models.tree.xgboost import XGBoost, XGBoostParameters

pytestmark = pytest.mark.leaks_keys

RESPONSES = ("reg", "bin", "multi")


def _csv(n=6000):
    """Deterministic integer-valued features (exact under any partition
    order) + a CAT feature + one response column per family."""
    f = [np.arange(n) % p for p in (97, 31, 13, 7, 53, 23)]
    cats = ("lo", "mid", "hi")
    bins = ("no", "yes")
    multis = ("a", "b", "c")
    lines = ["x0,x1,x2,x3,x4,x5,c,reg,bin,multi"]
    for i in range(n):
        s = (f[0][i] * 3 + f[1][i]) % 11
        lines.append(
            f"{f[0][i]},{f[1][i]},{f[2][i]},{f[3][i]},{f[4][i]},{f[5][i]},"
            f"{cats[i % 3]},{s}.0,{bins[int(s < 4)]},{multis[s % 3]}")
    return "\n".join(lines) + "\n"


def _wait_for(cond, timeout=15.0, every=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


def _form_cloud(n, prefix):
    clouds = []
    for i in range(n):
        c = Cloud("disttree", f"{prefix}{i}", hb_interval=0.05)
        s = KeyedStore()
        cdkv.install(c, s)
        ctasks.install(c)
        clouds.append(c)
    seeds = [c.info.addr for c in clouds]
    for c in clouds:
        c.start([a for a in seeds if a != c.info.addr])
    _wait_for(lambda: all(c.size() == n for c in clouds),
              msg=f"{n}-node cloud formation")
    return clouds


def _stop_all(clouds):
    for c in clouds:
        try:
            c.stop()
        except Exception:
            pass


def _parse_to_homes(cloud, key):
    text = _csv()
    setup = parse_setup(text)
    chunks = list(_iter_body_chunks(
        [text.encode()], 16384, setup.header, setup.skip_blank_lines))
    fr = ctasks.distributed_parse_chunks(chunks, setup, cloud=cloud, key=key)
    assert isinstance(fr, DistFrame)
    assert len({g["home_name"] for g in fr.chunk_layout["groups"]}) >= 2
    return fr


@pytest.fixture(scope="module")
def homed():
    """A formed 3-node cloud + a CSV parsed ONTO the ring."""
    clouds = _form_cloud(3, "dt")
    set_local_cloud(clouds[0])
    try:
        fr = _parse_to_homes(clouds[0], "dist_tree_df")
        yield clouds, fr
    finally:
        set_local_cloud(None)
        _stop_all(clouds)


def _params(algo, resp):
    ignored = [r for r in RESPONSES if r != resp]
    common = dict(response_column=resp, ignored_columns=ignored,
                  ntrees=3, max_depth=3, min_rows=1.0, seed=11)
    if algo == "gbm":
        return GBM(GBMParameters(nbins=12, **common))
    if algo == "drf":
        return DRF(DRFParameters(nbins=12, sample_rate=0.7, **common))
    return XGBoost(XGBoostParameters(nbins=12, **common))


def _fit(algo, resp, fr):
    return _params(algo, resp).train(fr)


def _sig(model):
    """Leaderboard-relevant bytes: every tree array + training metric."""
    bt = model.booster
    arrays = [
        np.stack(getattr(t, f))
        for t in bt.trees_per_class
        for f in ("feat", "split_bin", "default_left", "is_split", "leaf")
    ]
    return pickle.dumps([arrays, np.asarray(bt.init_margin),
                         metric_value(model, "auto")[0]])


def _counter(name, **labels):
    from h2o3_tpu.util import telemetry

    c = telemetry.REGISTRY.get(name)
    if c is None:
        return 0.0
    return c.value(**labels) if labels else c.total()


def _wire_bytes():
    from h2o3_tpu.util import telemetry

    c = telemetry.REGISTRY.get("rpc_payload_bytes_total")
    if c is None:
        return 0.0
    return sum(s["value"] for s in c.snapshot()["series"])


# ---------------------------------------------------------------------------
# the bit-identity matrix


class TestBitIdentity:
    @pytest.mark.parametrize("algo", ["gbm", "drf", "xgb"])
    @pytest.mark.parametrize("resp", ["reg", "bin", "multi"])
    def test_dist_matches_local(self, homed, monkeypatch, algo, resp):
        clouds, fr = homed
        monkeypatch.setenv("H2O3_TPU_DIST_HIST", "local")
        ref = _fit(algo, resp, fr)
        monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")
        t0 = _counter("dist_hist_fits_total", mode="dist")
        dist = _fit(algo, resp, fr)
        assert _counter("dist_hist_fits_total", mode="dist") == t0 + 1, (
            "fit did not take the distributed fan-out path")
        assert _sig(dist) == _sig(ref)

    @pytest.mark.parametrize("subtract", ["0", "1"])
    def test_subtract_modes(self, homed, monkeypatch, subtract):
        clouds, fr = homed
        monkeypatch.setenv("H2O3_TPU_TREE_SUBTRACT", subtract)
        monkeypatch.setenv("H2O3_TPU_DIST_HIST", "local")
        ref = _fit("gbm", "multi", fr)
        monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")
        assert _sig(_fit("gbm", "multi", fr)) == _sig(ref)


# ---------------------------------------------------------------------------
# wire discipline: partials cross, rows never do


def test_partials_only(homed, monkeypatch):
    clouds, fr = homed
    lay = fr.chunk_layout
    frame_bytes = 8 * int(lay["espc"][-1]) * len(lay["column_names"])
    monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")
    levels0 = _counter("dist_hist_levels_total")
    partial0 = _counter("dist_hist_partial_bytes_total")
    wire0 = _wire_bytes()
    _fit("gbm", "bin", fr)
    wire = _wire_bytes() - wire0
    levels = _counter("dist_hist_levels_total") - levels0
    partial = _counter("dist_hist_partial_bytes_total") - partial0
    assert levels > 0
    # per level, each home ships at most n_nodes x F x n_bins1 x 3 x 8
    # (one class block at depth<=3: <=4 frontier nodes)
    n_homes = len(lay["groups"])
    n_feat = 7  # x0..x5 + c
    n_bins1 = 12 + 1  # interior edges + NA bin
    per_level_cap = 4 * n_feat * n_bins1 * 3 * 8 * n_homes
    assert partial <= levels * per_level_cap
    # total wire (requests + responses, incl. the one-time y gather and
    # gossip noise) stays well under shipping the frame to the members
    assert wire < frame_bytes / 2


# ---------------------------------------------------------------------------
# warm fits ride the device cache: zero re-decode, zero re-upload


def test_warm_fit_reuses_resident_bins(homed, monkeypatch):
    """A second fit on an unmutated DistFrame must serve every home's
    binned codes and sketches from the device cache: zero apply_bins
    decodes, zero upload-charging misses — one bind-cache hit per group."""
    clouds, fr = homed
    monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")
    _fit("gbm", "reg", fr)  # cold at most once; later fits must be warm
    n_groups = len(fr.chunk_layout["groups"])
    hit0 = _counter("dist_hist_bind_cache_total", result="hit")
    miss0 = _counter("dist_hist_bind_cache_total", result="miss")
    up0 = _counter("devcache_requests_total",
                   kind="hist_bins_home", result="miss")
    sk0 = _counter("devcache_requests_total",
                   kind="hist_sketch_home", result="miss")
    _fit("gbm", "reg", fr)
    assert _counter("dist_hist_bind_cache_total", result="miss") == miss0, (
        "warm fit re-decoded binned codes")
    assert _counter("dist_hist_bind_cache_total",
                    result="hit") == hit0 + n_groups
    assert _counter("devcache_requests_total", kind="hist_bins_home",
                    result="miss") == up0, "warm fit re-uploaded binned codes"
    assert _counter("devcache_requests_total", kind="hist_sketch_home",
                    result="miss") == sk0, "warm fit re-sketched columns"


# ---------------------------------------------------------------------------
# batched level rounds


def test_batched_rounds_bit_identical(homed, monkeypatch):
    """Coalescing output-free fin ops into hist_levels multi-op rounds
    must not move a single bit — and must actually batch (>=2 ops per
    round) when enabled."""
    clouds, fr = homed
    monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")
    monkeypatch.setenv("H2O3_TPU_DIST_HIST_BATCH", "0")
    ref = _sig(_fit("gbm", "bin", fr))

    calls = {"n": 0}
    real = dist_hist.hist_levels

    def counting(payload, cloud, store):
        assert len(payload["ops"]) >= 2, "single-op round routed to batch op"
        calls["n"] += 1
        return real(payload, cloud, store)

    monkeypatch.setenv("H2O3_TPU_DIST_HIST_BATCH", "1")
    monkeypatch.setattr(dist_hist, "hist_levels", counting)
    monkeypatch.setitem(dist_hist._HANDLERS, "hist_levels", counting)
    assert _sig(_fit("gbm", "bin", fr)) == ref
    assert calls["n"] > 0, "batching on but no multi-op round went out"


# ---------------------------------------------------------------------------
# context fencing + replay


def test_seq_fence_409():
    st = dist_hist._GroupState(0)
    st.last_seq = 5
    with pytest.raises(crpc.RpcFault) as ei:
        dist_hist._check_seq(st, 8)
    assert ei.value.code == 409
    dist_hist._check_seq(st, 6)  # in-order op advances the fence
    assert st.last_seq == 6


def test_missing_ctx_404():
    with pytest.raises(crpc.RpcFault) as ei:
        dist_hist._ctx_group({"ctx_id": "nope#0", "g": 0})
    assert ei.value.code == 404


def test_replay_after_ctx_eviction(homed, monkeypatch):
    """An evicted home context (LRU pressure, member restart) must 404
    the next op and rebuild bit-identically from open+bind+oplog."""
    clouds, fr = homed
    monkeypatch.setenv("H2O3_TPU_DIST_HIST", "local")
    ref = _sig(_fit("gbm", "reg", fr))
    monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")

    real = dist_hist.hist_level
    lock = threading.Lock()
    fired = {"n": 0}

    def evicting(payload, cloud, store):
        with lock:
            if fired["n"] == 0 and payload["op"]["kind"] == "level":
                fired["n"] = 1
                dist_hist._ctx_drop(payload["ctx_id"])
        return real(payload, cloud, store)

    monkeypatch.setattr(dist_hist, "hist_level", evicting)
    monkeypatch.setitem(dist_hist._HANDLERS, "hist_level", evicting)
    assert _sig(_fit("gbm", "reg", fr)) == ref
    assert fired["n"] == 1


# ---------------------------------------------------------------------------
# a home refuses + dies mid-fit: the replica ladder finishes the fit


def test_dead_home_recovers(monkeypatch):
    from h2o3_tpu.cluster import faults

    clouds = _form_cloud(3, "dk")
    set_local_cloud(clouds[0])
    try:
        fr = _parse_to_homes(clouds[0], "dist_tree_kill_df")
        monkeypatch.setenv("H2O3_TPU_DIST_HIST", "local")
        ref = _sig(_fit("gbm", "bin", fr))
        monkeypatch.setenv("H2O3_TPU_DIST_HIST", "1")

        victim_name = next(
            g["home_name"] for g in fr.chunk_layout["groups"]
            if g["home_name"] != clouds[0].info.name)
        victim = next(c for c in clouds if c.info.name == victim_name)
        plan = faults.plan_from_dict({"seed": 7, "rules": [
            {"action": "drop", "side": "server", "src": victim_name,
             "method": "dtask:hist_level"},
        ]})
        faults.set_plan(plan)
        rep0 = _counter("cluster_fanout_recovered_total", path="replica")
        box = {}

        def _train():
            try:
                box["sig"] = _sig(_fit("gbm", "bin", fr))
            except Exception as e:  # pragma: no cover - invariant failure
                box["err"] = e

        th = threading.Thread(target=_train, daemon=True)
        th.start()
        time.sleep(0.3)
        victim.stop()
        th.join(timeout=120.0)
        assert plan.hits()[0] > 0, "fault rule never fired"
        assert "err" not in box, f"fit failed: {box.get('err')}"
        assert box["sig"] == ref
        assert _counter("cluster_fanout_recovered_total",
                        path="replica") > rep0
    finally:
        faults.clear_plan()
        set_local_cloud(None)
        _stop_all(clouds)


# ---------------------------------------------------------------------------
# grid search trains against the homed frame by reference


def test_search_ships_dist_reference(homed):
    from h2o3_tpu.cluster import search as csearch

    clouds, fr = homed
    payload = csearch.frame_payload(fr)
    assert set(payload) == {"__dist__"}
    assert payload["__dist__"]["frame_key"] == fr.key
    # a member rebuilds the handle from ITS OWN store, ring-resolved
    store2 = clouds[1].dkv_store
    fr2 = csearch.frame_restore(payload, store2)
    assert isinstance(fr2, DistFrame)
    assert fr2.chunk_layout["stamp"] == fr.chunk_layout["stamp"]
    assert fr2.nrows == fr.nrows and fr2.names == fr.names
    # no store (a member without the DKV plane) is a typed refusal
    with pytest.raises(crpc.RpcFault) as ei:
        csearch.frame_restore(payload, None)
    assert ei.value.code == 503
