"""BASELINE.json config smokes on the reference's own datasets.

Config 1: "GLM binomial (hex.glm) on prostate.csv — single-node smoke
(coef/AUC parity)". The dataset is read from the reference checkout at
test time (public Ondrechen prostate data shipped with h2o-py); oracle
is sklearn LogisticRegression at matching regularization. Config
parity for iris (accuracyTestCases.csv case 1 shape: multinomial GBM)
rides the same datasets.
"""

import os

import numpy as np
import pytest

from h2o3_tpu.frame.ingest import import_parse

pytestmark = pytest.mark.leaks_keys

_PROSTATE = "/root/reference/h2o-py/h2o/h2o_data/prostate.csv"
_IRIS = "/root/reference/h2o-r/h2o-package/inst/extdata/iris_wheader.csv"


@pytest.mark.skipif(not os.path.exists(_PROSTATE),
                    reason="reference checkout not present")
class TestProstateGLM:
    def test_coef_and_auc_parity_vs_sklearn(self):
        from sklearn.linear_model import LogisticRegression
        from sklearn.metrics import roc_auc_score

        from h2o3_tpu.models.glm import GLM, GLMParameters

        fr = import_parse(_PROSTATE)
        preds = ["AGE", "RACE", "DPROS", "DCAPS", "PSA", "VOL", "GLEASON"]
        fr2 = fr.cols([fr.names.index(c) for c in preds]
                      + [fr.names.index("CAPSULE")])
        y = fr.col("CAPSULE").numeric_view().astype(int)
        m = GLM(GLMParameters(
            response_column="CAPSULE", family="binomial", lambda_=0.0,
            standardize=False)).train(fr2.with_factor("CAPSULE")
                                      if hasattr(fr2, "with_factor")
                                      else fr2)
        X = np.column_stack([fr.col(c).numeric_view() for c in preds])
        sk = LogisticRegression(penalty=None, max_iter=5000,
                                tol=1e-10).fit(X, y)
        got = np.array([m.coefficients[c] for c in preds])
        want = sk.coef_[0]
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-4)
        assert m.coefficients["Intercept"] == pytest.approx(
            sk.intercept_[0], rel=5e-3, abs=5e-3)
        p1 = m.predict(fr2).col("p1").numeric_view() \
            if "p1" in m.predict(fr2).names else \
            m._predict_raw(fr2)[:, 1]
        auc_h2o = roc_auc_score(y, p1)
        auc_sk = roc_auc_score(y, sk.predict_proba(X)[:, 1])
        assert auc_h2o == pytest.approx(auc_sk, abs=1e-3)


@pytest.mark.skipif(not os.path.exists(_IRIS),
                    reason="reference checkout not present")
class TestIrisMultinomialGBM:
    def test_case1_shape(self):
        """accuracyTestCases.csv case 1: multinomial GBM on iris,
        default-ish parameters — sanity on the reference's data."""
        from h2o3_tpu.models.tree.gbm import GBM

        fr = import_parse(_IRIS)
        m = GBM(ntrees=20, max_depth=5, response_column="class",
                seed=42, min_rows=2).train(fr)
        pred = m.predict(fr)
        labels = pred.col("predict").data
        truth = fr.col("class").data
        acc = float((labels == truth).mean())
        assert acc > 0.95, acc
