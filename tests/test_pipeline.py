"""Scoring pipeline (mojo-pipeline extension analogue): build from
assembly + model, portable zip artifact, offline reload, REST routes,
rapids verb, client functions.

Reference: ``h2o-extensions/mojo-pipeline/.../MojoPipeline.java``
(transform + strict adaptFrame), ``rapids/AstPipelineTransform.java``
(``mojo.pipeline.transform``)."""

import base64
import json
import os
import urllib.request

import numpy as np
import pytest

from h2o3_tpu.api import start_server

pytestmark = pytest.mark.leaks_keys

rng0 = np.random.default_rng(7)
CSV = "x0,x1,y\n" + "\n".join(
    f"{a:.4f},{b:.4f},{'yes' if a + b > 0 else 'no'}"
    for a, b in rng0.normal(size=(400, 2))
)


@pytest.fixture(scope="module")
def server():
    s = start_server(port=0)
    yield s
    s.stop()


def _req(server, method, path, data=None, raw=False):
    body = json.dumps(data).encode() if data is not None else None
    req = urllib.request.Request(
        server.url + path, data=body,
        headers={"Content-Type": "application/json"} if body else {},
        method=method)
    try:
        with urllib.request.urlopen(req) as resp:
            payload = resp.read()
            return resp.status, payload if raw else json.loads(payload)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def trained(server):
    """Parsed frame + fitted assembly (log-feature) + GBM on the munged
    frame; returns (frame_id, assembly_key, model_id, munged_id)."""
    st, up = _req(server, "POST", "/3/PostFile", {"data": CSV})
    assert st == 200
    st, out = _req(server, "POST", "/3/Parse",
                   {"source_frames": [up["destination_frame"]],
                    "destination_frame": "pipe_train"})
    assert st == 200, out
    steps = [
        {"op": "BinaryOp", "fun": "*", "left": "x0", "right": "x1",
         "new_col_name": "x0x1"},
    ]
    st, out = _req(server, "POST", "/99/Assembly",
                   {"frame": "pipe_train", "steps": steps,
                    "destination_frame": "pipe_munged"})
    assert st == 200, out
    asm_key = out["assembly"]["name"]
    st, out = _req(server, "POST", "/3/ModelBuilders/gbm",
                   {"training_frame": "pipe_munged", "response_column": "y",
                    "ntrees": 8, "max_depth": 3, "seed": 1, "min_rows": 3,
                    "model_id": "pipe_gbm"})
    assert st == 200, out
    return "pipe_train", asm_key, "pipe_gbm", "pipe_munged"


def test_build_transform_parity(server, trained):
    frame_id, asm_key, model_id, munged_id = trained
    st, out = _req(server, "POST", "/99/PipelineMojo",
                   {"model": model_id, "assembly": asm_key})
    assert st == 200, out
    pipe_key = out["pipeline"]["name"]
    assert out["has_model"] and "x0" in out["in_names"]

    # pipeline(raw frame) == predict(munged frame)
    st, out = _req(server, "POST", "/99/PipelineMojo.transform",
                   {"pipeline": pipe_key, "frame": frame_id,
                    "destination_frame": "pipe_pred"})
    assert st == 200, out
    assert out["names"][0] == "predict"
    st, pf = _req(server, "GET",
                  "/3/Frames/pipe_pred/columns/pyes/summary")
    assert st == 200
    st, direct = _req(server, "POST",
                      f"/3/Predictions/models/{model_id}/frames/{munged_id}",
                      {"predictions_frame": "direct_pred"})
    assert st == 200, direct
    st, df = _req(server, "GET",
                  "/3/Frames/direct_pred/columns/pyes/summary")
    assert st == 200
    a = pf["frames"][0]["columns"][0]
    b = df["frames"][0]["columns"][0]
    assert a["mean"] == pytest.approx(b["mean"], rel=1e-5)


def test_artifact_roundtrip_offline(server, trained, tmp_path):
    """Download the zip, load it OUTSIDE the server (ScoringPipeline.load),
    and score rows without any cluster objects."""
    frame_id, asm_key, model_id, _ = trained
    st, out = _req(server, "POST", "/99/PipelineMojo",
                   {"model": model_id, "assembly": asm_key})
    assert st == 200
    pipe_key = out["pipeline"]["name"]
    st, blob = _req(server, "GET", f"/99/PipelineMojo.fetch/{pipe_key}",
                    raw=True)
    assert st == 200 and isinstance(blob, bytes) and blob[:2] == b"PK"
    path = os.path.join(tmp_path, "pipe.zip")
    with open(path, "wb") as f:
        f.write(blob)

    from h2o3_tpu.frame.frame import ColType, Column, Frame
    from h2o3_tpu.models.pipeline import ScoringPipeline

    pipe = ScoringPipeline.load(path)
    assert pipe.steps and pipe.mojo_bytes
    x0 = rng0.normal(size=50)
    x1 = rng0.normal(size=50)
    fr = Frame([Column("x0", x0, ColType.NUM),
                Column("x1", x1, ColType.NUM)])
    out_fr = pipe.transform(fr)
    assert out_fr.names[0] == "predict"
    probs = out_fr.col("pyes").numeric_view()
    assert probs.shape == (50,) and np.all((probs >= 0) & (probs <= 1))

    # strict adaptFrame: missing input column must raise
    with pytest.raises(ValueError, match="missing a column: x1"):
        pipe.transform(Frame([Column("x0", x0, ColType.NUM)]))


def test_import_and_rapids_verb(server, trained, tmp_path):
    frame_id, asm_key, model_id, _ = trained
    st, out = _req(server, "POST", "/99/PipelineMojo",
                   {"model": model_id, "assembly": asm_key})
    assert st == 200
    st, blob = _req(server, "GET",
                    f"/99/PipelineMojo.fetch/{out['pipeline']['name']}",
                    raw=True)
    assert st == 200

    # import the artifact back under a fresh key (base64 body)
    st, imp = _req(server, "POST", "/99/PipelineMojo.import",
                   {"data": base64.b64encode(blob).decode(),
                    "destination_key": "pipe_imported"})
    assert st == 200, imp
    assert imp["pipeline"]["name"] == "pipe_imported"

    # the rapids verb (AstPipelineTransform signature)
    st, out = _req(server, "POST", "/99/Rapids",
                   {"ast": f'(tmp= rapids_out (mojo.pipeline.transform '
                           f'"pipe_imported" {frame_id} 0))'})
    assert st == 200, out
    st, sf = _req(server, "GET",
                  "/3/Frames/rapids_out/columns/predict/summary")
    assert st == 200, sf

    # bad artifact -> 400, not a crash
    st, bad = _req(server, "POST", "/99/PipelineMojo.import",
                   {"data": base64.b64encode(b"not a zip").decode()})
    assert st == 400


def test_transform_only_pipeline(server, trained):
    """An assembly-only pipeline returns the munged frame (no model)."""
    frame_id, asm_key, _, _ = trained
    st, out = _req(server, "POST", "/99/PipelineMojo",
                   {"assembly": asm_key})
    assert st == 200, out
    assert out["has_model"] is False
    st, tr = _req(server, "POST", "/99/PipelineMojo.transform",
                  {"pipeline": out["pipeline"]["name"], "frame": frame_id,
                   "destination_frame": "munge_only"})
    assert st == 200, tr
    assert "x0x1" in tr["names"] and "y" in tr["names"]

    # neither model nor assembly -> 400
    st, err = _req(server, "POST", "/99/PipelineMojo", {})
    assert st == 400


def test_client_pipeline_functions(server, trained, tmp_path):
    """h2o.build_pipeline / download_pipeline / import_pipeline /
    pipeline_transform over real HTTP."""
    frame_id, asm_key, model_id, _ = trained
    import h2o3_tpu.client as h2o

    h2o.connect(server.url)
    key = h2o.build_pipeline(model_id, assembly_id=asm_key)
    path = h2o.download_pipeline(key, str(tmp_path))
    assert os.path.exists(path)
    key2 = h2o.import_pipeline(path, pipeline_id="client_pipe")
    assert key2 == "client_pipe"
    pred = h2o.pipeline_transform(key2, frame_id)
    assert "predict" in pred.names
