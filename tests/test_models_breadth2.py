"""Breadth algorithms round 2: TargetEncoder, ExtendedIsolationForest,
Aggregator, StackedEnsemble (reference parity per SURVEY.md §2.2/§2.7)."""

import numpy as np
import pytest

from h2o3_tpu import Frame
from h2o3_tpu.frame.frame import ColType, Column


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


def _cat_frame(rng, n=600):
    levels = np.array(["a", "b", "c"])
    codes = rng.integers(0, 3, size=n)
    base = np.array([0.2, 0.5, 0.8])[codes]
    y = (rng.random(n) < base).astype(np.int64)
    return Frame([
        Column("cat", codes.astype(np.int32), ColType.CAT, list(levels)),
        Column("num", rng.normal(size=n), ColType.NUM),
        Column("y", y.astype(np.int32), ColType.CAT, ["no", "yes"]),
    ]), codes, y


class TestTargetEncoder:
    def test_encodes_level_means(self, rng):
        from h2o3_tpu.models.target_encoder import TargetEncoder

        fr, codes, y = _cat_frame(rng)
        te = TargetEncoder(response_column="y", columns_to_encode=["cat"], noise=0.0)
        model = te.train(fr)
        out = model.transform(fr)
        assert "cat_te" in out.names
        enc = out.col("cat_te").numeric_view()
        for k in range(3):
            expected = y[codes == k].mean()
            assert np.allclose(enc[codes == k], expected, atol=1e-12)

    def test_blending_shrinks_rare_levels(self, rng):
        from h2o3_tpu.models.target_encoder import TargetEncoder

        n = 500
        codes = np.zeros(n, dtype=np.int32)
        codes[:3] = 1  # rare level with extreme mean
        y = np.zeros(n, dtype=np.int32)
        y[:3] = 1
        fr = Frame([
            Column("cat", codes, ColType.CAT, ["common", "rare"]),
            Column("y", y, ColType.CAT, ["no", "yes"]),
        ])
        blended = TargetEncoder(
            response_column="y", columns_to_encode=["cat"], blending=True,
            inflection_point=10, smoothing=20, noise=0.0,
        ).train(fr)
        raw = TargetEncoder(
            response_column="y", columns_to_encode=["cat"], blending=False, noise=0.0
        ).train(fr)
        b = blended.transform(fr).col("cat_te").numeric_view()
        r = raw.transform(fr).col("cat_te").numeric_view()
        prior = y.mean()
        # raw posterior for the rare level is 1.0; blending pulls it toward the prior
        assert r[0] == pytest.approx(1.0)
        assert prior < b[0] < 1.0
        assert abs(b[0] - prior) < abs(r[0] - prior)

    def test_loo_subtracts_own_row(self, rng):
        from h2o3_tpu.models.target_encoder import TargetEncoder

        fr, codes, y = _cat_frame(rng, n=100)
        m = TargetEncoder(
            response_column="y", columns_to_encode=["cat"],
            data_leakage_handling="leave_one_out", noise=0.0,
        ).train(fr)
        enc = m.transform(fr, as_training=True).col("cat_te").numeric_view()
        k, i = codes[0], 0
        mask = codes == k
        expected = (y[mask].sum() - y[i]) / (mask.sum() - 1)
        assert enc[i] == pytest.approx(expected)

    def test_unseen_level_gets_prior(self, rng):
        from h2o3_tpu.models.target_encoder import TargetEncoder

        fr, codes, y = _cat_frame(rng)
        m = TargetEncoder(response_column="y", columns_to_encode=["cat"], noise=0.0).train(fr)
        test = Frame([
            Column("cat", np.zeros(4, np.int32), ColType.CAT, ["zz"]),
            Column("num", np.zeros(4), ColType.NUM),
        ])
        enc = m.transform(test).col("cat_te").numeric_view()
        assert np.allclose(enc, m.prior_mean)


class TestExtendedIsolationForest:
    def test_outliers_score_higher(self, rng):
        from h2o3_tpu.models.ext_isolation_forest import ExtendedIsolationForest

        inliers = rng.normal(size=(400, 4))
        outliers = rng.normal(size=(8, 4)) * 0.2 + 9.0
        X = np.vstack([inliers, outliers])
        fr = Frame.from_dict({f"x{j}": X[:, j] for j in range(4)})
        m = ExtendedIsolationForest(ntrees=60, sample_size=128, extension_level=3,
                                    seed=7).train(fr)
        pred = m.predict(fr)
        assert pred.names == ["anomaly_score", "mean_length"]
        s = pred.col("anomaly_score").numeric_view()
        assert s.min() >= 0.0 and s.max() <= 1.0
        assert s[-8:].mean() > s[:400].mean() + 0.1

    def test_extension_level_validation(self, rng):
        from h2o3_tpu.models.ext_isolation_forest import ExtendedIsolationForest

        fr = Frame.from_dict({"a": rng.normal(size=50), "b": rng.normal(size=50)})
        with pytest.raises(ValueError, match="extension_level"):
            ExtendedIsolationForest(ntrees=2, extension_level=5, seed=1).train(fr)


class TestAggregator:
    def test_reduces_to_target_exemplars(self, rng):
        from h2o3_tpu.models.aggregator import Aggregator

        X = rng.normal(size=(3000, 3))
        fr = Frame.from_dict({f"x{j}": X[:, j] for j in range(3)})
        m = Aggregator(target_num_exemplars=100, rel_tol_num_exemplars=0.5,
                       seed=1).train(fr)
        out = m.output_frame
        n_ex = out.nrows
        assert n_ex <= 100 * 1.5 + 1
        assert "counts" in out.names
        # counts conserve rows
        assert out.col("counts").numeric_view().sum() == pytest.approx(3000)

    def test_small_data_all_exemplars(self, rng):
        from h2o3_tpu.models.aggregator import Aggregator

        X = rng.normal(size=(40, 2))
        fr = Frame.from_dict({"a": X[:, 0], "b": X[:, 1]})
        m = Aggregator(target_num_exemplars=5000, seed=1).train(fr)
        assert m.output_frame.nrows == 40  # radius never grows


class TestStackedEnsemble:
    def test_beats_or_matches_base_models(self, rng):
        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.models.stacked_ensemble import StackedEnsemble
        from h2o3_tpu.models.tree.gbm import GBM

        n = 800
        X = rng.normal(size=(n, 5))
        logit = X[:, 0] - 0.8 * X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.int32)
        d = {f"x{j}": X[:, j] for j in range(5)}
        d["y"] = y
        fr = Frame.from_dict(d)
        fr = Frame([*fr.drop("y").columns,
                    Column("y", y, ColType.CAT, ["0", "1"])])

        common = dict(response_column="y", nfolds=3,
                      keep_cross_validation_predictions=True, seed=11)
        glm = GLM(family="binomial", **common).train(fr)
        gbm = GBM(ntrees=20, max_depth=3, **common).train(fr)

        se = StackedEnsemble(base_models=[glm, gbm], response_column="y",
                             seed=11).train(fr)
        auc_se = se.training_metrics.auc
        auc_base = max(glm.training_metrics.auc, gbm.training_metrics.auc)
        assert auc_se > 0.5
        assert auc_se >= auc_base - 0.05

        preds = se.predict(fr)
        assert preds.nrows == n
        assert "predict" in preds.names

    def test_requires_cv_predictions(self, rng):
        from h2o3_tpu.models.glm import GLM
        from h2o3_tpu.models.stacked_ensemble import StackedEnsemble

        n = 100
        fr = Frame.from_dict({"x": rng.normal(size=n), "y": rng.normal(size=n)})
        glm = GLM(response_column="y", family="gaussian").train(fr)
        with pytest.raises(ValueError, match="holdout"):
            StackedEnsemble(base_models=[glm], response_column="y").train(fr)
