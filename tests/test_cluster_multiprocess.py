"""Multi-process cluster tier: real OS processes, real cloud formation.

Reference analogue: the test suite's "N JVMs on localhost" cloud
(water.runner.H2ORunner + @CloudSize(n)).  Every node here binds its RPC
listener on port 0 and publishes the resolved address through an
address file the harness folds into the next node's flatfile — no fixed
ports, no collisions under parallel CI.  Every wait carries its own
watchdog deadline so a wedged node fails the test with output instead of
hanging the tier.

The tests:
  * 2-node full-stack cloud over ``python -m h2o3_tpu`` — /3/Cloud
    quorum on both nodes, cross-node DKV through the REST surface, node
    RPC proxies, and the suspicion flip after a SIGKILL (tier-1);
  * 2-node map_reduce fan-out bit-exactness with a real remote DTask
    executor (tier-1);
  * 3-node formation via the light nodeproc entry (marked slow);
  * SIGKILL drills (marked slow): a member killed mid-fan-out whose
    range a survivor absorbs, and a chunk HOME killed mid-chunk-homed
    map_reduce whose range survivors re-execute from replica chunks —
    then re-adopts its chunks after a same-ident reboot.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: outer watchdog for any single wait; generous because a cold full-node
#: boot initializes the XLA CPU backend
WAIT = 120.0


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["H2O3_TPU_HB_INTERVAL"] = "0.2"  # suspicion window: 5 * 0.2s
    return env


class _Proc:
    """Subprocess + stdout collector + watchdog-bounded helpers."""

    def __init__(self, cmd, cwd, env):
        self.proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, cwd=cwd, env=env)
        self.lines = []
        self._lock = threading.Lock()
        self._reader = threading.Thread(target=self._pump, daemon=True)
        self._reader.start()

    def _pump(self):
        for line in self.proc.stdout:
            with self._lock:
                self.lines.append(line)

    def output(self):
        with self._lock:
            return "".join(self.lines)

    def wait_for_line(self, needle, timeout=WAIT):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            out = self.output()
            if needle in out:
                return out
            if self.proc.poll() is not None:
                pytest.fail(
                    f"process exited rc={self.proc.returncode} before "
                    f"{needle!r}:\n{out[-4000:]}")
            time.sleep(0.05)
        self.kill()
        pytest.fail(f"timed out waiting for {needle!r}:\n"
                    f"{self.output()[-4000:]}")

    def kill(self, sig=signal.SIGKILL):
        try:
            self.proc.send_signal(sig)
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()


def _wait_file(path, timeout=WAIT):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                content = f.read().strip()
            if content:
                return content
        except OSError:
            pass
        time.sleep(0.05)
    pytest.fail(f"address file {path} never appeared")


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _post_json(url, data, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(data).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _poll(fn, timeout, msg, every=0.2):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        ok, last = fn()
        if ok:
            return last
        time.sleep(every)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}; "
                f"last state: {str(last)[:2000]}")


def _full_node(tmp, name, flatfile, env):
    addr_file = os.path.join(tmp, f"{name}.addr")
    node = _Proc(
        [sys.executable, "-m", "h2o3_tpu", "--port", "0",
         "--name", "mpcloud", "--flatfile", flatfile,
         "--cluster-name", "mpcloud", "--node-name", name,
         "--cluster-address-file", addr_file],
        cwd=tmp, env=env)
    return node, addr_file


class TestTwoNodeCloudREST:
    """The acceptance path: formation quorum, cross-node DKV, proxies,
    and the suspicion flip — all through the REST surface."""

    def test_two_node_cloud(self, tmp_path):
        tmp = str(tmp_path)
        env = _env()
        flat0 = os.path.join(tmp, "flat0")
        open(flat0, "w").close()  # node 0 seeds nobody; node 1 seeds it
        n0, addr0_file = _full_node(tmp, "n0", flat0, env)
        n1 = None
        try:
            addr0 = _wait_file(addr0_file)
            flat1 = os.path.join(tmp, "flat1")
            with open(flat1, "w") as f:
                f.write(addr0 + "\n")
            n1, _ = _full_node(tmp, "n1", flat1, env)
            url0 = n0.wait_for_line("up at ").split("up at ")[1].split()[0]
            url1 = n1.wait_for_line("up at ").split("up at ")[1].split()[0]

            # -- formation: same sorted member list + hash on BOTH nodes
            def formed():
                try:
                    _, c0 = _get(url0 + "/3/Cloud")
                    _, c1 = _get(url1 + "/3/Cloud")
                except (urllib.error.URLError, OSError) as e:
                    return False, str(e)
                ok = (c0["cloud_size"] == 2 and c1["cloud_size"] == 2
                      and c0["consensus"] and c1["consensus"])
                return ok, (c0, c1)

            c0, c1 = _poll(formed, WAIT, "2-node cloud quorum")
            assert c0["cloud_hash"] == c1["cloud_hash"]
            assert [n["name"] for n in c0["nodes"]] == ["n0", "n1"]
            assert [n["name"] for n in c1["nodes"]] == ["n0", "n1"]
            assert all(n["healthy"] for n in c0["nodes"])
            ages = [n["last_heartbeat_age_ms"] for n in c0["nodes"]]
            assert all(isinstance(a, int) and a < 60000 for a in ages)

            # -- cross-node DKV: a key homed on n1, put via n0, read via
            # both (the distributed router, through REST)
            key = None
            for i in range(256):
                k = f"mpkey{i}"
                _, home = _get(url0 + f"/3/DKV/{k}/home")
                if home["home"] == "n1":
                    key = k
                    break
            assert key is not None, "no probe key homed on n1?!"
            st, put_out = _post_json(
                url0 + f"/3/DKV/{key}", {"value": {"answer": [4, 2]}})
            assert st == 200 and put_out["home"] == "n1"
            st, got0 = _get(url0 + f"/3/DKV/{key}")
            st1, got1 = _get(url1 + f"/3/DKV/{key}")
            assert st == 200 and st1 == 200
            assert got0["value"] == got1["value"] == {"answer": [4, 2]}

            # -- node-addressed observability proxies over RPC
            st, ticks1 = _get(url0 + "/3/WaterMeterCpuTicks/1")
            assert st == 200 and "cpu_ticks" in ticks1
            with urllib.request.urlopen(
                    url0 + "/3/Logs/nodes/1/files/default",
                    timeout=10.0) as resp:
                assert resp.status == 200

            # -- kill n1: /3/Cloud on n0 flips health inside the
            # suspicion window (5 beats * 0.2s, plus scheduling slack)
            n1.kill(signal.SIGKILL)
            t0 = time.monotonic()

            def flipped():
                try:
                    _, c = _get(url0 + "/3/Cloud")
                except (urllib.error.URLError, OSError) as e:
                    return False, str(e)
                n1_rows = [n for n in c["nodes"] if n["name"] == "n1"]
                # suspected (healthy: false, cloud_healthy flips) or
                # already removed from the member list entirely
                if n1_rows:
                    return (not n1_rows[0]["healthy"]
                            and not c["cloud_healthy"]), c
                return True, c

            _poll(flipped, 30.0, "suspicion flip after SIGKILL")
            assert time.monotonic() - t0 < 30.0
        finally:
            if n1 is not None:
                n1.kill()
            n0.kill()


def _write_mr_worker(tmp):
    """worker0: forms a 2-node cloud with a nodeproc peer, then checks
    distributed map_reduce bit-exactness against the local path."""
    with open(os.path.join(tmp, "mrfns.py"), "w") as f:
        f.write(
            "import jax.numpy as jnp\n"
            "def stat(cols, mask):\n"
            "    return {'s': jnp.sum(jnp.where(mask, cols['x'], 0.0)),\n"
            "            'n': jnp.sum(mask.astype(jnp.float32))}\n")
    script = f"""
import sys, time
sys.path.insert(0, {REPO!r})
sys.path.insert(0, {tmp!r})
import numpy as np
import mrfns
from h2o3_tpu.cluster.membership import Cloud
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.util import telemetry

cloud = Cloud("mrcloud", "w0", hb_interval=0.2)
ctasks.install(cloud)
with open({tmp!r} + "/w0.addr.tmp", "w") as f:
    f.write(f"{{cloud.info.host}}:{{cloud.info.port}}\\n")
import os
os.replace({tmp!r} + "/w0.addr.tmp", {tmp!r} + "/w0.addr")
cloud.start([])
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    if cloud.size() == 2 and cloud.consensus():
        break
    time.sleep(0.05)
assert cloud.size() == 2, f"cloud never formed: {{cloud.size()}}"

peer = next(m for m in cloud.members_sorted() if m.info.name == "w1")
assert ctasks.submit(cloud, peer, "echo", 7) == 7

cols = {{"x": np.arange(4001, dtype=np.float64)}}
local = ctasks.distributed_map_reduce(mrfns.stat, cols, cloud=None)
dist = ctasks.distributed_map_reduce(mrfns.stat, cols, cloud=cloud)
for k in ("s", "n"):
    a, b = np.asarray(local[k]), np.asarray(dist[k])
    assert a.tobytes() == b.tobytes(), f"{{k}}: {{a}} != {{b}}"
assert float(dist["s"]) == float(np.arange(4001).sum())
assert telemetry.REGISTRY.get("cluster_task_fanout").value() == 2

# the REMOTE node really ran its shard: its own meters say so
peer_metrics = cloud.client.call(
    peer.info.addr, "metrics", None, timeout=10.0)
assert peer_metrics.get("cluster_tasks_total", 0) >= 1, peer_metrics
cloud.stop()
print("W0 OK", flush=True)
"""
    path = os.path.join(tmp, "worker0.py")
    with open(path, "w") as f:
        f.write(script)
    return path


class TestMapReduceFanout:
    def test_two_node_map_reduce_bit_exact(self, tmp_path):
        tmp = str(tmp_path)
        env = _env()
        w0 = _Proc([sys.executable, _write_mr_worker(tmp)],
                   cwd=tmp, env=env)
        w1 = None
        try:
            addr0 = _wait_file(os.path.join(tmp, "w0.addr"))
            flat = os.path.join(tmp, "flat")
            with open(flat, "w") as f:
                f.write(addr0 + "\n")
            w1 = _Proc(
                [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
                 "--cluster-name", "mrcloud", "--node-name", "w1",
                 "--flatfile", flat, "--hb-interval", "0.2"],
                cwd=tmp, env=env)
            w0.wait_for_line("W0 OK", timeout=240)
            assert w0.proc.wait(timeout=30) == 0
        finally:
            if w1 is not None:
                w1.kill()
            w0.kill()


@pytest.mark.slow
class TestThreeNodeFormation:
    """3-node formation via the light nodeproc entry; the harness polls
    each node's ``members`` RPC until all three agree on one hash."""

    def test_three_nodes_agree(self, tmp_path):
        from h2o3_tpu.cluster.rpc import RpcClient, RPCError

        tmp = str(tmp_path)
        env = _env()
        procs = []
        addrs = []
        try:
            for i in range(3):
                flat = os.path.join(tmp, f"flat{i}")
                with open(flat, "w") as f:
                    f.write("".join(a + "\n" for a in addrs))
                addr_file = os.path.join(tmp, f"n{i}.addr")
                procs.append(_Proc(
                    [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
                     "--cluster-name", "tri", "--node-name", f"tri{i}",
                     "--flatfile", flat, "--address-file", addr_file,
                     "--hb-interval", "0.2"],
                    cwd=tmp, env=env))
                addrs.append(_wait_file(addr_file))
            client = RpcClient()
            targets = [(h, int(p)) for h, _, p in
                       (a.rpartition(":") for a in addrs)]

            def agree():
                views = []
                for t in targets:
                    try:
                        views.append(client.call(
                            t, "members", None, timeout=5.0))
                    except RPCError as e:
                        return False, str(e)
                ok = (all(v["size"] == 3 for v in views)
                      and len({v["hash"] for v in views}) == 1
                      and all(v["consensus"] for v in views)
                      and len({tuple(v["members"]) for v in views}) == 1)
                return ok, views

            views = _poll(agree, WAIT, "3-node quorum")
            assert len(views[0]["members"]) == 3
            client.close()
        finally:
            for p in procs:
                p.kill()


def _write_chaos_mr_worker(tmp):
    """worker0: forms a 3-node cloud with two nodeproc peers, scripts a
    server-side dtask delay onto the victim (w2) through the nemesis RPC
    surface, then runs distributed map_reduce while the harness SIGKILLs
    the victim mid-flight.  Asserts the result is bit-identical to the
    local path, that the victim's range was rescheduled onto a SURVIVOR
    (not re-run caller-locally), and that the survivor's own meters
    prove it absorbed the extra range."""
    with open(os.path.join(tmp, "mrfns.py"), "w") as f:
        f.write(
            "import jax.numpy as jnp\n"
            "def stat(cols, mask):\n"
            "    return {'s': jnp.sum(jnp.where(mask, cols['x'], 0.0)),\n"
            "            'n': jnp.sum(mask.astype(jnp.float32))}\n")
    script = f"""
import sys, time
sys.path.insert(0, {REPO!r})
sys.path.insert(0, {tmp!r})
import numpy as np
import mrfns
from h2o3_tpu.cluster.membership import Cloud
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.util import telemetry

cloud = Cloud("killcloud", "w0", hb_interval=0.2)
ctasks.install(cloud)
import os
with open({tmp!r} + "/w0.addr.tmp", "w") as f:
    f.write(f"{{cloud.info.host}}:{{cloud.info.port}}\\n")
os.replace({tmp!r} + "/w0.addr.tmp", {tmp!r} + "/w0.addr")
cloud.start([])
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    if cloud.size() == 3 and cloud.consensus():
        break
    time.sleep(0.05)
assert cloud.size() == 3, f"cloud never formed: {{cloud.size()}}"

victim = next(m for m in cloud.members_sorted() if m.info.name == "w2")
survivor = next(m for m in cloud.members_sorted() if m.info.name == "w1")
# nemesis: the victim sits on its dtask long enough for the harness's
# SIGKILL (fired on "MR START") to land while the range is in flight
out = cloud.client.call(victim.info.addr, "fault_plan_set", {{
    "seed": 7, "rules": [{{"action": "delay", "side": "server",
                           "method": "dtask", "delay_ms": 2500}}]}})
assert out["installed"], out

cols = {{"x": np.arange(4001, dtype=np.float64)}}
local = ctasks.distributed_map_reduce(mrfns.stat, cols, cloud=None)
print("MR START", flush=True)
dist = ctasks.distributed_map_reduce(mrfns.stat, cols, cloud=cloud)
for k in ("s", "n"):
    a, b = np.asarray(local[k]), np.asarray(dist[k])
    assert a.tobytes() == b.tobytes(), f"{{k}}: {{a}} != {{b}}"

# the dead member's range went to a SURVIVOR, not the caller-local
# last resort
rec = telemetry.REGISTRY.get("cluster_fanout_recovered_total")
assert rec is not None and rec.value(path="survivor") >= 1, (
    rec and rec.value(path="survivor"))
# remote-side proof: the survivor's own meters counted both its range
# and the rescheduled one
peer_metrics = cloud.client.call(
    survivor.info.addr, "metrics", None, timeout=10.0)
assert peer_metrics.get("cluster_tasks_total", 0) >= 2, peer_metrics

# and the cloud reconverges on the survivors
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if cloud.size() == 2:
        break
    time.sleep(0.05)
assert cloud.size() == 2, f"victim never removed: {{cloud.size()}}"
cloud.stop()
print("W0 OK", flush=True)
"""
    path = os.path.join(tmp, "worker0_chaos.py")
    with open(path, "w") as f:
        f.write(script)
    return path


class TestSigkillDuringFanout:
    """SIGKILL a member while its map_reduce range is in flight: the
    cluster — not the caller — absorbs the loss, bit-exactly."""

    def test_sigkill_mid_map_reduce(self, tmp_path):
        tmp = str(tmp_path)
        env = _env()
        env["H2O3_TPU_FAULTS"] = "1"  # nemesis RPC surface on every node
        w0 = _Proc([sys.executable, _write_chaos_mr_worker(tmp)],
                   cwd=tmp, env=env)
        peers = {}
        try:
            addr0 = _wait_file(os.path.join(tmp, "w0.addr"))
            flat = os.path.join(tmp, "flat")
            with open(flat, "w") as f:
                f.write(addr0 + "\n")
            for name in ("w1", "w2"):
                peers[name] = _Proc(
                    [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
                     "--cluster-name", "killcloud", "--node-name", name,
                     "--flatfile", flat, "--hb-interval", "0.2"],
                    cwd=tmp, env=env)
            w0.wait_for_line("MR START", timeout=240)
            # the victim's injected 2.5s dtask delay is still ticking:
            # this SIGKILL lands while it owns an in-flight range
            time.sleep(0.8)
            peers["w2"].kill(signal.SIGKILL)
            w0.wait_for_line("W0 OK", timeout=240)
            assert w0.proc.wait(timeout=30) == 0
        finally:
            for p in peers.values():
                p.kill()
            w0.kill()


def _write_chunk_home_worker(tmp):
    """worker0: forms a 3-node cloud, parses a CSV chunk-homed across the
    ring, scripts a server-side dtask delay onto a victim HOME, then runs
    a chunk-homed map_reduce while the harness SIGKILLs that home
    mid-flight.  Asserts the reduction is bit-identical to the local
    path, that the dead home's ranges re-executed FROM REPLICA CHUNKS
    (path=replica, zero caller-local re-parses), and — once the harness
    reboots the victim on its OLD port (same ident, same ring arcs) —
    that the restarted-empty home re-adopts its chunks through the
    read-repair walk and the chunk-homed MR still reduces bit-exactly."""
    with open(os.path.join(tmp, "mrfns.py"), "w") as f:
        f.write(
            "import jax.numpy as jnp\n"
            "def stat(cols, mask):\n"
            "    return {'n': jnp.sum(mask.astype(jnp.float32)),\n"
            "            'sx': jnp.sum(jnp.where(mask, cols['x'], 0.0)),\n"
            "            'sy': jnp.sum(jnp.where(mask, cols['y'], 0.0))}\n")
    script = f"""
import sys, time
sys.path.insert(0, {REPO!r})
sys.path.insert(0, {tmp!r})
import numpy as np
import mrfns
from h2o3_tpu.cluster.membership import boot_node
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.util import telemetry

cloud = boot_node("chunkcloud", "w0",
                  address_file={tmp!r} + "/w0.addr")
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    if cloud.size() == 3 and cloud.consensus():
        break
    time.sleep(0.05)
assert cloud.size() == 3, f"cloud never formed: {{cloud.size()}}"

from h2o3_tpu.frame.parse import _iter_body_chunks, parse_setup
from h2o3_tpu.cluster.frames import DistFrame, chunk_key

n = 12000
x = np.arange(n) % 97
y = (np.arange(n) * 7) % 31
text = "x,y\\n" + "".join(f"{{x[i]}},{{y[i]}}\\n" for i in range(n))
setup = parse_setup(text)
chunks = list(_iter_body_chunks([text.encode()], 8192, setup.header,
                                setup.skip_blank_lines))
assert len(chunks) >= 6, len(chunks)
fr = ctasks.distributed_parse_chunks(chunks, setup, cloud=cloud,
                                     key="mp_dist_frame")
assert isinstance(fr, DistFrame), type(fr)
lay = fr.chunk_layout
assert len({{g["home_name"] for g in lay["groups"]}}) >= 2, lay["groups"]
vgrp = next(g for g in lay["groups"] if g["home_name"] != "w0")
victim_name = vgrp["home_name"]
victim = next(m for m in cloud.members_sorted()
              if m.info.name == victim_name)
print("VICTIM " + victim_name, flush=True)

# nemesis: the victim home sits on its chunk task long enough for the
# harness's SIGKILL (fired on "MR START") to land while its range is
# in flight
out = cloud.client.call(victim.info.addr, "fault_plan_set", {{
    "seed": 7, "rules": [{{"action": "delay", "side": "server",
                           "method": "dtask", "delay_ms": 2500}}]}})
assert out["installed"], out

host = {{"x": x.astype(np.float64), "y": y.astype(np.float64)}}
local = ctasks.distributed_map_reduce(mrfns.stat, host, cloud=None)

def _same(a, b):
    import jax
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return all(np.asarray(p).tobytes() == np.asarray(q).tobytes()
               for p, q in zip(la, lb))

print("MR START", flush=True)
dist = ctasks.distributed_map_reduce(mrfns.stat, fr, cloud=cloud,
                                     timeout=120.0)
assert _same(local, dist), (local, dist)
rec = telemetry.REGISTRY.get("cluster_fanout_recovered_total")
assert rec is not None and rec.value(path="replica") >= 1, (
    rec and rec.value(path="replica"))
# the dead home's range came from replica chunks, NOT a caller re-parse
assert rec.value(path="local") == 0, rec.value(path="local")

deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if cloud.size() == 2:
        break
    time.sleep(0.05)
assert cloud.size() == 2, f"victim never removed: {{cloud.size()}}"
print("VICTIM DEAD", flush=True)

# the harness now reboots the victim on its OLD port: same ident, so
# the ring hands it back exactly the arcs (and chunks) it owned
deadline = time.monotonic() + 120
while time.monotonic() < deadline:
    if cloud.size() == 3 and cloud.consensus():
        break
    time.sleep(0.05)
assert cloud.size() == 3, f"victim never rejoined: {{cloud.size()}}"
reborn = next(m for m in cloud.members_sorted()
              if m.info.name == victim_name)

# restarted-empty home re-adopts its chunks: routed gets drive the
# read-repair walk, the anti-entropy sweep converges the rest, and the
# direct (local-only) probe on the reborn node proves possession
want = list(range(vgrp["lo"], vgrp["hi"]))
store = cloud.dkv_store
deadline = time.monotonic() + 60
adopted = 0
while time.monotonic() < deadline:
    for i in want:
        assert store.get(chunk_key(vgrp["anchor"], i)) is not None
    adopted = sum(
        1 for i in want
        if cloud.client.call(reborn.info.addr, "dkv_get",
                             {{"key": chunk_key(vgrp["anchor"], i)}},
                             timeout=10.0).get("found"))
    if adopted == len(want):
        break
    time.sleep(0.5)
assert adopted == len(want), f"re-adopted {{adopted}}/{{len(want)}}"

dist2 = ctasks.distributed_map_reduce(mrfns.stat, fr, cloud=cloud,
                                      timeout=120.0)
assert _same(local, dist2), (local, dist2)
cloud.stop()
print("W0 OK", flush=True)
"""
    path = os.path.join(tmp, "worker0_chunk_home.py")
    with open(path, "w") as f:
        f.write(script)
    return path


@pytest.mark.slow
class TestSigkillChunkHome:
    """SIGKILL a chunk HOME while its chunk-homed map_reduce range is in
    flight: survivors re-execute the range from replica chunks
    (path=replica, never a caller re-parse), and a same-ident reboot
    re-adopts the dead home's chunks through the read-repair walk."""

    def test_sigkill_chunk_home_mid_map_reduce(self, tmp_path):
        tmp = str(tmp_path)
        env = _env()
        env["H2O3_TPU_FAULTS"] = "1"  # nemesis RPC surface on every node
        w0 = _Proc([sys.executable, _write_chunk_home_worker(tmp)],
                   cwd=tmp, env=env)
        peers = {}
        addrs = {}
        try:
            addr0 = _wait_file(os.path.join(tmp, "w0.addr"))
            flat = os.path.join(tmp, "flat")
            with open(flat, "w") as f:
                f.write(addr0 + "\n")
            for name in ("w1", "w2"):
                addr_file = os.path.join(tmp, f"{name}.addr")
                peers[name] = _Proc(
                    [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
                     "--cluster-name", "chunkcloud", "--node-name", name,
                     "--flatfile", flat, "--address-file", addr_file,
                     "--hb-interval", "0.2"],
                    cwd=tmp, env=env)
                addrs[name] = _wait_file(addr_file)
            out = w0.wait_for_line("VICTIM ", timeout=240)
            victim = out.split("VICTIM ", 1)[1].split()[0]
            assert victim in peers, victim
            w0.wait_for_line("MR START", timeout=240)
            # the victim home's injected 2.5s dtask delay is still
            # ticking: this SIGKILL lands while its range is in flight
            time.sleep(0.8)
            peers[victim].kill(signal.SIGKILL)
            w0.wait_for_line("VICTIM DEAD", timeout=240)
            # reboot the victim on its OLD port — same ident, so the
            # ring hands the restarted-empty home its old arcs back
            old_port = addrs[victim].rpartition(":")[2]
            peers[victim + "'"] = _Proc(
                [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
                 "--cluster-name", "chunkcloud", "--node-name", victim,
                 "--flatfile", flat, "--port", old_port,
                 "--hb-interval", "0.2"],
                cwd=tmp, env=env)
            w0.wait_for_line("W0 OK", timeout=240)
            assert w0.proc.wait(timeout=30) == 0
        finally:
            for p in peers.values():
                p.kill()
            w0.kill()


def _write_search_worker(tmp):
    """worker0: forms a 3-node cloud, runs the single-node baseline grid
    BEFORE becoming the local cloud (so it walks in-process), scripts a
    server-side delay onto the victim's ``search_cell`` dtask through
    the nemesis RPC surface, then fans the same grid across the cloud
    while the harness SIGKILLs the victim mid-cell.  Asserts the
    distributed leaderboard is bit-identical to the baseline in
    canonical walk order, that survivors re-claimed the victim's cells
    (``path=survivor`` metered caller-side), that progress streamed
    from at least two members, and that membership reconverges."""
    script = f"""
import sys, time
sys.path.insert(0, {REPO!r})
import numpy as np
from h2o3_tpu.cluster import dkv as cdkv
from h2o3_tpu.cluster import tasks as ctasks
from h2o3_tpu.cluster.membership import Cloud, set_local_cloud
from h2o3_tpu.keyed import KeyedStore
from h2o3_tpu.models.glm import GLM, GLMParameters
from h2o3_tpu.models.grid import GridSearch, cell_key, metric_value
from h2o3_tpu.util import telemetry

cloud = Cloud("searchkill", "w0", hb_interval=0.2)
cdkv.install(cloud, KeyedStore())
ctasks.install(cloud)
import os
with open({tmp!r} + "/w0.addr.tmp", "w") as f:
    f.write(f"{{cloud.info.host}}:{{cloud.info.port}}\\n")
os.replace({tmp!r} + "/w0.addr.tmp", {tmp!r} + "/w0.addr")
cloud.start([])
deadline = time.monotonic() + 90
while time.monotonic() < deadline:
    if cloud.size() == 3 and cloud.consensus():
        break
    time.sleep(0.05)
assert cloud.size() == 3, f"cloud never formed: {{cloud.size()}}"

rng = np.random.default_rng(11)
n = 400
X = rng.normal(size=(n, 3))
logit = X @ np.array([1.0, -2.0, 0.5])
y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float64)
from h2o3_tpu.frame.frame import ColType, Column, Frame
cols = [Column(f"x{{i}}", X[:, i]) for i in range(3)]
cols.append(Column("y", y, ColType.CAT, ["n", "p"]))
fr = Frame(cols)

def gs():
    return GridSearch(
        GLM,
        GLMParameters(response_column="y", family="binomial",
                      seed=7, nfolds=2),
        {{"alpha": [0.0, 0.5, 1.0], "lambda_": [0.01, 0.1]}})

def rows(grid):
    return [(cell_key(hp), metric_value(m, "auto")[0])
            for hp, m in zip(grid.hyper_params, grid.models)]

# baseline walks in-process: no local cloud is set yet
base = rows(gs().train(fr))
assert len(base) == 6

victim = next(m for m in cloud.members_sorted() if m.info.name == "w2")
# nemesis: the victim sits on each search_cell long enough for the
# harness's SIGKILL (fired on "SEARCH START") to land mid-cell
out = cloud.client.call(victim.info.addr, "fault_plan_set", {{
    "seed": 7, "rules": [{{"action": "delay", "side": "server",
                           "method": "dtask:search_cell",
                           "delay_ms": 2500}}]}})
assert out["installed"], out

set_local_cloud(cloud)
print("SEARCH START", flush=True)
grid = gs().train(fr)
set_local_cloud(None)
assert len(grid.models) == 6, grid
assert rows(grid) == base, (rows(grid), base)

rec = telemetry.REGISTRY.get("cluster_search_recovered_total")
assert rec is not None and rec.value(path="survivor") >= 1, (
    rec and rec.value(path="survivor"))
from h2o3_tpu.cluster.search import search_progress
prog = search_progress(grid.grid_id)
assert prog is not None and prog["done"] == 6, prog
assert len(prog["by_member"]) >= 2, prog

deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if cloud.size() == 2:
        break
    time.sleep(0.05)
assert cloud.size() == 2, f"victim never removed: {{cloud.size()}}"
cloud.stop()
print("W0 OK", flush=True)
"""
    path = os.path.join(tmp, "worker0_search.py")
    with open(path, "w") as f:
        f.write(script)
    return path


@pytest.mark.slow
class TestSigkillSearchMember:
    """SIGKILL a member while it owns in-flight grid cells: survivors
    re-claim them and the leaderboard stays bit-identical to the
    single-node walk."""

    def test_sigkill_mid_grid_search(self, tmp_path):
        tmp = str(tmp_path)
        env = _env()
        env["H2O3_TPU_FAULTS"] = "1"  # nemesis RPC surface on every node
        env["JAX_PLATFORMS"] = "cpu"
        w0 = _Proc([sys.executable, _write_search_worker(tmp)],
                   cwd=tmp, env=env)
        peers = {}
        try:
            addr0 = _wait_file(os.path.join(tmp, "w0.addr"))
            flat = os.path.join(tmp, "flat")
            with open(flat, "w") as f:
                f.write(addr0 + "\n")
            for name in ("w1", "w2"):
                peers[name] = _Proc(
                    [sys.executable, "-m", "h2o3_tpu.cluster.nodeproc",
                     "--cluster-name", "searchkill", "--node-name", name,
                     "--flatfile", flat, "--hb-interval", "0.2"],
                    cwd=tmp, env=env)
            w0.wait_for_line("SEARCH START", timeout=240)
            # the victim's injected 2.5s search_cell delay is still
            # ticking: this SIGKILL lands while it owns in-flight cells
            time.sleep(0.8)
            peers["w2"].kill(signal.SIGKILL)
            w0.wait_for_line("W0 OK", timeout=240)
            assert w0.proc.wait(timeout=30) == 0
        finally:
            for p in peers.values():
                p.kill()
            w0.kill()
