"""Python client end-to-end over real HTTP (the h2o-py surface:
init -> upload -> munge lazily via rapids -> train -> predict -> mojo)."""

import numpy as np
import pytest

from h2o3_tpu import client as h2o


# legacy module predating the CheckKeysTask fixture: tests here
# share/train keys without per-test cleanup; the module-level
# sweeper still removes everything at module end
pytestmark = pytest.mark.leaks_keys


@pytest.fixture(scope="module")
def conn():
    c = h2o.init()
    yield c
    h2o.shutdown()


@pytest.fixture()
def iris(conn):
    rng = np.random.default_rng(11)
    n = 240
    sl = rng.normal(5.8, 0.8, n)
    sw = rng.normal(3.0, 0.4, n)
    species = np.where(sl + sw + rng.normal(0, 0.5, n) > 9.0, "virginica", "setosa")
    csv = "sepal_len,sepal_wid,species\n" + "\n".join(
        f"{a:.4f},{b:.4f},{c}" for a, b, c in zip(sl, sw, species)
    ) + "\n"
    return h2o.upload_csv(csv)


class TestClientFrames:
    def test_shape_and_names(self, iris):
        assert iris.dim == [240, 3]
        assert iris.names == ["sepal_len", "sepal_wid", "species"]
        assert iris.types["species"] == "cat"

    def test_lazy_expr_scalar(self, iris):
        col = iris["sepal_len"]
        assert col.mean() == pytest.approx(5.8, abs=0.2)
        assert col.max() > col.min()
        assert iris["sepal_wid"].sd() == pytest.approx(0.4, abs=0.1)

    def test_arithmetic_dag(self, iris):
        doubled = (iris["sepal_len"] * 2 + 1).mean()
        assert doubled == pytest.approx(iris["sepal_len"].mean() * 2 + 1, rel=1e-9)

    def test_boolean_row_filter(self, iris):
        big = iris[iris["sepal_len"] > 6.0, :]
        assert 0 < big.nrows < 240
        assert big["sepal_len"].min() > 6.0

    def test_slicing_and_cbind(self, iris):
        two = iris[["sepal_len", "sepal_wid"]]
        assert two.ncols == 2
        both = two.cbind(iris["species"])
        assert both.ncols == 3
        head = iris.head(5)
        assert head.nrows == 5

    def test_factor_roundtrip(self, iris):
        fr = iris["sepal_len"].asfactor()
        assert fr.types[fr.names[0]] == "cat"

    def test_download_as_dict(self, iris):
        data = iris.get_frame_data()
        assert set(data) == {"sepal_len", "sepal_wid", "species"}
        assert len(data["species"]) == 240

    def test_ls_and_remove(self, conn, iris):
        iris.refresh()
        assert iris.frame_id in h2o.ls()


class TestClientModels:
    def test_gbm_train_predict(self, iris):
        est = h2o.H2OGradientBoostingEstimator(ntrees=10, max_depth=3, seed=1)
        model = est.train(y="species", training_frame=iris)
        assert model.algo == "gbm"
        assert model.auc() > 0.85
        pred = model.predict(iris)
        assert pred.nrows == 240
        assert "predict" in pred.names

    def test_glm_coefficients(self, iris):
        est = h2o.H2OGeneralizedLinearEstimator(family="binomial", lambda_=0.0)
        m = est.train(
            x=["sepal_len", "sepal_wid"], y="species", training_frame=iris
        )
        coefs = m.coef()
        assert set(coefs) >= {"sepal_len", "sepal_wid"}

    def test_x_subsetting_ignores_columns(self, iris):
        est = h2o.H2OGeneralizedLinearEstimator(family="binomial")
        m = est.train(x=["sepal_len"], y="species", training_frame=iris)
        assert "sepal_wid" not in m.coef()

    def test_kmeans(self, iris):
        est = h2o.H2OKMeansEstimator(k=3, seed=1, ignored_columns=["species"])
        m = est.train(training_frame=iris)
        pred = m.predict(iris)
        vals = {float(v) for v in pred.get_frame_data()["predict"]}
        assert vals <= {0.0, 1.0, 2.0}

    def test_mojo_download_scores_offline(self, iris, tmp_path):
        est = h2o.H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=2)
        m = est.train(y="species", training_frame=iris)
        path = str(tmp_path / "client.mojo")
        m.download_mojo(path)
        from h2o3_tpu.genmodel import load_mojo

        mm = load_mojo(path)
        probs = mm.score0({"sepal_len": 6.0, "sepal_wid": 3.1})
        assert probs.shape == (2,)
        assert abs(probs.sum() - 1.0) < 1e-9

    def test_validation_frame_metrics(self, iris):
        est = h2o.H2OGeneralizedLinearEstimator(family="binomial")
        m = est.train(y="species", training_frame=iris, validation_frame=iris)
        assert m.auc(valid=True) == pytest.approx(m.auc(), abs=1e-9)

    def test_error_surfaces_as_exception(self, iris):
        est = h2o.H2OGeneralizedLinearEstimator(family="not_a_family")
        with pytest.raises(h2o.H2OResponseError, match="family"):
            est.train(y="species", training_frame=iris)


class TestClientReviewFixes:
    def test_open_ended_slice_bounded(self, iris):
        tail = iris[5:, :]
        assert tail.nrows == 235

    def test_stepped_slice_rejected(self, iris):
        with pytest.raises(TypeError, match="step"):
            iris[0:10:2]

    def test_two_clients_do_not_clobber_temps(self, conn):
        c2 = h2o.H2OConnection(conn.base_url) if hasattr(h2o, "H2OConnection") else None
        from h2o3_tpu.client.connection import H2OConnection
        from h2o3_tpu.client.frame import H2OFrame
        from h2o3_tpu.client.expr import ExprNode
        import h2o3_tpu.client.expr as expr_mod
        import itertools

        a = h2o.upload_csv("v\n1\n2\n3\n")
        b_conn = H2OConnection(conn.base_url)
        b = H2OFrame.from_key(b_conn, a.frame_id, nrows=3, ncols=1)
        # reset the counter to simulate a second process starting at 0
        expr_mod._tmp_counter = itertools.count()
        da = (a["v"] * 2)
        da.refresh()
        expr_mod._tmp_counter = itertools.count()
        db = (b["v"] * 3)
        db.refresh()
        assert da.frame_id != db.frame_id  # session-scoped keys
        assert da["v"].mean() == pytest.approx(4.0)
        assert db["v"].mean() == pytest.approx(6.0)
        b_conn.close()

    def test_head_clamps_on_small_frame(self, conn):
        small = h2o.upload_csv("v\n1\n2\n3\n")
        assert small.head().nrows == 3      # default 10 > 3: clamped
        assert small[0:100].nrows == 3      # oversized slice clamped


class TestClientPersistence:
    """h2o.save_model / load_model / import_mojo / save_frame / load_frame."""

    def test_binary_model_roundtrip(self, iris, tmp_path):
        est = h2o.H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
        model = est.train(y="species", training_frame=iris)
        before = model.predict(iris).get_frame_data()

        path = h2o.save_model(model, str(tmp_path) + "/")
        h2o.remove(model.model_id)
        loaded = h2o.load_model(path)
        assert loaded.model_id == model.model_id
        after = loaded.predict(iris).get_frame_data()
        assert before == after

    def test_mojo_import_roundtrip(self, iris, tmp_path):
        est = h2o.H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=2)
        model = est.train(y="species", training_frame=iris)
        mojo_path = model.download_mojo(str(tmp_path))
        generic = h2o.import_mojo(mojo_path)
        assert generic.algo == "generic"
        a = model.predict(iris).get_frame_data()
        b = generic.predict(iris).get_frame_data()
        # probabilities match exactly; the label column may differ where p is
        # near the cut (the source model scores with its trained max-F1
        # threshold, the imported model with the default 0.5 — as in the
        # reference's Generic)
        np.testing.assert_allclose(
            np.asarray(a["pvirginica"], float),
            np.asarray(b["pvirginica"], float), rtol=1e-6,
        )

    def test_frame_roundtrip(self, iris, tmp_path):
        path = h2o.save_frame(iris, str(tmp_path) + "/")
        loaded = h2o.load_frame(path, frame_id="iris_reloaded")
        assert loaded.dim == iris.dim
        assert loaded.names == iris.names


class TestClientGridTreeExplain:
    """Round-4 client surface: H2OGridSearch, H2OTree, explanation plots
    (h2o-py grid/tree/explanation analogues) over live REST."""

    def test_grid_search_client(self, conn):
        import numpy as np

        import h2o3_tpu.client as h2o
        from h2o3_tpu.client.grid import H2OGridSearch

        rng = np.random.default_rng(3)
        X = rng.normal(size=(300, 3))
        y = (X[:, 0] > 0).astype(int)
        csv = "a,b,c,y\n" + "\n".join(
            f"{r[0]},{r[1]},{r[2]},c{int(t)}" for r, t in zip(X, y))
        fr = h2o.upload_csv(csv)
        gs = H2OGridSearch("gbm", {"max_depth": [2, 3]}, ntrees=4,
                           min_rows=2, seed=1)
        gs.train(y="y", training_frame=fr)
        assert len(gs.model_ids) == 2
        aucs = [m.auc() for m in gs.models]
        assert all(a is not None and a > 0.5 for a in aucs)
        gs.get_grid(sort_by="auc")
        assert len(gs.model_ids) == 2

    def test_tree_inspection(self, conn):
        import numpy as np

        import h2o3_tpu.client as h2o
        from h2o3_tpu.client.estimators import H2OGradientBoostingEstimator
        from h2o3_tpu.client.tree import H2OTree

        rng = np.random.default_rng(4)
        X = rng.normal(size=(300, 3))
        y = (X[:, 0] > 0).astype(int)
        csv = "a,b,c,y\n" + "\n".join(
            f"{r[0]},{r[1]},{r[2]},c{int(t)}" for r, t in zip(X, y))
        fr = h2o.upload_csv(csv)
        est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3,
                                           min_rows=2, seed=1)
        est.train(y="y", training_frame=fr)
        t = H2OTree(est.model, 0)
        assert t.nodes >= 3 and any(t.is_split)
        root = 0
        assert t.is_split[root]
        assert t.left_child(root) == 1 and t.right_child(root) == 2
        assert "split on" in t.describe_node(root)
        leaf = next(i for i, s in enumerate(t.is_split) if not s)
        assert "leaf" in t.describe_node(leaf)

    def test_explanation_plots(self, conn):
        import numpy as np

        import h2o3_tpu.client as h2o
        from h2o3_tpu.client.estimators import H2OGradientBoostingEstimator
        from h2o3_tpu.client import explanation

        rng = np.random.default_rng(5)
        X = rng.normal(size=(200, 3))
        y = (X[:, 0] > 0).astype(int)
        csv = "a,b,c,y\n" + "\n".join(
            f"{r[0]},{r[1]},{r[2]},c{int(t)}" for r, t in zip(X, y))
        fr = h2o.upload_csv(csv)
        est = H2OGradientBoostingEstimator(ntrees=3, max_depth=3,
                                           min_rows=2, seed=1)
        est.train(y="y", training_frame=fr)
        fig = explanation.varimp_plot(est.model)
        assert fig.axes and len(fig.axes[0].patches) >= 1
        fig2 = explanation.pd_plot(est.model, fr, "a")
        assert fig2.axes and (fig2.axes[0].lines or fig2.axes[0].patches)
        import matplotlib.pyplot as plt

        plt.close("all")


def test_group_by_fluent(conn):
    csv = ("g,v,w\n" + "\n".join(
        f"{'ab'[i % 2]},{i},{i * 2}" for i in range(10)))
    fr = h2o.upload_csv(csv)
    out = fr.group_by("g").count().sum("v").mean("w").get_frame()
    data = out.get_frame_data()
    cols = list(data)
    assert len(data[cols[0]]) == 2  # two groups
    # group 'a' holds even i (0,2,4,6,8): count 5, sum v 20, mean w 8
    gcol = data[cols[0]]
    ai = gcol.index("a")
    nrow_col = next(c for c in cols if "nrow" in c)
    sum_col = next(c for c in cols if c.startswith("sum"))
    mean_col = next(c for c in cols if c.startswith("mean"))
    assert float(data[nrow_col][ai]) == 5
    assert float(data[sum_col][ai]) == 20
    assert float(data[mean_col][ai]) == 8


def test_frame_apply_lambda(conn):
    csv = "a,b\n1,10\n2,20\n3,30\n"
    fr = h2o.upload_csv(csv)
    # per-column standardize-ish expression lambda
    out = fr.apply(lambda x: (x - x.mean()) / x.sd())
    data = out.get_frame_data()
    import numpy as np

    a = np.array([float(v) for v in data["a"]])
    np.testing.assert_allclose(a, (np.array([1, 2, 3]) - 2) / 1.0)
    # per-column reducer
    sums = fr.apply(lambda x: x.sum()).get_frame_data()
    assert [float(v[0]) for v in sums.values()] == [6.0, 60.0]
    # row-wise reducer (axis=1): mean across each row's values
    rows = fr.apply(lambda x: x.mean(), axis=1).get_frame_data()
    vals = [float(v) for v in next(iter(rows.values()))]
    assert vals == [5.5, 11.0, 16.5]
    # comparisons trace element-wise (not Python identity)
    flags = fr.apply(lambda x: (x == 2).sum()).get_frame_data()
    assert [float(v[0]) for v in flags.values()] == [1.0, 0.0]
    import pytest as _pytest

    with _pytest.raises(ValueError, match="axis"):
        fr.apply(lambda x: x.sum(), axis=7)


class TestClientModelPrims:
    """Round-5 client surface: permutation importance + reset threshold
    (h2o-py ModelBase.permutation_importance / reset_model_threshold,
    emitting the AstPermutationVarImp / AstModelResetThreshold rapids)."""

    def _train(self, seed=5):
        import h2o3_tpu.client as h2o

        rng = np.random.default_rng(seed)
        X = rng.normal(size=(300, 3))
        y = (X[:, 0] + 0.2 * X[:, 1] > 0).astype(int)
        csv = "a,b,c,y\n" + "\n".join(
            f"{r[0]},{r[1]},{r[2]},c{int(t)}" for r, t in zip(X, y))
        fr = h2o.upload_csv(csv)
        est = h2o.H2OGradientBoostingEstimator(ntrees=5, max_depth=3, seed=1)
        est.train(y="y", training_frame=fr)
        return est.model, fr

    def test_permutation_importance(self, conn):
        model, fr = self._train()
        pvi = model.permutation_importance(fr, metric="auc", seed=42)
        data = pvi.get_frame_data()
        assert list(data)[0] == "Variable"
        assert "Scaled Importance" in data
        # strongest feature first, response not present
        assert data["Variable"][0] == "a"
        assert "y" not in data["Variable"]

    def test_permutation_importance_repeats(self, conn):
        model, fr = self._train()
        pvi = model.permutation_importance(fr, n_repeats=2, seed=42)
        data = pvi.get_frame_data()
        assert "Run 1" in data and "Run 2" in data

    def test_reset_threshold(self, conn):
        model, fr = self._train()
        old = model.reset_threshold(0.8)
        assert 0.0 < old < 1.0
        # a second reset returns the value just set
        assert model.reset_threshold(0.3) == pytest.approx(0.8)


def test_round5_munging_surface(conn):
    """The round-5 client widening executes server-side end to end."""
    import h2o3_tpu.client as h2o

    fr = h2o.upload_csv("a,b,s\n1,10,Cat\n2,20,dog\nNA,30,Cat\n4,40,bird\n")
    q = fr["a"].quantile([0.5]).get_frame_data()
    assert float(q["aQuantiles"][0]) == 2.0
    filled = fr.impute(0, "mean")
    vals = [float(v) for v in filled.get_frame_data()["a"]]
    assert vals[2] == pytest.approx((1 + 2 + 4) / 3)
    c = fr[["a", "b"]].cor(use="complete.obs").get_frame_data()
    assert float(c[list(c)[0]][0]) == pytest.approx(1.0)
    lo = fr["s"].tolower().get_frame_data()
    assert lo[list(lo)[0]][0] == "cat"
    n = fr["s"].nchar().get_frame_data()
    assert float(n[list(n)[0]][0]) == 3.0
    cs = fr["b"].cumsum().get_frame_data()
    assert [float(v) for v in cs[list(cs)[0]]] == [10.0, 30.0, 60.0, 100.0]


def test_make_mojo_pipeline(conn, tmp_path):
    """h2o.make_mojo_pipeline composes server-side models into one
    reference pipeline zip."""
    import zipfile

    import h2o3_tpu.client as h2o

    rng = np.random.default_rng(13)
    X = rng.normal(size=(200, 2))
    y = (X[:, 0] > 0).astype(int)
    csv = "a,b,y\n" + "\n".join(
        f"{r[0]},{r[1]},c{int(t)}" for r, t in zip(X, y))
    fr = h2o.upload_csv(csv)
    est = h2o.H2OGradientBoostingEstimator(ntrees=3, max_depth=2, seed=1)
    est.train(y="y", training_frame=fr)
    out = h2o.make_mojo_pipeline(
        {"main": est.model}, {}, "main", str(tmp_path))
    with zipfile.ZipFile(out) as z:
        assert "models/main/model.ini" in z.namelist()
        assert "algorithm = MOJO Pipeline" in z.read("model.ini").decode()
